"""Restore: full-tree rebuild and restore-time resharding.

Restore is read-shaped like the weight plane's pull: a replicated consumer
(``restore``) rebuilds the whole tree; a sharded consumer
(``restore_shards``) names its target geometry (``MeshSpec`` + partitions
or a full ``ShardedTreeSpec``) and a host, and reads ONLY the chunk files
intersecting that host's destination boxes. When the target mesh differs
from the saved one, the saved spec + target spec run through the weight
plane's planner (``weights/plan.plan_reshard``) — ``restore_plan`` exposes
the plan so callers can assert ``no_gather()`` before touching a byte,
and the per-host chunk reads are exactly the plan's receive edges: no
host ever materializes a full leaf it does not declare replicated.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from ray_tpu.ckpt import manifest as mf
from ray_tpu.ckpt.store import CheckpointStore


def restore_spec(manifest: mf.Manifest):
    """The ``ShardedTreeSpec`` a checkpoint was saved under (array leaves
    only — opaque ``py`` leaves have no geometry and always replicate)."""
    from ray_tpu.weights.spec import MeshSpec, ShardedTreeSpec
    from ray_tpu.weights.store import _spec_from_payload

    if manifest.spec is not None:
        return _spec_from_payload(manifest.spec)
    # unsharded save: single-host geometry, every leaf replicated
    return ShardedTreeSpec(
        mesh=MeshSpec.host_mesh(["ckpt"]),
        parts={p: () for p, e in manifest.leaves.items() if e.kind == mf.ND},
        meta={p: (tuple(e.shape), e.dtype)
              for p, e in manifest.leaves.items() if e.kind == mf.ND})


def restore_plan(manifest: mf.Manifest, dst_spec):
    """The reshard plan a sharded restore will execute (saved geometry ->
    ``dst_spec``). Callers assert plan-level invariants (``no_gather()``,
    byte counts) against it."""
    from ray_tpu.weights.plan import plan_reshard

    src = restore_spec(manifest)
    dst_meta = dict(dst_spec.meta)
    src_meta = {p: m for p, m in src.meta.items() if p in dst_meta}
    import dataclasses as _dc

    src = _dc.replace(src, meta=src_meta,
                      parts={p: src.parts.get(p, ()) for p in src_meta})
    return plan_reshard(src, dst_spec)


def _read_chunks(store: CheckpointStore,
                 sizes: Dict[str, int]) -> Dict[str, bytes]:
    """Batch chunk read through the store's tier plane when it has one:
    a ``TieredStore`` serves local bytes and pulls evicted chunks from
    the remote tier in parallel (sha256-verified, cached locally); a
    plain store reads the local pool serially as before."""
    fetch = getattr(store, "fetch_chunks", None)
    if fetch is not None:
        return fetch(sizes)
    return {h: mf.read_chunk(store.root, h) for h in sizes}


def _py_leaves(store: CheckpointStore, manifest: mf.Manifest) -> Dict[str, Any]:
    from ray_tpu._private.serialization import loads_oob

    sizes = {entry.chunks[""][0]: entry.chunks[""][1]
             for entry in manifest.leaves.values() if entry.kind == mf.PY}
    blobs = _read_chunks(store, sizes)
    out = {}
    for path, entry in manifest.leaves.items():
        if entry.kind == mf.PY:
            h, _ = entry.chunks[""]
            out[path] = loads_oob(blobs[h])
    return out


def restore_tree(store: CheckpointStore, ckpt_id: Optional[str] = None,
            *, timeout: float = 30.0) -> Any:
    """Rebuild the FULL tree of ``ckpt_id`` (default: latest committed).
    For replicated consumers only — sharded consumers use
    :func:`restore_shards` and never hold a gathered leaf."""
    import numpy as np

    from ray_tpu.weights.spec import box_slices, unflatten_tree

    if ckpt_id is None:
        manifest = store.latest()
        if manifest is None:
            raise FileNotFoundError(
                f"checkpoint store {store.root!r} has no committed "
                f"checkpoint")
    else:
        manifest = store.wait_for(ckpt_id, timeout=timeout)
    leaves: Dict[str, Any] = _py_leaves(store, manifest)
    sizes = {h: nb for entry in manifest.leaves.values()
             if entry.kind == mf.ND for h, nb in entry.chunks.values()}
    blobs = _read_chunks(store, sizes)
    for path, entry in manifest.leaves.items():
        if entry.kind != mf.ND:
            continue
        dt = np.dtype(entry.dtype)
        out = np.empty(entry.shape, dtype=dt)
        for box_s, (h, _nb) in entry.chunks.items():
            box = mf.decode_box(box_s) or tuple((0, s) for s in entry.shape)
            data = np.frombuffer(blobs[h], dtype=dt)
            out[box_slices(box)] = data.reshape(
                tuple(b - a for a, b in box))
        leaves[path] = out
    return unflatten_tree(manifest.skeleton, leaves)


def restore_shards(store: CheckpointStore, dst_spec, host: str,
                   ckpt_id: Optional[str] = None, *,
                   timeout: float = 30.0,
                   ) -> Tuple[Dict[str, Dict[Any, Any]], Dict[str, Any]]:
    """Read exactly ``host``'s destination shards of ``dst_spec`` from the
    checkpoint, resharding through the saved geometry. Returns
    ``({leaf: {dst_box: array}}, stats)`` where stats carries the bytes
    actually read and the plan's invariants; no full leaf is ever
    materialized unless a destination box IS the full leaf."""
    import numpy as np

    from ray_tpu.weights.spec import host_boxes, intersect_box, rel_slices

    if ckpt_id is None:
        manifest = store.latest()
        if manifest is None:
            raise FileNotFoundError(
                f"checkpoint store {store.root!r} has no committed "
                f"checkpoint")
    else:
        manifest = store.wait_for(ckpt_id, timeout=timeout)
    plan = restore_plan(manifest, dst_spec)
    # pass 1: every (leaf, dst box) names the chunks it intersects — the
    # union is exactly the bytes this host owns under the plan, fetched
    # once each (and, on a TieredStore, concurrently across tiers)
    needed: Dict[str, int] = {}
    per_leaf: Dict[str, list] = {}
    for leaf, (shape, dtype) in dst_spec.meta.items():
        entry = manifest.leaves.get(leaf)
        if entry is None or entry.kind != mf.ND:
            raise KeyError(f"checkpoint {manifest.ckpt_id!r} has no array "
                           f"leaf {leaf!r}")
        chunk_boxes = [
            (mf.decode_box(bs) or tuple((0, s) for s in entry.shape), h, nb)
            for bs, (h, nb) in entry.chunks.items()]
        per_leaf[leaf] = chunk_boxes
        for dbox in host_boxes(dst_spec.mesh, dst_spec.part_of(leaf),
                               shape, host):
            for cbox, h, nb in chunk_boxes:
                if intersect_box(dbox, cbox) is not None:
                    needed[h] = nb
    blobs = _read_chunks(store, needed)
    bytes_read = sum(len(b) for b in blobs.values())
    chunks_read = len(blobs)
    # pass 2: assemble this host's shards from the fetched chunk bytes
    out: Dict[str, Dict[Any, Any]] = {}
    cache: Dict[str, np.ndarray] = {}
    for leaf, (shape, dtype) in dst_spec.meta.items():
        dt = np.dtype(dtype)
        out[leaf] = {}
        for dbox in host_boxes(dst_spec.mesh, dst_spec.part_of(leaf),
                               shape, host):
            shard = np.empty(tuple(b - a for a, b in dbox), dtype=dt)
            for cbox, h, _nb in per_leaf[leaf]:
                inter = intersect_box(dbox, cbox)
                if inter is None:
                    continue
                chunk = cache.get(h)
                if chunk is None:
                    chunk = np.frombuffer(blobs[h], dtype=dt).reshape(
                        tuple(b - a for a, b in cbox))
                    cache[h] = chunk
                shard[rel_slices(inter, dbox)] = chunk[rel_slices(inter, cbox)]
            out[leaf][dbox] = shard
    stats = {"ckpt_id": manifest.ckpt_id, "bytes_read": bytes_read,
             "chunks_read": chunks_read, "no_gather": plan.no_gather(),
             "plan": plan.stats()}
    return out, stats


def restore_tree_shards(store: CheckpointStore, num_hosts: int, rank: int,
                        ckpt_id: Optional[str] = None, *, axis: str = "data",
                        timeout: float = 30.0) -> Dict[str, Any]:
    """Convenience for the elastic-train contract (every array leaf sharded
    along dim 0 across ``num_hosts`` ranks, matching
    ``train.scaling_policy.mesh_spec_for``): returns ``{"ckpt_id", "tree",
    "stats"}`` with this rank's dim-0 shard of every array leaf and full
    copies of opaque leaves."""
    import dataclasses as _dc

    from ray_tpu.train.scaling_policy import mesh_spec_for
    from ray_tpu.weights.spec import ShardedTreeSpec, unflatten_tree

    if ckpt_id is None:
        manifest = store.latest()
        if manifest is None:
            raise FileNotFoundError(
                f"checkpoint store {store.root!r} has no committed "
                f"checkpoint")
        ckpt_id = manifest.ckpt_id
    else:
        manifest = store.wait_for(ckpt_id, timeout=timeout)
    mesh = mesh_spec_for(num_hosts, axis=axis)
    src = restore_spec(manifest)
    dst = ShardedTreeSpec(
        mesh=mesh,
        parts={p: (axis,) + (None,) * (len(shape) - 1)
               for p, (shape, _) in src.meta.items()},
        meta=dict(src.meta))
    shards, stats = restore_shards(store, dst, mesh.hosts[rank], ckpt_id,
                                   timeout=timeout)
    leaves = {p: next(iter(boxes.values())) for p, boxes in shards.items()}
    leaves.update(_py_leaves(store, manifest))
    return {"ckpt_id": ckpt_id,
            "tree": unflatten_tree(manifest.skeleton, leaves),
            "stats": stats}

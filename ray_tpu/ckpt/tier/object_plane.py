"""Object-plane chunk tier: chunks live as owned objects in the cluster
object store, held by a named detached vault actor and registered in GCS
KV (``ns="ckpt_obj"``).

This is the "spill into the cluster itself" tier (reference analog: object
spilling / the plasma store as a storage substrate): a checkpoint mirrored
here survives the *saving host* dying — the vault actor owns the object
refs, so the bytes live wherever the store put them and are fetched over
the object transfer plane on restore. It is weaker than a bucket tier (a
full cluster loss loses the vault) and exists for the middle of the
durability spectrum: fast intra-cluster re-shard/restore traffic without
touching external storage.

Registration: every chunk put lands a ``{namespace}/{hash} -> {nbytes,
ts}`` row in GCS KV ns="ckpt_obj" (best-effort), so the sweeper and the
state API can enumerate object-plane residency without waking the vault.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu.ckpt.tier.backend import BackendUnavailable, ChunkBackend

_VAULT_PREFIX = "rtpu_chunk_vault:"
_KV_NS = "ckpt_obj"


class ChunkVaultActor:
    """Detached owner of the object-plane chunk pool for one namespace.
    Re-puts every blob so the refs are actor-owned: chunks outlive the
    uploading worker by design."""

    def __init__(self, namespace: str):
        self.namespace = namespace
        self._chunks: Dict[str, object] = {}   # hash -> ObjectRef
        self._meta: Dict[str, Dict[str, float]] = {}  # hash -> nbytes/ts
        self._manifests: Dict[str, bytes] = {}

    def _register(self, h: str, nbytes: int, ts: float) -> None:
        try:
            from ray_tpu._private import wire
            from ray_tpu.experimental.internal_kv import _internal_kv_put

            _internal_kv_put(f"{self.namespace}/{h}".encode(),
                             wire.dumps({"nbytes": nbytes, "ts": ts}),
                             namespace=_KV_NS)
        except Exception:
            pass  # registration is an index, not the source of truth

    def put_chunk(self, h: str, data: bytes) -> bool:
        if h in self._chunks:
            return False
        import ray_tpu

        self._chunks[h] = ray_tpu.put(data)
        self._meta[h] = {"nbytes": len(data), "ts": time.time()}
        self._register(h, len(data), self._meta[h]["ts"])
        return True

    def get_chunk(self, h: str, offset: int = 0,
                  length: Optional[int] = None) -> Optional[bytes]:
        # returns None (not raise) for a missing chunk: remote exceptions
        # arrive wrapped, and the backend wants a clean KeyError
        ref = self._chunks.get(h)
        if ref is None:
            return None
        import ray_tpu

        data = ray_tpu.get(ref)
        if offset or length is not None:
            end = None if length is None else offset + length
            data = data[offset:end]
        return data

    def has_chunk(self, h: str) -> bool:
        return h in self._chunks

    def delete_chunk(self, h: str) -> None:
        self._chunks.pop(h, None)
        self._meta.pop(h, None)
        try:
            from ray_tpu.experimental.internal_kv import _internal_kv_del

            _internal_kv_del(f"{self.namespace}/{h}".encode(),
                             namespace=_KV_NS)
        except Exception:
            pass

    def list_chunks(self) -> Dict[str, int]:
        return {h: int(m["nbytes"]) for h, m in self._meta.items()}

    def chunk_mtime(self, h: str) -> Optional[float]:
        m = self._meta.get(h)
        return None if m is None else float(m["ts"])

    def put_manifest(self, ckpt_id: str, data: bytes) -> None:
        self._manifests[ckpt_id] = bytes(data)

    def get_manifest(self, ckpt_id: str) -> Optional[bytes]:
        return self._manifests.get(ckpt_id)

    def list_manifests(self) -> List[str]:
        return sorted(self._manifests)

    def delete_manifest(self, ckpt_id: str) -> None:
        self._manifests.pop(ckpt_id, None)


class ObjectPlaneBackend(ChunkBackend):
    """Chunk/manifest contract over a :class:`ChunkVaultActor`."""

    kind = "object_plane"

    def __init__(self, namespace: str, create: bool = True,
                 timeout: float = 60.0):
        self.namespace = namespace
        self.timeout = timeout
        try:
            import ray_tpu

            name = _VAULT_PREFIX + namespace
            if create:
                actor_cls = ray_tpu.remote(ChunkVaultActor)
                self._actor = actor_cls.options(
                    name=name, lifetime="detached", get_if_exists=True,
                    max_concurrency=32, num_cpus=0.05).remote(namespace)
            else:
                self._actor = ray_tpu.get_actor(name)
        except Exception as e:
            raise BackendUnavailable(
                f"object-plane vault {namespace!r} unreachable: {e!r}") from e

    def _call(self, method: str, *args):
        import ray_tpu

        try:
            return ray_tpu.get(getattr(self._actor, method).remote(*args),
                               timeout=self.timeout)
        except Exception as e:
            raise BackendUnavailable(
                f"object-plane vault {self.namespace!r} call "
                f"{method} failed: {e!r}") from e

    def put(self, h: str, data: bytes) -> bool:
        return bool(self._call("put_chunk", h, data))

    def get(self, h: str, offset: int = 0,
            length: Optional[int] = None) -> bytes:
        data = self._call("get_chunk", h, offset, length)
        if data is None:
            raise KeyError(h)
        return data

    def has(self, h: str) -> bool:
        return bool(self._call("has_chunk", h))

    def delete(self, h: str) -> None:
        self._call("delete_chunk", h)

    def list_chunks(self) -> Dict[str, int]:
        return self._call("list_chunks")

    def chunk_mtime(self, h: str) -> Optional[float]:
        return self._call("chunk_mtime", h)

    def put_manifest(self, ckpt_id: str, data: bytes) -> None:
        self._call("put_manifest", ckpt_id, data)

    def get_manifest(self, ckpt_id: str) -> bytes:
        data = self._call("get_manifest", ckpt_id)
        if data is None:
            raise KeyError(ckpt_id)
        return data

    def list_manifests(self) -> List[str]:
        return self._call("list_manifests")

    def delete_manifest(self, ckpt_id: str) -> None:
        self._call("delete_manifest", ckpt_id)

    def descriptor(self) -> Dict[str, object]:
        return {"kind": self.kind, "namespace": self.namespace}

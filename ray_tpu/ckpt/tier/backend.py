"""ChunkBackend: the pluggable storage contract of the checkpoint tier.

A backend stores exactly two kinds of immutable blobs — content-addressed
*chunks* (named by the SHA-256 of their bytes) and *manifests* (named by
checkpoint id) — plus the store-level pointer files (``LATEST``,
residency index) as small named blobs. Because chunk names commit to
their content, every backend write is idempotent and every cross-backend
copy is verifiable: a reader recomputes the hash and rejects silently
corrupted bytes (``pario.py`` does this on every cross-tier read).

The contract is intentionally tiny — the mirror pump, the parallel IO
engine and the retention sweeper are all written against it:

- ``put(h, data) -> created`` — idempotent content-addressed write.
  ``created=False`` is the dedup hit (the tier already holds the bytes);
- ``get(h, offset, length)`` — ranged read (object-store ``Range:`` GETs;
  the local tier seeks). ``length=None`` reads to the end;
- ``has / delete / list_chunks / chunk_mtime`` — existence, reaping and
  enumeration for the sweeper. ``chunk_mtime`` returning ``None`` means
  "age unknown": the sweeper then refuses to reap (conservative — an
  in-flight mirror must never lose a chunk to a grace-window guess);
- ``put_manifest / get_manifest / list_manifests / delete_manifest`` —
  same shape for the (small, JSON) manifest blobs;
- ``descriptor()`` — a JSON-able ``{"kind": ...}`` payload from which
  :func:`backend_from_descriptor` reconstructs an equivalent backend in
  another process (the GCS sweeper, the CLI, a restoring host).

``LocalFSBackend`` is today's PR 4 on-disk layout verbatim — the tiered
store's *local* tier is byte-compatible with every existing store root.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from ray_tpu.ckpt import manifest as mf


class BackendUnavailable(RuntimeError):
    """The tier cannot serve the request right now (network fault, object
    owner dead, injected failure). Callers treat this as retryable."""


class ChunkBackend:
    """Abstract storage tier. All methods may raise
    :class:`BackendUnavailable`; everything else is a bug."""

    kind = "abstract"

    # -- chunks --------------------------------------------------------

    def put(self, h: str, data: bytes) -> bool:
        raise NotImplementedError

    def get(self, h: str, offset: int = 0,
            length: Optional[int] = None) -> bytes:
        raise NotImplementedError

    def has(self, h: str) -> bool:
        raise NotImplementedError

    def delete(self, h: str) -> None:
        raise NotImplementedError

    def list_chunks(self) -> Dict[str, int]:
        """hash -> nbytes for every chunk the tier holds."""
        raise NotImplementedError

    def chunk_mtime(self, h: str) -> Optional[float]:
        """Upload time of a chunk, or ``None`` when the tier cannot tell
        (the sweeper then never reaps it)."""
        return None

    # -- manifests -----------------------------------------------------

    def put_manifest(self, ckpt_id: str, data: bytes) -> None:
        raise NotImplementedError

    def get_manifest(self, ckpt_id: str) -> bytes:
        raise NotImplementedError

    def list_manifests(self) -> List[str]:
        raise NotImplementedError

    def delete_manifest(self, ckpt_id: str) -> None:
        raise NotImplementedError

    # -- admin ---------------------------------------------------------

    def stats(self) -> Dict[str, object]:
        chunks = self.list_chunks()
        return {"kind": self.kind, "num_chunks": len(chunks),
                "chunk_bytes": sum(chunks.values()),
                "num_manifests": len(self.list_manifests())}

    def descriptor(self) -> Dict[str, object]:
        raise NotImplementedError


class LocalFSBackend(ChunkBackend):
    """Today's on-disk checkpoint layout behind the backend contract —
    ``<root>/chunks/<hh>/<hash>`` + ``<root>/manifests/<id>.json``,
    byte-compatible with every pre-tier store root."""

    kind = "localfs"

    def __init__(self, root: str):
        self.root = os.path.abspath(os.fspath(root))

    # -- chunks --------------------------------------------------------

    def put(self, h: str, data: bytes) -> bool:
        path = mf.chunk_path(self.root, h)
        if os.path.exists(path):
            return False
        mf.atomic_write(path, data)
        return True

    def get(self, h: str, offset: int = 0,
            length: Optional[int] = None) -> bytes:
        try:
            with open(mf.chunk_path(self.root, h), "rb") as f:
                if offset:
                    f.seek(offset)
                return f.read() if length is None else f.read(length)
        except FileNotFoundError:
            raise KeyError(h) from None

    def has(self, h: str) -> bool:
        return os.path.exists(mf.chunk_path(self.root, h))

    def delete(self, h: str) -> None:
        try:
            os.remove(mf.chunk_path(self.root, h))
        except FileNotFoundError:
            pass

    def list_chunks(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        cdir = os.path.join(self.root, mf.CHUNK_DIR)
        if not os.path.isdir(cdir):
            return out
        for sub in os.listdir(cdir):
            subdir = os.path.join(cdir, sub)
            if not os.path.isdir(subdir):
                continue
            for h in os.listdir(subdir):
                if ".tmp." in h:
                    continue
                try:
                    out[h] = os.path.getsize(os.path.join(subdir, h))
                except OSError:
                    continue
        return out

    def chunk_mtime(self, h: str) -> Optional[float]:
        try:
            return os.path.getmtime(mf.chunk_path(self.root, h))
        except OSError:
            return None

    # -- manifests -----------------------------------------------------

    def put_manifest(self, ckpt_id: str, data: bytes) -> None:
        mf.atomic_write(mf.manifest_path(self.root, ckpt_id), data)

    def get_manifest(self, ckpt_id: str) -> bytes:
        try:
            with open(mf.manifest_path(self.root, ckpt_id), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise KeyError(ckpt_id) from None

    def list_manifests(self) -> List[str]:
        mdir = os.path.join(self.root, mf.MANIFEST_DIR)
        try:
            names = os.listdir(mdir)
        except FileNotFoundError:
            return []
        return sorted(n[:-5] for n in names
                      if n.endswith(".json") and ".tmp." not in n)

    def delete_manifest(self, ckpt_id: str) -> None:
        try:
            os.remove(mf.manifest_path(self.root, ckpt_id))
        except FileNotFoundError:
            pass

    def descriptor(self) -> Dict[str, object]:
        return {"kind": self.kind, "root": self.root}


def backend_from_descriptor(d: Dict[str, object]) -> ChunkBackend:
    """Reconstruct a backend from its :meth:`ChunkBackend.descriptor`
    payload — how the GCS sweeper and the CLI re-attach to a store's
    remote tier from a different process."""
    kind = d.get("kind")
    if kind == "localfs":
        return LocalFSBackend(str(d["root"]))
    if kind == "bucket":
        from ray_tpu.ckpt.tier.bucket import BucketBackend, bucket_client_from_descriptor

        client = bucket_client_from_descriptor(dict(d["client"]))  # type: ignore[arg-type]
        return BucketBackend(client, prefix=str(d.get("prefix") or ""))
    if kind == "object_plane":
        from ray_tpu.ckpt.tier.object_plane import ObjectPlaneBackend

        return ObjectPlaneBackend(str(d["namespace"]))
    raise ValueError(f"unknown chunk backend descriptor kind {kind!r}")

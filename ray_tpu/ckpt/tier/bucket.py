"""Bucket-style chunk tier: a prefix/key object namespace with ranged
reads and multipart-style uploads.

``DirBucketClient`` is the in-tree client — a bucket API (put/get/head/
list/delete + multipart) over a plain directory, one file per key. It is
the shape of a real object store (S3/GCS) boiled down to what the tier
needs: immutable whole-object puts, ``Range:`` reads, and uploads that
become visible only at ``complete_multipart`` (an aborted multipart is
invisible — the parts live under a hidden staging prefix until the final
atomic rename).

``FaultShim`` wraps any client with injectable per-op latency, failure
after N operations (raises :class:`BackendUnavailable`), and byte
corruption on reads — the test harness for every crash/fault path in the
tier (mirror-pump death mid-upload, sha-verify rejection, parallel-vs-
serial restore pricing under realistic per-object latency).

``BucketBackend`` maps the chunk/manifest contract onto bucket keys::

    <prefix>chunks/<hh>/<hash>      (multipart above ckpt_multipart_bytes)
    <prefix>manifests/<ckpt_id>.json

Chunk writes are idempotent by content address: ``put`` HEADs first, so
re-mirroring after a crash uploads only what is actually missing.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from typing import Dict, List, Optional

from ray_tpu.ckpt.tier.backend import BackendUnavailable, ChunkBackend

_STAGING = ".multipart/"  # staging prefix; never listed, never a chunk


class DirBucketClient:
    """Bucket semantics over a directory: one file per key, writes visible
    only after an atomic rename (a reader never sees a torn object)."""

    kind = "dir"

    def __init__(self, root: str):
        self.root = os.path.abspath(os.fspath(root))
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        if ".." in key.split("/"):
            raise ValueError(f"bucket key escapes the root: {key!r}")
        return os.path.join(self.root, *key.split("/"))

    # -- objects -------------------------------------------------------

    def put_object(self, key: str, data: bytes) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def get_object(self, key: str, start: int = 0,
                   length: Optional[int] = None) -> bytes:
        try:
            with open(self._path(key), "rb") as f:
                if start:
                    f.seek(start)
                return f.read() if length is None else f.read(length)
        except FileNotFoundError:
            raise KeyError(key) from None

    def head_object(self, key: str) -> Optional[Dict[str, float]]:
        try:
            st = os.stat(self._path(key))
        except OSError:
            return None
        return {"size": st.st_size, "mtime": st.st_mtime}

    def list_objects(self, prefix: str = "") -> Dict[str, int]:
        out: Dict[str, int] = {}
        for dirpath, _dirs, files in os.walk(self.root):
            for name in files:
                if ".tmp." in name:
                    continue
                full = os.path.join(dirpath, name)
                key = os.path.relpath(full, self.root).replace(os.sep, "/")
                if key.startswith(_STAGING) or not key.startswith(prefix):
                    continue
                try:
                    out[key] = os.path.getsize(full)
                except OSError:
                    continue
        return out

    def delete_object(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    # -- multipart -----------------------------------------------------

    def create_multipart(self, key: str) -> str:
        upload_id = uuid.uuid4().hex
        os.makedirs(self._path(f"{_STAGING}{upload_id}"), exist_ok=True)
        # the target key rides in the staging dir so complete() needs
        # only the upload id (mirrors real multipart-upload handles)
        self.put_object(f"{_STAGING}{upload_id}/.key", key.encode())
        return upload_id

    def upload_part(self, upload_id: str, part_no: int, data: bytes) -> None:
        self.put_object(f"{_STAGING}{upload_id}/{part_no:06d}", data)

    def complete_multipart(self, upload_id: str) -> None:
        """Concatenate the parts in order into the target key with one
        atomic rename — an incomplete multipart is never visible."""
        stage = self._path(f"{_STAGING}{upload_id}")
        key = self.get_object(f"{_STAGING}{upload_id}/.key").decode()
        parts = sorted(n for n in os.listdir(stage)
                       if n != ".key" and ".tmp." not in n)
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
        with open(tmp, "wb") as out:
            for name in parts:
                with open(os.path.join(stage, name), "rb") as f:
                    out.write(f.read())
            out.flush()
            os.fsync(out.fileno())
        os.replace(tmp, path)
        self.abort_multipart(upload_id)

    def abort_multipart(self, upload_id: str) -> None:
        import shutil

        shutil.rmtree(self._path(f"{_STAGING}{upload_id}"),
                      ignore_errors=True)

    def descriptor(self) -> Dict[str, object]:
        return {"kind": self.kind, "root": self.root}


def bucket_client_from_descriptor(d: Dict[str, object]) -> "DirBucketClient":
    if d.get("kind") == "dir":
        return DirBucketClient(str(d["root"]))
    raise ValueError(f"unknown bucket client descriptor kind {d.get('kind')!r}")


class FaultShim:
    """Injectable fault/latency wrapper around a bucket client.

    - ``latency_s`` sleeps before every op (or per-op via ``{"get": s}``);
    - ``fail_after`` raises :class:`BackendUnavailable` once the op
      counter passes it (``fail_ops`` restricts which ops count/fail) —
      "the mirror pump died mid-upload" in one line;
    - ``corrupt_get`` flips the first byte of read data (optionally only
      for keys matching the predicate) — exercises sha256 rejection.

    Thread-safe: the parallel IO engine hammers it from worker threads.
    """

    def __init__(self, client: DirBucketClient, *,
                 latency_s: object = 0.0,
                 fail_after: Optional[int] = None,
                 fail_ops: Optional[tuple] = None,
                 corrupt_get: object = False):
        self.client = client
        self.latency_s = latency_s
        self.fail_after = fail_after
        self.fail_ops = tuple(fail_ops or ())
        self.corrupt_get = corrupt_get
        self.op_counts: Dict[str, int] = {}
        self._lock = threading.Lock()
        self.kind = client.kind

    def _enter(self, op: str, key: str = "") -> None:
        with self._lock:
            self.op_counts[op] = self.op_counts.get(op, 0) + 1
            counted = sum(v for k, v in self.op_counts.items()
                          if not self.fail_ops or k in self.fail_ops)
        lat = self.latency_s
        if isinstance(lat, dict):
            lat = lat.get(op, 0.0)
        if lat:
            time.sleep(lat)
        if (self.fail_after is not None
                and (not self.fail_ops or op in self.fail_ops)
                and counted > self.fail_after):
            raise BackendUnavailable(
                f"injected fault: op {op!r} on {key!r} after "
                f"{self.fail_after} ops")

    def clear_fault(self) -> None:
        self.fail_after = None

    def ops(self, op: Optional[str] = None) -> int:
        with self._lock:
            if op is not None:
                return self.op_counts.get(op, 0)
            return sum(self.op_counts.values())

    # -- delegated ops -------------------------------------------------

    def put_object(self, key, data):
        self._enter("put", key)
        return self.client.put_object(key, data)

    def get_object(self, key, start: int = 0, length: Optional[int] = None):
        self._enter("get", key)
        data = self.client.get_object(key, start, length)
        corrupt = self.corrupt_get
        if callable(corrupt):
            corrupt = corrupt(key)
        if corrupt and data:
            data = bytes([data[0] ^ 0xFF]) + data[1:]
        return data

    def head_object(self, key):
        self._enter("head", key)
        return self.client.head_object(key)

    def list_objects(self, prefix: str = ""):
        self._enter("list", prefix)
        return self.client.list_objects(prefix)

    def delete_object(self, key):
        self._enter("delete", key)
        return self.client.delete_object(key)

    def create_multipart(self, key):
        self._enter("create_multipart", key)
        return self.client.create_multipart(key)

    def upload_part(self, upload_id, part_no, data):
        self._enter("upload_part", upload_id)
        return self.client.upload_part(upload_id, part_no, data)

    def complete_multipart(self, upload_id):
        self._enter("complete_multipart", upload_id)
        return self.client.complete_multipart(upload_id)

    def abort_multipart(self, upload_id):
        return self.client.abort_multipart(upload_id)

    def descriptor(self):
        # the shim is a test harness, not durable state: a re-attached
        # backend (sweeper, CLI) talks to the unwrapped client
        return self.client.descriptor()


class BucketBackend(ChunkBackend):
    """Chunk/manifest contract over a bucket client + key prefix."""

    kind = "bucket"

    def __init__(self, client, prefix: str = "",
                 multipart_bytes: Optional[int] = None):
        if prefix and not prefix.endswith("/"):
            prefix += "/"
        self.client = client
        self.prefix = prefix
        if multipart_bytes is None:
            from ray_tpu._private.config import RAY_CONFIG

            multipart_bytes = RAY_CONFIG.ckpt_multipart_bytes
        self.multipart_bytes = int(multipart_bytes)

    def _chunk_key(self, h: str) -> str:
        return f"{self.prefix}chunks/{h[:2]}/{h}"

    def _manifest_key(self, ckpt_id: str) -> str:
        return f"{self.prefix}manifests/{ckpt_id}.json"

    # -- chunks --------------------------------------------------------

    def put(self, h: str, data: bytes) -> bool:
        key = self._chunk_key(h)
        if self.client.head_object(key) is not None:
            return False  # content-addressed dedup: uploaded once, ever
        if len(data) > self.multipart_bytes:
            upload_id = self.client.create_multipart(key)
            try:
                for i in range(0, len(data), self.multipart_bytes):
                    self.client.upload_part(
                        upload_id, i // self.multipart_bytes,
                        data[i:i + self.multipart_bytes])
                self.client.complete_multipart(upload_id)
            except BaseException:
                self.client.abort_multipart(upload_id)
                raise
        else:
            self.client.put_object(key, data)
        return True

    def get(self, h: str, offset: int = 0,
            length: Optional[int] = None) -> bytes:
        return self.client.get_object(self._chunk_key(h), offset, length)

    def has(self, h: str) -> bool:
        return self.client.head_object(self._chunk_key(h)) is not None

    def delete(self, h: str) -> None:
        self.client.delete_object(self._chunk_key(h))

    def list_chunks(self) -> Dict[str, int]:
        objs = self.client.list_objects(f"{self.prefix}chunks/")
        return {key.rsplit("/", 1)[-1]: size for key, size in objs.items()}

    def chunk_mtime(self, h: str) -> Optional[float]:
        head = self.client.head_object(self._chunk_key(h))
        return None if head is None else head.get("mtime")

    # -- manifests -----------------------------------------------------

    def put_manifest(self, ckpt_id: str, data: bytes) -> None:
        self.client.put_object(self._manifest_key(ckpt_id), data)

    def get_manifest(self, ckpt_id: str) -> bytes:
        return self.client.get_object(self._manifest_key(ckpt_id))

    def list_manifests(self) -> List[str]:
        objs = self.client.list_objects(f"{self.prefix}manifests/")
        return sorted(key.rsplit("/", 1)[-1][:-5] for key in objs
                      if key.endswith(".json"))

    def delete_manifest(self, ckpt_id: str) -> None:
        self.client.delete_object(self._manifest_key(ckpt_id))

    def descriptor(self) -> Dict[str, object]:
        return {"kind": self.kind, "client": self.client.descriptor(),
                "prefix": self.prefix}

"""ParallelIO: the per-host bounded thread-pool chunk transfer engine.

Both directions of cross-tier traffic go through one engine per host:

- **fetch**: N worker threads pull full chunks concurrently, every byte
  sha256-verified against its content address before it is handed to the
  caller (a remote tier returning corrupt bytes is *rejected*, never
  silently restored). A failed/corrupt chunk does not poison the batch —
  the caller gets the partial result plus per-chunk errors and decides
  (the tiered store falls back to the local tier per chunk);
- **put**: uploads ride the same pool; ``put`` returning ``created=False``
  is the dedup hit that makes re-mirroring idempotent and delta saves
  upload only changed bytes;
- **in-flight byte cap**: per-host admission control — a worker blocks
  while admitting its chunk would push in-flight bytes over the cap
  (one oversized chunk is always admitted alone, so progress is
  guaranteed). Restore on a 96-host mesh must not buffer an unbounded
  slice of the checkpoint in RAM per host;
- **range coalescing**: ``read_ranges`` merges byte ranges whose gap is
  under ``ckpt_io_coalesce_gap`` into single ranged GETs — many small
  box-intersection reads against one chunk become few object-store
  round-trips.

Metrics ride the shared registry as ``ray_tpu.ckpt.tier.*``.
"""

from __future__ import annotations

import hashlib
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

from ray_tpu.ckpt.tier.backend import ChunkBackend

_metrics_lock = threading.Lock()
_metrics: Optional[dict] = None


def _obs() -> dict:
    """Lazily-created tier metrics on the shared registry."""
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Counter, Histogram

            _metrics = {
                "fetch_bytes": Counter(
                    "ray_tpu.ckpt.tier.fetch_bytes",
                    "chunk bytes fetched from a non-local tier"),
                "fetch_chunks": Counter(
                    "ray_tpu.ckpt.tier.fetch_chunks",
                    "chunks fetched from a non-local tier"),
                "upload_bytes": Counter(
                    "ray_tpu.ckpt.tier.upload_bytes",
                    "chunk bytes uploaded to a non-local tier"),
                "upload_chunks": Counter(
                    "ray_tpu.ckpt.tier.upload_chunks",
                    "chunks uploaded to a non-local tier"),
                "dedup_bytes": Counter(
                    "ray_tpu.ckpt.tier.dedup_bytes",
                    "upload bytes skipped because the tier already held "
                    "the content address"),
                "verify_failures": Counter(
                    "ray_tpu.ckpt.tier.verify_failures",
                    "cross-tier reads rejected by sha256 verification"),
                "inflight_wait_seconds": Histogram(
                    "ray_tpu.ckpt.tier.inflight_wait_seconds",
                    "time transfers waited on the per-host in-flight "
                    "byte cap",
                    boundaries=[0.001, 0.01, 0.1, 1, 10]),
            }
        return _metrics


class ChunkVerifyError(RuntimeError):
    """A cross-tier read returned bytes whose sha256 does not match the
    chunk's content address."""

    def __init__(self, h: str, got: str):
        super().__init__(f"chunk {h[:12]}… failed sha256 verification "
                         f"(tier returned content {got[:12]}…)")
        self.chunk = h
        self.got = got


class ChunkFetchError(RuntimeError):
    """One or more chunks of a parallel fetch failed. ``partial`` holds
    every chunk that DID arrive (verified); ``errors`` maps the failed
    hashes to their exceptions — callers fall back per chunk."""

    def __init__(self, errors: Dict[str, BaseException],
                 partial: Dict[str, bytes]):
        super().__init__(
            f"{len(errors)} of {len(errors) + len(partial)} chunk fetches "
            f"failed: {sorted(errors)[:3]}…")
        self.errors = errors
        self.partial = partial


class _ByteGate:
    """Admission control: at most ``cap`` payload bytes in flight. An
    oversized request is admitted only when nothing else is in flight."""

    def __init__(self, cap: int):
        self.cap = int(cap)
        self._inflight = 0
        self._cv = threading.Condition()

    def acquire(self, nbytes: int) -> float:
        import time

        t0 = time.monotonic()
        with self._cv:
            while self._inflight and self._inflight + nbytes > self.cap:
                self._cv.wait()
            self._inflight += nbytes
        return time.monotonic() - t0

    def release(self, nbytes: int) -> None:
        with self._cv:
            self._inflight -= nbytes
            self._cv.notify_all()


def coalesce_ranges(ranges: List[Tuple[int, int]],
                    gap: int) -> List[Tuple[int, int]]:
    """Merge ``(offset, length)`` ranges separated by at most ``gap``
    bytes into covering ranges (reading a small gap is cheaper than a
    second round-trip). Input need not be sorted; output is."""
    if not ranges:
        return []
    spans = sorted((off, off + ln) for off, ln in ranges if ln > 0)
    out: List[Tuple[int, int]] = []
    cur_s, cur_e = spans[0]
    for s, e in spans[1:]:
        if s - cur_e <= gap:
            cur_e = max(cur_e, e)
        else:
            out.append((cur_s, cur_e - cur_s))
            cur_s, cur_e = s, e
    out.append((cur_s, cur_e - cur_s))
    return out


class ParallelIO:
    """Bounded-parallel chunk transfer against one backend."""

    def __init__(self, backend: ChunkBackend, *,
                 threads: Optional[int] = None,
                 inflight_bytes: Optional[int] = None,
                 coalesce_gap: Optional[int] = None,
                 verify: bool = True):
        from ray_tpu._private.config import RAY_CONFIG

        self.backend = backend
        self.threads = max(1, int(threads if threads is not None
                                  else RAY_CONFIG.ckpt_io_threads))
        self._gate = _ByteGate(
            inflight_bytes if inflight_bytes is not None
            else RAY_CONFIG.ckpt_io_inflight_bytes)
        self.coalesce_gap = int(coalesce_gap if coalesce_gap is not None
                                else RAY_CONFIG.ckpt_io_coalesce_gap)
        self.verify = verify
        self.counters: Dict[str, int] = {}
        self._lock = threading.Lock()

    def _count(self, **kv: int) -> None:
        with self._lock:
            for k, v in kv.items():
                self.counters[k] = self.counters.get(k, 0) + v

    def _pool(self) -> ThreadPoolExecutor:
        return ThreadPoolExecutor(max_workers=self.threads,
                                  thread_name_prefix="ckpt-tier-io")

    # -- fetch ---------------------------------------------------------

    def _fetch_one(self, h: str, nbytes: int) -> bytes:
        wait = self._gate.acquire(max(nbytes, 1))
        try:
            if wait > 0.001:
                _obs()["inflight_wait_seconds"].observe(wait)
            data = self.backend.get(h)
            if self.verify:
                got = hashlib.sha256(data).hexdigest()
                if got != h:
                    _obs()["verify_failures"].inc(1)
                    self._count(verify_failures=1)
                    raise ChunkVerifyError(h, got)
            _obs()["fetch_bytes"].inc(len(data))
            _obs()["fetch_chunks"].inc(1)
            self._count(fetch_chunks=1, fetch_bytes=len(data))
            return data
        finally:
            self._gate.release(max(nbytes, 1))

    def fetch(self, sizes: Dict[str, int]) -> Dict[str, bytes]:
        """Fetch every chunk of ``{hash: expected_nbytes}`` concurrently,
        verified. Raises :class:`ChunkFetchError` carrying the verified
        partial result if any chunk fails."""
        if not sizes:
            return {}
        results: Dict[str, bytes] = {}
        errors: Dict[str, BaseException] = {}
        with self._pool() as pool:
            futs = {h: pool.submit(self._fetch_one, h, n)
                    for h, n in sizes.items()}
            for h, fut in futs.items():
                try:
                    results[h] = fut.result()
                except BaseException as e:
                    errors[h] = e
        if errors:
            raise ChunkFetchError(errors, results)
        return results

    def read_ranges(self, h: str, ranges: List[Tuple[int, int]],
                    ) -> List[bytes]:
        """Ranged reads of one chunk, coalesced (gap ≤ ``coalesce_gap``)
        into covering GETs and sliced back out. NOT content-verified —
        a partial read cannot be hashed against the chunk address; use
        :meth:`fetch` when crossing a tier you do not trust."""
        merged = coalesce_ranges(ranges, self.coalesce_gap)
        blocks: Dict[Tuple[int, int], bytes] = {}

        def _read(span: Tuple[int, int]) -> None:
            off, ln = span
            blocks[span] = self.backend.get(h, offset=off, length=ln)
            self._count(ranged_gets=1, ranged_bytes=ln)

        with self._pool() as pool:
            list(pool.map(_read, merged))
        out: List[bytes] = []
        for off, ln in ranges:
            for (m_off, m_ln), data in blocks.items():
                if m_off <= off and off + ln <= m_off + m_ln:
                    out.append(data[off - m_off:off - m_off + ln])
                    break
            else:
                raise AssertionError("range not covered by coalesced read")
        return out

    # -- put -----------------------------------------------------------

    def _put_one(self, h: str, data, nbytes: int) -> Tuple[bool, int]:
        wait = self._gate.acquire(max(nbytes, 1))
        try:
            if wait > 0.001:
                _obs()["inflight_wait_seconds"].observe(wait)
            if callable(data):
                # lazy loader: bytes materialize only once admitted by
                # the gate, so a big mirror never holds the whole
                # checkpoint in RAM
                data = data()
            created = self.backend.put(h, data)
            if created:
                _obs()["upload_bytes"].inc(len(data))
                _obs()["upload_chunks"].inc(1)
                self._count(upload_chunks=1, upload_bytes=len(data))
            else:
                _obs()["dedup_bytes"].inc(len(data))
                self._count(dedup_chunks=1, dedup_bytes=len(data))
            return created, len(data)
        finally:
            self._gate.release(max(nbytes, 1))

    def put_many(self, chunks: Dict[str, object],
                 sizes: Optional[Dict[str, int]] = None) -> Dict[str, int]:
        """Upload chunks concurrently (idempotent by content address).
        Values are bytes or zero-arg loaders returning bytes (``sizes``
        supplies expected byte counts for gating when loaders are used).
        Returns this call's counters: uploaded/deduped chunks and bytes.
        Raises the first backend error AFTER every in-flight worker has
        settled (no torn pool state; re-running is safe)."""
        out = {"upload_chunks": 0, "upload_bytes": 0,
               "dedup_chunks": 0, "dedup_bytes": 0}
        if not chunks:
            return out
        sizes = sizes or {}
        first_error: List[BaseException] = []
        with self._pool() as pool:
            futs = {h: pool.submit(
                self._put_one, h, data,
                sizes.get(h, len(data) if isinstance(data, bytes) else 1))
                for h, data in chunks.items()}
            for h, fut in futs.items():
                try:
                    created, n = fut.result()
                except BaseException as e:
                    if not first_error:
                        first_error.append(e)
                    continue
                if created:
                    out["upload_chunks"] += 1
                    out["upload_bytes"] += n
                else:
                    out["dedup_chunks"] += 1
                    out["dedup_bytes"] += n
        if first_error:
            raise first_error[0]
        return out

"""Retention sweeper: keep-last/pinned/grace policy applied across tiers.

PR 4 retention was a store-local method the saver called inline. The
sweeper promotes it to a standalone pass any process can run against any
store root — the GCS runs it cluster-wide (``_ckpt_sweep_loop``) over
every store that registered a sweep policy in its KV mirror, so retention
keeps working after the training driver (the only process that used to
call ``retention()``) is gone.

Safety invariants, in order of authority:

1. a chunk referenced by ANY live manifest — local or remote tier,
   pinned or not, including weight-plane durable versions (which publish
   as pinned manifests) — is never reaped;
2. a chunk referenced by an in-flight sharded save (named in a
   ``parts/<ckpt_id>/`` part-file that has not committed yet) is never
   reaped;
3. a chunk younger than ``grace_s`` is never reaped, on either tier — an
   async saver or a mirror pump writes chunks BEFORE the manifest that
   names them exists. On the remote tier a chunk whose upload time is
   *unknown* (``chunk_mtime() is None``) is treated as young forever:
   the sweeper refuses to guess;
4. only then does keep-last apply: unpinned manifests beyond the newest
   ``keep_last`` drop, then unreferenced chunks.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.ckpt import manifest as mf

_metrics_lock = threading.Lock()
_metrics: Optional[dict] = None


def _obs() -> dict:
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Counter

            _metrics = {
                "runs": Counter(
                    "ray_tpu.ckpt.tier.sweep_runs",
                    "retention sweeper passes completed"),
                "reaped_manifests": Counter(
                    "ray_tpu.ckpt.tier.sweep_reaped_manifests",
                    "manifests dropped by the retention sweeper, both tiers"),
                "reaped_bytes": Counter(
                    "ray_tpu.ckpt.tier.sweep_reaped_bytes",
                    "chunk bytes reclaimed by the retention sweeper, "
                    "both tiers"),
            }
        return _metrics


@dataclasses.dataclass
class SweepPolicy:
    """What a store asks the sweeper to enforce. ``keep_last=None`` keeps
    every checkpoint (the sweeper then only GCs orphan chunks)."""

    keep_last: Optional[int] = None
    grace_s: Optional[float] = None  # None -> RAY_CONFIG.ckpt_sweep_grace_s
    keep_ids: tuple = ()

    def resolved_grace(self) -> float:
        if self.grace_s is not None:
            return float(self.grace_s)
        from ray_tpu._private.config import RAY_CONFIG

        return float(RAY_CONFIG.ckpt_sweep_grace_s)

    def to_dict(self) -> Dict[str, Any]:
        return {"keep_last": self.keep_last, "grace_s": self.grace_s,
                "keep_ids": list(self.keep_ids)}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SweepPolicy":
        return cls(keep_last=d.get("keep_last"),
                   grace_s=d.get("grace_s"),
                   keep_ids=tuple(d.get("keep_ids") or ()))


def _inflight_chunks(root: str) -> Dict[str, int]:
    """Chunk hashes referenced by un-committed part-files of in-flight
    sharded saves — protected regardless of age (a slow peer host must
    not lose its already-written chunks to a sweep racing the commit)."""
    out: Dict[str, int] = {}
    pdir = os.path.join(root, mf.PART_DIR)
    if not os.path.isdir(pdir):
        return out
    for cid in os.listdir(pdir):
        sub = os.path.join(pdir, cid)
        if not os.path.isdir(sub):
            continue
        for name in os.listdir(sub):
            if not name.endswith(".json") or ".tmp." in name:
                continue
            try:
                with open(os.path.join(sub, name)) as f:
                    part = json.load(f)
                for leaf in (part.get("leaves") or {}).values():
                    for h, nb in leaf.values():
                        out[h] = int(nb)
            except (json.JSONDecodeError, OSError, KeyError, ValueError,
                    TypeError):
                continue
    return out


def sweep_store(root: str, policy: SweepPolicy,
                name: Optional[str] = None) -> Dict[str, Any]:
    """One retention pass over one store root, both tiers. Returns the
    report; never raises for per-object failures (a sweep must not die
    half way and strand the other stores in the loop). ``name`` keeps the
    report keyed by the store's REGISTERED name — the KV stats mirror and
    the sweep report must land under the same key in the state API."""
    from ray_tpu.ckpt.store import CheckpointStore
    from ray_tpu.ckpt.tier.tiered import TieredStore, _read_tier_file

    grace = policy.resolved_grace()
    backend, _sweep = _read_tier_file(root)
    if backend is not None:
        store: CheckpointStore = TieredStore(root, name, backend=backend,
                                             mirror=False)
    else:
        store = CheckpointStore(root, name)
    inflight = _inflight_chunks(root)
    report: Dict[str, Any] = {"root": store.root, "name": store.name,
                              "ts": time.time(),
                              "policy": policy.to_dict()}
    # -- local tier: the store's own retention, part-files protected ----
    report["local"] = store.retention(
        keep_last=policy.keep_last, keep_ids=list(policy.keep_ids),
        grace_s=grace)
    # -- remote tier ----------------------------------------------------
    if backend is not None:
        report["remote"] = _sweep_remote(store, backend, policy, grace,
                                         inflight)
    obs = _obs()
    obs["runs"].inc(1)
    reaped_m = report["local"].get("dropped_manifests", 0)
    reaped_b = report["local"].get("dropped_bytes", 0)
    if "remote" in report:
        reaped_m += report["remote"]["dropped_manifests"]
        reaped_b += report["remote"]["dropped_bytes"]
    obs["reaped_manifests"].inc(reaped_m)
    obs["reaped_bytes"].inc(reaped_b)
    report["dropped_manifests"] = reaped_m
    report["dropped_bytes"] = reaped_b
    return report


def _sweep_remote(store, backend, policy: SweepPolicy, grace: float,
                  inflight: Dict[str, int]) -> Dict[str, Any]:
    """Remote-tier half: drop remote manifests that survive neither
    locally nor under keep-last, then GC remote chunks no live manifest
    (either tier) or in-flight save references and whose upload age has
    cleared the grace window."""
    now = time.time()
    local_ids = set(store.list_ids())  # post-local-retention survivors
    pins = set(store.pins()) | set(policy.keep_ids)
    remote_ids = backend.list_manifests()
    # newest keep_last by id (ids sort by step; a remote-only manifest
    # has no local step row to consult); pinned ids are kept anyway and
    # must not consume keep-last slots
    if policy.keep_last is None:
        keep = set(remote_ids)
    elif policy.keep_last > 0:
        unpinned = [cid for cid in sorted(remote_ids) if cid not in pins]
        keep = set(unpinned[-policy.keep_last:])
    else:
        keep = set()
    keep |= local_ids | pins
    dropped_manifests = 0
    live: Dict[str, int] = dict(inflight)
    for cid in remote_ids:
        if cid not in keep:
            try:
                backend.delete_manifest(cid)
                dropped_manifests += 1
            except Exception:
                keep.add(cid)  # failed delete: keep its chunks live
    # live chunk set: every surviving manifest on either tier
    for cid in set(backend.list_manifests()) | local_ids:
        data = None
        try:
            data = backend.get_manifest(cid)
        except Exception:
            pass
        if data is None:
            try:
                with open(mf.manifest_path(store.root, cid), "rb") as f:
                    data = f.read()
            except OSError:
                continue
        try:
            live.update(mf.Manifest.from_json(json.loads(data)).chunk_set())
        except (json.JSONDecodeError, KeyError, ValueError):
            continue
    dropped_chunks = dropped_bytes = 0
    try:
        remote_chunks = backend.list_chunks()
    except Exception:
        remote_chunks = {}
    for h, n in remote_chunks.items():
        if h in live:
            continue
        mtime = None
        try:
            mtime = backend.chunk_mtime(h)
        except Exception:
            pass
        if mtime is None or now - mtime < grace:
            continue  # age unknown or young: may be an in-flight mirror
        try:
            backend.delete(h)
            dropped_chunks += 1
            dropped_bytes += n
        except Exception:
            continue
    return {"dropped_manifests": dropped_manifests,
            "dropped_chunks": dropped_chunks,
            "dropped_bytes": dropped_bytes}


def sweep_registered(entries: Dict[str, Dict[str, Any]],
                     ) -> List[Dict[str, Any]]:
    """Sweep every store whose KV stats mirror carries a ``sweep`` policy
    — the GCS-side cluster pass. ``entries`` is the decoded ns="ckpt"
    namespace dump ({store_name: stats})."""
    reports = []
    for name, stats in sorted(entries.items()):
        policy_d = stats.get("sweep")
        root = stats.get("root")
        if not policy_d or not root or not os.path.isdir(str(root)):
            continue
        try:
            reports.append(sweep_store(str(root),
                                       SweepPolicy.from_dict(policy_d),
                                       name=name))
        except Exception as e:
            reports.append({"root": root, "name": name, "ts": time.time(),
                            "error": repr(e)})
    return reports

"""ray_tpu.ckpt.tier: the pluggable checkpoint storage plane.

Layers (see ``ray_tpu/ckpt/README.md`` for the full design):

- ``backend``       — the ``ChunkBackend`` contract + ``LocalFSBackend``
- ``bucket``        — bucket/object-namespace backend, multipart uploads,
                      ``FaultShim`` fault/latency injector
- ``object_plane``  — chunks as owned cluster objects (vault actor)
- ``pario``         — bounded-parallel chunk IO with verification
- ``tiered``        — ``TieredStore``: local commits + async mirror pump,
                      residency, eviction, read-through restore
- ``sweeper``       — keep-last/pinned/grace retention across tiers
"""

from ray_tpu.ckpt.tier.backend import (BackendUnavailable, ChunkBackend,
                                       LocalFSBackend,
                                       backend_from_descriptor)
from ray_tpu.ckpt.tier.bucket import (BucketBackend, DirBucketClient,
                                      FaultShim)
from ray_tpu.ckpt.tier.pario import (ChunkFetchError, ChunkVerifyError,
                                     ParallelIO, coalesce_ranges)
from ray_tpu.ckpt.tier.sweeper import SweepPolicy, sweep_registered, sweep_store
from ray_tpu.ckpt.tier.tiered import TieredStore, attach

__all__ = [
    "BackendUnavailable",
    "ChunkBackend",
    "LocalFSBackend",
    "BucketBackend",
    "DirBucketClient",
    "FaultShim",
    "ObjectPlaneBackend",
    "ChunkFetchError",
    "ChunkVerifyError",
    "ParallelIO",
    "coalesce_ranges",
    "TieredStore",
    "attach",
    "SweepPolicy",
    "sweep_store",
    "sweep_registered",
    "backend_from_descriptor",
]


def __getattr__(name: str):
    # ObjectPlaneBackend pulls in the worker/actor machinery; keep it
    # lazy so offline tools can import the tier without a cluster stack
    if name == "ObjectPlaneBackend":
        from ray_tpu.ckpt.tier.object_plane import ObjectPlaneBackend

        return ObjectPlaneBackend
    raise AttributeError(f"module 'ray_tpu.ckpt.tier' has no attribute "
                         f"{name!r}")

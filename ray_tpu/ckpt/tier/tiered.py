"""TieredStore: a CheckpointStore whose chunk pool spans storage tiers.

Commits are EXACTLY today's local path — bounded-pause snapshot, local
chunk writes, atomic manifest commit — so the training loop never waits
on a remote tier. Durability arrives asynchronously:

1. **commit** lands locally; the checkpoint's *residency* becomes
   ``local``;
2. the **mirror pump** (one background thread) replicates chunk bytes to
   the remote :class:`ChunkBackend` through the parallel IO engine
   (content-address dedup: a chunk uploads once, ever — across steps AND
   across re-mirror attempts), then the manifest, and only then flips
   residency to ``remote``. A crash mid-mirror leaves ``mirroring`` —
   a partially-uploaded checkpoint is never presented as durable, and
   re-mirroring is idempotent by content address;
3. **evict_local** (explicit or policy-driven) drops local chunk bytes
   of a ``remote`` checkpoint (keeping chunks other local-resident
   checkpoints still reference). Restore then **read-through fetches**:
   local pool first, missing chunks pulled in parallel from the remote
   tier, sha256-verified, and cached back into the local pool.

The residency index lives at ``<root>/RESIDENCY`` (atomic JSON) and
rides the store's GCS KV mirror (ns="ckpt") so ``util.state``, the
dashboard and the CLI see per-checkpoint residency cluster-wide. The
backend descriptor persists at ``<root>/TIER`` so any process (the GCS
sweeper, ``ray-tpu ckpt``) can re-attach with :func:`attach`.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.ckpt import manifest as mf
from ray_tpu.ckpt.store import CheckpointStore
from ray_tpu.ckpt.tier.backend import (ChunkBackend, backend_from_descriptor)
from ray_tpu.ckpt.tier.pario import ChunkFetchError, ParallelIO

RESIDENCY_FILE = "RESIDENCY"
TIER_FILE = "TIER"

# residency states (monotonic per mirror attempt; evict sets "evicted"
# alongside "remote" — an evicted checkpoint is still fully durable)
LOCAL = "local"
MIRRORING = "mirroring"
REMOTE = "remote"


class TieredStore(CheckpointStore):
    """Local store + one remote chunk tier behind it."""

    def __init__(self, root: str, name: Optional[str] = None,
                 keep_last: Optional[int] = None, *,
                 backend: Optional[ChunkBackend] = None,
                 mirror: Optional[bool] = None,
                 io: Optional[ParallelIO] = None,
                 io_threads: Optional[int] = None,
                 sweep: Optional[Dict[str, Any]] = None):
        super().__init__(root, name, keep_last)
        from ray_tpu._private.config import RAY_CONFIG

        if backend is None:
            backend, persisted_sweep = _read_tier_file(self.root)
            if backend is None:
                raise ValueError(
                    f"store {self.root!r} has no TIER descriptor; pass "
                    f"backend= on first construction")
            if sweep is None:
                sweep = persisted_sweep
        self.backend = backend
        self.io = io or ParallelIO(backend, threads=io_threads)
        self.mirror_enabled = (RAY_CONFIG.ckpt_mirror_enabled
                               if mirror is None else bool(mirror))
        self.sweep_policy = dict(sweep) if sweep else None
        self._res_lock = threading.Lock()
        self._pump_q: "queue.Queue[Optional[str]]" = queue.Queue()
        self._pump_thread: Optional[threading.Thread] = None
        self._pump_stop = threading.Event()
        mf.atomic_write(os.path.join(self.root, TIER_FILE), json.dumps({
            "backend": self.backend.descriptor(),
            "sweep": self.sweep_policy}, sort_keys=True).encode())

    # -- residency index -----------------------------------------------

    def residency(self) -> Dict[str, Dict[str, Any]]:
        try:
            with open(os.path.join(self.root, RESIDENCY_FILE)) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return {}

    def _update_residency(self, ckpt_id: str, state: Optional[str] = None,
                          drop: bool = False, **extra: Any) -> None:
        with self._res_lock:
            idx = self.residency()
            if drop:
                idx.pop(ckpt_id, None)
            else:
                entry = idx.get(ckpt_id) or {}
                if state is not None:
                    entry["state"] = state
                entry["ts"] = time.time()
                entry.update(extra)
                idx[ckpt_id] = entry
            mf.atomic_write(os.path.join(self.root, RESIDENCY_FILE),
                            json.dumps(idx, sort_keys=True).encode())

    # -- commit: local as today, then enqueue the mirror ---------------

    def commit(self, manifest: mf.Manifest) -> None:
        super().commit(manifest)
        self.enqueue_mirror(manifest.ckpt_id)

    def enqueue_mirror(self, ckpt_id: str) -> None:
        """Register a locally-durable checkpoint for async mirroring.
        Used by ``commit`` and by non-commit writers (the weight plane's
        durable publish writes its manifest without moving ``LATEST`` and
        enqueues here). With mirroring disabled the checkpoint still gets
        a ``local`` residency entry."""
        self._update_residency(ckpt_id, LOCAL)
        if self.mirror_enabled:
            self._ensure_pump()
            self._pump_q.put(ckpt_id)

    # -- mirror pump ---------------------------------------------------

    def _ensure_pump(self) -> None:
        t = self._pump_thread
        if t is not None and t.is_alive():
            return
        self._pump_stop.clear()
        t = threading.Thread(target=self._pump_run, name="ckpt-mirror-pump",
                             daemon=True)
        self._pump_thread = t
        t.start()

    def _pump_run(self) -> None:
        while not self._pump_stop.is_set():
            try:
                cid = self._pump_q.get(timeout=0.2)
            except queue.Empty:
                continue
            if cid is None:
                return
            try:
                self.mirror_now(cid)
            except BaseException as e:
                # partial remote state is never presented as durable:
                # residency stays "mirroring" (+ the error) until an
                # explicit, idempotent re-mirror succeeds
                self._update_residency(cid, MIRRORING, error=repr(e))
                try:
                    from ray_tpu.util import events

                    events.record("ckpt_tier", "WARNING",
                                  f"mirror of {cid} failed: {e!r}",
                                  store=self.name)
                except Exception:
                    pass

    def mirror_now(self, ckpt_id: Optional[str] = None) -> Dict[str, int]:
        """Synchronously replicate one checkpoint (default: latest) to the
        remote tier. Idempotent by content address: chunks the tier holds
        are skipped, so retrying a crashed mirror uploads only the
        remainder. Order is chunks -> manifest -> residency flip, so a
        reader of the remote tier never sees a manifest whose chunks are
        missing, and residency=remote implies full durability."""
        ckpt_id = ckpt_id or self.latest_id()
        if ckpt_id is None:
            raise FileNotFoundError(f"store {self.root!r} has no checkpoint")
        manifest = self.read(ckpt_id)
        self._update_residency(ckpt_id, MIRRORING, error=None)
        t0 = time.monotonic()
        sizes = manifest.chunk_set()
        missing: Dict[str, int] = {}
        pre_dedup_chunks = pre_dedup_bytes = 0
        for h, n in sizes.items():
            if self.backend.has(h):
                pre_dedup_chunks += 1
                pre_dedup_bytes += n
            else:
                missing[h] = n
        counters = self.io.put_many(
            {h: (lambda h=h: mf.read_chunk(self.root, h)) for h in missing},
            sizes=missing)
        counters["dedup_chunks"] += pre_dedup_chunks
        counters["dedup_bytes"] += pre_dedup_bytes
        with open(mf.manifest_path(self.root, ckpt_id), "rb") as f:
            self.backend.put_manifest(ckpt_id, f.read())
        counters["mirror_s"] = time.monotonic() - t0
        self._update_residency(ckpt_id, REMOTE, error=None, **counters)
        self.mirror()  # refresh the KV stats mirror with new residency
        return counters

    def wait_mirrored(self, ckpt_id: Optional[str] = None,
                      timeout: float = 60.0) -> Dict[str, Any]:
        """Block until ``ckpt_id`` (default latest) is fully remote.
        Raises ``RuntimeError`` if its mirror attempt failed (the pump
        left an error on the residency entry) and ``TimeoutError`` if it
        never lands."""
        ckpt_id = ckpt_id or self.latest_id()
        if ckpt_id is None:
            raise FileNotFoundError(f"store {self.root!r} has no checkpoint")
        deadline = time.monotonic() + timeout
        while True:
            entry = self.residency().get(ckpt_id) or {}
            if entry.get("state") == REMOTE:
                return entry
            if entry.get("error"):
                raise RuntimeError(
                    f"mirror of {ckpt_id} failed: {entry['error']}")
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"checkpoint {ckpt_id} not mirrored within {timeout}s "
                    f"(state={entry.get('state')!r})")
            time.sleep(0.02)

    # -- eviction ------------------------------------------------------

    def evict_local(self, ckpt_id: str) -> Dict[str, int]:
        """Drop the local chunk bytes of a fully-mirrored checkpoint.
        Refuses unless residency is ``remote`` AND the remote tier still
        holds every chunk (verified now — never trade the only copy
        away). Chunks shared with a local-resident checkpoint stay."""
        entry = self.residency().get(ckpt_id) or {}
        if entry.get("state") != REMOTE:
            raise ValueError(
                f"refusing to evict {ckpt_id}: residency is "
                f"{entry.get('state', 'unknown')!r}, not {REMOTE!r}")
        manifest = self.read(ckpt_id)
        sizes = manifest.chunk_set()
        missing = [h for h in sizes if not self.backend.has(h)]
        if missing:
            raise RuntimeError(
                f"refusing to evict {ckpt_id}: remote tier lost "
                f"{len(missing)} chunks (e.g. {missing[0][:12]}…)")
        # chunks still referenced by a NON-evicted local checkpoint stay
        keep: set = set()
        residency = self.residency()
        for cid in self.list_ids():
            if cid == ckpt_id:
                continue
            if (residency.get(cid) or {}).get("evicted"):
                continue
            try:
                keep.update(self.read(cid).chunk_set())
            except (FileNotFoundError, json.JSONDecodeError, KeyError):
                continue
        dropped = freed = 0
        for h, n in sizes.items():
            if h in keep:
                continue
            path = mf.chunk_path(self.root, h)
            try:
                os.remove(path)
                dropped += 1
                freed += n
            except FileNotFoundError:
                pass
        self._update_residency(ckpt_id, REMOTE, evicted=True,
                               evicted_chunks=dropped, evicted_bytes=freed)
        self.mirror()
        return {"evicted_chunks": dropped, "evicted_bytes": freed}

    # -- read-through fetch (the restore path) -------------------------

    def fetch_chunks(self, sizes: Dict[str, int], *,
                     prefer: str = "local",
                     cache: bool = True) -> Dict[str, bytes]:
        """Read chunks across tiers: the local pool serves what it has,
        the rest is fetched in parallel from the remote tier (sha256
        verified) and — with ``cache=True`` — written back into the local
        pool so one remote round-trip serves every later reader on this
        host. ``prefer="remote"`` inverts the order (verification tools);
        a corrupt/unavailable remote chunk then falls back to the local
        copy instead of failing the batch."""
        out: Dict[str, bytes] = {}
        want_remote: Dict[str, int] = {}
        for h, n in sizes.items():
            if prefer != "remote" and os.path.exists(
                    mf.chunk_path(self.root, h)):
                out[h] = mf.read_chunk(self.root, h)
            else:
                want_remote[h] = n
        if want_remote:
            try:
                fetched = self.io.fetch(want_remote)
            except ChunkFetchError as e:
                fetched = dict(e.partial)
                # per-chunk fallback to the local tier; only a chunk
                # missing from EVERY tier fails the fetch
                unrecovered = {}
                for h, err in e.errors.items():
                    if os.path.exists(mf.chunk_path(self.root, h)):
                        fetched[h] = mf.read_chunk(self.root, h)
                    else:
                        unrecovered[h] = err
                if unrecovered:
                    raise ChunkFetchError(unrecovered, {**out, **fetched})
            for h, data in fetched.items():
                out[h] = data
                if cache:
                    mf.write_chunk(self.root, data)
        return out

    # -- verification / adoption ---------------------------------------

    def verify(self, ckpt_id: Optional[str] = None,
               deep: bool = False) -> Dict[str, Any]:
        """Check one checkpoint's remote durability. Shallow: manifest +
        every chunk present on the tier. ``deep=True`` additionally
        fetches every chunk and sha256-verifies the bytes."""
        ckpt_id = ckpt_id or self.latest_id()
        if ckpt_id is None:
            raise FileNotFoundError(f"store {self.root!r} has no checkpoint")
        manifest = self.read(ckpt_id)
        sizes = manifest.chunk_set()
        report: Dict[str, Any] = {"ckpt_id": ckpt_id, "chunks": len(sizes),
                                  "bytes": sum(sizes.values()), "deep": deep}
        try:
            self.backend.get_manifest(ckpt_id)
            report["manifest_remote"] = True
        except KeyError:
            report["manifest_remote"] = False
        missing = [h for h in sizes if not self.backend.has(h)]
        report["missing_chunks"] = len(missing)
        corrupt: List[str] = []
        if deep and not missing:
            try:
                self.io.fetch(sizes)
            except ChunkFetchError as e:
                corrupt = sorted(e.errors)
        report["corrupt_chunks"] = len(corrupt)
        report["ok"] = (report["manifest_remote"] and not missing
                        and not corrupt)
        return report

    def adopt_remote(self) -> List[str]:
        """Pull manifests that exist on the remote tier but not locally
        (a fresh/replacement host attaching to a durable store): the
        manifests land in the local index with residency
        ``remote, evicted`` — chunk bytes arrive lazily via read-through
        on first restore."""
        local = set(self.list_ids())
        adopted = []
        for cid in self.backend.list_manifests():
            if cid in local:
                continue
            data = self.backend.get_manifest(cid)
            json.loads(data)  # refuse to adopt a torn manifest
            mf.atomic_write(mf.manifest_path(self.root, cid), data)
            self._update_residency(cid, REMOTE, evicted=True, adopted=True)
            adopted.append(cid)
        if adopted:
            self.mirror()
        return adopted

    # -- stats / shutdown ----------------------------------------------

    def stats(self) -> Dict[str, Any]:
        s = super().stats()
        residency = self.residency()
        summary: Dict[str, int] = {}
        for entry in residency.values():
            key = "evicted" if entry.get("evicted") else \
                entry.get("state", "unknown")
            summary[key] = summary.get(key, 0) + 1
        s["tier"] = {
            "backend": self.backend.descriptor(),
            "mirror_enabled": self.mirror_enabled,
            "pump_alive": (self._pump_thread is not None
                           and self._pump_thread.is_alive()),
            "residency": residency,
            "residency_summary": summary,
            "io": dict(self.io.counters),
        }
        if self.sweep_policy:
            s["sweep"] = dict(self.sweep_policy)
        for row in s["checkpoints"]:
            entry = residency.get(row["ckpt_id"]) or {}
            row["residency"] = ("evicted" if entry.get("evicted")
                                else entry.get("state", LOCAL))
        return s

    def close(self, timeout: float = 5.0) -> None:
        """Stop the mirror pump (in-flight mirror finishes; queued ones
        are abandoned — they re-mirror idempotently on next attach)."""
        self._pump_stop.set()
        self._pump_q.put(None)
        t = self._pump_thread
        if t is not None and t.is_alive():
            t.join(timeout)


def _read_tier_file(root: str):
    try:
        with open(os.path.join(root, TIER_FILE)) as f:
            d = json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None, None
    desc = d.get("backend")
    backend = backend_from_descriptor(desc) if desc else None
    return backend, d.get("sweep")


def attach(root: str, **kwargs: Any) -> TieredStore:
    """Re-attach to a tiered store from its persisted ``TIER`` descriptor
    (CLI, sweeper, a replacement host)."""
    store = TieredStore(root, **kwargs)
    return store

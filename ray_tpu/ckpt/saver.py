"""Async checkpoint saver: bounded-pause snapshot, background commit.

The save path is split so the training step only ever pays for a host-RAM
snapshot (``np.copy`` of each local shard — the "barrier"), never for
serialization, hashing, or disk:

1. **snapshot** (caller thread, bounded pause): copy every array leaf;
   opaque non-array leaves are pickled immediately (they are tiny and a
   later mutation must not leak into the checkpoint);
2. **write** (background thread): serialize each shard box to bytes, hash
   it, write only chunks whose hash is new (content-addressed dedup — an
   unchanged leaf between steps costs zero write bytes), build the
   manifest, commit it atomically, run retention;
3. **backpressure**: at most one save is in flight; a second ``save()``
   while the previous is still writing blocks *then* (never mid-step),
   and the stall is recorded.

Metrics ride the PR 3 always-on registry (auto-flushed to the GCS):
``ray_tpu.ckpt.save_pause_seconds``, ``ray_tpu.ckpt.commit_seconds``,
``ray_tpu.ckpt.backpressure_seconds`` histograms and
``ray_tpu.ckpt.bytes_written`` / ``ray_tpu.ckpt.bytes_deduped`` counters.

Multi-host sharded saves (``save_host_shards`` + ``commit_host_parts``):
every host of the mesh writes its own shard chunks plus an atomic
per-host part-file; the committer (rank 0 by convention) merges the parts
into one manifest once all hosts have landed. No host ever serializes or
writes another host's bytes, and the checkpoint becomes visible only at
the single manifest commit.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.ckpt import manifest as mf
from ray_tpu.ckpt.store import CheckpointStore

_metrics_lock = threading.Lock()
_metrics: Optional[dict] = None


def _obs() -> dict:
    """Lazily-created plane metrics on the shared registry."""
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Counter, Histogram

            _metrics = {
                "pause": Histogram(
                    "ray_tpu.ckpt.save_pause_seconds",
                    "train-side pause while snapshotting state to host RAM",
                    boundaries=[0.001, 0.01, 0.1, 1, 10]),
                "commit": Histogram(
                    "ray_tpu.ckpt.commit_seconds",
                    "background serialize+write+commit duration",
                    boundaries=[0.01, 0.1, 1, 10, 100]),
                "backpressure": Histogram(
                    "ray_tpu.ckpt.backpressure_seconds",
                    "save() stall waiting for the previous in-flight save",
                    boundaries=[0.001, 0.01, 0.1, 1, 10]),
                "bytes_written": Counter(
                    "ray_tpu.ckpt.bytes_written",
                    "chunk bytes actually written (post-dedup)"),
                "bytes_deduped": Counter(
                    "ray_tpu.ckpt.bytes_deduped",
                    "chunk bytes skipped because the content already existed"),
            }
        return _metrics


# ---------------------------------------------------------------------------
# snapshot + encode
# ---------------------------------------------------------------------------


def _is_array(leaf: Any) -> bool:
    import numpy as np

    if isinstance(leaf, np.ndarray):
        return True
    t = type(leaf)
    return t.__module__.startswith(("jax", "jaxlib"))


def snapshot_tree(tree: Any) -> Tuple[Any, Dict[str, Tuple[str, Any]]]:
    """The bounded-pause half: ``(skeleton, {path: (kind, payload)})``.
    Array leaves are copied to host numpy; everything else is pickled NOW
    (through the audited serialization boundary) so later in-place
    mutation by the training loop cannot corrupt the checkpoint."""
    import numpy as np

    from ray_tpu._private.serialization import dumps_oob
    from ray_tpu.weights.spec import flatten_tree

    skeleton, leaves = flatten_tree(tree)
    snap: Dict[str, Tuple[str, Any]] = {}
    for path, leaf in leaves.items():
        if _is_array(leaf):
            snap[path] = (mf.ND, np.array(leaf, copy=True))
        else:
            snap[path] = (mf.PY, dumps_oob(leaf))
    return skeleton, snap


def _write_snapshot(store: CheckpointStore, ckpt_id: str, step: int,
                    skeleton: Any, snap: Dict[str, Tuple[str, Any]],
                    spec: Optional[Any], parent: Optional[str],
                    metrics: Optional[dict], pause_s: float,
                    keep_last: Optional[int]) -> mf.Manifest:
    """Background half: serialize/hash/write chunks, commit the manifest."""
    import numpy as np

    from ray_tpu.util import tracing

    t0 = time.monotonic()
    ser_start = time.time()
    spec_payload = None
    boxes_of = None
    if spec is not None:
        from ray_tpu.weights.spec import unique_boxes
        from ray_tpu.weights.store import _spec_payload

        spec_payload = _spec_payload(spec)
        boxes_of = {
            path: list(unique_boxes(spec.mesh, spec.part_of(path), shape))
            for path, (shape, _) in spec.meta.items()}
    leaves: Dict[str, mf.LeafEntry] = {}
    written = reused = written_b = reused_b = 0
    for path, (kind, payload) in sorted(snap.items()):
        if kind == mf.PY:
            h, created = mf.write_chunk(store.root, payload)
            entry = mf.LeafEntry(kind=mf.PY, shape=(), dtype="",
                                 chunks={"": (h, len(payload))})
            counts = [(created, len(payload))]
        else:
            from ray_tpu.weights.spec import box_slices

            arr = np.ascontiguousarray(payload)
            full = tuple((0, s) for s in arr.shape)
            boxes = (boxes_of or {}).get(path) or [full]
            chunks: Dict[str, Tuple[str, int]] = {}
            counts = []
            for box in boxes:
                data = np.ascontiguousarray(arr[box_slices(box)]).tobytes()
                h, created = mf.write_chunk(store.root, data)
                chunks[mf.encode_box(box)] = (h, len(data))
                counts.append((created, len(data)))
            entry = mf.LeafEntry(kind=mf.ND, shape=tuple(arr.shape),
                                 dtype=arr.dtype.str, chunks=chunks)
        leaves[path] = entry
        for created, n in counts:
            if created:
                written += 1
                written_b += n
            else:
                reused += 1
                reused_b += n
    # explicit record (not profile()): an exception mid-serialize must not
    # leave a suspended span generator behind on this background thread
    tracing.record_span("ckpt.serialize", ser_start, time.time(),
                        category="ckpt", ckpt_id=ckpt_id, step=step)
    total_b = written_b + reused_b
    write_s = time.monotonic() - t0
    manifest = mf.Manifest(
        ckpt_id=ckpt_id, step=step, ts=time.time(), parent=parent,
        skeleton=skeleton, spec=spec_payload, leaves=leaves,
        metrics=dict(metrics or {}),
        stats={"bytes_total": total_b, "bytes_written": written_b,
               "bytes_reused": reused_b, "chunks_written": written,
               "chunks_reused": reused,
               "dedup_ratio": (reused_b / total_b) if total_b else 0.0,
               "pause_s": pause_s, "write_s": write_s})
    with tracing.profile("ckpt.commit", category="ckpt", ckpt_id=ckpt_id):
        store.commit(manifest)
        if keep_last is not None:
            store.retention(keep_last)
    obs = _obs()
    obs["commit"].observe(write_s)
    obs["bytes_written"].inc(written_b)
    obs["bytes_deduped"].inc(reused_b)
    return manifest


class CheckpointSaver:
    """Per-process async saver over one store. Thread-safe; at most one
    save in flight (bounded memory: one extra state copy)."""

    def __init__(self, store: CheckpointStore,
                 keep_last: Optional[int] = None):
        self.store = store
        self.keep_last = keep_last if keep_last is not None else store.keep_last
        self._lock = threading.Lock()
        self._inflight: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._last_manifest: Optional[mf.Manifest] = None

    # -- public --------------------------------------------------------

    def save(self, tree: Any, *, step: int = 0,
             metrics: Optional[dict] = None, spec: Optional[Any] = None,
             blocking: bool = False) -> str:
        """Snapshot ``tree`` and commit it in the background. Returns the
        checkpoint id immediately (readers racing the commit use
        ``store.wait_for``). ``spec`` (a ``ShardedTreeSpec``) records the
        shard geometry and splits leaves into per-box chunks; without it
        the tree is saved as one full-extent chunk per leaf."""
        from ray_tpu.util import goodput, tracing

        with self._lock:
            # the whole caller-thread window — waiting out a prior
            # in-flight commit plus the synchronous snapshot — is what
            # the train loop experiences as the checkpoint pause
            with goodput.region("ckpt_pause"):
                self._drain_locked()  # backpressure + surface prior errors
                t0 = time.monotonic()
                with tracing.profile("ckpt.snapshot", category="ckpt",
                                     step=step):
                    skeleton, snap = snapshot_tree(tree)
                pause_s = time.monotonic() - t0
            goodput.count("ckpt_saves")
            _obs()["pause"].observe(pause_s)
            ckpt_id = mf.new_ckpt_id(step)
            parent = self.store.latest_id()

            def _run():
                try:
                    self._last_manifest = _write_snapshot(
                        self.store, ckpt_id, step, skeleton, snap, spec,
                        parent, metrics, pause_s, self.keep_last)
                except BaseException as e:  # surfaced on the next save/wait
                    self._error = e

            t = threading.Thread(target=_run, name="ckpt-saver", daemon=True)
            self._inflight = t
            t.start()
        if blocking:
            self.wait()
        return ckpt_id

    def wait(self, timeout: Optional[float] = None) -> Optional[mf.Manifest]:
        """Block until the in-flight save (if any) commits; re-raises a
        background failure. Returns the last committed manifest."""
        with self._lock:
            self._drain_locked(timeout)
            return self._last_manifest

    def in_flight(self) -> bool:
        t = self._inflight
        return t is not None and t.is_alive()

    # -- internals -----------------------------------------------------

    def _drain_locked(self, timeout: Optional[float] = None):
        t = self._inflight
        if t is not None and t.is_alive():
            t0 = time.monotonic()
            t.join(timeout)
            if t.is_alive():
                raise TimeoutError("checkpoint save still in flight")
            _obs()["backpressure"].observe(time.monotonic() - t0)
        self._inflight = None
        if self._error is not None:
            # raylint: disable=RCE001 the writer thread's _error/_last_manifest stores are ordered by the t.join()/is_alive() above (Thread.join happens-before); taking self._lock in _run instead would deadlock against this locked join
            err, self._error = self._error, None
            raise RuntimeError(f"background checkpoint save failed: {err!r}") \
                from err


def save_checkpoint(store: CheckpointStore, tree: Any, *, step: int = 0,
                    metrics: Optional[dict] = None,
                    spec: Optional[Any] = None,
                    keep_last: Optional[int] = None) -> mf.Manifest:
    """One-shot blocking save (tools, tests, small states)."""
    saver = CheckpointSaver(store, keep_last=keep_last)
    saver.save(tree, step=step, metrics=metrics, spec=spec, blocking=True)
    manifest = saver.wait()
    assert manifest is not None
    return manifest


# ---------------------------------------------------------------------------
# multi-host sharded save: chunks per host, one manifest commit
# ---------------------------------------------------------------------------


def save_host_shards(store: CheckpointStore, ckpt_id: str, spec: Any,
                     host: str, shards: Dict[str, Dict[Any, Any]],
                     *, skeleton: Any = None, step: int = 0) -> int:
    """One host's side of a sharded save: write the chunk bytes of the
    shard boxes this host is the designated writer for (first replica
    holder, matching the weight plane's publish convention), then land an
    atomic part-file describing them. Returns chunks written."""
    import numpy as np

    from ray_tpu.weights.spec import unique_boxes

    if skeleton is None:
        skeleton = {leaf: leaf for leaf in sorted(spec.meta)}
    part: Dict[str, Any] = {"host": host, "step": step, "leaves": {}}
    n = 0
    for leaf, boxes in shards.items():
        shape, _ = spec.meta[leaf]
        grid = unique_boxes(spec.mesh, spec.part_of(leaf), shape)
        entries = {}
        for box, arr in boxes.items():
            if grid.get(box, (host,))[0] != host:
                continue  # a replica peer writes this box
            data = np.ascontiguousarray(arr).tobytes()
            h, _created = mf.write_chunk(store.root, data)
            entries[mf.encode_box(box)] = [h, len(data)]
            n += 1
        if entries:
            part["leaves"][leaf] = entries
    import json

    mf.atomic_write(_part_path(store.root, ckpt_id, host),
                    json.dumps(part).encode())
    return n


def _part_path(root: str, ckpt_id: str, host: str) -> str:
    import os

    return os.path.join(root, mf.PART_DIR, ckpt_id,
                        f"{ckpt_id}.{host}.json")


def commit_host_parts(store: CheckpointStore, ckpt_id: str, spec: Any,
                      *, skeleton: Any = None, step: int = 0,
                      metrics: Optional[dict] = None,
                      timeout: float = 300.0) -> mf.Manifest:
    """The committer's side: wait for every mesh host's part-file, merge
    them into one manifest, commit atomically. Refuses to commit a
    checkpoint with missing shard boxes — a partial save never becomes
    visible."""
    import json
    import os

    from ray_tpu.weights.spec import unique_boxes
    from ray_tpu.weights.store import _spec_payload

    if skeleton is None:
        skeleton = {leaf: leaf for leaf in sorted(spec.meta)}
    hosts = list(spec.mesh.hosts)
    deadline = time.monotonic() + timeout
    parts = {}
    while len(parts) < len(hosts):
        for host in hosts:
            if host in parts:
                continue
            try:
                with open(_part_path(store.root, ckpt_id, host)) as f:
                    parts[host] = json.load(f)
            except (FileNotFoundError, json.JSONDecodeError):
                continue
        if len(parts) < len(hosts):
            if time.monotonic() >= deadline:
                missing = sorted(set(hosts) - set(parts))
                raise TimeoutError(
                    f"sharded save {ckpt_id!r}: hosts {missing} never "
                    f"landed their part-files within {timeout}s; refusing "
                    f"to commit a partial checkpoint")
            time.sleep(0.02)
    leaves: Dict[str, mf.LeafEntry] = {}
    total_b = 0
    for leaf, (shape, dtype) in spec.meta.items():
        chunks: Dict[str, Tuple[str, int]] = {}
        for part in parts.values():
            for box_s, (h, nb) in (part["leaves"].get(leaf) or {}).items():
                chunks[box_s] = (h, int(nb))
                total_b += int(nb)
        expect = {mf.encode_box(b) for b in
                  unique_boxes(spec.mesh, spec.part_of(leaf), shape)}
        if set(chunks) != expect:
            raise ValueError(
                f"sharded save {ckpt_id!r}: leaf {leaf!r} boxes "
                f"{sorted(set(chunks))} != expected {sorted(expect)}")
        leaves[leaf] = mf.LeafEntry(kind=mf.ND, shape=tuple(shape),
                                    dtype=dtype, chunks=chunks)
    manifest = mf.Manifest(
        ckpt_id=ckpt_id, step=step, ts=time.time(),
        parent=store.latest_id(), skeleton=skeleton,
        spec=_spec_payload(spec), leaves=leaves, metrics=dict(metrics or {}),
        stats={"bytes_total": total_b, "hosts": len(hosts)})
    store.commit(manifest)
    # part files are commit scaffolding, not checkpoint state
    import shutil

    shutil.rmtree(os.path.join(store.root, mf.PART_DIR, ckpt_id),
                  ignore_errors=True)
    return manifest

"""ray_tpu.ckpt: async sharded checkpointing with content-addressed chunks.

The checkpoint plane is the durable sibling of the weight plane
(``ray_tpu/weights``): the same ``(leaf, shard box)`` chunk geometry, but
committed to storage as an immutable manifest + content-addressed chunk
files instead of published to a live store actor. See
``ray_tpu/ckpt/README.md`` for the design.

Public surface::

    from ray_tpu import ckpt

    store = ckpt.CheckpointStore("/mnt/ckpts/run1", keep_last=5)
    saver = ckpt.CheckpointSaver(store)
    cid = saver.save(state, step=n)          # bounded pause, async commit
    saver.wait()                             # barrier (e.g. before exit)

    tree = ckpt.restore_tree(store)          # latest, full tree
    shards, stats = ckpt.restore_shards(store, dst_spec, host)
    plan = ckpt.restore_plan(store.latest(), dst_spec)  # no_gather() etc.

    store.pin(cid); store.retention(keep_last=3)
    ckpt.diff_manifests(store.read(a), store.read(b))   # chunk delta
"""

# Lazy exports (PEP 562), mirroring ray_tpu.weights: the plane pulls in
# numpy + the weights geometry, which must not ride along into processes
# that never checkpoint.
_EXPORTS = {
    "Manifest": "manifest", "LeafEntry": "manifest",
    "atomic_write": "manifest", "diff_manifests": "manifest",
    "new_ckpt_id": "manifest",
    "CheckpointStore": "store",
    "CheckpointSaver": "saver", "save_checkpoint": "saver",
    "save_host_shards": "saver", "commit_host_parts": "saver",
    "snapshot_tree": "saver",
    "restore_tree": "restore", "restore_shards": "restore",
    "restore_plan": "restore", "restore_spec": "restore",
    "restore_tree_shards": "restore",
    # storage tier plane (PR 19)
    "TieredStore": "tier.tiered", "attach": "tier.tiered",
    "ChunkBackend": "tier.backend", "LocalFSBackend": "tier.backend",
    "BucketBackend": "tier.bucket", "DirBucketClient": "tier.bucket",
    "FaultShim": "tier.bucket",
    "ObjectPlaneBackend": "tier.object_plane",
    "ParallelIO": "tier.pario",
    "SweepPolicy": "tier.sweeper", "sweep_store": "tier.sweeper",
}


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module 'ray_tpu.ckpt' has no attribute "
                             f"{name!r}")
    import importlib

    return getattr(importlib.import_module(f"ray_tpu.ckpt.{mod}"), name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))


__all__ = [
    "Manifest",
    "LeafEntry",
    "CheckpointStore",
    "CheckpointSaver",
    "atomic_write",
    "save_checkpoint",
    "save_host_shards",
    "commit_host_parts",
    "snapshot_tree",
    "restore_tree",
    "restore_shards",
    "restore_plan",
    "restore_spec",
    "restore_tree_shards",
    "diff_manifests",
    "new_ckpt_id",
    "TieredStore",
    "ChunkBackend",
    "LocalFSBackend",
    "BucketBackend",
    "DirBucketClient",
    "FaultShim",
    "ObjectPlaneBackend",
    "ParallelIO",
    "SweepPolicy",
    "sweep_store",
]

"""CheckpointStore: a named, GCS-registered checkpoint directory.

One store root holds many checkpoints sharing one content-addressed chunk
pool (``manifest.py`` layout). The store adds the management plane:

- ``list()/latest()/read()`` — enumeration and lookup, tolerant of torn
  files (a crashed save is invisible, never an error);
- ``pin()/unpin()`` — pinned checkpoints survive retention (milestones,
  eval-best);
- ``retention(keep_last)`` — bounded keep-last GC: unpinned manifests
  beyond the newest ``keep_last`` are dropped, then chunks no surviving
  manifest references are deleted. Drops are *counted* (manifests/chunks/
  bytes), mirrored to the GCS so truncation is visible, never silent;
- GCS registration: when a cluster is up, every mutation mirrors the
  store's stats to the KV ``ckpt`` namespace under the store name —
  feeding ``util.state.list_checkpoints()``, the dashboard's
  ``/api/checkpoints`` and ``ray-tpu ckpt list``. Registration is
  best-effort by contract: checkpointing must work (and is tested)
  without any cluster at all.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.ckpt import manifest as mf


class CheckpointStore:
    """Handle on one checkpoint directory (create-or-attach)."""

    def __init__(self, root: str, name: Optional[str] = None,
                 keep_last: Optional[int] = None):
        self.root = os.path.abspath(os.fspath(root))
        self.name = name or os.path.basename(self.root.rstrip("/")) or "ckpt"
        self.keep_last = keep_last
        os.makedirs(self.root, exist_ok=True)
        # monotonically-accumulated drop/GC counters (persisted so they
        # survive the process: truncation evidence must not vanish)
        self._counters = self._load_counters()
        self._last_mirror = 0.0
        # commit (caller thread) and a TieredStore's mirror pump both
        # throttle through _last_mirror
        self._mirror_lock = threading.Lock()

    # -- lookup --------------------------------------------------------

    def list(self) -> List[mf.Manifest]:
        """All valid checkpoints, oldest-first."""
        return [mf.read_manifest(self.root, cid)
                for cid in mf.list_manifest_ids(self.root)]

    def list_ids(self) -> List[str]:
        return mf.list_manifest_ids(self.root)

    def read(self, ckpt_id: str) -> mf.Manifest:
        return mf.read_manifest(self.root, ckpt_id)

    def latest_id(self) -> Optional[str]:
        """The committed ``LATEST`` pointer; falls back to the newest
        valid manifest when the pointer is missing or torn."""
        cid = mf.read_latest_id(self.root)
        if cid is not None:
            return cid
        ids = mf.list_manifest_ids(self.root)
        return ids[-1] if ids else None

    def latest(self) -> Optional[mf.Manifest]:
        cid = self.latest_id()
        return mf.read_manifest(self.root, cid) if cid else None

    def wait_for(self, ckpt_id: str, timeout: float = 30.0) -> mf.Manifest:
        """Block until ``ckpt_id``'s manifest is committed (the async
        saver hands out ids at snapshot time; readers that race the
        background commit park here instead of failing)."""
        deadline = time.monotonic() + timeout
        while True:
            try:
                return mf.read_manifest(self.root, ckpt_id)
            except (FileNotFoundError, json.JSONDecodeError, KeyError):
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"checkpoint {ckpt_id!r} not committed within "
                        f"{timeout}s (saver crashed mid-write?)")
                time.sleep(0.02)

    # -- commit --------------------------------------------------------

    def commit(self, manifest: mf.Manifest) -> None:
        mf.commit(self.root, manifest)
        # throttled: stats() walks every manifest + the chunk pool, and a
        # commit-per-step loop (tune trials) must not pay that each report
        self.mirror(min_interval=2.0)

    # -- pins ----------------------------------------------------------

    def pins(self) -> List[str]:
        try:
            with open(os.path.join(self.root, mf.PINS_FILE)) as f:
                return list(json.load(f))
        except (FileNotFoundError, json.JSONDecodeError):
            return []

    def pin(self, ckpt_id: str) -> None:
        self.read(ckpt_id)  # refuse to pin something that does not exist
        pins = self.pins()
        if ckpt_id not in pins:
            pins.append(ckpt_id)
            mf.atomic_write(os.path.join(self.root, mf.PINS_FILE),
                            json.dumps(pins).encode())
        self.mirror()

    def unpin(self, ckpt_id: str) -> None:
        pins = [p for p in self.pins() if p != ckpt_id]
        mf.atomic_write(os.path.join(self.root, mf.PINS_FILE),
                        json.dumps(pins).encode())
        self.mirror()

    # -- retention -----------------------------------------------------

    def retention(self, keep_last: Optional[int] = None,
                  keep_ids: Optional[List[str]] = None,
                  grace_s: float = 300.0) -> Dict[str, int]:
        """Bounded retention: keep the newest ``keep_last`` checkpoints
        (plus every pinned one, plus any explicitly listed ``keep_ids``),
        drop the rest, then garbage-collect unreferenced chunks. Returns
        and accumulates drop counters.

        ``grace_s``: chunks younger than this are never collected, even
        when no manifest references them — an async saver (or a sharded
        save's peer hosts) writes chunks BEFORE its manifest commits, and
        a concurrent retention pass must not delete them out from under
        the commit. Pass 0 only when no save can be in flight."""
        keep_last = self.keep_last if keep_last is None else keep_last
        ids = mf.list_manifest_ids(self.root)
        keep = set(self.pins()) | set(keep_ids or ())
        if keep_last is None:
            keep.update(ids)
        elif keep_last > 0:
            # keep-last counts checkpoints NOT already kept by pin/keep_ids:
            # pinned auxiliary manifests (e.g. the weight plane's durable
            # ``weights-*`` versions, which sort after ``step*`` ids) must
            # not consume keep-last slots and evict the newest training
            # checkpoint
            keep.update([cid for cid in ids if cid not in keep][-keep_last:])
        drop = [cid for cid in ids if cid not in keep]
        dropped_chunks = dropped_bytes = 0
        live: Dict[str, int] = {}
        for cid in ids:
            if cid in keep:
                try:
                    live.update(self.read(cid).chunk_set())
                except (FileNotFoundError, json.JSONDecodeError, KeyError):
                    continue
        # chunks referenced by in-flight sharded saves (un-committed
        # part-files) are live regardless of age: a slow peer host's
        # already-written chunks must survive a racing retention pass
        # even past the grace window
        from ray_tpu.ckpt.tier.sweeper import _inflight_chunks

        live.update(_inflight_chunks(self.root))
        for cid in drop:
            try:
                os.remove(mf.manifest_path(self.root, cid))
            except FileNotFoundError:
                pass
        # chunk GC: anything on disk no surviving manifest references
        cdir = os.path.join(self.root, mf.CHUNK_DIR)
        if os.path.isdir(cdir):
            for sub in os.listdir(cdir):
                subdir = os.path.join(cdir, sub)
                if not os.path.isdir(subdir):
                    continue
                for h in os.listdir(subdir):
                    if h in live or ".tmp." in h:
                        continue
                    path = os.path.join(subdir, h)
                    try:
                        if grace_s and (time.time() - os.path.getmtime(path)
                                        < grace_s):
                            continue  # may belong to an in-flight save
                        nbytes = os.path.getsize(path)
                        os.remove(path)
                        dropped_chunks += 1
                        dropped_bytes += nbytes
                    except OSError:
                        continue
        out = {"dropped_manifests": len(drop),
               "dropped_chunks": dropped_chunks,
               "dropped_bytes": dropped_bytes}
        if drop or dropped_chunks:
            for k, v in out.items():
                self._counters[k] = self._counters.get(k, 0) + v
            self._save_counters()
        self.mirror()
        return out

    # -- stats / GCS mirror --------------------------------------------

    def stats(self) -> Dict[str, Any]:
        manifests = self.list()
        pins = set(self.pins())
        chunk_bytes = 0
        cdir = os.path.join(self.root, mf.CHUNK_DIR)
        if os.path.isdir(cdir):
            for sub in os.listdir(cdir):
                subdir = os.path.join(cdir, sub)
                if os.path.isdir(subdir):
                    for h in os.listdir(subdir):
                        if ".tmp." not in h:
                            try:
                                chunk_bytes += os.path.getsize(
                                    os.path.join(subdir, h))
                            except OSError:
                                pass
        latest = self.latest_id()
        return {
            "name": self.name,
            "root": self.root,
            "latest": latest,
            "num_checkpoints": len(manifests),
            "pinned": sorted(pins),
            "chunk_pool_bytes": chunk_bytes,
            "drops": dict(self._counters),
            "checkpoints": [
                {"ckpt_id": m.ckpt_id, "step": m.step, "ts": m.ts,
                 "parent": m.parent, "total_bytes": m.total_bytes(),
                 "num_leaves": len(m.leaves),
                 "pinned": m.ckpt_id in pins,
                 "stats": m.stats, "metrics": m.metrics}
                for m in manifests
            ],
        }

    def mirror(self, min_interval: float = 0.0) -> None:
        """Mirror store stats into the GCS KV (``ckpt`` namespace) for the
        state API / dashboard / CLI. Best-effort by contract: stores must
        work with no cluster at all (unit tests, offline tools).
        ``min_interval`` rate-limits the (whole-store) stats walk on hot
        paths; explicit mutations (pin/retention) mirror unconditionally."""
        with self._mirror_lock:
            if min_interval and (time.time() - self._last_mirror
                                 < min_interval):
                return
            self._last_mirror = time.time()
        try:
            from ray_tpu._private.worker import is_initialized

            if not is_initialized():
                return
            from ray_tpu._private import wire
            from ray_tpu.experimental.internal_kv import _internal_kv_put

            _internal_kv_put(self.name.encode(), wire.dumps(self.stats()),
                             namespace="ckpt")
        except Exception:  # stats mirror is best-effort by contract
            pass

    # -- counters ------------------------------------------------------

    def _counters_path(self) -> str:
        return os.path.join(self.root, "retention_counters.json")

    def _load_counters(self) -> Dict[str, int]:
        try:
            with open(self._counters_path()) as f:
                return {k: int(v) for k, v in json.load(f).items()}
        except (FileNotFoundError, json.JSONDecodeError, ValueError):
            return {}

    def _save_counters(self) -> None:
        mf.atomic_write(self._counters_path(),
                        json.dumps(self._counters).encode())

"""Tuner: hyperparameter sweeps over trial actors.

Reference: python/ray/tune/tuner.py + execution/tune_controller.py — trials
run as resource-requesting actors; intermediate ``tune.report`` results flow
through a report hub actor; the scheduler (e.g. ASHA) stops losers early by
failing their next report with ``TuneStopException``.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.exceptions import TaskError
from ray_tpu.tune.schedulers import CONTINUE, FIFOScheduler, STOP

_session = threading.local()


class TuneStopException(Exception):
    """Raised inside a trial when the scheduler stops it early."""


class TuneExploitException(Exception):
    """Raised inside a trial when PBT replaces it with a better trial's
    checkpoint + perturbed config; the tuner restarts the trial."""

    def __init__(self, config, checkpoint):
        super().__init__("pbt exploit")
        self.config = config
        self.checkpoint = checkpoint


@dataclass
class TuneConfig:
    metric: str = "score"
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    scheduler: Any = None
    search_alg: Any = None  # a tune.searchers.Searcher proposing configs
    seed: int = 0
    # checkpoint-plane store root for trial checkpoints (PBT exploit state);
    # default: a run-scoped dir under /tmp, deleted when fit() returns.
    # On a multi-node cluster this MUST be a path shared by every trial
    # node (NFS/gcsfuse) — the same contract as RunConfig.storage_path
    storage_path: Optional[str] = None


@dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Dict[str, Any]
    error: Optional[str] = None
    stopped_early: bool = False


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: str, mode: str):
        self.results = results
        self._metric = metric
        self._mode = mode

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        ok = [r for r in self.results if r.error is None and metric in r.metrics]
        if not ok:
            raise ValueError("no successful trials with the requested metric")
        sign = 1 if mode == "max" else -1
        return max(ok, key=lambda r: sign * float(r.metrics[metric]))

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([
            {**r.metrics, **{f"config/{k}": v for k, v in r.config.items()},
             "trial_id": r.trial_id, "error": r.error}
            for r in self.results
        ])

    def __len__(self):
        return len(self.results)


@ray_tpu.remote(num_cpus=0.1)
class _ReportHub:
    """Collects trial reports and runs scheduler decisions centrally."""

    def __init__(self, scheduler_blob: bytes):
        # driver-authored blob: decode only through the audited
        # serialization boundary (raylint SER001)
        from ray_tpu._private.serialization import loads_trusted

        self.scheduler = loads_trusted(scheduler_blob)
        self.latest: Dict[str, Dict] = {}
        self.iters: Dict[str, int] = {}
        self.registered: set = set()
        self.finished: set = set()
        # report() runs on the actor's thread pool (max_concurrency > 1);
        # schedulers iterate shared dicts, so serialize their callbacks.
        # The condition variable implements synchronized-PBT rendezvous.
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)

    def register_trial(self, trial_id: str, config: Dict):
        # PBT needs trial configs for exploit mutation
        with self._cv:
            self.registered.add(trial_id)
            self.finished.discard(trial_id)  # exploit relaunch
            hook = getattr(self.scheduler, "register_trial", None)
            if hook is not None:
                hook(trial_id, config)
            self._cv.notify_all()
        return True

    def finish_trial(self, trial_id: str):
        """A trial completed or errored: release any rendezvous waiters."""
        with self._cv:
            self.finished.add(trial_id)
            self._cv.notify_all()
        return True

    def report(self, trial_id: str, metrics: Dict, checkpoint=None):
        with self._cv:
            self.iters[trial_id] = self.iters.get(trial_id, 0) + 1
            t = self.iters[trial_id]
            metrics = dict(metrics)
            metrics.setdefault("training_iteration", t)
            self.latest[trial_id] = metrics
            if checkpoint is not None:
                hook = getattr(self.scheduler, "record_checkpoint", None)
                if hook is not None:
                    hook(trial_id, checkpoint)
            sync_t = getattr(self.scheduler, "synch_interval", None)
            if sync_t and t % sync_t == 0:
                # synchronized PBT: wait until every live trial reached this
                # boundary (or finished) so the decision sees the whole
                # population. Bounded: a crashed trial, or one whose worker
                # cannot schedule (num_samples > max_concurrent_trials on a
                # saturated cluster), degrades to a partial-population
                # decision after the timeout instead of wedging the run.
                def _ready():
                    return all(self.iters.get(tid, 0) >= t
                               or tid in self.finished
                               for tid in self.registered)

                self._cv.notify_all()
                self._cv.wait_for(_ready, timeout=30.0)
            return self.scheduler.on_result(trial_id, metrics)

    # NOTE: exploited trials do NOT reset their iteration counter — the
    # count is total iterations executed by the trial slot, so perturbation
    # boundaries (t % interval) stay aligned across the population and the
    # synch rendezvous is not desynchronized by a routine exploit.

    def get_latest(self):
        return dict(self.latest)


@ray_tpu.remote
def _run_trial(fn_blob: bytes, config, trial_id: str, hub,
               ckpt_root=None) -> Dict:
    # runtime imports: the decorated function pickles by value, so it must not
    # close over module globals (the thread-local session is unpicklable)
    from ray_tpu._private.serialization import loads_trusted

    from ray_tpu.tune import tuner as _tuner

    # driver-authored trainable blob: audited boundary only (raylint SER001)
    fn = loads_trusted(fn_blob)
    _tuner._session.hub = hub
    _tuner._session.trial_id = trial_id
    _tuner._session.ckpt_root = ckpt_root
    config = _tuner._resolve_checkpoint_ref(config)
    try:
        out = fn(config)
        return {"metrics": out if isinstance(out, dict) else {}, "stopped": False}
    except _tuner.TuneStopException:
        return {"metrics": {}, "stopped": True}
    except _tuner.TuneExploitException as e:
        return {"metrics": {}, "exploit": {"config": e.config,
                                           "checkpoint": e.checkpoint}}
    finally:
        _tuner._session.hub = None


def _resolve_checkpoint_ref(config):
    """Rehydrate a checkpoint-plane ref in ``config["__checkpoint__"]``
    (the shape PBT exploit hands around) back into the tree the trainable
    expects. Plain checkpoint values pass through untouched."""
    ref = (config or {}).get("__checkpoint__")
    if not (isinstance(ref, dict) and "__ckpt_ref__" in ref):
        return config
    from ray_tpu.ckpt import CheckpointStore, restore_tree

    config = dict(config)
    try:
        config["__checkpoint__"] = restore_tree(
            CheckpointStore(ref["root"]), ref["__ckpt_ref__"], timeout=5.0)
    except (TimeoutError, FileNotFoundError) as e:
        raise RuntimeError(
            f"trial checkpoint {ref['__ckpt_ref__']!r} is not readable "
            f"from this node (store root {ref['root']!r}). PBT exploit "
            f"state lives on the checkpoint plane; on a multi-node "
            f"cluster set TuneConfig.storage_path to a path shared by "
            f"every trial node (NFS/gcsfuse), like RunConfig.storage_path "
            f"for train runs") from e
    return config


_trial_savers: Dict[str, Any] = {}  # store root -> per-process saver


def _save_trial_checkpoint(checkpoint):
    """Route a trial's reported checkpoint through the checkpoint plane:
    the tree is committed to the run's store and only a tiny manifest ref
    crosses to the hub — PBT exploit then is a manifest swap, and the
    donor state is never re-pickled through hub -> tuner -> trial.
    Content addressing dedups the unchanged leaves across a trial's
    consecutive reports and across cloned trials (the store root must be
    shared across trial nodes; see TuneConfig.storage_path)."""
    root = getattr(_session, "ckpt_root", None)
    if root is None or checkpoint is None or (
            isinstance(checkpoint, dict) and "__ckpt_ref__" in checkpoint):
        return checkpoint
    from ray_tpu.ckpt import CheckpointSaver, CheckpointStore

    saver = _trial_savers.get(root)
    if saver is None:
        saver = _trial_savers[root] = CheckpointSaver(CheckpointStore(root))
    # blocking: the ref may be exploited by another trial the moment the
    # hub sees it, so the manifest must be committed before it escapes
    cid = saver.save(checkpoint, blocking=True)
    return {"__ckpt_ref__": cid, "root": root}


def report(metrics: Dict[str, Any], checkpoint=None):
    """tune.report inside a trial. Raises TuneStopException when the
    scheduler stops the trial, TuneExploitException when PBT replaces it
    with a better trial's state. Checkpoints are saved to the run's
    checkpoint-plane store trial-side; the hub only ever sees manifest
    refs."""
    hub = getattr(_session, "hub", None)
    if hub is None:
        raise RuntimeError("tune.report called outside a trial")
    checkpoint = _save_trial_checkpoint(checkpoint)
    decision = ray_tpu.get(
        hub.report.remote(_session.trial_id, metrics, checkpoint), timeout=300)
    if decision == STOP:
        raise TuneStopException()
    if isinstance(decision, (tuple, list)) and decision and decision[0] == "EXPLOIT":
        payload = decision[1]
        raise TuneExploitException(payload["config"], payload["checkpoint"])


class Tuner:
    def __init__(self, trainable: Callable, *, param_space: Dict[str, Any],
                 tune_config: Optional[TuneConfig] = None,
                 resources_per_trial: Optional[Dict[str, float]] = None):
        self.trainable = trainable
        self.param_space = param_space
        self.tune_config = tune_config or TuneConfig()
        self.resources = resources_per_trial or {"CPU": 1.0}

    def fit(self) -> ResultGrid:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        tc = self.tune_config
        scheduler = tc.scheduler or FIFOScheduler()
        searcher = tc.search_alg
        if searcher is None:
            from ray_tpu.tune.searchers import BasicVariantSearcher

            searcher = BasicVariantSearcher(self.param_space, tc.num_samples,
                                            tc.seed)
        run_tag = uuid.uuid4().hex[:8]
        hub = _ReportHub.options(
            # every RUNNING trial may hold one hub thread at a synch
            # rendezvous; size the pool so waiters can never starve the
            # report() that would release them
            name=f"tune_hub_{run_tag}",
            max_concurrency=max(16, tc.max_concurrent_trials + 4),
        ).remote(cloudpickle.dumps(scheduler))
        fn_blob = cloudpickle.dumps(self.trainable)
        # trial checkpoints live on the checkpoint plane for the run's
        # lifetime; an ephemeral (default) root is deleted on completion
        ckpt_root = tc.storage_path or os.path.join(
            "/tmp/ray_tpu/tune_ckpts", f"run_{run_tag}")
        ephemeral_store = tc.storage_path is None

        pending: List[tuple] = []
        running: Dict[Any, tuple] = {}
        results: List[TrialResult] = []
        trial_seq = 0
        exhausted = False

        def launch(trial_id, cfg):
            ray_tpu.get(hub.register_trial.remote(trial_id, cfg), timeout=60)
            ref = _run_trial.options(
                num_cpus=self.resources.get("CPU", 1.0),
                num_tpus=self.resources.get("TPU", 0.0),
                resources={k: v for k, v in self.resources.items()
                           if k not in ("CPU", "TPU")},
            ).remote(fn_blob, cfg, trial_id, hub, ckpt_root)
            running[ref] = (trial_id, cfg)

        while True:
            # refill from exploit-requeues first, then the searcher
            while pending and len(running) < tc.max_concurrent_trials:
                launch(*pending.pop(0))
            while not exhausted and len(running) < tc.max_concurrent_trials:
                trial_id = f"trial_{trial_seq:05d}"
                cfg = searcher.suggest(trial_id)
                if cfg is None:
                    exhausted = True
                    break
                trial_seq += 1
                launch(trial_id, cfg)
            if not running and not pending and exhausted:
                break
            ready, _ = ray_tpu.wait(list(running.keys()), num_returns=1,
                                    timeout=1.0)
            for ref in ready:
                trial_id, cfg = running.pop(ref)
                latest = ray_tpu.get(hub.get_latest.remote(), timeout=60).get(
                    trial_id, {})
                try:
                    out = ray_tpu.get(ref, timeout=60)
                except TaskError as e:
                    ray_tpu.get(hub.finish_trial.remote(trial_id), timeout=60)
                    cfg_clean = {k: v for k, v in cfg.items()
                                 if k != "__checkpoint__"}
                    results.append(TrialResult(trial_id, cfg_clean, latest,
                                               error=str(e)[:500]))
                    searcher.on_trial_complete(
                        trial_id, {**latest, "__config__": cfg_clean})
                    continue
                exploit = out.get("exploit")
                if exploit is not None:
                    # PBT: restart this trial from the donor's checkpoint
                    # with the perturbed config
                    new_cfg = dict(exploit["config"])
                    new_cfg["__checkpoint__"] = exploit["checkpoint"]
                    pending.append((trial_id, new_cfg))
                    continue
                ray_tpu.get(hub.finish_trial.remote(trial_id), timeout=60)
                final = dict(latest)
                final.update(out.get("metrics") or {})
                cfg_clean = {k: v for k, v in cfg.items()
                             if k != "__checkpoint__"}
                results.append(TrialResult(trial_id, cfg_clean, final,
                                           stopped_early=out.get("stopped",
                                                                 False)))
                searcher.on_trial_complete(
                    trial_id, {**final, "__config__": cfg_clean})
        ray_tpu.kill(hub)
        if ephemeral_store:
            import shutil

            shutil.rmtree(ckpt_root, ignore_errors=True)
        return ResultGrid(results, tc.metric, tc.mode)

"""Tuner: hyperparameter sweeps over trial actors.

Reference: python/ray/tune/tuner.py + execution/tune_controller.py — trials
run as resource-requesting actors; intermediate ``tune.report`` results flow
through a report hub actor; the scheduler (e.g. ASHA) stops losers early by
failing their next report with ``TuneStopException``.
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.exceptions import TaskError
from ray_tpu.tune.schedulers import CONTINUE, FIFOScheduler, STOP
from ray_tpu.tune.search import generate_variants

_session = threading.local()


class TuneStopException(Exception):
    """Raised inside a trial when the scheduler stops it early."""


@dataclass
class TuneConfig:
    metric: str = "score"
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    scheduler: Any = None
    seed: int = 0


@dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Dict[str, Any]
    error: Optional[str] = None
    stopped_early: bool = False


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: str, mode: str):
        self.results = results
        self._metric = metric
        self._mode = mode

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        ok = [r for r in self.results if r.error is None and metric in r.metrics]
        if not ok:
            raise ValueError("no successful trials with the requested metric")
        sign = 1 if mode == "max" else -1
        return max(ok, key=lambda r: sign * float(r.metrics[metric]))

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([
            {**r.metrics, **{f"config/{k}": v for k, v in r.config.items()},
             "trial_id": r.trial_id, "error": r.error}
            for r in self.results
        ])

    def __len__(self):
        return len(self.results)


@ray_tpu.remote(num_cpus=0.1)
class _ReportHub:
    """Collects trial reports and runs scheduler decisions centrally."""

    def __init__(self, scheduler_blob: bytes):
        self.scheduler = cloudpickle.loads(scheduler_blob)
        self.latest: Dict[str, Dict] = {}
        self.iters: Dict[str, int] = {}

    def report(self, trial_id: str, metrics: Dict) -> str:
        self.iters[trial_id] = self.iters.get(trial_id, 0) + 1
        metrics = dict(metrics)
        metrics.setdefault("training_iteration", self.iters[trial_id])
        self.latest[trial_id] = metrics
        return self.scheduler.on_result(trial_id, metrics)

    def get_latest(self):
        return dict(self.latest)


@ray_tpu.remote
def _run_trial(fn_blob: bytes, config, trial_id: str, hub) -> Dict:
    # runtime imports: the decorated function pickles by value, so it must not
    # close over module globals (the thread-local session is unpicklable)
    import cloudpickle as _cp

    from ray_tpu.tune import tuner as _tuner

    fn = _cp.loads(fn_blob)
    _tuner._session.hub = hub
    _tuner._session.trial_id = trial_id
    try:
        out = fn(config)
        return {"metrics": out if isinstance(out, dict) else {}, "stopped": False}
    except _tuner.TuneStopException:
        return {"metrics": {}, "stopped": True}
    finally:
        _tuner._session.hub = None


def report(metrics: Dict[str, Any], checkpoint=None):
    """tune.report inside a trial; raises TuneStopException on ASHA stop."""
    hub = getattr(_session, "hub", None)
    if hub is None:
        raise RuntimeError("tune.report called outside a trial")
    decision = ray_tpu.get(
        hub.report.remote(_session.trial_id, metrics), timeout=300)
    if decision == STOP:
        raise TuneStopException()


class Tuner:
    def __init__(self, trainable: Callable, *, param_space: Dict[str, Any],
                 tune_config: Optional[TuneConfig] = None,
                 resources_per_trial: Optional[Dict[str, float]] = None):
        self.trainable = trainable
        self.param_space = param_space
        self.tune_config = tune_config or TuneConfig()
        self.resources = resources_per_trial or {"CPU": 1.0}

    def fit(self) -> ResultGrid:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        tc = self.tune_config
        variants = generate_variants(self.param_space, tc.num_samples, tc.seed)
        scheduler = tc.scheduler or FIFOScheduler()
        hub = _ReportHub.options(
            name=f"tune_hub_{uuid.uuid4().hex[:8]}", max_concurrency=16,
        ).remote(cloudpickle.dumps(scheduler))
        fn_blob = cloudpickle.dumps(self.trainable)

        pending = [(f"trial_{i:05d}", cfg) for i, cfg in enumerate(variants)]
        running: Dict[Any, tuple] = {}
        results: List[TrialResult] = []
        while pending or running:
            while pending and len(running) < tc.max_concurrent_trials:
                trial_id, cfg = pending.pop(0)
                ref = _run_trial.options(
                    num_cpus=self.resources.get("CPU", 1.0),
                    num_tpus=self.resources.get("TPU", 0.0),
                    resources={k: v for k, v in self.resources.items()
                               if k not in ("CPU", "TPU")},
                ).remote(fn_blob, cfg, trial_id, hub)
                running[ref] = (trial_id, cfg)
            ready, _ = ray_tpu.wait(list(running.keys()), num_returns=1,
                                    timeout=1.0)
            for ref in ready:
                trial_id, cfg = running.pop(ref)
                latest = ray_tpu.get(hub.get_latest.remote(), timeout=60).get(
                    trial_id, {})
                try:
                    out = ray_tpu.get(ref, timeout=60)
                    final = dict(latest)
                    final.update(out.get("metrics") or {})
                    results.append(TrialResult(trial_id, cfg, final,
                                               stopped_early=out.get("stopped",
                                                                     False)))
                except TaskError as e:
                    results.append(TrialResult(trial_id, cfg, latest,
                                               error=str(e)[:500]))
        ray_tpu.kill(hub)
        return ResultGrid(results, tc.metric, tc.mode)

"""Search algorithms that propose configs sequentially.

Reference: python/ray/tune/search — the Searcher interface
(search/searcher.py: suggest / on_trial_complete) with concrete
dependency-free implementations standing in for the optuna/hyperopt
integrations: a quasi-random low-discrepancy sampler and a TPE-style
good/bad density searcher."""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.search import GridSearch, Sampler


class Searcher:
    """suggest() -> config (or None when exhausted); observe completions."""

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Dict[str, Any]):
        pass


class BasicVariantSearcher(Searcher):
    """Random/grid sampling of the param space (the default)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int,
                 seed: int = 0):
        from ray_tpu.tune.search import generate_variants

        self._variants = generate_variants(param_space, num_samples, seed)
        self._i = 0

    def suggest(self, trial_id: str):
        if self._i >= len(self._variants):
            return None
        cfg = self._variants[self._i]
        self._i += 1
        return cfg


class QuasiRandomSearcher(Searcher):
    """Halton-sequence sampling over continuous dims: better coverage than
    iid uniform for small budgets (stands in for ax/skopt sobol)."""

    PRIMES = [2, 3, 5, 7, 11, 13, 17, 19, 23, 29]

    def __init__(self, param_space: Dict[str, Any], num_samples: int,
                 seed: int = 0):
        self.space = param_space
        self.num_samples = num_samples
        self._rng = random.Random(seed)
        self._i = 0

    @staticmethod
    def _halton(index: int, base: int) -> float:
        f, r = 1.0, 0.0
        i = index + 1
        while i > 0:
            f /= base
            r += f * (i % base)
            i //= base
        return r

    def suggest(self, trial_id: str):
        if self._i >= self.num_samples:
            return None
        cfg: Dict[str, Any] = {}
        dim = 0
        for key, spec in self.space.items():
            if isinstance(spec, GridSearch):
                cfg[key] = spec.values[self._i % len(spec.values)]
            elif isinstance(spec, Sampler):
                u = self._halton(self._i, self.PRIMES[dim % len(self.PRIMES)])
                dim += 1
                if spec.ppf is not None:
                    # inverse-CDF keeps the low-discrepancy stratification
                    cfg[key] = spec.ppf(u)
                else:
                    cfg[key] = spec.sample(random.Random(int(u * 1e9)))
            else:
                cfg[key] = spec
        self._i += 1
        return cfg


class TPESearcher(Searcher):
    """Tree-structured-Parzen-style: after warmup, sample candidates and
    keep the one most preferred by the good/bad observation split
    (reference role: tune's optuna TPE integration)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int,
                 metric: str = "score", mode: str = "max",
                 n_warmup: int = 4, gamma: float = 0.33,
                 n_candidates: int = 16, seed: int = 0):
        self.space = param_space
        self.num_samples = num_samples
        self.metric = metric
        self.mode = mode
        self.n_warmup = n_warmup
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._i = 0
        self._observations: List[Tuple[Dict, float]] = []

    def _draw(self) -> Dict[str, Any]:
        cfg = {}
        for key, spec in self.space.items():
            if isinstance(spec, GridSearch):
                cfg[key] = self._rng.choice(spec.values)
            elif isinstance(spec, Sampler):
                cfg[key] = spec.sample(self._rng)
            else:
                cfg[key] = spec
        return cfg

    def _score_candidate(self, cfg: Dict, good: List[Dict],
                         bad: List[Dict]) -> float:
        """log(p_good / p_bad) with Gaussian kernels over numeric dims and
        match counts over categorical dims."""

        def density(points: List[Dict]) -> float:
            if not points:
                return 1e-9
            total = 0.0
            for p in points:
                sim = 1.0
                for k, v in cfg.items():
                    pv = p.get(k)
                    if isinstance(v, (int, float)) and isinstance(pv, (int, float)):
                        scale = abs(pv) * 0.3 + 1e-3
                        sim *= math.exp(-((v - pv) ** 2) / (2 * scale ** 2))
                    else:
                        sim *= 1.0 if v == pv else 0.1
                total += sim
            return total / len(points) + 1e-12

        return math.log(density(good) / density(bad))

    def suggest(self, trial_id: str):
        if self._i >= self.num_samples:
            return None
        self._i += 1
        if len(self._observations) < self.n_warmup:
            return self._draw()
        obs = sorted(self._observations, key=lambda o: -o[1])
        n_good = max(1, int(len(obs) * self.gamma))
        good = [c for c, _ in obs[:n_good]]
        bad = [c for c, _ in obs[n_good:]] or good
        cands = [self._draw() for _ in range(self.n_candidates)]
        return max(cands, key=lambda c: self._score_candidate(c, good, bad))

    def on_trial_complete(self, trial_id: str, result: Dict[str, Any]):
        value = result.get(self.metric)
        if value is None:
            return
        value = float(value)
        if self.mode == "min":
            value = -value
        config = result.get("__config__", {})
        self._observations.append((config, value))

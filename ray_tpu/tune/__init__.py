"""ray_tpu.tune: hyperparameter search (reference: ray.tune)."""

from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
)
from ray_tpu.tune.searchers import (
    BasicVariantSearcher,
    QuasiRandomSearcher,
    Searcher,
    TPESearcher,
)
from ray_tpu.tune.search import choice, grid_search, loguniform, randint, uniform
from ray_tpu.tune.tuner import (
    ResultGrid,
    TrialResult,
    TuneConfig,
    Tuner,
    TuneStopException,
    report,
)

__all__ = [
    "Tuner",
    "TuneConfig",
    "ResultGrid",
    "TrialResult",
    "TuneStopException",
    "report",
    "grid_search",
    "choice",
    "uniform",
    "loguniform",
    "randint",
    "ASHAScheduler",
    "HyperBandScheduler",
    "MedianStoppingRule",
    "PopulationBasedTraining",
    "Searcher",
    "BasicVariantSearcher",
    "QuasiRandomSearcher",
    "TPESearcher",
    "FIFOScheduler",
]

"""Trial schedulers: FIFO + Async Successive Halving (ASHA).

Reference: python/ray/tune/schedulers/async_hyperband.py — rungs at
grace_period * reduction_factor^k; a trial reaching a rung stops unless its
metric is in the top 1/reduction_factor of results recorded at that rung.
"""

from __future__ import annotations

import math
from collections import defaultdict
from typing import Dict, List

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, metrics: Dict) -> str:
        return CONTINUE


class ASHAScheduler:
    def __init__(self, metric: str = "score", mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 3, time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        self.rung_results: Dict[int, List[float]] = defaultdict(list)

    def on_result(self, trial_id: str, metrics: Dict) -> str:
        t = int(metrics.get(self.time_attr, 0))
        value = metrics.get(self.metric)
        if value is None:
            return CONTINUE
        value = float(value)
        if self.mode == "min":
            value = -value
        if t >= self.max_t:
            return STOP
        for rung in self.rungs:
            if t == rung:
                results = self.rung_results[rung]
                results.append(value)
                if len(results) < self.rf:
                    return CONTINUE  # not enough data; optimistic continue
                cutoff_idx = max(0, math.ceil(len(results) / self.rf) - 1)
                cutoff = sorted(results, reverse=True)[cutoff_idx]
                return CONTINUE if value >= cutoff else STOP
        return CONTINUE

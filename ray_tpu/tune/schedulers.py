"""Trial schedulers: FIFO, ASHA, HyperBand, median stopping, PBT.

Reference: python/ray/tune/schedulers — async_hyperband.py (ASHA),
hyperband.py, median_stopping_rule.py, pbt.py. Schedulers see every
``tune.report`` through the central report hub and answer CONTINUE/STOP
(PBT may instead answer with an EXPLOIT directive carrying a new config +
checkpoint, which restarts the trial from the better trial's state).
"""

from __future__ import annotations

import math
import random
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional, Tuple

CONTINUE = "CONTINUE"
STOP = "STOP"
EXPLOIT = "EXPLOIT"


class FIFOScheduler:
    def on_result(self, trial_id: str, metrics: Dict) -> str:
        return CONTINUE


class ASHAScheduler:
    """Async successive halving (reference: async_hyperband.py): rungs at
    grace_period * reduction_factor^k; a trial reaching a rung stops unless
    its metric is in the top 1/reduction_factor recorded at that rung."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 3, time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        self.rung_results: Dict[int, List[float]] = defaultdict(list)

    def on_result(self, trial_id: str, metrics: Dict) -> str:
        t = int(metrics.get(self.time_attr, 0))
        value = metrics.get(self.metric)
        if value is None:
            return CONTINUE
        value = float(value)
        if self.mode == "min":
            value = -value
        if t >= self.max_t:
            return STOP
        for rung in self.rungs:
            if t == rung:
                results = self.rung_results[rung]
                results.append(value)
                if len(results) < self.rf:
                    return CONTINUE  # not enough data; optimistic continue
                cutoff_idx = max(0, math.ceil(len(results) / self.rf) - 1)
                cutoff = sorted(results, reverse=True)[cutoff_idx]
                return CONTINUE if value >= cutoff else STOP
        return CONTINUE


class HyperBandScheduler:
    """Multiple successive-halving brackets with different exploration/
    exploitation tradeoffs (reference: tune/schedulers/hyperband.py, run
    here in the async style: each bracket is an ASHA instance and trials
    are spread across brackets round-robin)."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 max_t: int = 100, reduction_factor: int = 3,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        rf = reduction_factor
        graces = []
        g = 1
        while g * rf <= max_t:  # integer loop: no float-log truncation
            graces.append(g)
            g *= rf
        self._brackets = [
            ASHAScheduler(metric=metric, mode=mode, max_t=max_t,
                          grace_period=grace, reduction_factor=rf,
                          time_attr=time_attr)
            for grace in (graces or [1])
        ]
        self._assignment: Dict[str, int] = {}
        self._next = 0

    def on_result(self, trial_id: str, metrics: Dict) -> str:
        idx = self._assignment.get(trial_id)
        if idx is None:
            idx = self._assignment[trial_id] = self._next % len(self._brackets)
            self._next += 1
        return self._brackets[idx].on_result(trial_id, metrics)


class MedianStoppingRule:
    """Stop a trial whose best result so far is worse than the median of
    the other trials' running averages (reference:
    tune/schedulers/median_stopping_rule.py)."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 grace_period: int = 3, min_samples_required: int = 3,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self.time_attr = time_attr
        self._history: Dict[str, List[float]] = defaultdict(list)

    def on_result(self, trial_id: str, metrics: Dict) -> str:
        value = metrics.get(self.metric)
        if value is None:
            return CONTINUE
        value = float(value)
        if self.mode == "min":
            value = -value
        self._history[trial_id].append(value)
        t = int(metrics.get(self.time_attr, len(self._history[trial_id])))
        if t < self.grace_period:
            return CONTINUE
        others = [vals for tid, vals in self._history.items()
                  if tid != trial_id and vals]
        if len(others) < self.min_samples:
            return CONTINUE
        running_avgs = [sum(vals) / len(vals) for vals in others]
        median = sorted(running_avgs)[len(running_avgs) // 2]
        best = max(self._history[trial_id])
        return STOP if best < median else CONTINUE


class PopulationBasedTraining:
    """PBT (reference: tune/schedulers/pbt.py): every
    ``perturbation_interval`` iterations, trials in the bottom quantile
    clone the checkpoint of a random top-quantile trial and continue with
    perturbed hyperparameters. Requires trials to pass ``checkpoint=`` to
    ``tune.report`` and to restore from ``config["__checkpoint__"]``."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25, seed: int = 0,
                 time_attr: str = "training_iteration",
                 synch: bool = True):
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        # synchronized PBT (reference: pbt.py synch=True): trials rendezvous
        # at perturbation boundaries so exploit decisions always see the
        # whole population — without it, fast trials finish before slow
        # ones even start and no exploit can ever fire. Deviation from the
        # reference: synch defaults ON (the deterministic mode).
        self.synch_interval = perturbation_interval if synch else None
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.time_attr = time_attr
        self._rng = random.Random(seed)
        # populated via the report hub
        self._scores: Dict[str, float] = {}
        self._configs: Dict[str, Dict] = {}
        self._checkpoints: Dict[str, Any] = {}

    # hub integration points -------------------------------------------

    def register_trial(self, trial_id: str, config: Dict):
        config = {k: v for k, v in config.items() if k != "__checkpoint__"}
        self._configs[trial_id] = config

    def record_checkpoint(self, trial_id: str, checkpoint: Any):
        self._checkpoints[trial_id] = checkpoint

    # -------------------------------------------------------------------

    def _mutate(self, config: Dict) -> Dict:
        out = dict(config)
        for key, spec in self.mutations.items():
            if key not in out:
                continue
            if isinstance(spec, list):
                out[key] = self._rng.choice(spec)
            elif isinstance(spec, tuple) and len(spec) == 2:
                lo, hi = spec
                # standard PBT perturbation: scale by 0.8 or 1.2, clamped
                factor = self._rng.choice([0.8, 1.2])
                out[key] = min(hi, max(lo, out[key] * factor))
            elif callable(spec):
                out[key] = spec()
        return out

    def on_result(self, trial_id: str, metrics: Dict):
        value = metrics.get(self.metric)
        if value is None:
            return CONTINUE
        value = float(value)
        if self.mode == "min":
            value = -value
        self._scores[trial_id] = value
        t = int(metrics.get(self.time_attr, 0))
        if t == 0 or t % self.interval != 0:
            return CONTINUE
        ranked = sorted(self._scores.items(), key=lambda kv: kv[1])
        n = len(ranked)
        if n < 3:
            return CONTINUE
        k = max(1, int(n * self.quantile))
        bottom = {tid for tid, _ in ranked[:k]}
        top = [tid for tid, _ in ranked[-k:]
               if tid != trial_id and tid in self._checkpoints]
        if trial_id in bottom and top:
            donor = self._rng.choice(top)
            new_config = self._mutate(self._configs.get(donor, {}))
            self._configs[trial_id] = dict(new_config)
            return (EXPLOIT, {"config": new_config,
                              "checkpoint": self._checkpoints[donor]})
        return CONTINUE

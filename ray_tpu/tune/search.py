"""Search space primitives + variant generation.

Reference: python/ray/tune/search (basic_variant grid/random sampling).
"""

from __future__ import annotations

import itertools
import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, List


@dataclass
class GridSearch:
    values: List[Any]


@dataclass
class Sampler:
    sample: Callable[[random.Random], Any]
    # inverse CDF: maps a quantile u in [0,1) to a value (lets quasi-random
    # searchers keep their low-discrepancy stratification)
    ppf: Callable[[float], Any] = None


def grid_search(values: List[Any]) -> GridSearch:
    return GridSearch(list(values))


def choice(values: List[Any]) -> Sampler:
    values = list(values)
    return Sampler(lambda rng: rng.choice(values),
                   ppf=lambda u: values[min(int(u * len(values)), len(values) - 1)])


def uniform(low: float, high: float) -> Sampler:
    return Sampler(lambda rng: rng.uniform(low, high),
                   ppf=lambda u: low + u * (high - low))


def loguniform(low: float, high: float) -> Sampler:
    import math

    return Sampler(
        lambda rng: math.exp(rng.uniform(math.log(low), math.log(high))),
        ppf=lambda u: math.exp(math.log(low) + u * (math.log(high) - math.log(low))))


def randint(low: int, high: int) -> Sampler:
    return Sampler(lambda rng: rng.randrange(low, high),
                   ppf=lambda u: min(low + int(u * (high - low)), high - 1))


def generate_variants(param_space: Dict[str, Any], num_samples: int,
                      seed: int = 0) -> List[Dict[str, Any]]:
    """Cross product over grid_search entries x num_samples draws of samplers."""
    rng = random.Random(seed)
    grid_keys = [k for k, v in param_space.items() if isinstance(v, GridSearch)]
    grids = [param_space[k].values for k in grid_keys]
    variants: List[Dict[str, Any]] = []
    combos = list(itertools.product(*grids)) if grid_keys else [()]
    for _ in range(max(num_samples, 1)):
        for combo in combos:
            cfg: Dict[str, Any] = {}
            for k, v in param_space.items():
                if isinstance(v, GridSearch):
                    cfg[k] = combo[grid_keys.index(k)]
                elif isinstance(v, Sampler):
                    cfg[k] = v.sample(rng)
                else:
                    cfg[k] = v
            variants.append(cfg)
    return variants

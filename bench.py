"""Flagship benchmark: transformer LM train-step MFU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
The north-star target (BASELINE.md) is >=35% MFU on the fine-tune path;
``vs_baseline`` is measured MFU / 0.35 (so 1.0 == target met). The reference
publishes no tokens/sec constants (BASELINE.json `published` is empty), so
the MFU target is the comparison axis.

Since BENCH_r06 the primary metric is the **overlapped + cross-replica-
sharded** data-parallel step across every local chip (per-chip MFU):
optimizer state sharded over the data axis (1/N per replica), grads
reduce-scattered out of the backward, updated params all-gathered — all
inside one XLA program whose async collectives hide the comms under
compute (see ray_tpu/parallel/OVERLAP.md). The emitted line carries a
per-phase breakdown (`fwd_bwd_s`, `optimizer_s`, `allreduce_s`,
`overlap_fraction`, `opt_state_bytes_per_replica`) so MFU movement is
attributable to a phase. The single-chip fused step stays on the line as
`mfu_1chip` for continuity with BENCH_r01-r05.
"""

from __future__ import annotations

import json
import os
import sys
import time


# bf16 peak FLOP/s per chip by generation (v5e default; override via env).
# ORDER MATTERS: more specific substrings first ("v5 lite" must not match
# the v5p entry).
PEAK_FLOPS = [
    ("v5e", 197e12),
    ("v5lite", 197e12),
    ("v5p", 459e12),
    ("v6e", 918e12),
    ("v6", 918e12),
    ("v4", 275e12),
    ("v5", 459e12),
]


def _peak_for(kind: str) -> float:
    env = os.environ.get("RAY_TPU_PEAK_FLOPS")
    if env:
        return float(env)
    kind = (kind or "").lower().replace(" ", "").replace("-", "")
    for key, val in PEAK_FLOPS:
        if key in kind:
            return val
    return 197e12


_TRANSIENT = ("remote_compile", "INTERNAL", "UNAVAILABLE", "DEADLINE")


def main() -> int:
    for attempt in range(3):
        rc, out = _attempt()
        if rc == 0:
            print(json.dumps(out))
            return 0
        err = out.get("error", "")
        if attempt < 2 and any(t in err for t in _TRANSIENT):
            # the tunneled remote-compile service fails transiently; retry
            time.sleep(5)
            continue
        break
    print(json.dumps(out))
    return 0


def _measure(cfg, mesh_devices, batch, seq, steps, warmup, peak):
    """One config's (mfu, tokens/s) on the given devices (fused step)."""
    import dataclasses

    import jax
    import numpy as np

    from ray_tpu.parallel import TrainStepBundle, create_mesh, make_optimizer

    cfg = dataclasses.replace(cfg, max_seq_len=seq)
    mesh = create_mesh({"data": 1, "fsdp": 1, "seq": 1, "tensor": 1,
                        "expert": 1}, devices=mesh_devices)
    bundle = TrainStepBundle(cfg, mesh, optimizer=make_optimizer(
        learning_rate=1e-4, warmup_steps=10, total_steps=1000))
    params, opt_state = bundle.init(jax.random.PRNGKey(0))
    batch_data = bundle.make_batch(np.random.default_rng(0), batch, seq)
    for _ in range(warmup):
        params, opt_state, loss = bundle.step(params, opt_state, batch_data)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = bundle.step(params, opt_state, batch_data)
    float(loss)
    dt = (time.perf_counter() - t0) / steps
    tps = batch * seq / dt
    return tps * cfg.flops_per_token() / peak, tps


def _phase_breakdown(bundle, params, opt_state, batch_data, step_time_s,
                     iters=3):
    """Price the split phase programs + the bare collectives so the fused
    sharded step's time decomposes attributably.

    - ``fwd_bwd_s``: split backward WITH the grad reduce-scatter on its
      output (the overlappable phase);
    - ``optimizer_s``: sharded update + param all-gather;
    - ``allreduce_s``: the bare collective cost (flat reduce-scatter over
      the grad bytes + flat all-gather over the param bytes);
    - ``overlap_fraction``: the share of ``allreduce_s`` the ONE-program
      step hides: (fwd_bwd_s + optimizer_s - step_time_s) / allreduce_s,
      clamped to [0, 1] (phase-split runs expose the collectives at
      program boundaries; the fused program overlaps them with compute).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    out = {}
    p, s = params, opt_state

    def _barrier(state):
        # tiny scalar readback (the reliable completion barrier on
        # tunneled TPU platforms; block_until_ready is not)
        for leaf in jax.tree_util.tree_leaves(state):
            if getattr(leaf, "shape", None) == ():
                return float(jax.device_get(leaf))
        return None

    # compile both split programs before any timed loop
    loss_w, grads_w = bundle._fwd_bwd_rs(p, batch_data)
    float(loss_w)
    p, s = bundle._opt_apply_sharded(grads_w, s, p)
    _barrier(s)
    # phase 1: split backward w/ reduce-scattered grads (loss readback =
    # program completion)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss, grads = bundle._fwd_bwd_rs(p, batch_data)
        float(loss)
    out["fwd_bwd_s"] = (time.perf_counter() - t0) / iters
    # phase 1+2 threaded (opt donates state+params, so each iteration
    # consumes and re-emits them)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss2, grads2 = bundle._fwd_bwd_rs(p, batch_data)
        float(loss2)
        p, s = bundle._opt_apply_sharded(grads2, s, p)
        _barrier(s)
    both = (time.perf_counter() - t0) / iters
    out["optimizer_s"] = max(both - out["fwd_bwd_s"], 0.0)

    # bare collectives at the real byte volumes (flat proxies: collective
    # cost is volume-bound, not tree-shape-bound)
    mesh = bundle.mesh
    n = bundle.dp_size
    gelems = sum(int(np.prod(a.shape)) for a in
                 jax.tree_util.tree_leaves(bundle._abstract_params))
    gelems = max((gelems // (n * n)) * (n * n), n * n)

    def rs(x):
        return jax.lax.psum_scatter(x, "data", scatter_dimension=0,
                                    tiled=True)

    def ag(x):
        return jax.lax.all_gather(x, "data", axis=0, tiled=True)

    rs_fn = jax.jit(shard_map(rs, mesh=mesh, in_specs=P("data"),
                              out_specs=P("data"), check_rep=False))
    ag_fn = jax.jit(shard_map(ag, mesh=mesh, in_specs=P("data"),
                              out_specs=P(), check_rep=False))
    flat = jnp.zeros((gelems,), jnp.float32)
    jax.block_until_ready(rs_fn(flat))  # compile
    jax.block_until_ready(ag_fn(flat))
    t0 = time.perf_counter()
    for _ in range(iters):
        jax.block_until_ready(rs_fn(flat))
        jax.block_until_ready(ag_fn(flat))
    out["allreduce_s"] = (time.perf_counter() - t0) / iters
    exposed_saved = out["fwd_bwd_s"] + out["optimizer_s"] - step_time_s
    out["overlap_fraction"] = round(
        max(0.0, min(1.0, exposed_saved / out["allreduce_s"]))
        if out["allreduce_s"] > 0 else 0.0, 4)
    out["fwd_bwd_s"] = round(out["fwd_bwd_s"], 4)
    out["optimizer_s"] = round(out["optimizer_s"], 4)
    out["allreduce_s"] = round(out["allreduce_s"], 4)
    return out


def _measure_sharded(cfg, devices, per_chip_batch, seq, steps, warmup, peak):
    """The primary path: DP across all local chips with the overlapped +
    sharded optimizer update (ONE program; opt state 1/N per replica)."""
    import dataclasses

    import jax
    import numpy as np

    from ray_tpu.parallel import TrainStepBundle, create_mesh, make_optimizer

    n = len(devices)
    cfg = dataclasses.replace(cfg, max_seq_len=seq)
    mesh = create_mesh({"data": n, "fsdp": 1, "seq": 1, "tensor": 1,
                        "expert": 1}, devices=devices)
    bundle = TrainStepBundle(
        cfg, mesh, shard_update=True,
        optimizer_factory=lambda spec_fn: make_optimizer(
            learning_rate=1e-4, warmup_steps=10, total_steps=1000,
            clip_spec_fn=spec_fn))
    params, opt_state = bundle.init_sharded(jax.random.PRNGKey(0))
    batch = per_chip_batch * n
    batch_data = bundle.make_batch(np.random.default_rng(0), batch, seq)
    for _ in range(warmup):
        params, opt_state, loss = bundle.step(params, opt_state, batch_data)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = bundle.step(params, opt_state, batch_data)
    float(loss)
    dt = (time.perf_counter() - t0) / steps
    tokens_per_sec = batch * seq / dt
    mfu = tokens_per_sec * cfg.flops_per_token() / (peak * n)
    stats = {
        "mfu": mfu,
        "tokens_per_sec": tokens_per_sec,
        "step_time_s": dt,
        "loss": float(loss),
        "n_chips": n,
        "batch_global": batch,
        "opt_state_bytes_per_replica":
            bundle.opt_state_bytes_per_replica(opt_state),
        "bucket_count": bundle.bucket_plan.num_buckets,
        "bucket_bytes": bundle.bucket_bytes,
    }
    stats["opt_state_bytes_total"] = bundle.opt_state_bytes_total()
    if not os.environ.get("RAY_TPU_BENCH_SKIP_PHASES"):
        try:
            stats.update(_phase_breakdown(bundle, params, opt_state,
                                          batch_data, dt))
        except Exception as e:  # breakdown must never sink the bench
            stats["phase_breakdown_error"] = str(e)[:160]
    return stats


def _attempt():
    t_start = time.time()
    config_name = os.environ.get("RAY_TPU_BENCH_CONFIG", "")
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ray_tpu.models import CONFIGS
        from ray_tpu.parallel import TrainStepBundle, create_mesh, make_optimizer
        from ray_tpu.utils import is_tpu

        devices = jax.devices()
        on_tpu = is_tpu()
        dev_kind = getattr(devices[0], "device_kind", "")

        if on_tpu:
            # 1b/b4 is the best measured single-chip shape (d_model 2048
            # matmuls fill the MXU; larger batches exceed the tunneled
            # compile service's limits)
            config_name = config_name or "1b"
            batch, seq = int(os.environ.get("RAY_TPU_BENCH_BATCH", "4")), 2048
            steps, warmup = 10, 3
            peak = _peak_for(str(dev_kind) or str(devices[0]))
        else:  # CI fallback: tiny on CPU so the bench always emits a line
            config_name, batch, seq, steps, warmup = config_name or "tiny", 4, 128, 3, 1
            peak = 1e12

        cfg = CONFIGS[config_name]
        import dataclasses

        cfg = dataclasses.replace(cfg, max_seq_len=seq)
        mesh = create_mesh({"data": 1, "fsdp": 1, "seq": 1, "tensor": 1,
                            "expert": 1}, devices=devices[:1])
        bundle = TrainStepBundle(cfg, mesh, optimizer=make_optimizer(
            learning_rate=1e-4, warmup_steps=10, total_steps=1000))
        params, opt_state = bundle.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        batch_data = bundle.make_batch(rng, batch, seq)

        for _ in range(warmup):
            params, opt_state, loss = bundle.step(params, opt_state, batch_data)
        float(loss)  # full host readback: block_until_ready is not a
        # reliable completion barrier on tunneled TPU platforms

        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, loss = bundle.step(params, opt_state, batch_data)
        float(loss)  # steps serialize through the params dependency chain
        dt = (time.perf_counter() - t0) / steps

        tokens_per_step = batch * seq
        tokens_per_sec = tokens_per_step / dt
        flops_per_token = cfg.flops_per_token()  # 6*N_active + attention
        mfu_1chip = tokens_per_sec * flops_per_token / peak

        result = {
            "metric": f"train_mfu_{config_name}",
            "value": round(mfu_1chip, 4),
            "unit": "mfu_fraction",
            "vs_baseline": round(mfu_1chip / 0.35, 4),
            "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
            "step_time_s": round(dt, 4),
            "loss": round(float(loss), 4),
            "device": str(devices[0]),
            "config": config_name,
            "batch": batch,
            "seq": seq,
            "mfu_1chip": round(mfu_1chip, 4),
            "step_time_1chip_s": round(dt, 4),
            # breakdown defaults for the 1-chip/CPU line (the sharded
            # phase below overwrites them when it runs)
            "fwd_bwd_s": 0.0,
            "optimizer_s": 0.0,
            "allreduce_s": 0.0,
            "overlap_fraction": 0.0,
            "opt_state_bytes_per_replica":
                bundle.opt_state_bytes_per_replica(opt_state),
        }
        # release the primary config's HBM before the sharded phase
        del params, opt_state, bundle, batch_data

        if on_tpu and len(devices) > 1 and not os.environ.get(
                "RAY_TPU_BENCH_SKIP_SHARDED"):
            # PRIMARY since BENCH_r06: overlapped bucketed allreduce +
            # cross-replica sharded optimizer update across every chip;
            # `value` is the per-chip MFU of that step. The 1-chip fused
            # number above stays on the line as mfu_1chip.
            try:
                sh = _measure_sharded(CONFIGS[config_name], devices,
                                      per_chip_batch=batch, seq=seq,
                                      steps=8, warmup=2, peak=peak)
                result["value"] = round(sh["mfu"], 4)
                result["vs_baseline"] = round(sh["mfu"] / 0.35, 4)
                result["tokens_per_sec_per_chip"] = round(
                    sh["tokens_per_sec"] / sh["n_chips"], 1)
                result["step_time_s"] = round(sh["step_time_s"], 4)
                result["loss"] = round(sh["loss"], 4)
                result["batch"] = sh["batch_global"]
                for k in ("n_chips", "fwd_bwd_s", "optimizer_s",
                          "allreduce_s", "overlap_fraction",
                          "opt_state_bytes_per_replica",
                          "opt_state_bytes_total", "bucket_count",
                          "bucket_bytes", "phase_breakdown_error"):
                    if k in sh:
                        result[k] = sh[k]
                result["sharded_update"] = True
            except Exception as e:  # fall back to the 1-chip line
                result["sharded_error"] = str(e)[:300]

        if on_tpu and config_name == "1b" and not os.environ.get(
                "RAY_TPU_BENCH_SKIP_SECONDARY"):
            # secondary config (VERDICT r3: report 350m too). b8/s1024 is
            # the best measured 350m fine-tune shape on one chip; the
            # pallas flash BACKWARD kernels (head_dim 64) carry it past
            # the 35% target.
            try:
                mfu2, tps2 = _measure(CONFIGS["350m"], mesh_devices=devices[:1],
                                      batch=8, seq=1024, steps=6, warmup=2,
                                      peak=peak)
                result["mfu_350m"] = round(mfu2, 4)
                result["tokens_per_sec_350m"] = round(tps2, 1)
                result["vs_target_350m"] = round(mfu2 / 0.35, 4)
            except Exception as e:  # secondary must never sink the bench
                result["mfu_350m_error"] = str(e)[:160]
        result["wall_s"] = round(time.time() - t_start, 1)
        return 0, result
    except Exception as e:  # always emit a parseable line
        import traceback

        return 1, {
            "metric": f"train_mfu_{config_name or 'unknown'}",
            "value": 0.0,
            "unit": "mfu_fraction",
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}",
            "traceback": traceback.format_exc()[-2000:],
        }


if __name__ == "__main__":
    sys.exit(main())

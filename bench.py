"""Flagship benchmark: transformer LM train-step MFU on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The north-star target (BASELINE.md) is >=35% MFU on the fine-tune path;
``vs_baseline`` is measured MFU / 0.35 (so 1.0 == target met). The reference
publishes no tokens/sec constants (BASELINE.json `published` is empty), so
the MFU target is the comparison axis.
"""

from __future__ import annotations

import json
import os
import sys
import time


# bf16 peak FLOP/s per chip by generation (v5e default; override via env).
# ORDER MATTERS: more specific substrings first ("v5 lite" must not match
# the v5p entry).
PEAK_FLOPS = [
    ("v5e", 197e12),
    ("v5lite", 197e12),
    ("v5p", 459e12),
    ("v6e", 918e12),
    ("v6", 918e12),
    ("v4", 275e12),
    ("v5", 459e12),
]


def _peak_for(kind: str) -> float:
    env = os.environ.get("RAY_TPU_PEAK_FLOPS")
    if env:
        return float(env)
    kind = (kind or "").lower().replace(" ", "").replace("-", "")
    for key, val in PEAK_FLOPS:
        if key in kind:
            return val
    return 197e12


_TRANSIENT = ("remote_compile", "INTERNAL", "UNAVAILABLE", "DEADLINE")


def main() -> int:
    for attempt in range(3):
        rc, out = _attempt()
        if rc == 0:
            print(json.dumps(out))
            return 0
        err = out.get("error", "")
        if attempt < 2 and any(t in err for t in _TRANSIENT):
            # the tunneled remote-compile service fails transiently; retry
            time.sleep(5)
            continue
        break
    print(json.dumps(out))
    return 0


def _measure(cfg, mesh_devices, batch, seq, steps, warmup, peak):
    """One config's (mfu, tokens/s) on the given devices."""
    import dataclasses

    import jax
    import numpy as np

    from ray_tpu.parallel import TrainStepBundle, create_mesh, make_optimizer

    cfg = dataclasses.replace(cfg, max_seq_len=seq)
    mesh = create_mesh({"data": 1, "fsdp": 1, "seq": 1, "tensor": 1,
                        "expert": 1}, devices=mesh_devices)
    bundle = TrainStepBundle(cfg, mesh, optimizer=make_optimizer(
        learning_rate=1e-4, warmup_steps=10, total_steps=1000))
    params, opt_state = bundle.init(jax.random.PRNGKey(0))
    batch_data = bundle.make_batch(np.random.default_rng(0), batch, seq)
    for _ in range(warmup):
        params, opt_state, loss = bundle.step(params, opt_state, batch_data)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = bundle.step(params, opt_state, batch_data)
    float(loss)
    dt = (time.perf_counter() - t0) / steps
    tps = batch * seq / dt
    return tps * cfg.flops_per_token() / peak, tps


def _attempt():
    t_start = time.time()
    config_name = os.environ.get("RAY_TPU_BENCH_CONFIG", "")
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ray_tpu.models import CONFIGS
        from ray_tpu.parallel import TrainStepBundle, create_mesh, make_optimizer
        from ray_tpu.utils import is_tpu

        devices = jax.devices()
        on_tpu = is_tpu()
        dev_kind = getattr(devices[0], "device_kind", "")

        if on_tpu:
            # 1b/b4 is the best measured single-chip shape (d_model 2048
            # matmuls fill the MXU; larger batches exceed the tunneled
            # compile service's limits)
            config_name = config_name or "1b"
            batch, seq = int(os.environ.get("RAY_TPU_BENCH_BATCH", "4")), 2048
            steps, warmup = 10, 3
            peak = _peak_for(str(dev_kind) or str(devices[0]))
        else:  # CI fallback: tiny on CPU so the bench always emits a line
            config_name, batch, seq, steps, warmup = config_name or "tiny", 4, 128, 3, 1
            peak = 1e12

        cfg = CONFIGS[config_name]
        import dataclasses

        cfg = dataclasses.replace(cfg, max_seq_len=seq)
        mesh = create_mesh({"data": 1, "fsdp": 1, "seq": 1, "tensor": 1,
                            "expert": 1}, devices=devices[:1])
        bundle = TrainStepBundle(cfg, mesh, optimizer=make_optimizer(
            learning_rate=1e-4, warmup_steps=10, total_steps=1000))
        params, opt_state = bundle.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        batch_data = bundle.make_batch(rng, batch, seq)

        for _ in range(warmup):
            params, opt_state, loss = bundle.step(params, opt_state, batch_data)
        float(loss)  # full host readback: block_until_ready is not a
        # reliable completion barrier on tunneled TPU platforms

        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, loss = bundle.step(params, opt_state, batch_data)
        float(loss)  # steps serialize through the params dependency chain
        dt = (time.perf_counter() - t0) / steps

        tokens_per_step = batch * seq
        tokens_per_sec = tokens_per_step / dt
        flops_per_token = cfg.flops_per_token()  # 6*N_active + attention
        mfu = tokens_per_sec * flops_per_token / peak

        result = {
            "metric": f"train_mfu_{config_name}",
            "value": round(mfu, 4),
            "unit": "mfu_fraction",
            "vs_baseline": round(mfu / 0.35, 4),
            "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
            "step_time_s": round(dt, 4),
            "loss": round(float(loss), 4),
            "device": str(devices[0]),
            "config": config_name,
            "batch": batch,
            "seq": seq,
            "wall_s": round(time.time() - t_start, 1),
        }
        # release the primary config's HBM before the secondary allocates
        del params, opt_state, bundle, batch_data
        if on_tpu and config_name == "1b" and not os.environ.get(
                "RAY_TPU_BENCH_SKIP_SECONDARY"):
            # secondary config (VERDICT r3: report 350m too). b8/s1024 is
            # the best measured 350m fine-tune shape on one chip; the
            # pallas flash BACKWARD kernels (head_dim 64) carry it past
            # the 35% target.
            try:
                mfu2, tps2 = _measure(CONFIGS["350m"], mesh_devices=devices[:1],
                                      batch=8, seq=1024, steps=6, warmup=2,
                                      peak=peak)
                result["mfu_350m"] = round(mfu2, 4)
                result["tokens_per_sec_350m"] = round(tps2, 1)
                result["vs_target_350m"] = round(mfu2 / 0.35, 4)
            except Exception as e:  # secondary must never sink the bench
                result["mfu_350m_error"] = str(e)[:160]
        return 0, result
    except Exception as e:  # always emit a parseable line
        import traceback

        return 1, {
            "metric": f"train_mfu_{config_name or 'unknown'}",
            "value": 0.0,
            "unit": "mfu_fraction",
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}",
            "traceback": traceback.format_exc()[-2000:],
        }


if __name__ == "__main__":
    sys.exit(main())

"""raylint context layer: execution-context provenance over the call graph.

Every rule so far asks *what* code does; the v3 race/fork/donation rules
also need to know *who runs it*. This module classifies every function in
the project graph by the execution contexts that can reach it:

* ``loop`` — event-loop code: ``async def`` bodies plus sync callbacks
  scheduled via ``call_soon*`` / ``call_later`` / ``create_task`` /
  ``ensure_future``.
* ``thread`` — background-thread code: ``threading.Thread(target=...)`` /
  ``Timer`` targets, ``run_in_executor`` / executor ``.submit`` thunks,
  and everything they call.
* ``fork`` — fork-child code: everything reachable from the zygote's
  ``_child_main`` (crossing spawn edges too — threads started in the child
  still run inside the forked image).
* ``main`` — caller-thread code: sync functions nobody spawns that aren't
  already loop/thread/fork-only, i.e. public API surface executed on
  whatever thread calls into the library.

Contexts propagate transitively through resolved call edges: ``loop`` and
``thread`` flow into *sync* callees only (an ``async def`` called from a
thread is not executed there — it must be scheduled, which is a spawn
edge); ``fork`` flows through everything because it is process-scoped.
A function can hold several contexts — a helper called from both the
reducer thread and the public API is genuinely bi-contextual, and the
race rules treat overlapping context sets as "cannot prove disjoint".

The index also computes:

* :meth:`ContextIndex.always_held` — the set of lock identities held on
  EVERY call path into a function (meet-over-callers fixpoint seeded at
  top), so a write inside ``_drain_locked`` is credited with the caller's
  ``with self._lock:`` even though the lock is lexically out of frame.
* :attr:`ContextIndex.forking` — functions that (transitively) reach an
  ``os.fork()`` call, for FRK001's locks-across-fork gate.

The whole index is cached in ``.graphcache.json`` under a ``contexts``
section keyed by a fingerprint of every file's content hash, so a warm
run skips call resolution entirely; overlay views (in-memory fixtures)
always recompute — their graph differs from the on-disk one.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from collections import deque
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from tools.raylint.graph import (FuncKey, GRAPH_SCHEMA_VERSION, GraphView,
                                 ProjectGraph)

# the context lattice; "main" is assigned in a second phase (see _build)
CONTEXTS = ("loop", "thread", "fork", "main")

_FIXPOINT_GUARD = 64  # always_held passes; the lattice only descends


def _fingerprint(graph: ProjectGraph) -> str:
    h = hashlib.sha256()
    for path in sorted(graph.shas):
        h.update(f"{path}:{graph.shas[path]}\n".encode("utf-8"))
    return h.hexdigest()


def _enc(key: FuncKey) -> str:
    return f"{key[0]}||{key[1]}"


def _dec(text: str) -> FuncKey:
    path, _, qual = text.partition("||")
    return (path, qual)


class ContextIndex:
    """Execution-context classification for every function in a GraphView."""

    def __init__(self, view: GraphView):
        self.view = view
        self.ctx: Dict[FuncKey, Set[str]] = {}
        # (func, ctx) -> the caller/spawner that propagated ctx (None = root)
        self.parent: Dict[Tuple[FuncKey, str], Optional[FuncKey]] = {}
        self.spawn_targets: Set[FuncKey] = set()
        self.forking: Set[FuncKey] = set()
        self._always: Dict[FuncKey, Optional[FrozenSet[str]]] = {}
        self.build_seconds = 0.0
        self.cache_hit = False
        started = time.perf_counter()
        if not self._load_cached():
            self._build()
            self._save_cached()
        self.build_seconds = time.perf_counter() - started
        g = getattr(view, "graph", None)
        if g is not None and view.overlay is None:
            g.stats["context_build_seconds"] = self.build_seconds
            g.stats["context_cache_hit"] = self.cache_hit

    # -- public queries ------------------------------------------------------

    def contexts(self, key: FuncKey) -> Set[str]:
        return self.ctx.get(key, set())

    def always_held(self, key: FuncKey) -> FrozenSet[str]:
        """Locks held on every known call path into ``key``. Top (a function
        only reachable through unresolved cycles) degrades to the empty set:
        claiming protection we cannot prove would hide races."""
        return self._always.get(key) or frozenset()

    def chain(self, key: FuncKey, ctx: str, limit: int = 6) -> str:
        """Provenance: ``root.qual -> ... -> key.qual`` for one context."""
        hops: List[str] = []
        cur: Optional[FuncKey] = key
        seen: Set[FuncKey] = set()
        while cur is not None and cur not in seen and len(hops) < limit:
            seen.add(cur)
            hops.append(cur[1])
            cur = self.parent.get((cur, ctx))
        return " <- ".join(hops)

    # -- construction --------------------------------------------------------

    def _funcs(self):
        for path, mod in self.view.modules():
            for qual, func in mod["functions"].items():
                yield (path, qual), func

    def _build(self):
        view = self.view
        callees: Dict[FuncKey, List[FuncKey]] = {}
        callers: Dict[FuncKey, List[Tuple[FuncKey, Tuple[str, ...]]]] = {}
        spawn_edges: Dict[FuncKey, List[Tuple[str, FuncKey]]] = {}
        loop_roots: List[FuncKey] = []
        thread_roots: List[FuncKey] = []
        fork_roots: List[FuncKey] = []
        fork_sites: List[FuncKey] = []

        for key, func in self._funcs():
            path = key[0]
            outs: List[FuncKey] = []
            for call in func["calls"]:
                target = view.resolve_call(path, func, call)
                if target is None or target == key:
                    continue
                outs.append(target)
                callers.setdefault(target, []).append(
                    (key, tuple(call["held"])))
            callees[key] = outs
            for kind, dotted, _line in func.get("spawns", ()):
                target = view.resolve_call(path, func, {"raw": dotted})
                if target is None:
                    continue
                spawn_edges.setdefault(key, []).append((kind, target))
                self.spawn_targets.add(target)
                (thread_roots if kind == "thread" else loop_roots).append(
                    target)
            if func["is_async"]:
                loop_roots.append(key)
            if key[1].split(".")[-1] == "_child_main":
                fork_roots.append(key)
            if func.get("forks"):
                fork_sites.append(key)

        self._callees = callees
        self._callers = callers
        self._spawn_edges = spawn_edges

        self._propagate("loop", loop_roots, cross_spawn=False,
                        into_async=False)
        self._propagate("thread", thread_roots, cross_spawn=False,
                        into_async=False)
        self._propagate("fork", fork_roots, cross_spawn=True, into_async=True)
        # phase 2: sync functions not spawned anywhere and not already
        # claimed by loop/thread/fork run on whichever thread calls the
        # library — the "main" context
        main_roots = []
        for key, func in self._funcs():
            if func["is_async"] or key in self.spawn_targets:
                continue
            have = self.ctx.get(key, set())
            if have & {"loop", "thread", "fork"}:
                continue
            main_roots.append(key)
        self._propagate("main", main_roots, cross_spawn=False,
                        into_async=False)

        self._compute_forking(fork_sites)
        self._compute_always_held()

    def _add_ctx(self, key: FuncKey, ctx: str,
                 parent: Optional[FuncKey]) -> bool:
        have = self.ctx.setdefault(key, set())
        if ctx in have:
            return False
        have.add(ctx)
        self.parent[(key, ctx)] = parent
        return True

    def _propagate(self, ctx: str, roots: List[FuncKey], cross_spawn: bool,
                   into_async: bool):
        q: deque = deque()
        for root in roots:
            if self.view.func(root) is not None \
                    and self._add_ctx(root, ctx, None):
                q.append(root)
        while q:
            key = q.popleft()
            for callee in self._callees.get(key, ()):
                tf = self.view.func(callee)
                if tf is None:
                    continue
                if tf["is_async"] and not into_async:
                    continue  # an async callee runs on the loop, not here
                if self._add_ctx(callee, ctx, key):
                    q.append(callee)
            if cross_spawn:
                for _kind, target in self._spawn_edges.get(key, ()):
                    if self._add_ctx(target, ctx, key):
                        q.append(target)

    def _compute_forking(self, fork_sites: List[FuncKey]):
        """Functions that transitively reach an ``os.fork()`` call: reverse
        reachability from the direct fork sites."""
        q = deque(fork_sites)
        self.forking.update(fork_sites)
        while q:
            key = q.popleft()
            for caller, _held in self._callers.get(key, ()):
                if caller not in self.forking:
                    self.forking.add(caller)
                    q.append(caller)

    def _compute_always_held(self):
        """Meet-over-callers fixpoint. Roots (spawn targets, async defs,
        ``_child_main``, functions with no resolved caller) start and stay
        at the empty set — they are entered lock-free. Everything else
        starts at top (None) and descends as caller values resolve, so a
        cycle with one outside entry converges to that entry's truth."""
        always = self._always
        for key, func in self._funcs():
            if func["is_async"] or key in self.spawn_targets \
                    or key[1].split(".")[-1] == "_child_main" \
                    or not self._callers.get(key):
                always[key] = frozenset()
            else:
                always[key] = None  # top
        roots = {k for k, v in always.items() if v == frozenset()}
        for _ in range(_FIXPOINT_GUARD):
            changed = False
            for key, sites in self._callers.items():
                if key in roots or key not in always:
                    continue
                meet: Optional[FrozenSet[str]] = None
                for caller, held in sites:
                    ch = always.get(caller)
                    if ch is None:
                        continue  # top caller: no constraint yet
                    contrib = frozenset(held) | ch
                    meet = contrib if meet is None else (meet & contrib)
                if meet is not None and meet != always[key]:
                    always[key] = meet
                    changed = True
            if not changed:
                break

    # -- disk cache ----------------------------------------------------------

    def _cache_doc_path(self):
        g = getattr(self.view, "graph", None)
        if g is None or self.view.overlay is not None:
            return None
        if not g.use_cache or g.cache_path is None:
            return None
        return g.cache_path

    def _load_cached(self) -> bool:
        path = self._cache_doc_path()
        if path is None or not path.is_file():
            return False
        try:
            doc = json.loads(path.read_text(encoding="utf-8"))
        except (OSError, ValueError):
            return False
        section = doc.get("contexts")
        if not isinstance(section, dict):
            return False
        if section.get("graph_version") != GRAPH_SCHEMA_VERSION \
                or section.get("fingerprint") != _fingerprint(self.view.graph):
            return False
        try:
            self.ctx = {_dec(k): set(v) for k, v in section["ctx"].items()}
            self.parent = {
                (_dec(k), c): (_dec(p) if p is not None else None)
                for k, per in section["parent"].items()
                for c, p in per.items()}
            self.spawn_targets = {_dec(k) for k in section["spawn_targets"]}
            self.forking = {_dec(k) for k in section["forking"]}
            self._always = {
                _dec(k): (frozenset(v) if v is not None else None)
                for k, v in section["always_held"].items()}
        except (KeyError, TypeError, AttributeError):
            return False
        self.cache_hit = True
        return True

    def _save_cached(self):
        path = self._cache_doc_path()
        if path is None:
            return
        try:
            doc = json.loads(path.read_text(encoding="utf-8"))
        except (OSError, ValueError):
            return  # no graph cache yet: nothing to attach the section to
        if doc.get("version") != GRAPH_SCHEMA_VERSION:
            return
        parent: Dict[str, Dict[str, Optional[str]]] = {}
        for (key, ctx), par in self.parent.items():
            parent.setdefault(_enc(key), {})[ctx] = (
                _enc(par) if par is not None else None)
        doc["contexts"] = {
            "graph_version": GRAPH_SCHEMA_VERSION,
            "fingerprint": _fingerprint(self.view.graph),
            "ctx": {_enc(k): sorted(v) for k, v in self.ctx.items()},
            "parent": parent,
            "spawn_targets": sorted(_enc(k) for k in self.spawn_targets),
            "forking": sorted(_enc(k) for k in self.forking),
            "always_held": {
                _enc(k): (sorted(v) if v is not None else None)
                for k, v in self._always.items()},
        }
        tmp = path.with_suffix(".tmp")
        try:
            tmp.write_text(json.dumps(doc, sort_keys=True), encoding="utf-8")
            os.replace(tmp, path)
        except OSError:
            pass  # the cache is an optimization; never fail the lint over it


def context_index(view: GraphView) -> ContextIndex:
    """The (per-view memoized) ContextIndex. Overlay views recompute from
    their own graph; the shared pristine view builds once per run and uses
    the ``.graphcache.json`` contexts section across runs."""
    idx = getattr(view, "_ctx_index", None)
    if idx is None:
        idx = ContextIndex(view)
        view._ctx_index = idx
    return idx

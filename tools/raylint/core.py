"""raylint core: the rule framework.

This module is deliberately self-contained (stdlib only) so it can run in any
environment the repo runs in — CI, a dev laptop, or inside a test — with zero
dependencies on ray_tpu itself. It provides:

* :class:`Finding` — one diagnostic, keyed for baseline matching by
  ``(rule, path, snippet)`` rather than line number, so baselines survive
  unrelated edits that shift lines.
* :class:`Rule` — base class; concrete rules live in ``tools/raylint/rules.py``
  and register themselves with :func:`register_rule`.
* Suppressions — ``# raylint: disable=RULE1,RULE2 <reason>`` on (or directly
  above) the offending line, and ``# raylint: disable-file=RULE`` anywhere in a
  file. ``disable=all`` suppresses every rule. Comments are found with
  :mod:`tokenize`, so the directives never fire inside string literals.
* Baseline — a checked-in JSON file of reviewed, grandfathered findings.
  Matching consumes entries from a multiset, so *new* occurrences of an
  already-baselined pattern in the same file still fail.
* :class:`Project` / :func:`check_paths` — the runner.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import re
import time
import tokenize
from collections import Counter
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

# Rule id for files that fail to parse: a syntax error in the tree is itself a
# finding (it would otherwise silently exempt the file from every rule).
PARSE_ERROR_RULE = "E999"

# Rule id for stale suppressions: a `# raylint: disable=RULE` that suppresses
# zero findings is itself an error (rules.py registers the marker class; the
# detection runs in check_source because it needs the pre-suppression finding
# set). Escape hatch: add SUP001 to the directive's own rule list
# (`# raylint: disable=ASY001,SUP001 <why it must stay>`) to keep a
# deliberately-dormant suppression.
STALE_SUPPRESSION_RULE = "SUP001"

_SKIP_DIRS = {"__pycache__", ".git", "build", ".eggs", "node_modules"}


# ---------------------------------------------------------------------------
# Findings
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    rule: str
    path: str  # posix path relative to the project root
    line: int
    col: int
    message: str
    snippet: str  # stripped source of the flagged line; part of the baseline key

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.snippet)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------

_RULES: Dict[str, type] = {}


def register_rule(cls: type) -> type:
    """Class decorator: add a Rule subclass to the global registry."""
    if not cls.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    if cls.name in _RULES and _RULES[cls.name] is not cls:
        raise ValueError(f"duplicate rule id {cls.name}")
    _RULES[cls.name] = cls
    return cls


_RULESETS_LOADED = False


def all_rules() -> Dict[str, type]:
    """Registry of rule id -> class (imports the bundled rule sets on first
    use — guarded by a flag, not registry emptiness, because importing one
    rule module as a side effect of something else must not mask the rest)."""
    global _RULESETS_LOADED
    if not _RULESETS_LOADED:
        from tools.raylint import rules as _  # noqa: F401  (self-registers)
        from tools.raylint import rules_interp as _i  # noqa: F401
        from tools.raylint import rules_ctx as _c  # noqa: F401
        _RULESETS_LOADED = True
    return dict(_RULES)


class Rule:
    """One invariant. Subclass, set ``name``/``summary``, implement ``check``."""

    name: str = ""
    summary: str = ""

    def check(self, module: "Module") -> Iterator[Finding]:
        raise NotImplementedError

    # -- helpers shared by concrete rules --

    def finding(self, module: "Module", node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        snippet = module.line(line).strip()
        return Finding(rule=self.name, path=module.path, line=line, col=col,
                       message=message, snippet=snippet)


# ---------------------------------------------------------------------------
# Import alias resolution (per module)
# ---------------------------------------------------------------------------


class ImportResolver:
    """Maps local names back to dotted import paths so ``from time import
    sleep as zzz; zzz()`` still resolves to ``time.sleep``."""

    def __init__(self, tree: ast.AST):
        self.aliases: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.aliases[a.asname] = a.name
                    # plain `import a.b` binds `a`, which already resolves
            elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.aliases[a.asname or a.name] = f"{node.module}.{a.name}"

    def dotted(self, node: ast.AST) -> Optional[str]:
        """Resolve an expression to a dotted name, or None if it isn't one."""
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        parts.append(cur.id)
        parts.reverse()
        mapped = self.aliases.get(parts[0])
        if mapped is not None:
            parts[0:1] = mapped.split(".")
        return ".".join(parts)


# ---------------------------------------------------------------------------
# Suppression comments
# ---------------------------------------------------------------------------

_DIRECTIVE_RE = re.compile(
    r"#\s*raylint:\s*disable(?P<filewide>-file)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_*]+(?:\s*,\s*[A-Za-z0-9_*]+)*)")


class Suppressions:
    """Per-line and per-file ``# raylint: disable=...`` directives.

    Each directive remembers its *origin* (the comment's own line), so the
    SUP001 stale-suppression pass can tell which directives never suppressed
    anything. ``by_line`` maps covered line -> rule -> origin lines;
    ``directives`` maps origin line -> the rule tokens as written (filewide
    directives use origin line as written too, flagged in ``filewide``).
    """

    def __init__(self, source: str):
        self.by_line: Dict[int, Dict[str, Set[int]]] = {}
        self.filewide: Dict[str, Set[int]] = {}
        self.directives: Dict[int, Set[str]] = {}
        try:
            tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            tokens = []
        code_lines: Set[int] = set()
        origin_rules: Dict[int, Set[str]] = {}
        for tok in tokens:
            if tok.type == tokenize.COMMENT:
                m = _DIRECTIVE_RE.search(tok.string)
                if not m:
                    continue
                rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
                rules = {"all" if r == "*" else r for r in rules}
                origin = tok.start[0]
                self.directives.setdefault(origin, set()).update(rules)
                if m.group("filewide"):
                    for r in rules:
                        self.filewide.setdefault(r, set()).add(origin)
                else:
                    origin_rules.setdefault(origin, set()).update(rules)
            elif tok.type not in (tokenize.NL, tokenize.NEWLINE, tokenize.INDENT,
                                  tokenize.DEDENT, tokenize.ENDMARKER):
                code_lines.add(tok.start[0])

        def bind(covered: int, origin: int):
            per_rule = self.by_line.setdefault(covered, {})
            for r in origin_rules.get(origin, ()):
                per_rule.setdefault(r, set()).add(origin)

        for origin in origin_rules:
            bind(origin, origin)
        # a directive on its own line also covers the next code line DIRECTLY
        # below it (only comment lines may intervene — a blank line breaks the
        # binding, so a stale directive can't silently drift onto unrelated
        # code); decorator lines are then descended through so "directly
        # above" works for decorated defs/classes too (findings anchor at the
        # def/class line)
        lines = source.splitlines()
        last = max(code_lines, default=0)

        def next_adjacent_code_line(after: int) -> int:
            """First code line after `after` with only comments between, or 0."""
            nxt = after + 1
            while nxt <= last:
                if nxt in code_lines:
                    return nxt
                if not lines[nxt - 1].strip().startswith("#"):
                    return 0  # blank (or other non-comment) line: binding ends
                nxt += 1
            return 0

        for origin in sorted(origin_rules):
            if origin in code_lines:
                continue
            nxt = next_adjacent_code_line(origin)
            while nxt:
                bind(nxt, origin)
                if lines[nxt - 1].lstrip().startswith("@"):
                    nxt = next_adjacent_code_line(nxt)  # decorator: descend
                else:
                    break

    def covers(self, rule: str, line: int) -> bool:
        return bool(self.covering_origins(rule, line))

    def covering_origins(self, rule: str, line: int) -> Set[Tuple[int, str]]:
        """(origin line, matching token) for every directive that suppresses
        ``rule`` at ``line``; the token is the rule id or ``all``."""
        out: Set[Tuple[int, str]] = set()
        per_rule = self.by_line.get(line, {})
        for token in (rule, "all"):
            for origin in self.filewide.get(token, ()):
                out.add((origin, token))
            for origin in per_rule.get(token, ()):
                out.add((origin, token))
        return out


def _stale_suppression_findings(module: "Module", project: "Project",
                                used: Set[Tuple[int, str]]) -> Iterator[Finding]:
    """SUP001: directives whose rule tokens suppressed zero findings this
    run. Tokens for rules not in the active set are skipped (a subset run
    can't judge them); ``all`` tokens are judged only on full-registry runs
    for the same reason."""
    sup = module.suppressions
    active = {r.name for r in project.rules}
    full_registry = active >= set(all_rules())
    for origin in sorted(sup.directives):
        tokens = sup.directives[origin]
        if STALE_SUPPRESSION_RULE in tokens:
            continue  # explicit allowlist: deliberately-dormant suppression
        for token in sorted(tokens):
            if token == "all":
                if not full_registry:
                    continue
            elif token not in active or token == STALE_SUPPRESSION_RULE:
                continue
            if (origin, token) in used:
                continue
            yield Finding(
                rule=STALE_SUPPRESSION_RULE, path=module.path, line=origin,
                col=0,
                message=(f"suppression `disable={token}` matches no {token} "
                         f"finding: the directive is dead — delete it, or "
                         f"add {STALE_SUPPRESSION_RULE} to its rule list "
                         f"with a reason to keep it deliberately"),
                snippet=module.line(origin).strip())


# ---------------------------------------------------------------------------
# Module + project
# ---------------------------------------------------------------------------


class Module:
    """One parsed source file as seen by rules."""

    def __init__(self, path: str, source: str, project: "Project"):
        self.path = path  # posix, relative to project root
        self.source = source
        self.lines = source.splitlines()
        self.project = project
        self.tree = ast.parse(source)  # raises SyntaxError; caller handles
        self.resolver = ImportResolver(self.tree)
        self.suppressions = Suppressions(source)

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def parts(self) -> Tuple[str, ...]:
        return Path(self.path).parts


class Project:
    """Shared state for one lint run (root dir + per-run rule caches)."""

    def __init__(self, root: Path, rule_names: Optional[Sequence[str]] = None):
        self.root = Path(root).resolve()
        registry = all_rules()
        if rule_names:
            unknown = set(rule_names) - set(registry)
            if unknown:
                raise KeyError(f"unknown rule id(s): {', '.join(sorted(unknown))}")
            self.rules = [registry[n]() for n in rule_names]
        else:
            self.rules = [cls() for cls in registry.values()]
        self.rules.sort(key=lambda r: r.name)
        self.cache: Dict[object, object] = {}  # scratch for project-aware rules
        self.timings: Dict[str, float] = {}  # rule id -> cumulative seconds
        self.finding_counts: Dict[str, int] = {}  # rule id -> raw findings

    def relpath(self, path: Path) -> str:
        p = Path(path).resolve()
        try:
            return p.relative_to(self.root).as_posix()
        except ValueError:
            return p.as_posix()

    def check_source(self, source: str, relpath: str) -> List[Finding]:
        """Lint one in-memory source blob (suppressions applied, no baseline)."""
        try:
            module = Module(relpath, source, self)
        except SyntaxError as e:
            return [Finding(rule=PARSE_ERROR_RULE, path=relpath,
                            line=e.lineno or 1, col=e.offset or 0,
                            message=f"syntax error: {e.msg}", snippet="")]
        except ValueError as e:  # e.g. NUL bytes (ast.parse, py<=3.11)
            return [Finding(rule=PARSE_ERROR_RULE, path=relpath, line=1,
                            col=0, message=f"unparseable: {e}", snippet="")]
        raw: List[Finding] = []
        for rule in self.rules:
            started = time.perf_counter()
            rule_findings = list(rule.check(module))
            self.timings[rule.name] = (self.timings.get(rule.name, 0.0)
                                       + time.perf_counter() - started)
            self.finding_counts[rule.name] = (
                self.finding_counts.get(rule.name, 0) + len(rule_findings))
            raw.extend(rule_findings)
        findings: List[Finding] = []
        used: Set[Tuple[int, str]] = set()  # (directive origin line, token)
        sup = module.suppressions
        for f in raw:
            origins = sup.covering_origins(f.rule, f.line)
            if origins:
                used |= origins
            else:
                findings.append(f)
        if any(r.name == STALE_SUPPRESSION_RULE for r in self.rules):
            raw_stale = list(_stale_suppression_findings(module, self, used))
            self.finding_counts[STALE_SUPPRESSION_RULE] = (
                self.finding_counts.get(STALE_SUPPRESSION_RULE, 0)
                + len(raw_stale))
            for f in raw_stale:
                if not sup.covering_origins(f.rule, f.line):
                    findings.append(f)
        findings.sort()
        return findings

    def check_file(self, path: Path) -> List[Finding]:
        rel = self.relpath(path)
        try:
            source = Path(path).read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError) as e:
            return [Finding(rule=PARSE_ERROR_RULE, path=rel, line=1, col=0,
                            message=f"unreadable: {e}", snippet="")]
        return self.check_source(source, rel)


def iter_py_files(paths: Iterable[Path]) -> Iterator[Path]:
    for p in paths:
        p = Path(p)
        if p.is_file():
            if p.suffix == ".py":
                yield p
        elif p.is_dir():
            for sub in sorted(p.rglob("*.py")):
                # skip-dir filter applies only BELOW the search root: a repo
                # checked out under a dot-prefixed ancestor must still lint
                rel_parts = sub.relative_to(p).parts
                if not any(part in _SKIP_DIRS or part.startswith(".")
                           for part in rel_parts):
                    yield sub


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------

BASELINE_VERSION = 1


def load_baseline(path: Path) -> Counter:
    """Baseline file -> multiset of (rule, path, snippet) keys."""
    data = json.loads(Path(path).read_text(encoding="utf-8"))
    counts: Counter = Counter()
    for entry in data.get("findings", []):
        key = (entry["rule"], entry["path"], entry["snippet"])
        counts[key] += int(entry.get("count", 1))
    return counts


def dump_baseline(findings: Iterable[Finding]) -> str:
    """Serialize findings as a sorted, deterministic baseline document."""
    counts: Counter = Counter(f.key() for f in findings)
    entries = [
        {"rule": rule, "path": path, "snippet": snippet, "count": n}
        for (rule, path, snippet), n in sorted(counts.items())
    ]
    doc = {
        "comment": "raylint baseline: reviewed, grandfathered findings. "
                   "Regenerate with `python -m tools.raylint --write-baseline` "
                   "only after reviewing every new entry.",
        "version": BASELINE_VERSION,
        "findings": entries,
    }
    return json.dumps(doc, indent=2, sort_keys=False) + "\n"


@dataclasses.dataclass
class Report:
    findings: List[Finding]          # new (non-baselined, non-suppressed)
    baselined: List[Finding]         # matched a baseline entry
    unused_baseline: List[Tuple[str, str, str]]  # stale baseline keys
    files_checked: int
    stats: Optional[dict] = None     # per-rule timings etc. (--stats)

    @property
    def ok(self) -> bool:
        """No NEW findings (the tier-1 'is the tree clean' question)."""
        return not self.findings

    @property
    def passed(self) -> bool:
        """The full gate contract: no new findings AND no stale baseline
        entries. This is what the CLI exit status reflects."""
        return self.ok and not self.unused_baseline

    def to_json(self) -> dict:
        return {
            "ok": self.passed,
            "files_checked": self.files_checked,
            "findings": [f.to_json() for f in self.findings],
            "baselined_count": len(self.baselined),
            "unused_baseline": [
                {"rule": r, "path": p, "snippet": s}
                for r, p, s in self.unused_baseline
            ],
        }


def check_paths(paths: Sequence[Path], root: Path,
                baseline: Optional[Counter] = None,
                rule_names: Optional[Sequence[str]] = None,
                stats: bool = False) -> Report:
    project = Project(root, rule_names)
    raw: List[Finding] = []
    scanned: Set[str] = set()
    for f in iter_py_files(paths):
        rel = project.relpath(f)
        if rel in scanned:  # overlapping search paths: lint each file once
            continue
        scanned.add(rel)
        raw.extend(project.check_file(f))
    raw.sort()
    remaining = Counter(baseline or ())
    new: List[Finding] = []
    matched: List[Finding] = []
    for finding in raw:
        if remaining.get(finding.key(), 0) > 0:
            remaining[finding.key()] -= 1
            matched.append(finding)
        else:
            new.append(finding)
    # an entry is only "stale" if its file was actually scanned AND its rule
    # actually ran — a subset run (paths or --rules) must not report
    # out-of-scope entries as stale
    active = {r.name for r in project.rules}
    unused = sorted(k for k, n in remaining.items()
                    if n > 0 and k[0] in active and k[1] in scanned
                    for _ in range(n))
    stats_doc = None
    if stats:
        stats_doc = {"rule_seconds": dict(project.timings),
                     "rule_findings": dict(project.finding_counts)}
        g = project.cache.get("graph")
        if g is not None:
            stats_doc["graph"] = dict(g.stats)
    return Report(findings=new, baselined=matched,
                  unused_baseline=unused, files_checked=len(scanned),
                  stats=stats_doc)

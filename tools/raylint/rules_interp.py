"""raylint interprocedural rule set: whole-program invariants.

These rules run on the graph layer (tools/raylint/graph.py) and the flow
layer (tools/raylint/flow.py) instead of single-file AST patterns:

* ASY004 — blocking call *transitively* reachable from an ``async def``
  through a chain of sync helpers. Generalizes ASY001, which only sees the
  direct call: ``async def handler`` -> ``self._sync_helper()`` ->
  ``_do_io()`` -> ``time.sleep`` stalls the event loop just the same.
* LCK002 — lock-order cycle in the *global* lock-acquisition graph, built
  from ``with <lock>:`` nesting within functions and across resolved call
  edges. Generalizes LCK001's hand-tiered GCS -> raylet -> core-worker
  direction to every lock on the control/weight/checkpoint/serve planes:
  any cycle (including a non-reentrant lock re-acquired through a helper —
  a self-deadlock) fails the lint.
* AWT002 — ``await`` while holding a lock, flow-sensitively: the held-lock
  set is propagated across intraprocedural CFG paths (``.acquire()`` /
  ``.release()``; aliases resolved via reaching definitions) and through one
  level of call inlining (a helper whose net effect is to leave a lock
  held). ASY002 only sees ``await`` lexically inside ``with <lock>:``.
* WIRE002 — wire-schema drift: for every ``register_struct`` entry in
  ``_private/wire.py``, encoded-field list vs decode-lambda reads vs the
  struct's actual fields must agree; and every RPC method must have both a
  client call site and a server handler (``_rpc_X`` or a ``method == "X"``
  dispatcher arm) somewhere in the tree — a one-sided add is a lint
  failure, not a runtime KeyError on a 16-node stress run.

Per-module reporting: each rule computes whole-program facts once (memoized
on the shared graph view) and emits only the findings that anchor in the
module currently being checked, so baseline/suppression semantics stay
file-local like every other raylint rule.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from tools.raylint import flow
from tools.raylint import graph as graphmod
from tools.raylint.core import Finding, Module, Rule, register_rule
from tools.raylint.graph import GraphView, summarize_module
from tools.raylint.rules import _is_lock_like

# paths (relative to repo root) whose locks participate in LCK002
_LCK_SCOPE = ("ray_tpu/_private/", "ray_tpu/weights/", "ray_tpu/ckpt/",
              "ray_tpu/serve/")


def _interp_state(module: Module) -> Tuple[Optional[GraphView], Optional[dict]]:
    """(GraphView, this module's summary). Pristine modules (content matches
    the on-disk graph) share one view so interprocedural memos persist
    across the whole run; fixtures get an overlay view with their fresh
    AST layered over the project graph."""
    project = module.project
    g = graphmod.project_graph(project)
    pristine_view: GraphView = project.cache.get("interp.view")
    if pristine_view is None:
        pristine_view = GraphView(g)
        project.cache["interp.view"] = pristine_view
    if pristine_view.is_pristine(module.path, module.source):
        return pristine_view, pristine_view.module(module.path)
    cache_key = ("interp.overlay", module.path, hash(module.source))
    cached = project.cache.get(cache_key)
    if cached is not None:
        return cached
    try:
        summary = summarize_module(module.path, module.source, module.tree)
    except SyntaxError:
        project.cache[cache_key] = (None, None)
        return None, None
    view = GraphView(g, overlay=summary)
    project.cache[cache_key] = (view, summary)
    return view, summary


def _fmt_chain(chain: List[tuple]) -> str:
    return " -> ".join(f"{p}:{q}:{ln}" for p, q, ln in chain)


def _lock_display(lock_id: str) -> str:
    # "ray_tpu._private.gcs:GcsServer._lock" -> "GcsServer._lock"
    return lock_id.split(":", 1)[-1]


# ---------------------------------------------------------------------------
# ASY004 — transitively-reachable blocking call from async context
# ---------------------------------------------------------------------------


@register_rule
class TransitiveBlockingCall(Rule):
    name = "ASY004"
    summary = ("blocking call reachable from `async def` through sync helper "
               "chains: stalls the event loop exactly like ASY001, one or "
               "more calls removed")

    def check(self, module: Module) -> Iterator[Finding]:
        view, summary = _interp_state(module)
        if view is None or summary is None:
            return iter(())
        findings: List[Finding] = []
        for func in summary["functions"].values():
            if not func["is_async"]:
                continue
            for call in func["calls"]:
                target = view.resolve_call(module.path, func, call)
                if target is None:
                    continue
                tf = view.func(target)
                if tf is None or tf["is_async"]:
                    continue
                hit = view.blocking_chain(target)
                if hit is None:
                    continue
                chain, what, hint = hit
                full = [(module.path, func["qual"], call["line"])] + chain
                findings.append(Finding(
                    rule=self.name, path=module.path, line=call["line"],
                    col=0,
                    message=(f"async `{func['qual']}` reaches blocking "
                             f"`{what}` through sync helper(s): "
                             f"{_fmt_chain(full)}; {hint} (or hand the whole "
                             f"chain to an executor)"),
                    snippet=module.line(call["line"]).strip()))
        return iter(findings)


# ---------------------------------------------------------------------------
# LCK002 — lock-order cycles in the global acquisition graph
# ---------------------------------------------------------------------------


def _tarjan_sccs(adj: Dict[str, Set[str]]) -> List[List[str]]:
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    for root in list(adj):
        if root in index:
            continue
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(adj.get(nxt, ())))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    n = stack.pop()
                    on_stack.discard(n)
                    scc.append(n)
                    if n == node:
                        break
                sccs.append(scc)
    return sccs


def _shortest_cycle_via(adj: Dict[str, Set[str]], a: str, b: str,
                        scc: Set[str]) -> List[str]:
    """Shortest b -> ... -> a path inside the SCC; the a -> b edge closes it."""
    if a == b:
        return [a, a]
    frontier = [[b]]
    seen = {b}
    while frontier:
        path = frontier.pop(0)
        for nxt in sorted(adj.get(path[-1], ())):
            if nxt == a:
                return [a] + path + [a]
            if nxt in scc and nxt not in seen:
                seen.add(nxt)
                frontier.append(path + [nxt])
    return [a, b, a]  # unreachable in a true SCC; defensive


@register_rule
class LockOrderCycle(Rule):
    name = "LCK002"
    summary = ("cycle in the global lock-acquisition graph (with-nesting "
               "across call edges): two paths that interleave deadlock — "
               "covers every lock in _private/, weights/, ckpt/, serve/")

    def _offending_edges(self, view: GraphView):
        cached = getattr(view, "_lck002_memo", None)
        if cached is not None:
            return cached
        edges = view.lock_graph(_LCK_SCOPE)
        adj: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        rlocks = view.rlock_ids()
        comp: Dict[str, int] = {}
        scc_sets: List[Set[str]] = []
        for i, scc in enumerate(_tarjan_sccs(adj)):
            scc_sets.append(set(scc))
            for n in scc:
                comp[n] = i
        offending = []  # (edge, site, cycle-path)
        for (a, b), site in sorted(edges.items()):
            if a == b:
                if a not in rlocks:
                    offending.append(((a, b), site, [a, a]))
            elif comp.get(a) == comp.get(b) \
                    and len(scc_sets[comp[a]]) >= 2:
                cycle = _shortest_cycle_via(adj, a, b, scc_sets[comp[a]])
                offending.append(((a, b), site, cycle))
        view._lck002_memo = offending
        return offending

    def check(self, module: Module) -> Iterator[Finding]:
        view, summary = _interp_state(module)
        if view is None:
            return iter(())
        findings: List[Finding] = []
        for (a, b), (path, line), cycle in self._offending_edges(view):
            if path != module.path:
                continue
            names = " -> ".join(f"`{_lock_display(n)}`" for n in cycle)
            if a == b:
                msg = (f"non-reentrant lock `{_lock_display(a)}` re-acquired "
                       f"on a path that already holds it (through a helper "
                       f"call): self-deadlock; make the inner path "
                       f"lock-free or use an RLock deliberately")
            else:
                msg = (f"`{_lock_display(b)}` acquired while holding "
                       f"`{_lock_display(a)}` closes the lock-order cycle "
                       f"{names}; pick one global order for these locks and "
                       f"invert this nesting")
            findings.append(Finding(
                rule=self.name, path=module.path, line=line, col=0,
                message=msg, snippet=module.line(line).strip()))
        return iter(findings)


# ---------------------------------------------------------------------------
# AWT002 — await while holding a lock (flow-sensitive, one-level inlining)
# ---------------------------------------------------------------------------


@register_rule
class AwaitHoldingLockFlow(Rule):
    name = "AWT002"
    summary = ("`await` while a lock acquired via `.acquire()` (or left held "
               "by a sync helper) is still held on some path: the loop "
               "thread parks holding it — ASY002 only sees lexical `with`")

    def check(self, module: Module) -> Iterator[Finding]:
        view, summary = _interp_state(module)
        if view is None or summary is None:
            return iter(())
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                findings.extend(self._check_async_fn(module, view, summary, node))
        return iter(findings)

    def _check_async_fn(self, module: Module, view: GraphView, summary: dict,
                        fn: ast.AsyncFunctionDef) -> List[Finding]:
        func = self._summary_for(summary, fn)
        if func is None:
            return []
        cfg = flow.build_cfg(fn)
        if not cfg.nodes:
            return []
        defs = flow.reaching_defs(cfg)
        resolver = module.resolver
        module_locks = _module_lock_names(summary)

        def norm(expr: ast.AST) -> Optional[str]:
            return graphmod.lock_identity(
                expr, resolver, summary["modname"], func["cls"],
                func["qual"], module_locks, aliases={})

        def lock_id_at(expr: ast.AST, stmt_index: int) -> Optional[str]:
            """Resolve a lock expression, following a local alias through
            its reaching definitions (all reaching defs must agree)."""
            if isinstance(expr, ast.Name):
                reaching = defs.get(stmt_index, {}).get(expr.id)
                if reaching and all(v is not None for v in reaching):
                    ids = set()
                    for value in reaching:
                        if isinstance(value, (ast.Name, ast.Attribute)) \
                                and _is_lock_like(value, resolver):
                            ids.add(norm(value))
                        else:
                            return None
                    if len(ids) == 1:
                        return ids.pop()
                return None
            if isinstance(expr, ast.Attribute) \
                    and _is_lock_like(expr, resolver):
                return norm(expr)
            return None

        index_of = {id(s): i for i, s in enumerate(cfg.nodes)}

        def transfer(stmt: ast.stmt, held: FrozenSet) -> FrozenSet:
            i = index_of[id(stmt)]
            out = set(held)
            awaited_calls = {
                id(n.value) for n in ast.walk(stmt)
                if isinstance(n, ast.Await) and isinstance(n.value, ast.Call)}
            for call in flow.stmt_calls(stmt):
                if not isinstance(call.func, ast.Attribute):
                    # maybe a helper with net lock effects
                    self._apply_helper(module, view, func, call, out)
                    continue
                attr = call.func.attr
                if attr in ("acquire", "release"):
                    lock = lock_id_at(call.func.value, i)
                    if lock is None:
                        continue
                    if attr == "acquire" and id(call) not in awaited_calls:
                        out.add(lock)
                    elif attr == "release":
                        out.discard(lock)
                else:
                    self._apply_helper(module, view, func, call, out)
            return frozenset(out)

        IN = flow.forward_may(cfg, transfer)
        findings = []
        seen_lines: Set[int] = set()
        for i, stmt in enumerate(cfg.nodes):
            held = IN[i]
            if not held:
                continue
            for aw in flow.stmt_awaits(stmt):
                line = getattr(aw, "lineno", stmt.lineno)
                if line in seen_lines:
                    continue
                seen_lines.add(line)
                locks = ", ".join(sorted(_lock_display(l) for l in held))
                findings.append(Finding(
                    rule=self.name, path=module.path, line=line, col=0,
                    message=(f"await with lock(s) {locks} still held on some "
                             f"path (acquired via .acquire() or a helper, "
                             f"not released before awaiting): the event-loop "
                             f"thread parks holding the lock — release "
                             f"first, or use asyncio primitives"),
                    snippet=module.line(line).strip()))
        return findings

    def _apply_helper(self, module: Module, view: GraphView, func: dict,
                      call: ast.Call, out: Set[str]):
        """One level of call inlining: a resolved sync helper's net
        acquire/release effect lands in the caller's held set."""
        raw = module.resolver.dotted(call.func)
        if raw is None:
            return
        entry = {"raw": raw, "attr": None, "line": call.lineno, "held": []}
        target = view.resolve_call(module.path, func, entry)
        if target is None:
            return
        tf = view.func(target)
        if tf is None or tf["is_async"]:
            return
        acquired, released = view.net_lock_effects(target)
        out.update(acquired)
        out.difference_update(released)

    @staticmethod
    def _summary_for(summary: dict, fn: ast.AST) -> Optional[dict]:
        for func in summary["functions"].values():
            if func["line"] == fn.lineno and func["is_async"]:
                return func
        return None


def _module_lock_names(summary: dict) -> Set[str]:
    """Module-level lock names aren't kept in summaries; recover the common
    case (module-global `_lock = threading.Lock()`) from the lock ids
    already recorded, so `lock_identity` normalizes the same at rule time
    as it did at summary time."""
    return {
        lock.split(":", 1)[1]
        for fq in summary["functions"].values()
        for lock, _ in fq["acquires"] + fq["acq_calls"]
        if ":" in lock and "." not in lock.split(":", 1)[1]
    }


# ---------------------------------------------------------------------------
# WIRE002 — wire-schema drift
# ---------------------------------------------------------------------------


@register_rule
class WireSchemaDrift(Rule):
    name = "WIRE002"
    summary = ("wire-schema drift: register_struct field list vs decode "
               "reads vs struct definition must agree, and every RPC method "
               "needs both a client call site and a server handler")

    def _universe(self, view: GraphView):
        cached = getattr(view, "_wire002_memo", None)
        if cached is None:
            cached = (view.rpc_handlers(), view.rpc_calls())
            view._wire002_memo = cached
        return cached

    def check(self, module: Module) -> Iterator[Finding]:
        view, summary = _interp_state(module)
        if view is None or summary is None:
            return iter(())
        findings: List[Finding] = []
        handlers, calls = self._universe(view)

        def add(line: int, message: str):
            findings.append(Finding(
                rule=self.name, path=module.path, line=line, col=0,
                message=message, snippet=module.line(line).strip()))

        # client side: a called method with no handler anywhere
        own_calls = {}
        for name, sites in calls.items():
            for path, line in sites:
                if path == module.path:
                    own_calls.setdefault(name, []).append(line)
        for name, lines in sorted(own_calls.items()):
            if name in handlers:
                continue
            for line in lines:
                add(line, f"RPC `{name}` is called here but no server "
                          f"defines a handler for it (`_rpc_{name}` or a "
                          f"`method == \"{name}\"` dispatcher arm): this "
                          f"raises at runtime on the first call")
        # server side: a handler nobody calls
        own_handlers = [(n, l) for n, l in
                        summary["rpc_handlers"] + summary["rpc_dispatch"]]
        for name, line in sorted(own_handlers):
            if name not in calls:
                add(line, f"RPC handler `{name}` has no client call site "
                          f"anywhere in ray_tpu/: dead wire surface — "
                          f"delete it, or suppress with the reason it "
                          f"exists (external tooling, test protocol)")
        # registry parity (wire.py only)
        if Path(module.path).name == "wire.py":
            findings.extend(self._registry_findings(module, view, summary))
        return iter(findings)

    def _registry_findings(self, module: Module, view: GraphView,
                           summary: dict) -> List[Finding]:
        findings: List[Finding] = []

        def add(line: int, message: str):
            findings.append(Finding(
                rule=self.name, path=module.path, line=line, col=0,
                message=message, snippet=module.line(line).strip()))

        for entry in summary["wire_registry"]:
            fields = entry["fields"]
            decode_fields = entry["decode_fields"]
            line = entry["line"]
            cls_raw = entry["cls"] or "<unknown>"
            cls_name = cls_raw.rsplit(".", 1)[-1]
            if fields is not None and decode_fields is not None:
                for missing in sorted(set(decode_fields) - set(fields)):
                    add(line, f"decode for `{cls_name}` reads field "
                              f"`{missing}` that is not in its encoded "
                              f"field list: KeyError on every decoded "
                              f"message — add it to fields=(...) too")
                for extra in sorted(set(fields) - set(decode_fields)):
                    add(line, f"`{cls_name}` encodes field `{extra}` that "
                              f"its decode never reads: the value is "
                              f"silently dropped on the receiving side — "
                              f"read it in decode or stop encoding it")
            if fields is not None and entry["cls"]:
                cls_def = self._class_def(view, entry["cls"])
                if cls_def is not None:
                    known = set(cls_def["fields"]) | set(cls_def["init_params"])
                    for f in fields:
                        if f not in known:
                            add(line, f"`{cls_name}` has no field or "
                                      f"constructor parameter `{f}`; the "
                                      f"encoder would raise AttributeError "
                                      f"on every send — fix the fields "
                                      f"tuple or the struct")
        return findings

    @staticmethod
    def _class_def(view: GraphView, dotted: str) -> Optional[dict]:
        parts = dotted.split(".")
        if len(parts) < 2:
            return None
        for cut in range(len(parts) - 1, 0, -1):
            mod_name = ".".join(parts[:cut])
            path = view._by_modname.get(mod_name)
            if path is None:
                continue
            rest = parts[cut:]
            if len(rest) == 1:
                return view._modules[path]["classes"].get(rest[0])
            return None
        return None

"""raylint: AST-based invariant checker for the ray_tpu distributed runtime.

See tools/raylint/README.md for rules, rationale, and suppression syntax.
Programmatic entry points:

    from tools.raylint import core
    report = core.check_paths([Path("ray_tpu")], root=REPO_ROOT)
"""

from tools.raylint.core import (  # noqa: F401
    Finding,
    Project,
    Report,
    Rule,
    all_rules,
    check_paths,
    dump_baseline,
    load_baseline,
    register_rule,
)

__version__ = "0.1.0"

"""raylint: invariant checker for the ray_tpu distributed runtime.

v1: per-file AST pattern rules (rules.py). v2 adds whole-program analysis:
a project-wide import/call graph (graph.py, content-hash cached) and
per-function CFG dataflow (flow.py) driving the interprocedural rules in
rules_interp.py (ASY004/LCK002/AWT002/WIRE002).

See tools/raylint/README.md for rules, rationale, and suppression syntax.
Programmatic entry points:

    from tools.raylint import core
    report = core.check_paths([Path("ray_tpu")], root=REPO_ROOT)
"""

from tools.raylint.core import (  # noqa: F401
    Finding,
    Project,
    Report,
    Rule,
    all_rules,
    check_paths,
    dump_baseline,
    load_baseline,
    register_rule,
)

__version__ = "0.2.0"

"""raylint CLI.

Usage::

    python -m tools.raylint [paths ...] [options]

With no paths, lints ``ray_tpu/`` under the repo root. Exit status: 0 when
clean (every finding suppressed or baselined), 1 when new findings exist,
2 on usage errors.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import Counter
from pathlib import Path

from tools.raylint import core

REPO_ROOT = Path(__file__).resolve().parents[2]
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.raylint",
        description="AST-based invariant checker for the ray_tpu runtime.")
    p.add_argument("paths", nargs="*", default=[],
                   help="files or directories to lint (default: ray_tpu/)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit a JSON report instead of text")
    p.add_argument("--baseline", type=Path, default=DEFAULT_BASELINE,
                   help=f"baseline file (default: {DEFAULT_BASELINE})")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: report every finding")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline file from the current findings "
                        "(review the diff before committing!)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="list registered rules and exit")
    return p


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        for name, cls in sorted(core.all_rules().items()):
            print(f"{name}  {cls.summary}")
        return 0

    rule_names = None
    if args.rules:
        rule_names = [r.strip() for r in args.rules.split(",") if r.strip()]

    if args.write_baseline and (args.paths or rule_names):
        # a partial run would overwrite the baseline with only its own
        # subset, silently erasing every other reviewed entry
        print("raylint: --write-baseline requires a full default run "
              "(no explicit paths, no --rules)", file=sys.stderr)
        return 2

    paths = [Path(p) for p in args.paths] or [REPO_ROOT / "ray_tpu"]
    for p in paths:
        if not p.exists():
            print(f"raylint: no such path: {p}", file=sys.stderr)
            return 2

    baseline = Counter()
    if not (args.no_baseline or args.write_baseline):
        if args.baseline.is_file():
            try:
                baseline = core.load_baseline(args.baseline)
            except (ValueError, KeyError) as e:
                print(f"raylint: bad baseline {args.baseline}: {e}",
                      file=sys.stderr)
                return 2

    try:
        report = core.check_paths(paths, REPO_ROOT, baseline=baseline,
                                  rule_names=rule_names)
    except KeyError as e:
        print(f"raylint: {e.args[0]}", file=sys.stderr)
        return 2

    if args.write_baseline:
        parse_errors = [f for f in report.findings
                        if f.rule == core.PARSE_ERROR_RULE]
        if parse_errors:
            # grandfathering a parse error would exempt the file from every
            # rule forever; it must be fixed, not baselined
            for f in parse_errors:
                print(f.render(), file=sys.stderr)
            print("raylint: refusing to write a baseline containing parse "
                  "errors", file=sys.stderr)
            return 2
        args.baseline.write_text(core.dump_baseline(report.findings),
                                 encoding="utf-8")
        print(f"raylint: wrote {len(report.findings)} finding(s) to "
              f"{args.baseline}")
        return 0

    if args.as_json:
        print(json.dumps(report.to_json(), indent=2))
    else:
        for f in report.findings:
            print(f.render())
        for rule, path, snippet in report.unused_baseline:
            print(f"warning: stale baseline entry {rule} at {path}: {snippet!r}",
                  file=sys.stderr)
        if report.passed:
            status = "clean"
        elif report.ok:
            status = (f"{len(report.unused_baseline)} stale baseline "
                      f"entr(y/ies)")
        else:
            status = f"{len(report.findings)} finding(s)"
        print(f"raylint: {report.files_checked} file(s), {status}, "
              f"{len(report.baselined)} baselined", file=sys.stderr)
    # stale entries fail too: tier-1 (tests/test_raylint.py) rejects them,
    # so the CLI must not report a false green
    return 0 if report.passed else 1


if __name__ == "__main__":
    sys.exit(main())

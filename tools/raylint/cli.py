"""raylint CLI.

Usage::

    python -m tools.raylint [paths ...] [options]

With no paths, lints ``ray_tpu/`` under the repo root. Exit status: 0 when
clean (every finding suppressed or baselined), 1 when new findings exist,
2 on usage errors.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from collections import Counter
from pathlib import Path

from tools.raylint import core

REPO_ROOT = Path(__file__).resolve().parents[2]
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.raylint",
        description="AST-based invariant checker for the ray_tpu runtime.")
    p.add_argument("paths", nargs="*", default=[],
                   help="files or directories to lint (default: ray_tpu/)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit a JSON report instead of text")
    p.add_argument("--baseline", type=Path, default=DEFAULT_BASELINE,
                   help=f"baseline file (default: {DEFAULT_BASELINE})")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: report every finding")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline file from the current findings "
                        "(review the diff before committing!)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="list registered rules and exit")
    p.add_argument("--changed", action="store_true",
                   help="lint only files changed vs git HEAD (plus "
                        "untracked), intersected with the target paths")
    p.add_argument("--stats", action="store_true",
                   help="print per-rule wall time and raw finding counts "
                        "(and graph-cache stats) to stderr")
    p.add_argument("--no-graph-cache", action="store_true",
                   help="ignore and don't write tools/raylint/.graphcache.json")
    return p


def _changed_files(repo_root: Path):
    """Changed-vs-HEAD plus untracked .py files, repo-relative. Returns
    None when git itself fails — the caller must error out rather than
    treat a broken git as 'nothing changed' and report a false green."""
    out = []
    for args in (["git", "diff", "--name-only", "HEAD", "--", "*.py"],
                 ["git", "ls-files", "--others", "--exclude-standard",
                  "--", "*.py"]):
        try:
            proc = subprocess.run(args, cwd=repo_root, capture_output=True,
                                  text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired) as e:
            print(f"raylint: --changed needs git: {e}", file=sys.stderr)
            return None
        if proc.returncode != 0:
            print(f"raylint: `{' '.join(args)}` failed: "
                  f"{proc.stderr.strip()}", file=sys.stderr)
            return None
        out.extend(l.strip() for l in proc.stdout.splitlines() if l.strip())
    return sorted(set(out))


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        for name, cls in sorted(core.all_rules().items()):
            print(f"{name}  {cls.summary}")
        return 0

    rule_names = None
    if args.rules:
        rule_names = [r.strip() for r in args.rules.split(",") if r.strip()]

    if args.write_baseline and (args.paths or rule_names or args.changed):
        # a partial run would overwrite the baseline with only its own
        # subset, silently erasing every other reviewed entry
        print("raylint: --write-baseline requires a full default run "
              "(no explicit paths, no --rules, no --changed)",
              file=sys.stderr)
        return 2

    if args.no_graph_cache:
        # scoped to this invocation: an in-process caller (tests,
        # programmatic use) must not have the cache silently disabled for
        # every later run in the same interpreter
        prior = os.environ.get("RAYLINT_NO_GRAPH_CACHE")
        os.environ["RAYLINT_NO_GRAPH_CACHE"] = "1"
        try:
            return _run(args, rule_names)
        finally:
            if prior is None:
                os.environ.pop("RAYLINT_NO_GRAPH_CACHE", None)
            else:
                os.environ["RAYLINT_NO_GRAPH_CACHE"] = prior
    return _run(args, rule_names)


def _run(args, rule_names) -> int:
    paths = [Path(p) for p in args.paths] or [REPO_ROOT / "ray_tpu"]
    for p in paths:
        if not p.exists():
            print(f"raylint: no such path: {p}", file=sys.stderr)
            return 2

    if args.changed:
        changed_rel = _changed_files(REPO_ROOT)
        if changed_rel is None:
            return 2  # git failure must not read as "nothing to lint"
        targets = {f.resolve() for p in paths for f in core.iter_py_files([p])}
        changed = [REPO_ROOT / rel for rel in changed_rel]
        paths = [p for p in changed if p.exists() and p.resolve() in targets]
        if not paths:
            print("raylint: no changed files in scope", file=sys.stderr)
            return 0

    baseline = Counter()
    if not (args.no_baseline or args.write_baseline):
        if args.baseline.is_file():
            try:
                baseline = core.load_baseline(args.baseline)
            except (ValueError, KeyError) as e:
                print(f"raylint: bad baseline {args.baseline}: {e}",
                      file=sys.stderr)
                return 2

    started = time.perf_counter()
    try:
        report = core.check_paths(paths, REPO_ROOT, baseline=baseline,
                                  rule_names=rule_names, stats=args.stats)
    except KeyError as e:
        print(f"raylint: {e.args[0]}", file=sys.stderr)
        return 2
    elapsed = time.perf_counter() - started

    if args.stats and report.stats is not None:
        timings = report.stats.get("rule_seconds", {})
        counts = report.stats.get("rule_findings", {})
        print("raylint --stats (per-rule wall time over the whole run):",
              file=sys.stderr)
        for rule in sorted(timings, key=lambda r: -timings[r]):
            print(f"  {rule:8s} {timings[rule] * 1000:9.1f} ms  "
                  f"{counts.get(rule, 0):5d} raw finding(s)", file=sys.stderr)
        g = report.stats.get("graph")
        if g:
            print(f"  graph    {g['build_seconds'] * 1000:9.1f} ms  "
                  f"{g['files']} file(s), {g['cache_hits']} cache hit(s), "
                  f"{g['parsed']} parsed", file=sys.stderr)
            if "context_build_seconds" in g:
                hit = "cached" if g.get("context_cache_hit") else "computed"
                print(f"  context  {g['context_build_seconds'] * 1000:9.1f}"
                      f" ms  ({hit})", file=sys.stderr)
        print(f"  total    {elapsed * 1000:9.1f} ms", file=sys.stderr)

    if args.write_baseline:
        parse_errors = [f for f in report.findings
                        if f.rule == core.PARSE_ERROR_RULE]
        if parse_errors:
            # grandfathering a parse error would exempt the file from every
            # rule forever; it must be fixed, not baselined
            for f in parse_errors:
                print(f.render(), file=sys.stderr)
            print("raylint: refusing to write a baseline containing parse "
                  "errors", file=sys.stderr)
            return 2
        args.baseline.write_text(core.dump_baseline(report.findings),
                                 encoding="utf-8")
        print(f"raylint: wrote {len(report.findings)} finding(s) to "
              f"{args.baseline}")
        return 0

    if args.as_json:
        print(json.dumps(report.to_json(), indent=2))
    else:
        for f in report.findings:
            print(f.render())
        for rule, path, snippet in report.unused_baseline:
            print(f"warning: stale baseline entry {rule} at {path}: {snippet!r}",
                  file=sys.stderr)
        if report.passed:
            status = "clean"
        elif report.ok:
            status = (f"{len(report.unused_baseline)} stale baseline "
                      f"entr(y/ies)")
        else:
            status = f"{len(report.findings)} finding(s)"
        print(f"raylint: {report.files_checked} file(s), {status}, "
              f"{len(report.baselined)} baselined", file=sys.stderr)
    # stale entries fail too: tier-1 (tests/test_raylint.py) rejects them,
    # so the CLI must not report a false green
    return 0 if report.passed else 1


if __name__ == "__main__":
    sys.exit(main())

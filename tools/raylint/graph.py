"""raylint graph layer: whole-program import/call graph over ``ray_tpu/``.

raylint v1 rules are per-file AST pattern matchers; the bugs that hurt most in
the multi-plane control plane are cross-function and cross-file (a blocking
call three sync helpers below an ``async def``, a lock-order cycle spanning
gcs.py and raylet.py, a wire struct whose serializer and deserializer
drifted). This module gives rules a *project* view:

* :func:`summarize_module` — one pass over a module's AST producing a
  JSON-serializable :class:`dict` summary: every function (module-level,
  class methods, nested defs) with its async/sync color, resolved call
  expressions, direct blocking calls, lock acquisitions (``with`` nesting
  edges and ``.acquire()``/``.release()`` pairs), RPC handler/call-site
  material, and wire-registry entries.
* :class:`ProjectGraph` — the summaries for every file under
  ``<root>/ray_tpu``, built lazily and cached to
  ``tools/raylint/.graphcache.json`` keyed by file content hashes, so a
  warm tier-1 run re-parses only edited files.
* :class:`GraphView` — resolution + interprocedural queries (transitive
  blocking chains, transitive lock acquisitions, the global lock graph,
  RPC parity universe) over the project graph with an optional per-module
  overlay, so in-memory fixtures (``Project.check_source``) analyze their
  own fresh AST while still seeing the rest of the tree.

Everything here is stdlib-only, like the rest of raylint.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import time
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.raylint.core import ImportResolver, iter_py_files
from tools.raylint.rules import _BLOCKING_CALLS, _SOCKET_METHODS, _is_lock_like

# bump whenever summarize_module's output shape or content rules change —
# cached summaries from an older summarizer are silently wrong otherwise
GRAPH_SCHEMA_VERSION = 8

DEFAULT_CACHE_NAME = ".graphcache.json"

# Callee terminal names whose first string-literal argument is an RPC method
# name (RpcClient.call/notify plus the thin wrappers grown around them).
_RPC_CALL_TERMINALS = {"call", "notify", "_gcs"}

# receiver hints for `.result()` — a concurrent.futures result() blocks the
# calling thread until the future resolves
_FUTURE_HINTS = ("fut", "future", "promise")

# container methods that are a single bytecode op under the GIL (the
# sanctioned lock-free producer/consumer idiom on deque) vs. mutations that
# are compound or invalidate concurrent readers
_ATOMIC_METHODS = {"append", "appendleft", "pop", "popleft"}
_MUTATING_METHODS = _ATOMIC_METHODS | {
    "add", "discard", "remove", "clear", "update", "extend", "insert",
    "setdefault", "popitem"}

# module-level constructors whose instances are mutable process state; the
# kind feeds FRK001's fork-safety gate and RCE001's field classification
_STATE_CONSTRUCTORS = {
    "Lock": "lock", "RLock": "lock", "Condition": "lock", "Event": "lock",
    "Semaphore": "lock", "BoundedSemaphore": "lock", "Barrier": "lock",
    "ContextVar": "contextvar",
    "deque": "buffer", "defaultdict": "buffer", "Counter": "buffer",
    "OrderedDict": "buffer", "dict": "buffer", "list": "buffer",
    "set": "buffer", "Queue": "buffer", "SimpleQueue": "buffer",
    "LifoQueue": "buffer", "PriorityQueue": "buffer", "local": "buffer",
}

# spawn-site shapes: callee terminal -> (context kind, target arg position).
# Thread(target=...) / Timer(_, f) start a background thread; call_soon* /
# call_later / create_task / ensure_future schedule onto the event loop.
_THREAD_SPAWN_ARG = {"Timer": 1, "run_in_executor": 1}
_LOOP_SPAWN_ARG = {"call_soon": 0, "call_soon_threadsafe": 0,
                   "call_later": 1, "call_at": 1,
                   "create_task": 0, "ensure_future": 0, "spawn": 0}


def _scoped_walk(fn):
    """Walk a function's AST without descending into nested defs/lambdas
    (their bodies bind and run in their own scope)."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def _sha(source: str) -> str:
    return hashlib.sha256(source.encode("utf-8", "replace")).hexdigest()


def _modname(path: str) -> str:
    name = path[:-3] if path.endswith(".py") else path
    name = name.replace("/", ".")
    if name.endswith(".__init__"):
        name = name[: -len(".__init__")]
    return name


def _is_camel_method(value: str) -> bool:
    return (bool(value) and value[0].isupper() and value.isidentifier()
            and not value.isupper())


def lock_identity(expr: ast.AST, resolver: ImportResolver, modname: str,
                  cls: Optional[str], qual: str, module_locks: Set[str],
                  aliases: Dict[str, str]) -> Optional[str]:
    """Normalize a lock expression to a project-global identity:
    ``self._lock`` in class C -> ``mod:C._lock``; a module-level name ->
    ``mod:_lock``; a local alias resolves to its target; anything else
    keeps its expanded dotted path scoped to the module (a plain local
    gets function scope — distinct per function, by design)."""
    dotted = resolver.dotted(expr)
    if dotted is None:
        return None
    head = dotted.split(".", 1)[0]
    if head == "self":
        return f"{modname}:{cls or '<module>'}.{dotted[5:]}"
    if dotted in aliases:
        return aliases[dotted]
    if "." not in dotted:
        if dotted in module_locks:
            return f"{modname}:{dotted}"
        return f"{modname}:{qual}:{dotted}"
    return f"{modname}:{dotted}"


# ---------------------------------------------------------------------------
# Module summarization
# ---------------------------------------------------------------------------


class _FunctionSummarizer(ast.NodeVisitor):
    """Walks ONE function body (not descending into nested defs/lambdas),
    collecting calls, blocking calls, lock operations, and awaits."""

    def __init__(self, owner: "_ModuleSummarizer", qual: str,
                 cls: Optional[str], node):
        self.owner = owner
        self.qual = qual
        self.cls = cls
        self.node = node
        self.resolver = owner.resolver
        self.calls: List[dict] = []
        self.blocking: List[dict] = []
        self.acquires: List[List] = []       # [lockid, line] from `with`
        self.lock_edges: List[List] = []     # [held, acquired, line]
        self.acq_calls: List[List] = []      # [lockid, line] from .acquire()
        self.rel_calls: List[List] = []      # [lockid, line] from .release()
        self.awaits: List[int] = []
        self.held: List[str] = []            # lexical with-lock stack
        # v3 context/race material
        self.self_reads: List[List] = []     # [attr, line, held]
        self.self_writes: List[List] = []    # [attr, line, held, kind]
        self.global_reads: List[List] = []   # [name, line, held]
        self.global_writes: List[List] = []  # [name, line, held, kind]
        self.spawns: List[List] = []         # [kind, dotted target, line]
        self.forks: List[List] = []          # [line, held] for os.fork()
        self._skip_attrs: Set[int] = set()   # id(node): method receivers etc.
        self.global_decls: Set[str] = set()
        self.local_binds: Set[str] = {
            a.arg for a in (node.args.posonlyargs + node.args.args
                            + node.args.kwonlyargs)}
        for va in (node.args.vararg, node.args.kwarg):
            if va is not None:
                self.local_binds.add(va.arg)
        self._collect_scope(node)
        # lock_id (called while computing the aliases) consults self.aliases,
        # so it must exist — empty — before the alias pass runs
        self.aliases: Dict[str, str] = {}
        self.aliases = self._local_lock_aliases(node)
        self.var_literals = self._literal_assigns(node)

    def _collect_scope(self, fn):
        """Pre-pass: which plain names are bound locally vs declared
        ``global``, so a bare-name read can be attributed to module state."""
        for sub in _scoped_walk(fn):
            if isinstance(sub, ast.Global):
                self.global_decls.update(sub.names)
            elif isinstance(sub, ast.Nonlocal):
                self.local_binds.update(sub.names)
            else:
                targets = []
                if isinstance(sub, ast.Assign):
                    targets = sub.targets
                elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                    targets = [sub.target]
                elif isinstance(sub, (ast.For, ast.AsyncFor)):
                    targets = [sub.target]
                elif isinstance(sub, ast.withitem) and sub.optional_vars:
                    targets = [sub.optional_vars]
                elif isinstance(sub, ast.NamedExpr):
                    targets = [sub.target]
                elif isinstance(sub, ast.ExceptHandler) and sub.name:
                    self.local_binds.add(sub.name)
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            self.local_binds.add(n.id)
        self.local_binds -= self.global_decls

    def _local_lock_aliases(self, fn) -> Dict[str, str]:
        """``lk = self._lock`` (assigned exactly once) lets ``with lk:`` and
        ``lk.acquire()`` resolve to the real lock identity."""
        assigns: Dict[str, List[Optional[str]]] = {}
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name):
                name = sub.targets[0].id
                lock = None
                if isinstance(sub.value, (ast.Name, ast.Attribute)) \
                        and _is_lock_like(sub.value, self.resolver):
                    lock = self.lock_id(sub.value)
                assigns.setdefault(name, []).append(lock)
        return {name: vals[0] for name, vals in assigns.items()
                if len(vals) == 1 and vals[0] is not None}

    def _literal_assigns(self, fn) -> Dict[str, List[str]]:
        """``method = "X"`` / ``method = "A" if c else "B"`` — so a
        ``client.call(method, ...)`` still counts as a wire-method mention
        for WIRE002's parity check."""
        out: Dict[str, List[str]] = {}

        def lits(expr) -> List[str]:
            if isinstance(expr, ast.Constant) and isinstance(expr.value, str) \
                    and _is_camel_method(expr.value):
                return [expr.value]
            if isinstance(expr, ast.IfExp):
                return lits(expr.body) + lits(expr.orelse)
            return []

        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name):
                found = lits(sub.value)
                if found:
                    out.setdefault(sub.targets[0].id, []).extend(found)
        return out

    # -- lock identities ----------------------------------------------------

    def lock_id(self, expr: ast.AST) -> Optional[str]:
        """Normalize a lock expression to a project-global identity."""
        return lock_identity(expr, self.resolver, self.owner.modname,
                             self.cls, self.qual, self.owner.module_locks,
                             self.aliases)

    # -- visitors -----------------------------------------------------------

    def visit_FunctionDef(self, node):  # nested def: separate function
        self.owner.add_function(node, parent_qual=self.qual, cls=self.cls)

    def visit_AsyncFunctionDef(self, node):
        self.owner.add_function(node, parent_qual=self.qual, cls=self.cls)

    def visit_Lambda(self, node):
        pass  # calls inside a lambda run at the lambda's call time, not here

    def visit_Await(self, node):
        self.awaits.append(node.lineno)
        self.generic_visit(node)

    def _is_lockish(self, expr: ast.AST) -> bool:
        """Lock-like by name, or a local alias of one (`lk = self._lock`)."""
        if isinstance(expr, ast.Name) and expr.id in self.aliases:
            return True
        return isinstance(expr, (ast.Name, ast.Attribute)) \
            and _is_lock_like(expr, self.resolver)

    def _visit_with(self, node):
        taken: List[str] = []
        for item in node.items:
            expr = item.context_expr
            if self._is_lockish(expr):
                lock = self.lock_id(expr)
                if lock is not None:
                    for held in self.held:
                        self.lock_edges.append([held, lock, node.lineno])
                    self.acquires.append([lock, node.lineno])
                    self.held.append(lock)
                    taken.append(lock)
        self.generic_visit(node)
        if taken:
            del self.held[-len(taken):]

    def visit_With(self, node):
        self._visit_with(node)

    def visit_AsyncWith(self, node):
        self._visit_with(node)

    # -- shared-state accesses (context/race material) ----------------------

    def _is_module_name(self, name: str) -> bool:
        return (name in self.owner.state_names
                and (name in self.global_decls
                     or name not in self.local_binds))

    def visit_Name(self, node: ast.Name):
        if isinstance(node.ctx, ast.Load) and self._is_module_name(node.id):
            self.global_reads.append([node.id, node.lineno, list(self.held)])
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute):
        if isinstance(node.ctx, ast.Load) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self" \
                and id(node) not in self._skip_attrs:
            self.self_reads.append([node.attr, node.lineno, list(self.held)])
        self.generic_visit(node)

    def _record_store(self, target: ast.AST, line: int, kind: str):
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._record_store(elt, line, kind)
        elif isinstance(target, ast.Starred):
            self._record_store(target.value, line, kind)
        elif isinstance(target, ast.Attribute):
            if isinstance(target.value, ast.Name) and target.value.id == "self":
                self.self_writes.append(
                    [target.attr, line, list(self.held), kind])
        elif isinstance(target, ast.Name):
            if self._is_module_name(target.id):
                self.global_writes.append(
                    [target.id, line, list(self.held), kind])
        elif isinstance(target, ast.Subscript):
            base = target.value
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self":
                self.self_writes.append(
                    [base.attr, line, list(self.held), "mut"])
                self._skip_attrs.add(id(base))
            elif isinstance(base, ast.Name) and self._is_module_name(base.id):
                self.global_writes.append(
                    [base.id, line, list(self.held), "mut"])

    def visit_Assign(self, node: ast.Assign):
        for t in node.targets:
            self._record_store(t, node.lineno, "assign")
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._record_store(node.target, node.lineno, "rmw")
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._record_store(node.target, node.lineno, "assign")
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete):
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                self._record_store(t, node.lineno, "mut")
        self.generic_visit(node)

    def _check_shared_mutation(self, node: ast.Call, attr: Optional[str]):
        """``self.X.append(...)`` / ``_buffer.append(...)`` are writes to the
        container, classified atomic (single bytecode) or compound."""
        f = node.func
        if not isinstance(f, ast.Attribute):
            return
        recv = f.value
        if isinstance(recv, ast.Name) and recv.id == "self":
            self._skip_attrs.add(id(f))  # `self.method()`: not a data read
            return
        if attr not in _MUTATING_METHODS:
            return
        kind = "atomic" if attr in _ATOMIC_METHODS else "mut"
        if isinstance(recv, ast.Attribute) \
                and isinstance(recv.value, ast.Name) \
                and recv.value.id == "self":
            self.self_writes.append(
                [recv.attr, node.lineno, list(self.held), kind])
            self._skip_attrs.add(id(recv))
        elif isinstance(recv, ast.Name) and self._is_module_name(recv.id):
            self.global_writes.append(
                [recv.id, node.lineno, list(self.held), kind])

    def _spawn_target_expr(self, expr: ast.AST) -> Optional[str]:
        """Dotted name of a callable handed to a spawn site; a coroutine
        factory call (``create_task(self._run())``) unwraps to its func."""
        if isinstance(expr, ast.Call):
            expr = expr.func
        if isinstance(expr, ast.Lambda):
            return None
        return self.resolver.dotted(expr)

    def _check_spawn(self, node: ast.Call, raw: Optional[str],
                     attr: Optional[str]):
        term = attr if attr is not None else (
            raw.rsplit(".", 1)[-1] if raw else "")
        target = None
        kind = None
        if term in ("Thread", "Process"):
            for kw in node.keywords:
                if kw.arg == "target":
                    kind, target = "thread", self._spawn_target_expr(kw.value)
        elif term in _THREAD_SPAWN_ARG:
            pos = _THREAD_SPAWN_ARG[term]
            if len(node.args) > pos:
                kind, target = "thread", self._spawn_target_expr(node.args[pos])
        elif term == "submit" and isinstance(node.func, ast.Attribute):
            recv = (self.resolver.dotted(node.func.value) or "").lower()
            if "executor" in recv or "pool" in recv:
                if node.args:
                    kind, target = "thread", self._spawn_target_expr(node.args[0])
        elif term in _LOOP_SPAWN_ARG:
            pos = _LOOP_SPAWN_ARG[term]
            if len(node.args) > pos:
                kind, target = "loop", self._spawn_target_expr(node.args[pos])
        if kind and target:
            self.spawns.append([kind, target, node.lineno])

    def visit_Call(self, node: ast.Call):
        raw = self.resolver.dotted(node.func)
        attr = node.func.attr if isinstance(node.func, ast.Attribute) else None
        # literal string args that look like RPC method names
        lits = [[i, a.value] for i, a in enumerate(node.args)
                if isinstance(a, ast.Constant) and isinstance(a.value, str)
                and _is_camel_method(a.value)]
        # first-arg variable that may hold a method-name literal
        var0 = node.args[0].id if (node.args
                                   and isinstance(node.args[0], ast.Name)) \
            else None
        entry = {"raw": raw, "attr": attr, "line": node.lineno,
                 "held": list(self.held)}
        if lits:
            entry["lit"] = lits
        if var0:
            entry["var0"] = var0
        self.calls.append(entry)
        self._check_blocking(node, raw, attr)
        self._check_lock_call(node, attr)
        self._check_shared_mutation(node, attr)
        self._check_spawn(node, raw, attr)
        if raw == "os.fork":
            self.forks.append([node.lineno, list(self.held)])
        self.generic_visit(node)

    def _check_blocking(self, node, raw, attr):
        if raw in _BLOCKING_CALLS:
            self.blocking.append({
                "what": raw, "line": node.lineno,
                "hint": _BLOCKING_CALLS[raw]})
        elif attr in _SOCKET_METHODS and isinstance(node.func, ast.Attribute):
            recv = self.resolver.dotted(node.func.value) or ""
            if "sock" in recv.lower():
                self.blocking.append({
                    "what": f"<socket>.{attr}", "line": node.lineno,
                    "hint": "use asyncio streams"})
        elif attr == "result" and isinstance(node.func, ast.Attribute):
            recv = (self.resolver.dotted(node.func.value) or "").lower()
            if any(h in recv for h in _FUTURE_HINTS):
                self.blocking.append({
                    "what": f"{recv}.result", "line": node.lineno,
                    "hint": "blocks until the future resolves; await it (or "
                            "wrap in run_in_executor)"})

    def _check_lock_call(self, node, attr):
        if attr not in ("acquire", "release") \
                or not isinstance(node.func, ast.Attribute):
            return
        recv = node.func.value
        if not self._is_lockish(recv):
            return
        lock = self.lock_id(recv)
        if lock is None:
            return
        if attr == "acquire":
            self.acq_calls.append([lock, node.lineno])
        else:
            self.rel_calls.append([lock, node.lineno])

    def summary(self) -> dict:
        node = self.node
        params = [a.arg for a in node.args.posonlyargs + node.args.args]
        return {
            "qual": self.qual,
            "cls": self.cls,
            "is_async": isinstance(node, ast.AsyncFunctionDef),
            "line": node.lineno,
            "params": params,
            "calls": self.calls,
            "blocking": self.blocking,
            "acquires": self.acquires,
            "lock_edges": self.lock_edges,
            "acq_calls": self.acq_calls,
            "rel_calls": self.rel_calls,
            "awaits": self.awaits,
            "var_literals": self.var_literals,
            "self_reads": self.self_reads,
            "self_writes": self.self_writes,
            "global_reads": self.global_reads,
            "global_writes": self.global_writes,
            "spawns": self.spawns,
            "forks": self.forks,
        }


class _ModuleSummarizer:
    def __init__(self, path: str, tree: ast.AST):
        self.path = path
        self.modname = _modname(path)
        self.resolver = ImportResolver(tree)
        self.functions: Dict[str, dict] = {}
        self.classes: Dict[str, dict] = {}
        self.module_locks: Set[str] = set()
        self.rlocks: Set[str] = set()        # lock ids constructed as RLock
        self.rpc_handlers: List[List] = []   # [name, line]
        self.rpc_dispatch: List[List] = []   # [name, line] (method == "X")
        self.wire_registry: List[dict] = []
        self.module_state: Dict[str, List] = {}  # name -> [line, kind]
        self._module_consts: Dict[str, int] = {}  # immutable inits, by line
        self.state_names: Set[str] = set()
        self._collect_module_names(tree)
        for node in tree.body:
            self._top_level(node)
        self._collect_dispatch_and_registry(tree)

    def _collect_module_names(self, tree):
        for node in tree.body:
            if isinstance(node, ast.Assign):
                targets, value = [], node.value
                for t in node.targets:
                    targets.extend(t.elts if isinstance(t, (ast.Tuple, ast.List))
                                   else [t])
            elif isinstance(node, ast.AnnAssign):
                targets, value = [node.target], node.value
            else:
                continue
            is_rlock = (isinstance(value, ast.Call)
                        and (self.resolver.dotted(value.func) or "")
                        .endswith("RLock"))
            for t in targets:
                if isinstance(t, ast.Name):
                    self.module_locks.add(t.id)
                    if is_rlock:
                        self.rlocks.add(f"{self.modname}:{t.id}")
                    self._classify_module_state(t.id, value, node.lineno)
        self.state_names = set(self.module_state) | set(self._module_consts)

    def _classify_module_state(self, name: str, value, line: int):
        """Module-level mutable state for FRK001/RCE001: lock primitives,
        mutable containers, contextvars — and (promoted later) plain
        constants rebound from function bodies via ``global``."""
        if name.startswith("__") or value is None:
            return
        kind = None
        if isinstance(value, ast.Call):
            terminal = (self.resolver.dotted(value.func) or "").rsplit(
                ".", 1)[-1]
            kind = _STATE_CONSTRUCTORS.get(terminal)
        elif isinstance(value, (ast.Dict, ast.List, ast.Set)):
            kind = "buffer"
        elif isinstance(value, ast.Constant):
            self._module_consts.setdefault(name, line)
            return
        if kind is not None:
            self.module_state.setdefault(name, [line, kind])

    def _top_level(self, node, cls: Optional[str] = None):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.add_function(node, parent_qual=None, cls=cls)
        elif isinstance(node, ast.ClassDef) and cls is None:
            bases = [self.resolver.dotted(b) for b in node.bases]
            fields: List[str] = []
            methods: List[str] = []
            for sub in node.body:
                if isinstance(sub, ast.AnnAssign) and isinstance(sub.target, ast.Name):
                    fields.append(sub.target.id)
                elif isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            fields.append(t.id)
                elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    methods.append(sub.name)
                    self._top_level(sub, cls=node.name)
                    # instance attributes (`self.x = ...` anywhere in a
                    # method) are fields too — WIRE002 checks encoded field
                    # names against them
                    for inner in ast.walk(sub):
                        if isinstance(inner, (ast.Assign, ast.AnnAssign)):
                            targets = inner.targets if isinstance(
                                inner, ast.Assign) else [inner.target]
                            for t in targets:
                                if isinstance(t, ast.Attribute) \
                                        and isinstance(t.value, ast.Name) \
                                        and t.value.id == "self":
                                    fields.append(t.attr)
            init = self.functions.get(f"{node.name}.__init__")
            init_params = init["params"][1:] if init else []
            self.classes[node.name] = {
                "bases": [b for b in bases if b],
                "fields": fields,
                "methods": methods,
                "init_params": init_params,
            }

    def add_function(self, node, parent_qual: Optional[str],
                     cls: Optional[str]):
        qual = node.name if parent_qual is None else f"{parent_qual}.{node.name}"
        if cls is not None and parent_qual is None:
            qual = f"{cls}.{node.name}"
        summarizer = _FunctionSummarizer(self, qual, cls, node)
        for stmt in node.body:
            summarizer.visit(stmt)
        self.functions[qual] = summarizer.summary()
        if node.name.startswith("_rpc_"):
            self.rpc_handlers.append([node.name[5:], node.lineno])
        # RLock detection: `self._x = threading.RLock()` / `_x = RLock()`,
        # annotated form (`self._x: RLock = RLock()`) included
        for sub in ast.walk(node):
            if not isinstance(sub, (ast.Assign, ast.AnnAssign)) \
                    or not isinstance(sub.value, ast.Call):
                continue
            dotted = self.resolver.dotted(sub.value.func) or ""
            if not dotted.endswith("RLock"):
                continue
            targets = sub.targets if isinstance(sub, ast.Assign) \
                else [sub.target]
            for t in targets:
                lock = summarizer.lock_id(t) if isinstance(
                    t, (ast.Name, ast.Attribute)) else None
                if lock:
                    self.rlocks.add(lock)

    def _collect_dispatch_and_registry(self, tree):
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                params = {a.arg for a in node.args.posonlyargs + node.args.args}
                if "method" in params:
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Compare) and len(sub.ops) == 1 \
                                and isinstance(sub.ops[0], ast.Eq):
                            sides = [sub.left] + sub.comparators
                            names = {s.id for s in sides
                                     if isinstance(s, ast.Name)}
                            lits = [s.value for s in sides
                                    if isinstance(s, ast.Constant)
                                    and isinstance(s.value, str)]
                            if "method" in names and lits \
                                    and _is_camel_method(lits[0]):
                                self.rpc_dispatch.append([lits[0], sub.lineno])
            elif isinstance(node, ast.Call):
                f = node.func
                term = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else "")
                if term == "register_struct" and node.args:
                    self.wire_registry.append(
                        self._registry_entry(node))

    def _registry_entry(self, call: ast.Call) -> dict:
        cls_raw = self.resolver.dotted(call.args[0])
        fields = None
        decode_fields = None
        for kw in call.keywords:
            if kw.arg == "fields" and isinstance(kw.value, (ast.Tuple, ast.List)):
                fields = [e.value for e in kw.value.elts
                          if isinstance(e, ast.Constant)
                          and isinstance(e.value, str)]
            elif kw.arg == "decode":
                if isinstance(kw.value, ast.Lambda) \
                        and len(kw.value.args.args) == 1:
                    pname = kw.value.args.args[0].arg
                    decode_fields = sorted({
                        sub.slice.value
                        for sub in ast.walk(kw.value.body)
                        if isinstance(sub, ast.Subscript)
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id == pname
                        and isinstance(sub.slice, ast.Constant)
                        and isinstance(sub.slice.value, str)})
        return {"cls": cls_raw, "line": call.lineno, "fields": fields,
                "decode_fields": decode_fields}

    def summary(self) -> dict:
        # promote constant-initialized module names that some function
        # rebinds via `global` — those are counters/flags, i.e. mutable
        # process state FRK001/RCE001 must see
        for func in self.functions.values():
            for name, _line, _held, _kind in func["global_writes"]:
                if name in self._module_consts and name not in self.module_state:
                    self.module_state[name] = [self._module_consts[name],
                                               "counter"]
        return {
            "path": self.path,
            "modname": self.modname,
            "functions": self.functions,
            "classes": self.classes,
            "rlocks": sorted(self.rlocks),
            "rpc_handlers": self.rpc_handlers,
            "rpc_dispatch": self.rpc_dispatch,
            "wire_registry": self.wire_registry,
            "module_state": {k: v for k, v in sorted(self.module_state.items())},
        }


def summarize_module(path: str, source: str,
                     tree: Optional[ast.AST] = None) -> dict:
    """Summarize one module for the project graph. Raises SyntaxError on
    unparseable source (callers treat that as 'no summary')."""
    if tree is None:
        tree = ast.parse(source)
    return _ModuleSummarizer(path, tree).summary()


# ---------------------------------------------------------------------------
# Project graph + content-hash cache
# ---------------------------------------------------------------------------


class ProjectGraph:
    """Summaries for every file under ``<root>/ray_tpu``, content-hash cached."""

    def __init__(self, root: Path, cache_path: Optional[Path] = None,
                 use_cache: bool = True):
        self.root = Path(root)
        self.cache_path = cache_path
        self.use_cache = use_cache
        self.summaries: Dict[str, dict] = {}
        self.shas: Dict[str, str] = {}
        self.by_modname: Dict[str, str] = {}
        self.stats = {"files": 0, "parsed": 0, "cache_hits": 0,
                      "build_seconds": 0.0}
        self._build()

    def _load_cache(self) -> Dict[str, dict]:
        if not self.use_cache or self.cache_path is None \
                or not self.cache_path.is_file():
            return {}
        try:
            doc = json.loads(self.cache_path.read_text(encoding="utf-8"))
        except (OSError, ValueError):
            return {}
        if doc.get("version") != GRAPH_SCHEMA_VERSION:
            return {}
        files = doc.get("files")
        return files if isinstance(files, dict) else {}

    def _save_cache(self):
        if not self.use_cache or self.cache_path is None:
            return
        doc = {
            "comment": "raylint graph cache: per-file call-graph summaries "
                       "keyed by content hash. Safe to delete; never commit.",
            "version": GRAPH_SCHEMA_VERSION,
            "files": {p: {"sha": self.shas[p], "summary": s}
                      for p, s in self.summaries.items()},
        }
        tmp = self.cache_path.with_suffix(".tmp")
        try:
            tmp.write_text(json.dumps(doc, sort_keys=True), encoding="utf-8")
            os.replace(tmp, self.cache_path)
        except OSError:
            pass  # cache is an optimization; never fail the lint over it

    def _build(self):
        started = time.perf_counter()
        cached = self._load_cache()
        dirty = False
        tree_root = self.root / "ray_tpu"
        for file in iter_py_files([tree_root] if tree_root.is_dir() else []):
            try:
                rel = file.resolve().relative_to(self.root.resolve()).as_posix()
            except ValueError:
                rel = file.as_posix()
            try:
                source = file.read_text(encoding="utf-8")
            except (OSError, UnicodeDecodeError):
                continue
            sha = _sha(source)
            self.stats["files"] += 1
            entry = cached.get(rel)
            if entry and entry.get("sha") == sha:
                self.summaries[rel] = entry["summary"]
                self.shas[rel] = sha
                self.stats["cache_hits"] += 1
                continue
            try:
                self.summaries[rel] = summarize_module(rel, source)
            except SyntaxError:
                continue  # E999 is reported by the core runner
            self.shas[rel] = sha
            self.stats["parsed"] += 1
            dirty = True
        if dirty or (cached and set(cached) != set(self.summaries)):
            self._save_cache()
        for rel, summary in self.summaries.items():
            self.by_modname[summary["modname"]] = rel
        self.stats["build_seconds"] = time.perf_counter() - started


def project_graph(project) -> ProjectGraph:
    """The (cached-per-run) ProjectGraph for a raylint ``Project``. The
    on-disk cache lives under the PROJECT's tools/raylint/ (so a test
    project rooted in tmp_path never clobbers the repo's cache); roots
    without that directory run cache-less."""
    g = project.cache.get("graph")
    if g is None:
        cache_dir = Path(project.root) / "tools" / "raylint"
        cache_path = (cache_dir / DEFAULT_CACHE_NAME) if cache_dir.is_dir() \
            else None
        use_cache = not os.environ.get("RAYLINT_NO_GRAPH_CACHE")
        g = ProjectGraph(project.root, cache_path=cache_path,
                         use_cache=use_cache)
        project.cache["graph"] = g
    return g


# ---------------------------------------------------------------------------
# GraphView: resolution + interprocedural queries
# ---------------------------------------------------------------------------

FuncKey = Tuple[str, str]  # (path, qualname)


class GraphView:
    """Project graph plus an optional overlay module (the module currently
    being linted, summarized from its in-memory AST)."""

    def __init__(self, graph: ProjectGraph, overlay: Optional[dict] = None):
        self.graph = graph
        self.overlay = overlay
        self._modules: Dict[str, dict] = dict(graph.summaries)
        self._by_modname = dict(graph.by_modname)
        if overlay is not None:
            self._modules[overlay["path"]] = overlay
            self._by_modname[overlay["modname"]] = overlay["path"]
        self._blocking_memo: Dict[FuncKey, Optional[tuple]] = {}
        self._acq_memo: Dict[FuncKey, Dict[str, tuple]] = {}

    # -- plumbing -----------------------------------------------------------

    def modules(self) -> Iterable[Tuple[str, dict]]:
        return self._modules.items()

    def module(self, path: str) -> Optional[dict]:
        return self._modules.get(path)

    def func(self, key: FuncKey) -> Optional[dict]:
        mod = self._modules.get(key[0])
        if mod is None:
            return None
        return mod["functions"].get(key[1])

    def is_pristine(self, path: str, source: str) -> bool:
        """True when the module content matches the on-disk graph summary,
        so global analyses memoized without an overlay stay valid."""
        sha = self.graph.shas.get(path)
        return sha is not None and sha == _sha(source)

    # -- name resolution ----------------------------------------------------

    def _method_on_class(self, mod: dict, cls_name: str, meth: str,
                         _depth: int = 0) -> Optional[FuncKey]:
        cls = mod["classes"].get(cls_name)
        if cls is None:
            return None
        if meth in cls["methods"]:
            return (mod["path"], f"{cls_name}.{meth}")
        if _depth >= 4:
            return None
        for base in cls["bases"]:
            if "." not in base:
                found = self._method_on_class(mod, base, meth, _depth + 1)
                if found:
                    return found
            else:
                bmod_name, _, bcls = base.rpartition(".")
                bpath = self._by_modname.get(bmod_name)
                if bpath:
                    found = self._method_on_class(
                        self._modules[bpath], bcls, meth, _depth + 1)
                    if found:
                        return found
        return None

    def _dotted_target(self, dotted: str) -> Optional[FuncKey]:
        """``pkg.mod.fn`` or ``pkg.mod.Class.method`` -> FuncKey."""
        parts = dotted.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod_name = ".".join(parts[:cut])
            path = self._by_modname.get(mod_name)
            if path is None:
                continue
            mod = self._modules[path]
            rest = parts[cut:]
            if len(rest) == 1:
                if rest[0] in mod["functions"]:
                    return (path, rest[0])
                if rest[0] in mod["classes"]:  # constructor
                    init = f"{rest[0]}.__init__"
                    if init in mod["functions"]:
                        return (path, init)
                return None
            if len(rest) == 2:
                return self._method_on_class(mod, rest[0], rest[1])
            return None
        return None

    def resolve_call(self, path: str, func: dict, call: dict) -> Optional[FuncKey]:
        """Resolve one recorded call site to a project function, or None."""
        raw = call.get("raw")
        mod = self._modules.get(path)
        if mod is None or raw is None:
            return None
        if raw.startswith("self."):
            rest = raw[5:]
            if "." in rest or func.get("cls") is None:
                return None  # attribute hop / not a method
            return self._method_on_class(mod, func["cls"], rest)
        if raw.startswith("cls."):
            rest = raw[4:]
            if "." in rest or func.get("cls") is None:
                return None
            return self._method_on_class(mod, func["cls"], rest)
        if "." not in raw:
            nested = f"{func['qual']}.{raw}"
            if nested in mod["functions"]:
                return (path, nested)
            if raw in mod["functions"]:
                return (path, raw)
            if raw in mod["classes"]:
                init = f"{raw}.__init__"
                if init in mod["functions"]:
                    return (path, init)
            return None
        # fully-dotted (alias-expanded) name; also ClassName.method in-module
        head, _, meth = raw.partition(".")
        if head in mod["classes"] and "." not in meth:
            found = self._method_on_class(mod, head, meth)
            if found:
                return found
        return self._dotted_target(raw)

    # -- interprocedural queries --------------------------------------------

    def blocking_chain(self, key: FuncKey) -> Optional[tuple]:
        """If the SYNC function at ``key`` (transitively) makes a blocking
        call, return ``(chain, what, hint)`` where chain is a list of
        ``(path, qual, line)`` hops ending at the blocking call site."""
        return self._blocking_chain(key, set(), 0)[0]

    def _blocking_chain(self, key: FuncKey, stack: Set[FuncKey],
                        depth: int) -> Tuple[Optional[tuple], bool]:
        """(result, tainted). A result computed under a pruned traversal —
        a recursion-cycle hit or the depth cap — is ``tainted`` and must
        NOT be memoized as a definitive None: a different entry point may
        reach the same node with a live path the pruned one couldn't see.
        A FOUND chain is always valid and always cacheable."""
        if key in self._blocking_memo:
            return self._blocking_memo[key], False
        func = self.func(key)
        if func is None or func["is_async"]:
            return None, False
        if key in stack or depth > 12:
            return None, True
        stack.add(key)
        tainted = False
        result = None
        if func["blocking"]:
            b = func["blocking"][0]
            result = ([(key[0], key[1], b["line"])], b["what"], b["hint"])
        else:
            for call in func["calls"]:
                target = self.resolve_call(key[0], func, call)
                if target is None or target == key:
                    continue
                tf = self.func(target)
                if tf is None or tf["is_async"]:
                    continue
                sub, sub_tainted = self._blocking_chain(target, stack,
                                                        depth + 1)
                tainted |= sub_tainted
                if sub is not None:
                    chain = [(key[0], key[1], call["line"])] + sub[0]
                    result = (chain, sub[1], sub[2])
                    break
        stack.discard(key)
        if result is not None or not tainted:
            self._blocking_memo[key] = result
        return result, tainted and result is None

    def transitive_acquires(self, key: FuncKey) -> Dict[str, tuple]:
        """All ``with``-style lock acquisitions reachable from ``key``
        (itself included), as ``{lock_id: (path, line)}``."""
        return self._transitive_acquires(key, set(), 0)[0]

    def _transitive_acquires(self, key: FuncKey, stack: Set[FuncKey],
                             depth: int) -> Tuple[Dict[str, tuple], bool]:
        """(acquisitions, tainted). Same memo discipline as
        ``_blocking_chain``: a set computed under a pruned traversal is a
        valid under-approximation for the CALLER's use but must not be
        cached as this node's definitive answer."""
        if key in self._acq_memo:
            return self._acq_memo[key], False
        func = self.func(key)
        if func is None:
            return {}, False
        if key in stack or depth > 6:
            return {}, True
        stack.add(key)
        tainted = False
        out: Dict[str, tuple] = {}
        for lock, line in func["acquires"]:
            out.setdefault(lock, (key[0], line))
        for call in func["calls"]:
            target = self.resolve_call(key[0], func, call)
            if target is None or target == key:
                continue
            sub, sub_tainted = self._transitive_acquires(target, stack,
                                                         depth + 1)
            tainted |= sub_tainted
            for lock, site in sub.items():
                out.setdefault(lock, site)
        stack.discard(key)
        if not tainted:
            self._acq_memo[key] = out
        return out, tainted

    def net_lock_effects(self, key: FuncKey) -> Tuple[Set[str], Set[str]]:
        """Flow-insensitive ``.acquire()``/``.release()`` balance for one
        function: (locks it acquires and does not release, locks it
        releases). Used by AWT002's one-level call inlining."""
        func = self.func(key)
        if func is None:
            return set(), set()
        acq = [l for l, _ in func["acq_calls"]]
        rel = {l for l, _ in func["rel_calls"]}
        return {l for l in acq if l not in rel}, rel

    def lock_graph(self, scope_paths: Optional[Sequence[str]] = None
                   ) -> Dict[Tuple[str, str], tuple]:
        """The global lock-acquisition-order graph: edge (A, B) when B is
        acquired while A is held — via ``with`` nesting in one function or
        across resolved call edges. Value is the anchoring (path, line)."""
        edges: Dict[Tuple[str, str], tuple] = {}
        for path, mod in self.modules():
            if scope_paths is not None and not any(
                    path.startswith(p) for p in scope_paths):
                continue
            for func in mod["functions"].values():
                for a, b, line in func["lock_edges"]:
                    edges.setdefault((a, b), (path, line))
                for call in func["calls"]:
                    if not call["held"]:
                        continue
                    target = self.resolve_call(path, func, call)
                    if target is None:
                        continue
                    for lock, _site in self.transitive_acquires(target).items():
                        for held in call["held"]:
                            edges.setdefault((held, lock),
                                             (path, call["line"]))
        return edges

    def rlock_ids(self) -> Set[str]:
        out: Set[str] = set()
        for _, mod in self.modules():
            out.update(mod.get("rlocks", ()))
        return out

    # -- RPC parity universe -------------------------------------------------

    def rpc_handlers(self) -> Dict[str, List[tuple]]:
        out: Dict[str, List[tuple]] = {}
        for path, mod in self.modules():
            for name, line in mod["rpc_handlers"]:
                out.setdefault(name, []).append((path, line))
            for name, line in mod["rpc_dispatch"]:
                out.setdefault(name, []).append((path, line))
        return out

    def rpc_calls(self) -> Dict[str, List[tuple]]:
        """Wire-method mentions at call sites: literal first args to
        call/notify/wrappers, literals reaching a ``method`` variable used as
        first arg, and literals passed to a resolved callee's ``method``
        parameter."""
        out: Dict[str, List[tuple]] = {}

        def note(name: str, path: str, line: int):
            out.setdefault(name, []).append((path, line))

        for path, mod in self.modules():
            for func in mod["functions"].values():
                # literal strings (possibly via if/else) assigned to locals
                var_literals = self._var_literals(path, func)
                for call in func["calls"]:
                    raw = call.get("raw") or ""
                    attr = call.get("attr")
                    term = attr if attr is not None else raw.rsplit(".", 1)[-1]
                    direct = (term in _RPC_CALL_TERMINALS
                              or term.endswith("_call"))
                    lits = call.get("lit", ())
                    if direct:
                        for pos, value in lits:
                            if pos == 0:
                                note(value, path, call["line"])
                        var0 = call.get("var0")
                        if var0 and var0 in var_literals:
                            for value in var_literals[var0]:
                                note(value, path, call["line"])
                    elif lits:
                        # resolved callee with a `method` parameter: the
                        # literal at that position is a wire-method mention
                        target = self.resolve_call(path, func, call)
                        tf = self.func(target) if target else None
                        if tf and "method" in tf["params"]:
                            idx = tf["params"].index("method")
                            if raw.startswith(("self.", "cls.")) \
                                    and tf["params"][:1] == ["self"]:
                                idx -= 1
                            for pos, value in lits:
                                if pos == idx:
                                    note(value, path, call["line"])
        return out

    @staticmethod
    def _var_literals(path: str, func: dict) -> Dict[str, List[str]]:
        return func.get("var_literals", {})

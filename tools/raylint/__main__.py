import sys

from tools.raylint.cli import main

sys.exit(main())

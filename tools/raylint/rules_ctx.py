"""raylint context-sensitive rule set: races, fork safety, donation.

Built on the context layer (tools/raylint/context.py), which classifies
every function by the execution contexts that can reach it (event loop,
background thread, fork child, caller thread) and computes the locks held
on every path into a function:

* RCE001 — shared-state race: a ``self.X`` field or module global written
  from two *provably disjoint* execution contexts with no common lock.
  Disjointness is the false-positive gate: an over-approximated context
  set that overlaps the other site's ("this helper runs on the loop AND
  the caller thread") cannot prove a race, so it stays silent. One
  exception: a SINGLE unlocked write site whose function is reachable
  from a background thread and another context races with itself — the
  same code object runs concurrently in both (the classic unlocked
  lazy-init ``if _x is None: _x = ...``), so multi-context there is the
  race, not an over-approximation. Lock
  credit is the lexical ``with``-stack at the write site union the locks
  held on every call path into the function (``always_held``), so writes
  inside ``*_locked`` helpers are attributed correctly. ``__init__``
  writes are construction-time (happens-before publication) and exempt;
  single-bytecode container ops (``append``/``popleft``) are exempt here
  and judged by RCE002.
* RCE002 — advisory: a field read from event-loop context and written
  from thread context, neither side locked, without the sanctioned
  GIL-atomic deque idiom. Weaker than RCE001 (reads tear less loudly
  than writes) but exactly the stale-read shape that breaks bitwise
  parity contracts nondeterministically.
* FRK001 — fork-safety gate, two parts. (a) A module whose code runs in
  fork-child context and whose module-level mutable state (locks,
  buffers, counters, contextvars) is touched by that code must define a
  fork-reachable ``*after_fork*`` reset hook — otherwise state inherited
  from the zygote image (stale buffers, parent pids, half-filled caches)
  leaks into every worker. (b) Holding a lock across ``os.fork()`` — or
  calling into a transitively-forking function while holding one — is an
  error: the child inherits the locked mutex with no owner thread.
* DON001 — use-after-donate: inside the jit planes, a variable passed at
  a ``donate_argnums`` position of a jitted call has its device buffer
  invalidated by XLA; reading it afterwards on any CFG path returns
  garbage or raises. ``donate_argnums`` values are constant-folded
  through tuples, conditionals (``(0, 1) if donate else ()``) and local
  aliases, so the may-donate set is exact for the repo's idioms.

Per-module reporting, same as rules_interp: whole-program facts are
memoized on the shared graph view; each module emits only findings that
anchor in it, so suppressions and baselines stay file-local.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from tools.raylint import flow
from tools.raylint.context import ContextIndex, context_index
from tools.raylint.core import Finding, Module, Rule, register_rule
from tools.raylint.graph import GraphView
from tools.raylint.rules import _TRACING_TRANSFORMS
from tools.raylint.rules_interp import _interp_state, _lock_display

# paths whose shared state participates in the race rules
_RCE_SCOPE = ("ray_tpu/_private/", "ray_tpu/collective/", "ray_tpu/ckpt/",
              "ray_tpu/weights/", "ray_tpu/serve/")

# the jit planes DON001 watches
_DON_SCOPE = ("ray_tpu/parallel/", "ray_tpu/train/", "ray_tpu/llm/")

# field names that ARE synchronization primitives: assigning a lock is
# setup, not shared data the lock rules should race-check
_LOCKISH_SUFFIXES = ("_lock", "_rlock", "_mutex", "_cv", "_cond",
                     "_condition", "_event", "_sem", "_semaphore")


def _is_lockish_name(name: str) -> bool:
    return name in ("lock", "mutex", "cv") or name.endswith(_LOCKISH_SUFFIXES)


def _ctx_state(module: Module
               ) -> Tuple[Optional[GraphView], Optional[dict],
                          Optional[ContextIndex]]:
    view, summary = _interp_state(module)
    if view is None or summary is None:
        return None, None, None
    return view, summary, context_index(view)


class _Site:
    __slots__ = ("qual", "line", "locks", "ctxs", "kind")

    def __init__(self, qual: str, line: int, locks: FrozenSet[str],
                 ctxs: FrozenSet[str], kind: str):
        self.qual = qual
        self.line = line
        self.locks = locks
        self.ctxs = ctxs
        self.kind = kind

    def where(self) -> str:
        ctxs = "/".join(sorted(self.ctxs)) or "?"
        locks = (", holding " + ", ".join(
            sorted(_lock_display(l) for l in self.locks))
            if self.locks else ", no lock")
        return f"`{self.qual}`:{self.line} [{ctxs}{locks}]"


def _field_sites(view: GraphView, idx: ContextIndex, path: str
                 ) -> Dict[Tuple[Optional[str], str], Dict[str, List[_Site]]]:
    """Per shared field of one module: read/write sites with their context
    sets and effective locks. Key: (class or None-for-module-global, name).
    ``fork`` is excluded from the context sets — it is process-scoped, not
    a thread of execution racing within one process."""
    mod = view.module(path)
    out: Dict[Tuple[Optional[str], str], Dict[str, List[_Site]]] = {}

    def bucket(cls: Optional[str], name: str) -> Dict[str, List[_Site]]:
        return out.setdefault((cls, name), {"reads": [], "writes": []})

    for qual, func in mod["functions"].items():
        key = (path, qual)
        if qual.split(".")[-1] == "__init__":
            continue  # construction happens-before publication
        ctxs = frozenset(idx.contexts(key)) - {"fork"}
        if not ctxs:
            continue  # unreachable/unresolved: cannot attribute a context
        base = idx.always_held(key)
        cls = func.get("cls")
        for attr, line, held, kind in func.get("self_writes", ()):
            if cls is None or _is_lockish_name(attr):
                continue
            bucket(cls, attr)["writes"].append(
                _Site(qual, line, frozenset(held) | base, ctxs, kind))
        for attr, line, held in func.get("self_reads", ()):
            if cls is None or _is_lockish_name(attr):
                continue
            bucket(cls, attr)["reads"].append(
                _Site(qual, line, frozenset(held) | base, ctxs, "read"))
        for name, line, held, kind in func.get("global_writes", ()):
            if _is_lockish_name(name):
                continue
            bucket(None, name)["writes"].append(
                _Site(qual, line, frozenset(held) | base, ctxs, kind))
        for name, line, held in func.get("global_reads", ()):
            if _is_lockish_name(name):
                continue
            bucket(None, name)["reads"].append(
                _Site(qual, line, frozenset(held) | base, ctxs, "read"))
    return out


def _racing_pair(writes: List[_Site]
                 ) -> Optional[Tuple[_Site, _Site]]:
    """First (deterministic) pair of write sites with provably disjoint
    context sets and no common lock, or None. A single unlocked write
    site races with ITSELF when its function is reachable from a
    background thread AND another context (the same code object runs
    concurrently in both) — returned as (site, site). The self-pair
    demands a thread context because loop and main can be the same OS
    thread during startup in some planes; two *distinct* sites with
    disjoint sets keep the wider loop-vs-caller lattice."""
    sites = sorted((w for w in writes if w.kind != "atomic"),
                   key=lambda s: (s.line, s.qual))
    for i, a in enumerate(sites):
        for b in sites[i + 1:]:
            if a.ctxs.isdisjoint(b.ctxs) and not (a.locks & b.locks):
                return a, b
    for a in sites:
        if "thread" in a.ctxs and len(a.ctxs) >= 2 and not a.locks:
            return a, a
    return None


# ---------------------------------------------------------------------------
# RCE001 / RCE002 — shared-state races across execution contexts
# ---------------------------------------------------------------------------


@register_rule
class SharedStateRace(Rule):
    name = "RCE001"
    summary = ("shared field written from two provably disjoint execution "
               "contexts (loop/thread/caller) with no common lock: a data "
               "race the tests can't reproduce deterministically")

    def check(self, module: Module) -> Iterator[Finding]:
        view, summary, idx = _ctx_state(module)
        if view is None or not module.path.startswith(_RCE_SCOPE):
            return iter(())
        findings: List[Finding] = []
        for (cls, name), sites in sorted(_field_sites(
                view, idx, module.path).items(), key=lambda kv: str(kv[0])):
            pair = _racing_pair(sites["writes"])
            if pair is None:
                continue
            a, b = pair
            display = f"{cls}.{name}" if cls else name
            anchor = max(a, b, key=lambda s: s.line)
            if a is b:
                message = (f"`{display}` is written at {a.where()}, a "
                           f"single site whose function runs concurrently "
                           f"in multiple execution contexts, with no lock: "
                           f"two racing calls interleave the read-check-"
                           f"write — guard the write with a lock")
            else:
                message = (f"`{display}` is written from disjoint execution "
                           f"contexts with no common lock: {a.where()} vs "
                           f"{b.where()} — guard both writes with one lock, "
                           f"or confine mutation to a single context")
            findings.append(Finding(
                rule=self.name, path=module.path, line=anchor.line, col=0,
                message=message,
                snippet=module.line(anchor.line).strip()))
        return iter(findings)


@register_rule
class LoopThreadStaleRead(Rule):
    name = "RCE002"
    summary = ("advisory: field read on the event loop and written from a "
               "background thread, neither side locked (deque append/popleft "
               "single-bytecode idiom exempt): stale reads break parity "
               "contracts nondeterministically")

    def check(self, module: Module) -> Iterator[Finding]:
        view, summary, idx = _ctx_state(module)
        if view is None or not module.path.startswith(_RCE_SCOPE):
            return iter(())
        findings: List[Finding] = []
        for (cls, name), sites in sorted(_field_sites(
                view, idx, module.path).items(), key=lambda kv: str(kv[0])):
            if _racing_pair(sites["writes"]) is not None:
                continue  # RCE001 already owns this field
            loop_reads = [r for r in sites["reads"]
                          if "loop" in r.ctxs and not r.locks]
            thread_writes = [w for w in sites["writes"]
                             if "thread" in w.ctxs and not w.locks
                             and w.kind != "atomic"]
            hit = None
            for r in sorted(loop_reads, key=lambda s: (s.line, s.qual)):
                for w in sorted(thread_writes, key=lambda s: (s.line, s.qual)):
                    if r.ctxs.isdisjoint(w.ctxs):
                        hit = (r, w)
                        break
                if hit:
                    break
            if hit is None:
                continue
            r, w = hit
            display = f"{cls}.{name}" if cls else name
            findings.append(Finding(
                rule=self.name, path=module.path, line=w.line, col=0,
                message=(f"`{display}` is read on the event loop at "
                         f"{r.where()} but written from thread context here "
                         f"({w.where()}) with no lock on either side: the "
                         f"loop can observe a stale or torn value — lock "
                         f"both sides, or hand off through a deque/queue"),
                snippet=module.line(w.line).strip()))
        return iter(findings)


# ---------------------------------------------------------------------------
# FRK001 — fork-safety gate
# ---------------------------------------------------------------------------


@register_rule
class ForkSafetyGate(Rule):
    name = "FRK001"
    summary = ("fork-unsafe state: module-level mutable state used from "
               "fork-child context without a reset-after-fork hook, or a "
               "lock held across os.fork() — the zygote image leaks parent "
               "state (or an ownerless locked mutex) into every worker")

    def check(self, module: Module) -> Iterator[Finding]:
        view, summary, idx = _ctx_state(module)
        if view is None:
            return iter(())
        findings: List[Finding] = []
        findings.extend(self._unreset_state(module, summary, idx))
        findings.extend(self._locked_forks(module, view, summary, idx))
        return iter(findings)

    def _unreset_state(self, module: Module, summary: dict,
                       idx: ContextIndex) -> List[Finding]:
        state = summary.get("module_state") or {}
        if not state:
            return []
        fork_funcs = {
            qual: func for qual, func in summary["functions"].items()
            if "fork" in idx.contexts((module.path, qual))}
        if not fork_funcs:
            return []
        if any("after_fork" in qual.lower() for qual in fork_funcs):
            return []  # a fork-reachable reset hook covers the module
        modname = summary["modname"]
        touched: Dict[str, str] = {}  # state name -> example fork-ctx qual
        for qual in sorted(fork_funcs):
            func = fork_funcs[qual]
            for name, _line, _held in func.get("global_reads", ()):
                touched.setdefault(name, qual)
            for name, _line, _held, _kind in func.get("global_writes", ()):
                touched.setdefault(name, qual)
            for lock, _line in (func.get("acquires", [])
                                + func.get("acq_calls", [])):
                prefix, _, rest = lock.partition(":")
                if prefix == modname and "." not in rest and ":" not in rest:
                    touched.setdefault(rest, qual)
        out = []
        for name, (line, kind) in sorted(state.items()):
            if name not in touched:
                continue
            chain = idx.chain((module.path, touched[name]), "fork")
            out.append(Finding(
                rule=self.name, path=module.path, line=line, col=0,
                message=(f"module-level {kind} `{name}` is used from "
                         f"fork-child context ({chain}) but this module has "
                         f"no fork-reachable reset hook: state inherited "
                         f"from the zygote image leaks into every worker — "
                         f"add a reset_after_fork() wired into "
                         f"worker_main.reset_observability_after_fork, or "
                         f"suppress with the reason it is fork-safe"),
                snippet=module.line(line).strip()))
        return out

    def _locked_forks(self, module: Module, view: GraphView, summary: dict,
                      idx: ContextIndex) -> List[Finding]:
        out = []
        for qual, func in sorted(summary["functions"].items()):
            for line, held in func.get("forks", ()):
                if not held:
                    continue
                locks = ", ".join(sorted(_lock_display(l) for l in held))
                out.append(Finding(
                    rule=self.name, path=module.path, line=line, col=0,
                    message=(f"os.fork() while holding lock(s) {locks}: the "
                             f"child inherits a locked mutex with no owner "
                             f"thread and deadlocks on first acquire — "
                             f"release before forking"),
                    snippet=module.line(line).strip()))
            for call in func["calls"]:
                if not call["held"]:
                    continue
                target = view.resolve_call(module.path, func, call)
                if target is None or target not in idx.forking:
                    continue
                locks = ", ".join(sorted(_lock_display(l)
                                         for l in call["held"]))
                out.append(Finding(
                    rule=self.name, path=module.path, line=call["line"],
                    col=0,
                    message=(f"call into fork path `{target[1]}` while "
                             f"holding lock(s) {locks}: the forked child "
                             f"inherits the locked mutex — release before "
                             f"reaching os.fork()"),
                    snippet=module.line(call["line"]).strip()))
        return out


# ---------------------------------------------------------------------------
# DON001 — use-after-donate in the jit planes
# ---------------------------------------------------------------------------


def _terminal(dotted: str) -> str:
    return dotted.rsplit(".", 1)[-1]


def _is_jit_call(call: ast.Call, resolver) -> bool:
    dotted = resolver.dotted(call.func) or ""
    return (dotted in _TRACING_TRANSFORMS
            or _terminal(dotted) in ("jit", "pjit"))


def _fold_argnums(expr: ast.AST, env: Dict[str, List[ast.AST]],
                  depth: int = 0) -> Optional[Set[int]]:
    """Constant-fold a donate_argnums expression to a may-donate position
    set. IfExp folds to the union of both branches; a local alias follows
    its (single-scope) assignments. None = not statically foldable."""
    if depth > 4:
        return None
    if isinstance(expr, ast.Constant):
        if expr.value is None:
            return set()
        if isinstance(expr.value, bool):
            return None
        if isinstance(expr.value, int):
            return {expr.value}
        return None
    if isinstance(expr, (ast.Tuple, ast.List)):
        out: Set[int] = set()
        for elt in expr.elts:
            sub = _fold_argnums(elt, env, depth + 1)
            if sub is None:
                return None
            out |= sub
        return out
    if isinstance(expr, ast.IfExp):
        a = _fold_argnums(expr.body, env, depth + 1)
        b = _fold_argnums(expr.orelse, env, depth + 1)
        if a is None and b is None:
            return None
        return (a or set()) | (b or set())
    if isinstance(expr, ast.Name):
        values = env.get(expr.id)
        if not values:
            return None
        out = set()
        for value in values:
            sub = _fold_argnums(value, env, depth + 1)
            if sub is None:
                return None
            out |= sub
        return out
    return None


def _scope_env(body: List[ast.stmt]) -> Dict[str, List[ast.AST]]:
    """name -> assigned value expressions within one scope (not crossing
    nested defs), for folding ``donate_args = (0, 1) if donate else ()``."""
    env: Dict[str, List[ast.AST]] = {}
    stack = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            env.setdefault(node.targets[0].id, []).append(node.value)
        stack.extend(ast.iter_child_nodes(node))
    return env


def _donate_positions_of(call: ast.Call, resolver,
                         env: Dict[str, List[ast.AST]],
                         params: Optional[List[str]] = None
                         ) -> Optional[Set[int]]:
    """May-donate positions declared by one jit(...) call, or None."""
    if not _is_jit_call(call, resolver):
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return _fold_argnums(kw.value, env)
        if kw.arg == "donate_argnames" and params is not None:
            names: Set[str] = set()
            value = kw.value
            elts = value.elts if isinstance(value, (ast.Tuple, ast.List)) \
                else [value]
            for elt in elts:
                if isinstance(elt, ast.Constant) \
                        and isinstance(elt.value, str):
                    names.add(elt.value)
            return {params.index(n) for n in names if n in params}
    return None


class _DonateBindings:
    """Which callables in a module donate, and at which positions:
    ``self._step = jax.jit(fn, donate_argnums=...)`` binds ("self", attr);
    ``g = jax.jit(...)`` binds ("name", g); a def decorated with
    ``@jax.jit(...)`` / ``@partial(jax.jit, ...)`` binds ("name", def)."""

    def __init__(self, module: Module):
        self.self_attrs: Dict[str, Set[int]] = {}
        self.names: Dict[str, Set[int]] = {}
        resolver = module.resolver
        for scope in self._scopes(module.tree):
            env = _scope_env(scope)
            for node in scope:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign) \
                            and len(sub.targets) == 1 \
                            and isinstance(sub.value, ast.Call):
                        positions = _donate_positions_of(
                            sub.value, resolver, env)
                        if not positions:
                            continue
                        t = sub.targets[0]
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == "self":
                            self.self_attrs.setdefault(
                                t.attr, set()).update(positions)
                        elif isinstance(t, ast.Name):
                            self.names.setdefault(
                                t.id, set()).update(positions)
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            params = [a.arg for a in node.args.posonlyargs + node.args.args]
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                target = dec
                dotted = resolver.dotted(dec.func) or ""
                if _terminal(dotted) == "partial" and dec.args:
                    inner = ast.Call(func=dec.args[0], args=[],
                                     keywords=dec.keywords)
                    ast.copy_location(inner, dec)
                    target = inner
                positions = _donate_positions_of(target, resolver, {},
                                                 params=params)
                if positions:
                    self.names.setdefault(node.name, set()).update(positions)

    @staticmethod
    def _scopes(tree: ast.AST):
        yield tree.body
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node.body

    def __bool__(self):
        return bool(self.self_attrs or self.names)

    def positions_for(self, call: ast.Call) -> Optional[Set[int]]:
        f = call.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == "self":
            return self.self_attrs.get(f.attr)
        if isinstance(f, ast.Name):
            return self.names.get(f.id)
        return None


@register_rule
class UseAfterDonate(Rule):
    name = "DON001"
    summary = ("variable read after being passed at a donate_argnums "
               "position of a jitted call: XLA invalidated its buffer — "
               "the read returns garbage or raises on any path that "
               "reaches it")

    def check(self, module: Module) -> Iterator[Finding]:
        if not module.path.startswith(_DON_SCOPE):
            return iter(())
        bindings = _DonateBindings(module)
        if not bindings:
            return iter(())
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_fn(module, bindings, node))
        return iter(findings)

    def _check_fn(self, module: Module, bindings: _DonateBindings,
                  fn: ast.AST) -> List[Finding]:
        cfg = flow.build_cfg(fn)
        if not cfg.nodes:
            return []
        gens: Dict[int, List[Tuple[str, int]]] = {}
        kills: Dict[int, Set[str]] = {}
        for i, stmt in enumerate(cfg.nodes):
            g: List[Tuple[str, int]] = []
            for call in flow.stmt_calls(stmt):
                positions = bindings.positions_for(call)
                if not positions:
                    continue
                for pos in sorted(positions):
                    if pos < len(call.args) \
                            and isinstance(call.args[pos], ast.Name):
                        g.append((call.args[pos].id, call.lineno))
            gens[i] = g
            kills[i] = self._killed(stmt)
        if not any(gens.values()):
            return []
        index_of = {id(s): i for i, s in enumerate(cfg.nodes)}

        def transfer(stmt: ast.stmt, facts: FrozenSet) -> FrozenSet:
            i = index_of[id(stmt)]
            out = set(facts)
            out.update(gens[i])
            return frozenset(f for f in out if f[0] not in kills[i])

        IN = flow.forward_may(cfg, transfer)
        findings: List[Finding] = []
        seen: Set[Tuple[int, str]] = set()
        for i, stmt in enumerate(cfg.nodes):
            facts = IN[i]
            if not facts:
                continue
            donated = {}
            for name, line in facts:
                donated.setdefault(name, line)
            for node in flow._header_walk(stmt):
                if isinstance(node, ast.Name) \
                        and isinstance(node.ctx, ast.Load) \
                        and node.id in donated \
                        and (node.lineno, node.id) not in seen:
                    seen.add((node.lineno, node.id))
                    findings.append(Finding(
                        rule=self.name, path=module.path, line=node.lineno,
                        col=node.col_offset,
                        message=(f"`{node.id}` was donated to the jitted "
                                 f"call at line {donated[node.id]} "
                                 f"(donate_argnums): its device buffer is "
                                 f"invalidated — reading it afterwards "
                                 f"returns garbage or raises; reorder the "
                                 f"read before the call, rebind the name "
                                 f"from the call's result, or drop the "
                                 f"donation"),
                        snippet=module.line(node.lineno).strip()))
        return findings

    @staticmethod
    def _killed(stmt: ast.stmt) -> Set[str]:
        targets: List[ast.AST] = []
        if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, ast.Delete):
            targets = stmt.targets
        out: Set[str] = set()
        for sub in flow._header_walk(stmt):
            if isinstance(sub, ast.NamedExpr):
                targets.append(sub.target)
            elif isinstance(sub, (ast.With, ast.AsyncWith)):
                for item in sub.items:
                    if item.optional_vars is not None:
                        targets.append(item.optional_vars)
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    out.add(n.id)
        return out

"""raylint rule set: the invariants this runtime actually depends on.

Each rule encodes a failure mode we have hit (or designed against) in the
distributed runtime — see tools/raylint/README.md for the full rationale and
suppression guidance per rule.

* ASY001 — blocking call inside an ``async def`` body (event-loop stall).
* ASY002 — ``await`` while holding a ``threading`` lock, or a ``threading``
  primitive constructed on the event loop where an ``asyncio`` one belongs.
* SER001 — ``pickle.loads``/``cloudpickle.loads`` outside the sanctioned
  serialization boundary (``_private/serialization.py``, ``_private/wire.py``).
* EXC001 — exception-swallowing ``except ...: pass`` on control-plane paths
  (``_private/``, ``autoscaler/``, ``dag/``) with no log call.
* WIRE001 — a struct defined in a wire-schema module that is not registered
  in the ``wire.py`` registry (it would raise WireError at runtime, or worse,
  tempt someone to pickle it).
* TRC001 — a JAX tracer escaping into actor/object state: a value stored on
  ``self`` or shipped through ``.remote()``/``ray_tpu.put()`` from inside a
  ``jit``/``grad``-traced function.
* ASY003 — a leaked asyncio task: ``asyncio.ensure_future``/``create_task``
  whose result is neither awaited, stored, nor given a done-callback — its
  exception is swallowed until GC (often never); use
  ``ray_tpu._private.async_util.spawn``. Also flags the
  ``self._background.append(ensure_future(...))`` shape: a handle parked in
  long-lived state until shutdown swallows failures just the same.
* LCK001 — lock-order inversion across the GCS -> raylet -> core-worker
  hierarchy: nesting tiered locks against the call direction is the ABBA
  deadlock that wedges a whole node's control plane.
* SUP001 — stale suppression: a ``# raylint: disable=RULE`` comment that
  suppresses zero findings (the code it excused was fixed or moved). Dead
  directives accumulate and silently excuse FUTURE regressions on that
  line; delete them, or add ``SUP001`` to the directive's rule list with a
  reason to keep one deliberately dormant. (Detection lives in core.py —
  it needs the pre-suppression finding set; the class below is the
  registry marker so ``--rules``/``--list-rules`` see it.)

The interprocedural rules (ASY004, LCK002, AWT002, WIRE002) live in
``tools/raylint/rules_interp.py`` on top of the graph/flow layers.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Set

from tools.raylint.core import Finding, Module, Rule, register_rule

# ---------------------------------------------------------------------------
# shared visitor: track whether we are in an async frame
# ---------------------------------------------------------------------------


class _AsyncFrameVisitor(ast.NodeVisitor):
    """Walks a module tracking the innermost function frame. ``in_async`` is
    True only when the nearest enclosing function is an ``async def`` — code
    inside a nested sync ``def`` or ``lambda`` runs off the loop (e.g. an
    executor thunk) and is NOT async context."""

    def __init__(self, module: Module):
        self.module = module
        self.frames: List[str] = []  # "async" | "sync"
        self.findings: List[Finding] = []

    @property
    def in_async(self) -> bool:
        return bool(self.frames) and self.frames[-1] == "async"

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self.frames.append("async")
        self.generic_visit(node)
        self.frames.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self.frames.append("sync")
        self.generic_visit(node)
        self.frames.pop()

    def visit_Lambda(self, node: ast.Lambda):
        self.frames.append("sync")
        self.generic_visit(node)
        self.frames.pop()


def _contains_await(nodes) -> bool:
    """True if an await/async-for/async-with occurs in these nodes WITHOUT
    crossing into a nested function definition."""
    stack = list(nodes)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return False


def _terminal(dotted: Optional[str]) -> str:
    return dotted.rsplit(".", 1)[-1] if dotted else ""


def _is_lock_like(node: ast.AST, resolver) -> bool:
    """Heuristic: an expression that names a mutex (``self._lock``,
    ``_exec_lock``, ``store.mutex`` ...) — but not e.g. ``self.block``."""
    dotted = resolver.dotted(node)
    name = _terminal(dotted).lower()
    return (name in ("lock", "rlock", "mutex")
            or name.endswith(("_lock", "_rlock", "_mutex")))


# ---------------------------------------------------------------------------
# ASY001 — blocking calls in async bodies
# ---------------------------------------------------------------------------

# dotted call -> remediation hint. Every one of these parks the entire event
# loop (every actor task, RPC reply, and heartbeat on this node) until it
# returns.
_BLOCKING_CALLS: Dict[str, str] = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "subprocess.run": "use `asyncio.create_subprocess_exec` or an executor",
    "subprocess.call": "use `asyncio.create_subprocess_exec` or an executor",
    "subprocess.check_call": "use `asyncio.create_subprocess_exec` or an executor",
    "subprocess.check_output": "use `asyncio.create_subprocess_exec` or an executor",
    "subprocess.getoutput": "use `asyncio.create_subprocess_exec` or an executor",
    "subprocess.getstatusoutput": "use `asyncio.create_subprocess_exec` or an executor",
    "os.system": "use `asyncio.create_subprocess_shell` or an executor",
    "os.wait": "use `asyncio.create_subprocess_exec` and await it",
    "os.waitpid": "use `asyncio.create_subprocess_exec` and await it",
    "urllib.request.urlopen": "run it in an executor thread",
    "requests.get": "run it in an executor thread",
    "requests.post": "run it in an executor thread",
    "requests.put": "run it in an executor thread",
    "requests.patch": "run it in an executor thread",
    "requests.delete": "run it in an executor thread",
    "requests.head": "run it in an executor thread",
    "requests.request": "run it in an executor thread",
    "socket.create_connection": "use `asyncio.open_connection`",
    "ray_tpu.get": "a cluster round-trip blocks the loop; await the async "
                   "API or wrap in `loop.run_in_executor`",
    "ray_tpu.wait": "a cluster round-trip blocks the loop; await the async "
                    "API or wrap in `loop.run_in_executor`",
}

# method names that block when called on a raw socket; only flagged when the
# receiver's name mentions a socket, to keep the false-positive rate near zero
_SOCKET_METHODS = {"recv", "recv_into", "accept", "sendall", "makefile"}


@register_rule
class BlockingCallInAsync(Rule):
    name = "ASY001"
    summary = ("blocking call inside `async def`: stalls every task, RPC and "
               "heartbeat sharing this event loop")

    def check(self, module: Module) -> Iterator[Finding]:
        rule = self

        class V(_AsyncFrameVisitor):
            def visit_Call(self, node: ast.Call):
                if self.in_async:
                    dotted = module.resolver.dotted(node.func)
                    hint = _BLOCKING_CALLS.get(dotted or "")
                    if hint is not None:
                        self.findings.append(rule.finding(
                            module, node,
                            f"blocking `{dotted}(...)` in async context; {hint}"))
                    elif (isinstance(node.func, ast.Attribute)
                          and node.func.attr in _SOCKET_METHODS):
                        recv = module.resolver.dotted(node.func.value) or ""
                        if "sock" in recv.lower():
                            self.findings.append(rule.finding(
                                module, node,
                                f"blocking socket op `.{node.func.attr}(...)` in "
                                f"async context; use asyncio streams"))
                self.generic_visit(node)

        v = V(module)
        v.visit(module.tree)
        return iter(v.findings)


# ---------------------------------------------------------------------------
# ASY002 — threading primitives on the event loop
# ---------------------------------------------------------------------------

_THREADING_PRIMITIVES = {
    "threading.Lock": "asyncio.Lock",
    "threading.RLock": "asyncio.Lock",
    "threading.Condition": "asyncio.Condition",
    "threading.Semaphore": "asyncio.Semaphore",
    "threading.BoundedSemaphore": "asyncio.Semaphore",
    "threading.Event": "asyncio.Event",
    "threading.Barrier": "asyncio.Barrier",
}


@register_rule
class AwaitUnderThreadLock(Rule):
    name = "ASY002"
    summary = ("`await` while holding a threading lock (cross-thread "
               "deadlock), or a threading primitive where an asyncio one "
               "belongs")

    def check(self, module: Module) -> Iterator[Finding]:
        rule = self
        awaited: Set[int] = {
            id(n.value) for n in ast.walk(module.tree) if isinstance(n, ast.Await)
        }

        class V(_AsyncFrameVisitor):
            def visit_With(self, node: ast.With):
                if self.in_async:
                    for item in node.items:
                        expr = item.context_expr
                        # `with lock:` — a Call like `lock.acquire_timeout()`
                        # is out of scope; names/attrs only
                        if isinstance(expr, (ast.Name, ast.Attribute)) \
                                and _is_lock_like(expr, module.resolver) \
                                and _contains_await(node.body):
                            self.findings.append(rule.finding(
                                module, node,
                                "await inside `with <threading lock>`: the "
                                "loop thread parks while holding the lock — "
                                "any thread that then takes the lock and "
                                "schedules loop work deadlocks; use "
                                "`asyncio.Lock` or release before awaiting"))
                self.generic_visit(node)

            def visit_Call(self, node: ast.Call):
                if self.in_async:
                    dotted = module.resolver.dotted(node.func)
                    repl = _THREADING_PRIMITIVES.get(dotted or "")
                    if repl:
                        self.findings.append(rule.finding(
                            module, node,
                            f"`{dotted}()` constructed in async context; its "
                            f"blocking acquire/wait would stall the loop — "
                            f"use `{repl}`"))
                    elif (isinstance(node.func, ast.Attribute)
                          and node.func.attr == "acquire"
                          and id(node) not in awaited
                          and _is_lock_like(node.func.value, module.resolver)):
                        self.findings.append(rule.finding(
                            module, node,
                            "non-awaited `.acquire()` on a lock in async "
                            "context blocks the event loop; use "
                            "`async with` / `await lock.acquire()`"))
                self.generic_visit(node)

        v = V(module)
        v.visit(module.tree)
        return iter(v.findings)


# ---------------------------------------------------------------------------
# ASY003 — leaked asyncio tasks (fire-and-forget without an owner)
# ---------------------------------------------------------------------------

# Spawning calls whose returned task must not be discarded: a task whose
# result nobody ever retrieves reports its exception only when the task
# object is garbage-collected — "Task exception was never retrieved",
# minutes later or never. On the control plane that converts a crashed
# scheduling/flush loop into a silent distributed hang.
_SPAWN_CALLS = {"asyncio.ensure_future", "asyncio.create_task"}
_SPAWN_METHODS = {"ensure_future", "create_task"}


def _is_spawn_call(node: ast.Call, resolver) -> bool:
    dotted = resolver.dotted(node.func)
    if dotted in _SPAWN_CALLS:
        return True
    # loop.create_task(...) / self.loop.create_task(...): method form on
    # anything whose name mentions a loop
    if isinstance(node.func, ast.Attribute) and node.func.attr in _SPAWN_METHODS:
        recv = resolver.dotted(node.func.value) or ""
        return "loop" in recv.lower()
    return False


@register_rule
class LeakedAsyncioTask(Rule):
    name = "ASY003"
    summary = ("fire-and-forget asyncio task: its exception is swallowed "
               "until GC (often never); store/await it or use "
               "async_util.spawn (done-callback logging)")

    def check(self, module: Module) -> Iterator[Finding]:
        findings = []
        for node in ast.walk(module.tree):
            # only a bare expression STATEMENT discards the task; an
            # assignment, await, or chained .add_done_callback(...) keep an
            # owner (appending to long-lived state is handled below)
            if not isinstance(node, ast.Expr):
                continue
            value = node.value
            if isinstance(value, ast.Call) and _is_spawn_call(
                    value, module.resolver):
                findings.append(self.finding(
                    module, value,
                    "spawned task is neither awaited, stored, nor given a "
                    "done-callback — its exception dies with the task "
                    "object; use ray_tpu._private.async_util.spawn(...) "
                    "(or keep a handle / add_done_callback)"))
            elif isinstance(value, ast.Call):
                # lambda bodies passed to call_later/call_soon share the leak
                for arg in value.args:
                    if isinstance(arg, ast.Lambda) \
                            and isinstance(arg.body, ast.Call) \
                            and _is_spawn_call(arg.body, module.resolver):
                        findings.append(self.finding(
                            module, arg.body,
                            "fire-and-forget task spawned inside a lambda "
                            "callback; route through async_util.spawn so "
                            "failures are logged"))
                # `self._background.append(ensure_future(...))`: the handle
                # is kept (so the bare-Expr branch misses it) but nothing
                # ever awaits a list parked until shutdown — the crash is
                # still silent until GC. A LOCAL list (`waiters.append`) is
                # typically awaited in-scope and stays allowed.
                if (isinstance(value.func, ast.Attribute)
                        and value.func.attr in ("append", "add")
                        and isinstance(value.func.value, ast.Attribute)
                        and len(value.args) == 1
                        and isinstance(value.args[0], ast.Call)
                        and _is_spawn_call(value.args[0], module.resolver)):
                    findings.append(self.finding(
                        module, value.args[0],
                        "task appended to long-lived state without failure "
                        "logging: a stored-but-never-awaited task swallows "
                        "its exception until GC; append "
                        "async_util.spawn(...) instead (same handle, "
                        "logged failures)"))
        return iter(findings)


# ---------------------------------------------------------------------------
# SER001 — unpickling outside the serialization boundary
# ---------------------------------------------------------------------------

_UNPICKLE_CALLS = {
    "pickle.loads", "pickle.load", "pickle.Unpickler",
    "cloudpickle.loads", "cloudpickle.load",
}

# The ONLY modules allowed to unpickle: the object-plane serializer and the
# typed wire codec (which by construction never unpickles network input).
_SER_ALLOWLIST = {
    "ray_tpu/_private/serialization.py",
    "ray_tpu/_private/wire.py",
}


@register_rule
class UnpickleOutsideBoundary(Rule):
    name = "SER001"
    summary = ("pickle/cloudpickle load outside _private/serialization.py: "
               "unpickling network input is remote code execution")

    def check(self, module: Module) -> Iterator[Finding]:
        if module.path in _SER_ALLOWLIST:
            return iter(())
        findings = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                dotted = module.resolver.dotted(node.func)
                if dotted in _UNPICKLE_CALLS:
                    findings.append(self.finding(
                        module, node,
                        f"`{dotted}(...)` outside the serialization boundary; "
                        f"route through ray_tpu._private.serialization (e.g. "
                        f"`loads_trusted`) so every unpickle site is auditable"))
        return iter(findings)


# ---------------------------------------------------------------------------
# EXC001 — swallowed exceptions on the control plane
# ---------------------------------------------------------------------------

# Handler types that are control flow, not error swallowing, when caught
# alone: bounded waits and lookup misses.
_EXC_EXEMPT = {
    "asyncio.TimeoutError", "TimeoutError", "concurrent.futures.TimeoutError",
    "asyncio.CancelledError", "CancelledError",
    "KeyError", "IndexError", "FileNotFoundError",
    "StopIteration", "StopAsyncIteration", "GeneratorExit",
    "queue.Empty", "queue.Full",
}

# Path components that mark control-plane code. A stall or swallowed error
# here takes down scheduling/heartbeats for the whole node, not one task.
_EXC_PATH_PARTS = {"_private", "autoscaler", "dag"}


def _handler_types(handler: ast.ExceptHandler, resolver) -> List[Optional[str]]:
    t = handler.type
    if t is None:
        return [None]  # bare except
    if isinstance(t, ast.Tuple):
        return [resolver.dotted(e) for e in t.elts]
    return [resolver.dotted(t)]


def _swallows(handler: ast.ExceptHandler) -> bool:
    """Body is nothing but pass / ... / continue / break / bare return —
    i.e. the error is dropped without a trace (a `return value` or any other
    statement at least does something with the failure)."""
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Return) and stmt.value is None:
            continue
        if (isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant)
                and stmt.value.value is Ellipsis):
            continue
        return False
    return True


@register_rule
class SwallowedException(Rule):
    name = "EXC001"
    summary = ("`except ...: pass` on a control-plane path with no log call: "
               "the next symptom is a distributed hang with no trace")

    def check(self, module: Module) -> Iterator[Finding]:
        if not (_EXC_PATH_PARTS & set(module.parts())):
            return iter(())
        findings = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ExceptHandler) or not _swallows(node):
                continue
            types = _handler_types(node, module.resolver)
            if all(t is not None and t in _EXC_EXEMPT for t in types):
                continue
            shown = ", ".join(t or "<bare>" for t in types)
            findings.append(self.finding(
                module, node,
                f"swallowed `except {shown}` with no log call; add "
                f"`logger.debug(...)` with context, or suppress with a reason "
                f"(`# raylint: disable=EXC001 <why>`)"))
        return iter(findings)


# ---------------------------------------------------------------------------
# TRC001 — JAX tracers escaping into actor/object state
# ---------------------------------------------------------------------------

# Transforms that TRACE their function: inside these bodies every value is a
# Tracer, and letting one escape the trace is at best an
# UnexpectedTracerError at the next use, at worst a silently baked-in
# constant (jit) or a leaked trace-context hold on device buffers.
_TRACING_TRANSFORMS = {
    "jax.jit", "jax.grad", "jax.value_and_grad", "jax.vmap", "jax.pmap",
    "jax.checkpoint", "jax.remat", "jax.custom_jvp", "jax.custom_vjp",
    "jax.lax.scan", "jax.lax.while_loop", "jax.lax.cond",
    "jax.experimental.shard_map.shard_map", "shard_map.shard_map",
}


def _jit_target_names(tree: ast.AST, resolver) -> Set[str]:
    """Names of functions passed to a tracing transform anywhere in the
    module: ``jax.jit(step)``, ``self._fwd = jax.jit(self._fwd_impl)``,
    ``train = jit(train_impl, donate_argnums=0)`` ..."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = resolver.dotted(node.func)
        if dotted not in _TRACING_TRANSFORMS:
            continue
        for arg in node.args[:1]:  # the traced callable is arg 0
            if isinstance(arg, ast.Name):
                names.add(arg.id)
            elif isinstance(arg, ast.Attribute):
                names.add(arg.attr)
    return names


def _is_traced_def(node, resolver) -> bool:
    """Decorated directly (`@jax.jit`), via a call (`@jax.jit`/
    `@partial(jax.jit, ...)`), or by any tracing transform."""
    for dec in node.decorator_list:
        target = dec
        if isinstance(dec, ast.Call):
            dotted = resolver.dotted(dec.func) or ""
            if dotted in ("functools.partial", "partial") and dec.args:
                target = dec.args[0]
            else:
                target = dec.func
        if (resolver.dotted(target) or "") in _TRACING_TRANSFORMS:
            return True
    return False


@register_rule
class TracerEscape(Rule):
    name = "TRC001"
    summary = ("JAX tracer escaping into actor/object state: a traced value "
               "stored on `self` or shipped via `.remote()`/`put()` from a "
               "jit/grad scope")

    def check(self, module: Module) -> Iterator[Finding]:
        resolver = module.resolver
        traced_names = _jit_target_names(module.tree, resolver)
        findings: List[Finding] = []

        def scan_traced_body(fn_node):
            for node in ast.walk(fn_node):
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"
                                and not isinstance(node.value, ast.Constant)):
                            findings.append(self.finding(
                                module, node,
                                f"`self.{t.attr} = ...` inside a traced "
                                f"function: the stored value is a Tracer — "
                                f"it escapes the trace into actor state and "
                                f"dies with UnexpectedTracerError (or bakes "
                                f"in a constant); return it from the jitted "
                                f"function instead"))
                elif isinstance(node, ast.Call):
                    dotted = resolver.dotted(node.func)
                    if dotted in ("ray_tpu.put", "ray.put"):
                        findings.append(self.finding(
                            module, node,
                            f"`{dotted}(...)` inside a traced function "
                            f"ships a Tracer into the object plane; move "
                            f"the put outside the jit/grad scope"))
                    elif (isinstance(node.func, ast.Attribute)
                          and node.func.attr == "remote"):
                        findings.append(self.finding(
                            module, node,
                            "`.remote(...)` inside a traced function: task "
                            "args would be Tracers (and the submission "
                            "itself is a traced side effect that jit will "
                            "elide on cache hits); submit outside the "
                            "traced scope"))

        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if _is_traced_def(node, resolver) or node.name in traced_names:
                scan_traced_body(node)
        return iter(findings)


# ---------------------------------------------------------------------------
# LCK001 — lock-order inversions across the control-plane hierarchy
# ---------------------------------------------------------------------------

# The control plane's lock hierarchy follows its call direction:
# GCS (tier 0) -> raylet (tier 1) -> core worker (tier 2). A thread/task may
# nest lock acquisitions only DOWN the hierarchy (gcs lock, then raylet
# lock, then worker lock). Two call paths nesting in opposite orders is the
# classic ABBA deadlock — and across these components it wedges scheduling
# for the whole node, not one request. Locks are tiered by name
# (`_gcs_lock`, `raylet_mutex`, `_core_worker_lock`, ...); locks whose
# names carry no tier are out of scope, as is any pair within one tier.
_LCK_TIERS = (
    ("gcs", 0),
    ("raylet", 1),
    ("core_worker", 2), ("core", 2), ("worker", 2),
)


def _lock_tier(dotted: Optional[str]) -> Optional[int]:
    name = _terminal(dotted).lower()
    for marker, tier in _LCK_TIERS:
        if marker in name:
            return tier
    return None


@register_rule
class LockOrderInversion(Rule):
    name = "LCK001"
    summary = ("lock acquired AGAINST the GCS -> raylet -> core-worker "
               "hierarchy while a lower-tier lock is held (ABBA deadlock "
               "across control-plane components)")

    def check(self, module: Module) -> Iterator[Finding]:
        rule = self
        resolver = module.resolver

        def lock_exprs(items):
            """(tier, dotted) for each tiered lock taken by a with-item."""
            out = []
            for item in items:
                expr = item.context_expr
                if isinstance(expr, ast.Call):  # `with lock.acquire_timeout()`
                    expr = expr.func
                    if isinstance(expr, ast.Attribute):
                        expr = expr.value
                if isinstance(expr, (ast.Name, ast.Attribute)) \
                        and _is_lock_like(expr, resolver):
                    tier = _lock_tier(resolver.dotted(expr))
                    if tier is not None:
                        out.append((tier, resolver.dotted(expr)))
            return out

        class V(ast.NodeVisitor):
            """Tracks the stack of held tiered locks through with-nesting.
            The stack resets at function boundaries (a nested def runs on
            its own call path)."""

            def __init__(self):
                self.held: List[tuple] = []
                self.findings: List[Finding] = []

            def _visit_with(self, node):
                taken = lock_exprs(node.items)
                # push incrementally: `with a, b:` acquires left-to-right,
                # so b must be checked against a, not only against outer
                # with-statements
                for tier, dotted in taken:
                    for held_tier, held_dotted in self.held:
                        if tier < held_tier:
                            self.findings.append(rule.finding(
                                module, node,
                                f"`{dotted}` (tier {tier}) acquired while "
                                f"holding `{held_dotted}` (tier "
                                f"{held_tier}): lock order must follow "
                                f"GCS -> raylet -> core worker; invert the "
                                f"nesting or release the inner lock first"))
                    self.held.append((tier, dotted))
                self.generic_visit(node)
                if taken:
                    del self.held[-len(taken):]

            def visit_With(self, node):
                self._visit_with(node)

            def visit_AsyncWith(self, node):
                self._visit_with(node)

            def _visit_fn(self, node):
                saved, self.held = self.held, []
                self.generic_visit(node)
                self.held = saved

            def visit_FunctionDef(self, node):
                self._visit_fn(node)

            def visit_AsyncFunctionDef(self, node):
                self._visit_fn(node)

            def visit_Lambda(self, node):
                self._visit_fn(node)

        v = V()
        v.visit(module.tree)
        return iter(v.findings)


# ---------------------------------------------------------------------------
# WIRE001 — wire structs missing from the registry
# ---------------------------------------------------------------------------

# Modules whose dataclasses ARE the control-plane schema: anything defined
# here is meant to cross RPC, so it must be in wire.py's registry or be
# explicitly annotated as process-local.
_WIRE_STRUCT_MODULES = {
    "ray_tpu/_private/common.py",
    "ray_tpu/util/scheduling_strategies.py",
}
_WIRE_REGISTRY_MODULE = "ray_tpu/_private/wire.py"
_WIRE_CACHE_KEY = "wire001.registered"


def _registered_wire_names(project) -> Set[str]:
    """Parse wire.py and collect every class name passed (directly, or via a
    registration loop) to register_struct/register_id."""
    cached = project.cache.get(_WIRE_CACHE_KEY)
    if cached is not None:
        return cached
    names: Set[str] = set()
    path = project.root / _WIRE_REGISTRY_MODULE
    if path.is_file():
        tree = ast.parse(path.read_text(encoding="utf-8"))

        def is_register(call: ast.Call) -> bool:
            fn = call.func
            attr = fn.attr if isinstance(fn, ast.Attribute) else \
                fn.id if isinstance(fn, ast.Name) else ""
            return attr in ("register_struct", "register_id")

        def terminal_name(expr: ast.AST) -> Optional[str]:
            if isinstance(expr, ast.Attribute):
                return expr.attr
            if isinstance(expr, ast.Name):
                return expr.id
            return None

        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and is_register(node) and node.args:
                n = terminal_name(node.args[0])
                if n:
                    names.add(n)
            elif isinstance(node, ast.For):
                # `for c in (ids.JobID, ...): register_id(c)`
                has_register = any(
                    isinstance(sub, ast.Call) and is_register(sub)
                    for sub in ast.walk(node))
                if has_register and isinstance(node.iter, (ast.Tuple, ast.List)):
                    for elt in node.iter.elts:
                        n = terminal_name(elt)
                        if n:
                            names.add(n)
    project.cache[_WIRE_CACHE_KEY] = names
    return names


def _is_dataclass_decorated(node: ast.ClassDef, resolver) -> bool:
    for dec in node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        dotted = resolver.dotted(target) or ""
        if dotted in ("dataclass", "dataclasses.dataclass"):
            return True
    return False


@register_rule
class UnregisteredWireStruct(Rule):
    name = "WIRE001"
    summary = ("dataclass in a wire-schema module missing from the wire.py "
               "registry: sending it raises WireError at runtime")

    def check(self, module: Module) -> Iterator[Finding]:
        if module.path not in _WIRE_STRUCT_MODULES:
            return iter(())
        registered = _registered_wire_names(module.project)
        findings = []
        for node in module.tree.body:
            if (isinstance(node, ast.ClassDef)
                    and _is_dataclass_decorated(node, module.resolver)
                    and node.name not in registered):
                findings.append(self.finding(
                    module, node,
                    f"wire-schema dataclass `{node.name}` is not registered in "
                    f"wire.py (_register_builtin_types); register it, or mark "
                    f"it process-local with `# raylint: disable=WIRE001 <why>`"))
        return iter(findings)


# ---------------------------------------------------------------------------
# SUP001 — stale suppressions (marker class; detection in core.check_source)
# ---------------------------------------------------------------------------


@register_rule
class StaleSuppression(Rule):
    name = "SUP001"
    summary = ("`# raylint: disable=RULE` that suppresses zero findings: "
               "dead directives excuse future regressions; delete them (or "
               "add SUP001 to the directive's rule list to keep it)")

    def check(self, module: Module) -> Iterator[Finding]:
        return iter(())  # core.check_source runs the real detection


# ---------------------------------------------------------------------------
# CKP001 — checkpoint-plane writes outside the atomic-commit helper
# ---------------------------------------------------------------------------

# Modules whose on-disk artifacts carry the checkpoint plane's atomicity
# invariant: a torn manifest/chunk/pointer write corrupts restore. Every
# file write there must go through ``ckpt.manifest.atomic_write`` (write
# temp + fsync + rename) — the one sanctioned raw-write site, which
# carries its own suppression.
_CKP_PATH_PREFIXES = ("ray_tpu/ckpt/",)
_CKP_PATH_FILES = {"ray_tpu/train/checkpoint.py"}

# attribute calls that write file content directly
_CKP_WRITE_ATTRS = ("write_text", "write_bytes")

# dotted calls that serialize straight into a file object
_CKP_DUMP_CALLS = {"json.dump", "pickle.dump", "cloudpickle.dump",
                   "numpy.save", "np.save"}

# Storage-backend write chokepoints (ckpt/tier): a ChunkBackend or bucket
# client OWNS its tier's durability discipline, so its designated write
# methods may open files directly — PROVIDED the method itself upholds the
# temp+fsync+rename contract. Checked structurally: the method must call
# both ``os.fsync`` and ``os.replace``; a backend write method that opens
# a file without them still flags.
_CKP_BACKEND_CLASS_SUFFIXES = ("Backend", "BucketClient")
_CKP_BACKEND_WRITE_METHODS = {"put", "put_object", "put_manifest",
                              "upload_part", "complete_multipart"}


def _ckp_backend_exempt_calls(module: Module) -> set:
    """ids of Call nodes inside a storage-backend write method that
    provably renames a fsynced temp file into place."""
    exempt: set = set()
    for cls in ast.walk(module.tree):
        if not (isinstance(cls, ast.ClassDef)
                and cls.name.endswith(_CKP_BACKEND_CLASS_SUFFIXES)):
            continue
        for fn in cls.body:
            if not (isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and fn.name in _CKP_BACKEND_WRITE_METHODS):
                continue
            dotted = {module.resolver.dotted(n.func)
                      for n in ast.walk(fn) if isinstance(n, ast.Call)}
            if {"os.fsync", "os.replace"} <= dotted:
                exempt.update(id(n) for n in ast.walk(fn)
                              if isinstance(n, ast.Call))
    return exempt


def _open_write_mode(call: ast.Call) -> bool:
    """True if this ``open(...)`` call names a write/append/create mode.
    A non-constant mode is treated as a write (the caller can suppress
    with a reason if it provably is not)."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return False  # default "r"
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return any(c in mode.value for c in "wax+")
    return True


@register_rule
class CheckpointWriteOutsideHelper(Rule):
    name = "CKP001"
    summary = ("checkpoint/manifest file write outside "
               "ckpt.manifest.atomic_write: a torn write breaks the plane's "
               "atomicity invariant (a reader may observe a partial file)")

    def check(self, module: Module) -> Iterator[Finding]:
        if not (module.path.startswith(_CKP_PATH_PREFIXES)
                or module.path in _CKP_PATH_FILES):
            return iter(())
        exempt = _ckp_backend_exempt_calls(module)
        findings = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) or id(node) in exempt:
                continue
            dotted = module.resolver.dotted(node.func)
            if dotted in ("open", "io.open", "builtins.open"):
                if _open_write_mode(node):
                    findings.append(self.finding(
                        module, node,
                        "file opened for writing on a checkpoint-plane "
                        "path; route the bytes through "
                        "`ckpt.manifest.atomic_write` so a crash can "
                        "never leave a torn manifest/chunk visible"))
            elif dotted in _CKP_DUMP_CALLS:
                findings.append(self.finding(
                    module, node,
                    f"`{dotted}(...)` serializes straight into a file on "
                    f"a checkpoint-plane path; serialize to bytes and "
                    f"commit via `ckpt.manifest.atomic_write`"))
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr in _CKP_WRITE_ATTRS):
                findings.append(self.finding(
                    module, node,
                    f"`.{node.func.attr}(...)` writes file content "
                    f"directly on a checkpoint-plane path; use "
                    f"`ckpt.manifest.atomic_write`"))
        return iter(findings)


# ---------------------------------------------------------------------------
# OBS001: observability hygiene — metric naming and static span names
# ---------------------------------------------------------------------------

_OBS_METRIC_CTORS = {"Counter", "Gauge", "Histogram"}
_OBS_NAME_PREFIXES = ("ray_tpu_", "ray_tpu.")


def _call_arg(node: ast.Call, index: int, keyword: str) -> Optional[ast.AST]:
    if len(node.args) > index:
        return node.args[index]
    for kw in node.keywords:
        if kw.arg == keyword:
            return kw.value
    return None


@register_rule
class ObservabilityHygiene(Rule):
    name = "OBS001"
    summary = ("observability hygiene: metric instruments must carry the "
               "ray_tpu prefix and a non-empty description, and "
               "tracing.profile() span names must be static strings — an "
               "f-string per request/task is a cardinality bomb in every "
               "span consumer (GCS ring, timeline, Perfetto)")

    def check(self, module: Module) -> Iterator[Finding]:
        if not module.path.startswith("ray_tpu/"):
            return iter(())
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = module.resolver.dotted(node.func) or ""
            terminal = _terminal(dotted)
            # metrics constructors: resolved through util.metrics (so
            # collections.Counter and friends never match)
            if terminal in _OBS_METRIC_CTORS and "metrics" in dotted:
                findings.extend(self._check_metric(module, node, terminal))
            elif terminal == "profile" and "tracing" in dotted:
                findings.extend(self._check_span(module, node))
        return iter(findings)

    def _check_metric(self, module: Module, node: ast.Call,
                      ctor: str) -> List[Finding]:
        out: List[Finding] = []
        name = _call_arg(node, 0, "name")
        if not (isinstance(name, ast.Constant)
                and isinstance(name.value, str)):
            out.append(self.finding(
                module, node,
                f"{ctor} name must be a static string literal (the "
                f"ray_tpu prefix convention is unverifiable otherwise, "
                f"and dynamic names multiply Prometheus series)"))
        elif not name.value.startswith(_OBS_NAME_PREFIXES):
            out.append(self.finding(
                module, node,
                f"metric `{name.value}` must carry the `ray_tpu_` prefix "
                f"(every exported series is namespaced; unprefixed names "
                f"collide with user/app metrics in /metrics)"))
        desc = _call_arg(node, 1, "description")
        if desc is None or (isinstance(desc, ast.Constant)
                            and not str(desc.value or "").strip()):
            out.append(self.finding(
                module, node,
                f"{ctor} needs a non-empty description — it renders as "
                f"the `# HELP` line of the Prometheus exposition"))
        return out

    def _check_span(self, module: Module, node: ast.Call) -> List[Finding]:
        name = _call_arg(node, 0, "name")
        if isinstance(name, ast.Constant) and isinstance(name.value, str):
            return []
        return [self.finding(
            module, node,
            "tracing.profile() span name must be a static string — "
            "f-strings/concatenation mint one span NAME per request or "
            "task (cardinality bomb in the GCS span table and every "
            "timeline view); put the variable part in span kwargs, e.g. "
            "profile(\"pull\", store=name)")]


# ---------------------------------------------------------------------------
# RSH001: reshard plans must be proven no-gather before transport lowering
# ---------------------------------------------------------------------------

# calls that mint a reshard plan
_RSH_PLAN_SOURCES = {"plan_reshard", "restore_plan"}
# transport-lowering entry points that execute/lower a plan's data movement
_RSH_LOWER_SINKS = {"collective_reshard", "redistribute", "lower_collective"}


@register_rule
class ReshardNoGatherUnasserted(Rule):
    name = "RSH001"
    summary = ("reshard plan reaches a transport lowering without an "
               "explicit `plan.no_gather()` check: a plan that gathers a "
               "full leaf onto one host is exactly the XLA "
               "replicate-then-slice rematerialization the collective "
               "redistribution tier exists to kill (MULTICHIP_r05) — "
               "assert the invariant where the plan is made, or carry a "
               "reasoned suppression")

    def check(self, module: Module) -> Iterator[Finding]:
        if not module.path.startswith("ray_tpu/"):
            return iter(())
        findings: List[Finding] = []
        seen: set = set()
        funcs = [n for n in ast.walk(module.tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for fn in funcs:
            plans: dict = {}   # var -> assignment line
            guards: dict = {}  # var -> earliest no_gather() line
            sinks: list = []   # (var, sink call node)
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call):
                    dotted = module.resolver.dotted(node.value.func) or ""
                    if _terminal(dotted) in _RSH_PLAN_SOURCES:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                plans[t.id] = node.lineno
                if not isinstance(node, ast.Call):
                    continue
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "no_gather" \
                        and isinstance(node.func.value, ast.Name):
                    var = node.func.value.id
                    guards[var] = min(guards.get(var, node.lineno),
                                      node.lineno)
                dotted = module.resolver.dotted(node.func) or ""
                if _terminal(dotted) in _RSH_LOWER_SINKS:
                    for arg in list(node.args) \
                            + [kw.value for kw in node.keywords]:
                        if isinstance(arg, ast.Name):
                            sinks.append((arg.id, node))
            for var, node in sinks:
                if var not in plans:
                    continue  # plan came from elsewhere (param, attr)
                key = (node.lineno, node.col_offset)
                if key in seen:
                    continue  # nested-def walk saw this sink already
                guard = guards.get(var)
                if guard is not None and guard <= node.lineno:
                    continue
                seen.add(key)
                findings.append(self.finding(
                    module, node,
                    f"`{var}` (a reshard plan from "
                    f"plan_reshard/restore_plan) is lowered to a transport "
                    f"without `{var}.no_gather()` being checked first; a "
                    f"gathering plan must be rejected before any byte "
                    f"moves (use weights.maybe_lower_collective for the "
                    f"logged fallback)"))
        return iter(findings)

"""raylint flow layer: intraprocedural CFG + dataflow.

Gives flow-sensitive rules (AWT002 today) a real control-flow graph per
function instead of a lexical walk:

* :func:`build_cfg` — statement-level CFG over one function body. Compound
  statements (``if``/``while``/``for``/``try``/``with``) are descended into;
  leaf statements are the CFG nodes. Loops get back edges, ``break``/
  ``continue``/``return``/``raise`` divert to the right successor, and every
  statement in a ``try`` body may also jump to each handler (exceptions can
  occur anywhere — a may-analysis must see that path).
* :func:`forward_may` — generic forward may-dataflow (union at joins,
  iterate to fixpoint) parameterized by a per-statement transfer function.
  Used for the held-locks analysis.
* :func:`reaching_defs` — classic reaching definitions over the CFG:
  for each statement, which assignment of each local name may reach it.
  Rules use it to resolve lock aliases (``lk = self._lock; lk.acquire()``)
  flow-sensitively.

Nested function definitions and lambdas are opaque single statements here:
their bodies run on a different call path and are analyzed as their own
functions by the graph layer.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, FrozenSet, Iterator, List, Optional, Tuple

_OPAQUE = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class CFG:
    """Statement-level control-flow graph. ``nodes[i]`` is an ast.stmt;
    ``succ[i]`` its successor indices. Node 0's predecessors: none (entry
    edges start at ``entry``); ``EXIT`` (= -1) is the virtual exit."""

    EXIT = -1

    def __init__(self):
        self.nodes: List[ast.stmt] = []
        self.succ: Dict[int, List[int]] = {}
        self.entry: List[int] = []

    def add(self, stmt: ast.stmt) -> int:
        idx = len(self.nodes)
        self.nodes.append(stmt)
        self.succ[idx] = []
        return idx

    def edge(self, a: int, b: int):
        if b not in self.succ[a]:
            self.succ[a].append(b)

    def preds(self) -> Dict[int, List[int]]:
        out: Dict[int, List[int]] = {i: [] for i in range(len(self.nodes))}
        for a, succs in self.succ.items():
            for b in succs:
                if b != CFG.EXIT:
                    out[b].append(a)
        return out


class _Builder:
    def __init__(self):
        self.cfg = CFG()
        self._loop_stack: List[Tuple[List[int], List[int]]] = []  # (breaks, continues)

    # Each _stmt/_suite method takes the list of dangling edge sources
    # (node indices whose next sequential successor is unknown yet) and
    # returns the new dangling list. try->handler edges are added by a
    # post-pass in _try over the body's node range (covers nesting too).

    def _connect(self, sources: List[int], target: int):
        for s in sources:
            self.cfg.edge(s, target)

    def _suite(self, stmts: List[ast.stmt], incoming: List[int]) -> List[int]:
        dangling = incoming
        for stmt in stmts:
            dangling = self._stmt(stmt, dangling)
        return dangling

    def _leaf(self, stmt: ast.stmt, incoming: List[int]) -> Tuple[int, List[int]]:
        idx = self.cfg.add(stmt)
        if not self.cfg.entry and not incoming:
            self.cfg.entry = [idx]
        self._connect(incoming, idx)
        return idx, [idx]

    def _stmt(self, stmt: ast.stmt, incoming: List[int]) -> List[int]:
        if isinstance(stmt, ast.If):
            idx, out = self._leaf(stmt, incoming)  # the test
            then_out = self._suite(stmt.body, list(out))
            else_out = self._suite(stmt.orelse, list(out)) \
                if stmt.orelse else list(out)
            return then_out + else_out
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            idx, out = self._leaf(stmt, incoming)  # test / iter
            self._loop_stack.append(([], []))
            body_out = self._suite(stmt.body, list(out))
            breaks, continues = self._loop_stack.pop()
            self._connect(body_out + continues, idx)  # back edge
            else_out = self._suite(stmt.orelse, list(out)) \
                if stmt.orelse else list(out)
            return else_out + breaks
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            idx, out = self._leaf(stmt, incoming)  # the with items
            return self._suite(stmt.body, list(out))
        if isinstance(stmt, ast.Try):
            return self._try(stmt, incoming)
        if isinstance(stmt, (ast.Return, ast.Raise)):
            idx, _ = self._leaf(stmt, incoming)
            self.cfg.edge(idx, CFG.EXIT)
            return []
        if isinstance(stmt, ast.Break):
            idx, _ = self._leaf(stmt, incoming)
            if self._loop_stack:
                self._loop_stack[-1][0].append(idx)
            return []
        if isinstance(stmt, ast.Continue):
            idx, _ = self._leaf(stmt, incoming)
            if self._loop_stack:
                self._loop_stack[-1][1].append(idx)
            return []
        # leaf statement (incl. nested defs, which are opaque)
        _, out = self._leaf(stmt, incoming)
        return out

    def _try(self, stmt: ast.Try, incoming: List[int]) -> List[int]:
        # collect the body's nodes so every one can reach every handler head
        start = len(self.cfg.nodes)
        body_out = self._suite(stmt.body, incoming)
        body_nodes = list(range(start, len(self.cfg.nodes)))
        out = list(body_out)
        handler_outs: List[int] = []
        for h in stmt.handlers:
            h_start = len(self.cfg.nodes)
            h_out = self._suite(h.body, [])
            # edge from every body node to this handler's first node
            if len(self.cfg.nodes) > h_start:
                head = h_start
                for b in body_nodes:
                    self.cfg.edge(b, head)
                # an empty incoming list would make the handler unreachable
                # from entry; that's correct — it's reachable via body edges
            handler_outs.extend(h_out)
        out.extend(handler_outs)
        if stmt.orelse:
            out = self._suite(stmt.orelse, body_out) + handler_outs
        if stmt.finalbody:
            out = self._suite(stmt.finalbody, out)
        return out


def build_cfg(fn: ast.AST) -> CFG:
    """CFG over the body of a FunctionDef/AsyncFunctionDef."""
    b = _Builder()
    dangling = b._suite(list(fn.body), [])
    for d in dangling:
        b.cfg.edge(d, CFG.EXIT)
    if not b.cfg.entry and b.cfg.nodes:
        b.cfg.entry = [0]
    return b.cfg


# ---------------------------------------------------------------------------
# Dataflow
# ---------------------------------------------------------------------------

Transfer = Callable[[ast.stmt, FrozenSet], FrozenSet]


def forward_may(cfg: CFG, transfer: Transfer,
                init: FrozenSet = frozenset()) -> Dict[int, FrozenSet]:
    """Forward may-analysis: IN[n] = union(OUT[preds]); OUT[n] =
    transfer(stmt, IN[n]). Returns the IN set per node index."""
    n = len(cfg.nodes)
    preds = cfg.preds()
    IN: Dict[int, FrozenSet] = {i: frozenset() for i in range(n)}
    OUT: Dict[int, FrozenSet] = {i: frozenset() for i in range(n)}
    for e in cfg.entry:
        IN[e] = init
    work = list(range(n))
    guard = 0
    while work and guard < 20 * (n + 1):
        guard += 1
        i = work.pop(0)
        new_in = init if i in cfg.entry else frozenset()
        for p in preds[i]:
            new_in = new_in | OUT[p]
        new_out = transfer(cfg.nodes[i], new_in)
        if new_in != IN[i] or new_out != OUT[i]:
            IN[i], OUT[i] = new_in, new_out
            for s in cfg.succ[i]:
                if s != CFG.EXIT and s not in work:
                    work.append(s)
    return IN


def header_children(stmt: ast.stmt) -> List[ast.AST]:
    """The expressions owned by this CFG node itself. For compound
    statements (whose suites are separate CFG nodes) that is only the
    header — test / iter / with-items — never the body; for leaf
    statements it is the whole statement."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.target, stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return list(stmt.items)
    if isinstance(stmt, ast.Try):
        return []
    return [stmt]


def _header_walk(stmt: ast.stmt) -> Iterator[ast.AST]:
    stack: List[ast.AST] = list(header_children(stmt))
    while stack:
        node = stack.pop()
        if node is not stmt and isinstance(node, _OPAQUE):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def stmt_calls(stmt: ast.stmt) -> Iterator[ast.Call]:
    """Call expressions owned by this CFG node, not crossing nested defs
    (nor the suites of compound statements — those are their own nodes)."""
    for node in _header_walk(stmt):
        if isinstance(node, ast.Call):
            yield node


def stmt_awaits(stmt: ast.stmt) -> Iterator[ast.AST]:
    """Awaits owned by this CFG node (an AsyncFor/AsyncWith header is
    itself an implicit await), not crossing nested defs or suites."""
    if isinstance(stmt, (ast.AsyncFor, ast.AsyncWith)):
        yield stmt
        return
    for node in _header_walk(stmt):
        if isinstance(node, ast.Await):
            yield node


# ---------------------------------------------------------------------------
# Reaching definitions
# ---------------------------------------------------------------------------


def reaching_defs(cfg: CFG) -> Dict[int, Dict[str, Tuple[ast.AST, ...]]]:
    """For each node index, a map of local name -> the assignment value
    expressions that may reach it. Only simple ``name = expr`` assignments
    define names (aug-assign, for-targets, etc. map to ``()`` = unknown)."""
    # encode facts as frozenset of (name, def_key); def registry on the side
    defs_at: Dict[int, Dict[str, Tuple]] = {}
    registry: Dict[int, Tuple[str, Optional[ast.AST]]] = {}
    by_stmt: Dict[int, List[int]] = {}
    kill_names: Dict[int, List[str]] = {}
    next_id = [0]

    def reg(name: str, value: Optional[ast.AST]) -> int:
        next_id[0] += 1
        registry[next_id[0]] = (name, value)
        return next_id[0]

    for i, stmt in enumerate(cfg.nodes):
        gen: List[int] = []
        kills: List[str] = []
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            name = stmt.targets[0].id
            gen.append(reg(name, stmt.value))
            kills.append(name)
        else:
            # any other binding of a plain name makes it "unknown" — but only
            # bindings owned by THIS node (a compound header's suites are
            # separate CFG nodes with their own gen/kill)
            targets = []
            if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                targets = [stmt.target]
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                targets = [stmt.target]
            elif isinstance(stmt, ast.Assign):
                targets = stmt.targets
            for sub in _header_walk(stmt):
                if isinstance(sub, (ast.NamedExpr,)):
                    targets = targets + [sub.target]
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        gen.append(reg(n.id, None))
                        kills.append(n.id)
        by_stmt[i] = gen
        kill_names[i] = kills

    def transfer(stmt: ast.stmt, in_set: FrozenSet) -> FrozenSet:
        i = _index_of[id(stmt)]
        out = {f for f in in_set if registry[f][0] not in kill_names[i]}
        out.update(by_stmt[i])
        return frozenset(out)

    _index_of = {id(s): i for i, s in enumerate(cfg.nodes)}
    IN = forward_may(cfg, transfer)
    for i in range(len(cfg.nodes)):
        env: Dict[str, Tuple] = {}
        for f in IN[i]:
            name, value = registry[f]
            env.setdefault(name, ())
            env[name] = env[name] + (value,)
        defs_at[i] = env
    return defs_at

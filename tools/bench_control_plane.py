"""Control-plane bench: actor creates/s, tasks/s, lease-grant latency.

The companion to tools/stress.py for the provisioning plane (ISSUE 8 /
ROADMAP "control-plane throughput"): measures the paths the zygote prefork
pool + batched lease grants attack, and can run the same envelope with the
pool DISABLED (cold subprocess spawns, the STRESS_r05 configuration) to
show the ratio on one host.

Usage:
  python tools/bench_control_plane.py [--nodes 2] [--actors 40]
      [--tasks 4000] [--lease-samples 50] [--drivers 4] [--out FILE]
  python tools/bench_control_plane.py --compare --out STRESS_r06.json
      # runs warm then cold in fresh interpreters, emits both + speedups

``--drivers K`` adds a multi-driver task phase: K driver PROCESSES submit
against the same cluster concurrently (the reference runtime's shape —
ownership is per-driver by design, PAPER.md L2), reporting per-driver and
aggregate tasks/s. This is the number that proves the cluster side scales
past the single-owner submission ceiling.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("RAY_TPU_JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

COLD_ENV = {
    # the STRESS_r05 configuration: every lease miss pays a cold
    # interpreter+import spawn, no zygote, no warm pool, no prestart
    "RAY_TPU_WORKER_ZYGOTE_ENABLED": "0",
    "RAY_TPU_WORKER_POOL_WARM_TARGET": "0",
    "RAY_TPU_PRESTART_WORKERS": "0",
}


def phase_actors(total: int) -> dict:
    import ray_tpu

    @ray_tpu.remote(num_cpus=0.1)
    class _A:
        def ping(self):
            return 1

    t0 = time.perf_counter()
    actors = [_A.remote() for _ in range(total)]
    ray_tpu.get([a.ping.remote() for a in actors], timeout=3600.0)
    created = time.perf_counter() - t0
    t1 = time.perf_counter()
    ray_tpu.get([a.ping.remote() for a in actors], timeout=600.0)
    call_round = time.perf_counter() - t1
    for a in actors:
        ray_tpu.kill(a)
    return {"actors": total,
            "actor_create_wall_s": round(created, 2),
            "actor_creates_per_s": round(total / created, 2),
            "actor_call_round_s": round(call_round, 3)}


def phase_tasks(total: int, window: int = 1000) -> dict:
    import ray_tpu

    @ray_tpu.remote(num_cpus=0.1)
    def _noop(i):
        return i

    t0 = time.perf_counter()
    in_flight = [_noop.remote(i) for i in range(min(window, total))]
    submitted = len(in_flight)
    completed = 0
    while in_flight:
        ready, in_flight = ray_tpu.wait(
            in_flight, num_returns=min(len(in_flight), 100), timeout=300.0)
        completed += len(ready)
        while submitted < total and len(in_flight) < window:
            in_flight.append(_noop.remote(submitted))
            submitted += 1
    dt = time.perf_counter() - t0
    assert completed == total, (completed, total)
    out = {"tasks": total, "tasks_wall_s": round(dt, 2),
           "tasks_per_s": round(total / dt, 1)}
    try:
        from ray_tpu._private.worker import _global_worker

        stats = _global_worker.submit_stats()
        out["submit_per_task_us"] = stats["per_submit_us"]
        out["submit_fast_path_frac"] = round(
            stats["fast_path"] / max(1, stats["count"]), 3)
        out["submit_kickoff_wakeups"] = stats["kickoff_wakeups"]
        out["submit_spec_frames"] = stats["spec_frames"]
    except Exception:
        pass  # client/local modes have no core-worker submit stats
    return out


def phase_tasks_multidriver(drivers: int, total: int, address: str) -> dict:
    """Fork `drivers` driver processes against the running cluster, each
    submitting total/drivers no-op tasks. Aggregate tasks/s is measured
    over the union window (first start to last finish), so driver skew
    counts against it."""
    per = max(1, total // drivers)
    procs = []
    for i in range(drivers):
        out_path = f"/tmp/_bench_cp_driver{i}_{os.getpid()}.json"
        cmd = [sys.executable, os.path.abspath(__file__), "--child-driver",
               "--address", address, "--tasks", str(per), "--out", out_path]
        procs.append((subprocess.Popen(cmd), out_path))
    results = []
    for proc, out_path in procs:
        rc = proc.wait(timeout=1800)
        assert rc == 0, f"driver subprocess failed (rc={rc})"
        with open(out_path) as f:
            results.append(json.load(f))
        os.unlink(out_path)
    window = max(r["t1"] for r in results) - min(r["t0"] for r in results)
    agg = round(per * drivers / window, 1)
    return {
        "drivers": drivers,
        "multidriver_tasks": per * drivers,
        "multidriver_window_s": round(window, 2),
        "per_driver_tasks_per_s": [r["tasks_per_s"] for r in results],
        "aggregate_tasks_per_s": agg,
        "driver_submit_per_task_us": results[0].get("submit_per_task_us"),
    }


def child_driver(address: str, tasks: int, out_path: str):
    """One forked driver of the multi-driver phase: connect, submit, report
    wall-clock endpoints (time.time() — comparable across processes)."""
    import ray_tpu

    ray_tpu.init(address=address)
    try:
        t0 = time.time()
        result = phase_tasks(tasks)
        result["t0"], result["t1"] = t0, time.time()
        with open(out_path, "w") as f:
            json.dump(result, f)
    finally:
        ray_tpu.shutdown()


def phase_lease_latency(samples: int) -> dict:
    """Direct RequestWorkerLease/Return round trips against the local
    raylet: grant latency with a warm pool is adoption cost; cold it is a
    full worker spawn. Also measures the multi-grant form (count=8)."""
    from ray_tpu._private import wire
    from ray_tpu._private.rpc import RetryingRpcClient
    from ray_tpu._private.worker import _global_worker as core

    client = RetryingRpcClient(core.raylet_address)

    async def one(count=1):
        t0 = time.perf_counter()
        reply = wire.loads(await client.call("RequestWorkerLease", wire.dumps(
            {"resources": {"CPU": 0.1}, "job_id": None, "count": count}),
            timeout=120.0))
        dt = time.perf_counter() - t0
        assert reply["status"] == "granted", reply
        grants = [reply] + (reply.get("extra_grants") or [])
        for g in grants:
            await client.call("ReturnWorkerLease", wire.dumps(
                {"lease_id": g["lease_id"]}))
        return dt, len(grants)

    lat = []
    for _ in range(samples):
        dt, _n = core._run(one(), 180.0)
        lat.append(dt)
    lat.sort()
    _, batch = core._run(one(count=8), 180.0)
    core._run(client.close(), 30.0)
    return {
        "lease_samples": samples,
        "lease_grant_p50_ms": round(lat[len(lat) // 2] * 1000, 2),
        "lease_grant_p95_ms": round(lat[int(len(lat) * 0.95)] * 1000, 2),
        "lease_multigrant_count8": batch,
    }


def pool_stats() -> dict:
    from ray_tpu.util.state import get_node_stats, list_nodes

    out = {}
    for n in list_nodes():
        if not n["alive"]:
            continue
        stats = get_node_stats(n["address"])
        out[n["node_id"][:10]] = stats.get("worker_pool", {})
    return out


def run(nodes: int, actors: int, tasks: int, lease_samples: int,
        drivers: int = 1) -> dict:
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    wall0 = time.perf_counter()
    cluster = Cluster(initialize_head=True,
                      head_node_args={"resources": {"CPU": 8.0}})
    for _ in range(nodes - 1):
        cluster.add_node(resources={"CPU": 8.0})
    ray_tpu.init(address=cluster.address)
    try:
        from ray_tpu.util.state import list_nodes

        deadline = time.time() + 120
        while time.time() < deadline:
            if len([n for n in list_nodes() if n["alive"]]) >= nodes:
                break
            time.sleep(0.2)
        result = {"nodes": nodes,
                  "mode": "cold" if os.environ.get(
                      "RAY_TPU_WORKER_ZYGOTE_ENABLED") == "0" else "warm"}
        result.update(phase_lease_latency(lease_samples))
        print(f"[bench] lease p50 {result['lease_grant_p50_ms']}ms",
              flush=True)
        result.update(phase_actors(actors))
        print(f"[bench] actors: {result['actor_creates_per_s']}/s", flush=True)
        result.update(phase_tasks(tasks))
        print(f"[bench] tasks: {result['tasks_per_s']}/s", flush=True)
        if drivers > 1:
            result.update(phase_tasks_multidriver(
                drivers, tasks, cluster.address))
            print(f"[bench] multidriver x{drivers}: "
                  f"{result['aggregate_tasks_per_s']}/s aggregate", flush=True)
        result["worker_pools"] = pool_stats()
        result["total_wall_s"] = round(time.perf_counter() - wall0, 2)
        return result
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def compare(args) -> dict:
    """Run warm and cold in fresh interpreters (env must be set before the
    cluster boots; children inherit)."""
    out = {}
    for mode in ("warm", "cold"):
        env = dict(os.environ)
        if mode == "cold":
            env.update(COLD_ENV)
        tmp = f"/tmp/_bench_cp_{mode}.json"
        cmd = [sys.executable, os.path.abspath(__file__),
               "--nodes", str(args.nodes), "--actors", str(args.actors),
               "--tasks", str(args.tasks),
               "--lease-samples", str(args.lease_samples), "--out", tmp]
        print(f"[bench] === {mode} run ===", flush=True)
        subprocess.run(cmd, env=env, check=True, timeout=3600)
        with open(tmp) as f:
            out[mode] = json.load(f)
    out["speedup_actor_creates"] = round(
        out["warm"]["actor_creates_per_s"]
        / max(out["cold"]["actor_creates_per_s"], 1e-9), 1)
    out["speedup_tasks"] = round(
        out["warm"]["tasks_per_s"] / max(out["cold"]["tasks_per_s"], 1e-9), 2)
    out["speedup_lease_p50"] = round(
        out["cold"]["lease_grant_p50_ms"]
        / max(out["warm"]["lease_grant_p50_ms"], 1e-9), 1)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=2)
    ap.add_argument("--actors", type=int, default=40)
    ap.add_argument("--tasks", type=int, default=4000)
    ap.add_argument("--lease-samples", type=int, default=50)
    ap.add_argument("--drivers", type=int, default=1,
                    help="run a K-driver-process task phase against the "
                         "same cluster and report aggregate tasks/s")
    ap.add_argument("--compare", action="store_true",
                    help="run warm AND cold (fresh interpreters), emit both")
    ap.add_argument("--child-driver", action="store_true",
                    help=argparse.SUPPRESS)  # internal: multidriver child
    ap.add_argument("--address", default="")
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    if args.child_driver:
        child_driver(args.address, args.tasks, args.out)
        return
    if args.compare:
        result = compare(args)
    else:
        result = run(args.nodes, args.actors, args.tasks, args.lease_samples,
                     args.drivers)
    result["argv"] = sys.argv[1:]
    print(json.dumps(result, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)


if __name__ == "__main__":
    main()

"""Control-plane bench: actor creates/s, tasks/s, lease-grant latency.

The companion to tools/stress.py for the provisioning plane (ISSUE 8 /
ROADMAP "control-plane throughput"): measures the paths the zygote prefork
pool + batched lease grants attack, and can run the same envelope with the
pool DISABLED (cold subprocess spawns, the STRESS_r05 configuration) to
show the ratio on one host.

Usage:
  python tools/bench_control_plane.py [--nodes 2] [--actors 40]
      [--tasks 4000] [--lease-samples 50] [--out FILE]
  python tools/bench_control_plane.py --compare --out STRESS_r06.json
      # runs warm then cold in fresh interpreters, emits both + speedups
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("RAY_TPU_JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

COLD_ENV = {
    # the STRESS_r05 configuration: every lease miss pays a cold
    # interpreter+import spawn, no zygote, no warm pool, no prestart
    "RAY_TPU_WORKER_ZYGOTE_ENABLED": "0",
    "RAY_TPU_WORKER_POOL_WARM_TARGET": "0",
    "RAY_TPU_PRESTART_WORKERS": "0",
}


def phase_actors(total: int) -> dict:
    import ray_tpu

    @ray_tpu.remote(num_cpus=0.1)
    class _A:
        def ping(self):
            return 1

    t0 = time.perf_counter()
    actors = [_A.remote() for _ in range(total)]
    ray_tpu.get([a.ping.remote() for a in actors], timeout=3600.0)
    created = time.perf_counter() - t0
    t1 = time.perf_counter()
    ray_tpu.get([a.ping.remote() for a in actors], timeout=600.0)
    call_round = time.perf_counter() - t1
    for a in actors:
        ray_tpu.kill(a)
    return {"actors": total,
            "actor_create_wall_s": round(created, 2),
            "actor_creates_per_s": round(total / created, 2),
            "actor_call_round_s": round(call_round, 3)}


def phase_tasks(total: int, window: int = 1000) -> dict:
    import ray_tpu

    @ray_tpu.remote(num_cpus=0.1)
    def _noop(i):
        return i

    t0 = time.perf_counter()
    in_flight = [_noop.remote(i) for i in range(min(window, total))]
    submitted = len(in_flight)
    completed = 0
    while in_flight:
        ready, in_flight = ray_tpu.wait(
            in_flight, num_returns=min(len(in_flight), 100), timeout=300.0)
        completed += len(ready)
        while submitted < total and len(in_flight) < window:
            in_flight.append(_noop.remote(submitted))
            submitted += 1
    dt = time.perf_counter() - t0
    assert completed == total, (completed, total)
    return {"tasks": total, "tasks_wall_s": round(dt, 2),
            "tasks_per_s": round(total / dt, 1)}


def phase_lease_latency(samples: int) -> dict:
    """Direct RequestWorkerLease/Return round trips against the local
    raylet: grant latency with a warm pool is adoption cost; cold it is a
    full worker spawn. Also measures the multi-grant form (count=8)."""
    from ray_tpu._private import wire
    from ray_tpu._private.rpc import RetryingRpcClient
    from ray_tpu._private.worker import _global_worker as core

    client = RetryingRpcClient(core.raylet_address)

    async def one(count=1):
        t0 = time.perf_counter()
        reply = wire.loads(await client.call("RequestWorkerLease", wire.dumps(
            {"resources": {"CPU": 0.1}, "job_id": None, "count": count}),
            timeout=120.0))
        dt = time.perf_counter() - t0
        assert reply["status"] == "granted", reply
        grants = [reply] + (reply.get("extra_grants") or [])
        for g in grants:
            await client.call("ReturnWorkerLease", wire.dumps(
                {"lease_id": g["lease_id"]}))
        return dt, len(grants)

    lat = []
    for _ in range(samples):
        dt, _n = core._run(one(), 180.0)
        lat.append(dt)
    lat.sort()
    _, batch = core._run(one(count=8), 180.0)
    core._run(client.close(), 30.0)
    return {
        "lease_samples": samples,
        "lease_grant_p50_ms": round(lat[len(lat) // 2] * 1000, 2),
        "lease_grant_p95_ms": round(lat[int(len(lat) * 0.95)] * 1000, 2),
        "lease_multigrant_count8": batch,
    }


def pool_stats() -> dict:
    from ray_tpu.util.state import get_node_stats, list_nodes

    out = {}
    for n in list_nodes():
        if not n["alive"]:
            continue
        stats = get_node_stats(n["address"])
        out[n["node_id"][:10]] = stats.get("worker_pool", {})
    return out


def run(nodes: int, actors: int, tasks: int, lease_samples: int) -> dict:
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    wall0 = time.perf_counter()
    cluster = Cluster(initialize_head=True,
                      head_node_args={"resources": {"CPU": 8.0}})
    for _ in range(nodes - 1):
        cluster.add_node(resources={"CPU": 8.0})
    ray_tpu.init(address=cluster.address)
    try:
        from ray_tpu.util.state import list_nodes

        deadline = time.time() + 120
        while time.time() < deadline:
            if len([n for n in list_nodes() if n["alive"]]) >= nodes:
                break
            time.sleep(0.2)
        result = {"nodes": nodes,
                  "mode": "cold" if os.environ.get(
                      "RAY_TPU_WORKER_ZYGOTE_ENABLED") == "0" else "warm"}
        result.update(phase_lease_latency(lease_samples))
        print(f"[bench] lease p50 {result['lease_grant_p50_ms']}ms",
              flush=True)
        result.update(phase_actors(actors))
        print(f"[bench] actors: {result['actor_creates_per_s']}/s", flush=True)
        result.update(phase_tasks(tasks))
        print(f"[bench] tasks: {result['tasks_per_s']}/s", flush=True)
        result["worker_pools"] = pool_stats()
        result["total_wall_s"] = round(time.perf_counter() - wall0, 2)
        return result
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def compare(args) -> dict:
    """Run warm and cold in fresh interpreters (env must be set before the
    cluster boots; children inherit)."""
    out = {}
    for mode in ("warm", "cold"):
        env = dict(os.environ)
        if mode == "cold":
            env.update(COLD_ENV)
        tmp = f"/tmp/_bench_cp_{mode}.json"
        cmd = [sys.executable, os.path.abspath(__file__),
               "--nodes", str(args.nodes), "--actors", str(args.actors),
               "--tasks", str(args.tasks),
               "--lease-samples", str(args.lease_samples), "--out", tmp]
        print(f"[bench] === {mode} run ===", flush=True)
        subprocess.run(cmd, env=env, check=True, timeout=3600)
        with open(tmp) as f:
            out[mode] = json.load(f)
    out["speedup_actor_creates"] = round(
        out["warm"]["actor_creates_per_s"]
        / max(out["cold"]["actor_creates_per_s"], 1e-9), 1)
    out["speedup_tasks"] = round(
        out["warm"]["tasks_per_s"] / max(out["cold"]["tasks_per_s"], 1e-9), 2)
    out["speedup_lease_p50"] = round(
        out["cold"]["lease_grant_p50_ms"]
        / max(out["warm"]["lease_grant_p50_ms"], 1e-9), 1)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=2)
    ap.add_argument("--actors", type=int, default=40)
    ap.add_argument("--tasks", type=int, default=4000)
    ap.add_argument("--lease-samples", type=int, default=50)
    ap.add_argument("--compare", action="store_true",
                    help="run warm AND cold (fresh interpreters), emit both")
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    if args.compare:
        result = compare(args)
    else:
        result = run(args.nodes, args.actors, args.tasks, args.lease_samples)
    result["argv"] = sys.argv[1:]
    print(json.dumps(result, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)


if __name__ == "__main__":
    main()

"""Overlapped-train-step microbenchmark (``python -m tools.bench_train``).

Prices the PR 12 train-step flavors against each other on whatever
devices are present (the 8-device CPU mesh in CI; the TPU slice on
hardware), so BENCH rounds can attribute MFU movement to a phase:

* ``fused_step_us``           — the single fused program, unsharded
  (the 1-replica fallback / pre-PR-12 path)
* ``fused_sharded_step_us``   — ONE program with the cross-replica
  sharded optimizer update (reduce-scatter grads, 1/N opt state,
  all-gather params; XLA async collectives overlap them with compute)
* ``split_sharded_step_us``   — the phase-split flavor (fwd_bwd with
  reduce-scattered grads + sharded opt program): the difference against
  ``fused_sharded_step_us`` is the comm time a program boundary exposes
* ``traced_sharded_step_us``  — the explicit bucketed pipeline the traced
  tier runs (per-bucket reduce programs + spans)
* ``bucket_plan``             — the layer-order bucket plan stats
* ``opt_state_bytes_per_replica`` / ``opt_state_bytes_total``
* ``reducer_allreduce_mb_s``  — AsyncBucketReducer throughput through the
  CPU collective tier (single-process rank-0 loopback)

Emits one JSON object on stdout (plus ``--out FILE``).
"""

from __future__ import annotations

import argparse
import json
import os
import time


def _mesh_and_bundle(bucket_bytes: int):
    import jax

    from ray_tpu.models import CONFIGS
    from ray_tpu.parallel import TrainStepBundle, create_mesh, make_optimizer

    devs = jax.devices()
    n = len(devs)
    mesh = create_mesh({"data": n, "fsdp": 1, "seq": 1, "tensor": 1,
                        "expert": 1}, devices=devs)
    factory = lambda spec_fn: make_optimizer(  # noqa: E731
        learning_rate=1e-3, warmup_steps=5, total_steps=1000,
        clip_spec_fn=spec_fn)
    bundle = TrainStepBundle(CONFIGS["tiny"], mesh,
                             optimizer_factory=factory,
                             shard_update=n > 1, bucket_bytes=bucket_bytes)
    return bundle, n


def _time_steps(fn, init, batch, steps, warmup):
    import jax

    params, opt_state = init()
    for _ in range(warmup):
        params, opt_state, loss = fn(params, opt_state, batch)
        jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = fn(params, opt_state, batch)
    jax.block_until_ready(loss)
    return (time.perf_counter() - t0) / steps * 1e6, (params, opt_state)


def bench_step_flavors(bucket_bytes: int, steps: int = 10,
                       warmup: int = 3) -> dict:
    """One bucketed+sharded step of every flavor under JAX_PLATFORMS=cpu
    is ALSO the tier-1 smoke path (tests/test_train_smoke.py) — keep this
    callable cheap and hardware-free."""
    import jax
    import numpy as np

    from ray_tpu.util import tracing

    out = {}
    bundle, n = _mesh_and_bundle(bucket_bytes)
    out["n_devices"] = n
    batch = bundle.make_batch(np.random.default_rng(0), 2 * n, 64)

    out["fused_step_us"], _ = _time_steps(
        lambda p, s, b: bundle._fused_step(p, s, b),
        lambda: bundle.init(jax.random.PRNGKey(0)), batch, steps, warmup)
    if bundle.shard_update:
        out["fused_sharded_step_us"], (ps, ss) = _time_steps(
            lambda p, s, b: bundle._fused_step_sharded(p, s, b),
            lambda: bundle.init_sharded(jax.random.PRNGKey(0)),
            batch, steps, warmup)

        def split(p, s, b):
            loss, g = bundle._fwd_bwd_rs(p, b)
            p, s = bundle._opt_apply_sharded(g, s, p)
            return p, s, loss

        out["split_sharded_step_us"], _ = _time_steps(
            split, lambda: bundle.init_sharded(jax.random.PRNGKey(0)),
            batch, steps, warmup)
        was_enabled = tracing.enabled()
        tracing.enable()
        try:
            out["traced_sharded_step_us"], _ = _time_steps(
                lambda p, s, b: bundle.step(p, s, b),
                lambda: bundle.init_sharded(jax.random.PRNGKey(0)),
                batch, max(steps // 2, 1), warmup)
        finally:
            if not was_enabled:
                tracing._enabled = False
                os.environ.pop("RAY_TPU_ENABLE_TRACING", None)
        out["opt_state_bytes_per_replica"] = \
            bundle.opt_state_bytes_per_replica(ss)
        out["opt_state_bytes_total"] = bundle.opt_state_bytes_total()
        out["bucket_plan"] = bundle.bucket_plan.stats()
    return out


def bench_reducer(mb: int = 8, compression=None) -> dict:
    """AsyncBucketReducer throughput on a world-size-1 loopback group
    (prices the pack/unpack + thread handoff floor, no network). With
    ``compression`` the same tree rides the quantized path — the wire
    accounting (``*_wire_reduction_x``) is the fp32-vs-quantized byte
    ratio the ISSUE acceptance bar reads."""
    import numpy as np

    from ray_tpu import collective as col
    from ray_tpu.collective.bucketed import (AsyncBucketReducer, leaf_meta,
                                             plan_buckets)

    tag = compression or "fp32"
    group = f"bench_train.reducer.{tag}"
    tree = {f"leaf{i}": np.random.default_rng(i).normal(
        size=(mb * 1024, 128)).astype(np.float32) for i in range(2)}
    col.init_collective_group(1, 0, backend="cpu", group_name=group)
    plan = plan_buckets(leaf_meta(tree), bucket_bytes=4 << 20, world_size=1)
    red = AsyncBucketReducer(group, plan, compression=compression)
    prefix = "reducer" if compression is None else f"reducer_{compression}"
    try:
        red.reduce_tree(tree)  # warm
        nbytes = sum(a.nbytes for a in tree.values())
        t0 = time.perf_counter()
        iters = 3
        for _ in range(iters):
            red.reduce_tree(tree)
        dt = (time.perf_counter() - t0) / iters
    finally:
        red.shutdown()
        col.destroy_collective_group(group)
    out = {f"{prefix}_allreduce_mb_s": nbytes / dt / 1e6,
           f"{prefix}_buckets": plan.num_buckets}
    if compression is not None:
        ws = red.wire_stats()
        out[f"{prefix}_wire_bytes"] = ws["bytes_wire"]
        out[f"{prefix}_fp32_bytes"] = ws["bytes_fp32_equiv"]
        out[f"{prefix}_wire_reduction_x"] = ws.get("wire_reduction_x", 0.0)
        out[f"{prefix}_encode_s_per_iter"] = round(
            ws["encode_s"] / (iters + 1), 5)
    return out


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default="")
    parser.add_argument("--bucket-bytes", type=int, default=1 << 20)
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--skip-reducer", action="store_true")
    parser.add_argument("--compression", default="int8",
                        help="codec for the quantized-reducer pricing "
                             "(int8/fp8/bf16; 'none' skips it)")
    args = parser.parse_args()

    t0 = time.time()
    result = bench_step_flavors(args.bucket_bytes, steps=args.steps)
    if not args.skip_reducer:
        import ray_tpu

        started = not ray_tpu.is_initialized()
        if started:
            ray_tpu.init(num_cpus=2)
        try:
            result.update(bench_reducer())
            if args.compression and args.compression != "none":
                result.update(bench_reducer(compression=args.compression))
        finally:
            if started:
                ray_tpu.shutdown()
    result["wall_s"] = round(time.time() - t0, 1)
    blob = json.dumps(result, indent=2, default=str)
    print(blob)
    if args.out:
        with open(args.out, "w") as f:
            f.write(blob)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

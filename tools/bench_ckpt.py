"""Checkpoint-plane microbenchmark (``python -m tools.bench_ckpt``).

Measures what the checkpoint plane costs and saves, so future rounds can
hold the line on "a save never stalls the step":

* ``blocking_save_ms``       — synchronous save of the benchmark state
* ``async_pause_ms``         — the step-side pause of an async save
                               (snapshot only; writes happen off-thread)
* ``step_overhead_pct_*``    — simulated train-loop slowdown vs the
                               no-checkpoint baseline, blocking vs async
* ``dedup_ratio``            — chunk bytes reused when re-saving a state
                               with only 1/8 of its leaves changed
* ``incremental_save_ms``    — wall time of that mostly-deduped save
* ``restore_mb_s``           — full-tree restore throughput
* ``shard_restore_mb_s``     — per-host sharded restore throughput (4->2
                               reshard through the planner)

``--tier`` adds the storage-tier plane (ckpt/tier) on a latency-shimmed
bucket backend (FaultShim, 5 ms/op — an object store across a DC hop):

* ``tier_mirror_mb_s``             — first mirror (all chunks upload)
* ``tier_mirror_dedup_ratio``      — re-mirror after a 1/8 delta: bytes
                                     skipped by content-address dedup
* ``tier_restore_parallel_mb_s``   — restore-from-remote, local pool
                                     evicted, parallel chunk IO
* ``tier_restore_serial_mb_s``     — same restore forced single-thread
* ``tier_parallel_speedup``        — parallel / serial (gate: >= 2x)

Emits one JSON object on stdout (plus --out FILE) so CKPT rounds can
track regressions (tools/benchtrack.py family "CKPT"). No cluster
needed.
"""

from __future__ import annotations

import argparse
import json
import shutil
import tempfile
import time


def _state(num_leaves: int, leaf_elems: int):
    import numpy as np

    # content-distinct leaves: content addressing dedups identical bytes
    # ACROSS leaves too, which would make an all-zeros benchmark state
    # report a fantasy dedup ratio
    return {f"layer{i:02d}": {
        "w": np.arange(leaf_elems, dtype=np.float32) * 0.37 + i,
        "b": np.arange(leaf_elems // 64, dtype=np.float32) * (i + 1),
    } for i in range(num_leaves)}


def _mb(tree) -> float:
    import numpy as np

    total = 0
    for sub in tree.values():
        for arr in sub.values():
            total += np.asarray(arr).nbytes
    return total / 1e6


def bench_saves(root: str, state, steps: int = 4, step_s: float = 0.1):
    """Simulated train loop: baseline / blocking saves / async saves."""
    from ray_tpu import ckpt

    def loop(save_fn):
        t0 = time.perf_counter()
        for i in range(steps):
            for sub in state.values():
                sub["w"] += 1.0  # full mutation: dedup cannot help
            time.sleep(step_s)
            if save_fn:
                save_fn(i)
        return time.perf_counter() - t0

    baseline_s = loop(None)

    bstore = ckpt.CheckpointStore(f"{root}/blocking")
    tb = []

    def _blocking(i):
        t = time.perf_counter()
        ckpt.save_checkpoint(bstore, state, step=i)
        tb.append(time.perf_counter() - t)

    blocking_s = loop(_blocking)

    astore = ckpt.CheckpointStore(f"{root}/async")
    saver = ckpt.CheckpointSaver(astore)
    ta = []

    def _async(i):
        t = time.perf_counter()
        saver.save(state, step=i)
        ta.append(time.perf_counter() - t)

    async_s = loop(_async)
    saver.wait()
    return {
        "state_mb": round(_mb(state), 2),
        "steps": steps,
        "blocking_save_ms": round(1e3 * sorted(tb)[len(tb) // 2], 3),
        "async_pause_ms": round(1e3 * sorted(ta)[len(ta) // 2], 3),
        "step_overhead_pct_blocking": round(
            100.0 * (blocking_s - baseline_s) / baseline_s, 1),
        "step_overhead_pct_async": round(
            100.0 * (async_s - baseline_s) / baseline_s, 1),
    }


def bench_dedup(root: str, state):
    from ray_tpu import ckpt

    store = ckpt.CheckpointStore(f"{root}/dedup")
    ckpt.save_checkpoint(store, state, step=1)
    # touch 1/8 of the layers (a fractional delta no other layer's content
    # collides with); the rest dedups to existing chunks
    keys = sorted(state)
    for k in keys[: max(1, len(keys) // 8)]:
        state[k]["w"] += 0.25
    t0 = time.perf_counter()
    manifest = ckpt.save_checkpoint(store, state, step=2)
    dt = time.perf_counter() - t0
    return {
        "incremental_save_ms": round(1e3 * dt, 3),
        "dedup_ratio": round(manifest.stats["dedup_ratio"], 4),
        "bytes_written": manifest.stats["bytes_written"],
        "bytes_reused": manifest.stats["bytes_reused"],
    }


def bench_restore(root: str, state):
    from ray_tpu import ckpt
    from ray_tpu.train.scaling_policy import mesh_spec_for
    from ray_tpu.weights.spec import ShardedTreeSpec

    store = ckpt.CheckpointStore(f"{root}/restore")
    manifest = ckpt.save_checkpoint(store, state, step=1)
    t0 = time.perf_counter()
    tree = ckpt.restore_tree(store)
    full_s = time.perf_counter() - t0
    mb = _mb(tree)

    # sharded flavor: save dim-0-sharded over 4 ranks, restore rank 0 of 2
    import numpy as np

    flat = {f"{k}/w": np.tile(sub["w"], (8, 1)) for k, sub in state.items()}
    spec4 = ShardedTreeSpec(
        mesh=mesh_spec_for(4),
        parts={p: ("data", None) for p in flat},
        meta={p: (a.shape, a.dtype.str) for p, a in flat.items()})
    m2 = ckpt.save_checkpoint(store, flat, step=2, spec=spec4)
    dst = ShardedTreeSpec(
        mesh=mesh_spec_for(2),
        parts={p: ("data", None) for p in flat},
        meta=dict(spec4.meta))
    t0 = time.perf_counter()
    _shards, stats = ckpt.restore_shards(store, dst, "rank0", m2.ckpt_id)
    shard_s = time.perf_counter() - t0
    return {
        "restore_mb": round(mb, 2),
        "restore_mb_s": round(mb / full_s, 1),
        "shard_restore_mb_s": round(stats["bytes_read"] / 1e6 / shard_s, 1),
        "shard_no_gather": stats["no_gather"],
        "manifest_chunks": len(manifest.chunk_set()),
    }


def bench_tier(root: str, state, threads: int = 8,
               latency_s: float = 0.005):
    """Storage-tier plane: mirror throughput + cross-step upload dedup,
    then restore-from-remote (local pool evicted) parallel vs serial
    through a latency-shimmed bucket backend."""
    from ray_tpu import ckpt

    shim = ckpt.FaultShim(ckpt.DirBucketClient(f"{root}/bucket"),
                          latency_s=latency_s)

    def _attach(n):
        return ckpt.TieredStore(f"{root}/tier", name="bench-tier",
                                mirror=False,
                                backend=ckpt.BucketBackend(shim),
                                io_threads=n)

    store = _attach(threads)
    man1 = ckpt.save_checkpoint(store, state, step=1)
    mb = _mb(state)
    t0 = time.perf_counter()
    store.mirror_now(man1.ckpt_id)
    mirror_s = time.perf_counter() - t0

    # step 2 touches 1/8 of the layers: the re-mirror uploads only the
    # changed chunks, content addressing dedups the rest
    keys = sorted(state)
    for k in keys[: max(1, len(keys) // 8)]:
        state[k]["w"] += 0.25
    man2 = ckpt.save_checkpoint(store, state, step=2)
    c2 = store.mirror_now(man2.ckpt_id)
    moved = c2["upload_bytes"] + c2["dedup_bytes"]

    # evict the local pool: restores now read through the remote tier
    store.evict_local(man1.ckpt_id)
    store.evict_local(man2.ckpt_id)
    t0 = time.perf_counter()
    tree = ckpt.restore_tree(store, man2.ckpt_id)
    par_s = time.perf_counter() - t0
    rmb = _mb(tree)
    # the read-through fetch cached the chunks back locally; drop them
    # again and repeat single-threaded
    store.evict_local(man2.ckpt_id)
    serial = _attach(1)
    t0 = time.perf_counter()
    ckpt.restore_tree(serial, man2.ckpt_id)
    ser_s = time.perf_counter() - t0
    store.close()
    serial.close()
    return {
        "tier_latency_ms_per_op": latency_s * 1e3,
        "tier_io_threads": threads,
        "tier_mirror_mb_s": round(mb / mirror_s, 1),
        "tier_mirror_dedup_ratio": round(c2["dedup_bytes"] / moved, 4)
        if moved else 0.0,
        "tier_delta_upload_bytes": c2["upload_bytes"],
        "tier_restore_parallel_mb_s": round(rmb / par_s, 1),
        "tier_restore_serial_mb_s": round(rmb / ser_s, 1),
        "tier_parallel_speedup": round(ser_s / par_s, 2),
    }


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default="")
    parser.add_argument("--leaves", type=int, default=16)
    parser.add_argument("--leaf-elems", type=int, default=1 << 17)
    parser.add_argument("--steps", type=int, default=4)
    parser.add_argument("--tier", action="store_true",
                        help="add the storage-tier plane benchmarks")
    parser.add_argument("--tier-threads", type=int, default=8)
    args = parser.parse_args(argv)

    root = tempfile.mkdtemp(prefix="bench_ckpt_")
    try:
        out = {"bench": "ckpt"}
        out.update(bench_saves(root, _state(args.leaves, args.leaf_elems),
                               steps=args.steps))
        out.update(bench_dedup(root, _state(args.leaves, args.leaf_elems)))
        out.update(bench_restore(root, _state(args.leaves, args.leaf_elems)))
        if args.tier:
            out.update(bench_tier(root, _state(args.leaves, args.leaf_elems),
                                  threads=args.tier_threads))
    finally:
        shutil.rmtree(root, ignore_errors=True)
    print(json.dumps(out, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=2)
    return out


if __name__ == "__main__":
    main()

#!/usr/bin/env bash
# One-shot lint entry point: run raylint over the runtime with the checked-in
# baseline (exactly what tests/test_raylint.py enforces in tier-1).
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m tools.raylint "$@"

#!/usr/bin/env bash
# One-shot lint entry point: run raylint over the runtime with the checked-in
# baseline (exactly what tests/test_raylint.py enforces in tier-1).
#
# CI contract (asserted by tests/test_raylint.py::test_lint_sh_json_contract):
#   tools/lint.sh --json     machine-readable report on stdout
#   exit 0                   clean (every finding fixed/suppressed/baselined)
#   exit 1                   new findings or stale baseline entries
#   exit 2                   usage error
# Other useful flags pass straight through: --changed (git-diff-scoped run),
# --stats (per-rule timings), --no-graph-cache (cold whole-program build).
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m tools.raylint "$@"

"""Multi-learner RL throughput bench (``python -m tools.bench_rl_learners``).

Produces the RL_MULTILEARNER_r* artifact: PPO CartPole steps/sec at N
learners with the gradient allreduce on the fp32 collective path vs the
quantized (int8 + error-feedback) path — the end-to-end number for the
EQuARX-style compression tier. Also reports final mean episode return per
flavor so a throughput win cannot silently ship a quality regression.

Usage::

    python tools/bench_rl_learners.py [--learners 4] [--iters 8]
        [--compression int8] [--out RL_MULTILEARNER_r06.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_flavor(num_learners: int, iters: int, compression, seed: int = 1,
               num_cpus: int = 8) -> dict:
    import numpy as np

    import ray_tpu
    from ray_tpu.rl import PPOConfig

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=num_cpus)
    algo = PPOConfig(
        env="CartPole-v1",
        num_env_runners=2,
        num_envs_per_runner=4,
        rollout_length=128,
        epochs=8,
        num_learners=num_learners,
        grad_compression=compression,
        seed=seed,
    ).build()
    sps, returns = [], []
    try:
        algo.train()  # warm: compile + actor spin-up out of the window
        for _ in range(iters):
            m = algo.train()
            sps.append(m["steps_per_sec"])
            returns.append(m["episode_return_mean"])
    finally:
        algo.stop()
        ray_tpu.shutdown()
    return {
        "steps_per_sec": round(float(np.median(sps)), 1),
        "steps_per_sec_mean": round(float(np.mean(sps)), 1),
        "episode_return_final": round(float(returns[-1]), 1),
        "loss_metric_iters": iters,
    }


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--learners", type=int, default=4)
    parser.add_argument("--iters", type=int, default=8)
    parser.add_argument("--compression", default="int8")
    parser.add_argument("--num-cpus", type=int, default=8)
    parser.add_argument("--out", default="")
    args = parser.parse_args()

    t0 = time.time()
    n = args.learners
    from ray_tpu.collective.quant import resolve_codec

    codec = resolve_codec(args.compression)
    # analytic per-element both-legs ratio (fp32 = 4 B/el on each leg);
    # matches the reducer's measured wire_stats() at real tree sizes —
    # int8:256 -> 3.94x, fp8 -> 3.94x, bf16 -> 2.0x
    wire_x = round(4.0 / codec.bytes_per_element, 2) if codec else 1.0
    fp32 = run_flavor(n, args.iters, None, num_cpus=args.num_cpus)
    quant = run_flavor(n, args.iters, args.compression,
                       num_cpus=args.num_cpus)
    result = {
        f"sps_num_learners_{n}_fp32": fp32["steps_per_sec"],
        f"sps_num_learners_{n}_{args.compression}": quant["steps_per_sec"],
        "ratio_quant_vs_fp32": round(
            quant["steps_per_sec"] / max(fp32["steps_per_sec"], 1e-9), 3),
        "return_final_fp32": fp32["episode_return_final"],
        f"return_final_{args.compression}": quant["episode_return_final"],
        "detail": {"fp32": fp32, args.compression: quant},
        "wire_reduction_x": wire_x,
        "note": (
            f"PPO CartPole steps/sec, 2 env-runners, {n} learners, CPU CI "
            f"tier: gradient allreduce on the fp32 collective path vs the "
            f"{args.compression} block-quantized path (error-feedback, "
            f"contribute + broadcast legs quantized — {wire_x}x fewer "
            f"wire bytes; see collective/QUANT.md). CPU-tier caveat: the "
            f"'wire' here is same-host shared memory (free), so the SPS "
            f"ratio prices the ENCODE overhead only — the byte reduction "
            f"pays on DCN/ICI-bound multi-host learner groups, where the "
            f"traced tier runs the jitted quantize->all_to_all->dequant "
            f"programs over the real interconnect."),
        "wall_s": round(time.time() - t0, 1),
    }
    blob = json.dumps(result, indent=1)
    print(blob)
    if args.out:
        with open(args.out, "w") as f:
            f.write(blob + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

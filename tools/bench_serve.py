"""Sustained-load serving benchmark (``python -m tools.bench_serve``).

Closes the serving loop end to end: an open-loop Poisson arrival process
(arrivals fire on the clock whether or not earlier requests finished — the
load model that exposes queueing collapse, unlike closed-loop ramps) drives
a streaming token deployment under the demand-driven autoscaler, through
three phases:

* **burst** — high arrival rate; the rate window must price the demand and
  scale UP (``scaled_up``), while a ROLLING weight update runs concurrently
  (redeploy → max-surge-1 replica replacement with drain-before-kill) and
  no request may drop;
* **drain** — low arrival rate; demand decays through the hysteresis band
  and the deployment must scale DOWN (``scaled_down``);
* the controller's transition timeline (reason + window metrics per scale
  action) is captured verbatim into the artifact.

Reported: p50/p99 TTFT (client-observed first streamed token), p50/p99
completion latency, aggregate tokens/s, per-phase arrival rates, the
autoscale transition timeline, and ``dropped_requests`` (acceptance bar:
**zero** across the rolling update). Emits one JSON object on stdout
(plus ``--out FILE``) — checked in as ``SERVE_r01.json``.

``--smoke`` shrinks rates/durations for the tier-1 wrapper
(tests/test_serve_autoscale.py::test_bench_serve_smoke).
"""

from __future__ import annotations

import argparse
import json
import threading
import time

DEPLOYMENT = "bench_serve_tokens"


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def _build_app(serve, *, max_replicas: int, window_s: float,
               downscale_delay_s: float, token_delay_s: float):
    @serve.deployment(
        name=DEPLOYMENT,
        max_ongoing_requests=64,
        autoscaling_config={
            "min_replicas": 1, "max_replicas": max_replicas,
            "target_ongoing_requests": 2.0,
            "upscale_delay_s": 0.5, "downscale_delay_s": downscale_delay_s,
            "window_s": window_s, "scale_cooldown_s": 0.5,
        },
        slo={"queue_target_s": 0.5},
        ray_actor_options={"num_cpus": 0.25})
    class TokenServer:
        def __init__(self, version: int = 0):
            self._weights_version = version

        def update_weights(self, version: int) -> int:
            self._weights_version = version
            return version

        async def __call__(self, body):
            import asyncio

            for _ in range(int(body.get("tokens", 8))):
                await asyncio.sleep(token_delay_s)
            return {"tokens": int(body.get("tokens", 8)),
                    "weights_version": self._weights_version}

        async def stream(self, body):
            import asyncio

            for i in range(int(body.get("tokens", 8))):
                await asyncio.sleep(token_delay_s)
                yield {"token": i, "weights_version": self._weights_version}

    return TokenServer


class _LoadGenerator:
    """Open-loop Poisson client: one dispatcher thread fires requests on
    the drawn arrival clock; each request runs on its own thread so a slow
    response never holds back the arrival process."""

    def __init__(self, ray_tpu, handle, tokens_per_request: int):
        self.ray_tpu = ray_tpu
        self.handle = handle
        self.tokens = tokens_per_request
        self.lock = threading.Lock()
        self.ttft_s: list = []
        self.latency_s: list = []
        self.tokens_out = 0
        self.dropped: list = []
        self._threads: list = []

    def _one(self, stream: bool):
        t0 = time.monotonic()
        body = {"tokens": self.tokens}
        try:
            if stream:
                gen = self.handle.options(
                    method_name="stream", stream=True).remote(body)
                first = self.ray_tpu.get(next(gen), timeout=120)
                ttft = time.monotonic() - t0
                n = 1
                for ref in gen:
                    self.ray_tpu.get(ref, timeout=120)
                    n += 1
                assert first["token"] == 0
            else:
                out = self.ray_tpu.get(self.handle.remote(body), timeout=120)
                ttft = time.monotonic() - t0
                n = out["tokens"]
            latency = time.monotonic() - t0
            with self.lock:
                self.ttft_s.append(ttft)
                self.latency_s.append(latency)
                self.tokens_out += n
        except Exception as e:
            with self.lock:
                self.dropped.append(f"{type(e).__name__}: {e}")

    def run_phase(self, rate_hz: float, duration_s: float, *,
                  stream_every: int = 4, seed: int = 0) -> int:
        """Fire Poisson arrivals at ``rate_hz`` for ``duration_s``; every
        ``stream_every``-th request uses the streaming path (client-observed
        TTFT), the rest the unary path (keeps thread count bounded)."""
        import random as _random

        rng = _random.Random(seed)
        end = time.monotonic() + duration_s
        fired = 0
        next_at = time.monotonic()
        while next_at < end:
            delay = next_at - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            th = threading.Thread(
                target=self._one, args=(fired % stream_every == 0,),
                daemon=True)
            th.start()
            self._threads.append(th)
            fired += 1
            next_at += rng.expovariate(rate_hz)
        return fired

    def join(self, timeout_s: float = 180.0):
        deadline = time.monotonic() + timeout_s
        for th in self._threads:
            th.join(max(0.1, deadline - time.monotonic()))
        still = sum(1 for th in self._threads if th.is_alive())
        if still:
            with self.lock:
                self.dropped.append(f"{still} requests unfinished at join")


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default="")
    parser.add_argument("--smoke", action="store_true",
                        help="tiny rates/durations for the tier-1 wrapper")
    args = parser.parse_args(argv)

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve import api as serve_api

    # demand math: concurrency = rate x (tokens x token_delay_s); the
    # burst must price to >1 replica (target_ongoing=2) and the drain to
    # well under the hysteresis band
    if args.smoke:
        burst_rate, burst_s = 40.0, 4.0
        drain_rate, drain_s = 1.0, 6.0
        settle_s, window_s, downscale_delay_s = 8.0, 4.0, 1.0
        tokens, token_delay_s, max_replicas = 8, 0.02, 3
    else:
        burst_rate, burst_s = 60.0, 8.0
        drain_rate, drain_s = 2.0, 10.0
        settle_s, window_s, downscale_delay_s = 14.0, 5.0, 2.0
        tokens, token_delay_s, max_replicas = 8, 0.02, 6

    ray_tpu.init(num_cpus=8)
    dep = _build_app(serve, max_replicas=max_replicas, window_s=window_s,
                     downscale_delay_s=downscale_delay_s,
                     token_delay_s=token_delay_s)
    handle = serve.run(dep.bind(0), name=DEPLOYMENT)
    ray_tpu.get([handle.remote({"tokens": 1}) for _ in range(4)],
                timeout=120)  # warm

    gen = _LoadGenerator(ray_tpu, handle, tokens)
    t_start = time.time()

    # rolling weight update mid-burst: redeploy with a new init argument
    # (code_version bump → max-surge-1 replica replacement with
    # drain-before-kill) — the zero-drop criterion covers this window
    def _rolling_update():
        time.sleep(burst_s * 0.3)
        serve.run(dep.bind(1), name=DEPLOYMENT)

    updater = threading.Thread(target=_rolling_update, daemon=True)
    updater.start()

    fired_burst = gen.run_phase(burst_rate, burst_s, seed=1)
    updater.join(timeout=60.0)
    fired_drain = gen.run_phase(drain_rate, drain_s, seed=2)
    gen.join()

    # let the window decay so the downscale path fires before read-back
    controller = serve_api._get_controller(create=False)
    deadline = time.monotonic() + settle_s + 30.0
    state = {}
    while time.monotonic() < deadline:
        state = ray_tpu.get(
            controller.get_autoscale_state.remote(DEPLOYMENT), timeout=30)
        if any(t["direction"] == "down" for t in state["transitions"]) \
                and state["target"] == 1:
            break
        time.sleep(0.5)

    wall_s = time.time() - t_start
    ttft = sorted(gen.ttft_s)
    latency = sorted(gen.latency_s)
    transitions = [
        {"t_s": round(t["ts"] - t_start, 3), "from": t["from"],
         "to": t["to"], "direction": t["direction"], "reason": t["reason"],
         "metrics": t["metrics"]}
        for t in state.get("transitions", [])]
    verified = ray_tpu.get(handle.remote({"tokens": 1}), timeout=60)
    out = {
        "mode": "smoke" if args.smoke else "full",
        "requests_fired": fired_burst + fired_drain,
        "requests_completed": len(latency),
        "dropped_requests": len(gen.dropped),
        "dropped_detail": gen.dropped[:10],
        "burst_rate_hz": burst_rate,
        "drain_rate_hz": drain_rate,
        "ttft_p50_ms": (_percentile(ttft, 0.5) or 0) * 1e3,
        "ttft_p99_ms": (_percentile(ttft, 0.99) or 0) * 1e3,
        "latency_p50_ms": (_percentile(latency, 0.5) or 0) * 1e3,
        "latency_p99_ms": (_percentile(latency, 0.99) or 0) * 1e3,
        "tokens_per_s": gen.tokens_out / max(wall_s, 1e-9),
        "tokens_total": gen.tokens_out,
        "scaled_up": any(t["direction"] == "up" for t in transitions),
        "scaled_down": any(t["direction"] == "down" for t in transitions),
        "max_target": max([t["to"] for t in transitions], default=1),
        "final_target": state.get("target"),
        "rolling_update_weights_version": verified["weights_version"],
        "transitions": transitions,
        "final_rollup": state.get("rollup"),
        "wall_s": wall_s,
    }

    serve.delete(DEPLOYMENT)
    ray_tpu.shutdown()

    print(json.dumps(out, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=2)
    return out


if __name__ == "__main__":
    main()

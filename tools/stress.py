"""Control-plane scale/stress harness (reference: release/benchmarks/ —
many_nodes/many_tasks/many_actors/many_pgs + the object-broadcast shape in
release/benchmarks/object_store.py).

Runs the whole envelope on ONE machine: N virtual raylet processes under a
single GCS, then drives tasks / actors / placement groups / a wide object
broadcast through the real two-level scheduler and object plane. Numbers are
committed as STRESS_r{N}.json so every round has envelope evidence, and
`tests/test_stress.py` pins a scaled-down version so regressions fail CI.

Usage: python tools/stress.py [--nodes 16] [--tasks 20000] [--actors 512]
                              [--pgs 100] [--broadcast-mb 100] [--out FILE]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# runnable as `python tools/stress.py` from the repo root: sys.path[0] is
# tools/, so put the repo root (where ray_tpu/ lives) in front
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Light workers: the stress tier never runs device compute, so spawned
# processes must not pay the TPU-plugin import (~3s + 140MB each on the CI
# host). Must happen before the cluster boots; children inherit.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("RAY_TPU_JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import ray_tpu  # noqa: E402
from ray_tpu.cluster_utils import Cluster  # noqa: E402
from ray_tpu.util.placement_group import (placement_group,  # noqa: E402
                                          remove_placement_group)
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy  # noqa: E402


@ray_tpu.remote(num_cpus=1)
def _noop(i):
    return i


@ray_tpu.remote(num_cpus=0.1)
class _StressActor:
    def __init__(self, rank):
        self.rank = rank

    def ping(self):
        return self.rank


@ray_tpu.remote(num_cpus=0.5)
def _consume(blob, rank):
    return (rank, len(blob))


def phase_tasks(total: int, window: int = 2000) -> dict:
    """Submit `total` no-op tasks keeping ~`window` in flight (the reference
    many_tasks shape: sustained pipeline, not one barrier)."""
    t0 = time.perf_counter()
    in_flight = [_noop.remote(i) for i in range(min(window, total))]
    submitted = len(in_flight)
    completed = 0
    while in_flight:
        ready, in_flight = ray_tpu.wait(
            in_flight, num_returns=min(len(in_flight), 100), timeout=300.0)
        completed += len(ready)
        while submitted < total and len(in_flight) < window:
            in_flight.append(_noop.remote(submitted))
            submitted += 1
    dt = time.perf_counter() - t0
    assert completed == total, (completed, total)
    return {"tasks": total, "tasks_wall_s": round(dt, 2),
            "tasks_per_s": round(total / dt, 1)}


def phase_actors(total: int) -> dict:
    t0 = time.perf_counter()
    actors = [_StressActor.remote(i) for i in range(total)]
    ranks = ray_tpu.get([a.ping.remote() for a in actors], timeout=1200.0)
    assert sorted(ranks) == list(range(total))
    created = time.perf_counter() - t0
    # one sync call round per actor, all pipelined
    t1 = time.perf_counter()
    ray_tpu.get([a.ping.remote() for a in actors], timeout=600.0)
    call_round = time.perf_counter() - t1
    for a in actors:
        ray_tpu.kill(a)
    return {"actors": total,
            "actor_create_wall_s": round(created, 2),
            "actor_creates_per_s": round(total / created, 1),
            "actor_call_round_s": round(call_round, 2)}


def phase_pgs(total: int) -> dict:
    t0 = time.perf_counter()
    pgs = [placement_group([{"pg_slot": 1.0}, {"pg_slot": 1.0}],
                           strategy="PACK") for _ in range(total)]
    for pg in pgs:
        assert pg.ready(timeout=600.0)
    created = time.perf_counter() - t0
    t1 = time.perf_counter()
    for pg in pgs:
        remove_placement_group(pg)
    removed = time.perf_counter() - t1
    return {"pgs": total, "pg_create_wall_s": round(created, 2),
            "pgs_per_s": round(total / created, 1),
            "pg_remove_wall_s": round(removed, 2)}


def phase_broadcast(mb: int, node_ids: list) -> dict:
    import numpy as np

    blob = np.random.default_rng(0).integers(
        0, 255, size=mb * 1024 * 1024, dtype=np.uint8)
    ref = ray_tpu.put(blob)
    t0 = time.perf_counter()
    refs = [_consume.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(node_id=nid))
        .remote(ref, i) for i, nid in enumerate(node_ids)]
    out = ray_tpu.get(refs, timeout=600.0)
    dt = time.perf_counter() - t0
    assert all(n == mb * 1024 * 1024 for _, n in out)
    agg = mb * len(node_ids) / dt
    return {"broadcast_mb": mb, "broadcast_nodes": len(node_ids),
            "broadcast_wall_s": round(dt, 2),
            "broadcast_agg_MB_per_s": round(agg, 1)}


def run(nodes: int, tasks: int, actors: int, pgs: int, broadcast_mb: int,
        cpus_per_node: float = 4.0) -> dict:
    wall0 = time.perf_counter()
    cluster = Cluster(initialize_head=True, head_node_args={
        "resources": {"CPU": cpus_per_node, "pg_slot": float(pgs)}})
    for _ in range(nodes - 1):
        cluster.add_node(resources={"CPU": cpus_per_node,
                                    "pg_slot": float(pgs)})
    ray_tpu.init(address=cluster.address)
    try:
        from ray_tpu.util.state import list_nodes

        deadline = time.time() + 120
        while time.time() < deadline:
            alive = [n for n in list_nodes() if n["alive"]]
            if len(alive) >= nodes:
                break
            time.sleep(0.5)
        assert len(alive) >= nodes, f"only {len(alive)}/{nodes} nodes alive"
        result = {"nodes": nodes, "cpus_per_node": cpus_per_node}
        print(f"[stress] {nodes} nodes up", flush=True)
        result.update(phase_tasks(tasks))
        print(f"[stress] tasks: {result['tasks_per_s']}/s", flush=True)
        result.update(phase_actors(actors))
        print(f"[stress] actors: {result['actor_creates_per_s']}/s creates",
              flush=True)
        result.update(phase_pgs(pgs))
        print(f"[stress] pgs: {result['pgs_per_s']}/s", flush=True)
        result.update(phase_broadcast(
            broadcast_mb, [n["node_id"] for n in alive]))
        print(f"[stress] broadcast: {result['broadcast_agg_MB_per_s']} MB/s "
              f"aggregate", flush=True)
        result["total_wall_s"] = round(time.perf_counter() - wall0, 2)
        return result
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=16)
    ap.add_argument("--tasks", type=int, default=20000)
    ap.add_argument("--actors", type=int, default=512)
    ap.add_argument("--pgs", type=int, default=100)
    ap.add_argument("--broadcast-mb", type=int, default=100)
    ap.add_argument("--cpus-per-node", type=float, default=4.0)
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    result = run(args.nodes, args.tasks, args.actors, args.pgs,
                 args.broadcast_mb, args.cpus_per_node)
    result["argv"] = sys.argv[1:]
    print(json.dumps(result, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)


if __name__ == "__main__":
    main()

"""Weight-plane microbenchmark: plan stats + transfer throughput on the
8-device virtual CPU mesh (bench.py-style JSON output).

Measures the three flows the weight plane exists for:

- ``plan``: planner stats for a 4-host train mesh -> 2-host serve mesh
  reshard of the payload tree (edges, bytes moved, unique chunk bytes).
- ``broadcast``: one publisher -> N subscriber actors pulling the same
  version through the store (fan-out throughput, aggregate MB/s).
- ``reshard``: 4 source actors publish planned chunks, 2 destination actors
  pull their resharded shards (end-to-end MB/s for the cross-mesh path).
- ``compression`` (``--compression int8``): quantized publish/allreduce wire
  bytes vs fp32 (the EQuARX tier — codec bytes ratio must clear ~4x).
- ``delta`` (``--delta``): small-update delta publish bytes vs a full
  publish, with a byte-exact pull check.

Usage::

    python tools/bench_weights.py [--payload-mb 8] [--runners 8]
                                  [--compression int8] [--delta]

Prints one JSON list of ``{"name": ..., "value": ..., "unit": ...}`` rows
(the microbenchmark idiom of ``_private/microbenchmark.py``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _payload_tree(payload_mb: float):
    n = int(payload_mb * 1024 * 1024 // 4 // 8) * 8  # float32, 8-divisible
    return {"w": np.arange(n, dtype=np.float32).reshape(8, n // 8)}


def bench_compression(payload_mb: float, compression: str) -> list:
    """Quantized-tier pricing: (a) bucket-allreduce wire bytes through the
    2-rank quantized collective vs fp32 at equal tree size, (b) quantized
    store publish bytes + pull error."""
    import time as _time

    import ray_tpu
    from ray_tpu.collective import quant
    from ray_tpu.weights import WeightStore

    codec = quant.resolve_codec(compression)
    if codec is None:  # --compression none/off: nothing to price
        return []
    tree = _payload_tree(payload_mb)
    raw = tree["w"].nbytes
    rows = []

    @ray_tpu.remote(num_cpus=0.5)
    class Rank:
        def __init__(self, rank, world, comp):
            from ray_tpu import collective as col

            col.init_collective_group(world, rank, backend="cpu",
                                      group_name="bench_w.quant")
            self.rank, self.world, self.comp = rank, world, comp

        def reduce(self, payload_mb):
            from ray_tpu.collective.bucketed import (AsyncBucketReducer,
                                                     leaf_meta,
                                                     plan_buckets)

            tree = _payload_tree(payload_mb)
            plan = plan_buckets(leaf_meta(tree), bucket_bytes=4 << 20,
                                world_size=self.world)
            red = AsyncBucketReducer("bench_w.quant", plan,
                                     compression=self.comp)
            try:
                t0 = _time.perf_counter()
                red.reduce_tree(tree)
                dt = _time.perf_counter() - t0
                return red.wire_stats(), dt
            finally:
                red.shutdown()

    ranks = [Rank.remote(r, 2, compression) for r in range(2)]
    (stats, dt), _ = ray_tpu.get(
        [a.reduce.remote(payload_mb) for a in ranks], timeout=600)
    rows += [
        {"name": "quant_allreduce_fp32_bytes",
         "value": stats["bytes_fp32_equiv"], "unit": "bytes"},
        {"name": "quant_allreduce_wire_bytes",
         "value": stats["bytes_wire"], "unit": "bytes"},
        {"name": "quant_allreduce_reduction",
         "value": stats.get("wire_reduction_x", 0.0), "unit": "x"},
        {"name": "quant_allreduce_s", "value": round(dt, 4), "unit": "s"},
    ]
    for a in ranks:
        ray_tpu.kill(a)

    store = WeightStore(f"bench_quant_{compression}")
    v = store.publish(tree, durable=True, compression=compression)
    pulled = store.pull(v)
    import numpy as _np

    err = float(_np.abs(pulled["w"] - tree["w"]).max()
                / _np.abs(tree["w"]).max())
    pub = store.stats()["versions"][str(v)]["bytes_published"]
    rows += [
        {"name": "quant_publish_bytes", "value": pub, "unit": "bytes"},
        {"name": "quant_publish_raw_bytes", "value": raw, "unit": "bytes"},
        {"name": "quant_publish_reduction", "value": round(raw / pub, 2),
         "unit": "x"},
        {"name": "quant_pull_rel_err", "value": round(err, 5), "unit": "x"},
        {"name": "quant_codec_bytes_per_el",
         "value": round(codec.bytes_per_element, 4), "unit": "B"},
    ]
    return rows


def bench_delta(payload_mb: float, leaves: int = 16,
                changed: int = 2) -> list:
    """Delta-publish pricing: change ``changed`` of ``leaves`` leaves and
    compare published bytes vs the full publish; pulls must be byte-exact."""
    import numpy as _np

    from ray_tpu.weights import WeightStore

    n = max(int(payload_mb * 1024 * 1024 // 4 // leaves), 64)
    rng = _np.random.default_rng(0)
    tree = {f"l{i}": rng.normal(size=n).astype(_np.float32)
            for i in range(leaves)}
    store = WeightStore("bench_delta")
    v1 = store.publish(tree, durable=True)
    tree2 = dict(tree)
    for i in range(changed):
        tree2[f"l{i}"] = tree[f"l{i}"] + 1.0
    v2 = store.publish(tree2, durable=True, delta_from=v1)
    pulled = store.pull(v2)
    exact = all(_np.array_equal(pulled[k], tree2[k]) for k in tree2)
    vs = store.stats()["versions"]
    full = vs[str(v1)]["bytes_published"]
    delta = vs[str(v2)]["bytes_published"]
    return [
        {"name": "delta_full_publish_bytes", "value": full, "unit": "bytes"},
        {"name": "delta_publish_bytes", "value": delta, "unit": "bytes"},
        {"name": "delta_fraction", "value": round(delta / full, 4),
         "unit": "x"},
        {"name": "delta_bytes_reused", "value": vs[str(v2)]["bytes_reused"],
         "unit": "bytes"},
        {"name": "delta_pull_byte_exact", "value": int(exact), "unit": "bool"},
    ]


def main(payload_mb: float = 8.0, runners: int = 8,
         compression: str = "", delta: bool = False) -> list:
    import ray_tpu
    from ray_tpu.weights import (MeshSpec, ShardedTreeSpec, WeightStore,
                                 local_shards_of, plan_reshard,
                                 publish_host_shards)

    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=max(8, runners))
    tree = _payload_tree(payload_mb)
    nbytes = tree["w"].nbytes
    rows = []

    # -- plan stats: 4-host train mesh -> 2-host serve mesh ---------------
    src_mesh = MeshSpec((4,), ("data",), tuple(f"t{i}" for i in range(4)))
    dst_mesh = MeshSpec((2,), ("model",), ("s0", "s1"))
    src = ShardedTreeSpec.from_tree(tree, src_mesh, default_part=("data",))
    dst = ShardedTreeSpec.from_tree(tree, dst_mesh,
                                    parts={"w": (None, "model")})
    plan = plan_reshard(src, dst)
    st = plan.stats()
    rows += [
        {"name": "plan_edges", "value": st["num_edges"], "unit": "edges"},
        {"name": "plan_bytes_moved", "value": st["bytes_moved"],
         "unit": "bytes"},
        {"name": "plan_unique_chunk_bytes", "value": st["unique_chunk_bytes"],
         "unit": "bytes"},
        {"name": "plan_no_gather", "value": int(plan.no_gather()),
         "unit": "bool"},
    ]

    # -- broadcast fan-out throughput -------------------------------------
    @ray_tpu.remote(num_cpus=0.1)
    class Subscriber:
        def __init__(self, store_name):
            self.store = WeightStore(store_name)

        def pull(self, version):
            tree = self.store.pull(version)
            return int(tree["w"].nbytes)

    store = WeightStore("bench_broadcast")
    subs = [Subscriber.remote("bench_broadcast") for _ in range(runners)]
    version = store.publish(tree)
    ray_tpu.get([s.pull.remote(version) for s in subs], timeout=300)  # warm
    t0 = time.perf_counter()
    moved = sum(ray_tpu.get([s.pull.remote(version) for s in subs],
                            timeout=300))
    dt = time.perf_counter() - t0
    rows += [
        {"name": "broadcast_fanout", "value": runners, "unit": "consumers"},
        {"name": "broadcast_MB_s", "value": round(moved / dt / 1e6, 1),
         "unit": "MB/s"},
    ]
    for s in subs:
        ray_tpu.kill(s)

    # -- cross-mesh reshard throughput ------------------------------------
    @ray_tpu.remote(num_cpus=0.1)
    class SrcHost:
        def __init__(self, store_name, host, src, dst, tree_blob):
            from ray_tpu._private.serialization import loads_trusted

            self.store = WeightStore(store_name)
            self.host, self.src, self.dst = host, src, dst
            self.shards = local_shards_of(loads_trusted(tree_blob),
                                          src, host)

        def publish(self, version):
            return publish_host_shards(self.store, version, self.src,
                                       self.host, self.shards,
                                       dst_spec=self.dst)

    @ray_tpu.remote(num_cpus=0.1)
    class DstHost:
        def __init__(self, store_name, host, dst):
            self.store = WeightStore(store_name)
            self.host, self.dst = host, dst

        def pull(self, version):
            shards = self.store.pull_shards(self.dst, self.host, version)
            return sum(a.nbytes for boxes in shards.values()
                       for a in boxes.values())

    import cloudpickle

    blob = cloudpickle.dumps(tree)
    srcs = [SrcHost.remote("bench_reshard", h, src, dst, blob)
            for h in src_mesh.hosts]
    dsts = [DstHost.remote("bench_reshard", h, dst) for h in dst_mesh.hosts]
    t0 = time.perf_counter()
    ray_tpu.get([s.publish.remote(1) for s in srcs], timeout=300)
    moved = sum(ray_tpu.get([d.pull.remote(1) for d in dsts], timeout=300))
    dt = time.perf_counter() - t0
    rows += [
        {"name": "reshard_bytes", "value": moved, "unit": "bytes"},
        {"name": "reshard_MB_s", "value": round(moved / dt / 1e6, 1),
         "unit": "MB/s"},
        {"name": "payload_MB", "value": round(nbytes / 1e6, 1),
         "unit": "MB"},
    ]
    for a in srcs + dsts:
        ray_tpu.kill(a)

    if compression:
        rows += bench_compression(payload_mb, compression)
    if delta:
        rows += bench_delta(payload_mb)
    return rows


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--payload-mb", type=float, default=8.0)
    parser.add_argument("--runners", type=int, default=8)
    parser.add_argument("--compression", default="",
                        help="price the quantized tier (int8/fp8/bf16)")
    parser.add_argument("--delta", action="store_true",
                        help="price the delta-publish tier")
    args = parser.parse_args()
    import ray_tpu

    rows = main(args.payload_mb, args.runners, args.compression, args.delta)
    print(json.dumps(rows))
    ray_tpu.shutdown()
    sys.exit(0)

"""Weight-plane microbenchmark: plan stats + transfer throughput on the
8-device virtual CPU mesh (bench.py-style JSON output).

Measures the three flows the weight plane exists for:

- ``plan``: planner stats for a 4-host train mesh -> 2-host serve mesh
  reshard of the payload tree (edges, bytes moved, unique chunk bytes).
- ``broadcast``: one publisher -> N subscriber actors pulling the same
  version through the store (fan-out throughput, aggregate MB/s).
- ``reshard``: 4 source actors publish planned chunks, 2 destination actors
  pull their resharded shards (end-to-end MB/s for the cross-mesh path).

Usage::

    python tools/bench_weights.py [--payload-mb 8] [--runners 8]

Prints one JSON list of ``{"name": ..., "value": ..., "unit": ...}`` rows
(the microbenchmark idiom of ``_private/microbenchmark.py``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _payload_tree(payload_mb: float):
    n = int(payload_mb * 1024 * 1024 // 4 // 8) * 8  # float32, 8-divisible
    return {"w": np.arange(n, dtype=np.float32).reshape(8, n // 8)}


def main(payload_mb: float = 8.0, runners: int = 8) -> list:
    import ray_tpu
    from ray_tpu.weights import (MeshSpec, ShardedTreeSpec, WeightStore,
                                 local_shards_of, plan_reshard,
                                 publish_host_shards)

    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=max(8, runners))
    tree = _payload_tree(payload_mb)
    nbytes = tree["w"].nbytes
    rows = []

    # -- plan stats: 4-host train mesh -> 2-host serve mesh ---------------
    src_mesh = MeshSpec((4,), ("data",), tuple(f"t{i}" for i in range(4)))
    dst_mesh = MeshSpec((2,), ("model",), ("s0", "s1"))
    src = ShardedTreeSpec.from_tree(tree, src_mesh, default_part=("data",))
    dst = ShardedTreeSpec.from_tree(tree, dst_mesh,
                                    parts={"w": (None, "model")})
    plan = plan_reshard(src, dst)
    st = plan.stats()
    rows += [
        {"name": "plan_edges", "value": st["num_edges"], "unit": "edges"},
        {"name": "plan_bytes_moved", "value": st["bytes_moved"],
         "unit": "bytes"},
        {"name": "plan_unique_chunk_bytes", "value": st["unique_chunk_bytes"],
         "unit": "bytes"},
        {"name": "plan_no_gather", "value": int(plan.no_gather()),
         "unit": "bool"},
    ]

    # -- broadcast fan-out throughput -------------------------------------
    @ray_tpu.remote(num_cpus=0.1)
    class Subscriber:
        def __init__(self, store_name):
            self.store = WeightStore(store_name)

        def pull(self, version):
            tree = self.store.pull(version)
            return int(tree["w"].nbytes)

    store = WeightStore("bench_broadcast")
    subs = [Subscriber.remote("bench_broadcast") for _ in range(runners)]
    version = store.publish(tree)
    ray_tpu.get([s.pull.remote(version) for s in subs], timeout=300)  # warm
    t0 = time.perf_counter()
    moved = sum(ray_tpu.get([s.pull.remote(version) for s in subs],
                            timeout=300))
    dt = time.perf_counter() - t0
    rows += [
        {"name": "broadcast_fanout", "value": runners, "unit": "consumers"},
        {"name": "broadcast_MB_s", "value": round(moved / dt / 1e6, 1),
         "unit": "MB/s"},
    ]
    for s in subs:
        ray_tpu.kill(s)

    # -- cross-mesh reshard throughput ------------------------------------
    @ray_tpu.remote(num_cpus=0.1)
    class SrcHost:
        def __init__(self, store_name, host, src, dst, tree_blob):
            from ray_tpu._private.serialization import loads_trusted

            self.store = WeightStore(store_name)
            self.host, self.src, self.dst = host, src, dst
            self.shards = local_shards_of(loads_trusted(tree_blob),
                                          src, host)

        def publish(self, version):
            return publish_host_shards(self.store, version, self.src,
                                       self.host, self.shards,
                                       dst_spec=self.dst)

    @ray_tpu.remote(num_cpus=0.1)
    class DstHost:
        def __init__(self, store_name, host, dst):
            self.store = WeightStore(store_name)
            self.host, self.dst = host, dst

        def pull(self, version):
            shards = self.store.pull_shards(self.dst, self.host, version)
            return sum(a.nbytes for boxes in shards.values()
                       for a in boxes.values())

    import cloudpickle

    blob = cloudpickle.dumps(tree)
    srcs = [SrcHost.remote("bench_reshard", h, src, dst, blob)
            for h in src_mesh.hosts]
    dsts = [DstHost.remote("bench_reshard", h, dst) for h in dst_mesh.hosts]
    t0 = time.perf_counter()
    ray_tpu.get([s.publish.remote(1) for s in srcs], timeout=300)
    moved = sum(ray_tpu.get([d.pull.remote(1) for d in dsts], timeout=300))
    dt = time.perf_counter() - t0
    rows += [
        {"name": "reshard_bytes", "value": moved, "unit": "bytes"},
        {"name": "reshard_MB_s", "value": round(moved / dt / 1e6, 1),
         "unit": "MB/s"},
        {"name": "payload_MB", "value": round(nbytes / 1e6, 1),
         "unit": "MB"},
    ]
    for a in srcs + dsts:
        ray_tpu.kill(a)
    return rows


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--payload-mb", type=float, default=8.0)
    parser.add_argument("--runners", type=int, default=8)
    args = parser.parse_args()
    import ray_tpu

    rows = main(args.payload_mb, args.runners)
    print(json.dumps(rows))
    ray_tpu.shutdown()
    sys.exit(0)

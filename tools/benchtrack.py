#!/usr/bin/env python
"""Bench trajectory tracker: one schema over the repo's bench artifacts
plus a regression gate.

The repo accumulates per-round JSON artifacts with five different shapes
(``BENCH_rNN.json`` nests under ``parsed``, ``PIPE_rNN.json`` is a list
of name/value entries, ``STRESS``/``SERVE``/``OBS`` are flat dicts).
This tool normalizes them into one trajectory —
``family -> [(round, {metric: value}), ...]`` — and flags metric
regressions beyond per-metric relative thresholds (MFU, tasks/s, TTFT
p99, bubble/overlap fractions, observability overhead), closing the
ROADMAP residual "overlap_fraction regression tracking across BENCH
rounds".

Modes:
  python tools/benchtrack.py            # print the trajectory
  python tools/benchtrack.py --check    # regression gate (exit 1 on fail)
  python tools/benchtrack.py --json     # machine-readable trajectory

``--check`` compares each family's latest round against its previous
round per metric (direction-aware: higher-better throughput vs
lower-better latency), plus ABSOLUTE bars for the observability
overhead percentages (the OBS_r01 "always-on instrumentation stays
under 5% of the hot path" contract). Wired into tier-1 as a smoke test
(tests/test_benchtrack.py).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# observability overhead bar (percent): always-on hooks must stay under
# this on the hot paths, whatever the previous round measured
OBS_OVERHEAD_BAR_PCT = 5.0


@dataclass
class MetricSpec:
    """How one metric regresses: ``direction`` is which way is GOOD
    ("higher" throughput/fractions vs "lower" latency/overhead);
    ``rel_threshold`` the tolerated relative move in the bad direction
    vs the previous round; ``bar`` an optional absolute ceiling that
    applies regardless of history (lower-better metrics only)."""

    direction: str
    rel_threshold: float = 0.10
    bar: Optional[float] = None
    # absolute FLOOR for higher-better metrics, the dual of ``bar``: the
    # value must stay at or above it regardless of history (e.g. the
    # tiered-restore ">= 2x parallel speedup" acceptance)
    floor: Optional[float] = None


# explicit specs for the flat-dict families; PIPE metric names are
# priced by suffix rules below (the stage count in the name varies)
METRIC_SPECS: Dict[str, MetricSpec] = {
    # BENCH (train MFU)
    "train_mfu_1b": MetricSpec("higher", 0.05),
    "mfu_350m": MetricSpec("higher", 0.05),
    "tokens_per_sec_per_chip": MetricSpec("higher", 0.05),
    "step_time_s": MetricSpec("lower", 0.10),
    # STRESS (control-plane throughput)
    "tasks_per_s": MetricSpec("higher", 0.15),
    "aggregate_tasks_per_s": MetricSpec("higher", 0.15),
    "actor_creates_per_s": MetricSpec("higher", 0.20),
    "lease_grant_p50_ms": MetricSpec("lower", 0.50),
    "lease_grant_p95_ms": MetricSpec("lower", 0.50),
    "submit_fast_path_frac": MetricSpec("higher", 0.05),
    # SERVE (latency + loss)
    "ttft_p50_ms": MetricSpec("lower", 0.25),
    "ttft_p99_ms": MetricSpec("lower", 0.25),
    "latency_p99_ms": MetricSpec("lower", 0.25),
    "tokens_per_s": MetricSpec("higher", 0.15),
    "dropped_requests": MetricSpec("lower", 0.0, bar=0.0),
    # OBS (always-on instrumentation overhead, percent): gated by the
    # absolute <=5% bar, generously thresholded round-over-round (these
    # are microbenchmarks with real scheduling noise)
    "events_delta_pct": MetricSpec("lower", 3.0, bar=OBS_OVERHEAD_BAR_PCT),
    "train_step_delta_pct": MetricSpec("lower", 3.0,
                                       bar=OBS_OVERHEAD_BAR_PCT),
    "serve_request_delta_pct": MetricSpec("lower", 3.0,
                                          bar=OBS_OVERHEAD_BAR_PCT),
    "hot_path_span_overhead_pct": MetricSpec("lower", 3.0,
                                             bar=OBS_OVERHEAD_BAR_PCT),
    "goodput_delta_pct": MetricSpec("lower", 3.0,
                                    bar=OBS_OVERHEAD_BAR_PCT),
    "train_step_goodput_delta_pct": MetricSpec("lower", 3.0,
                                               bar=OBS_OVERHEAD_BAR_PCT),
    # CKPT (checkpoint plane + storage tier; tools/bench_ckpt.py --tier).
    # Generous relative thresholds — tmpfs/CI microbenchmarks — but a
    # hard absolute floor on the parallel-restore speedup: the tier's
    # reason to exist is that restore-from-remote is not serial
    "blocking_save_ms": MetricSpec("lower", 0.50),
    "async_pause_ms": MetricSpec("lower", 0.50),
    "dedup_ratio": MetricSpec("higher", 0.10),
    "restore_mb_s": MetricSpec("higher", 0.30),
    "shard_restore_mb_s": MetricSpec("higher", 0.30),
    "tier_mirror_mb_s": MetricSpec("higher", 0.30),
    "tier_mirror_dedup_ratio": MetricSpec("higher", 0.10),
    "tier_restore_parallel_mb_s": MetricSpec("higher", 0.30),
    "tier_restore_serial_mb_s": MetricSpec("higher", 0.30),
    "tier_parallel_speedup": MetricSpec("higher", 0.20, floor=2.0),
}

# suffix -> spec rules for PIPE-style generated metric names
SUFFIX_SPECS: List[Tuple[str, MetricSpec]] = [
    ("_tokens_per_s", MetricSpec("higher", 0.15)),
    ("tokens_per_s", MetricSpec("higher", 0.15)),
    ("_vs_single_mesh", MetricSpec("higher", 0.15)),
    ("_bubble_fraction", MetricSpec("lower", 0.25)),
    ("_idle_fraction_measured", MetricSpec("lower", 0.25)),
    ("_overlap_fraction", MetricSpec("higher", 0.10)),
]


def spec_for(metric: str) -> Optional[MetricSpec]:
    spec = METRIC_SPECS.get(metric)
    if spec is not None:
        return spec
    for suffix, s in SUFFIX_SPECS:
        if metric.endswith(suffix):
            return s
    return None


# -- per-family extraction (each returns {metric: float}) ----------------


def _numeric(d: dict, keys) -> Dict[str, float]:
    out = {}
    for k in keys:
        v = d.get(k)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[k] = float(v)
    return out


def _extract_bench(payload) -> Dict[str, float]:
    parsed = payload.get("parsed") or {}
    out = {}
    metric = parsed.get("metric")
    if metric and isinstance(parsed.get("value"), (int, float)):
        out[str(metric)] = float(parsed["value"])
    out.update(_numeric(parsed, ("mfu_350m", "tokens_per_sec_per_chip",
                                 "step_time_s", "overlap_fraction",
                                 "mfu_1chip")))
    return out


def _extract_flat(payload) -> Dict[str, float]:
    if not isinstance(payload, dict):
        return {}
    return {k: float(v) for k, v in payload.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
            and spec_for(k) is not None}


def _extract_pipe(payload) -> Dict[str, float]:
    if not isinstance(payload, list):
        return {}
    out = {}
    for entry in payload:
        if not isinstance(entry, dict):
            continue
        name, value = entry.get("name"), entry.get("value")
        if (isinstance(name, str) and isinstance(value, (int, float))
                and not isinstance(value, bool)
                and spec_for(name) is not None):
            out[name] = float(value)
    return out


def _extract_pipe_floors(payload) -> Dict[str, float]:
    """Per-row ``meta.floor`` annotations (e.g. the analytic bubble bound
    under a simulated bubble_fraction): an absolute floor the value must
    hold REGARDLESS of direction — a lower-better metric dropping below
    its analytic floor means the measurement lied, not that it improved."""
    if not isinstance(payload, list):
        return {}
    out = {}
    for entry in payload:
        if not isinstance(entry, dict):
            continue
        name = entry.get("name")
        floor = (entry.get("meta") or {}).get("floor") \
            if isinstance(entry.get("meta"), dict) else None
        if (isinstance(name, str) and isinstance(floor, (int, float))
                and not isinstance(floor, bool)):
            out[name] = float(floor)
    return out


def _extract_pipe_host(payload) -> Optional[int]:
    """The host envelope the round was measured on (the config row's
    ``meta.host_cpus``). Rounds from different envelopes are not
    comparable round-over-round: a 64-core round vs a 1-core round would
    read as a catastrophic throughput regression when nothing regressed."""
    if not isinstance(payload, list):
        return None
    for entry in payload:
        if isinstance(entry, dict) and entry.get("name") == "config":
            cpus = (entry.get("meta") or {}).get("host_cpus") \
                if isinstance(entry.get("meta"), dict) else None
            if isinstance(cpus, int):
                return cpus
    return None


FAMILIES = {
    "BENCH": _extract_bench,
    "STRESS": _extract_flat,
    "SERVE": _extract_flat,
    "PIPE": _extract_pipe,
    "OBS": _extract_flat,
    "CKPT": _extract_flat,
}

_ROUND_RE = re.compile(r"^([A-Z_]+?)_r(\d+)\.json$")


def load_trajectory(root: str = REPO_ROOT) -> Dict[str, List[dict]]:
    """All recognized artifacts normalized into one trajectory:
    ``{family: [{"round": n, "file": name, "metrics": {...}}, ...]}``,
    rounds ascending. Unreadable/foreign files are skipped (the repo
    root also holds non-bench JSON)."""
    out: Dict[str, List[dict]] = {}
    for path in sorted(glob.glob(os.path.join(root, "*.json"))):
        m = _ROUND_RE.match(os.path.basename(path))
        if not m or m.group(1) not in FAMILIES:
            continue
        family, rnd = m.group(1), int(m.group(2))
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            continue
        metrics = FAMILIES[family](payload)
        if not metrics:
            continue
        rec = {"round": rnd, "file": os.path.basename(path),
               "metrics": metrics}
        if family == "PIPE":
            floors = _extract_pipe_floors(payload)
            if floors:
                rec["floors"] = floors
            rec["host_cpus"] = _extract_pipe_host(payload)
        out.setdefault(family, []).append(rec)
    for rounds in out.values():
        rounds.sort(key=lambda r: r["round"])
    return out


def check(root: str = REPO_ROOT) -> Tuple[List[str], List[str]]:
    """The regression gate. Returns ``(failures, passes)`` as printable
    lines; empty ``failures`` means the gate is green. Latest round vs
    previous round per family/metric (direction-aware relative
    threshold), plus the absolute bars on every round's latest."""
    trajectory = load_trajectory(root)
    failures: List[str] = []
    passes: List[str] = []
    for family, rounds in sorted(trajectory.items()):
        latest = rounds[-1]
        prev = rounds[-2] if len(rounds) > 1 else None
        if prev is not None and "host_cpus" in latest \
                and latest["host_cpus"] != prev.get("host_cpus"):
            # incomparable host envelopes: absolute bars/floors still
            # apply, but round-over-round moves re-baseline here
            passes.append(
                f"{family} {latest['file']}: host envelope changed "
                f"({prev.get('host_cpus')} -> {latest['host_cpus']} "
                f"cpus), relative gate re-baselined")
            prev = None
        for metric, value in sorted(latest["metrics"].items()):
            spec = spec_for(metric)
            if spec is None:
                continue
            where = f"{family} {latest['file']} {metric}"
            if spec.bar is not None and value > spec.bar:
                failures.append(
                    f"{where}: {value:g} over the absolute bar "
                    f"{spec.bar:g}")
                continue
            if spec.floor is not None and value < spec.floor:
                failures.append(
                    f"{where}: {value:g} under the absolute floor "
                    f"{spec.floor:g}")
                continue
            # per-row floor metadata (PIPE: the analytic bubble bound);
            # 1e-9 slack because the simulated bubble EQUALS the bound
            meta_floor = latest.get("floors", {}).get(metric)
            if meta_floor is not None and value < meta_floor - 1e-9:
                failures.append(
                    f"{where}: {value:g} under the analytic floor "
                    f"{meta_floor:g} (meta.floor)")
                continue
            base = (prev or {}).get("metrics", {}).get(metric) \
                if prev else None
            if base is None:
                passes.append(f"{where}: {value:g} (no prior round)")
                continue
            if spec.direction == "higher":
                floor = base * (1.0 - spec.rel_threshold)
                # a negative-baseline metric can't price a relative
                # floor meaningfully; treat any value as holding
                if base > 0 and value < floor:
                    failures.append(
                        f"{where}: {value:g} < {floor:g} "
                        f"(prev {base:g}, -{spec.rel_threshold:.0%} "
                        f"threshold)")
                    continue
            else:
                ceil = base + abs(base) * spec.rel_threshold \
                    if base != 0 else spec.rel_threshold
                if value > ceil and (spec.bar is None or value > 0):
                    failures.append(
                        f"{where}: {value:g} > {ceil:g} "
                        f"(prev {base:g}, +{spec.rel_threshold:.0%} "
                        f"threshold)")
                    continue
            passes.append(f"{where}: {value:g} (prev {base:g})")
    return failures, passes


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="bench artifact trajectory + regression gate")
    parser.add_argument("--dir", default=REPO_ROOT,
                        help="artifact directory (default: repo root)")
    parser.add_argument("--check", action="store_true",
                        help="regression gate: exit 1 on any regression")
    parser.add_argument("--json", action="store_true",
                        help="print the normalized trajectory as JSON")
    args = parser.parse_args(argv)

    if args.check:
        failures, passes = check(args.dir)
        for line in passes:
            print(f"  ok   {line}")
        for line in failures:
            print(f"  FAIL {line}")
        print(f"benchtrack: {len(passes)} ok, {len(failures)} regressed")
        return 1 if failures else 0

    trajectory = load_trajectory(args.dir)
    if args.json:
        print(json.dumps(trajectory, indent=2, sort_keys=True))
        return 0
    for family, rounds in sorted(trajectory.items()):
        print(f"{family}: rounds "
              f"{', '.join(str(r['round']) for r in rounds)}")
        latest = rounds[-1]
        for metric, value in sorted(latest["metrics"].items()):
            series = [r["metrics"].get(metric) for r in rounds]
            path = " -> ".join("?" if v is None else f"{v:g}"
                               for v in series)
            print(f"  {metric:40} {path}")
    if not trajectory:
        print("no bench artifacts found")
    return 0


if __name__ == "__main__":
    sys.exit(main())

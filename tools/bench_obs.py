"""Observability overhead microbenchmark (``python -m tools.bench_obs``).

Measures what the task-event pipeline and tracing layer cost, so future
rounds can hold the line on "observability is pay-for-what-you-use":

* ``span_record_per_s``       — tracing.record_span throughput (enabled)
* ``event_record_us``         — one task_events.record() call (enabled)
* ``event_flush_us_per_task`` — amortized per-task cost of the 4-transition
                                record + batched AddTaskEvents flush
* ``submit_us_*``             — end-to-end no-op task latency with
                                observability fully off (baseline), task
                                events on (default config, goodput ledger
                                included), events with only the goodput
                                ledger off, and tracing on
* ``*_delta_pct``             — overhead relative to the disabled baseline
* ``train_step_us_*``         — one TrainStepBundle step (tiny config) with
                                built-in spans on vs everything disabled
* ``serve_request_us_*``      — one serve request through a handle (built-in
                                route/queue/execute spans) on vs disabled
* ``history_scrape_ms_*``     — GetMetricsHistory RPC cost (names + one
                                full series) against a live GCS ring

The acceptance bar rides ``traced_delta_pct`` (the microbench
task-throughput path): end-to-end hot-path span overhead must stay <= 5%
vs events-disabled; ``goodput_delta_pct`` / ``train_step_goodput_delta_pct``
hold the same bar for the default-on goodput ledger. Emits one JSON
object on stdout (plus --out FILE) that ``tools/benchtrack.py --check``
tracks for regressions.
"""

from __future__ import annotations

import argparse
import json
import time


def _bench_span_record(n: int = 20_000) -> float:
    from ray_tpu.util import tracing

    t0 = time.perf_counter()
    now = time.time()
    for i in range(n):
        tracing.record_span("bench_span", now, now + 1e-6,
                            category="bench", idx=i)
    dt = time.perf_counter() - t0
    tracing.flush()
    return n / dt


def _bench_event_record(n: int = 20_000) -> float:
    from ray_tpu._private import task_events

    t0 = time.perf_counter()
    for i in range(n):
        task_events.record(f"bench{i:08x}", task_events.SUBMITTED,
                           name="bench", job_id="bench")
    dt = time.perf_counter() - t0
    task_events.drain()  # don't ship 20k synthetic events to the GCS
    return dt / n * 1e6


def _bench_event_flush(n_tasks: int = 2_000) -> float:
    """4 transitions per task + a real AddTaskEvents flush, amortized."""
    from ray_tpu._private import task_events

    t0 = time.perf_counter()
    for i in range(n_tasks):
        tid = f"flush{i:08x}"
        for st in (task_events.SUBMITTED, task_events.SCHEDULED,
                   task_events.RUNNING, task_events.FINISHED):
            task_events.record(tid, st, name="bench_flush", job_id="bench")
    task_events.flush()
    return (time.perf_counter() - t0) / n_tasks * 1e6


def _bench_submission_configs(ray_tpu, configs, rounds: int = 4,
                              n: int = 200):
    """Measure no-op task submit+complete latency under each observability
    config. Rounds are INTERLEAVED across configs (a-b-c, a-b-c, ...) so
    cluster warmup/noise drift hits every config equally; reports the
    per-config minimum."""
    @ray_tpu.remote
    def _noop(i):
        return i

    # warmup: function push + worker lease
    ray_tpu.get([_noop.remote(i) for i in range(20)], timeout=120)
    best = {name: float("inf") for name, _ in configs}
    for _ in range(rounds):
        for name, apply in configs:
            apply()
            t0 = time.perf_counter()
            ray_tpu.get([_noop.remote(i) for i in range(n)], timeout=300)
            best[name] = min(best[name],
                             (time.perf_counter() - t0) / n * 1e6)
    return best


def _bench_train_step(configs, steps: int = 12, warmup: int = 3):
    """Per-step latency of the tiny-config TrainStepBundle under each
    observability config (the built-in span path vs fully disabled)."""
    import jax
    import numpy as np

    from ray_tpu.models import CONFIGS
    from ray_tpu.parallel import TrainStepBundle, create_mesh

    mesh = create_mesh({"data": 1, "fsdp": 1, "seq": 1, "tensor": 1,
                        "expert": 1}, devices=jax.devices()[:1])
    bundle = TrainStepBundle(CONFIGS["tiny"], mesh, donate=False)
    batch = bundle.make_batch(np.random.default_rng(0), 2, 64)
    # per-config live state; warm every config up front so compiles and
    # first-touch costs never land inside a timed window
    state = {}
    for name, apply in configs:
        apply()
        params, opt_state = bundle.init(jax.random.PRNGKey(0))
        for _ in range(warmup):
            params, opt_state, loss = bundle.step(params, opt_state, batch)
        jax.block_until_ready(loss)
        state[name] = (params, opt_state)
    # rounds INTERLEAVED across configs (like the submit bench) so CPU
    # frequency/cache drift hits every config equally; per-config minimum
    best = {name: float("inf") for name, _ in configs}
    for _ in range(4):
        for name, apply in configs:
            apply()
            params, opt_state = state[name]
            t0 = time.perf_counter()
            for _ in range(steps):
                params, opt_state, loss = bundle.step(params, opt_state,
                                                      batch)
            jax.block_until_ready(loss)
            best[name] = min(best[name],
                             (time.perf_counter() - t0) / steps * 1e6)
            state[name] = (params, opt_state)
    return best


def _bench_serve_request(ray_tpu, configs, n: int = 100):
    """Per-request latency of one serve request through a handle (the
    built-in route/queue/execute span path) under each config."""
    from ray_tpu import serve

    @serve.deployment(name="bench_obs_echo", num_replicas=1)
    class _Echo:
        def __call__(self, x):
            return x

    handle = serve.run(_Echo.bind(), name="bench_obs_echo")
    ray_tpu.get([handle.remote(i) for i in range(20)], timeout=120)  # warm
    best = {name: float("inf") for name, _ in configs}
    for _ in range(5):
        for name, apply in configs:
            apply()
            t0 = time.perf_counter()
            ray_tpu.get([handle.remote(i) for i in range(n)], timeout=300)
            best[name] = min(best[name],
                             (time.perf_counter() - t0) / n * 1e6)
    serve.delete("bench_obs_echo")
    return best


def _bench_history_scrape(n: int = 50):
    """GetMetricsHistory cost against the live GCS ring: the names index
    and one full raw series, in milliseconds per call."""
    from ray_tpu._private import worker as worker_mod

    core = worker_mod.global_worker()
    t0 = time.perf_counter()
    names = []
    for _ in range(n):
        names = core._run(core._gcs_call("GetMetricsHistory", {}))["names"]
    names_ms = (time.perf_counter() - t0) / n * 1e3
    series_ms = 0.0
    if names:
        target = next((x for x in names if "lease_queue" in x), names[0])
        t0 = time.perf_counter()
        for _ in range(n):
            core._run(core._gcs_call(
                "GetMetricsHistory", {"name": target, "tier": "raw"}))
        series_ms = (time.perf_counter() - t0) / n * 1e3
    return {"history_scrape_ms_names": names_ms,
            "history_scrape_ms_series": series_ms,
            "history_names_recorded": len(names)}


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default="")
    parser.add_argument("--tasks", type=int, default=200)
    parser.add_argument("--rounds", type=int, default=3)
    args = parser.parse_args(argv)

    import os

    # fast history sampling so the scrape bench has points to serve
    os.environ.setdefault("RAY_TPU_METRICS_HISTORY_INTERVAL_S", "0.5")
    os.environ.setdefault("RAY_TPU_METRICS_FLUSH_INTERVAL_S", "2.0")

    import ray_tpu
    from ray_tpu._private import task_events
    from ray_tpu.util import tracing

    ray_tpu.init(num_cpus=4)
    out = {}

    def _goodput(on: bool):
        # config env is read per-access, so this flips the ledger hooks
        # (region timers, compile watch, flush payload) live in-process
        os.environ["RAY_TPU_GOODPUT_ENABLED"] = "1" if on else "0"

    def _off():
        task_events.set_enabled(False)
        tracing._enabled = False
        _goodput(False)

    def _events():
        # the DEFAULT production config: task events + goodput ledger on
        task_events.set_enabled(True)
        tracing._enabled = False
        _goodput(True)

    def _events_nogoodput():
        task_events.set_enabled(True)
        tracing._enabled = False
        _goodput(False)

    def _traced():
        task_events.set_enabled(True)
        tracing._enabled = True
        _goodput(True)

    best = _bench_submission_configs(
        ray_tpu,
        [("disabled", _off), ("events", _events),
         ("events_nogoodput", _events_nogoodput), ("traced", _traced)],
        args.rounds, args.tasks)
    out["submit_us_disabled"] = best["disabled"]
    out["submit_us_events"] = best["events"]
    out["submit_us_events_nogoodput"] = best["events_nogoodput"]
    out["submit_us_traced"] = best["traced"]

    out["events_delta_pct"] = 100.0 * (
        out["submit_us_events"] / out["submit_us_disabled"] - 1.0)
    out["traced_delta_pct"] = 100.0 * (
        out["submit_us_traced"] / out["submit_us_disabled"] - 1.0)
    # goodput-ledger cost on the no-op task path: default config (ledger
    # on) vs the same config with only the ledger off
    out["goodput_delta_pct"] = 100.0 * (
        out["submit_us_events"] / out["submit_us_events_nogoodput"] - 1.0)

    out["span_record_per_s"] = _bench_span_record()
    out["event_record_us"] = _bench_event_record()
    out["event_flush_us_per_task"] = _bench_event_flush()

    # hot-path built-in spans, three configs per path:
    #   disabled — RAY_TPU_TASK_EVENTS=0, tracing off (nothing recorded)
    #   events   — the DEFAULT production config: task events + always-on
    #              histograms + built-in span instrumentation present
    #              (profile() short-circuits; this is what every user pays)
    #   traced   — full span COLLECTION on (diagnostic mode: every span
    #              recorded + shipped to the GCS trace table)
    hot_configs = [("disabled", _off), ("events", _events),
                   ("events_nogoodput", _events_nogoodput),
                   ("traced", _traced)]
    try:
        train = _bench_train_step(hot_configs)
        for name, us in train.items():
            out[f"train_step_us_{name}"] = us
        out["train_step_delta_pct"] = 100.0 * (
            train["events"] / train["disabled"] - 1.0)
        out["train_step_traced_delta_pct"] = 100.0 * (
            train["traced"] / train["disabled"] - 1.0)
        # goodput-ledger cost on the warm train step (region timers + a
        # compile-watch key per step; ledger on vs only the ledger off)
        out["train_step_goodput_delta_pct"] = 100.0 * (
            train["events"] / train["events_nogoodput"] - 1.0)
    except Exception as e:  # no jax/flax in this env: skip, don't sink
        out["train_step_error"] = f"{type(e).__name__}: {e}"
    serve_lat = _bench_serve_request(ray_tpu, hot_configs)
    for name, us in serve_lat.items():
        out[f"serve_request_us_{name}"] = us
    out["serve_request_delta_pct"] = 100.0 * (
        serve_lat["events"] / serve_lat["disabled"] - 1.0)
    out["serve_request_traced_delta_pct"] = 100.0 * (
        serve_lat["traced"] / serve_lat["disabled"] - 1.0)

    # THE acceptance bar: end-to-end overhead of the default always-on
    # config on the microbench task-throughput path vs events-disabled
    out["hot_path_span_overhead_pct"] = out["events_delta_pct"]

    # metrics-history scrape cost (the ring has been sampling all along)
    _events()
    out.update(_bench_history_scrape())

    tracing._enabled = None
    task_events.set_enabled(None)
    os.environ.pop("RAY_TPU_GOODPUT_ENABLED", None)
    ray_tpu.shutdown()

    print(json.dumps(out, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=2)
    return out


if __name__ == "__main__":
    main()

"""Observability overhead microbenchmark (``python -m tools.bench_obs``).

Measures what the task-event pipeline and tracing layer cost, so future
rounds can hold the line on "observability is pay-for-what-you-use":

* ``span_record_per_s``       — tracing.record_span throughput (enabled)
* ``event_record_us``         — one task_events.record() call (enabled)
* ``event_flush_us_per_task`` — amortized per-task cost of the 4-transition
                                record + batched AddTaskEvents flush
* ``submit_us_*``             — end-to-end no-op task latency with
                                observability fully off (baseline), task
                                events on (default config), and tracing on
* ``*_delta_pct``             — overhead relative to the disabled baseline

Emits one JSON object on stdout (plus --out FILE) so BENCH rounds can
track regressions.
"""

from __future__ import annotations

import argparse
import json
import time


def _bench_span_record(n: int = 20_000) -> float:
    from ray_tpu.util import tracing

    t0 = time.perf_counter()
    now = time.time()
    for i in range(n):
        tracing.record_span("bench_span", now, now + 1e-6,
                            category="bench", idx=i)
    dt = time.perf_counter() - t0
    tracing.flush()
    return n / dt


def _bench_event_record(n: int = 20_000) -> float:
    from ray_tpu._private import task_events

    t0 = time.perf_counter()
    for i in range(n):
        task_events.record(f"bench{i:08x}", task_events.SUBMITTED,
                           name="bench", job_id="bench")
    dt = time.perf_counter() - t0
    task_events.drain()  # don't ship 20k synthetic events to the GCS
    return dt / n * 1e6


def _bench_event_flush(n_tasks: int = 2_000) -> float:
    """4 transitions per task + a real AddTaskEvents flush, amortized."""
    from ray_tpu._private import task_events

    t0 = time.perf_counter()
    for i in range(n_tasks):
        tid = f"flush{i:08x}"
        for st in (task_events.SUBMITTED, task_events.SCHEDULED,
                   task_events.RUNNING, task_events.FINISHED):
            task_events.record(tid, st, name="bench_flush", job_id="bench")
    task_events.flush()
    return (time.perf_counter() - t0) / n_tasks * 1e6


def _bench_submission_configs(ray_tpu, configs, rounds: int = 4,
                              n: int = 200):
    """Measure no-op task submit+complete latency under each observability
    config. Rounds are INTERLEAVED across configs (a-b-c, a-b-c, ...) so
    cluster warmup/noise drift hits every config equally; reports the
    per-config minimum."""
    @ray_tpu.remote
    def _noop(i):
        return i

    # warmup: function push + worker lease
    ray_tpu.get([_noop.remote(i) for i in range(20)], timeout=120)
    best = {name: float("inf") for name, _ in configs}
    for _ in range(rounds):
        for name, apply in configs:
            apply()
            t0 = time.perf_counter()
            ray_tpu.get([_noop.remote(i) for i in range(n)], timeout=300)
            best[name] = min(best[name],
                             (time.perf_counter() - t0) / n * 1e6)
    return best


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default="")
    parser.add_argument("--tasks", type=int, default=200)
    parser.add_argument("--rounds", type=int, default=3)
    args = parser.parse_args(argv)

    import ray_tpu
    from ray_tpu._private import task_events
    from ray_tpu.util import tracing

    ray_tpu.init(num_cpus=4)
    out = {}

    def _off():
        task_events.set_enabled(False)
        tracing._enabled = False

    def _events():
        task_events.set_enabled(True)
        tracing._enabled = False

    def _traced():
        task_events.set_enabled(True)
        tracing._enabled = True

    best = _bench_submission_configs(
        ray_tpu,
        [("disabled", _off), ("events", _events), ("traced", _traced)],
        args.rounds, args.tasks)
    out["submit_us_disabled"] = best["disabled"]
    out["submit_us_events"] = best["events"]
    out["submit_us_traced"] = best["traced"]

    out["events_delta_pct"] = 100.0 * (
        out["submit_us_events"] / out["submit_us_disabled"] - 1.0)
    out["traced_delta_pct"] = 100.0 * (
        out["submit_us_traced"] / out["submit_us_disabled"] - 1.0)

    out["span_record_per_s"] = _bench_span_record()
    out["event_record_us"] = _bench_event_record()
    out["event_flush_us_per_task"] = _bench_event_flush()

    tracing._enabled = None
    task_events.set_enabled(None)
    ray_tpu.shutdown()

    print(json.dumps(out, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=2)
    return out


if __name__ == "__main__":
    main()

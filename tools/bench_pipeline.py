"""Pipeline-parallel microbenchmark: 1F1B bubble + throughput vs single
mesh (bench.py-style JSON output; writes PIPE_r*.json at the repo root).

Measures, per stage count S (default 2 and 4, M microbatches each):

- ``tokens_per_s``: end-to-end pipeline training throughput over real
  stage actors + channels, vs the single-mesh fused ``TrainStepBundle``
  step at the same total batch (the equal-chip-count baseline on the CPU
  tier: both sides own the same 8 virtual devices).
- ``bubble_fraction``: the 1F1B schedule's analytic bubble from the
  event simulator (exactly (S-1)/(S-1+M) at equal per-microbatch costs —
  the acceptance bound), plus the *measured* per-stage idle fraction
  (wall - compute)/wall, which on the CPU tier also carries
  serialization + channel costs.
- ``activation_bytes_per_microbatch``: what one microbatch hand-off
  puts on the wire between adjacent stages.

Usage::

    python tools/bench_pipeline.py [--stages 2,4] [--microbatches 8]
        [--steps 3] [--out PIPE_r01.json]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _bench_cfg(n_layers: int):
    from ray_tpu.models.transformer import CONFIGS

    # n_kv_heads=4 so the single-mesh baseline shards over the default
    # 8-device mesh's tensor=4 axis (tiny's GQA kv=2 does not divide it)
    return dataclasses.replace(CONFIGS["tiny"], n_layers=n_layers,
                               n_kv_heads=4, remat=False)


def main(stages=(2, 4), microbatches: int = 8, microbatch_size: int = 2,
         seq_len: int = 64, steps: int = 3, n_layers: int = 4,
         out: str = None) -> list:
    import numpy as np

    import ray_tpu
    from ray_tpu.parallel.mesh import create_mesh, default_mesh_axes
    from ray_tpu.parallel.train import TrainStepBundle
    from ray_tpu.train.pipeline import (
        PipelineConfig,
        PipelineTrainer,
        bubble_upper_bound,
        make_microbatches,
        simulate,
    )

    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=max(8, max(stages) + 1))
    cfg = _bench_cfg(n_layers)
    batch_tokens = microbatches * microbatch_size * seq_len
    rows = []

    # -- single-mesh baseline (fused step, same total batch) --------------
    mesh = create_mesh(default_mesh_axes(8))
    bundle = TrainStepBundle(cfg, mesh, donate=False)
    pipe0 = PipelineConfig(num_stages=1, num_microbatches=microbatches,
                           microbatch_size=microbatch_size, seq_len=seq_len)
    import jax

    params, opt_state = bundle.init(jax.random.PRNGKey(0))
    mbs = make_microbatches(cfg, pipe0, 0, 0)
    batch = {k: np.concatenate([m[k] for m in mbs]) for k in mbs[0]}
    params, opt_state, _ = bundle.step(params, opt_state, batch)  # compile
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, _ = bundle.step(params, opt_state, batch)
    single_tps = steps * batch_tokens / (time.perf_counter() - t0)
    rows.append({"name": "single_mesh_tokens_per_s", "value": single_tps,
                 "unit": "tokens/s"})

    # -- pipeline at each stage count -------------------------------------
    for S in stages:
        pipe = PipelineConfig(num_stages=S, num_microbatches=microbatches,
                              microbatch_size=microbatch_size,
                              seq_len=seq_len)
        trainer = PipelineTrainer(cfg, pipe, run_name=f"bench_pipe_s{S}")
        try:
            trainer.train(1)  # compile + warm the channels
            t0 = time.perf_counter()
            stats = trainer.train(1 + steps)
            elapsed = time.perf_counter() - t0
            tps = steps * batch_tokens / elapsed
            sim = simulate(S, microbatches)
            measured_idle = float(np.mean(
                [1.0 - c / w for c, w in
                 zip(stats[-1]["compute_s"],
                     [stats[-1]["wall_s"]] * S)]))
            rows += [
                {"name": f"pipeline_s{S}_tokens_per_s", "value": tps,
                 "unit": "tokens/s"},
                {"name": f"pipeline_s{S}_vs_single_mesh", "value":
                 tps / single_tps, "unit": "x"},
                {"name": f"pipeline_s{S}_bubble_fraction",
                 "value": sim["bubble_fraction"], "unit": "fraction"},
                {"name": f"pipeline_s{S}_bubble_bound",
                 "value": bubble_upper_bound(S, microbatches),
                 "unit": "fraction"},
                {"name": f"pipeline_s{S}_idle_fraction_measured",
                 "value": measured_idle, "unit": "fraction"},
                {"name": f"pipeline_s{S}_activation_bytes_per_microbatch",
                 "value": stats[-1]["activation_bytes_per_mb"],
                 "unit": "bytes"},
            ]
        finally:
            trainer.shutdown()

    rows.append({"name": "config", "value": 0, "unit": "meta",
                 "meta": {"n_layers": n_layers, "d_model": cfg.d_model,
                          "microbatches": microbatches,
                          "microbatch_size": microbatch_size,
                          "seq_len": seq_len, "steps": steps}})
    if out:
        with open(out, "w") as f:
            json.dump(rows, f, indent=1)
    return rows


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", default="2,4")
    ap.add_argument("--microbatches", type=int, default=8)
    ap.add_argument("--microbatch-size", type=int, default=2)
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--n-layers", type=int, default=4)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    rows = main(stages=tuple(int(s) for s in args.stages.split(",")),
                microbatches=args.microbatches,
                microbatch_size=args.microbatch_size,
                seq_len=args.seq_len, steps=args.steps,
                n_layers=args.n_layers, out=args.out)
    print(json.dumps(rows, indent=1))

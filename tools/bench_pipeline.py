"""Pipeline-parallel microbenchmark: 1F1B bubble + throughput vs single
mesh (bench.py-style JSON output; writes PIPE_r*.json at the repo root).

Measures, per stage count S (default 2 and 4, M microbatches each) and
optionally per interleave factor V (``--interleave``):

- ``tokens_per_s``: end-to-end pipeline training throughput over real
  stage actors + channels, vs the single-mesh fused ``TrainStepBundle``
  step at the same total batch (the equal-chip-count baseline on the CPU
  tier: both sides own the same 8 virtual devices).
- ``bubble_fraction``: the (interleaved) 1F1B schedule's analytic bubble
  from the event simulator — exactly (S-1)/(S-1+V*M) at equal per-chunk
  costs, carried as ``meta.floor`` on the row so benchtrack can hold the
  measurement to the analytic bound — plus the *measured* per-stage idle
  fraction (wall - compute)/wall, which on the CPU tier also carries
  serialization + channel costs.
- ``activation_bytes_per_microbatch``: what one microbatch hand-off
  puts on the wire between adjacent stages.
- per-hop channel breakdown (``hop_*_ms`` rows): where one training
  step's channel time goes on the zero-copy fast path — array extract
  (encode), skeleton pickle, slot memcpy (copy), downstream ack wait,
  and reader-side decode. ``hop_pickle_ms`` prices ONLY the tree
  skeleton: a fat pickle row here means arrays fell off the zero-copy
  path.

``--activation-compression int8`` streams forward activations quantized
(block-scaled int8 codes on the wire, exact gradients); rows gain a
``_q8`` tag so they never alias the exact-path trajectory.

Usage::

    python tools/bench_pipeline.py [--stages 2,4] [--microbatches 8]
        [--interleave 2] [--activation-compression int8]
        [--steps 3] [--out PIPE_r02.json]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _bench_cfg(n_layers: int):
    from ray_tpu.models.transformer import CONFIGS

    # n_kv_heads=4 so the single-mesh baseline shards over the default
    # 8-device mesh's tensor=4 axis (tiny's GQA kv=2 does not divide it)
    return dataclasses.replace(CONFIGS["tiny"], n_layers=n_layers,
                               n_kv_heads=4, remat=False)


_HOP_KEYS = ("send_encode_s", "send_pickle_s", "send_copy_s",
             "send_ack_wait_s", "recv_copy_s", "recv_decode_s")


def _hop_rows(prefix: str, hops: list) -> list:
    """Aggregate one step's per-rank hop stats into ``hop_*_ms`` rows
    (summed across ranks: total channel time spent per step)."""
    rows = []
    for key in _HOP_KEYS:
        total = sum(h.get(key, 0.0) for h in hops)
        name = key[:-2].replace("send_", "hop_").replace("recv_", "hop_rx_")
        rows.append({"name": f"{prefix}_{name}_ms", "value": total * 1e3,
                     "unit": "ms"})
    wire = sum(h.get("send_wire_bytes", 0) for h in hops)
    rows.append({"name": f"{prefix}_hop_wire_bytes", "value": wire,
                 "unit": "bytes"})
    # bytes that still pass through pickle: the tree skeleton only.
    # wire - pickled = bytes that rode the zero-copy array path
    pickled = sum(h.get("send_skel_bytes", 0) for h in hops)
    rows.append({"name": f"{prefix}_hop_pickled_bytes", "value": pickled,
                 "unit": "bytes"})
    return rows


def main(stages=(2, 4), microbatches: int = 8, microbatch_size: int = 2,
         seq_len: int = 64, steps: int = 3, n_layers: int = 4,
         interleave: int = 1, activation_compression: str = None,
         out: str = None) -> list:
    import numpy as np

    import ray_tpu
    from ray_tpu.parallel.mesh import create_mesh, default_mesh_axes
    from ray_tpu.parallel.train import TrainStepBundle
    from ray_tpu.train.pipeline import (
        PipelineConfig,
        PipelineTrainer,
        bubble_upper_bound,
        make_microbatches,
        simulate,
    )

    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=max(8, max(stages) + 1))
    cfg = _bench_cfg(n_layers)
    batch_tokens = microbatches * microbatch_size * seq_len
    rows = []

    # -- single-mesh baseline (fused step, same total batch) --------------
    mesh = create_mesh(default_mesh_axes(8))
    bundle = TrainStepBundle(cfg, mesh, donate=False)
    pipe0 = PipelineConfig(num_stages=1, num_microbatches=microbatches,
                           microbatch_size=microbatch_size, seq_len=seq_len)
    import jax

    params, opt_state = bundle.init(jax.random.PRNGKey(0))
    mbs = make_microbatches(cfg, pipe0, 0, 0)
    batch = {k: np.concatenate([m[k] for m in mbs]) for k in mbs[0]}
    params, opt_state, _ = bundle.step(params, opt_state, batch)  # compile
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, _ = bundle.step(params, opt_state, batch)
    single_tps = steps * batch_tokens / (time.perf_counter() - t0)
    rows.append({"name": "single_mesh_tokens_per_s", "value": single_tps,
                 "unit": "tokens/s"})

    # -- pipeline at each (stage count, interleave) -----------------------
    variants = [(S, 1) for S in stages]
    if interleave > 1:
        # each of the S*V virtual stages needs at least one layer
        variants += [(S, interleave) for S in stages
                     if S * interleave <= n_layers]
    for S, V in variants:
        tag = f"pipeline_s{S}" + (f"v{V}" if V > 1 else "") \
            + ("_q8" if activation_compression else "")
        pipe = PipelineConfig(num_stages=S, num_microbatches=microbatches,
                              microbatch_size=microbatch_size,
                              seq_len=seq_len, virtual_stages=V,
                              activation_compression=activation_compression)
        trainer = PipelineTrainer(cfg, pipe,
                                  run_name=f"bench_pipe_s{S}v{V}")
        try:
            trainer.train(1)  # compile + warm the channels
            t0 = time.perf_counter()
            stats = trainer.train(1 + steps)
            elapsed = time.perf_counter() - t0
            tps = steps * batch_tokens / elapsed
            bound = bubble_upper_bound(S, microbatches, V)
            sim = simulate(S, microbatches, num_chunks=V,
                           channel_depth=pipe.channel_depth)
            measured_idle = float(np.mean(
                [1.0 - c / w for c, w in
                 zip(stats[-1]["compute_s"],
                     [stats[-1]["wall_s"]] * S)]))
            rows += [
                {"name": f"{tag}_tokens_per_s", "value": tps,
                 "unit": "tokens/s"},
                {"name": f"{tag}_vs_single_mesh", "value":
                 tps / single_tps, "unit": "x"},
                # the simulator's bubble can never undercut the analytic
                # bound; benchtrack enforces the floor on this row
                {"name": f"{tag}_bubble_fraction",
                 "value": sim["bubble_fraction"], "unit": "fraction",
                 "meta": {"floor": bound}},
                {"name": f"{tag}_bubble_bound", "value": bound,
                 "unit": "fraction"},
                {"name": f"{tag}_idle_fraction_measured",
                 "value": measured_idle, "unit": "fraction"},
                {"name": f"{tag}_activation_bytes_per_microbatch",
                 "value": stats[-1]["activation_bytes_per_mb"],
                 "unit": "bytes"},
            ]
            rows += _hop_rows(tag, stats[-1].get("hop", []))
        finally:
            trainer.shutdown()

    rows.append({"name": "config", "value": 0, "unit": "meta",
                 "meta": {"n_layers": n_layers, "d_model": cfg.d_model,
                          "microbatches": microbatches,
                          "microbatch_size": microbatch_size,
                          "seq_len": seq_len, "steps": steps,
                          "interleave": interleave,
                          "activation_compression":
                          activation_compression,
                          # the host envelope: benchtrack only prices
                          # round-over-round moves between rounds from
                          # comparable machines
                          "host_cpus": os.cpu_count()}})
    if out:
        with open(out, "w") as f:
            json.dump(rows, f, indent=1)
    return rows


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", default="2,4")
    ap.add_argument("--microbatches", type=int, default=8)
    ap.add_argument("--microbatch-size", type=int, default=2)
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--n-layers", type=int, default=4)
    ap.add_argument("--interleave", type=int, default=1,
                    help="also bench V model chunks per rank (V>1)")
    ap.add_argument("--activation-compression", default=None,
                    help="stream fwd activations quantized (e.g. int8)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    rows = main(stages=tuple(int(s) for s in args.stages.split(",")),
                microbatches=args.microbatches,
                microbatch_size=args.microbatch_size,
                seq_len=args.seq_len, steps=args.steps,
                n_layers=args.n_layers, interleave=args.interleave,
                activation_compression=args.activation_compression,
                out=args.out)
    print(json.dumps(rows, indent=1))

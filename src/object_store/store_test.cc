// Native unit tests for the arena object store, driven through the same C
// ABI the Python binding uses (reference: the gtest tier colocated with
// src/ray/object_manager/plasma/tests — here assert-based so the only
// dependency is g++). Built and executed by tests/test_native_store.py.

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>

extern "C" {
void* rts_open(const char* path, uint64_t capacity, int create);
void rts_close(void* handle);
int rts_alloc(void* handle, const uint8_t* oid, uint64_t size, uint64_t* offset_out);
int rts_seal(void* handle, const uint8_t* oid);
int rts_lookup(void* handle, const uint8_t* oid, uint64_t* offset, uint64_t* size,
               int* sealed);
int rts_free(void* handle, const uint8_t* oid);
uint64_t rts_used(void* handle);
uint64_t rts_capacity(void* handle);
uint64_t rts_num_objects(void* handle);
uint64_t rts_largest_free(void* handle);
int rts_read(void* handle, uint64_t offset, uint64_t length, uint8_t* out);
int rts_write(void* handle, uint64_t offset, const uint8_t* data, uint64_t length);
}

namespace {

void MakeId(uint8_t* out, int n) {
  std::memset(out, 0, 16);
  std::memcpy(out, &n, sizeof(n));
}

int tests_run = 0;
#define CHECK(cond)                                                         \
  do {                                                                      \
    if (!(cond)) {                                                          \
      std::fprintf(stderr, "FAILED %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      return 1;                                                             \
    }                                                                       \
  } while (0)
#define RUN(fn)                          \
  do {                                   \
    if (fn(path)) return 1;              \
    ++tests_run;                         \
  } while (0)

int TestAllocSealLookupFree(const std::string& base) {
  std::string path = base + ".a";
  void* s = rts_open(path.c_str(), 1 << 20, 1);
  CHECK(s != nullptr);
  CHECK(rts_capacity(s) == (1u << 20));
  CHECK(rts_used(s) == 0);

  uint8_t id[16];
  MakeId(id, 1);
  uint64_t off = 0;
  CHECK(rts_alloc(s, id, 1000, &off) == 0);
  CHECK(off % 64 == 0);  // 64-byte aligned for zero-copy numpy/jax maps
  CHECK(rts_num_objects(s) == 1);

  uint64_t loff = 0, lsize = 0;
  int sealed = -1;
  CHECK(rts_lookup(s, id, &loff, &lsize, &sealed) == 0);
  CHECK(loff == off && lsize == 1000 && sealed == 0);

  const char payload[] = "arena-store-native-test";
  CHECK(rts_write(s, off, reinterpret_cast<const uint8_t*>(payload),
                  sizeof(payload)) == 0);
  CHECK(rts_seal(s, id) == 0);
  CHECK(rts_lookup(s, id, &loff, &lsize, &sealed) == 0);
  CHECK(sealed == 1);
  uint8_t back[sizeof(payload)] = {0};
  CHECK(rts_read(s, off, sizeof(payload), back) == 0);
  CHECK(std::memcmp(back, payload, sizeof(payload)) == 0);

  CHECK(rts_free(s, id) == 0);
  CHECK(rts_num_objects(s) == 0);
  CHECK(rts_used(s) == 0);
  rts_close(s);
  return 0;
}

int TestDuplicateAndMissing(const std::string& base) {
  std::string path = base + ".b";
  void* s = rts_open(path.c_str(), 1 << 20, 1);
  CHECK(s != nullptr);
  uint8_t id[16];
  MakeId(id, 7);
  uint64_t off = 0;
  CHECK(rts_alloc(s, id, 128, &off) == 0);
  // duplicate key must be rejected, not silently re-allocated
  CHECK(rts_alloc(s, id, 128, &off) != 0);
  uint8_t missing[16];
  MakeId(missing, 999);
  uint64_t o, sz;
  int sealed;
  CHECK(rts_lookup(s, missing, &o, &sz, &sealed) != 0);
  CHECK(rts_free(s, missing) != 0);
  rts_close(s);
  return 0;
}

int TestCoalescingRecoversLargestFree(const std::string& base) {
  std::string path = base + ".c";
  const uint64_t cap = 1 << 20;
  void* s = rts_open(path.c_str(), cap, 1);
  CHECK(s != nullptr);
  const uint64_t initial_largest = rts_largest_free(s);
  uint8_t ids[8][16];
  uint64_t off;
  for (int i = 0; i < 8; ++i) {
    MakeId(ids[i], 100 + i);
    CHECK(rts_alloc(s, ids[i], 32 * 1024, &off) == 0);
  }
  CHECK(rts_largest_free(s) < initial_largest);
  // free every other block: largest free stays fragmented...
  for (int i = 0; i < 8; i += 2) CHECK(rts_free(s, ids[i]) == 0);
  uint64_t fragmented = rts_largest_free(s);
  // ...then free the rest: neighbors must COALESCE back to one region
  for (int i = 1; i < 8; i += 2) CHECK(rts_free(s, ids[i]) == 0);
  CHECK(rts_largest_free(s) == initial_largest);
  CHECK(fragmented < initial_largest);
  rts_close(s);
  return 0;
}

int TestOutOfMemory(const std::string& base) {
  std::string path = base + ".d";
  void* s = rts_open(path.c_str(), 64 * 1024, 1);
  CHECK(s != nullptr);
  uint8_t id[16], id2[16];
  MakeId(id, 1);
  MakeId(id2, 2);
  uint64_t off;
  CHECK(rts_alloc(s, id, 32 * 1024, &off) == 0);
  // no contiguous room left for this one
  CHECK(rts_alloc(s, id2, 48 * 1024, &off) != 0);
  // freeing makes it fit again
  CHECK(rts_free(s, id) == 0);
  CHECK(rts_alloc(s, id2, 48 * 1024, &off) == 0);
  rts_close(s);
  return 0;
}

int TestReopenExisting(const std::string& base) {
  std::string path = base + ".e";
  void* s = rts_open(path.c_str(), 1 << 18, 1);
  CHECK(s != nullptr);
  uint8_t id[16];
  MakeId(id, 42);
  uint64_t off;
  CHECK(rts_alloc(s, id, 4096, &off) == 0);
  const char word[] = "persist";
  CHECK(rts_write(s, off, reinterpret_cast<const uint8_t*>(word),
                  sizeof(word)) == 0);
  rts_close(s);
  // a second mapping of the same file sees the same bytes (this is what
  // client processes do: open create=0 and read sealed regions zero-copy)
  void* s2 = rts_open(path.c_str(), 1 << 18, 0);
  CHECK(s2 != nullptr);
  uint8_t back[sizeof(word)] = {0};
  CHECK(rts_read(s2, off, sizeof(word), back) == 0);
  CHECK(std::memcmp(back, word, sizeof(word)) == 0);
  rts_close(s2);
  return 0;
}

int TestBoundsChecked(const std::string& base) {
  std::string path = base + ".f";
  void* s = rts_open(path.c_str(), 64 * 1024, 1);
  CHECK(s != nullptr);
  uint8_t buf[16] = {0};
  CHECK(rts_read(s, 60 * 1024, 8 * 1024, buf) != 0);   // past capacity
  CHECK(rts_write(s, 64 * 1024, buf, 1) != 0);
  rts_close(s);
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  std::string path = argc > 1 ? argv[1] : "/dev/shm/rtpu_native_test";
  RUN(TestAllocSealLookupFree);
  RUN(TestDuplicateAndMissing);
  RUN(TestCoalescingRecoversLargestFree);
  RUN(TestOutOfMemory);
  RUN(TestReopenExisting);
  RUN(TestBoundsChecked);
  std::printf("OK %d native arena tests\n", tests_run);
  return 0;
}

// Shared-memory arena object store (native core of the node object plane).
//
// Role-equivalent of the reference's plasma store internals
// (src/ray/object_manager/plasma: plasma_allocator.cc + dlmalloc arena +
// obj_lifecycle_mgr.cc), re-designed for the TPU host runtime: one mmap'd
// /dev/shm arena per node, a first-fit free list with coalescing, and an
// object table keyed by 16-byte ids. The raylet process owns allocation;
// worker processes map the same arena file and read objects zero-copy at
// the returned offsets (fd passing not required — the arena is a named
// file, which also lets jax/numpy map buffers directly).
//
// Exposed as a C ABI for ctypes (no pybind11 in the image).

#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <map>
#include <mutex>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <unordered_map>

namespace {

struct ObjectId {
  uint8_t bytes[16];
  bool operator==(const ObjectId& o) const {
    return std::memcmp(bytes, o.bytes, 16) == 0;
  }
};

struct ObjectIdHash {
  size_t operator()(const ObjectId& id) const {
    uint64_t h;
    std::memcpy(&h, id.bytes, 8);
    uint64_t l;
    std::memcpy(&l, id.bytes + 8, 8);
    return static_cast<size_t>(h ^ (l * 0x9e3779b97f4a7c15ULL));
  }
};

struct Entry {
  uint64_t offset;
  uint64_t size;
  bool sealed;
};

constexpr uint64_t kAlign = 64;

inline uint64_t AlignUp(uint64_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }

class ArenaStore {
 public:
  ArenaStore(const std::string& path, uint64_t capacity, bool create)
      : path_(path), capacity_(AlignUp(capacity)) {
    int flags = O_RDWR | (create ? O_CREAT : 0);
    fd_ = ::open(path.c_str(), flags, 0600);
    if (fd_ < 0) {
      ok_ = false;
      return;
    }
    if (create && ::ftruncate(fd_, static_cast<off_t>(capacity_)) != 0) {
      ok_ = false;
      return;
    }
    base_ = ::mmap(nullptr, capacity_, PROT_READ | PROT_WRITE, MAP_SHARED, fd_, 0);
    if (base_ == MAP_FAILED) {
      ok_ = false;
      return;
    }
    if (create) {
      free_list_[0] = capacity_;  // offset -> length
    }
  }

  ~ArenaStore() {
    if (base_ != nullptr && base_ != MAP_FAILED) ::munmap(base_, capacity_);
    if (fd_ >= 0) ::close(fd_);
  }

  bool ok() const { return ok_; }

  // First-fit allocation with free-list coalescing on free.
  int Alloc(const ObjectId& id, uint64_t size, uint64_t* offset_out) {
    std::lock_guard<std::mutex> g(mu_);
    if (objects_.count(id)) return -2;  // exists
    uint64_t need = AlignUp(size);
    for (auto it = free_list_.begin(); it != free_list_.end(); ++it) {
      if (it->second >= need) {
        uint64_t off = it->first;
        uint64_t rest = it->second - need;
        free_list_.erase(it);
        if (rest > 0) free_list_[off + need] = rest;
        objects_[id] = Entry{off, size, false};
        used_ += need;
        *offset_out = off;
        return 0;
      }
    }
    return -1;  // out of memory / fragmentation
  }

  int Seal(const ObjectId& id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end()) return -1;
    it->second.sealed = true;
    return 0;
  }

  int Lookup(const ObjectId& id, uint64_t* offset, uint64_t* size, int* sealed) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end()) return -1;
    *offset = it->second.offset;
    *size = it->second.size;
    *sealed = it->second.sealed ? 1 : 0;
    return 0;
  }

  int Free(const ObjectId& id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end()) return -1;
    uint64_t off = it->second.offset;
    uint64_t len = AlignUp(it->second.size);
    objects_.erase(it);
    used_ -= len;
    // coalesce with neighbors
    auto next = free_list_.lower_bound(off);
    if (next != free_list_.begin()) {
      auto prev = std::prev(next);
      if (prev->first + prev->second == off) {
        off = prev->first;
        len += prev->second;
        free_list_.erase(prev);
      }
    }
    next = free_list_.lower_bound(off + len);
    if (next != free_list_.end() && next->first == off + len) {
      len += next->second;
      free_list_.erase(next);
    }
    free_list_[off] = len;
    return 0;
  }

  uint64_t Used() {
    std::lock_guard<std::mutex> g(mu_);
    return used_;
  }

  uint64_t Capacity() const { return capacity_; }
  void* Base() const { return base_; }

  uint64_t NumObjects() {
    std::lock_guard<std::mutex> g(mu_);
    return objects_.size();
  }

  uint64_t LargestFree() {
    std::lock_guard<std::mutex> g(mu_);
    uint64_t best = 0;
    for (auto& kv : free_list_) best = kv.second > best ? kv.second : best;
    return best;
  }

 private:
  std::string path_;
  uint64_t capacity_;
  int fd_ = -1;
  void* base_ = nullptr;
  bool ok_ = true;
  std::mutex mu_;
  std::unordered_map<ObjectId, Entry, ObjectIdHash> objects_;
  std::map<uint64_t, uint64_t> free_list_;  // offset -> length, sorted
  uint64_t used_ = 0;
};

ObjectId ToId(const uint8_t* oid) {
  ObjectId id;
  std::memcpy(id.bytes, oid, 16);
  return id;
}

}  // namespace

extern "C" {

void* rts_open(const char* path, uint64_t capacity, int create) {
  auto* store = new ArenaStore(path, capacity, create != 0);
  if (!store->ok()) {
    delete store;
    return nullptr;
  }
  return store;
}

void rts_close(void* handle) { delete static_cast<ArenaStore*>(handle); }

int rts_alloc(void* handle, const uint8_t* oid, uint64_t size, uint64_t* offset_out) {
  return static_cast<ArenaStore*>(handle)->Alloc(ToId(oid), size, offset_out);
}

int rts_seal(void* handle, const uint8_t* oid) {
  return static_cast<ArenaStore*>(handle)->Seal(ToId(oid));
}

int rts_lookup(void* handle, const uint8_t* oid, uint64_t* offset, uint64_t* size,
               int* sealed) {
  return static_cast<ArenaStore*>(handle)->Lookup(ToId(oid), offset, size, sealed);
}

int rts_free(void* handle, const uint8_t* oid) {
  return static_cast<ArenaStore*>(handle)->Free(ToId(oid));
}

uint64_t rts_used(void* handle) { return static_cast<ArenaStore*>(handle)->Used(); }

uint64_t rts_capacity(void* handle) {
  return static_cast<ArenaStore*>(handle)->Capacity();
}

uint64_t rts_num_objects(void* handle) {
  return static_cast<ArenaStore*>(handle)->NumObjects();
}

uint64_t rts_largest_free(void* handle) {
  return static_cast<ArenaStore*>(handle)->LargestFree();
}

// direct data access helpers (server-side copies for spill/restore)
int rts_read(void* handle, uint64_t offset, uint64_t length, uint8_t* out) {
  auto* store = static_cast<ArenaStore*>(handle);
  if (offset + length > store->Capacity()) return -1;
  std::memcpy(out, static_cast<uint8_t*>(store->Base()) + offset, length);
  return 0;
}

int rts_write(void* handle, uint64_t offset, const uint8_t* data, uint64_t length) {
  auto* store = static_cast<ArenaStore*>(handle);
  if (offset + length > store->Capacity()) return -1;
  std::memcpy(static_cast<uint8_t*>(store->Base()) + offset, data, length);
  return 0;
}

}  // extern "C"

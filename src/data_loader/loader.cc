// Native token-file data loader (role-equivalent of the reference's C++
// dataset/dataloader plumbing, re-architected for TPU input pipelines:
// the hot path that keeps a per-host training loop fed must not run in
// Python). An mmap'd token file is sampled into a ring of batch buffers
// by a background prefetch thread; the Python side (ctypes wrapper in
// ray_tpu/data/token_loader.py) hands zero-copy int32 views straight to
// jax.device_put.
//
// File format: raw little-endian tokens, dtype selected by token_bytes
// (2 = uint16, 4 = int32/uint32). Each sampled row is `seq + 1`
// consecutive tokens at a seeded-random offset (targets = inputs shifted
// by one, sliced in Python).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct XorShift {
  uint64_t s;
  explicit XorShift(uint64_t seed) : s(seed ? seed : 0x9e3779b97f4a7c15ULL) {}
  uint64_t next() {
    s ^= s << 13;
    s ^= s >> 7;
    s ^= s << 17;
    return s;
  }
};

struct Loader {
  int fd = -1;
  const uint8_t* data = nullptr;
  size_t file_bytes = 0;
  int64_t num_tokens = 0;
  int token_bytes = 4;
  int64_t batch = 0, seq = 0;
  int n_buffers = 0;
  std::vector<int32_t*> buffers;      // n_buffers x (batch * (seq+1))
  std::vector<int> state;             // 0=free, 1=filled, 2=held
  std::mutex mu;
  std::condition_variable cv_filled, cv_free;
  std::thread worker;
  std::atomic<bool> stop{false};
  XorShift rng;
  int64_t batches_produced = 0;

  explicit Loader(uint64_t seed) : rng(seed) {}

  void fill_loop() {
    while (!stop.load(std::memory_order_relaxed)) {
      int slot = -1;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_free.wait(lk, [&] {
          if (stop.load(std::memory_order_relaxed)) return true;
          for (int i = 0; i < n_buffers; i++)
            if (state[i] == 0) return true;
          return false;
        });
        if (stop.load(std::memory_order_relaxed)) return;
        for (int i = 0; i < n_buffers; i++)
          if (state[i] == 0) { slot = i; break; }
      }
      fill(buffers[slot]);
      {
        std::lock_guard<std::mutex> lk(mu);
        state[slot] = 1;
        batches_produced++;
      }
      cv_filled.notify_one();
    }
  }

  void fill(int32_t* out) {
    const int64_t row = seq + 1;
    const int64_t max_start = num_tokens - row;
    for (int64_t b = 0; b < batch; b++) {
      int64_t start = max_start > 0 ? (int64_t)(rng.next() % (uint64_t)(max_start + 1)) : 0;
      if (token_bytes == 4) {
        std::memcpy(out + b * row, data + (size_t)start * 4, (size_t)row * 4);
      } else {  // widen uint16 -> int32
        const uint16_t* src = reinterpret_cast<const uint16_t*>(data) + start;
        int32_t* dst = out + b * row;
        for (int64_t i = 0; i < row; i++) dst[i] = (int32_t)src[i];
      }
    }
  }
};

}  // namespace

extern "C" {

void* dl_create(const char* path, int64_t batch, int64_t seq, uint64_t seed,
                int n_buffers, int token_bytes) {
  if (n_buffers < 1 || batch < 1 || seq < 1) return nullptr;
  if (token_bytes != 2 && token_bytes != 4) return nullptr;
  auto* L = new Loader(seed);
  L->fd = open(path, O_RDONLY);
  if (L->fd < 0) { delete L; return nullptr; }
  struct stat st;
  if (fstat(L->fd, &st) != 0 || st.st_size < (seq + 1) * token_bytes) {
    close(L->fd);
    delete L;
    return nullptr;
  }
  L->file_bytes = (size_t)st.st_size;
  L->data = (const uint8_t*)mmap(nullptr, L->file_bytes, PROT_READ, MAP_PRIVATE,
                                 L->fd, 0);
  if (L->data == MAP_FAILED) { close(L->fd); delete L; return nullptr; }
  madvise((void*)L->data, L->file_bytes, MADV_RANDOM);
  L->token_bytes = token_bytes;
  L->num_tokens = (int64_t)(L->file_bytes / (size_t)token_bytes);
  L->batch = batch;
  L->seq = seq;
  L->n_buffers = n_buffers;
  L->buffers.resize(n_buffers);
  L->state.assign(n_buffers, 0);
  for (int i = 0; i < n_buffers; i++)
    L->buffers[i] = new int32_t[(size_t)batch * (size_t)(seq + 1)];
  L->worker = std::thread([L] { L->fill_loop(); });
  return L;
}

// Blocks until a filled buffer is ready; returns its slot (>=0), marks held.
int dl_next(void* h) {
  auto* L = (Loader*)h;
  std::unique_lock<std::mutex> lk(L->mu);
  L->cv_filled.wait(lk, [&] {
    if (L->stop.load(std::memory_order_relaxed)) return true;
    for (int i = 0; i < L->n_buffers; i++)
      if (L->state[i] == 1) return true;
    return false;
  });
  if (L->stop.load(std::memory_order_relaxed)) return -1;
  for (int i = 0; i < L->n_buffers; i++) {
    if (L->state[i] == 1) {
      L->state[i] = 2;
      return i;
    }
  }
  return -1;
}

int32_t* dl_buffer(void* h, int slot) {
  auto* L = (Loader*)h;
  if (slot < 0 || slot >= L->n_buffers) return nullptr;
  return L->buffers[slot];
}

void dl_release(void* h, int slot) {
  auto* L = (Loader*)h;
  {
    std::lock_guard<std::mutex> lk(L->mu);
    if (slot >= 0 && slot < L->n_buffers && L->state[slot] == 2)
      L->state[slot] = 0;
  }
  L->cv_free.notify_one();
}

int64_t dl_num_tokens(void* h) { return ((Loader*)h)->num_tokens; }

int64_t dl_batches_produced(void* h) {
  auto* L = (Loader*)h;
  std::lock_guard<std::mutex> lk(L->mu);
  return L->batches_produced;
}

void dl_destroy(void* h) {
  auto* L = (Loader*)h;
  {
    // store under the mutex: orders against the cv predicates so the
    // worker / a blocked dl_next can't miss the wakeup
    std::lock_guard<std::mutex> lk(L->mu);
    L->stop.store(true);
  }
  L->cv_free.notify_all();
  L->cv_filled.notify_all();
  if (L->worker.joinable()) L->worker.join();
  for (auto* b : L->buffers) delete[] b;
  if (L->data && L->data != MAP_FAILED) munmap((void*)L->data, L->file_bytes);
  if (L->fd >= 0) close(L->fd);
  delete L;
}

}  // extern "C"

"""Serve gRPC ingress (reference: serve/_private/proxy.py gRPC proxy):
unary and server-streaming routing to deployments over a generic handler."""

import time

import pytest

pytest.importorskip("grpc")

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.grpc_ingress import ServeGrpcClient, start_grpc_proxy


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(resources={"CPU": 6.0})
    yield
    try:
        serve.shutdown()
    except Exception:
        pass
    ray_tpu.shutdown()


def test_grpc_unary_and_stream(cluster):
    @serve.deployment
    class Api:
        def __call__(self, body):
            return {"sum": sum(body.get("xs", []))}

        async def tokens(self, body):
            import asyncio

            for i in range(int(body.get("n", 3))):
                await asyncio.sleep(0.2)
                yield {"tok": i}

    serve.run(Api.bind(), name="api")
    port = start_grpc_proxy()
    client = ServeGrpcClient(f"127.0.0.1:{port}")
    try:
        assert client.call("api", {"xs": [1, 2, 3]}) == {"sum": 6}

        t0 = time.monotonic()
        first_at = None
        chunks = []
        for chunk in client.stream("api", {"n": 3}, method="tokens"):
            if first_at is None:
                first_at = time.monotonic() - t0
            chunks.append(chunk)
        assert chunks == [{"tok": 0}, {"tok": 1}, {"tok": 2}]
        assert first_at < 0.55, f"stream not incremental: {first_at:.2f}s"
    finally:
        client.close()
        serve.delete("api")


def test_grpc_unknown_deployment_errors(cluster):
    import grpc

    @serve.deployment
    def noop(body):
        return 1

    serve.run(noop.bind(), name="noop")
    port = start_grpc_proxy()
    client = ServeGrpcClient(f"127.0.0.1:{port}")
    try:
        with pytest.raises(grpc.RpcError):
            client.call("no-such-deployment", {}, timeout=15.0)
    finally:
        client.close()
        serve.delete("noop")

"""Cluster health plane tests: metrics time-series history (two downsample
tiers), the GCS task-timeline endpoint (Perfetto golden), the
stuck/straggler health monitor, built-in hot-path spans (train step + serve
request with ZERO manual instrumentation), obs fork-safety, and the
off-loop task-event read handoff."""

import asyncio
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu._private import task_events
from ray_tpu.util import state, tracing

# fast cadences for the cluster-backed tests: both history tiers fill and
# the health monitor scans within seconds (must be set before the fixture
# spawns the GCS — children inherit the env)
_FAST_ENV = {
    "RAY_TPU_ENABLE_TRACING": "1",
    "RAY_TPU_METRICS_HISTORY_INTERVAL_S": "0.5",
    "RAY_TPU_METRICS_HISTORY_ROLLUP_S": "2.0",
    "RAY_TPU_HEALTH_SCAN_INTERVAL_S": "1.0",
    "RAY_TPU_METRICS_FLUSH_INTERVAL_S": "2.0",
}


@pytest.fixture(scope="module")
def health_cluster():
    ray_tpu.shutdown()
    old = {k: os.environ.get(k) for k in _FAST_ENV}
    os.environ.update(_FAST_ENV)
    tracing._enabled = None  # re-read the flag
    worker = ray_tpu.init(num_cpus=4, include_dashboard=True)
    yield worker
    ray_tpu.shutdown()
    for k, v in old.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    tracing._enabled = None


def _wait_for(predicate, timeout=30, interval=0.5):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = predicate()
        if out:
            return out
        time.sleep(interval)
    return predicate()


def _http_json(address, path):
    with urllib.request.urlopen(f"http://{address}{path}", timeout=30) as r:
        return json.loads(r.read().decode())


# ---------------------------------------------------------------------------
# metrics history: two tiers + rollup correctness (unit)
# ---------------------------------------------------------------------------


def _payload(t, node, metrics):
    return {"pid": 1, "time": t, "node": node, "metrics": metrics}


def test_metrics_history_two_tiers_and_rollup():
    from ray_tpu._private.gcs import MetricsHistory

    h = MetricsHistory(raw_interval_s=5.0, raw_points=8,
                       rollup_interval_s=60.0, rollup_points=4)
    t0 = time.time()
    for i in range(25):
        t = t0 + i * 5
        h.observe_payload("procA", _payload(t, "n1", {
            "ray_tpu_g": {"kind": "gauge", "description": "d",
                          "data": {"{}": float(i)}},
            "ray_tpu_c": {"kind": "counter", "description": "d",
                          "data": {"{}": 10.0 * i}},
            "ray_tpu_h": {"kind": "histogram", "description": "d",
                          "data": {"counts": {"{}": [i, 2 * i, 0]},
                                   "sums": {"{}": 0.5 * i},
                                   "boundaries": [0.1, 1.0]}},
        }))
        # a second process contributes too: gauges sum across processes
        h.observe_payload("procB", _payload(t, "n2", {
            "ray_tpu_g": {"kind": "gauge", "description": "d",
                          "data": {"{}": 100.0}}}))
        h.sample(now=t)

    # raw tier: bounded ring at the 5 s cadence
    raw = h.series("ray_tpu_g", tier="raw", now=t0 + 24 * 5)
    assert raw["tier"] == "raw" and raw["interval_s"] == 5.0
    assert len(raw["points"]) == 8  # ring bound
    assert raw["points"][-1]["value"] == 24.0 + 100.0  # cross-process sum
    assert raw["points"][-1]["max"] == 100.0

    # rollup tier: avg/min/max over the raw points of each 60 s window
    roll = h.series("ray_tpu_g", tier="rollup", now=t0 + 24 * 5)
    assert roll["tier"] == "rollup" and roll["interval_s"] == 60.0
    assert len(roll["points"]) >= 2
    last = roll["points"][-1]
    # last rollup at t0+120 over the raw points still in the 8-deep ring
    # AND inside the 60 s window: samples i=17..24 -> values 117..124
    contributing = [i + 100.0 for i in range(17, 25)]
    assert last["value"] == pytest.approx(sum(contributing)
                                          / len(contributing))
    assert last["min"] == pytest.approx(min(contributing))

    # counters: cumulative last + rate; histograms keep bucket vectors
    c_last = h.series("ray_tpu_c", tier="rollup")["points"][-1]
    assert c_last["value"] == 240.0
    assert c_last["rate"] == pytest.approx(10.0 / 5.0)  # +10 every 5 s
    h_last = h.series("ray_tpu_h", tier="rollup")["points"][-1]
    assert h_last["count"] == 24 + 48
    assert h_last["buckets"] == [24, 48, 0]
    assert h_last["boundaries"] == [0.1, 1.0]
    assert set(h.names()) == {"ray_tpu_c", "ray_tpu_g", "ray_tpu_h"}

    # auto tier: a window wider than the raw ring escalates to rollup
    assert h.series("ray_tpu_g", window_s=30.0)["tier"] == "raw"
    assert h.series("ray_tpu_g", window_s=3600.0)["tier"] == "rollup"


def test_metrics_history_stale_process_pruned():
    from ray_tpu._private.gcs import MetricsHistory

    h = MetricsHistory(raw_interval_s=5.0, raw_points=8,
                       rollup_interval_s=60.0, rollup_points=4)
    now = time.time()
    h.observe_payload("dead", _payload(now - 600, "n1", {
        "ray_tpu_g": {"kind": "gauge", "description": "d",
                      "data": {"{}": 7.0}}}))
    h.sample(now=now)
    assert h.series("ray_tpu_g", tier="raw")["points"] == []
    assert h.latest_by_node("ray_tpu_g") == {}


# ---------------------------------------------------------------------------
# timeline golden (unit)
# ---------------------------------------------------------------------------


def _mk_records():
    from ray_tpu._private.gcs import GcsTaskManager

    mgr = GcsTaskManager(max_per_job=64)
    t0 = 1000.0
    mgr.add_events([
        {"task_id": "p1", "job_id": "j", "state": "SUBMITTED", "ts": t0,
         "name": "parent_fn", "span_id": "spanP"},
        {"task_id": "p1", "job_id": "j", "state": "RUNNING", "ts": t0 + 0.2,
         "worker": "w1", "node": "nodeA", "span_id": "spanP"},
        {"task_id": "c1", "job_id": "j", "state": "SUBMITTED",
         "ts": t0 + 0.3, "name": "child_fn", "span_id": "spanC",
         "parent_span": "spanP"},
        {"task_id": "c1", "job_id": "j", "state": "RUNNING", "ts": t0 + 0.5,
         "worker": "w2", "node": "nodeB"},
        {"task_id": "c1", "job_id": "j", "state": "FINISHED", "ts": t0 + 0.9},
        {"task_id": "p1", "job_id": "j", "state": "FINISHED", "ts": t0 + 1.0},
        # an old task outside the query window
        {"task_id": "old", "job_id": "j", "state": "FINISHED", "ts": 10.0,
         "name": "ancient"},
    ])
    return mgr.list_tasks(limit=100)


def test_build_timeline_golden_perfetto():
    from ray_tpu._private.gcs import build_timeline

    trace = build_timeline(_mk_records(), spans=[
        {"name": "train.step", "cat": "train", "ts": 1000.4, "dur": 0.1,
         "pid": 42, "tid": 7, "span_id": "s1"}])
    # Perfetto golden: round-trips through JSON with a traceEvents list
    trace = json.loads(json.dumps(trace))
    events = trace["traceEvents"]
    assert isinstance(events, list) and events

    slices = [e for e in events if e.get("ph") == "X"]
    for e in slices:  # chrome-trace required slice keys
        assert {"name", "ph", "ts", "pid", "tid", "dur"} <= set(e)
    names = {e["name"] for e in slices}
    assert {"parent_fn", "child_fn", "pending:child_fn",
            "train.step"} <= names

    # track metadata: one process per node, threads named per worker
    procs = [e for e in events if e.get("name") == "process_name"]
    assert {p["args"]["name"] for p in procs} >= {"node:nodeA", "node:nodeB"}

    # flow arrows: the parent->child task edge renders as a matched
    # s/f pair binding inside the parent slice
    starts = [e for e in events if e.get("ph") == "s"]
    finishes = [e for e in events if e.get("ph") == "f"]
    assert {e["id"] for e in starts} == {e["id"] for e in finishes}
    assert len(starts) >= 1
    parent_slice = next(e for e in slices if e["name"] == "parent_fn")
    s0 = starts[0]
    assert parent_slice["ts"] <= s0["ts"] <= \
        parent_slice["ts"] + parent_slice["dur"]

    # time-window filter drops the ancient task but keeps the fresh pair
    windowed = build_timeline(_mk_records(), start_ts=999.0, end_ts=1002.0)
    wnames = {e["name"] for e in windowed["traceEvents"]
              if e.get("ph") == "X"}
    assert "ancient" not in wnames and "parent_fn" in wnames


# ---------------------------------------------------------------------------
# health monitor (unit, against a bare GcsServer)
# ---------------------------------------------------------------------------


def test_health_scan_flags_stuck_straggler_and_pool():
    from ray_tpu._private import wire
    from ray_tpu._private.gcs import GcsServer

    gcs = GcsServer()
    now = time.time()
    # per-function history: 5 quick FINISHED runs of stuck_fn, then one
    # RUNNING for 120 s (>> p99 and the 30 s floor)
    events = []
    for i in range(5):
        t = now - 300 + i
        events += [
            {"task_id": f"ok{i}", "job_id": "j", "state": "RUNNING",
             "ts": t, "name": "stuck_fn"},
            {"task_id": f"ok{i}", "job_id": "j", "state": "FINISHED",
             "ts": t + 0.1, "name": "stuck_fn"},
        ]
    events.append({"task_id": "victim", "job_id": "j", "state": "RUNNING",
                   "ts": now - 120, "name": "stuck_fn", "node": "nodeX",
                   "worker": "w9"})
    # a fresh RUNNING task must NOT be flagged
    events.append({"task_id": "fresh", "job_id": "j", "state": "RUNNING",
                   "ts": now - 1, "name": "stuck_fn"})
    gcs.task_manager.ingest(events)

    # straggler: node n3's lease queue is an outlier vs the median
    for node, depth in (("n1", 0.0), ("n2", 1.0), ("n3", 50.0)):
        gcs.metrics_history.observe_payload(f"raylet_{node}", _payload(
            now, node, {"ray_tpu_raylet_lease_queue_depth": {
                "kind": "gauge", "description": "d",
                "data": {"{}": depth}}}))

    # provisioning pathology: a dead zygote and a starved warm pool
    gcs.kv[("workers", "raylet_n4")] = wire.dumps(
        {"node": "n4", "time": now,
         "pool": {"enabled": True, "zygote_alive": False,
                  "zygote_restarts": 3}})
    gcs.kv[("workers", "raylet_n5")] = wire.dumps(
        {"node": "n5", "time": now,
         "pool": {"enabled": True, "zygote_alive": True, "warm_target": 2,
                  "warm_default_env": 0, "misses": 10}})

    report = asyncio.run(gcs._health_scan())
    gcs.task_manager.stop()

    kinds = {}
    for f in report["findings"]:
        kinds.setdefault(f["kind"], []).append(f)
    assert report["status"] == "error"  # dead zygote is an error
    stuck = kinds["stuck_task"]
    assert [f["task_id"] for f in stuck] == ["victim"]
    assert stuck[0]["age_s"] > stuck[0]["threshold_s"]
    assert stuck[0]["p99_s"] == pytest.approx(0.1, abs=0.05)
    stragglers = kinds["straggler_node"]
    assert [f["node"] for f in stragglers] == ["n3"]
    assert stragglers[0]["metric"] == "ray_tpu_raylet_lease_queue_depth"
    assert [f["node"] for f in kinds["dead_zygote"]] == ["n4"]
    assert [f["node"] for f in kinds["pool_starvation"]] == ["n5"]


def test_health_warnings_are_rate_limited(caplog):
    import logging

    from ray_tpu._private.gcs import GcsServer

    gcs = GcsServer()
    now = time.time()
    gcs.task_manager.ingest([
        {"task_id": "victim", "job_id": "j", "state": "RUNNING",
         "ts": now - 10_000, "name": "lonely_fn"}])
    with caplog.at_level(logging.WARNING, logger="ray_tpu.gcs"):
        asyncio.run(gcs._health_scan())
        asyncio.run(gcs._health_scan())  # same finding, inside the window
    gcs.task_manager.stop()
    warned = [r for r in caplog.records if "stuck_task" in r.getMessage()]
    assert len(warned) == 1  # once per health_warn_interval_s, not per scan


# ---------------------------------------------------------------------------
# task-event read handoff runs off the event loop (unit)
# ---------------------------------------------------------------------------


def test_read_handoff_merges_and_runs_off_loop():
    from ray_tpu._private.gcs import ShardedTaskEvents

    tm = ShardedTaskEvents(nshards=4)
    tm.ingest([{"task_id": f"t{i:04x}", "job_id": "j", "state": "FINISHED",
                "ts": float(i), "name": "fn"} for i in range(500)])

    async def main():
        loop_thread = threading.get_ident()
        seen = {}

        def closure(t):
            seen["thread"] = threading.get_ident()
            return t.summarize()

        summ = await tm.read(closure)
        return loop_thread, seen["thread"], summ

    loop_thread, merge_thread, summ = asyncio.run(main())
    tm.stop()
    assert merge_thread != loop_thread  # query ran on the merge thread
    assert summ["total"] == 500  # read-your-writes: everything enqueued


# ---------------------------------------------------------------------------
# fork safety (unit): a forked worker never re-emits inherited buffers
# ---------------------------------------------------------------------------


@pytest.mark.skipif(not hasattr(os, "fork"), reason="fork-less platform")
def test_fork_resets_inherited_obs_buffers():
    from ray_tpu.util.metrics import Counter

    task_events.set_enabled(True)
    task_events.record("deadbeef", task_events.SUBMITTED, name="fork_probe")
    old_enabled = tracing._enabled
    tracing._enabled = True
    tracing.record_span("fork_parent_span", time.time(), time.time())
    old_tag = tracing._proc_tag
    counter = Counter("ray_tpu_fork_probe_total", "fork-safety probe")
    counter.inc(5)

    r, w = os.pipe()
    pid = os.fork()
    if pid == 0:  # child: the zygote fork path's reset, then introspect
        code = 1
        try:
            os.close(r)
            from ray_tpu._private.worker_main import (
                reset_observability_after_fork)

            reset_observability_after_fork()
            events, dropped = task_events.drain()
            with tracing._lock:
                n_spans = len(tracing._buffer)
            os.write(w, json.dumps({
                "events": len(events), "dropped": dropped,
                "spans": n_spans,
                "tag_changed": tracing._proc_tag != old_tag,
                "counter": sum(counter.snapshot().values()),
            }).encode())
            code = 0
        finally:
            os._exit(code)
    os.close(w)
    try:
        chunks = b""
        while True:
            chunk = os.read(r, 65536)
            if not chunk:
                break
            chunks += chunk
        _, status = os.waitpid(pid, 0)
        assert os.waitstatus_to_exitcode(status) == 0
        out = json.loads(chunks.decode())
        # the child re-emits NOTHING of the parent's buffers, and flushes
        # under its own proc tag (no clobbering the parent's GCS keys)
        assert out == {"events": 0, "dropped": 0, "spans": 0,
                       "tag_changed": True, "counter": 0}
        # the parent's buffers are untouched
        events, _ = task_events.drain()
        assert [e["task_id"] for e in events] == ["deadbeef"]
    finally:
        os.close(r)
        tracing._enabled = old_enabled
        task_events.set_enabled(None)
        with tracing._lock:
            tracing._buffer.clear()


# ---------------------------------------------------------------------------
# cluster: built-in hot-path spans (the acceptance tier-1 test)
# ---------------------------------------------------------------------------


def test_train_and_serve_builtin_spans(health_cluster, tmp_path):
    """One train step + one serve request, ZERO manual instrumentation:
    the built-in spans and histograms must land in /metrics and the
    chrome trace."""
    tracing.clear()

    # --- one REAL train step through the library path ---
    import jax
    import numpy as np

    from ray_tpu.models import CONFIGS
    from ray_tpu.parallel import TrainStepBundle, create_mesh

    mesh = create_mesh({"data": 1, "fsdp": 1, "seq": 1, "tensor": 1,
                        "expert": 1}, devices=jax.devices()[:1])
    bundle = TrainStepBundle(CONFIGS["tiny"], mesh)
    params, opt_state = bundle.init(jax.random.PRNGKey(0))
    batch = bundle.make_batch(np.random.default_rng(0), 2, 64)
    params, opt_state, loss = bundle.step(params, opt_state, batch)
    assert float(loss) > 0

    # --- one REAL serve request through a handle ---
    from ray_tpu import serve

    @serve.deployment(name="span_echo", num_replicas=1)
    class Echo:
        def __call__(self, x):
            return x * 2

    handle = serve.run(Echo.bind(), name="span_echo")
    assert ray_tpu.get(handle.remote(21), timeout=120) == 42

    # spans: train phases from this process, serve phases cluster-wide
    def _spans():
        spans = tracing.get_spans()
        names = {s["name"] for s in spans}
        want = {"train.step", "train.fwd_bwd", "train.optimizer",
                "serve.route", "serve.queue", "serve.execute"}
        return spans if want <= names else None

    spans = _wait_for(_spans, timeout=30)
    assert spans is not None, {s["name"] for s in tracing.get_spans()}
    by_name = {s["name"]: s for s in spans}
    # the phase spans tree up under train.step
    assert by_name["train.fwd_bwd"]["parent_id"] == \
        by_name["train.step"]["span_id"]

    # chrome trace: the built-in spans render as slices
    out = str(tmp_path / "trace.json")
    tracing.export_chrome_trace(out)
    names = {e["name"] for e in json.load(open(out))["traceEvents"]}
    assert {"train.step", "serve.execute"} <= names

    # /metrics: the built-in histograms ship via the auto-flush loops
    # (train histograms live in THIS driver process: force one publish
    # instead of waiting out the flush interval)
    from ray_tpu.util.metrics import publish_metrics

    publish_metrics()
    address = health_cluster.node_supervisor.dashboard_address

    def _metrics():
        with urllib.request.urlopen(f"http://{address}/metrics",
                                    timeout=30) as r:
            body = r.read().decode()
        want = ("ray_tpu_train_step_seconds_bucket",
                "ray_tpu_train_fwd_bwd_seconds_count",
                "ray_tpu_serve_execute_seconds_bucket",
                "ray_tpu_serve_queue_seconds_count",
                "ray_tpu_serve_requests")
        return body if all(w in body for w in want) else None

    body = _wait_for(_metrics, timeout=40)
    assert body is not None, "built-in hot-path histograms missing"

    # /api/timeline: the same spans and the task slices in ONE trace
    def _timeline():
        trace = _http_json(address, "/api/timeline")
        names = {e["name"] for e in trace["traceEvents"]}
        return trace if "train.step" in names else None

    trace = _wait_for(_timeline, timeout=30)
    assert trace is not None
    events = trace["traceEvents"]
    assert any(e.get("cat") == "task" for e in events)  # task slices
    serve.shutdown()


# ---------------------------------------------------------------------------
# cluster: health endpoint + CLI flag injected pathologies
# ---------------------------------------------------------------------------


def test_health_endpoint_and_cli_flag_injected_pathology(health_cluster):
    from ray_tpu._private import worker as worker_mod

    core = worker_mod.global_worker()
    now = time.time()
    events = []
    for i in range(5):
        t = now - 300 + i
        events += [
            {"task_id": f"hok{i:02d}", "job_id": "healthj",
             "state": "RUNNING", "ts": t, "name": "inject_stuck_fn"},
            {"task_id": f"hok{i:02d}", "job_id": "healthj",
             "state": "FINISHED", "ts": t + 0.1, "name": "inject_stuck_fn"},
        ]
    events.append({"task_id": "hvictim", "job_id": "healthj",
                   "state": "RUNNING", "ts": now - 300,
                   "name": "inject_stuck_fn", "node": "nodeS"})
    core._run(core._gcs_call("AddTaskEvents", {"events": events}))

    # straggler raylet: synthetic per-node metric snapshots (one outlier)
    from ray_tpu._private import wire

    for node, lag in (("fakeA", 0.01), ("fakeB", 0.02), ("fakeC", 9.0)):
        core._run(core._gcs_call("KVPut", {
            "ns": "metrics", "key": f"proc_fake_{node}",
            "value": wire.dumps(_payload(time.time(), node, {
                "ray_tpu_raylet_loop_lag_seconds": {
                    "kind": "gauge", "description": "d",
                    "data": {"{}": lag}}}))}))

    address = health_cluster.node_supervisor.dashboard_address
    # flagged within one scan interval (1 s here); ?scan=1 forces one NOW
    health = _http_json(address, "/api/health?scan=1")
    kinds = {f["kind"]: f for f in health["findings"]}
    assert health["status"] in ("warning", "error")
    assert "stuck_task" in kinds, health
    assert kinds["stuck_task"]["name"] == "inject_stuck_fn"
    assert "straggler_node" in kinds, health
    assert kinds["straggler_node"]["node"] == "fakeC"

    # the periodic scanner also picks it up without ?scan (one interval)
    periodic = _wait_for(
        lambda: (lambda h: h if h["findings"] else None)(
            _http_json(address, "/api/health")), timeout=15)
    assert periodic and periodic["scan_count"] >= 1

    # util.state surface
    health2 = state.cluster_health()
    assert any(f["kind"] == "stuck_task" for f in health2["findings"])

    # ray-tpu health CLI (a real subprocess driver)
    gcs_address = health_cluster.node_supervisor.gcs_address
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "--address",
         gcs_address, "health", "--scan"],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr[-2000:]
    assert "stuck_task" in out.stdout
    assert "straggler_node" in out.stdout


# ---------------------------------------------------------------------------
# cluster: metrics history endpoint serves both tiers
# ---------------------------------------------------------------------------


def test_metrics_history_endpoint_two_tiers(health_cluster):
    address = health_cluster.node_supervisor.dashboard_address

    # raylet gauges flush every 2 s here; the 0.5 s sampler then has
    # points, and the 2 s rollup tier fills shortly after
    def _names():
        names = _http_json(address, "/api/metrics/history")
        return names if "ray_tpu_raylet_lease_queue_depth" in names else None

    assert _wait_for(_names, timeout=40), "no metric names recorded"

    def _raw():
        h = _http_json(
            address, "/api/metrics/history"
                     "?name=ray_tpu_raylet_lease_queue_depth&tier=raw")
        return h if len(h["points"]) >= 2 else None

    raw = _wait_for(_raw, timeout=30)
    assert raw and raw["tier"] == "raw"
    assert all("value" in p and "ts" in p for p in raw["points"])

    def _rollup():
        h = _http_json(
            address, "/api/metrics/history"
                     "?name=ray_tpu_raylet_lease_queue_depth&tier=rollup")
        return h if h["points"] else None

    roll = _wait_for(_rollup, timeout=30)
    assert roll and roll["tier"] == "rollup"
    assert {"value", "min", "max", "n_raw"} <= set(roll["points"][-1])

    # the window parameter picks the tier automatically
    auto = _http_json(
        address, "/api/metrics/history"
                 "?name=ray_tpu_raylet_lease_queue_depth&window=86400")
    assert auto["tier"] == "rollup"

    # util.state surface reads the same series
    assert "ray_tpu_raylet_lease_queue_depth" in state.metrics_history()
    s = state.metrics_history("ray_tpu_raylet_lease_queue_depth",
                              tier="raw")
    assert s["points"]

"""Client-mode (ray-tpu://) tests (reference tier: util/client tests)."""

import subprocess
import sys
import time

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def client_cluster(tmp_path_factory):
    """A cluster + client proxy in a separate process; this test process
    connects only through ray-tpu:// (a true external client)."""
    ray_tpu.shutdown()
    tmp = tmp_path_factory.mktemp("client")
    script = tmp / "host.py"
    script.write_text(
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import ray_tpu\n"
        "from ray_tpu.util.client import start_client_server\n"
        "ray_tpu.init(num_cpus=4)\n"
        "start_client_server(port=0, host='127.0.0.1')\n")
    import os

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + ":" + env.get("PYTHONPATH", "")
    proc = subprocess.Popen([sys.executable, str(script)], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    deadline = time.monotonic() + 120
    addr = None
    while time.monotonic() < deadline:
        line = proc.stdout.readline().decode()
        if "listening on" in line:
            addr = line.strip().rsplit(" ", 1)[1]
            break
        if proc.poll() is not None:
            raise RuntimeError("client server died: "
                               + proc.stdout.read().decode()[-2000:])
    assert addr, "client server never came up"
    yield f"ray-tpu://{addr}"
    proc.kill()


def test_client_tasks_objects_actors(client_cluster):
    ray_tpu.shutdown()
    ray_tpu.init(address=client_cluster)
    try:
        # objects
        ref = ray_tpu.put({"hello": 42})
        assert ray_tpu.get(ref, timeout=60)["hello"] == 42

        # tasks (including ref args crossing the proxy)
        @ray_tpu.remote
        def add(a, b):
            return a + b

        r1 = add.remote(1, 2)
        r2 = add.remote(r1, ray_tpu.put(10))
        assert ray_tpu.get(r2, timeout=120) == 13

        # wait
        ready, pending = ray_tpu.wait([r1, r2], num_returns=2, timeout=60)
        assert len(ready) == 2 and not pending

        # actors
        @ray_tpu.remote
        class Counter:
            def __init__(self, start):
                self.n = start

            def incr(self, by=1):
                self.n += by
                return self.n

        c = Counter.options(num_cpus=0.1).remote(100)
        assert ray_tpu.get(c.incr.remote(), timeout=120) == 101
        assert ray_tpu.get(c.incr.remote(5), timeout=60) == 106

        # errors propagate
        @ray_tpu.remote
        def boom():
            raise ValueError("kaboom")

        with pytest.raises(Exception, match="kaboom"):
            ray_tpu.get(boom.remote(), timeout=120)

        # cluster info
        assert ray_tpu.cluster_resources().get("CPU") == 4.0
    finally:
        ray_tpu.shutdown()

"""Device-object transport tests (reference tier:
python/ray/tests/test_gpu_objects* — tensors stay in the producing actor,
refs carry markers, consumers pull p2p)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.experimental import device_objects


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=6)
    yield ray_tpu
    ray_tpu.shutdown()


@ray_tpu.remote(num_cpus=0.5)
class Producer:
    def __init__(self):
        import jax.numpy as jnp

        self._jnp = jnp
        self.produced = 0

    @ray_tpu.method(tensor_transport="device")
    def weights(self, scale):
        self.produced += 1
        return self._jnp.full((64, 64), float(scale))

    def num_produced(self):
        return self.produced


@ray_tpu.remote(num_cpus=0.5)
class Consumer:
    def total(self, w):
        import numpy as np

        return float(np.asarray(w).sum())

    @ray_tpu.method(tensor_transport="device")
    def double(self, w):
        import jax.numpy as jnp

        return jnp.asarray(w) * 2.0


def test_driver_get_pulls_from_holder(cluster):
    p = Producer.remote()
    ref = p.weights.remote(3.0)
    w = ray_tpu.get(ref, timeout=120)
    assert float(np.asarray(w)[0, 0]) == 3.0
    assert np.asarray(w).shape == (64, 64)


def test_actor_to_actor_p2p(cluster):
    p = Producer.remote()
    c = Consumer.remote()
    ref = p.weights.remote(2.0)
    # the consumer receives the real array (pulled from the producer)
    assert ray_tpu.get(c.total.remote(ref), timeout=120) == 2.0 * 64 * 64


def test_chained_device_objects(cluster):
    p = Producer.remote()
    c = Consumer.remote()
    ref1 = p.weights.remote(1.0)
    ref2 = c.double.remote(ref1)  # consumer holds its own device object
    # generous timeout: three actors cold-import jax under suite load
    assert ray_tpu.get(
        Consumer.remote().total.remote(ref2), timeout=300) == 2.0 * 64 * 64


def test_free_releases_holder_memory(cluster):
    p = Producer.remote()
    ref = p.weights.remote(5.0)
    ray_tpu.get(ref, timeout=120)  # ensure produced
    assert device_objects.free(ref) is True
    assert device_objects.free(ref) is False
    c = Consumer.remote()
    with pytest.raises(Exception):
        ray_tpu.get(c.total.remote(ref), timeout=60)


def test_options_override_disables_decorator_transport(cluster):
    p = Producer.remote()
    # "object" forces the plain object-plane return for this call
    ref = p.weights.options(tensor_transport="object").remote(4.0)
    w = ray_tpu.get(ref, timeout=120)
    assert float(np.asarray(w)[0, 0]) == 4.0
    with pytest.raises(TypeError):
        device_objects.free(ref)  # not a marker: traveled as a plain object


def test_transport_via_method_options(cluster):
    @ray_tpu.remote(num_cpus=0.5)
    class Plain:
        def make(self):
            return np.ones(8)

    a = Plain.remote()
    ref = a.make.options(tensor_transport="device").remote()
    out = ray_tpu.get(ref, timeout=120)
    assert np.asarray(out).sum() == 8


# keep last: tears down the module cluster
def test_local_mode_actor_calls_unaffected():
    ray_tpu.shutdown()
    ray_tpu.init(local_mode=True)
    try:
        @ray_tpu.remote
        class A:
            def f(self):
                return 7

        a = A.remote()
        assert ray_tpu.get(a.f.remote()) == 7
    finally:
        ray_tpu.shutdown()

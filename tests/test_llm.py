"""LLM layer tests (CPU tier — SURVEY.md §4: accelerator features need a
hardware-free tier). Covers: paged-KV decode vs. the training forward,
continuous batching determinism, page-boundary growth, serve + data
integration."""

import numpy as np
import pytest

from ray_tpu.llm.config import EngineConfig, LLMConfig, SamplingParams


def make_config(**ekw):
    eng = dict(max_num_seqs=4, max_model_len=128, page_size=16,
               prefill_bucket_min=16)
    eng.update(ekw)
    return LLMConfig(model_id="tiny", engine_config=EngineConfig(**eng),
                     model_overrides={"attention_impl": "xla"})


@pytest.fixture(scope="module")
def engine():
    from ray_tpu.llm.engine import JaxLLMEngine

    return JaxLLMEngine(make_config(), seed=0)


def test_decode_matches_training_forward(engine):
    """Greedy generation through the paged cache must equal argmax over the
    training model's full forward re-run each step (same params)."""
    import jax.numpy as jnp

    from ray_tpu.models.transformer import Transformer

    model = Transformer(engine.mcfg)
    prompt = engine.tokenizer.encode("check equivalence")
    out = engine.generate([list(prompt)], SamplingParams(max_tokens=6))[0]

    toks = list(prompt)
    expect = []
    for _ in range(6):
        logits = model.apply(engine.params, jnp.asarray([toks]))
        nxt = int(jnp.argmax(logits[0, -1]))
        expect.append(nxt)
        if nxt == engine.tokenizer.eos_token_id:
            break
        toks.append(nxt)
    assert out.token_ids == expect


def test_continuous_batching_matches_sequential(engine):
    prompts = ["hello world", "the quick brown fox", "a", "zzzz"]
    batched = engine.generate(prompts, SamplingParams(max_tokens=8))
    singles = [engine.generate([p], SamplingParams(max_tokens=8))[0]
               for p in prompts]
    assert [o.token_ids for o in batched] == [o.token_ids for o in singles]
    assert all(o.finished for o in batched)


def test_generation_crosses_page_boundaries(engine):
    """Prompt of 14 + 40 new tokens crosses several 16-token pages."""
    prompt = list(range(3, 17))
    out = engine.generate([prompt], SamplingParams(max_tokens=40))[0]
    assert len(out.token_ids) == 40 or out.finish_reason == "stop"


def test_sampling_seeded_and_bounded(engine):
    from ray_tpu.llm.engine import JaxLLMEngine

    sp = SamplingParams(max_tokens=12, temperature=0.8, top_k=8)
    e1 = JaxLLMEngine(make_config(), params=engine.params, seed=7)
    e2 = JaxLLMEngine(make_config(), params=engine.params, seed=7)
    a = e1.generate(["seeded"], sp)[0].token_ids
    b = e2.generate(["seeded"], sp)[0].token_ids
    assert a == b
    assert len(a) <= 12


def test_per_request_seed_batch_independent(engine):
    """seed=N must reproduce regardless of what else is in the batch."""
    from ray_tpu.llm.engine import JaxLLMEngine

    sp = SamplingParams(max_tokens=10, temperature=1.0, seed=42)
    alone = engine.generate(["seeded prompt"], sp)[0].token_ids
    e2 = JaxLLMEngine(make_config(), params=engine.params, seed=999)
    mixed = e2.generate(["seeded prompt", "other a", "other b"], sp)
    assert mixed[0].token_ids == alone


def test_capacity_rejection():
    """A request that can never fit the page pool raises instead of
    livelocking admission (num_pages too small for prompt+max_tokens)."""
    from ray_tpu.llm.engine import JaxLLMEngine

    cfg = make_config(max_num_seqs=1, max_model_len=64, num_pages=3)
    eng = JaxLLMEngine(cfg, seed=0)
    with pytest.raises(ValueError, match="KV pages"):
        eng.add_request("too-big", list(range(3, 30)),
                        SamplingParams(max_tokens=32))
    # a request that fits still works
    out = eng.generate([list(range(3, 20))], SamplingParams(max_tokens=8))[0]
    assert out.finished


def test_preemption_keeps_generated_tokens(engine):
    """Force page exhaustion mid-generation: preempted requests must keep
    their already-emitted tokens and respect max_tokens overall."""
    from ray_tpu.llm.engine import JaxLLMEngine

    # 2 slots but pages for ~1.5 long sequences -> decode-time exhaustion
    cfg = make_config(max_num_seqs=2, max_model_len=64, num_pages=7)
    eng = JaxLLMEngine(cfg, params=engine.params, seed=0)
    prompts = [list(range(3, 3 + 30)), list(range(40, 40 + 30))]
    outs = eng.generate(prompts, SamplingParams(max_tokens=30))
    assert all(o.finished for o in outs)
    assert all(len(o.token_ids) <= 30 for o in outs)
    # greedy: outputs must match a roomy engine's outputs despite preemption
    roomy = JaxLLMEngine(make_config(max_num_seqs=2, max_model_len=64),
                         params=engine.params, seed=0)
    expect = roomy.generate(prompts, SamplingParams(max_tokens=30))
    assert [o.token_ids for o in outs] == [o.token_ids for o in expect]


def test_max_model_len_truncates(engine):
    long_prompt = list(np.random.default_rng(0).integers(3, 200, size=300))
    out = engine.generate([long_prompt], SamplingParams(max_tokens=4))[0]
    assert out.finished


def test_more_requests_than_slots(engine):
    prompts = [f"req {i}" for i in range(10)]  # > max_num_seqs=4
    outs = engine.generate(prompts, SamplingParams(max_tokens=5))
    assert len(outs) == 10 and all(o.finished for o in outs)


def test_save_load_params(tmp_path, engine):
    from ray_tpu.llm.engine import JaxLLMEngine, save_params

    save_params(engine.params, str(tmp_path))
    cfg = make_config()
    cfg.checkpoint_path = str(tmp_path)
    e2 = JaxLLMEngine(cfg)
    a = engine.generate(["persist"], SamplingParams(max_tokens=5))[0]
    b = e2.generate(["persist"], SamplingParams(max_tokens=5))[0]
    assert a.token_ids == b.token_ids


def test_serve_llm(ray_local):
    import ray_tpu
    from ray_tpu.llm.serve_llm import build_llm_deployment
    from ray_tpu.serve import api as serve_api

    app = build_llm_deployment(make_config(), name="llm-test")
    handle = serve_api.run(app)
    out = ray_tpu.get(handle.remote({"prompt": "hi", "max_tokens": 4}),
                      timeout=300)
    assert out["object"] == "text_completion"
    assert out["choices"][0]["finish_reason"] in ("stop", "length")
    chat = ray_tpu.get(handle.remote(
        {"messages": [{"role": "user", "content": "hi"}], "max_tokens": 4}),
        timeout=300)
    assert chat["object"] == "chat.completion"
    serve_api.shutdown()


@pytest.mark.isolated
def test_data_llm_processor(ray_local):
    from ray_tpu import data as rdata
    from ray_tpu.llm.data_llm import build_llm_processor

    ds = rdata.from_items([{"prompt": f"p{i}"} for i in range(6)],
                          parallelism=2)
    proc = build_llm_processor(
        make_config(), sampling_params=SamplingParams(max_tokens=3))
    try:
        rows = proc(ds).take_all()
        assert len(rows) == 6
        assert all("generated_text" in r for r in rows)
    finally:
        proc.shutdown()

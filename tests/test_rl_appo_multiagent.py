"""APPO + multi-agent env runner learning tests (CPU tier).

Reference: rllib/algorithms/appo/appo.py:347 (IMPALA sampling + clipped
surrogate + target net), rllib/env/multi_agent_env_runner.py; rllib treats
tuned_examples run-to-reward as CI assertions (SURVEY.md §4).
"""

import pytest

import ray_tpu
from ray_tpu.rl import APPOConfig, MultiAgentPPOConfig


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=6)
    yield ray_tpu
    ray_tpu.shutdown()


def test_appo_learns_cartpole(cluster):
    algo = APPOConfig(
        env="CartPole-v1", num_env_runners=2, num_envs_per_runner=4,
        rollout_length=64, num_rollouts_per_update=2, lr=3e-3,
        entropy_coef=0.01, target_update_freq=4, seed=0).build()
    best = 0.0
    try:
        # same bar as the sibling IMPALA learning test (>60 within 90
        # iterations): the surrogate must demonstrably improve the policy
        for _ in range(130):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best > 60.0:
                break
        assert best > 60.0, f"APPO failed to learn: best={best}"
        state = algo.get_state()
        assert "target_params" in state and "params" in state
    finally:
        algo.stop()


def test_multi_agent_shared_policy_learns_rendezvous(cluster):
    algo = MultiAgentPPOConfig(
        env="rendezvous", num_env_runners=2, rollout_length=128,
        lr=5e-3, epochs=4, seed=0).build()
    best = 0.0
    try:
        for _ in range(40):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best >= 10.0:  # horizon 16; random play scores ~3
                break
        assert best >= 10.0, f"multi-agent PPO failed to learn: best={best}"
    finally:
        algo.stop()


def test_multi_agent_per_agent_policies(cluster):
    """Distinct policies per agent train independently and still learn."""
    algo = MultiAgentPPOConfig(
        env="rendezvous", num_env_runners=2, rollout_length=128,
        policy_mapping={"a0": "p0", "a1": "p1"},
        lr=5e-3, epochs=4, seed=1).build()
    try:
        assert sorted(algo.policies) == ["p0", "p1"]
        best = 0.0
        for _ in range(40):
            result = algo.train()
            assert "loss_p0" in result and "loss_p1" in result
            best = max(best, result["episode_return_mean"])
            if best >= 10.0:
                break
        assert best >= 10.0, f"per-agent policies failed: best={best}"
    finally:
        algo.stop()

"""Task cancellation (cooperative + force) and streaming generators.

Reference: CoreWorker::CancelTask paths in core_worker.cc (cooperative
raise / force worker kill), num_returns="streaming" dynamic returns
(task_manager.cc + generator_waiter.cc), python/ray/tests/test_cancel.py
and test_streaming_generator.py scenarios.
"""

import time

import pytest

import ray_tpu
from ray_tpu.exceptions import TaskCancelledError


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=6)
    yield ray_tpu
    ray_tpu.shutdown()


def test_cancel_running_task_cooperative(cluster):
    @ray_tpu.remote(num_cpus=0.5)
    def spin():
        t0 = time.time()
        while time.time() - t0 < 60.0:
            time.sleep(0.01)
        return "finished"

    ref = spin.remote()
    time.sleep(2.0)  # let it start
    t0 = time.monotonic()
    ray_tpu.cancel(ref)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=60)
    assert time.monotonic() - t0 < 30.0  # cancelled, not run to completion


def test_cancel_pending_task(cluster):
    @ray_tpu.remote(num_cpus=6.0)
    def blocker():
        time.sleep(8.0)
        return "b"

    @ray_tpu.remote(num_cpus=6.0)
    def queued():
        return "q"

    b = blocker.remote()
    time.sleep(1.0)
    q = queued.remote()  # cannot schedule while blocker holds all CPUs
    time.sleep(0.5)
    ray_tpu.cancel(q)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(q, timeout=60)
    assert ray_tpu.get(b, timeout=60) == "b"


def test_cancel_force_kills_worker(cluster):
    @ray_tpu.remote(num_cpus=0.5, max_retries=0)
    def stubborn():
        while True:  # ignores cooperative cancellation forever
            try:
                time.sleep(0.5)
            except BaseException:
                pass

    ref = stubborn.remote()
    time.sleep(2.0)
    ray_tpu.cancel(ref, force=True)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=60)


def test_cancel_finished_task_is_noop(cluster):
    @ray_tpu.remote
    def quick():
        return 7

    ref = quick.remote()
    assert ray_tpu.get(ref, timeout=60) == 7
    ray_tpu.cancel(ref)  # no-op, no error
    assert ray_tpu.get(ref, timeout=60) == 7


def test_streaming_generator_basic(cluster):
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * 10

    out = [ray_tpu.get(ref, timeout=60) for ref in gen.remote(5)]
    assert out == [0, 10, 20, 30, 40]


def test_streaming_consumed_while_producing(cluster):
    """Refs become available as items are yielded, before the task ends."""
    @ray_tpu.remote(num_returns="streaming")
    def slow_gen():
        for i in range(4):
            time.sleep(0.5)
            yield i

    it = slow_gen.remote()
    t0 = time.monotonic()
    first = ray_tpu.get(next(it), timeout=60)
    first_latency = time.monotonic() - t0
    rest = [ray_tpu.get(r, timeout=60) for r in it]
    assert first == 0 and rest == [1, 2, 3]
    # the first item arrived well before all 4 * 0.5s of production
    assert first_latency < 1.9, f"stream not incremental: {first_latency:.1f}s"


def test_streaming_large_items_ride_the_store(cluster):
    import numpy as np

    @ray_tpu.remote(num_returns="streaming")
    def big_gen():
        for i in range(3):
            yield np.full(300_000, float(i))

    vals = [ray_tpu.get(r, timeout=120) for r in big_gen.remote()]
    assert [float(v[0]) for v in vals] == [0.0, 1.0, 2.0]


def test_streaming_generator_error_propagates(cluster):
    @ray_tpu.remote(num_returns="streaming")
    def bad_gen():
        yield 1
        raise ValueError("mid-stream failure")

    it = bad_gen.remote()
    assert ray_tpu.get(next(it), timeout=60) == 1
    with pytest.raises(Exception, match="mid-stream failure"):
        for ref in it:
            ray_tpu.get(ref, timeout=60)


def test_streaming_slow_consumer_items_survive(cluster):
    """Items yielded by an already-finished generator must stay readable
    until the consumer reaches them (arrival pins outlive the free grace)."""
    @ray_tpu.remote(num_returns="streaming")
    def fast_gen():
        for i in range(4):
            yield i * 3

    it = fast_gen.remote()
    time.sleep(3.0)  # generator done; free grace long past
    assert [ray_tpu.get(r, timeout=60) for r in it] == [0, 3, 6, 9]


def test_streaming_error_preserves_prior_items(cluster):
    """A mid-stream failure must not clobber already-yielded values."""
    @ray_tpu.remote(num_returns="streaming")
    def half_gen():
        yield "ok-0"
        yield "ok-1"
        raise RuntimeError("boom at 2")

    it = half_gen.remote()
    r0, r1 = next(it), next(it)
    with pytest.raises(Exception, match="boom at 2"):
        next(it)
    time.sleep(1.5)  # past the completion error processing
    assert ray_tpu.get(r0, timeout=60) == "ok-0"
    assert ray_tpu.get(r1, timeout=60) == "ok-1"


def test_streaming_non_generator_errors(cluster):
    @ray_tpu.remote(num_returns="streaming")
    def not_a_gen():
        return 42

    it = not_a_gen.remote()
    with pytest.raises(Exception, match="did not return a generator"):
        next(it)


# ---------------------------------------------------------------------------
# actor-task cancellation (reference: CancelTask actor paths; queued calls
# dropped, running async calls asyncio-cancelled, force refused)
# ---------------------------------------------------------------------------


def test_cancel_queued_actor_task(cluster):
    @ray_tpu.remote(num_cpus=0.5)
    class Slow:
        def work(self, seconds):
            time.sleep(seconds)
            return "done"

    a = Slow.remote()
    first = a.work.remote(6.0)
    time.sleep(1.0)  # first call occupies the single-concurrency actor
    queued = a.work.remote(0.1)
    ray_tpu.cancel(queued)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(queued, timeout=60)
    # the actor itself is unharmed and finishes the first call
    assert ray_tpu.get(first, timeout=60) == "done"
    ray_tpu.kill(a)


def test_cancel_running_async_actor_task(cluster):
    import asyncio as aio

    @ray_tpu.remote(num_cpus=0.5)
    class AsyncActor:
        async def sleepy(self, seconds):
            await aio.sleep(seconds)
            return "slept"

        async def quick(self):
            return "quick"

    a = AsyncActor.remote()
    assert ray_tpu.get(a.quick.remote(), timeout=60) == "quick"
    ref = a.sleepy.remote(60.0)
    time.sleep(1.5)  # in flight
    t0 = time.monotonic()
    ray_tpu.cancel(ref)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=60)
    assert time.monotonic() - t0 < 30.0
    # actor survives and serves further calls
    assert ray_tpu.get(a.quick.remote(), timeout=60) == "quick"
    ray_tpu.kill(a)


def test_cancel_actor_task_force_refused(cluster):
    @ray_tpu.remote(num_cpus=0.5)
    class A:
        def m(self):
            time.sleep(5.0)
            return 1

    a = A.remote()
    ref = a.m.remote()
    with pytest.raises(ValueError, match="force=True is not supported"):
        ray_tpu.cancel(ref, force=True)
    assert ray_tpu.get(ref, timeout=60) == 1
    ray_tpu.kill(a)

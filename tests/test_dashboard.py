"""Dashboard-lite tests (reference tier: dashboard REST + Prometheus +
jobs endpoints, python/ray/dashboard/modules/*/tests)."""

import json
import time
import urllib.request

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def dash_cluster():
    ray_tpu.shutdown()
    worker = ray_tpu.init(num_cpus=4, include_dashboard=True)
    address = worker.node_supervisor.dashboard_address
    yield address
    ray_tpu.shutdown()


def _get(address, path, timeout=30):
    with urllib.request.urlopen(f"http://{address}{path}", timeout=timeout) as r:
        body = r.read().decode()
        ctype = r.headers.get("Content-Type", "")
    return body, ctype


def _get_json(address, path):
    body, _ = _get(address, path)
    return json.loads(body)


def test_state_endpoints(dash_cluster):
    @ray_tpu.remote
    class Marker:
        def ping(self):
            return True

    m = Marker.options(name="dash_marker", num_cpus=0.1).remote()
    assert ray_tpu.get(m.ping.remote(), timeout=60)

    nodes = _get_json(dash_cluster, "/api/nodes")
    assert len([n for n in nodes if n["alive"]]) == 1
    actors = _get_json(dash_cluster, "/api/actors")
    assert any(a["name"] == "dash_marker" for a in actors)
    summary = _get_json(dash_cluster, "/api/summary")
    assert summary["num_nodes"] == 1 and summary["num_actors"] >= 1
    status = _get_json(dash_cluster, "/api/cluster_status")
    assert status["nodes"] and "demands" in status


def test_index_html(dash_cluster):
    body, ctype = _get(dash_cluster, "/")
    assert "text/html" in ctype
    assert "ray_tpu cluster" in body


def test_prometheus_metrics(dash_cluster):
    from ray_tpu.util.metrics import Counter, publish_metrics

    c = Counter("dash_test_total", description="test counter")
    c.inc(3.0)
    publish_metrics()

    body, ctype = _get(dash_cluster, "/metrics")
    assert "text/plain" in ctype
    assert "ray_tpu_cluster_nodes_alive 1" in body
    assert 'ray_tpu_cluster_resource_total{resource="CPU"} 4' in body
    assert "dash_test_total" in body


def test_jobs_rest_roundtrip(dash_cluster):
    payload = json.dumps({
        "entrypoint": "python -c \"print('dash job ran')\"",
    }).encode()
    req = urllib.request.Request(
        f"http://{dash_cluster}/api/jobs", data=payload,
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=60) as r:
        sid = json.loads(r.read())["submission_id"]

    deadline = time.monotonic() + 120
    status = None
    while time.monotonic() < deadline:
        info = _get_json(dash_cluster, f"/api/jobs/{sid}")
        status = info["status"]
        if status in ("SUCCEEDED", "FAILED", "STOPPED"):
            break
        time.sleep(0.5)
    assert status == "SUCCEEDED", f"job ended as {status}"
    logs, _ = _get(dash_cluster, f"/api/jobs/{sid}/logs")
    assert "dash job ran" in logs
    jobs = _get_json(dash_cluster, "/api/jobs")
    assert any(j["submission_id"] == sid for j in jobs)

"""Data layer tests (reference tier: python/ray/data/tests basics)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


def test_range_map_filter_take(cluster):
    ds = rd.range(100, parallelism=4).map(lambda r: {"id": r["id"] * 2})
    ds = ds.filter(lambda r: r["id"] % 4 == 0)
    out = ds.take(5)
    assert [r["id"] for r in out] == [0, 4, 8, 12, 16]
    assert ds.count() == 50


def test_map_batches(cluster):
    ds = rd.range(64, parallelism=2).map_batches(
        lambda b: {"id": b["id"], "sq": b["id"] ** 2}, batch_size=16)
    rows = ds.take_all()
    assert rows[5]["sq"] == 25
    assert len(rows) == 64


def test_flat_map_and_union(cluster):
    a = rd.from_items([{"x": 1}, {"x": 2}], parallelism=1)
    b = a.flat_map(lambda r: [r, r])
    assert b.count() == 4
    assert a.union(b).count() == 6


def test_iter_batches_shapes(cluster):
    ds = rd.range(50, parallelism=3)
    batches = list(ds.iter_batches(batch_size=16))
    sizes = [len(b["id"]) for b in batches]
    assert sum(sizes) == 50
    assert all(s == 16 for s in sizes[:-1])


def test_repartition_and_split(cluster):
    ds = rd.range(40, parallelism=3).repartition(4)
    assert ds.num_blocks() == 4
    parts = rd.range(40, parallelism=2).split(4)
    counts = [p.count() for p in parts]
    assert sum(counts) == 40
    assert all(c == 10 for c in counts)
    ids = sorted(r["id"] for p in parts for r in p.take_all())
    assert ids == list(range(40))


def test_random_shuffle(cluster):
    ds = rd.range(30, parallelism=2).random_shuffle(seed=42)
    ids = [r["id"] for r in ds.take_all()]
    assert sorted(ids) == list(range(30))
    assert ids != list(range(30))


def test_parquet_roundtrip(cluster, tmp_path):
    ds = rd.range(20, parallelism=2).map(lambda r: {"id": r["id"], "y": r["id"] * 1.5})
    files = ds.write_parquet(str(tmp_path / "pq"))
    assert len(files) == 2
    back = rd.read_parquet(str(tmp_path / "pq"))
    assert back.count() == 20
    assert back.to_pandas()["y"].sum() == sum(i * 1.5 for i in range(20))


def test_from_pandas_and_numpy(cluster):
    import pandas as pd

    df = pd.DataFrame({"a": [1, 2, 3]})
    assert rd.from_pandas(df).count() == 3
    assert rd.from_numpy(np.ones((4, 2))).count() == 4


def test_train_integration_shards(cluster, tmp_path):
    """Dataset splits feed train workers via get_dataset_shard."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    ds = rd.range(20, parallelism=2)

    def loop(config):
        from ray_tpu import train

        shard = train.get_dataset_shard("train")
        total = sum(r["id"] for r in shard.take_all())
        train.report({"total": total, "rank": train.get_context().get_world_rank()})
        return total

    trainer = JaxTrainer(
        loop,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1.0}),
        run_config=RunConfig(storage_path=str(tmp_path), name="shards"),
        datasets={"train": ds},
    )
    result = trainer.fit()
    assert result.error is None

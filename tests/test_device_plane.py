"""Device-tier data plane: cross-node compiled-graph channels, XlaGroup
eager p2p via device objects, and PD KV handoff riding the device plane.

Reference: experimental/channel/torch_tensor_accelerator_channel.py and
experimental_mutable_object_provider.cc (cross-node channel legs),
the accelerator-channel p2p tier, and pd_server.py KV-transfer connectors.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


def test_compiled_dag_spans_two_nodes():
    """A compiled pipeline whose stages live on DIFFERENT nodes: the edge
    channels switch to the cross-host mailbox tier automatically."""
    ray_tpu.shutdown()
    cluster = Cluster(head_node_args={"resources": {"CPU": 2.0}})
    cluster.add_node(resources={"CPU": 2.0, "zone_b": 4.0})
    ray_tpu.init(address=cluster.address)
    cluster.wait_for_nodes(2)
    try:
        from ray_tpu.dag import InputNode

        @ray_tpu.remote(num_cpus=1.0)
        class Doubler:
            def double(self, x):
                return x * 2

        @ray_tpu.remote(num_cpus=1.0, resources={"zone_b": 1.0})
        class AddTen:  # forced onto node B
            def add(self, x):
                return x + 10

        a = Doubler.remote()
        b = AddTen.remote()
        with InputNode() as inp:
            dag = b.add.bind(a.double.bind(inp))
        compiled = dag.experimental_compile()
        try:
            outs = [compiled.execute(i) for i in range(6)]
            assert [o.get(timeout=120) for o in outs] == [
                i * 2 + 10 for i in range(6)]
            # the a->b edge crossed nodes: its channel must be cross-host
            assert any(s.get("type") == "xhost"
                       for s in compiled._chan_specs.values()), (
                compiled._chan_specs)
        finally:
            compiled.teardown()
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=6)
    yield ray_tpu
    ray_tpu.shutdown()


def test_xla_group_eager_p2p(cluster):
    """Eager send/recv between two actors: the tensor stays in the
    sender's device store until the receiver pulls it directly."""
    @ray_tpu.remote(num_cpus=1.0)
    class Peer:
        def __init__(self, rank):
            from ray_tpu.collective import XlaGroup

            self.rank = rank
            self.group = XlaGroup("p2p_test", world_size=2, rank=rank)

        def exchange(self):
            import jax.numpy as jnp

            if self.rank == 0:
                self.group.send(jnp.arange(8.0), dst_rank=1, tag=3)
                self.group.send(jnp.full((4,), 7.0), dst_rank=1, tag=3)
                return "sent"
            first = self.group.recv(src_rank=0, tag=3)
            second = self.group.recv(src_rank=0, tag=3)
            return np.asarray(first).tolist(), np.asarray(second).tolist()

    p0, p1 = Peer.remote(0), Peer.remote(1)
    r0 = p0.exchange.remote()
    r1 = p1.exchange.remote()
    assert ray_tpu.get(r0, timeout=120) == "sent"
    first, second = ray_tpu.get(r1, timeout=120)
    assert first == list(np.arange(8.0))
    assert second == [7.0] * 4
    ray_tpu.kill(p0)
    ray_tpu.kill(p1)


def test_pd_kv_rides_device_plane(cluster):
    """prefill's reply is a device-object marker (KV stays in the prefill
    worker); decode pulls it p2p and the result matches the monolithic
    engine exactly."""
    from ray_tpu.experimental.device_objects import DeviceObjectMarker
    from ray_tpu.llm.config import EngineConfig, LLMConfig, SamplingParams
    from ray_tpu.llm.pd import DecodeWorker, PrefillWorker

    def make_config():
        return LLMConfig(
            model_id="tiny",
            engine_config=EngineConfig(max_num_seqs=4, max_model_len=128,
                                       page_size=16, prefill_bucket_min=16),
            model_overrides={"attention_impl": "xla"})

    from ray_tpu.llm.engine import JaxLLMEngine

    prompt = "the quick brown fox"
    mono = JaxLLMEngine(make_config(), seed=0)
    expect = mono.generate([prompt], SamplingParams(max_tokens=8))[0]

    pre_cls = ray_tpu.remote(num_cpus=1.0)(PrefillWorker)
    dec_cls = ray_tpu.remote(num_cpus=1.0)(DecodeWorker)
    pre = pre_cls.remote(make_config(), None)
    dec = dec_cls.remote(make_config(), None)
    state_ref = pre.prefill.remote(prompt, SamplingParams(max_tokens=8))
    out = ray_tpu.get(dec.decode.remote(state_ref), timeout=300)
    assert out["token_ids"] == expect.token_ids, (out, expect)
    # the driver-visible reply value is a marker, not the KV payload
    w = ray_tpu._private.worker.global_worker()
    raw = w.memory_store.get(state_ref.id)
    assert isinstance(raw, DeviceObjectMarker), type(raw)
    ray_tpu.kill(pre)
    ray_tpu.kill(dec)

"""Scaled-down control-plane stress envelope (reference: release/benchmarks/
distributed/many_nodes_tests — the full-size run lives in tools/stress.py and
its committed STRESS_r{N}.json).

Asserts the envelope COMPLETES — every task result accounted for, every actor
reachable, every PG reaches ready and releases its bundles — at a scale CI can
afford; throughput numbers come from the full run.
"""

import json
import os
import subprocess
import sys

import pytest


def test_stress_envelope_scaled(tmp_path):
    # already subprocess-isolated: the whole envelope runs in its own
    # interpreter via tools/stress.py
    out = tmp_path / "stress.json"
    env = dict(os.environ)
    proc = subprocess.run(
        [sys.executable, os.path.join("tools", "stress.py"),
         "--nodes", "6", "--tasks", "1500", "--actors", "40", "--pgs", "12",
         "--broadcast-mb", "16", "--out", str(out)],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, capture_output=True, text=True, timeout=780)
    assert proc.returncode == 0, (
        f"stress run failed\n--- stdout ---\n{proc.stdout[-4000:]}"
        f"\n--- stderr ---\n{proc.stderr[-3000:]}")
    result = json.loads(out.read_text())
    assert result["tasks"] == 1500
    assert result["actors"] == 40
    assert result["pgs"] == 12
    assert result["broadcast_nodes"] == 6
    assert result["tasks_per_s"] > 20

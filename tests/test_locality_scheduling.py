"""Data-locality lease targeting (reference:
src/ray/core_worker/task_submission/lease_policy.cc — the lease chain starts
at the raylet holding the most argument bytes; spillback tie-breaks on the
same locality map)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy


@pytest.fixture
def two_nodes():
    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True,
                      head_node_args={"resources": {"CPU": 2.0}})
    cluster.add_node(resources={"CPU": 2.0})
    ray_tpu.init(address=cluster.address)
    from ray_tpu.util.state import list_nodes

    import time

    deadline = time.time() + 60
    while time.time() < deadline:
        nodes = [n for n in list_nodes() if n["alive"]]
        if len(nodes) >= 2:
            break
        time.sleep(0.2)
    yield cluster, nodes
    ray_tpu.shutdown()
    cluster.shutdown()


@ray_tpu.remote(num_cpus=0.5)
def produce():
    import ray_tpu.runtime_context as rc

    return np.zeros(2 * 1024 * 1024, dtype=np.uint8), \
        rc.get_runtime_context().get_node_id()


@ray_tpu.remote(num_cpus=0.5)
def consume(blob_and_node):
    import ray_tpu.runtime_context as rc

    blob, producer_node = blob_and_node
    return len(blob), producer_node, rc.get_runtime_context().get_node_id()


def test_consumer_schedules_onto_arg_node(two_nodes):
    cluster, nodes = two_nodes
    head_id = next(n["node_id"] for n in nodes if n["is_head"])
    other_id = next(n["node_id"] for n in nodes if not n["is_head"])
    # pin the producer (and its 2MB output) to the non-head node
    ref = produce.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=other_id)).remote()
    # resolve so the output is sealed + its location announced
    _, producer_node = ray_tpu.get(ref, timeout=120)
    assert producer_node == other_id
    # the consumer has no affinity: locality must steer it to the arg node
    # (without locality the owner's local raylet — the head — would grant,
    # since it has free CPU)
    n, producer_node, consumer_node = ray_tpu.get(
        consume.remote(ref), timeout=120)
    assert n == 2 * 1024 * 1024
    assert consumer_node == other_id, (
        f"consumer ran on {consumer_node[:8]}, arg lives on {other_id[:8]}")
    assert head_id != other_id

"""Streaming-executor data layer tests (reference tier:
python/ray/data/tests — groupby, sort, join, zip, union, limit,
actor pools, stats)."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield ray_tpu
    ray_tpu.shutdown()


def test_groupby_aggregates(cluster):
    ds = rdata.from_items(
        [{"k": i % 3, "v": float(i)} for i in range(30)], parallelism=4)
    rows = ds.groupby("k").aggregate(("count", None), ("sum", "v"),
                                     ("mean", "v")).take_all()
    assert len(rows) == 3
    by_k = {r["k"]: r for r in rows}
    assert by_k[0]["count()"] == 10
    assert by_k[0]["sum(v)"] == sum(float(i) for i in range(30) if i % 3 == 0)
    assert by_k[1]["mean(v)"] == pytest.approx(
        np.mean([i for i in range(30) if i % 3 == 1]))


def test_groupby_min_max_std(cluster):
    ds = rdata.from_items([{"k": "a", "v": float(i)} for i in range(5)]
                          + [{"k": "b", "v": 100.0}], parallelism=3)
    rows = ds.groupby("k").aggregate(("min", "v"), ("max", "v"),
                                     ("std", "v")).take_all()
    by_k = {r["k"]: r for r in rows}
    assert by_k["a"]["min(v)"] == 0.0 and by_k["a"]["max(v)"] == 4.0
    assert by_k["a"]["std(v)"] == pytest.approx(np.std(range(5), ddof=1))
    assert by_k["b"]["std(v)"] == 0.0


def test_map_groups(cluster):
    ds = rdata.from_items([{"k": i % 2, "v": i} for i in range(10)],
                          parallelism=3)
    rows = ds.groupby("k").map_groups(
        lambda members: [{"k": members[0]["k"],
                          "total": sum(m["v"] for m in members)}]).take_all()
    by_k = {r["k"]: r["total"] for r in rows}
    assert by_k == {0: 20, 1: 25}


def test_sort_global_order(cluster):
    rng = np.random.default_rng(0)
    vals = rng.permutation(200).tolist()
    ds = rdata.from_items([{"v": v} for v in vals], parallelism=6)
    out = [r["v"] for r in ds.sort("v").take_all()]
    assert out == sorted(vals)
    out_desc = [r["v"] for r in ds.sort("v", descending=True).take_all()]
    assert out_desc == sorted(vals, reverse=True)


def test_join_inner_and_left(cluster):
    left = rdata.from_items([{"id": i, "a": i * 10} for i in range(8)],
                            parallelism=3)
    right = rdata.from_items([{"id": i, "b": i * 100} for i in range(4, 12)],
                             parallelism=3)
    inner = left.join(right, on="id").take_all()
    assert sorted(r["id"] for r in inner) == [4, 5, 6, 7]
    assert all(r["b"] == r["id"] * 100 and r["a"] == r["id"] * 10 for r in inner)

    lj = left.join(right, on="id", how="left").take_all()
    assert sorted(r["id"] for r in lj) == list(range(8))
    missing = [r for r in lj if r["id"] < 4]
    assert all("b" not in r for r in missing)


def test_zip_and_union(cluster):
    a = rdata.from_items([{"x": i} for i in range(10)], parallelism=2)
    b = rdata.from_items([{"y": i * 2} for i in range(10)], parallelism=2)
    zipped = a.zip(b).take_all()
    assert all(r["y"] == r["x"] * 2 for r in zipped)

    u = a.union(b)
    assert u.count() == 20


def test_limit_early_stop(cluster):
    # limit(5) over a large dataset must not run all read tasks
    ds = rdata.range(100000, parallelism=64).limit(5)
    rows = ds.take_all()
    assert [r["id"] for r in rows] == [0, 1, 2, 3, 4]


def test_map_batches_class_udf_actor_pool(cluster):
    class AddBias:
        def __init__(self, bias):
            self.bias = bias
            self.calls = 0

        def __call__(self, batch):
            self.calls += 1
            return {"id": batch["id"], "b": batch["id"] + self.bias}

    ds = rdata.range(64, parallelism=4).map_batches(
        AddBias, fn_constructor_args=(100,), concurrency=2)
    rows = ds.take_all()
    assert len(rows) == 64
    assert all(r["b"] == r["id"] + 100 for r in rows)


def test_fused_chain_order_preserved(cluster):
    ds = (rdata.range(100, parallelism=8)
          .map(lambda r: {"id": r["id"], "sq": r["id"] ** 2})
          .filter(lambda r: r["id"] % 2 == 0)
          .map(lambda r: {"sq": r["sq"]}))
    rows = ds.take_all()
    assert [r["sq"] for r in rows] == [i ** 2 for i in range(100) if i % 2 == 0]


def test_count_does_not_fetch(cluster):
    assert rdata.range(5000, parallelism=10).count() == 5000


def test_random_shuffle(cluster):
    ds = rdata.range(300, parallelism=4).random_shuffle(seed=7)
    out = [r["id"] for r in ds.take_all()]
    assert sorted(out) == list(range(300))
    assert out != list(range(300))


def test_repartition(cluster):
    ds = rdata.range(100, parallelism=2).repartition(8).materialize()
    assert ds.num_blocks() == 8
    assert ds.count() == 100


def test_write_and_read_roundtrips(cluster, tmp_path):
    ds = rdata.from_items([{"a": i, "b": f"s{i}"} for i in range(20)],
                          parallelism=3)
    pq_paths = ds.write_parquet(str(tmp_path / "pq"))
    assert len(pq_paths) == 3
    back = rdata.read_parquet(str(tmp_path / "pq"))
    assert back.count() == 20

    csv_paths = ds.write_csv(str(tmp_path / "csv"))
    assert csv_paths and rdata.read_csv(str(tmp_path / "csv")).count() == 20

    json_paths = ds.write_json(str(tmp_path / "j"))
    assert json_paths
    assert rdata.read_json(str(tmp_path / "j")).count() == 20


def test_read_text(cluster, tmp_path):
    p = tmp_path / "f.txt"
    p.write_text("alpha\nbeta\ngamma\n")
    rows = rdata.read_text(str(p)).take_all()
    assert [r["text"] for r in rows] == ["alpha", "beta", "gamma"]


def test_stats_populated(cluster):
    ds = rdata.range(100, parallelism=4).map(lambda r: r)
    ds.take_all()
    s = ds.stats()
    assert "Read" in s and "Map" in s and "tasks" in s


def test_train_test_split(cluster):
    train, test = rdata.range(100, parallelism=4).train_test_split(0.2)
    assert train.count() == 80 and test.count() == 20


def test_empty_dataset_through_shuffle(cluster):
    assert rdata.from_items([{"v": 1}]).filter(
        lambda r: False).random_shuffle().count() == 0
    assert rdata.from_items([{"v": 1}]).filter(
        lambda r: False).sort("v").take_all() == []


def test_schema_abandons_stream_cleanly(cluster):
    ds = rdata.range(10000, parallelism=32)
    assert ds.schema() is not None  # early abandon must not deadlock
    assert ds.count() == 10000  # and the dataset is still consumable


def test_zip_mismatched_parallelism(cluster):
    a = rdata.from_items([{"x": i} for i in range(10)], parallelism=2)
    b = rdata.from_items([{"y": i * 3} for i in range(10)], parallelism=5)
    rows = a.zip(b).take_all()
    assert len(rows) == 10
    assert all(r["y"] == r["x"] * 3 for r in rows)


def test_zip_unequal_rows_raises(cluster):
    a = rdata.from_items([{"x": i} for i in range(5)])
    b = rdata.from_items([{"y": i} for i in range(6)])
    with pytest.raises(Exception, match="equal row counts"):
        a.zip(b).take_all()


def test_materialized_parent_not_reexecuted(cluster):
    import os
    import tempfile

    marker = tempfile.mktemp()

    def touch(r, marker=marker):
        with open(marker, "a") as f:
            f.write("x")
        return r

    ds = rdata.range(4, parallelism=1).map(touch).materialize()
    runs1 = os.path.getsize(marker)
    assert ds.take(2) and ds.count() == 4  # derived ops reuse the cache
    assert os.path.getsize(marker) == runs1


def test_groupby_minmax_strings(cluster):
    ds = rdata.from_items([{"k": 1, "name": n}
                           for n in ["bob", "alice", "carol"]])
    rows = ds.groupby("k").aggregate(("min", "name"), ("max", "name")).take_all()
    assert rows[0]["min(name)"] == "alice" and rows[0]["max(name)"] == "carol"


def test_repartition_balances_tiny_blocks(cluster):
    # 40 one-row blocks -> 4 partitions: no partition may hog everything
    ds = rdata.from_items([{"v": i} for i in range(40)],
                          parallelism=40).repartition(4).materialize()
    assert ds.count() == 40
    sizes = [b.rows for b in ds._materialized]
    assert len(sizes) == 4 and max(sizes) < 40


def test_pipeline_soak_no_row_loss(cluster):
    """Repeated multi-stage pipelines must never drop rows (the executor
    raises on undrained operators at termination)."""
    for trial in range(5):
        orders = rdata.from_items(
            [{"u": f"u{i % 5}", "v": float(i)} for i in range(200)],
            parallelism=8)
        totals = orders.groupby("u").sum("v")
        users = rdata.from_items([{"u": f"u{i}", "t": i} for i in range(5)])
        out = totals.join(users, on="u").sort("sum(v)", descending=True)
        rows = out.take_all()
        assert len(rows) == 5, f"trial {trial}: lost rows {rows}"
        assert [r["u"] for r in rows] == ["u4", "u3", "u2", "u1", "u0"]

"""Elastic Train: losing a node mid-run re-forms the worker group at the
largest mesh-shaped size the shrunken cluster can host and resumes from
the latest checkpoint.

Reference: train/v2 scaling_policy.py:32 (the elasticity interface the
reference defines but only implements as `fixed`); this build implements
the elastic policy TPU-first (whole-slice / power-of-two sizes only,
fresh processes per re-form since a jax.distributed mesh cannot shrink
in place — SURVEY.md §7 hard part (b)).
"""

import json
import os
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.train import FailureConfig, JaxTrainer, RunConfig, ScalingConfig


def _elastic_loop(config):
    """Checkpoints every step; crashes the whole group when a worker dies
    (rank 1+ sleeps forever on a dead node -> the group task fails)."""
    import tempfile

    from ray_tpu import train
    from ray_tpu.train import Checkpoint

    ctx = train.get_context()
    start = 0
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        with open(os.path.join(ckpt.as_directory(), "state.json")) as f:
            start = json.load(f)["step"]
    marker = config["marker"]
    for step in range(start, config["steps"]):
        if step == 2 and ctx.get_world_size() == 4:
            # first incarnation: EVERY worker stalls (per-rank marker) so
            # none finishes before the driver kills node B mid-training
            open(f"{marker}.{ctx.get_world_rank()}", "w").close()
            time.sleep(600.0)
        metrics = {"step": step + 1, "world_size": ctx.get_world_size()}
        if ctx.get_world_rank() == 0:
            with tempfile.TemporaryDirectory() as d:
                with open(os.path.join(d, "state.json"), "w") as f:
                    json.dump({"step": step + 1}, f)
                train.report(metrics, checkpoint=Checkpoint.from_directory(d))
        else:
            train.report(metrics)
    return {"final_world_size": ctx.get_world_size(), "resumed_from": start}


def test_elastic_reform_after_node_loss(tmp_path):
    ray_tpu.shutdown()
    cluster = Cluster(head_node_args={"resources": {"CPU": 3.0}})
    node_b = cluster.add_node(resources={"CPU": 2.0})
    ray_tpu.init(address=cluster.address)
    cluster.wait_for_nodes(2)
    marker = str(tmp_path / "stall_once")
    try:
        trainer = JaxTrainer(
            _elastic_loop,
            train_loop_config={"steps": 5, "marker": marker},
            scaling_config=ScalingConfig(
                num_workers=4, elastic=True, min_workers=1,
                elastic_granularity="pow2",
                resources_per_worker={"CPU": 1.0}),
            run_config=RunConfig(
                storage_path=str(tmp_path / "runs"), name="elastic",
                failure_config=FailureConfig(max_failures=2)),
        )
        import threading

        result_box = {}

        def _fit():
            result_box["result"] = trainer.fit()

        t = threading.Thread(target=_fit, daemon=True)
        t.start()
        # wait for the first incarnation (4 workers) to all reach the stall
        deadline = time.time() + 120
        while sum(os.path.exists(f"{marker}.{r}") for r in range(4)) < 4:
            assert time.time() < deadline, "group never started training"
            time.sleep(0.5)
        time.sleep(1.0)
        cluster.remove_node(node_b)  # kills the workers living there
        t.join(timeout=300)
        assert not t.is_alive(), "training did not finish after node loss"
        result = result_box["result"]
        assert result.error is None, result.error
        # 3 CPUs remain (head, 1 held by the controller actor? no — the
        # controller is 0-cpu by default); pow2 floor of min(4, feasible)
        assert result.metrics["world_size"] == 2, result.metrics
        # the re-formed group resumed from the checkpointed step, not 0
        assert result.metrics["step"] == 5
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()

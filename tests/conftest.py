"""Test config: force a virtual 8-device CPU mesh before jax initializes.

Mirrors the reference's CPU test tier (SURVEY.md §4): all sharding/collective
tests run on xla_force_host_platform_device_count=8 so CI needs no TPUs.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["RAY_TPU_JAX_PLATFORMS"] = "cpu"  # honored by ray_tpu.utils.import_jax
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

# let spawned worker processes import functions defined in test modules
_tests_dir = os.path.dirname(os.path.abspath(__file__))
_pp = os.environ.get("PYTHONPATH", "")
if _tests_dir not in _pp.split(":"):
    os.environ["PYTHONPATH"] = f"{_tests_dir}:{_pp}" if _pp else _tests_dir

from ray_tpu.utils import import_jax  # noqa: E402

import_jax()  # apply the platform override before any test touches jax

import pytest  # noqa: E402


@pytest.fixture
def ray_local():
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(local_mode=True)
    yield ray_tpu
    ray_tpu.shutdown()

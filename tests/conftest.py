"""Test config: force a virtual 8-device CPU mesh before jax initializes.

Mirrors the reference's CPU test tier (SURVEY.md §4): all sharding/collective
tests run on xla_force_host_platform_device_count=8 so CI needs no TPUs.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["RAY_TPU_JAX_PLATFORMS"] = "cpu"  # honored by ray_tpu.utils.import_jax
# The CPU test tier never touches the TPU plugin: dropping the pool address
# keeps the site hook from eagerly importing jax + registering PJRT in EVERY
# spawned process (raylets, workers) — ~3s and ~140MB per process, which on a
# 1-CPU CI box dominates suite wall-clock and memory. Workers that need jax
# import it lazily on CPU.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

# Persistent XLA compilation cache for the TEST tier only: the suite
# compiles the same tiny-model programs over and over in fresh processes
# (train/pipeline/rl actors, isolated-subprocess tests, spawned workers
# inherit this env) — cache hits turn those recompiles into loads. Scoped
# per interpreter version under /tmp; harmless if the backend declines it.
if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
    import sys as _sys

    _cache = f"/tmp/ray_tpu_test_jax_cache_py{_sys.version_info[0]}{_sys.version_info[1]}"
    os.makedirs(_cache, exist_ok=True)
    os.environ["JAX_COMPILATION_CACHE_DIR"] = _cache
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

# let spawned worker processes import functions defined in test modules
_tests_dir = os.path.dirname(os.path.abspath(__file__))
_pp = os.environ.get("PYTHONPATH", "")
if _tests_dir not in _pp.split(":"):
    os.environ["PYTHONPATH"] = f"{_tests_dir}:{_pp}" if _pp else _tests_dir

from ray_tpu.utils import import_jax  # noqa: E402

import_jax()  # apply the platform override before any test touches jax

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "isolated: run this test in a fresh subprocess (native-heap "
        "protection: a jax/arrow segfault there cannot kill the suite)")


@pytest.hookimpl(tryfirst=True)
def pytest_runtest_protocol(item, nextitem):
    """Run @pytest.mark.isolated tests in a fresh interpreter.

    The one known suite-killer is a native-heap interaction between jax/XLA
    and pyarrow that needs ~25 min of accumulated in-process state and then
    segfaults PYTEST itself (README "Known issues"). Subprocess isolation
    keeps `pytest tests/ -q` a single green command: the child's verdict is
    reported through normal TestReports, and a child crash becomes a plain
    test failure instead of a dead suite."""
    if (item.get_closest_marker("isolated") is None
            or os.environ.get("RAY_TPU_TEST_IN_SUBPROCESS")):
        return None  # default protocol

    import subprocess
    import sys
    from _pytest.reports import TestReport

    hook = item.ihook
    hook.pytest_runtest_logstart(nodeid=item.nodeid, location=item.location)
    env = dict(os.environ, RAY_TPU_TEST_IN_SUBPROCESS="1")
    start = __import__("time").time()
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", "-q", "-x", "--no-header",
             item.nodeid],
            cwd=str(item.config.rootpath), env=env,
            capture_output=True, text=True, timeout=900)
        rc, out, err = proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        rc = -1
        out = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) \
            else (e.stdout or "")
        err = (e.stderr or b"").decode() if isinstance(e.stderr, bytes) \
            else (e.stderr or "")
        err += "\n[isolated subprocess timed out after 900s]"
    dur = __import__("time").time() - start
    if rc == 0 and " skipped" in out and " passed" not in out:
        # the child ran but skipped (pytest still exits 0): report a skip,
        # not a phantom pass
        outcome = "skipped"
        longrepr = (str(item.fspath), item.location[1] or 0,
                    f"skipped in isolated subprocess:\n{out[-1500:]}")
    elif rc == 0:
        outcome, longrepr = "passed", None
    else:
        outcome = "failed"
        longrepr = (f"isolated subprocess exited rc={rc}\n"
                    f"--- stdout (tail) ---\n{out[-6000:]}\n"
                    f"--- stderr (tail) ---\n{err[-3000:]}")
    reports = [
        TestReport(item.nodeid, item.location, {}, "passed", None,
                   "setup", duration=0.0),
        TestReport(item.nodeid, item.location, {}, outcome, longrepr,
                   "call", duration=dur, start=start, stop=start + dur),
        TestReport(item.nodeid, item.location, {}, "passed", None,
                   "teardown", duration=0.0),
    ]
    for rep in reports:
        hook.pytest_runtest_logreport(report=rep)
    hook.pytest_runtest_logfinish(nodeid=item.nodeid, location=item.location)
    # the default protocol ends every item with teardown_exact(nextitem),
    # popping module/class fixtures the next item doesn't need. Skipping it
    # here leaves the previous module's finalizers on the setup stack and
    # the NEXT file's first test dies with "previous item was not torn
    # down properly".
    try:
        item.session._setupstate.teardown_exact(nextitem)
    except Exception:
        pass
    return True


@pytest.fixture
def ray_local():
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(local_mode=True)
    yield ray_tpu
    ray_tpu.shutdown()

"""Unit tests for raylint's whole-program layers: the call graph
(tools/raylint/graph.py) and the CFG/dataflow engine (tools/raylint/flow.py)
that the interprocedural rules (ASY004/LCK002/AWT002/WIRE002) run on."""

import ast
import json
import sys
import textwrap
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[1]
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from tools.raylint import flow  # noqa: E402
from tools.raylint.graph import (  # noqa: E402
    GraphView,
    ProjectGraph,
    summarize_module,
    _modname,
)


def summarize(src, path="ray_tpu/_private/m.py"):
    return summarize_module(path, textwrap.dedent(src))


def make_tree(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return tmp_path


# ---------------------------------------------------------------------------
# summaries: functions, async coloring, calls, locks
# ---------------------------------------------------------------------------


def test_summary_async_coloring_and_qualnames():
    s = summarize("""
        def top():
            pass

        async def atop():
            def inner():
                pass

        class C:
            def m(self):
                pass

            async def am(self):
                pass
    """)
    fns = s["functions"]
    assert fns["top"]["is_async"] is False
    assert fns["atop"]["is_async"] is True
    assert fns["atop.inner"]["is_async"] is False  # nested def, own entry
    assert fns["C.m"]["is_async"] is False
    assert fns["C.am"]["is_async"] is True
    assert fns["C.m"]["cls"] == "C"


def test_summary_records_calls_with_alias_expansion():
    s = summarize("""
        from time import sleep as zzz
        import subprocess as sp

        def f(self):
            zzz(1)
            sp.run(["x"])
            self._helper()
    """)
    raws = {c["raw"] for c in s["functions"]["f"]["calls"]}
    assert "time.sleep" in raws
    assert "subprocess.run" in raws
    assert "self._helper" in raws
    # direct blocking calls are pre-extracted for the chain query
    whats = {b["what"] for b in s["functions"]["f"]["blocking"]}
    assert whats == {"time.sleep", "subprocess.run"}


def test_summary_lock_edges_and_held_calls():
    s = summarize("""
        import threading

        class C:
            def f(self):
                with self._a_lock:
                    with self._b_lock:
                        pass
                    self.helper()
    """)
    f = s["functions"]["C.f"]
    mod = _modname("ray_tpu/_private/m.py")
    a = f"{mod}:C._a_lock"
    b = f"{mod}:C._b_lock"
    assert [a, b] == [l for l, _ in f["acquires"]]
    assert [[a, b, 7]] == [e for e in f["lock_edges"]]
    held_calls = [(c["raw"], c["held"]) for c in f["calls"] if c["held"]]
    assert ("self.helper", [a]) in held_calls


def test_summary_module_level_lock_identity():
    s = summarize("""
        import threading

        _lock = threading.Lock()

        def f():
            with _lock:
                pass
    """)
    mod = _modname("ray_tpu/_private/m.py")
    assert s["functions"]["f"]["acquires"] == [[f"{mod}:_lock", 7]]


# ---------------------------------------------------------------------------
# resolution: method vs module calls, cross-module, constructors
# ---------------------------------------------------------------------------


def test_resolution_self_method_vs_module_function(tmp_path):
    root = make_tree(tmp_path, {"ray_tpu/_private/m.py": """
        def helper():
            pass

        class C:
            def helper(self):
                pass

            def go(self):
                self.helper()
                helper()
    """})
    g = ProjectGraph(root, cache_path=None, use_cache=False)
    view = GraphView(g)
    path = "ray_tpu/_private/m.py"
    go = g.summaries[path]["functions"]["C.go"]
    targets = {view.resolve_call(path, go, c) for c in go["calls"]}
    assert (path, "C.helper") in targets   # self.helper() -> the method
    assert (path, "helper") in targets     # helper() -> module function


def test_resolution_cross_module_and_constructor(tmp_path):
    root = make_tree(tmp_path, {
        "ray_tpu/_private/a.py": """
            from ray_tpu._private.b import worker, Klass
            import ray_tpu._private.b as bmod

            def go():
                worker()
                bmod.worker()
                Klass()
        """,
        "ray_tpu/_private/b.py": """
            def worker():
                pass

            class Klass:
                def __init__(self):
                    pass
        """,
    })
    g = ProjectGraph(root, cache_path=None, use_cache=False)
    view = GraphView(g)
    go = g.summaries["ray_tpu/_private/a.py"]["functions"]["go"]
    targets = [view.resolve_call("ray_tpu/_private/a.py", go, c)
               for c in go["calls"]]
    assert targets.count(("ray_tpu/_private/b.py", "worker")) == 2
    assert ("ray_tpu/_private/b.py", "Klass.__init__") in targets


def test_resolution_base_class_method_same_module(tmp_path):
    root = make_tree(tmp_path, {"ray_tpu/_private/m.py": """
        class Base:
            def shared(self):
                pass

        class Child(Base):
            def go(self):
                self.shared()
    """})
    g = ProjectGraph(root, cache_path=None, use_cache=False)
    view = GraphView(g)
    path = "ray_tpu/_private/m.py"
    go = g.summaries[path]["functions"]["Child.go"]
    assert view.resolve_call(path, go, go["calls"][0]) == (path, "Base.shared")


def test_blocking_chain_crosses_modules_and_memoizes(tmp_path):
    root = make_tree(tmp_path, {
        "ray_tpu/_private/a.py": """
            from ray_tpu._private.b import step

            def outer():
                step()
        """,
        "ray_tpu/_private/b.py": """
            import time

            def step():
                time.sleep(1)
        """,
    })
    g = ProjectGraph(root, cache_path=None, use_cache=False)
    view = GraphView(g)
    hit = view.blocking_chain(("ray_tpu/_private/a.py", "outer"))
    assert hit is not None
    chain, what, _hint = hit
    assert what == "time.sleep"
    assert [q for _, q, _ in chain] == ["outer", "step"]
    # an async function never participates in a sync chain
    assert view.blocking_chain(("ray_tpu/_private/a.py", "missing")) is None


# ---------------------------------------------------------------------------
# cache: warm hits, invalidation on edit, schema versioning
# ---------------------------------------------------------------------------


def test_cache_invalidation_on_file_edit(tmp_path):
    root = make_tree(tmp_path, {"ray_tpu/_private/m.py": """
        def f():
            pass
    """})
    cache = tmp_path / "graphcache.json"
    g1 = ProjectGraph(root, cache_path=cache)
    assert g1.stats["parsed"] == 1 and g1.stats["cache_hits"] == 0
    assert cache.is_file()

    # warm rebuild: pure cache hits, no re-parse
    g2 = ProjectGraph(root, cache_path=cache)
    assert g2.stats["cache_hits"] == 1 and g2.stats["parsed"] == 0
    assert g2.summaries == g1.summaries

    # edit the file: its hash changes, so only it re-parses
    (root / "ray_tpu/_private/m.py").write_text("def g():\n    pass\n")
    g3 = ProjectGraph(root, cache_path=cache)
    assert g3.stats["parsed"] == 1 and g3.stats["cache_hits"] == 0
    assert "g" in g3.summaries["ray_tpu/_private/m.py"]["functions"]
    assert "f" not in g3.summaries["ray_tpu/_private/m.py"]["functions"]


def test_cache_schema_version_mismatch_forces_rebuild(tmp_path):
    root = make_tree(tmp_path, {"ray_tpu/_private/m.py": "def f():\n    pass\n"})
    cache = tmp_path / "graphcache.json"
    ProjectGraph(root, cache_path=cache)
    doc = json.loads(cache.read_text())
    doc["version"] = -1
    cache.write_text(json.dumps(doc))
    g = ProjectGraph(root, cache_path=cache)
    assert g.stats["parsed"] == 1 and g.stats["cache_hits"] == 0


def test_corrupt_cache_is_ignored(tmp_path):
    root = make_tree(tmp_path, {"ray_tpu/_private/m.py": "def f():\n    pass\n"})
    cache = tmp_path / "graphcache.json"
    cache.write_text("{not json")
    g = ProjectGraph(root, cache_path=cache)
    assert g.stats["parsed"] == 1
    # and the bad file was replaced with a valid one
    assert json.loads(cache.read_text())["files"]


# ---------------------------------------------------------------------------
# lock graph: cycle fixture at the graph level
# ---------------------------------------------------------------------------


def test_lock_graph_cycle_edges(tmp_path):
    root = make_tree(tmp_path, {"ray_tpu/_private/m.py": """
        import threading

        class P:
            def one(self):
                with self._a_lock:
                    self.grab_b()

            def grab_b(self):
                with self._b_lock:
                    pass

            def two(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
    """})
    g = ProjectGraph(root, cache_path=None, use_cache=False)
    view = GraphView(g)
    edges = view.lock_graph(("ray_tpu/_private/",))
    mod = _modname("ray_tpu/_private/m.py")
    a, b = f"{mod}:P._a_lock", f"{mod}:P._b_lock"
    assert (a, b) in edges  # via the call edge one -> grab_b
    assert (b, a) in edges  # via lexical nesting in two
    # rlock registry: none constructed here
    assert view.rlock_ids() == set()


def test_rlock_construction_is_recorded(tmp_path):
    root = make_tree(tmp_path, {"ray_tpu/_private/m.py": """
        import threading

        class P:
            def __init__(self):
                self._re_lock = threading.RLock()
    """})
    g = ProjectGraph(root, cache_path=None, use_cache=False)
    mod = _modname("ray_tpu/_private/m.py")
    assert GraphView(g).rlock_ids() == {f"{mod}:P._re_lock"}


# ---------------------------------------------------------------------------
# RPC universe: handlers, dispatcher arms, wrappers — WIRE002's raw material
# ---------------------------------------------------------------------------


def test_rpc_universe_collection(tmp_path):
    root = make_tree(tmp_path, {
        "ray_tpu/_private/server.py": """
            class S:
                async def _rpc_Alpha(self, req, conn):
                    return {}

                async def _handle(self, method, payload, conn):
                    if method == "Beta":
                        return b""
        """,
        "ray_tpu/_private/client.py": """
            class C:
                async def _wrapped_call(self, method, payload):
                    pass

                async def go(self, client, kind):
                    await client.call("Alpha", b"")
                    method = "Beta" if kind else "Alpha"
                    await client.call(method, b"")
                    await self._wrapped_call("Gamma", b"")
        """,
    })
    g = ProjectGraph(root, cache_path=None, use_cache=False)
    view = GraphView(g)
    handlers = view.rpc_handlers()
    calls = view.rpc_calls()
    assert set(handlers) == {"Alpha", "Beta"}
    # direct literal, via-variable literals, and wrapper `method` param
    assert set(calls) == {"Alpha", "Beta", "Gamma"}


def test_wire_registry_extraction():
    s = summarize("""
        def register_struct(cls, fields=None, decode=None):
            return cls

        class Spec:
            pass

        register_struct(Spec, fields=("a", "b"),
                        decode=lambda f: Spec(f["a"], f["b"], f["ghost"]))
    """, path="ray_tpu/_private/wire.py")
    (entry,) = s["wire_registry"]
    assert entry["fields"] == ["a", "b"]
    assert entry["decode_fields"] == ["a", "b", "ghost"]


# ---------------------------------------------------------------------------
# flow layer: CFG shape, may-analysis, reaching definitions
# ---------------------------------------------------------------------------


def _fn(src):
    tree = ast.parse(textwrap.dedent(src))
    return next(n for n in ast.walk(tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)))


def test_cfg_if_branches_join():
    cfg = flow.build_cfg(_fn("""
        def f(c):
            a = 1
            if c:
                b = 2
            else:
                b = 3
            return b
    """))
    # the return node is reachable from both branch bodies
    ret = next(i for i, n in enumerate(cfg.nodes) if isinstance(n, ast.Return))
    preds = cfg.preds()[ret]
    assert len(preds) == 2


def test_cfg_while_has_back_edge():
    cfg = flow.build_cfg(_fn("""
        def f(n):
            while n:
                n -= 1
            return n
    """))
    loop = next(i for i, n in enumerate(cfg.nodes) if isinstance(n, ast.While))
    body = next(i for i, n in enumerate(cfg.nodes)
                if isinstance(n, ast.AugAssign))
    assert loop in cfg.succ[body]  # back edge


def test_cfg_try_body_reaches_handler():
    cfg = flow.build_cfg(_fn("""
        def f():
            risky()
            cleanup()
    """))
    assert len(cfg.nodes) == 2
    cfg = flow.build_cfg(_fn("""
        def f():
            try:
                risky()
            except Exception:
                handle()
            done()
    """))
    risky = next(i for i, n in enumerate(cfg.nodes)
                 if "risky" in ast.dump(n))
    handle = next(i for i, n in enumerate(cfg.nodes)
                  if "handle" in ast.dump(n))
    assert handle in cfg.succ[risky]  # the exception path exists


def test_forward_may_unions_branches():
    fn = _fn("""
        def f(c):
            if c:
                acquire()
            use()
    """)
    cfg = flow.build_cfg(fn)

    def transfer(stmt, facts):
        if "acquire" in ast.dump(stmt):
            return facts | {"L"}
        return facts

    IN = flow.forward_may(cfg, transfer)
    use = next(i for i, n in enumerate(cfg.nodes) if "use" in ast.dump(n))
    assert IN[use] == frozenset({"L"})  # may-held via the if-branch


def test_reaching_defs_tracks_unique_and_merged():
    fn = _fn("""
        def f(c):
            x = source_a()
            if c:
                x = source_b()
            sink(x)
    """)
    cfg = flow.build_cfg(fn)
    defs = flow.reaching_defs(cfg)
    sink = next(i for i, n in enumerate(cfg.nodes) if "sink" in ast.dump(n))
    values = defs[sink]["x"]
    assert len(values) == 2  # both definitions may reach the sink
    dumped = " ".join(ast.dump(v) for v in values if v is not None)
    assert "source_a" in dumped and "source_b" in dumped


# ---------------------------------------------------------------------------
# memoization discipline: pruned traversals must not poison the cache
# ---------------------------------------------------------------------------


def test_blocking_chain_memo_not_poisoned_by_cycle_pruning(tmp_path):
    # entry_a explores the a<->b cycle first; the pruned traversal of b
    # must not memoize "no blocking" for b, or entry_b's real chain
    # (b -> a -> c -> time.sleep) silently disappears (order-dependent
    # false negative)
    root = make_tree(tmp_path, {"ray_tpu/_private/m.py": """
        import time

        def c():
            time.sleep(1)

        def a(n):
            if n:
                b(n - 1)
            c()

        def b(n):
            a(n)

        async def entry_a():
            a(1)

        async def entry_b():
            b(1)
    """})
    g = ProjectGraph(root, cache_path=None, use_cache=False)
    view = GraphView(g)
    path = "ray_tpu/_private/m.py"
    assert view.blocking_chain((path, "a")) is not None
    assert view.blocking_chain((path, "b")) is not None
    # and again, order-reversed, on a fresh view
    view2 = GraphView(g)
    assert view2.blocking_chain((path, "b")) is not None
    assert view2.blocking_chain((path, "a")) is not None


def test_transitive_acquires_memo_not_poisoned_by_cycle_pruning(tmp_path):
    root = make_tree(tmp_path, {"ray_tpu/_private/m.py": """
        import threading

        class P:
            def a(self, n):
                if n:
                    self.b(n - 1)
                with self._deep_lock:
                    pass

            def b(self, n):
                self.a(n)
    """})
    g = ProjectGraph(root, cache_path=None, use_cache=False)
    view = GraphView(g)
    path = "ray_tpu/_private/m.py"
    mod = _modname(path)
    # probing a (which prunes at the a<->b cycle) first must not hide
    # b's reachable acquisition afterwards
    assert f"{mod}:P._deep_lock" in view.transitive_acquires((path, "P.a"))
    assert f"{mod}:P._deep_lock" in view.transitive_acquires((path, "P.b"))


def test_module_level_rlock_is_reentrancy_exempt(tmp_path):
    # a module-global RLock re-acquired through a helper is reentrant,
    # not a self-deadlock
    root = make_tree(tmp_path, {"ray_tpu/_private/m.py": """
        import threading

        _re_lock = threading.RLock()

        def outer():
            with _re_lock:
                inner()

        def inner():
            with _re_lock:
                pass
    """})
    g = ProjectGraph(root, cache_path=None, use_cache=False)
    view = GraphView(g)
    mod = _modname("ray_tpu/_private/m.py")
    assert f"{mod}:_re_lock" in view.rlock_ids()
    edges = view.lock_graph(("ray_tpu/_private/",))
    key = (f"{mod}:_re_lock", f"{mod}:_re_lock")
    # the self-edge may exist in the graph; LCK002 exempts it via rlock_ids
    if key in edges:
        assert f"{mod}:_re_lock" in view.rlock_ids()


def test_summarize_survives_bare_name_lock_alias():
    # `lk = _lock` (module-level lock aliased to a local) must summarize,
    # not crash: lock_id runs during the alias pre-pass itself
    s = summarize("""
        import threading

        _lock = threading.Lock()

        def f():
            lk = _lock
            with lk:
                pass
    """)
    mod = _modname("ray_tpu/_private/m.py")
    assert s["functions"]["f"]["acquires"] == [[f"{mod}:_lock", 8]]


def test_annotated_module_lock_and_rlock_are_recognized(tmp_path):
    # AnnAssign forms: `_lock: threading.Lock = threading.Lock()` must get
    # module-level identity (not per-function fragments), and an annotated
    # RLock must be reentrancy-exempt in LCK002's registry
    root = make_tree(tmp_path, {"ray_tpu/_private/m.py": """
        import threading

        _lock: threading.Lock = threading.Lock()

        def put():
            with _lock:
                _evict()

        def _evict():
            with _lock:
                pass

        class S:
            def __init__(self):
                self._re_lock: threading.RLock = threading.RLock()
    """})
    g = ProjectGraph(root, cache_path=None, use_cache=False)
    view = GraphView(g)
    mod = _modname("ray_tpu/_private/m.py")
    # one shared identity -> the self-deadlock edge exists in the graph
    edges = view.lock_graph(("ray_tpu/_private/",))
    assert (f"{mod}:_lock", f"{mod}:_lock") in edges
    # annotated RLock recorded as reentrant
    assert f"{mod}:S._re_lock" in view.rlock_ids()


# ---------------------------------------------------------------------------
# context layer (tools/raylint/context.py): execution-context inference
# ---------------------------------------------------------------------------

from tools.raylint.context import ContextIndex, context_index  # noqa: E402

_P = "ray_tpu/_private/m.py"


def _ctx_index_for(tmp_path, src):
    root = make_tree(tmp_path, {_P: src})
    g = ProjectGraph(root, cache_path=None, use_cache=False)
    return ContextIndex(GraphView(g))


def test_context_thread_loop_main_propagation(tmp_path):
    idx = _ctx_index_for(tmp_path, """
        import threading

        def start():
            threading.Thread(target=_bg).start()

        def _bg():
            shared()
            tick()

        async def tick():
            shared()

        def api():
            shared()

        def shared():
            pass

        def register(loop):
            loop.call_soon(_cb)

        def _cb():
            pass
    """)
    # spawn target: thread root, and ONLY thread (not a main entry point)
    assert idx.contexts((_P, "_bg")) == {"thread"}
    assert (_P, "_bg") in idx.spawn_targets
    # async def: loop root; thread does NOT cross into async bodies
    assert idx.contexts((_P, "tick")) == {"loop"}
    # a sync helper reachable from all three accumulates all three
    assert idx.contexts((_P, "shared")) == {"thread", "loop", "main"}
    # un-spawned sync entry points are main
    assert idx.contexts((_P, "start")) == {"main"}
    # loop.call_soon callback is a loop root via the spawn edge
    assert idx.contexts((_P, "_cb")) == {"loop"}
    assert (_P, "_cb") in idx.spawn_targets


def test_context_fork_crosses_spawns_and_async(tmp_path):
    idx = _ctx_index_for(tmp_path, """
        import os
        import threading

        def _child_main():
            boot()

        def boot():
            threading.Thread(target=_flush).start()
            drain()

        def _flush():
            pass

        async def drain():
            pass

        def spawner():
            return os.fork()

        def outer():
            return spawner()
    """)
    # fork is process-scoped: it crosses thread-spawn edges AND enters
    # async bodies (the coroutine still runs inside the forked image)
    assert "fork" in idx.contexts((_P, "boot"))
    assert "fork" in idx.contexts((_P, "_flush"))
    assert "fork" in idx.contexts((_P, "drain"))
    # .forking is reverse reachability from os.fork() sites only
    assert idx.forking == {(_P, "spawner"), (_P, "outer")}
    # provenance chain walks back to the fork root
    chain = idx.chain((_P, "_flush"), "fork")
    assert chain.startswith("_flush")
    assert "_child_main" in chain


def test_context_always_held_meet_and_cycles(tmp_path):
    idx = _ctx_index_for(tmp_path, """
        import threading

        _lock = threading.Lock()

        def entry_a():
            with _lock:
                helper()
                helper2()

        def entry_b():
            with _lock:
                ring_a()

        def entry_c():
            helper2()

        def helper():
            pass

        def helper2():
            pass

        def ring_a():
            ring_b()

        def ring_b():
            ring_a()

        def orbit_a():
            orbit_b()

        def orbit_b():
            orbit_a()
    """)
    # every caller holds the lock -> the helper inherits it
    held = idx.always_held((_P, "helper"))
    assert len(held) == 1 and next(iter(held)).endswith("_lock")
    # a cycle with ONE locked outside entry converges to that entry's truth
    assert idx.always_held((_P, "ring_a")) == held
    assert idx.always_held((_P, "ring_b")) == held
    # meet over callers: one unlocked caller degrades to the empty set
    assert idx.always_held((_P, "helper2")) == frozenset()
    # an isolated mutual-recursion cycle stays at top internally (no known
    # entry) and degrades to the SAFE answer — no lock credit — at query
    assert idx._always[(_P, "orbit_a")] is None
    assert idx.always_held((_P, "orbit_a")) == frozenset()


def test_context_memo_per_view(tmp_path):
    root = make_tree(tmp_path, {_P: "def f():\n    pass\n"})
    g = ProjectGraph(root, cache_path=None, use_cache=False)
    view = GraphView(g)
    idx1 = context_index(view)
    assert context_index(view) is idx1  # memoized on the view
    # a different view (e.g. an overlay) gets its own index
    assert context_index(GraphView(g)) is not idx1


def test_context_cache_roundtrip_and_invalidation(tmp_path):
    src = """
        import threading

        def start():
            threading.Thread(target=_bg).start()

        def _bg():
            helper()

        def helper():
            pass
    """
    root = make_tree(tmp_path, {_P: src})
    cache = tmp_path / "graphcache.json"

    g1 = ProjectGraph(root, cache_path=cache)
    idx1 = ContextIndex(GraphView(g1))
    assert idx1.cache_hit is False
    assert idx1.contexts((_P, "helper")) >= {"thread"}

    # warm rebuild: the contexts section rides the graph cache
    g2 = ProjectGraph(root, cache_path=cache)
    idx2 = ContextIndex(GraphView(g2))
    assert idx2.cache_hit is True
    assert idx2.ctx == idx1.ctx
    assert idx2._always == idx1._always
    assert idx2.spawn_targets == idx1.spawn_targets
    assert idx2.forking == idx1.forking

    # contexts-section schema bump -> recompute (same answers)
    doc = json.loads(cache.read_text())
    doc["contexts"]["graph_version"] = -1
    cache.write_text(json.dumps(doc))
    g3 = ProjectGraph(root, cache_path=cache)
    idx3 = ContextIndex(GraphView(g3))
    assert idx3.cache_hit is False
    assert idx3.ctx == idx1.ctx

    # editing a file changes the fingerprint -> recompute, new facts land
    (root / _P).write_text((root / _P).read_text()
                           + "\ndef extra():\n    helper()\n")
    g4 = ProjectGraph(root, cache_path=cache)
    idx4 = ContextIndex(GraphView(g4))
    assert idx4.cache_hit is False
    assert (_P, "extra") in idx4.ctx


def test_context_overlay_view_never_uses_disk_cache(tmp_path):
    root = make_tree(tmp_path, {_P: "def f():\n    pass\n"})
    cache = tmp_path / "graphcache.json"
    g1 = ProjectGraph(root, cache_path=cache)
    ContextIndex(GraphView(g1))  # seeds the contexts section
    overlay = summarize_module(_P, "def g():\n    pass\n")
    idx = ContextIndex(GraphView(ProjectGraph(root, cache_path=cache),
                                 overlay=overlay))
    # the overlay's summaries differ from disk: it must recompute, and
    # must not clobber the pristine cache either
    assert idx.cache_hit is False
    assert (_P, "g") in idx.ctx
    doc = json.loads(cache.read_text())
    cached_quals = {k.rsplit("||", 1)[-1] for k in doc["contexts"]["ctx"]}
    assert "f" in cached_quals and "g" not in cached_quals

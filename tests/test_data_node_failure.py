"""Ray Data survives node loss mid-job via lineage reconstruction.

The round-1 gap this closes (VERDICT): a host dying mid-shuffle used to be a
terminal ObjectLostError; with ownership refcounting + lineage the data
layer recovers by re-executing the producing tasks (reference:
object_recovery_manager.h:41 driving test_reconstruction*.py scenarios).
"""

import time

import pytest

import ray_tpu
from ray_tpu import data as rdata
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def two_node_cluster():
    ray_tpu.shutdown()
    cluster = Cluster(head_node_args={"resources": {"CPU": 3.0}})
    node_b = cluster.add_node(resources={"CPU": 3.0, "zone_b": 10.0})
    ray_tpu.init(address=cluster.address)
    cluster.wait_for_nodes(2)
    yield cluster, node_b
    ray_tpu.shutdown()
    cluster.shutdown()


def test_shuffle_survives_node_kill(two_node_cluster):
    """Materialize blocks spread over both nodes, kill one node, then run a
    shuffle + aggregate over the stale refs: results must be exact."""
    cluster, node_b = two_node_cluster
    n = 4000
    ds = rdata.range(n, parallelism=8).map_batches(
        lambda b: {"id": b["id"], "pad": b["id"] * 0}, batch_size=None)
    ds = ds.materialize()  # blocks now live on both nodes
    cluster.remove_node(node_b)
    time.sleep(1.0)
    cluster.add_node(resources={"CPU": 3.0, "zone_b": 10.0})
    cluster.wait_for_nodes(2)  # head + replacement (the killed node may already be marked dead)
    # consuming the materialized blocks requires reconstructing whatever
    # lived on the killed node
    total = sum(r["id"] for r in ds.iter_rows())
    assert total == n * (n - 1) // 2


def test_groupby_aggregate_survives_node_kill(two_node_cluster):
    cluster, node_b = two_node_cluster
    n = 2000
    ds = rdata.range(n, parallelism=8).materialize()
    cluster.remove_node(node_b)
    time.sleep(1.0)
    cluster.add_node(resources={"CPU": 3.0, "zone_b": 10.0})
    cluster.wait_for_nodes(2)  # head + replacement (the killed node may already be marked dead)
    out = (ds.map_batches(lambda b: {"k": b["id"] % 4, "v": b["id"]},
                          batch_size=None)
             .groupby("k").sum("v"))
    rows = {r["k"]: r["sum(v)"] for r in out.iter_rows()}
    expected = {k: sum(v for v in range(n) if v % 4 == k) for k in range(4)}
    assert rows == expected

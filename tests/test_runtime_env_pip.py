"""pip/uv runtime environments: per-env cached venvs, workers launched
inside them (reference: python/ray/_private/runtime_env/pip.py + uv.py,
python/ray/tests/test_runtime_env_2.py).

The CI image has no package index (zero egress), so the test installs a
hand-rolled wheel from a local path — exactly what pip does with any
requirement, minus the network.
"""

import os
import zipfile

import pytest

import ray_tpu
from ray_tpu._private.runtime_env import ensure_env_python, normalize

PKG = "graft_renv_demo"


def _make_wheel(tmp_path, version="0.1", value=42) -> str:
    """A minimal valid wheel: one module + dist-info."""
    name = f"{PKG}-{version}-py3-none-any.whl"
    path = str(tmp_path / name)
    di = f"{PKG}-{version}.dist-info"
    record_rows = []
    with zipfile.ZipFile(path, "w") as z:
        files = {
            f"{PKG}.py": f"VALUE = {value}\n",
            f"{di}/METADATA": (f"Metadata-Version: 2.1\nName: {PKG}\n"
                               f"Version: {version}\n"),
            f"{di}/WHEEL": ("Wheel-Version: 1.0\nGenerator: graft\n"
                            "Root-Is-Purelib: true\nTag: py3-none-any\n"),
        }
        for arc, content in files.items():
            z.writestr(arc, content)
            record_rows.append(f"{arc},,")
        record_rows.append(f"{di}/RECORD,,")
        z.writestr(f"{di}/RECORD", "\n".join(record_rows) + "\n")
    return path


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(resources={"CPU": 4.0})
    yield
    ray_tpu.shutdown()


def test_pip_env_task(cluster, tmp_path):
    wheel = _make_wheel(tmp_path)

    @ray_tpu.remote(runtime_env={"pip": [wheel]})
    def use_pkg():
        import graft_renv_demo

        return graft_renv_demo.VALUE

    # the base env must NOT have the package — otherwise this test is a lie
    with pytest.raises(ImportError):
        import graft_renv_demo  # noqa: F401

    assert ray_tpu.get(use_pkg.remote(), timeout=300) == 42

    @ray_tpu.remote
    def plain():
        try:
            import graft_renv_demo  # noqa: F401

            return "leaked"
        except ImportError:
            return "isolated"

    # env-hash-keyed worker pool: a no-env task gets a base-env worker
    assert ray_tpu.get(plain.remote(), timeout=120) == "isolated"


def test_pip_env_cached_venv(tmp_path):
    wheel = _make_wheel(tmp_path, version="0.2", value=7)
    renv = normalize({"pip": [wheel]})
    py1 = ensure_env_python(renv)
    assert py1 and os.path.exists(py1)
    import time

    t0 = time.perf_counter()
    py2 = ensure_env_python(renv)
    assert py2 == py1
    assert time.perf_counter() - t0 < 0.5  # cache hit, no rebuild
    # the venv interpreter sees both the new package and the base env
    import subprocess
    import sys as _sys

    out = subprocess.run(
        [py1, "-c", "import graft_renv_demo, msgpack; "
         "print(graft_renv_demo.VALUE)"],
        capture_output=True, text=True, timeout=60,
        env={k: v for k, v in os.environ.items()})
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "7"
    del _sys


def test_uv_spec_normalizes_to_pip():
    renv = normalize({"uv": ["left-pad==1.0"]})
    assert renv["pip"]["packages"] == ["left-pad==1.0"]
    assert renv["pip"]["installer"] == "uv"


def test_pip_install_failure_surfaces(cluster):
    @ray_tpu.remote(runtime_env={
        "pip": ["this-package-cannot-exist-graft-xyz==9.9.9"]})
    def f():
        return 1

    from ray_tpu.exceptions import TaskError

    with pytest.raises(TaskError):
        ray_tpu.get(f.remote(), timeout=300)

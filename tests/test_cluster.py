"""Distributed runtime tests: real GCS + raylet + worker processes.

Reference tier: python/ray/tests/test_basic.py + test_actor.py running under
ray_start_regular (conftest.py:596).
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import TaskError


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


def test_submit_and_get(cluster):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 2), timeout=60) == 3


def test_parallel_tasks(cluster):
    @ray_tpu.remote
    def sq(x):
        return x * x

    refs = [sq.remote(i) for i in range(20)]
    assert ray_tpu.get(refs, timeout=60) == [i * i for i in range(20)]


def test_task_chain_ref_args(cluster):
    @ray_tpu.remote
    def inc(x):
        return x + 1

    ref = inc.remote(0)
    for _ in range(5):
        ref = inc.remote(ref)
    assert ray_tpu.get(ref, timeout=60) == 6


def test_large_object_roundtrip(cluster):
    arr = np.random.rand(512, 1024)  # 4 MiB -> shared-memory store

    @ray_tpu.remote
    def total(a):
        return float(a.sum())

    ref = ray_tpu.put(arr)
    assert abs(ray_tpu.get(total.remote(ref), timeout=60) - arr.sum()) < 1e-6


def test_large_task_result(cluster):
    @ray_tpu.remote
    def big():
        return np.ones((1024, 1024))  # 8 MiB result -> store, not inline

    out = ray_tpu.get(big.remote(), timeout=60)
    assert out.shape == (1024, 1024) and out[0, 0] == 1.0


def test_task_error(cluster):
    @ray_tpu.remote
    def boom():
        raise RuntimeError("exploded")

    with pytest.raises(TaskError, match="exploded"):
        ray_tpu.get(boom.remote(), timeout=60)


def test_nested_tasks(cluster):
    @ray_tpu.remote
    def inner(x):
        return x * 2

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) + 1

    assert ray_tpu.get(outer.remote(10), timeout=60) == 21


def test_actor_lifecycle(cluster):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def incr(self, by=1):
            self.n += by
            return self.n

        def value(self):
            return self.n

    c = Counter.remote(100)
    refs = [c.incr.remote() for _ in range(10)]
    results = ray_tpu.get(refs, timeout=60)
    assert results == list(range(101, 111))
    assert ray_tpu.get(c.value.remote(), timeout=60) == 110


def test_named_actor_cross_process(cluster):
    @ray_tpu.remote
    class Registry:
        def __init__(self):
            self.data = {}

        def set(self, k, v):
            self.data[k] = v
            return True

        def get(self, k):
            return self.data.get(k)

    Registry.options(name="reg", lifetime="detached").remote()

    @ray_tpu.remote
    def writer():
        h = ray_tpu.get_actor("reg")
        return ray_tpu.get(h.set.remote("from_task", 42))

    assert ray_tpu.get(writer.remote(), timeout=60)
    h = ray_tpu.get_actor("reg")
    assert ray_tpu.get(h.get.remote("from_task"), timeout=60) == 42
    ray_tpu.kill(h)


def test_actor_handle_passed_to_task(cluster):
    @ray_tpu.remote
    class Store:
        def __init__(self):
            self.v = 0

        def bump(self):
            self.v += 1
            return self.v

    s = Store.remote()

    @ray_tpu.remote
    def bump_it(handle):
        return ray_tpu.get(handle.bump.remote())

    assert ray_tpu.get(bump_it.remote(s), timeout=60) == 1
    assert ray_tpu.get(s.bump.remote(), timeout=60) == 2


def test_wait(cluster):
    @ray_tpu.remote
    def slow(t):
        time.sleep(t)
        return t

    fast = slow.remote(0.01)
    slower = slow.remote(5.0)
    ready, rest = ray_tpu.wait([fast, slower], num_returns=1, timeout=30)
    assert ready == [fast] and rest == [slower]


def test_cluster_resources(cluster):
    total = ray_tpu.cluster_resources()
    assert total.get("CPU") == 4.0
    assert len(ray_tpu.nodes()) == 1


def test_async_actor(cluster):
    @ray_tpu.remote
    class AsyncActor:
        async def work(self, x):
            import asyncio

            await asyncio.sleep(0.01)
            return x + 1

    a = AsyncActor.options(max_concurrency=4).remote()
    out = ray_tpu.get([a.work.remote(i) for i in range(8)], timeout=60)
    assert out == [i + 1 for i in range(8)]

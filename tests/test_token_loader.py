"""Native data-loader tests (reference tier: C++ dataloader unit tests)."""

import numpy as np
import pytest

from ray_tpu.data.token_loader import TokenFileLoader, write_token_file


@pytest.fixture
def corpus(tmp_path):
    tokens = np.arange(100_000, dtype=np.int32) % 32000
    path = str(tmp_path / "corpus.bin")
    write_token_file(path, tokens)
    return path, tokens


def test_native_build_and_sample(corpus):
    path, tokens = corpus
    with TokenFileLoader(path, batch=4, seq=128, seed=7) as loader:
        assert loader.native, "native loader failed to build"
        assert loader.num_tokens == len(tokens)
        batch = loader.next_batch()
        assert batch["tokens"].shape == (4, 128)
        assert batch["targets"].shape == (4, 128)
        # rows are consecutive corpus slices: the corpus is arange % 32000,
        # so successive tokens differ by 1 (mod 32000)
        t, y = batch["tokens"], batch["targets"]
        assert np.all(y[:, :-1] == t[:, 1:])
        diffs = np.diff(t.astype(np.int64), axis=1) % 32000
        assert np.all(diffs == 1), "rows are not consecutive corpus slices"


def test_single_buffer_ring_does_not_deadlock(corpus):
    path, _ = corpus
    with TokenFileLoader(path, batch=2, seq=32, seed=5, n_buffers=1) as loader:
        for _ in range(3):
            assert loader.next_batch()["tokens"].shape == (2, 32)


def test_prefetch_overlaps(corpus):
    path, _ = corpus
    import time

    with TokenFileLoader(path, batch=8, seq=512, seed=1, n_buffers=3) as loader:
        loader.next_batch()
        time.sleep(0.2)  # background thread should have refilled the ring
        assert loader.batches_produced() >= 2


def test_seeded_determinism(corpus):
    path, _ = corpus
    with TokenFileLoader(path, batch=4, seq=64, seed=42) as a:
        b1 = a.next_batch()["tokens"].copy()
    with TokenFileLoader(path, batch=4, seq=64, seed=42) as b:
        b2 = b.next_batch()["tokens"].copy()
    np.testing.assert_array_equal(b1, b2)


def test_python_fallback_matches_api(corpus):
    path, tokens = corpus
    loader = TokenFileLoader(path, batch=2, seq=32, seed=3, force_python=True)
    assert not loader.native
    batch = loader.next_batch()
    assert batch["tokens"].shape == (2, 32)
    assert np.all(batch["targets"][:, :-1] == batch["tokens"][:, 1:])


def test_uint16_tokens(tmp_path):
    tokens = (np.arange(10_000) % 60000).astype(np.uint16)
    path = str(tmp_path / "c16.bin")
    write_token_file(path, tokens, token_bytes=2)
    with TokenFileLoader(path, batch=2, seq=16, token_bytes=2) as loader:
        batch = loader.next_batch()
        assert batch["tokens"].dtype == np.int32
        assert batch["tokens"].max() < 60000


def test_feeds_train_step(corpus):
    """End-to-end: native loader -> TrainStepBundle on the CPU mesh."""
    path, _ = corpus
    from ray_tpu.utils import import_jax

    jax = import_jax()
    from ray_tpu.models import CONFIGS
    from ray_tpu.parallel import TrainStepBundle, create_mesh

    mesh = create_mesh({"data": 1, "fsdp": 1, "seq": 1, "tensor": 1},
                       devices=jax.devices()[:1])
    bundle = TrainStepBundle(CONFIGS["tiny"], mesh)
    params, opt = bundle.init(jax.random.PRNGKey(0))
    with TokenFileLoader(path, batch=4, seq=128, seed=0) as loader:
        for i, batch in zip(range(3), loader.batches()):
            batch = {k: np.ascontiguousarray(v) % 256 if k != "mask" else v
                     for k, v in batch.items()}
            dev = {k: jax.device_put(v, bundle.batch_sharding)
                   for k, v in batch.items()}
            params, opt, loss = bundle.step(params, opt, dev)
    assert np.isfinite(float(loss))

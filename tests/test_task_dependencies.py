"""Dependent-task submission regressions (the round-3 PushTaskBatch
deadlock): chains and fan-in graphs submitted before any get must complete,
and task batches must never serialize independent long tasks.

Reference: the owner-side dependency resolver shape —
src/ray/core_worker/task_submission/dependency_resolver.cc used by
normal_task_submitter.cc:32 (deps resolve before dispatch).
"""

import time

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield ray_tpu
    ray_tpu.shutdown()


def test_deep_chain_before_get(cluster):
    @ray_tpu.remote
    def inc(x):
        return x + 1

    r = 0
    for _ in range(100):
        r = inc.remote(r)
    assert ray_tpu.get(r, timeout=180) == 100


def test_mixed_fanin_graph_before_get(cluster):
    @ray_tpu.remote
    def inc(x):
        return x + 1

    @ray_tpu.remote
    def add(*xs):
        return sum(xs)

    leaves = [inc.remote(i) for i in range(8)]          # 1..8
    mids = [add.remote(leaves[i], leaves[i + 1]) for i in range(0, 8, 2)]
    root = add.remote(*mids)
    assert ray_tpu.get(root, timeout=180) == sum(range(1, 9))


def test_chain_on_large_objects(cluster):
    """Chains through store-resident (non-inline) values."""
    import numpy as np

    @ray_tpu.remote
    def bump(a):
        return a + 1.0

    r = bump.remote(np.zeros(300_000))
    for _ in range(5):
        r = bump.remote(r)
    out = ray_tpu.get(r, timeout=180)
    assert out.shape == (300_000,) and float(out[0]) == 6.0


def test_failed_producer_propagates_to_dependents(cluster):
    @ray_tpu.remote
    def boom():
        raise ValueError("producer failed")

    @ray_tpu.remote
    def consume(x):
        return x

    ref = consume.remote(consume.remote(boom.remote()))
    with pytest.raises(Exception, match="producer failed"):
        ray_tpu.get(ref, timeout=180)


def test_long_tasks_run_in_parallel(cluster):
    """Batching must not serialize independent long tasks onto one worker."""
    @ray_tpu.remote
    def slow(i):
        time.sleep(1.5)
        return i

    # warm the pool so the measurement sees steady state, not cold spawns
    ray_tpu.get([slow.remote(i) for i in range(4)], timeout=180)
    t0 = time.monotonic()
    out = ray_tpu.get([slow.remote(i) for i in range(4)], timeout=180)
    dt = time.monotonic() - t0
    assert sorted(out) == [0, 1, 2, 3]
    assert dt < 4.5, f"independent tasks serialized: {dt:.1f}s"


def test_infeasible_tasks_fail_even_when_queued_deep(cluster):
    """2+ queued infeasible tasks must all get the scheduling error (the
    respawn loop must not make the last-pusher drain unreachable)."""
    from ray_tpu._private.config import RAY_CONFIG
    from ray_tpu.exceptions import TaskError

    @ray_tpu.remote(resources={"NoSuchThing": 1.0})
    def impossible(i):
        return i

    old = RAY_CONFIG.infeasible_task_timeout_s
    RAY_CONFIG.infeasible_task_timeout_s = 3.0
    try:
        refs = [impossible.remote(i) for i in range(3)]
        for ref in refs:
            with pytest.raises(TaskError, match="scheduling failed"):
                ray_tpu.get(ref, timeout=120)
    finally:
        RAY_CONFIG.infeasible_task_timeout_s = old

"""Per-node agent stats + worker profiling (reference:
python/ray/dashboard/agent.py, modules/reporter/ — py-spy stack sampling and
memray allocation tracking, rebuilt as cooperative in-process profilers)."""

import time

import pytest

import ray_tpu
from ray_tpu.dashboard.agent import MemoryProfiler, sample_stacks
from ray_tpu.util.state import get_node_stats, list_nodes, profile_worker


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(resources={"CPU": 4.0})
    yield
    ray_tpu.shutdown()


def test_sample_stacks_catches_hot_function():
    def busy_loop(deadline):
        x = 0
        while time.monotonic() < deadline:
            x += 1
        return x

    import threading

    t = threading.Thread(target=busy_loop,
                         args=(time.monotonic() + 1.5,), daemon=True)
    t.start()
    out = sample_stacks(duration_s=0.8, interval_ms=5.0)
    t.join()
    assert out["samples"] > 10
    assert any("busy_loop" in stack for stack in out["folded"])


def test_memory_profiler_tracks_allocations():
    prof = MemoryProfiler()
    prof.start(frames=8)
    hog = [bytearray(1024) for _ in range(2000)]
    snap = prof.snapshot(top=10)
    prof.stop()
    assert snap["status"] == "ok"
    assert snap["current_kb"] > 1500
    assert snap["top"], "expected at least one allocation site"
    del hog


def test_node_agent_stats(cluster):
    @ray_tpu.remote(num_cpus=0.1)
    def warm():
        return 1

    assert ray_tpu.get(warm.remote(), timeout=120) == 1
    node = next(n for n in list_nodes() if n["alive"])
    stats = get_node_stats(node["address"], agent=True)
    agent = stats["agent"]
    assert agent["mem_total_mb"] > 0
    assert agent["cpu_percent"] >= 0.0
    assert isinstance(agent["workers"], list) and agent["workers"]
    w = agent["workers"][0]
    assert w["rss_mb"] > 0 and w["num_threads"] >= 1


def test_profile_running_worker(cluster):
    @ray_tpu.remote(num_cpus=0.1)
    class Spinner:
        def spin(self, seconds):
            deadline = time.monotonic() + seconds
            n = 0
            while time.monotonic() < deadline:
                n += 1
            return n

        def pid(self):
            import os

            return os.getpid()

    a = Spinner.remote()
    pid = ray_tpu.get(a.pid.remote(), timeout=120)
    node = next(n for n in list_nodes() if n["alive"])
    ref = a.spin.remote(4.0)  # keep the worker busy while we sample
    out = profile_worker(node["address"], pid, kind="stacks",
                         duration_s=1.0, interval_ms=5.0)
    assert out["status"] == "ok", out
    prof = out["profile"]
    assert prof["samples"] > 10
    assert any("spin" in stack for stack in prof["folded"]), \
        list(prof["folded"])[:5]
    ray_tpu.get(ref, timeout=120)

    mem = profile_worker(node["address"], pid, kind="memory",
                         action="start")
    assert mem["profile"]["status"] == "started"
    mem = profile_worker(node["address"], pid, kind="memory",
                         action="snapshot")
    assert mem["profile"]["status"] == "ok"
    profile_worker(node["address"], pid, kind="memory", action="stop")

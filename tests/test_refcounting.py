"""Ownership-based distributed refcounting + lineage reconstruction.

Reference tier: python/ray/tests/test_reference_counting*.py and
test_reconstruction*.py — owner frees objects cluster-wide when local refs,
in-flight submissions, and borrowers all reach zero
(src/ray/core_worker/reference_counter.h:44); lost task outputs are rebuilt
by re-executing the producing task from retained lineage
(object_recovery_manager.h:41, task_manager.h:183).
"""

import gc
import pickle
import time

import numpy as np
import pytest

from ray_tpu._private import wire
import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


def _store_objects():
    w = ray_tpu._private.worker.global_worker()
    return wire.loads(w._run(w.raylet.call("StoreStats", b"")))["num_objects"]


def _wait_store_below(n, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if _store_objects() <= n:
            return True
        time.sleep(0.25)
    return False


def test_put_freed_on_ref_drop(cluster):
    before = _store_objects()
    ref = ray_tpu.put(np.arange(300_000))
    assert ray_tpu.get(ref, timeout=60)[5] == 5
    assert _store_objects() == before + 1
    del ref
    gc.collect()
    assert _wait_store_below(before), "dropped put ref was not freed"


def test_task_return_freed_on_ref_drop(cluster):
    @ray_tpu.remote
    def big():
        return np.ones(400_000)

    before = _store_objects()
    ref = big.remote()
    assert ray_tpu.get(ref, timeout=60).shape == (400_000,)
    del ref
    gc.collect()
    assert _wait_store_below(before), "dropped task-return ref was not freed"


def test_ref_alive_while_held(cluster):
    ref = ray_tpu.put(np.full(300_000, 3.0))
    time.sleep(2.5)  # longer than the free grace period
    assert ray_tpu.get(ref, timeout=60)[0] == 3.0
    del ref
    gc.collect()


def test_borrower_keeps_object_alive(cluster):
    @ray_tpu.remote
    class Holder:
        def __init__(self):
            self.box = None

        def stash(self, box):
            self.box = box  # keeps the contained ref: becomes a borrower
            return "ok"

        def read(self):
            return float(ray_tpu.get(self.box[0])[0])

        def drop(self):
            self.box = None
            return "dropped"

    h = Holder.remote()
    ref = ray_tpu.put(np.full(300_000, 7.0))
    assert ray_tpu.get(h.stash.remote([ref]), timeout=60) == "ok"
    del ref
    gc.collect()
    time.sleep(3.0)  # owner zero + grace passed; borrow must protect it
    assert ray_tpu.get(h.read.remote(), timeout=60) == 7.0


def test_borrow_release_frees_object(cluster):
    @ray_tpu.remote
    class Holder2:
        def __init__(self):
            self.box = None

        def stash(self, box):
            self.box = box
            return "ok"

        def drop(self):
            self.box = None
            return "dropped"

    h = Holder2.remote()
    before = _store_objects()
    ref = ray_tpu.put(np.full(300_000, 9.0))
    assert ray_tpu.get(h.stash.remote([ref]), timeout=60) == "ok"
    time.sleep(1.0)  # let the borrow register
    del ref
    gc.collect()
    assert ray_tpu.get(h.drop.remote(), timeout=60) == "dropped"
    assert _wait_store_below(before, timeout=20.0), (
        "object not freed after the last borrower released it")


def test_inflight_args_pinned(cluster):
    """A ref dropped right after submission must survive until the task
    consumed it (submission pins)."""

    @ray_tpu.remote
    def slow_read(arr):
        time.sleep(2.0)
        return float(arr[0])

    ref = ray_tpu.put(np.full(300_000, 11.0))
    out = slow_read.remote(ref)
    del ref
    gc.collect()
    assert ray_tpu.get(out, timeout=60) == 11.0


def test_nested_ref_in_stored_value_pinned(cluster):
    """A large stored value containing a ref pins the inner object."""
    inner = ray_tpu.put(np.full(200_000, 13.0))
    outer = ray_tpu.put({"pad": np.zeros(200_000), "inner": inner})
    del inner
    gc.collect()
    time.sleep(2.5)
    got = ray_tpu.get(outer, timeout=60)
    assert ray_tpu.get(got["inner"], timeout=60)[0] == 13.0
    del got, outer
    gc.collect()


def test_lineage_reconstruction_after_node_death():
    """Kill the node holding the only copy of a task output; a downstream
    consumer must still complete via lineage re-execution."""
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    cluster = Cluster(head_node_args={"resources": {"CPU": 2.0}})
    node_b = cluster.add_node(resources={"CPU": 2.0, "zone_b": 2.0})
    ray_tpu.init(address=cluster.address)
    try:
        cluster.wait_for_nodes(2)

        @ray_tpu.remote(resources={"zone_b": 0.1}, num_cpus=0.1, max_retries=3)
        def produce(seed):
            return np.full(300_000, float(seed))

        @ray_tpu.remote(num_cpus=0.1, max_retries=3)
        def consume(arr):
            return float(arr[0]) + float(arr[-1])

        ref = produce.remote(21)
        assert ray_tpu.get(ref, timeout=120)[0] == 21.0
        cluster.remove_node(node_b)
        time.sleep(1.0)
        cluster.add_node(resources={"CPU": 2.0, "zone_b": 2.0})
        cluster.wait_for_nodes(2)  # head + replacement (the killed node may already be marked dead)
        assert ray_tpu.get(consume.remote(ref), timeout=180) == 42.0
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_lineage_reconstruction_recursive():
    """A lost intermediate whose own args were also lost reconstructs the
    whole upstream chain."""
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    cluster = Cluster(head_node_args={"resources": {"CPU": 2.0}})
    node_b = cluster.add_node(resources={"CPU": 2.0, "zone_b": 2.0})
    ray_tpu.init(address=cluster.address)
    try:
        cluster.wait_for_nodes(2)

        @ray_tpu.remote(resources={"zone_b": 0.1}, num_cpus=0.1, max_retries=3)
        def stage1():
            return np.full(300_000, 5.0)

        @ray_tpu.remote(resources={"zone_b": 0.1}, num_cpus=0.1, max_retries=3)
        def stage2(arr):
            return arr * 2.0

        r1 = stage1.remote()
        r2 = stage2.remote(r1)
        assert ray_tpu.get(r2, timeout=120)[0] == 10.0
        cluster.remove_node(node_b)  # both copies gone
        time.sleep(1.0)
        cluster.add_node(resources={"CPU": 2.0, "zone_b": 2.0})
        cluster.wait_for_nodes(2)  # head + replacement (the killed node may already be marked dead)

        @ray_tpu.remote(num_cpus=0.1)
        def consume(arr):
            return float(arr[17])

        assert ray_tpu.get(consume.remote(r2), timeout=180) == 10.0
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()

"""Runtime env + job submission + log monitor tests (reference tier:
python/ray/tests/test_runtime_env*.py, dashboard/modules/job/tests)."""

import os
import sys
import time

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


def test_runtime_env_env_vars(cluster):
    @ray_tpu.remote(runtime_env={"env_vars": {"MY_RENV_FLAG": "hello42"}})
    def read_env():
        import os

        return os.environ.get("MY_RENV_FLAG")

    assert ray_tpu.get(read_env.remote(), timeout=120) == "hello42"

    @ray_tpu.remote
    def read_plain():
        import os

        return os.environ.get("MY_RENV_FLAG")

    # workers are keyed by env hash: a no-env task must NOT see the var
    assert ray_tpu.get(read_plain.remote(), timeout=120) is None


def test_runtime_env_working_dir(cluster, tmp_path):
    pkg = tmp_path / "mypkg"
    pkg.mkdir()
    (pkg / "my_renv_module.py").write_text("VALUE = 'from-working-dir'\n")
    (pkg / "data.txt").write_text("payload\n")

    @ray_tpu.remote(runtime_env={"working_dir": str(pkg)})
    def use_pkg():
        import os

        import my_renv_module

        return my_renv_module.VALUE, os.path.exists("data.txt")

    value, has_file = ray_tpu.get(use_pkg.remote(), timeout=120)
    assert value == "from-working-dir"
    assert has_file  # cwd is the extracted working_dir


def test_runtime_env_actor(cluster):
    @ray_tpu.remote(runtime_env={"env_vars": {"ACTOR_RENV": "yes"}})
    class EnvActor:
        def read(self):
            import os

            return os.environ.get("ACTOR_RENV")

    a = EnvActor.remote()
    assert ray_tpu.get(a.read.remote(), timeout=120) == "yes"
    ray_tpu.kill(a)


def test_runtime_env_unsupported_field(cluster):
    with pytest.raises(ValueError, match="not supported"):
        @ray_tpu.remote(runtime_env={"conda": {"dependencies": ["requests"]}})
        def f():
            return 1

        f.remote()


def test_job_submission_end_to_end(cluster, tmp_path):
    from ray_tpu.job import JobStatus, JobSubmissionClient

    script = tmp_path / "workdir" / "job_script.py"
    script.parent.mkdir()
    script.write_text(
        "import os, sys\n"
        "print('job says hi', os.environ.get('JOBVAR'))\n"
        "import ray_tpu\n"
        "ray_tpu.init(log_to_driver=False)\n"
        "@ray_tpu.remote\n"
        "def sq(x):\n"
        "    return x * x\n"
        "print('answer', ray_tpu.get(sq.remote(7), timeout=120))\n"
    )
    client = JobSubmissionClient()
    sid = client.submit_job(
        entrypoint=f"{sys.executable} job_script.py",
        runtime_env={"working_dir": str(script.parent),
                     "env_vars": {"JOBVAR": "jv1",
                                  "JAX_PLATFORMS": "cpu"}})
    status = client.wait_until_finished(sid, timeout=240)
    logs = client.get_job_logs(sid)
    assert status == JobStatus.SUCCEEDED, logs
    assert "job says hi jv1" in logs
    assert "answer 49" in logs
    assert any(j["submission_id"] == sid for j in client.list_jobs())


def test_job_failure_and_stop(cluster):
    from ray_tpu.job import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    sid = client.submit_job(entrypoint=f"{sys.executable} -c 'raise SystemExit(3)'")
    assert client.wait_until_finished(sid, timeout=120) == JobStatus.FAILED
    assert client.get_job_info(sid)["exit_code"] == 3

    sid2 = client.submit_job(
        entrypoint=f"{sys.executable} -c 'import time; time.sleep(600)'")
    deadline = time.time() + 60
    while client.get_job_status(sid2) == JobStatus.PENDING:
        assert time.time() < deadline
        time.sleep(0.2)
    assert client.stop_job(sid2)
    assert client.wait_until_finished(sid2, timeout=60) == JobStatus.STOPPED
    # terminal jobs can be deleted
    assert client.delete_job(sid)
    with pytest.raises(ValueError):
        client.get_job_status(sid)

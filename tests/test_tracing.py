"""Tracing/profile-event tests (reference tier: task events -> GCS ->
timeline; util/tracing)."""

import json
import os

import pytest

import ray_tpu
from ray_tpu.util import tracing


@pytest.fixture(scope="module")
def traced_cluster():
    ray_tpu.shutdown()
    os.environ["RAY_TPU_ENABLE_TRACING"] = "1"
    tracing._enabled = None  # re-read the flag
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()
    os.environ.pop("RAY_TPU_ENABLE_TRACING", None)
    tracing._enabled = None


def test_task_and_actor_spans_collected(traced_cluster):
    @ray_tpu.remote
    def traced_fn(x):
        with tracing.profile("inner_work", detail="custom"):
            return x + 1

    @ray_tpu.remote
    class Actor:
        def ping(self):
            return "pong"

    assert ray_tpu.get(traced_fn.remote(1), timeout=60) == 2
    a = Actor.options(num_cpus=0.1).remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"

    import time

    deadline = time.monotonic() + 30
    spans = []
    while time.monotonic() < deadline:
        spans = tracing.get_spans()
        names = {s["name"] for s in spans}
        if "traced_fn" in names and "Actor.ping" in names \
                and "inner_work" in names:
            break
        time.sleep(0.5)
    names = {s["name"] for s in spans}
    assert "traced_fn" in names, names
    assert "Actor.ping" in names, names
    assert "inner_work" in names, names
    cats = {s["name"]: s["cat"] for s in spans}
    assert cats["traced_fn"] == "task"
    assert cats["Actor.ping"] == "actor_task"
    assert cats["inner_work"] == "user"


def test_chrome_trace_export(traced_cluster, tmp_path):
    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get([f.remote() for _ in range(3)], timeout=60)
    out = str(tmp_path / "trace.json")
    import time

    deadline = time.monotonic() + 30
    n = 0
    while time.monotonic() < deadline:
        n = tracing.export_chrome_trace(out)
        if n >= 3:
            break
        time.sleep(0.5)
    assert n >= 3
    data = json.load(open(out))
    ev = data["traceEvents"][0]
    assert ev["ph"] == "X" and "ts" in ev and "dur" in ev


def test_disabled_is_noop():
    tracing._enabled = None
    os.environ.pop("RAY_TPU_ENABLE_TRACING", None)
    t0 = len(tracing._buffer)
    tracing.record_span("ignored", 0.0, 1.0)
    assert len(tracing._buffer) == t0


def test_structured_events(traced_cluster):
    """System events (actor death) land in the GCS event ring and are
    queryable; user code can report its own (reference: util/event.cc +
    export events)."""
    from ray_tpu.util import events

    events.record("mytest", "warning", "hello events", foo=1)

    @ray_tpu.remote(max_restarts=0)
    class Doomed:
        def ping(self):
            return "ok"

    d = Doomed.remote()
    ray_tpu.get(d.ping.remote(), timeout=60)
    ray_tpu.kill(d)
    import time as _t

    deadline = _t.time() + 30
    found_user = found_actor = False
    while _t.time() < deadline and not (found_user and found_actor):
        evs = events.list_events(limit=500)
        found_user = any(e["source"] == "mytest"
                         and e["metadata"].get("foo") == 1 for e in evs)
        found_actor = any(e["source"] == "actor" for e in evs)
        _t.sleep(0.5)
    assert found_user, "user event not recorded"
    assert found_actor, "actor lifecycle event not recorded"

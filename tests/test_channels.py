"""Channel-plane tests (ray_tpu/dag/channels.py): the zero-copy seqlock
slot ring under the PR-20 fast-path contract.

Covers:
(a) array-aware zero-copy framing round trips — dtypes, nested trees,
    0-d / empty / non-contiguous arrays, inline non-array leaves,
    quantized activation streaming (int8 codes + exact non-float leaves),
(b) ring-depth semantics: ``depth`` writes run ahead of the reader, the
    next write blocks on the ack of value ``n - depth``, and both sides'
    TimeoutErrors carry the version/ack state of the wedged slot,
(c) torn-read safety: length and seq are validated UNDER the version
    snapshot — a crashed writer (killed mid-slot) never presents a torn
    even version, and a reader that outlives the writer times out with
    diagnostics instead of decoding garbage,
(d) crash-restart attach: both endpoints derive their resume sequences
    from the shm state,
(e) gang re-form hygiene: ``channel_shm_paths`` covers every ring any
    rank opens (V=1 chain and V>1 full ring), so the controller's unlink
    sweep leaves no generation behind,
(f) cross-host leg: the writer's bounded retry + the mailbox's sequence
    dedup never double-deliver a value.
"""

import os
import signal
import subprocess
import sys
import time
import uuid

import numpy as np
import pytest

import ray_tpu
from ray_tpu.dag.channels import MAX_READERS, Channel, ChannelClosed  # noqa: F401


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


def _ring(name=None, **kw):
    name = name or f"tch_{uuid.uuid4().hex[:8]}"
    kw.setdefault("capacity", 1 << 16)
    writer = Channel(name, create=True, **kw)
    reader = Channel(name, reader_slot=0)
    return name, writer, reader


# ---------------------------------------------------------------------------
# (a) zero-copy framing round trips
# ---------------------------------------------------------------------------


def _roundtrip(writer, reader, value):
    writer.write(value, timeout=10)
    return reader.read(timeout=10)


def test_zero_copy_roundtrip_dtypes_and_trees():
    import collections

    _, w, r = _ring(depth=2)
    Point = collections.namedtuple("Point", "x y")
    base = np.arange(24, dtype=np.float32).reshape(4, 6)
    cases = [
        base,
        np.float64(3.25) * np.ones((), np.float64),     # 0-d
        np.zeros((0, 5), np.int32),                      # empty
        np.arange(10, dtype=np.int64)[::2],              # non-contiguous
        base.T,                                          # transposed view
        np.array([True, False, True]),
        {"a": base, "b": [np.uint8(7) * np.ones(3, np.uint8), "text"],
         "c": (1, 2.5, None), "p": Point(np.ones(2, np.float32), "tag")},
        {"scalars": 42, "s": "inline-only", "t": (1, [2, 3])},
    ]
    try:
        for value in cases:
            got = _roundtrip(w, r, value)
            flat_w, flat_g = _flatten(value), _flatten(got)
            assert len(flat_w) == len(flat_g)
            for a, b in zip(flat_w, flat_g):
                if isinstance(a, np.ndarray) or hasattr(a, "__array__"):
                    a = np.asarray(a)
                    assert a.dtype == np.asarray(b).dtype
                    assert a.shape == np.asarray(b).shape
                    np.testing.assert_array_equal(a, np.asarray(b))
                else:
                    assert a == b or (a is None and b is None)
        # namedtuple type survives the skeleton
        got = _roundtrip(w, r, Point(np.ones(2, np.float32), 5))
        assert type(got).__name__ == "Point" and got.y == 5
    finally:
        w.close(unlink=True)
        r.close()


def _flatten(x):
    if isinstance(x, dict):
        return [v for k in sorted(x) for v in _flatten(x[k])]
    if isinstance(x, (list, tuple)):
        return [v for item in x for v in _flatten(item)]
    return [x]


def test_zero_copy_roundtrip_jax_and_bf16():
    import jax.numpy as jnp

    _, w, r = _ring(depth=1)
    try:
        tree = {"x": jnp.arange(16, dtype=jnp.float32).reshape(4, 4),
                "y": jnp.ones((3,), jnp.bfloat16),
                "mb": 3}
        got = _roundtrip(w, r, tree)
        np.testing.assert_array_equal(np.asarray(got["x"]),
                                      np.asarray(tree["x"]))
        assert np.asarray(got["y"]).dtype == np.asarray(tree["y"]).dtype
        np.testing.assert_array_equal(
            np.asarray(got["y"]).view(np.uint16),
            np.asarray(tree["y"]).view(np.uint16))
        assert got["mb"] == 3
        # the hot path reports real stats: one frame, no pickle of arrays
        assert w.last_write_stats["wire_bytes"] == \
            r.last_read_stats["wire_bytes"] > 0
    finally:
        w.close(unlink=True)
        r.close()


def test_quantized_activation_streaming_int8():
    _, w, r = _ring(depth=1, capacity=1 << 16)
    try:
        w.set_codec("int8")
        f = np.linspace(-4.0, 4.0, 512).astype(np.float32).reshape(8, 64)
        tree = {"act": f, "mask": np.ones(8, np.int32), "mb": 1}
        w.write(tree, timeout=10)
        wire_q = w.last_write_stats["wire_bytes"]
        got = r.read(timeout=10)
        # float leaf: approximate (block-scaled int8), int leaf: exact
        assert np.abs(np.asarray(got["act"]) - f).max() < 0.05
        np.testing.assert_array_equal(got["mask"], tree["mask"])
        assert got["mb"] == 1
        # quantization actually shrank the wire footprint
        w.set_codec(None)
        w.write(tree, timeout=10)
        wire_exact = w.last_write_stats["wire_bytes"]
        got2 = r.read(timeout=10)
        np.testing.assert_array_equal(np.asarray(got2["act"]), f)
        assert wire_q < wire_exact
    finally:
        w.close(unlink=True)
        r.close()


# ---------------------------------------------------------------------------
# (b) ring depth + backpressure diagnostics
# ---------------------------------------------------------------------------


def test_ring_depth_overlap_and_backpressure():
    _, w, r = _ring(depth=2)
    try:
        # depth=2: two writes complete with no reader ack at all
        w.write({"v": 0}, timeout=5)
        w.write({"v": 1}, timeout=5)
        # the third blocks on the ack of value 0 (slot reuse)
        with pytest.raises(TimeoutError) as ei:
            w.write({"v": 2}, timeout=0.3)
        msg = str(ei.value)
        assert "acks=" in msg and "slot 0" in msg and "seq 0" in msg
        # draining frees the ring in FIFO order
        assert r.read(timeout=5)["v"] == 0
        w.write({"v": 2}, timeout=5)
        assert r.read(timeout=5)["v"] == 1
        assert r.read(timeout=5)["v"] == 2
    finally:
        w.close(unlink=True)
        r.close()


def test_reader_timeout_reports_slot_state():
    _, w, r = _ring(depth=2)
    try:
        with pytest.raises(TimeoutError) as ei:
            r.read(timeout=0.3)
        msg = str(ei.value)
        assert "version=" in msg and "want=" in msg and "acks=" in msg
    finally:
        w.close(unlink=True)
        r.close()


def test_crash_restart_attach_resumes_sequences():
    name, w, r = _ring(depth=2)
    try:
        for i in range(3):
            w.write({"v": i}, timeout=5)
            if i < 2:
                assert r.read(timeout=5)["v"] == i
        # both endpoints die (no unlink) and fresh processes re-attach
        w.close()
        r.close()
        w2 = Channel(name)               # writer attach: resumes at seq 3
        r2 = Channel(name, reader_slot=0)  # reader attach: resumes at seq 2
        assert w2._wseq == 3 and r2._rseq == 2
        assert r2.read(timeout=5)["v"] == 2
        w2.write({"v": 3}, timeout=5)
        assert r2.read(timeout=5)["v"] == 3
    finally:
        Channel(name).close(unlink=True)


# ---------------------------------------------------------------------------
# (c) torn-read safety under writer crash
# ---------------------------------------------------------------------------

_CRASH_WRITER = r"""
import sys, numpy as np
sys.path.insert(0, {repo!r})
from ray_tpu.dag.channels import Channel

ch = Channel({name!r}, capacity=1 << 22, create=True, depth=2)
n = 0
while True:  # killed by SIGKILL mid-loop; large payload widens the window
    ch.write({{"seq": n, "data": np.full((1 << 18,), n, np.int64)}},
             timeout=60)
    n += 1
"""


def test_writer_killed_mid_slot_never_presents_torn_value(tmp_path):
    name = f"tch_crash_{uuid.uuid4().hex[:8]}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-c", _CRASH_WRITER.format(repo=repo, name=name)],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    path = f"/dev/shm/rtpu_chan_{name}"
    try:
        deadline = time.time() + 30
        reader = None
        while reader is None and time.time() < deadline:
            try:  # the file can exist before the child seals the header
                reader = Channel(name, reader_slot=0)
            except (FileNotFoundError, RuntimeError, ValueError):
                time.sleep(0.05)
        assert reader is not None, "crash writer never created the ring"
        seen = -1
        for _ in range(8):  # healthy stream first: uniform, in order
            v = reader.read(timeout=30)
            data = np.asarray(v["data"])
            assert data.min() == data.max() == v["seq"], "torn value"
            assert v["seq"] == seen + 1
            seen = v["seq"]
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)
        # drain whatever the dead writer sealed; every surviving value
        # must still be internally consistent — a torn (mid-copy) slot
        # must never present an even version to the reader
        try:
            while True:
                v = reader.read(timeout=0.5)
                data = np.asarray(v["data"])
                assert data.min() == data.max() == v["seq"], \
                    "reader decoded a torn slot after writer crash"
        except TimeoutError as e:
            assert "version=" in str(e)  # diagnostics survive the crash
        reader.close()
    finally:
        if proc.poll() is None:
            proc.kill()
        if os.path.exists(path):
            os.unlink(path)


def test_torn_header_is_not_trusted():
    """A sealed version with a garbage length/seq (emulated torn header)
    must never drive the payload copy — the reader keeps spinning."""
    import struct

    name, w, r = _ring(depth=1)
    try:
        w.write({"v": 1}, timeout=5)
        # corrupt the slot: bump seq so the snapshot validation fails
        base = w._slot_base(0)
        struct.pack_into("<Q", w.seg.buf, base + 16, 999)
        with pytest.raises(TimeoutError) as ei:
            r.read(timeout=0.3)
        assert "slot_seq=999" in str(ei.value)
        # restore the real seq: the same read now succeeds
        struct.pack_into("<Q", w.seg.buf, base + 16, 0)
        assert r.read(timeout=5)["v"] == 1
    finally:
        w.close(unlink=True)
        r.close()


# ---------------------------------------------------------------------------
# (e) gang re-form unlinks every ring generation
# ---------------------------------------------------------------------------


def test_channel_shm_paths_cover_all_rings():
    from ray_tpu.train.pipeline.stage import _chan_names, channel_shm_paths

    for S in (2, 3, 4):
        for V in (1, 2, 3):
            paths = set(channel_shm_paths("run", 0, S, V))
            opened = set()
            for s in range(S):
                names = _chan_names("run", 0, s, S, V)
                opened |= {f"/dev/shm/rtpu_chan_{n}"
                           for n in names.values() if n}
            # every endpoint any rank opens is covered by the unlink sweep
            assert opened == paths, (S, V)
            # V=1 chain: S-1 edges per direction; V>1 ring: S per direction
            assert len(paths) == (2 * (S - 1) if V == 1 else 2 * S), (S, V)
    assert channel_shm_paths("run", 0, 1, 1) == []
    # generations never collide (re-formed gang gets fresh rings)
    assert not (set(channel_shm_paths("run", 0, 2, 2)) &
                set(channel_shm_paths("run", 1, 2, 2)))


def test_gang_reform_unlink_sweeps_generations():
    from ray_tpu.train.pipeline.stage import channel_shm_paths

    run = f"tgang_{uuid.uuid4().hex[:6]}"
    created = []
    for gen in (0, 1):
        for p in channel_shm_paths(run, gen, 2, 2):
            name = os.path.basename(p)[len("rtpu_chan_"):]
            Channel(name, capacity=1 << 12, create=True, depth=2).close()
            created.append(p)
    assert all(os.path.exists(p) for p in created)
    # the controller's kill path: unlink every generation's paths
    for gen in (0, 1):
        for p in channel_shm_paths(run, gen, 2, 2):
            if os.path.exists(p):
                os.unlink(p)
    assert not any(os.path.exists(p) for p in created)


# ---------------------------------------------------------------------------
# (f) cross-host writer: bounded retry + sequence dedup
# ---------------------------------------------------------------------------


def test_cross_host_retry_and_dedup(cluster):
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.dag.channels import CrossHostReader, CrossHostWriter

    w = worker_mod.global_worker()
    mbox = f"xch_{uuid.uuid4().hex[:8]}@0"
    writer = CrossHostWriter("xch_test", [(mbox, w.address)])
    reader = CrossHostReader(mbox)
    try:
        writer.write({"v": 0})
        assert reader.read(timeout=10)["v"] == 0

        # transient RPC failure: the first attempt dies, the retry lands —
        # exactly one delivery
        real_client = w._worker_client
        fails = {"n": 1}

        class _Flaky:
            def __init__(self, inner):
                self._inner = inner

            async def call(self, method, payload, **kw):
                if method == "ChanPush" and fails["n"] > 0:
                    fails["n"] -= 1
                    raise ConnectionResetError("injected transient failure")
                return await self._inner.call(method, payload, **kw)

        w._worker_client = lambda addr: _Flaky(real_client(addr))
        try:
            writer.write({"v": 1})
        finally:
            w._worker_client = real_client
        assert fails["n"] == 0, "injected failure never fired"
        assert reader.read(timeout=10)["v"] == 1

        # ambiguous failure: the push LANDED but the ack was lost; the
        # writer's re-push of the same sequence must dedup at the mailbox
        seq_before = writer._seq
        writer.write({"v": 2})
        writer._seq = seq_before  # emulate the lost-ack retry
        writer.write({"v": 2})
        assert reader.read(timeout=10)["v"] == 2
        with pytest.raises(TimeoutError):
            reader.read(timeout=0.5)  # no double delivery
        # a NEW sequence after the dup flows normally
        writer._seq = seq_before + 1
        writer.write({"v": 3})
        assert reader.read(timeout=10)["v"] == 3
    finally:
        reader.close(unlink=True)

"""Tier-1 gate: raylint must pass over ray_tpu/ with the checked-in baseline.

This is the enforcement point for the runtime's source-level invariants
(tools/raylint/README.md): introducing a blocking call in an async body, an
await under a threading lock, a stray unpickle, a silently swallowed
control-plane exception, or an unregistered wire struct fails tier-1 — no
extra CI infrastructure needed.
"""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[1]
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

import pytest  # noqa: E402

from tools.raylint import core  # noqa: E402

BASELINE = REPO_ROOT / "tools" / "raylint" / "baseline.json"


@pytest.fixture(scope="module")
def repo_report():
    baseline = core.load_baseline(BASELINE)
    return core.check_paths([REPO_ROOT / "ray_tpu"], REPO_ROOT,
                            baseline=baseline)


def test_repo_is_clean_with_baseline(repo_report):
    report = repo_report
    # a vacuously-green scan (0 files) must fail, not pass
    assert report.files_checked > 50, report.files_checked
    msg = "\n".join(f.render() for f in report.findings)
    assert report.ok, (
        f"new raylint finding(s) — fix them, add a "
        f"`# raylint: disable=<RULE> <reason>` with justification, or (for "
        f"reviewed-benign cases) regenerate the baseline:\n{msg}")


def test_baseline_has_no_stale_entries(repo_report):
    """Every baseline entry must still match a real finding: when a fix
    removes one, the baseline shrinks with it (keeps the file honest)."""
    report = repo_report
    stale = "\n".join(f"{r} {p}: {s!r}" for r, p, s in report.unused_baseline)
    assert not report.unused_baseline, (
        f"stale baseline entries — rerun "
        f"`python -m tools.raylint --write-baseline`:\n{stale}")


def test_baseline_is_sorted_and_deterministic():
    doc = json.loads(BASELINE.read_text())
    keys = [(e["rule"], e["path"], e["snippet"]) for e in doc["findings"]]
    assert keys == sorted(keys), "baseline entries must be sorted"
    assert len(keys) == len(set(keys)), (
        "duplicate baseline keys (use the count field instead)")
    assert all(e.get("count", 1) >= 1 for e in doc["findings"])


def test_at_least_five_rules_active():
    rules = core.all_rules()
    assert len(rules) >= 5, f"expected >= 5 rules, have {sorted(rules)}"
    for required in ("ASY001", "ASY002", "SER001", "EXC001", "WIRE001"):
        assert required in rules


def test_gate_catches_new_violations():
    """A deliberately-bad control-plane snippet must trip every async/ser/exc
    rule — proving the tier-1 gate actually fires on regressions."""
    bad = textwrap.dedent("""
        import asyncio
        import pickle
        import threading
        import time

        async def handler(self, req):
            time.sleep(1)                     # ASY001
            with self._lock:                  # ASY002
                await asyncio.sleep(0)
            state = pickle.loads(req)         # SER001
            try:
                return state
            except Exception:                 # EXC001
                pass
    """)
    project = core.Project(REPO_ROOT)
    findings = project.check_source(bad, "ray_tpu/_private/fake_control.py")
    hit = {f.rule for f in findings}
    assert {"ASY001", "ASY002", "SER001", "EXC001"} <= hit, (
        f"gate failed to flag a deliberately-bad snippet; got {sorted(hit)}: "
        + "\n".join(f.render() for f in findings))


def test_cli_end_to_end(tmp_path):
    """`python -m tools.raylint` exits 0 on the repo and 1 on a bad tree."""
    clean = subprocess.run(
        [sys.executable, "-m", "tools.raylint", "ray_tpu"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
    assert clean.returncode == 0, clean.stdout + clean.stderr

    bad_dir = tmp_path / "_private"
    bad_dir.mkdir()
    (bad_dir / "bad.py").write_text(
        "import time\nasync def f():\n    time.sleep(1)\n")
    dirty = subprocess.run(
        [sys.executable, "-m", "tools.raylint", str(bad_dir), "--json"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
    assert dirty.returncode == 1, dirty.stdout + dirty.stderr
    payload = json.loads(dirty.stdout)
    assert payload["findings"] and payload["findings"][0]["rule"] == "ASY001"


def test_v2_rules_registered():
    rules = core.all_rules()
    for required in ("ASY004", "LCK002", "AWT002", "WIRE002", "SUP001"):
        assert required in rules, f"v2 rule {required} missing"


def test_v3_context_rules_registered():
    rules = core.all_rules()
    for required in ("RCE001", "RCE002", "FRK001", "DON001"):
        assert required in rules, f"v3 rule {required} missing"


def test_full_tree_wall_time_under_budget_with_warm_graph_cache(repo_report):
    """The whole-program layer must not make tier-1 slow: a full-tree run
    with a warm graph cache stays under 30 s. The module-scoped repo_report
    fixture above already warmed the cache (and the first run itself has
    the same budget in CI practice)."""
    import time as _time

    baseline = core.load_baseline(BASELINE)
    started = _time.perf_counter()
    report = core.check_paths([REPO_ROOT / "ray_tpu"], REPO_ROOT,
                              baseline=baseline)
    elapsed = _time.perf_counter() - started
    assert report.files_checked > 50
    assert elapsed < 30.0, (
        f"full-tree raylint took {elapsed:.1f}s with a warm graph cache; "
        f"the tier-1 budget is 30s — check tools/raylint/.graphcache.json "
        f"is being used (and that no rule lost its memoization)")


def test_lint_sh_json_contract(tmp_path):
    """tools/lint.sh --json: exit 0 + parseable JSON on a clean tree, and
    nonzero exit + findings in the JSON on a dirty one (the contract the
    tier-1 gate and CI wrappers rely on)."""
    lint_sh = REPO_ROOT / "tools" / "lint.sh"
    clean = subprocess.run(["bash", str(lint_sh), "--json"], cwd=REPO_ROOT,
                           capture_output=True, text=True, timeout=120)
    assert clean.returncode == 0, clean.stdout + clean.stderr
    payload = json.loads(clean.stdout)
    assert payload["ok"] is True and payload["files_checked"] > 50

    bad_dir = tmp_path / "_private"
    bad_dir.mkdir()
    (bad_dir / "bad.py").write_text(
        "import time\nasync def f():\n    time.sleep(1)\n")
    dirty = subprocess.run(["bash", str(lint_sh), str(bad_dir), "--json"],
                           cwd=REPO_ROOT, capture_output=True, text=True,
                           timeout=120)
    assert dirty.returncode == 1, dirty.stdout + dirty.stderr
    payload = json.loads(dirty.stdout)
    assert payload["ok"] is False and payload["findings"]


def test_changed_flag_scopes_to_git_diff(tmp_path):
    """--changed lints only files changed vs HEAD (here: none in scope)."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.raylint", "--changed", "--rules",
         "ASY001"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
    # whatever the working tree currently holds, the run must terminate
    # cleanly and must not report out-of-scope stale baseline entries
    assert proc.returncode in (0, 1), proc.stdout + proc.stderr
    if "no changed files in scope" in proc.stderr:
        assert proc.returncode == 0


def test_stats_flag_reports_per_rule_timings():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.raylint", "--stats",
         str(REPO_ROOT / "ray_tpu" / "_private" / "wire.py")],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "per-rule wall time" in proc.stderr
    assert "ASY004" in proc.stderr and "graph" in proc.stderr


# ---------------------------------------------------------------------------
# regression tests for the findings the v2 rules surfaced and we fixed
# ---------------------------------------------------------------------------


def test_raylet_main_has_no_transitive_blocking_chain():
    """PR 9 fix: raylet construction (which may compile the native store —
    a g++ subprocess) was reachable from the async main body; it now runs
    in sync context before the loop exists. ASY004 must stay clean on
    raylet.py so the chain cannot quietly come back."""
    report = core.check_paths(
        [REPO_ROOT / "ray_tpu" / "_private" / "raylet.py"], REPO_ROOT,
        rule_names=["ASY004"])
    msgs = "\n".join(f.render() for f in report.findings)
    assert report.findings == [], msgs
    # and the construction really is outside the async def
    src = (REPO_ROOT / "ray_tpu" / "_private" / "raylet.py").read_text()
    run_body = src.split("async def run():", 1)[1]
    assert "Raylet(" not in run_body.split("asyncio.run(run())")[0]


def test_dead_rpc_handlers_stay_deleted():
    """PR 9 fix: _rpc_ListJobs (GCS), the Exit and RemoveBorrower dispatcher
    arms (core worker) had no caller anywhere — deleted. WIRE002 keeps
    gcs.py/core_worker.py free of orphan handlers from here on."""
    report = core.check_paths(
        [REPO_ROOT / "ray_tpu" / "_private" / "gcs.py",
         REPO_ROOT / "ray_tpu" / "_private" / "core_worker.py"], REPO_ROOT,
        rule_names=["WIRE002"])
    msgs = "\n".join(f.render() for f in report.findings)
    assert report.findings == [], msgs
    gcs_src = (REPO_ROOT / "ray_tpu" / "_private" / "gcs.py").read_text()
    cw_src = (REPO_ROOT / "ray_tpu" / "_private" / "core_worker.py").read_text()
    assert "_rpc_ListJobs" not in gcs_src
    assert '"RemoveBorrower"' not in cw_src
    assert '"Exit"' not in cw_src


def test_write_baseline_refuses_changed_scoped_run():
    """--changed --write-baseline would rewrite the whole baseline from the
    changed-file subset, erasing reviewed entries for unchanged files."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.raylint", "--changed",
         "--write-baseline"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 2
    assert "full default run" in proc.stderr


def test_changed_errors_when_git_fails(tmp_path):
    """A git failure must exit 2, not read as 'nothing changed' (a broken
    git in CI would otherwise pass the lint gate green over unlinted
    edits). PATH without git makes every git invocation fail."""
    import os

    env = dict(os.environ, PATH=str(tmp_path))  # empty dir: no git
    proc = subprocess.run(
        [sys.executable, "-m", "tools.raylint", "--changed"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=60, env=env)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "git" in proc.stderr

"""Tier-1 gate: raylint must pass over ray_tpu/ with the checked-in baseline.

This is the enforcement point for the runtime's source-level invariants
(tools/raylint/README.md): introducing a blocking call in an async body, an
await under a threading lock, a stray unpickle, a silently swallowed
control-plane exception, or an unregistered wire struct fails tier-1 — no
extra CI infrastructure needed.
"""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[1]
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

import pytest  # noqa: E402

from tools.raylint import core  # noqa: E402

BASELINE = REPO_ROOT / "tools" / "raylint" / "baseline.json"


@pytest.fixture(scope="module")
def repo_report():
    baseline = core.load_baseline(BASELINE)
    return core.check_paths([REPO_ROOT / "ray_tpu"], REPO_ROOT,
                            baseline=baseline)


def test_repo_is_clean_with_baseline(repo_report):
    report = repo_report
    # a vacuously-green scan (0 files) must fail, not pass
    assert report.files_checked > 50, report.files_checked
    msg = "\n".join(f.render() for f in report.findings)
    assert report.ok, (
        f"new raylint finding(s) — fix them, add a "
        f"`# raylint: disable=<RULE> <reason>` with justification, or (for "
        f"reviewed-benign cases) regenerate the baseline:\n{msg}")


def test_baseline_has_no_stale_entries(repo_report):
    """Every baseline entry must still match a real finding: when a fix
    removes one, the baseline shrinks with it (keeps the file honest)."""
    report = repo_report
    stale = "\n".join(f"{r} {p}: {s!r}" for r, p, s in report.unused_baseline)
    assert not report.unused_baseline, (
        f"stale baseline entries — rerun "
        f"`python -m tools.raylint --write-baseline`:\n{stale}")


def test_baseline_is_sorted_and_deterministic():
    doc = json.loads(BASELINE.read_text())
    keys = [(e["rule"], e["path"], e["snippet"]) for e in doc["findings"]]
    assert keys == sorted(keys), "baseline entries must be sorted"
    assert len(keys) == len(set(keys)), (
        "duplicate baseline keys (use the count field instead)")
    assert all(e.get("count", 1) >= 1 for e in doc["findings"])


def test_at_least_five_rules_active():
    rules = core.all_rules()
    assert len(rules) >= 5, f"expected >= 5 rules, have {sorted(rules)}"
    for required in ("ASY001", "ASY002", "SER001", "EXC001", "WIRE001"):
        assert required in rules


def test_gate_catches_new_violations():
    """A deliberately-bad control-plane snippet must trip every async/ser/exc
    rule — proving the tier-1 gate actually fires on regressions."""
    bad = textwrap.dedent("""
        import asyncio
        import pickle
        import threading
        import time

        async def handler(self, req):
            time.sleep(1)                     # ASY001
            with self._lock:                  # ASY002
                await asyncio.sleep(0)
            state = pickle.loads(req)         # SER001
            try:
                return state
            except Exception:                 # EXC001
                pass
    """)
    project = core.Project(REPO_ROOT)
    findings = project.check_source(bad, "ray_tpu/_private/fake_control.py")
    hit = {f.rule for f in findings}
    assert {"ASY001", "ASY002", "SER001", "EXC001"} <= hit, (
        f"gate failed to flag a deliberately-bad snippet; got {sorted(hit)}: "
        + "\n".join(f.render() for f in findings))


def test_cli_end_to_end(tmp_path):
    """`python -m tools.raylint` exits 0 on the repo and 1 on a bad tree."""
    clean = subprocess.run(
        [sys.executable, "-m", "tools.raylint", "ray_tpu"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
    assert clean.returncode == 0, clean.stdout + clean.stderr

    bad_dir = tmp_path / "_private"
    bad_dir.mkdir()
    (bad_dir / "bad.py").write_text(
        "import time\nasync def f():\n    time.sleep(1)\n")
    dirty = subprocess.run(
        [sys.executable, "-m", "tools.raylint", str(bad_dir), "--json"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
    assert dirty.returncode == 1, dirty.stdout + dirty.stderr
    payload = json.loads(dirty.stdout)
    assert payload["findings"] and payload["findings"][0]["rule"] == "ASY001"

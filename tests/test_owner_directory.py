"""Owner-resident object directory (reference:
src/ray/object_manager/ownership_object_directory.cc — location reads are
served by the object's owner; the GCS keeps the durable write-through copy
as fallback)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import wire
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy


@pytest.fixture
def two_nodes():
    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True,
                      head_node_args={"resources": {"CPU": 2.0}})
    cluster.add_node(resources={"CPU": 2.0})
    ray_tpu.init(address=cluster.address)
    from ray_tpu.util.state import list_nodes

    deadline = time.time() + 60
    while time.time() < deadline:
        nodes = [n for n in list_nodes() if n["alive"]]
        if len(nodes) >= 2:
            break
        time.sleep(0.2)
    yield cluster, nodes
    ray_tpu.shutdown()
    cluster.shutdown()


@ray_tpu.remote(num_cpus=0.5)
def produce_big():
    return np.ones(1024 * 1024, dtype=np.uint8)


def test_owner_table_filled_and_queryable(two_nodes):
    cluster, nodes = two_nodes
    other_id = next(n["node_id"] for n in nodes if not n["is_head"])
    ref = produce_big.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=other_id)).remote()
    assert ray_tpu.get(ref, timeout=120).sum() == 1024 * 1024

    from ray_tpu._private import worker as worker_mod

    core = worker_mod.global_worker()
    # the raylet's seal announcement reaches the owner (this driver)
    deadline = time.time() + 30
    entry = None
    while time.time() < deadline:
        entry = core._obj_locations.get(ref.id.binary())
        if entry and entry["nodes"]:
            break
        time.sleep(0.2)
    assert entry and entry["nodes"], "owner never received the announcement"
    assert other_id in entry["nodes"]
    assert entry["size"] >= 1024 * 1024

    # the owner answers location queries over its worker RPC (what a
    # pulling raylet uses before falling back to the GCS)
    async def _query():
        reply = await core._worker_client(core.address).call(
            "ObjectLocQuery", wire.dumps({"oid": ref.id.binary()}),
            timeout=10.0)
        return wire.loads(reply)

    out = core._run(_query())
    assert any(loc["node_id"] == other_id for loc in out["locations"])

    # consuming on the head still pulls fine (owner-first read path)
    @ray_tpu.remote(num_cpus=0.5)
    def consume(a):
        return int(a.sum())

    head_id = next(n["node_id"] for n in nodes if n["is_head"])
    assert ray_tpu.get(consume.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=head_id)).remote(ref), timeout=120) == 1024 * 1024

    # freeing the ref clears the owner-resident entry
    del ref
    import gc

    gc.collect()
    deadline = time.time() + 30
    while time.time() < deadline:
        if not core._obj_locations:
            break
        time.sleep(0.2)
    assert not core._obj_locations, core._obj_locations


def test_owner_gone_falls_back_to_gcs(two_nodes):
    """A pull whose owner hint is unreachable must still resolve through
    the GCS directory copy."""
    cluster, nodes = two_nodes
    other_id = next(n["node_id"] for n in nodes if not n["is_head"])
    ref = produce_big.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=other_id)).remote()
    ray_tpu.get(ref, timeout=120)

    from ray_tpu._private import worker as worker_mod

    core = worker_mod.global_worker()

    # ask the HEAD raylet to pull with a bogus owner hint: the owner-first
    # leg fails fast and the GCS fallback serves the locations
    async def _pull_with_bad_owner():
        return wire.loads(await core.raylet.call("StoreGet", wire.dumps({
            "oid": ref.id.binary(), "timeout": 60.0, "pull": True,
            "owner": "127.0.0.1:1"}), timeout=70.0))

    reply = core._run(_pull_with_bad_owner())
    assert reply["status"] in ("shm", "shm_arena", "inline"), reply

"""Quantized + delta comms tier (collective/quant.py + the compression
knobs on the bucketed collectives, the traced train step, and PPO grad
sync).

Contracts pinned here:

- codec roundtrips hold across block boundaries, ragged tails, and
  non-finite inputs (scales stay finite — a NaN scale would poison the
  whole block);
- error feedback keeps quantized accumulation unbiased (the EQuARX
  mechanism that makes int8 training converge);
- the quantized allreduce moves >= 3.5x fewer wire bytes than fp32 at
  equal tree size, and every rank still ends bitwise-identical to its
  peers;
- compression is STRICTLY opt-in: compression=None paths reproduce the
  PR 12 fp32 behavior exactly (bitwise), including the sharded-step
  bit-exact contract (grad_dtype="fp32" default builds the identical
  programs — asserted against the fused step).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.collective import quant
from ray_tpu.collective.quant import ErrorFeedback, QuantCodec


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield ray_tpu
    ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# codec property tests
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name,tol", [("int8", 0.01), ("fp8", 0.06),
                                      ("bf16", 0.01)])
@pytest.mark.parametrize("n", [1, 7, 63, 64, 65, 255, 256, 257, 1000])
def test_codec_roundtrip_block_boundaries(name, tol, n):
    codec = QuantCodec(name, 64)
    rng = np.random.default_rng(n)
    x = (rng.normal(size=n) * 10).astype(np.float32)
    qt = quant.quantize(x, codec)
    y = quant.dequantize(qt)
    assert y.shape == x.shape and y.dtype == x.dtype
    assert np.isfinite(qt.scales).all()
    assert np.abs(y - x).max() <= tol * np.abs(x).max()
    if name != "bf16":
        # block scale is per 64 elements; codes are 1 byte/element with
        # the ragged tail truncated (never shipped)
        assert qt.codes.size == n
        assert qt.scales.size == -(-n // 64)


def test_codec_shapes_and_dtypes_roundtrip():
    codec = QuantCodec("int8", 32)
    rng = np.random.default_rng(0)
    for shape in [(3, 5), (2, 3, 4), ()]:
        for dtype in (np.float32, np.float64):
            x = np.asarray(rng.normal(size=shape) * 5, dtype=dtype)
            y = quant.dequantize(quant.quantize(x, codec))
            assert y.shape == x.shape and y.dtype == x.dtype


def test_codec_nonfinite_inputs_keep_scales_finite():
    codec = QuantCodec("int8", 4)
    x = np.array([1.0, np.nan, np.inf, -np.inf, 2.0, -3.0], np.float32)
    qt = quant.quantize(x, codec)
    y = quant.dequantize(qt)
    assert np.isfinite(qt.scales).all()
    assert np.isfinite(y).all()
    # NaN encodes as 0; inf saturates at the block's finite amax
    assert y[1] == 0.0
    assert abs(y[0] - 1.0) < 0.05 and abs(y[4] - 2.0) < 0.05


def test_codec_zeros_roundtrip_exact():
    for name in ("int8", "fp8"):
        qt = quant.quantize(np.zeros(130, np.float32), QuantCodec(name, 64))
        assert np.isfinite(qt.scales).all()  # zero blocks get scale 1.0
        assert np.array_equal(quant.dequantize(qt), np.zeros(130, np.float32))


def test_encode_decode_single_buffer_form():
    rng = np.random.default_rng(3)
    x = rng.normal(size=(33, 7)).astype(np.float32)
    wire, meta = quant.encode_array(x, QuantCodec("int8", 32))
    assert wire.dtype == np.uint8 and wire.ndim == 1
    assert wire.nbytes < 0.35 * x.nbytes
    y = quant.decode_array(wire, meta)
    assert y.shape == x.shape and np.abs(y - x).max() < 0.1


def test_resolve_codec_specs():
    assert quant.resolve_codec(None) is None
    assert quant.resolve_codec("none") is None
    assert quant.resolve_codec("fp32") is None
    c = quant.resolve_codec("int8:128")
    assert (c.name, c.block) == ("int8", 128)
    assert quant.resolve_codec("fp8").block == quant.DEFAULT_BLOCK
    assert quant.resolve_codec(c) is c
    with pytest.raises(ValueError):
        quant.resolve_codec("int4")
    with pytest.raises(TypeError):
        quant.resolve_codec(123)


def test_error_feedback_carries_quantization_error():
    """Accumulating EF-quantized gradients tracks the fp32 accumulation;
    the same codec WITHOUT error feedback drifts ~an order of magnitude
    further (the residual is systematic rounding bias)."""
    codec = QuantCodec("int8", 64)
    ef = ErrorFeedback(codec)
    rng = np.random.default_rng(7)
    g = rng.normal(size=512).astype(np.float32)
    acc_f = np.zeros_like(g)
    acc_ef = np.zeros_like(g)
    acc_raw = np.zeros_like(g)
    for _ in range(50):
        acc_f += g
        acc_ef += quant.dequantize(ef.encode("k", g))
        acc_raw += quant.dequantize(quant.quantize(g, codec))
    drift_ef = np.abs(acc_ef - acc_f).max()
    drift_raw = np.abs(acc_raw - acc_f).max()
    assert drift_ef < 0.1 * drift_raw
    assert ef.residual_norm("k") > 0.0
    ef.reset()
    assert ef.residual_norm("k") == 0.0


# ---------------------------------------------------------------------------
# quantized bucket collectives across actor ranks
# ---------------------------------------------------------------------------


def _grad_tree(seed: int, scale_kb: int = 64):
    rng = np.random.default_rng(seed)
    n = scale_kb * 256 // 2  # total fp32 elements across two leaves
    return {
        "wide": rng.normal(size=(n // 16, 16)).astype(np.float32),
        "deep": rng.normal(size=(n,)).astype(np.float32),
    }


@ray_tpu.remote(num_cpus=0.5)
class _QuantRank:
    def __init__(self, rank, world, base, compression):
        from ray_tpu.collective.bucketed import init_sharded_optimizer_groups

        init_sharded_optimizer_groups(world, rank, backend="cpu",
                                      base_name=base)
        self.rank, self.world = rank, world
        self.base, self.comp = base, compression

    def reduce_tree(self, seed, bucket_bytes):
        from ray_tpu.collective.bucketed import (AsyncBucketReducer,
                                                 leaf_meta, plan_buckets)

        tree = _grad_tree(seed)
        plan = plan_buckets(leaf_meta(tree), bucket_bytes=bucket_bytes,
                            world_size=self.world)
        red = AsyncBucketReducer(self.base, plan, compression=self.comp)
        try:
            return red.reduce_tree(tree), red.wire_stats()
        finally:
            red.shutdown()

    def sharded_steps(self, steps, bucket_bytes, clip):
        import optax

        from ray_tpu.collective.bucketed import (ShardedBucketOptimizer,
                                                 leaf_meta, plan_buckets)

        params = _grad_tree(1000)
        plan = plan_buckets(leaf_meta(params), bucket_bytes=bucket_bytes,
                            world_size=self.world)
        opt = ShardedBucketOptimizer(
            self.base, plan, self.rank, optax.adam(1e-2), params,
            clip_global_norm=clip, compression=self.comp)
        try:
            for step in range(steps):
                grads = _grad_tree(step * self.world + self.rank)
                tree, stats = opt.step(grads)
            return {k: np.asarray(v) for k, v in tree.items()}, stats
        finally:
            opt.shutdown()


def test_quantized_reducer_wire_reduction_and_rank_agreement(cluster):
    """int8 bucket allreduce: >= 3.5x fewer wire bytes than fp32 at equal
    tree size, every rank sees the identical reduced tree, and the result
    tracks the exact sum to quantization tolerance."""
    world = 4
    ranks = [_QuantRank.remote(r, world, "q_red", "int8")
             for r in range(world)]
    outs = ray_tpu.get([a.reduce_tree.remote(r, 1 << 16)
                        for r, a in enumerate(ranks)], timeout=180)
    expect = {}
    for key in ("wide", "deep"):
        expect[key] = np.stack([_grad_tree(r)[key]
                                for r in range(world)]).sum(axis=0)
    for tree, _ in outs:
        for key in expect:
            rel = np.abs(tree[key] - expect[key]).max() / \
                np.abs(expect[key]).max()
            assert rel < 0.02, (key, rel)
    t0, _ = outs[0]
    for tree, _ in outs[1:]:
        for key in t0:
            assert np.array_equal(t0[key], tree[key])
    stats = outs[0][1]
    assert stats["compression"] == "int8"
    assert stats["buckets_quantized"] > 0
    assert stats["wire_reduction_x"] >= 3.5, stats
    for a in ranks:
        ray_tpu.kill(a)


def test_reducer_compression_none_bitwise_parity(cluster):
    """Regression guard: compression=None reproduces the uncompressed
    reduce EXACTLY (bitwise vs the rank-ordered stacked sum — the PR 12
    contract) and never touches the quantized path."""
    world = 2
    ranks = [_QuantRank.remote(r, world, "q_none", None)
             for r in range(world)]
    outs = ray_tpu.get([a.reduce_tree.remote(r, 1 << 16)
                        for r, a in enumerate(ranks)], timeout=120)
    for key in ("wide", "deep"):
        expect = np.stack([_grad_tree(r)[key]
                           for r in range(world)]).sum(axis=0)
        for tree, stats in outs:
            assert np.array_equal(tree[key], expect)
            assert stats["compression"] is None
            assert stats["buckets_quantized"] == 0
            assert stats["bytes_wire"] == 0
    for a in ranks:
        ray_tpu.kill(a)


def test_sharded_optimizer_quantized_ranks_identical(cluster):
    """Quantized ShardedBucketOptimizer: grads ride the int8 reduce and
    param refreshes ship as quantized DELTAS — ranks stay bitwise
    identical to each other and track the fp32 trajectory."""
    import optax

    world, steps, clip = 4, 3, 0.5
    ranks = [_QuantRank.remote(r, world, "q_opt", "int8")
             for r in range(world)]
    outs = ray_tpu.get(
        # bucket_bytes sized for ~4 buckets so ownership (and the owner's
        # upload leg) spreads across ranks
        [a.sharded_steps.remote(steps, 1 << 14, clip) for a in ranks],
        timeout=240)
    p0, s0 = outs[0]
    for p, _ in outs[1:]:
        for key in p0:
            assert np.array_equal(p0[key], p[key])
    assert s0["compression"] == "int8"
    assert s0["broadcast_wire_bytes"] < 0.5 * s0["broadcast_fp32_bytes"]
    assert s0["reduce_wire"]["wire_reduction_x"] >= 3.5
    # fp32 reference trajectory (same summed grads through the same
    # per-leaf math): quantized params stay close
    ref = _grad_tree(1000)
    opt = optax.adam(1e-2)
    state = opt.init(ref)
    for step in range(steps):
        summed = {k: np.stack([_grad_tree(step * world + r)[k]
                               for r in range(world)]).sum(axis=0)
                  for k in ref}
        acc = np.float32(0.0)
        for key in ref:
            acc = np.float32(acc + np.float32(
                np.sum(np.square(summed[key].astype(np.float32)))))
        factor = np.float32(clip / max(float(np.sqrt(acc)), clip))
        clipped = {k: (v * factor).astype(v.dtype)
                   for k, v in summed.items()}
        upd, state = opt.update(clipped, state, ref)
        import optax as _optax

        ref = _optax.apply_updates(ref, upd)
    for key in ref:
        denom = np.abs(np.asarray(ref[key])).max()
        assert np.abs(p0[key] - np.asarray(ref[key])).max() < 0.05 * denom
    for a in ranks:
        ray_tpu.kill(a)


# ---------------------------------------------------------------------------
# XLA tier: jitted quantize -> all_to_all -> dequant reduce-scatter
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name,tol", [("int8", 0.02), ("fp8", 0.06),
                                      ("bf16", 0.02)])
def test_xla_quantized_reduce_scatter_matches_psum_scatter(name, tol):
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()), ("data",))
    n = len(jax.devices())
    fn = quant.quantized_psum_scatter_1d(mesh, "data", QuantCodec(name, 64))
    rng = np.random.default_rng(0)
    for L in (n * n * 64 * 2, n * n * 3):  # block-aligned AND ragged
        x = rng.normal(size=L).astype(np.float32)
        out = np.asarray(fn(x))
        expect = x.reshape(n, n, -1).sum(axis=0).reshape(-1)
        assert out.shape == (L // n,)
        rel = np.abs(out - expect).max() / np.abs(expect).max()
        assert rel < tol, (name, L, rel)
    # the analytic wire accounting the bench reports: int8 ~4x under fp32
    fp32 = quant.xla_wire_bytes(1 << 20, n, None)
    q = quant.xla_wire_bytes(1 << 20, n, QuantCodec("int8"))
    assert fp32 / q >= 3.5


def test_traced_bundle_compression_and_bf16_flavors():
    """TrainStepBundle: the traced sharded step with compression="int8"
    and grad_dtype="bf16" both track the fp32 traced step; the default
    (fp32, no compression) build path is byte-identical to PR 12 (same
    program objects, no codec)."""
    import os

    import jax

    from ray_tpu.models import CONFIGS
    from ray_tpu.parallel import TrainStepBundle, create_mesh, make_optimizer
    from ray_tpu.util import tracing

    devs = jax.devices()
    mesh = create_mesh({"data": len(devs), "fsdp": 1, "seq": 1, "tensor": 1,
                        "expert": 1}, devices=devs)
    factory = lambda spec_fn: make_optimizer(  # noqa: E731
        learning_rate=1e-3, warmup_steps=5, total_steps=100,
        clip_spec_fn=spec_fn)

    def run(**kw):
        b = TrainStepBundle(CONFIGS["tiny"], mesh, optimizer_factory=factory,
                            shard_update=True, bucket_bytes=1 << 20, **kw)
        params, opt = b.init_sharded(jax.random.PRNGKey(0))
        batch = b.make_batch(np.random.default_rng(0), 16, 64)
        params, opt, loss = b.step(params, opt, batch)
        return b, float(loss), jax.tree_util.tree_leaves(params)[0]

    base = TrainStepBundle(CONFIGS["tiny"], mesh, optimizer_factory=factory,
                           shard_update=True, bucket_bytes=1 << 20)
    assert base._codec is None and base.grad_dtype == "fp32"

    was = tracing.enabled()
    tracing.enable()
    try:
        _, loss_f, leaf_f = run()
        _, loss_q, leaf_q = run(compression="int8")
    finally:
        if not was:
            tracing._enabled = False
            os.environ.pop("RAY_TPU_ENABLE_TRACING", None)
    assert abs(loss_q - loss_f) <= 0.02 * abs(loss_f)
    rel = np.abs(np.asarray(leaf_q) - np.asarray(leaf_f)).max()
    assert rel < 0.01, rel
    # bf16 grad narrowing on the one-program sharded path stays close to
    # fp32 (master accumulation: opt state + params remain fp32)
    _, loss_b, _ = run(grad_dtype="bf16")
    assert abs(loss_b - loss_f) <= 0.02 * abs(loss_f)
    with pytest.raises(ValueError):
        run(grad_dtype="fp16")


# ---------------------------------------------------------------------------
# PPO int8 convergence parity (the error-feedback convergence test)
# ---------------------------------------------------------------------------


def _ppo_batch(rng, n, obs_dim, n_actions):
    return {
        "obs": rng.standard_normal((n, obs_dim)).astype(np.float32),
        "actions": rng.integers(0, n_actions, n).astype(np.int32),
        "logp": (-np.log(n_actions)
                 + 0.1 * rng.standard_normal(n)).astype(np.float32),
        "advantages": rng.standard_normal(n).astype(np.float32),
        "returns": rng.standard_normal(n).astype(np.float32),
    }


def test_ppo_int8_grad_sync_loss_parity(cluster):
    """The convergence contract of the quantized tier: a 2-learner PPO
    stream with int8+error-feedback grad sync stays within 2% of the fp32
    run's loss, with ranks bitwise-identical to each other."""
    import dataclasses

    import jax

    from ray_tpu.rl.learner_group import LearnerGroup
    from ray_tpu.rl.ppo import PPOConfig, PPOLearner

    obs_dim, n_actions = 4, 2
    base_cfg = PPOConfig(env="CartPole-v1", epochs=2, num_minibatches=4,
                         seed=3)

    def make_group(cfg):
        def factory(rank, world_size, group_name, _cfg=cfg):
            return PPOLearner(_cfg, obs_dim, n_actions,
                              world_size=world_size, rank=rank,
                              group_name=group_name)

        return LearnerGroup(factory, num_learners=2)

    g_fp32 = make_group(base_cfg)
    g_int8 = make_group(dataclasses.replace(base_cfg,
                                            grad_compression="int8"))
    try:
        rng = np.random.default_rng(0)
        losses = {"fp32": [], "int8": []}
        for step in range(6):
            batch = _ppo_batch(rng, 256, obs_dim, n_actions)
            losses["fp32"].append(g_fp32.update(dict(batch))["loss"])
            losses["int8"].append(g_int8.update(dict(batch))["loss"])
        # loss parity within 2% at every step of the stream
        for lf, lq in zip(losses["fp32"], losses["int8"]):
            assert abs(lq - lf) <= 0.02 * max(abs(lf), 1e-3), (lf, lq)
        # quantized ranks still agree with each other bitwise
        params = g_int8.foreach_learner("get_params")
        for a, b in zip(jax.tree_util.tree_leaves(params[0]),
                        jax.tree_util.tree_leaves(params[1])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # and the parameter trees end close to the fp32 group's
        pf = jax.tree_util.tree_leaves(g_fp32.get_params())
        pq = jax.tree_util.tree_leaves(g_int8.get_params())
        for a, b in zip(pf, pq):
            a, b = np.asarray(a), np.asarray(b)
            # relative on real-magnitude leaves, absolute floor for
            # near-zero bias leaves (whole-tree scale ~1e-1)
            assert np.abs(a - b).max() < 0.05 * np.abs(a).max() + 2e-3
    finally:
        g_fp32.shutdown()
        g_int8.shutdown()

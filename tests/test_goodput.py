"""Goodput ledger tests: exclusive/exhaustive region attribution (unit,
incl. concurrent regions on racing threads), shape/dtype-keyed recompile
detection, zygote fork-safety, the GCS-side per-job ledger + health
findings (fixtures), and the acceptance e2e — one real CPU train job with
an injected recompile, input stall and checkpoint save, attributed
end-to-end through ``/api/goodput``, ``util.state.goodput()`` and
``ray-tpu goodput``, with the recompile-storm and input-bound findings
landing in ``/api/health``."""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import goodput, state

# fast cadences + aggressive finding thresholds so the cluster e2e sees
# flushed ledgers and health findings within seconds (set before the
# fixture spawns the GCS/workers — children inherit the env)
_FAST_ENV = {
    "RAY_TPU_METRICS_FLUSH_INTERVAL_S": "1.0",
    "RAY_TPU_HEALTH_SCAN_INTERVAL_S": "1.0",
    "RAY_TPU_GOODPUT_MIN_WALL_S": "1.0",
    "RAY_TPU_GOODPUT_RECOMPILE_STORM_N": "2",
    "RAY_TPU_GOODPUT_INPUT_BOUND_FRAC": "0.01",
}


@pytest.fixture(autouse=True)
def _clean_ledger():
    goodput.reset()
    yield
    goodput.reset()


def _wait_for(predicate, timeout=30, interval=0.5):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = predicate()
        if out:
            return out
        time.sleep(interval)
    return predicate()


def _http_json(address, path):
    with urllib.request.urlopen(f"http://{address}{path}", timeout=30) as r:
        return json.loads(r.read().decode())


# ---------------------------------------------------------------------------
# region API: exclusive nesting, exhaustive decomposition (unit)
# ---------------------------------------------------------------------------


def test_nested_regions_are_exclusive():
    goodput.set_job("u-nest")
    with goodput.region("step_compute"):
        time.sleep(0.06)
        with goodput.region("compile"):
            time.sleep(0.08)
        time.sleep(0.02)
    snap = goodput.snapshot()
    b = snap["buckets"]
    # the child's 0.08 s belongs to compile ONLY — never double-billed
    assert 0.05 <= b["step_compute"] <= 0.14
    assert 0.07 <= b["compile"] <= 0.12
    assert b["step_compute"] + b["compile"] <= snap["wall_s"] + 1e-6


def test_snapshot_is_exhaustive_sum_to_wall():
    goodput.set_job("u-sum")
    with goodput.region("input_stall"):
        time.sleep(0.03)
    time.sleep(0.05)  # unattributed -> derived idle
    snap = goodput.snapshot()
    total = sum(snap["buckets"].values())  # includes derived idle
    assert snap["buckets"]["idle"] >= 0.04
    assert total == pytest.approx(snap["wall_s"], rel=0.02)
    # every declared bucket is present even when zero
    assert set(goodput.BUCKETS) < set(snap["buckets"])


def test_concurrent_regions_on_racing_threads():
    """Two threads attribute into different buckets at the same time:
    the thread-local frame stacks never cross, each bucket gets its own
    thread's seconds (per-thread exclusivity; across threads the sums
    may legitimately exceed single wall-clock)."""
    goodput.set_job("u-threads")
    barrier = threading.Barrier(2)

    def work(bucket):
        barrier.wait()
        for _ in range(5):
            with goodput.region(bucket):
                time.sleep(0.02)

    threads = [threading.Thread(target=work, args=(b,))
               for b in ("step_compute", "input_stall")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    b = goodput.snapshot()["buckets"]
    assert 0.08 <= b["step_compute"] <= 0.30
    assert 0.08 <= b["input_stall"] <= 0.30


def test_set_job_change_resets_accumulators():
    goodput.set_job("u-a")
    goodput.add("ckpt_pause", 3.0)
    goodput.count("ckpt_saves")
    goodput.set_job("u-a")  # same job: accumulators survive
    assert goodput.snapshot()["buckets"]["ckpt_pause"] == 3.0
    goodput.set_job("u-b")  # new job: a reused worker leaks nothing
    snap = goodput.snapshot()
    assert snap["job"] == "u-b"
    assert snap["buckets"]["ckpt_pause"] == 0.0
    assert snap["counters"] == {}


def test_flush_payload_none_for_idle_process():
    # an untagged process that attributed nothing stays out of the
    # goodput KV namespace entirely
    assert goodput.flush_payload(node="n") is None
    goodput.add("overhead", 0.01)
    pay = goodput.flush_payload(node="n")
    assert pay is not None and pay["node"] == "n" and pay["pid"] == os.getpid()


# ---------------------------------------------------------------------------
# compile watch: shape/dtype keying (unit)
# ---------------------------------------------------------------------------


def test_compile_watch_keying():
    w = goodput.CompileWatch()
    b1 = {"x": np.zeros((2, 4), np.float32), "y": np.zeros(2, np.int32)}
    b1b = {"y": np.zeros(2, np.int32), "x": np.zeros((9, 9), np.float32)[:2, :4]}
    b2 = {"x": np.zeros((2, 8), np.float32), "y": np.zeros(2, np.int32)}
    b3 = {"x": np.zeros((2, 4), np.float64), "y": np.zeros(2, np.int32)}

    assert w.observe("f", goodput.batch_key(b1)) == "compile"
    # warm hit: same shapes/dtypes (key order independent) => nothing
    assert w.observe("f", goodput.batch_key(b1b)) is None
    # same fn + new shape => RECOMPILE, new dtype too
    assert w.observe("f", goodput.batch_key(b2)) == "recompile"
    assert w.observe("f", goodput.batch_key(b3)) == "recompile"
    assert w.observe("f", goodput.batch_key(b2)) is None
    # a different program starts its own key space
    assert w.observe("g", goodput.batch_key(b2)) == "compile"


# ---------------------------------------------------------------------------
# fork safety: the zygote path drops inherited ledger state (unit)
# ---------------------------------------------------------------------------


@pytest.mark.skipif(not hasattr(os, "fork"), reason="fork-less platform")
def test_fork_resets_inherited_ledger():
    goodput.set_job("fork-parent")
    goodput.add("step_compute", 7.0)
    goodput.count("steps", 3)

    r, w = os.pipe()
    pid = os.fork()
    if pid == 0:  # child: the zygote fork path's reset, then introspect
        code = 1
        try:
            os.close(r)
            from ray_tpu._private.worker_main import (
                reset_observability_after_fork)

            reset_observability_after_fork()
            snap = goodput.snapshot()
            os.write(w, json.dumps({
                "job": snap["job"],
                "steps": snap["counters"].get("steps", 0),
                "step_compute": snap["buckets"]["step_compute"],
                "payload_none": goodput.flush_payload() is None,
            }).encode())
            code = 0
        finally:
            os._exit(code)
    os.close(w)
    try:
        chunks = b""
        while True:
            chunk = os.read(r, 65536)
            if not chunk:
                break
            chunks += chunk
        _, status = os.waitpid(pid, 0)
        assert os.waitstatus_to_exitcode(status) == 0
        out = json.loads(chunks.decode())
        # the child re-reports NOTHING of the parent's job: no
        # double-counted seconds under a fresh proc key
        assert out == {"job": "", "steps": 0, "step_compute": 0.0,
                       "payload_none": True}
        # the parent's ledger is untouched
        assert goodput.snapshot()["buckets"]["step_compute"] == 7.0
    finally:
        os.close(r)


# ---------------------------------------------------------------------------
# GCS ledger: per-job aggregation + health findings (fixtures)
# ---------------------------------------------------------------------------


class _Cfg:
    goodput_min_wall_s = 5.0
    goodput_recompile_storm_n = 3
    goodput_recompile_window_s = 300.0
    goodput_input_bound_frac = 0.25
    goodput_ckpt_budget_s = 5.0
    goodput_regression_drop = 0.1
    goodput_regression_min_points = 3


def _pay(job, t, wall, buckets=None, counters=None, node="n1", mfu=None):
    p = {"job": job, "pid": 1, "time": t, "started": t - wall,
         "wall_s": wall, "node": node,
         "buckets": dict(buckets or {}), "counters": dict(counters or {})}
    if mfu is not None:
        p["mfu"] = mfu
    return p


def _ledger():
    from ray_tpu._private.gcs import GoodputLedger

    return GoodputLedger()


def test_ledger_aggregates_processes_per_job():
    led = _ledger()
    now = 1000.0
    led.observe("proc_a", _pay("jobX", now, 100.0,
                               {"step_compute": 60.0, "input_stall": 10.0},
                               {"steps": 50}, node="nodeA", mfu=0.4))
    led.observe("proc_b", _pay("jobX", now - 500, 100.0,  # stale proc
                               {"step_compute": 20.0}, {"steps": 10},
                               node="nodeB", mfu=0.3))
    view = led.jobs(now)["jobX"]
    assert view["wall_s"] == 200.0
    assert view["buckets"]["step_compute"] == 80.0
    assert view["counters"]["steps"] == 60
    assert view["goodput_fraction"] == pytest.approx(0.4)
    assert view["mfu"] == 0.4  # max across procs
    assert view["procs"] == 2 and view["fresh_procs"] == 1
    assert view["nodes"] == ["nodeA", "nodeB"]

    # a re-tagged proc moves jobs: its old entry stops inflating jobX
    led.observe("proc_a", _pay("jobY", now, 50.0, {"step_compute": 5.0}))
    jobs = led.jobs(now)
    assert jobs["jobX"]["wall_s"] == 100.0
    assert jobs["jobY"]["procs"] == 1


def test_ledger_findings_fixtures():
    led = _ledger()
    now = 2000.0
    led.observe("p1", _pay("stormy", now, 100.0,
                           {"step_compute": 50.0, "compile": 20.0},
                           {"recompiles": 5, "compiles": 6}))
    led.observe("p2", _pay("starved", now, 100.0,
                           {"step_compute": 40.0, "input_stall": 30.0}))
    led.observe("p3", _pay("pausey", now, 100.0,
                           {"step_compute": 50.0, "ckpt_pause": 30.0},
                           {"ckpt_saves": 3}))
    led.observe("p4", _pay("short", now, 1.0,  # under min wall: exempt
                           {"input_stall": 0.9}, {"recompiles": 9}))
    led.observe("p5", _pay("stale", now - 500, 100.0,
                           {"input_stall": 90.0}))

    found = led.findings(now, _Cfg())
    by_kind = {(f["kind"], f["job"]): f for f in found}
    storm = by_kind[("recompile_storm", "stormy")]
    assert storm["recompiles_in_window"] == 5 and storm["severity"] == "warning"
    bound = by_kind[("input_bound", "starved")]
    assert bound["input_stall_fraction"] == pytest.approx(0.3)
    pause = by_kind[("ckpt_pause_over_budget", "pausey")]
    assert pause["mean_pause_s"] == pytest.approx(10.0)
    # the short job and the stale (finished) job never warn
    assert not any(f["job"] in ("short", "stale") for f in found)

    # storm windowing: with no NEW recompiles the trailing window drains
    # and the storm finding stops re-firing
    later = now + 10.0
    led.observe("p1", _pay("stormy", later, 110.0,
                           {"step_compute": 55.0, "compile": 20.0},
                           {"recompiles": 5, "compiles": 6}))
    again = led.findings(later, _Cfg())
    assert not any(f["kind"] == "recompile_storm" for f in again)


def test_ledger_goodput_regression_finding():
    led = _ledger()
    cfg = _Cfg()
    now = 3000.0
    # three healthy scans build the trailing window at fraction 0.8
    for i in range(3):
        led.observe("p1", _pay("reg", now + i, 100.0 + i,
                               {"step_compute": 0.8 * (100.0 + i)}))
        assert not any(f["kind"] == "goodput_regression"
                       for f in led.findings(now + i, cfg))
    # then the job collapses to 0.5: drop 0.3 > the 0.1 threshold
    led.observe("p1", _pay("reg", now + 3, 200.0, {"step_compute": 100.0}))
    found = [f for f in led.findings(now + 3, cfg)
             if f["kind"] == "goodput_regression"]
    assert found and found[0]["job"] == "reg"
    assert found[0]["trailing_mean"] == pytest.approx(0.8)
    assert found[0]["goodput_fraction"] == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# cluster e2e: injected recompile + input stall + ckpt pause, attributed
# through every surface (the acceptance test)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def goodput_cluster():
    ray_tpu.shutdown()
    old = {k: os.environ.get(k) for k in _FAST_ENV}
    os.environ.update(_FAST_ENV)
    worker = ray_tpu.init(num_cpus=4, include_dashboard=True)
    yield worker
    ray_tpu.shutdown()
    for k, v in old.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


@ray_tpu.remote
def _goodput_probe(ckpt_dir):
    """One REAL CPU train job on a worker: four library train steps with
    a batch seq-length change (=> jit recompiles through the compile
    watch), a starved device-prefetch iterator (=> input_stall via the
    real consumer loop), and a checkpoint save (=> ckpt_pause)."""
    import jax

    from ray_tpu import data
    from ray_tpu.ckpt.saver import CheckpointSaver
    from ray_tpu.ckpt.store import CheckpointStore
    from ray_tpu.models import CONFIGS
    from ray_tpu.parallel import TrainStepBundle, create_mesh
    from ray_tpu.util import goodput as gp

    gp.set_job("goodput-e2e")
    mesh = create_mesh({"data": 1, "fsdp": 1, "seq": 1, "tensor": 1,
                        "expert": 1}, devices=jax.devices()[:1])
    bundle = TrainStepBundle(CONFIGS["tiny"], mesh)
    params, opt_state = bundle.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    loss = None
    for seq in (32, 32, 48, 64):  # 48/64 are NEW shape keys -> recompiles
        batch = bundle.make_batch(rng, 2, seq)
        params, opt_state, loss = bundle.step(params, opt_state, batch)

    # injected input stall: a slow host pipeline starving the REAL
    # iter_device_batches consumer loop
    ds = data.from_items([{"x": 0}])

    def slow_iter(batch_size=256, drop_last=False):
        for _ in range(3):
            time.sleep(0.4)
            yield {"x": np.ones((2, 8), np.float32)}

    ds.iter_batches = slow_iter
    consumed = sum(1 for _ in ds.iter_device_batches(batch_size=2,
                                                     device_prefetch=1))

    saver = CheckpointSaver(CheckpointStore(ckpt_dir))
    saver.save(jax.device_get(params), step=1, blocking=True)

    time.sleep(2.5)  # hold past one observability flush (1 s cadence)
    return {"snapshot": gp.snapshot(), "consumed": consumed,
            "loss": float(loss)}


def test_goodput_e2e_all_surfaces(goodput_cluster, tmp_path):
    out = ray_tpu.get(_goodput_probe.remote(str(tmp_path / "ckpt")),
                      timeout=600)
    assert out["consumed"] == 3 and out["loss"] > 0
    local = out["snapshot"]
    assert local["counters"]["recompiles"] >= 2
    assert local["counters"]["input_waits"] >= 3
    assert local["counters"]["ckpt_saves"] == 1

    # --- /api/goodput: the flushed ledger, attributed and exhaustive ---
    address = goodput_cluster.node_supervisor.dashboard_address

    def _job():
        jobs = _http_json(address, "/api/goodput")
        view = jobs.get("goodput-e2e")
        if view and all(view["buckets"].get(b, 0) > 0
                        for b in ("compile", "input_stall", "ckpt_pause")):
            return view
        return None

    view = _wait_for(_job, timeout=60)
    assert view, "goodput ledger never landed on /api/goodput"
    assert view["buckets"]["step_compute"] > 0
    assert view["counters"]["recompiles"] >= 2
    # exhaustive: buckets (incl. derived idle) sum to wall within 2%
    assert sum(view["buckets"].values()) == pytest.approx(
        view["wall_s"], rel=0.02)
    # the injected ~1.2 s stall is actually in the input bucket
    assert view["buckets"]["input_stall"] >= 0.8

    # ?job= filter
    only = _http_json(address, "/api/goodput?job=goodput-e2e")
    assert set(only) == {"goodput-e2e"}

    # --- util.state surface ---
    jobs = state.goodput()
    assert jobs["goodput-e2e"]["buckets"]["ckpt_pause"] > 0
    assert state.goodput(job="goodput-e2e")["goodput-e2e"]["wall_s"] > 0

    # --- ray-tpu goodput CLI (a real subprocess driver) ---
    gcs_address = goodput_cluster.node_supervisor.gcs_address
    cli = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "--address",
         gcs_address, "goodput"],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert cli.returncode == 0, cli.stderr[-2000:]
    assert "goodput-e2e" in cli.stdout
    for bucket in ("compile", "input_stall", "ckpt_pause"):
        assert bucket in cli.stdout

    # --- health findings: recompile storm + input-bound job ---
    def _findings():
        health = _http_json(address, "/api/health?scan=1")
        kinds = {f["kind"] for f in health["findings"]
                 if f.get("job") == "goodput-e2e"}
        if {"recompile_storm", "input_bound"} <= kinds:
            return health
        return None

    health = _wait_for(_findings, timeout=30)
    assert health, "goodput findings never reached /api/health"

"""Tier-1 smoke for tools/benchtrack.py: the bench-artifact regression
gate must be green on the repo's checked-in artifacts, and must actually
FIRE on a synthetic regressed artifact (a gate that can't fail guards
nothing)."""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[1]
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from tools import benchtrack  # noqa: E402


def test_check_green_on_repo_artifacts():
    """The tier-1 wiring: every checked-in BENCH/STRESS/SERVE/PIPE/OBS
    artifact clears its per-metric threshold (and the OBS absolute
    overhead bars)."""
    failures, passes = benchtrack.check(str(REPO_ROOT))
    assert not failures, "\n".join(failures)
    # the gate saw real artifacts, it did not vacuously pass on nothing
    assert len(passes) >= 10
    families = {line.split()[0] for line in passes}
    assert {"BENCH", "STRESS", "SERVE", "PIPE", "OBS"} <= families


def test_cli_check_exit_codes(tmp_path):
    """`--check` exits 0 on the repo and 1 on a regressed artifact set."""
    out = subprocess.run(
        [sys.executable, os.path.join("tools", "benchtrack.py"), "--check"],
        capture_output=True, text=True, timeout=120, cwd=str(REPO_ROOT))
    assert out.returncode == 0, out.stdout + out.stderr

    (tmp_path / "SERVE_r01.json").write_text(json.dumps(
        {"ttft_p99_ms": 230.0, "latency_p99_ms": 300.0,
         "tokens_per_s": 200.0, "dropped_requests": 0}))
    (tmp_path / "SERVE_r02.json").write_text(json.dumps(
        {"ttft_p99_ms": 500.0, "latency_p99_ms": 310.0,
         "tokens_per_s": 205.0, "dropped_requests": 0}))
    out = subprocess.run(
        [sys.executable, os.path.join("tools", "benchtrack.py"), "--check",
         "--dir", str(tmp_path)],
        capture_output=True, text=True, timeout=120, cwd=str(REPO_ROOT))
    assert out.returncode == 1, out.stdout + out.stderr
    assert "ttft_p99_ms" in out.stdout and "FAIL" in out.stdout


def test_regression_directions(tmp_path):
    """Direction-aware thresholds: an MFU drop (higher-better) and a TTFT
    blowup (lower-better) both fire; improvements never do."""
    def bench(n, mfu):
        (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps(
            {"n": n, "parsed": {"metric": "train_mfu_1b", "value": mfu,
                                "step_time_s": 0.5}}))

    bench(1, 0.46)
    bench(2, 0.40)  # -13% > the 5% MFU threshold
    failures, _ = benchtrack.check(str(tmp_path))
    assert any("train_mfu_1b" in f for f in failures), failures

    bench(2, 0.47)  # improvement: green
    failures, passes = benchtrack.check(str(tmp_path))
    assert not failures, failures
    assert any("train_mfu_1b" in p for p in passes)


def test_obs_absolute_bar_fires_without_history(tmp_path):
    """The observability <=5% overhead contract is an ABSOLUTE bar: a
    single round over it fails even with no prior round to compare."""
    (tmp_path / "OBS_r01.json").write_text(json.dumps(
        {"events_delta_pct": 7.2, "train_step_delta_pct": 1.0}))
    failures, _ = benchtrack.check(str(tmp_path))
    assert any("events_delta_pct" in f and "absolute bar" in f
               for f in failures), failures


def test_pipe_analytic_floor_metadata_fires(tmp_path):
    """A PIPE row carrying ``meta.floor`` (the analytic bubble bound) is
    held to it absolutely — a simulated bubble below the bound means the
    measurement lied, even with no prior round."""
    (tmp_path / "PIPE_r01.json").write_text(json.dumps(
        [{"name": "pipeline_s2_bubble_fraction", "value": 0.05,
          "unit": "fraction", "meta": {"floor": 0.1111}}]))
    failures, _ = benchtrack.check(str(tmp_path))
    assert any("analytic floor" in f for f in failures), failures

    (tmp_path / "PIPE_r01.json").write_text(json.dumps(
        [{"name": "pipeline_s2_bubble_fraction", "value": 0.1111,
          "unit": "fraction", "meta": {"floor": 0.1111}}]))
    failures, _ = benchtrack.check(str(tmp_path))
    assert not failures, failures


def test_pipe_host_envelope_rebaselines(tmp_path):
    """Rounds measured on different host envelopes (config row's
    ``meta.host_cpus``) never price round-over-round moves against each
    other; same-envelope rounds still gate."""
    def pipe(n, tps, cpus=None):
        rows = [{"name": "pipeline_s2_tokens_per_s", "value": tps,
                 "unit": "tokens/s"}]
        if cpus is not None:
            rows.append({"name": "config", "value": 0, "unit": "meta",
                         "meta": {"host_cpus": cpus}})
        (tmp_path / f"PIPE_r{n:02d}.json").write_text(json.dumps(rows))

    pipe(1, 9000.0)            # legacy round, unknown envelope
    pipe(2, 900.0, cpus=1)     # 10x "drop" on a 1-core box: re-baseline
    failures, passes = benchtrack.check(str(tmp_path))
    assert not failures, failures
    assert any("host envelope changed" in p for p in passes), passes

    pipe(3, 500.0, cpus=1)     # same envelope: the relative gate fires
    failures, _ = benchtrack.check(str(tmp_path))
    assert any("tokens_per_s" in f for f in failures), failures


def test_trajectory_normalizes_heterogeneous_schemas(tmp_path):
    """BENCH nests under `parsed`, PIPE is a list of name/value entries,
    STRESS is flat — all land in the one trajectory schema, rounds
    ascending, foreign JSON skipped."""
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"parsed": {"metric": "train_mfu_1b", "value": 0.45}}))
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"parsed": {"metric": "train_mfu_1b", "value": 0.44}}))
    (tmp_path / "PIPE_r01.json").write_text(json.dumps(
        [{"name": "pipeline_s2_bubble_fraction", "value": 0.11,
          "unit": "fraction"},
         {"name": "pipeline_s2_tokens_per_s", "value": 8700.0,
          "unit": "tok/s"}]))
    (tmp_path / "STRESS_r01.json").write_text(json.dumps(
        {"tasks_per_s": 2358.6, "mode": "smoke"}))
    (tmp_path / "NOT_A_BENCH.json").write_text("{}")
    (tmp_path / "BENCH_r03.json").write_text("not json at all")

    traj = benchtrack.load_trajectory(str(tmp_path))
    assert set(traj) == {"BENCH", "PIPE", "STRESS"}
    assert [r["round"] for r in traj["BENCH"]] == [1, 2]
    assert traj["PIPE"][0]["metrics"] == {
        "pipeline_s2_bubble_fraction": 0.11,
        "pipeline_s2_tokens_per_s": 8700.0}
    assert traj["STRESS"][0]["metrics"] == {"tasks_per_s": 2358.6}

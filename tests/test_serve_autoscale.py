"""Serve autoscale plane tests (ray_tpu/serve/autoscale/).

Unit tier: the rate window (burst-blindness regression), the demand
policy (hysteresis / cooldown / SLO pressure), DRR fair-queue ordering
and bounds, consistent-hash ring stability, prefix-router accounting.

Integration tier (cluster fixture): sustained load bursts scale a
deployment up, the drain scales it down, nothing drops, scale events
land in the task plane; ingress admission sheds on a full tenant queue;
the prefix routing policy keeps a prompt prefix on one replica; the
bench_serve harness runs end to end in --smoke mode.
"""

import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.autoscale import (
    ConsistentHashRing,
    DeploymentMetricsWindow,
    FairQueue,
    LoadShedError,
    PolicyState,
    PrefixRouter,
    SLOConfig,
    decide,
)
from ray_tpu.serve.api import AutoscalingConfig


# ---------------------------------------------------------------------------
# unit: window + policy
# ---------------------------------------------------------------------------


def _stat(arrived=0, completed=0, execute_sum=0.0, execute_count=0,
          ongoing=0, peak=0, queue_samples=()):
    return {"arrived": arrived, "completed": completed,
            "execute_sum": execute_sum, "execute_count": execute_count,
            "ongoing": ongoing, "peak": peak,
            "queue_samples": list(queue_samples)}


def test_window_rates_from_counter_deltas():
    w = DeploymentMetricsWindow(window_s=10.0)
    w.observe([_stat()], now=100.0)
    w.observe([_stat(arrived=40, completed=40, execute_sum=8.0,
                     execute_count=40, queue_samples=[0.01, 0.5])],
              now=102.0)
    assert w.arrival_rate(102.0) == pytest.approx(20.0)
    assert w.completion_rate(102.0) == pytest.approx(20.0)
    assert w.execute_mean_s(102.0) == pytest.approx(0.2)
    assert w.queue_p99_s(102.0) == pytest.approx(0.5)


def test_window_burst_blindness_regression():
    """The PR 8 case, covered structurally: a burst that arrives AND fully
    drains between two polls leaves ongoing=0/peak small at both ticks —
    a point gauge sees nothing, the cumulative arrival counter prices it."""
    w = DeploymentMetricsWindow(window_s=10.0)
    w.observe([_stat()], now=10.0)
    # 100 requests came and went entirely between the two polls
    w.observe([_stat(arrived=100, completed=100, execute_sum=30.0,
                     execute_count=100, ongoing=0, peak=2)], now=11.0)
    assert w.arrival_rate(11.0) == pytest.approx(100.0)
    auto = AutoscalingConfig(min_replicas=1, max_replicas=8,
                             target_ongoing_requests=2.0,
                             upscale_delay_s=0.0, scale_cooldown_s=0.0)
    d = decide(w, current_target=1, config=auto, state=PolicyState(),
               now=11.0)
    # Little's law: 100/s x 0.3s = 30 concurrent -> 15 replicas, clamped
    assert d.direction == "up"
    assert d.want == 8


def test_window_counter_reset_clamped():
    """A replica death steps the cluster-summed cumulative counter DOWN;
    the rate must clamp at zero, not go negative."""
    w = DeploymentMetricsWindow(window_s=10.0)
    w.observe([_stat(arrived=500)], now=50.0)
    w.observe([_stat(arrived=120)], now=51.0)  # membership shrank
    assert w.arrival_rate(51.0) == 0.0


def test_policy_hysteresis_and_cooldown():
    auto = AutoscalingConfig(min_replicas=1, max_replicas=4,
                             target_ongoing_requests=2.0,
                             upscale_delay_s=0.0, downscale_delay_s=0.0,
                             hysteresis=0.1, scale_cooldown_s=5.0)
    st = PolicyState()

    def window_with_demand(concurrency, now):
        w = DeploymentMetricsWindow(window_s=10.0)
        w.observe([_stat()], now=now - 1.0)
        w.observe([_stat(arrived=int(concurrency * 10),
                         completed=int(concurrency * 10),
                         execute_sum=concurrency,
                         execute_count=int(concurrency * 10))], now=now)
        return w

    # demand 6 concurrency / target 2 -> 3 replicas: jump straight there
    d = decide(window_with_demand(6.0, 100.0), current_target=1,
               config=auto, state=st, now=100.0)
    assert (d.direction, d.want) == ("up", 3)
    # cooldown: pressure persists but the next action must wait
    d = decide(window_with_demand(8.0, 101.0), current_target=3,
               config=auto, state=st, now=101.0)
    assert d.direction == "hold"
    # hysteresis: demand 1.9 fits 2 replicas but NOT under the band below
    # (2-1)*(1-0.1)=0.9, so no release even after the cooldown
    d = decide(window_with_demand(1.9 * 2.0, 110.0), current_target=2,
               config=auto, state=st, now=110.0)
    assert d.direction == "hold"
    # true idle clears the band -> step down ONE replica
    d = decide(window_with_demand(0.2, 120.0), current_target=3,
               config=auto, state=st, now=120.0)
    assert (d.direction, d.want) == ("down", 2)


def test_policy_queue_slo_pressure():
    """Queue p99 over the registered target reads as up-pressure even when
    the rate math says capacity is sufficient."""
    auto = AutoscalingConfig(min_replicas=1, max_replicas=4,
                             target_ongoing_requests=2.0,
                             upscale_delay_s=0.0, scale_cooldown_s=0.0)
    w = DeploymentMetricsWindow(window_s=10.0)
    w.observe([_stat()], now=10.0)
    w.observe([_stat(arrived=10, completed=10, execute_sum=0.5,
                     execute_count=10, queue_samples=[2.0] * 8)], now=11.0)
    st = PolicyState()
    assert decide(w, current_target=1, config=auto, state=st, now=11.0
                  ).direction == "hold"  # demand alone is tiny
    d = decide(w, current_target=1, config=auto, state=PolicyState(),
               now=11.0, queue_target_s=0.5)
    assert d.direction == "up"
    assert "SLO" in d.reason


def test_autoscaling_config_backcompat_dict():
    # pre-PR dicts (no window/hysteresis/cooldown keys) must still parse
    cfg = AutoscalingConfig.from_dict({
        "min_replicas": 1, "max_replicas": 3,
        "target_ongoing_requests": 2.0, "upscale_delay_s": 0.5,
        "downscale_delay_s": 1.0})
    assert cfg.window_s == 10.0 and cfg.hysteresis == 0.1
    with pytest.raises(ValueError):
        AutoscalingConfig.from_dict({"max_replicaz": 2})
    with pytest.raises(ValueError):
        SLOConfig.from_dict({"ttft_target_s": 0.5, "bogus": 1})


# ---------------------------------------------------------------------------
# unit: fair queue + routing
# ---------------------------------------------------------------------------


def test_fair_queue_drr_weighted_ordering():
    q = FairQueue(max_depth_per_tenant=16, weights={"a": 2.0, "b": 1.0})
    for i in range(6):
        assert q.push("a", ("a", i))
        assert q.push("b", ("b", i))
    drained = [q.pop() for _ in range(12)]
    assert q.pop() is None
    # per-tenant FIFO preserved
    assert [i for t, i in drained if t == "a"] == list(range(6))
    assert [i for t, i in drained if t == "b"] == list(range(6))
    # weighted share: while both tenants are backlogged (the first 9
    # pops), tenant a (weight 2) drains ~2x tenant b
    first9 = [t for t, _ in drained[:9]]
    assert first9.count("a") == 6 and first9.count("b") == 3


def test_fair_queue_bounded_depth_sheds():
    q = FairQueue(max_depth_per_tenant=4)
    assert all(q.push("flood", i) for i in range(4))
    assert not q.push("flood", 99)  # full -> shed
    assert q.push("other", "x")  # another tenant is unaffected
    assert len(q) == 5


def test_consistent_ring_minimal_remap():
    class R:
        def __init__(self, h):
            self._actor_id = type("A", (), {"hex": lambda s, h=h: h})()

    reps = [R("aa"), R("bb"), R("cc"), R("dd")]
    ring = ConsistentHashRing(reps)
    before = {f"k{i}": ring.lookup(f"k{i}")._actor_id.hex()
              for i in range(400)}
    ring2 = ConsistentHashRing(reps[:3])  # "dd" left
    moved_non_victim = sum(
        1 for k, owner in before.items()
        if owner != "dd" and ring2.lookup(k)._actor_id.hex() != owner)
    assert moved_non_victim == 0  # only the victim's keys remap
    victim_keys = sum(1 for v in before.values() if v == "dd")
    assert 0 < victim_keys < 200  # ~1/4 of the space, not half


def test_prefix_router_key_and_hit_accounting():
    r = PrefixRouter("dep", prefix_len=8)
    assert r.key_of({"prompt": "abcdefghij-tail"}) == "abcdefgh"
    assert r.key_of("raw prompt string")[:3] == "raw"
    assert r.key_of({"messages": [{"role": "user"}]}) is not None
    assert r.key_of(12345) is None

    class R:
        def __init__(self, h):
            self._actor_id = type("A", (), {"hex": lambda s, h=h: h})()

    reps = [R("aa"), R("bb"), R("cc")]
    first = r.pick("warm-key", reps, version=1)
    for _ in range(5):  # repeat hits stay on the same replica
        assert r.pick("warm-key", reps, version=1) is first


# ---------------------------------------------------------------------------
# integration
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=6)
    yield ray_tpu
    try:
        serve.shutdown()
    except Exception:
        pass
    ray_tpu.shutdown()


def test_sustained_load_scale_up_drain_down(cluster):
    """Burst -> rate window prices demand -> scale up; drain -> demand
    decays under the hysteresis band -> scale down; every request
    completes and the scale history + task-plane events record why."""
    from ray_tpu.serve import api as serve_api

    @serve.deployment(
        name="surge", max_ongoing_requests=32,
        autoscaling_config={"min_replicas": 1, "max_replicas": 3,
                            "target_ongoing_requests": 2.0,
                            "upscale_delay_s": 0.3,
                            "downscale_delay_s": 0.8,
                            "window_s": 3.0, "scale_cooldown_s": 0.3},
        ray_actor_options={"num_cpus": 0.25})
    class Surge:
        async def __call__(self, body):
            import asyncio

            await asyncio.sleep(0.15)
            return body["i"]

    handle = serve.run(Surge.bind(), name="surge")
    controller = serve_api._get_controller(create=False)
    # open-loop burst: fire 80 requests over ~2s without waiting
    refs = []
    for i in range(80):
        refs.append(handle.remote({"i": i}))
        time.sleep(0.025)
    out = ray_tpu.get(refs, timeout=120)
    assert sorted(out) == list(range(80))  # zero drops, zero dupes

    state = ray_tpu.get(
        controller.get_autoscale_state.remote("surge"), timeout=30)
    ups = [t for t in state["transitions"] if t["direction"] == "up"]
    assert ups, f"no scale-up recorded: {state}"
    assert ups[0]["to"] > ups[0]["from"]
    assert "demand" in ups[0]["reason"] or "SLO" in ups[0]["reason"]
    assert ups[0]["metrics"]["arrival_rate"] > 0

    # drain: demand decays through the window -> back to min_replicas
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        state = ray_tpu.get(
            controller.get_autoscale_state.remote("surge"), timeout=30)
        if state["target"] == 1 and any(
                t["direction"] == "down" for t in state["transitions"]):
            break
        time.sleep(0.5)
    downs = [t for t in state["transitions"] if t["direction"] == "down"]
    assert downs, f"no scale-down recorded: {state}"
    assert state["target"] == 1

    # monotonic reconciliation: the transition log chains exactly
    # (each action starts from where the previous one landed)
    trs = state["transitions"]
    for prev, nxt in zip(trs, trs[1:]):
        assert nxt["from"] == prev["to"]

    # replicas converge on the target after the drain grace
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        st = serve.status()["surge"]
        if st["num_replicas"] == 1 and st["draining"] == 0:
            break
        time.sleep(0.5)
    assert serve.status()["surge"]["num_replicas"] == 1

    # structured scale events reached the task plane
    from ray_tpu.util import events as events_mod

    evs = [e for e in events_mod.list_events(source="serve")
           if "autoscale surge" in e["message"]]
    assert any(e["metadata"].get("direction") == "up" for e in evs)
    assert any(e["metadata"].get("direction") == "down" for e in evs)
    serve.delete("surge")


def test_ingress_shed_and_fair_admission(cluster):
    """A flooding tenant hits its bounded queue and sheds; admitted work
    all completes; a second tenant is never starved out."""

    @serve.deployment(name="gated", max_ongoing_requests=2,
                      ray_actor_options={"num_cpus": 0.25})
    class Gated:
        async def __call__(self, body):
            import asyncio

            await asyncio.sleep(0.1)
            return body["tenant"]

    serve.run(Gated.bind(), name="gated")
    ingress = serve.build_ingress(
        "gated",
        {"max_queue_depth": 8, "latency_budget_s": 30.0,
         "tenant_weights": {"vip": 2.0}},
        max_inflight_per_replica=2)
    futures, shed_sync = [], 0
    for i in range(40):
        f = ingress.submit({"tenant": "flood"}, tenant="flood")
        # a shed future is resolved synchronously by submit()
        if f.done() and isinstance(f.exception(), LoadShedError):
            shed_sync += 1
        else:
            futures.append(f)
    vip = [ingress.submit({"tenant": "vip"}, tenant="vip")
           for _ in range(4)]
    assert shed_sync > 0, "flood never hit the bounded queue"
    assert len(futures) <= 8 + 4  # bound + inflight window
    for f in futures:
        assert f.result(timeout=60) == "flood"
    for f in vip:
        assert f.result(timeout=60) == "vip"
    st = ingress.stats()
    assert st["shed"] == shed_sync
    assert st["completed"] == len(futures) + len(vip)
    assert st["queued"] == 0 and st["inflight"] == 0
    ingress.close()
    serve.delete("gated")


def test_ingress_deadline_shed(cluster):
    """A request whose latency budget expires while queued is shed at
    dispatch instead of burning replica time."""

    @serve.deployment(name="slowpoke", max_ongoing_requests=1,
                      ray_actor_options={"num_cpus": 0.25})
    class Slowpoke:
        async def __call__(self, body):
            import asyncio

            await asyncio.sleep(0.4)
            return "done"

    serve.run(Slowpoke.bind(), name="slowpoke")
    ingress = serve.build_ingress(
        "slowpoke", {"max_queue_depth": 64, "latency_budget_s": 0.3},
        max_inflight_per_replica=1)
    futs = [ingress.submit({}) for _ in range(6)]
    outcomes = {"ok": 0, "shed": 0}
    for f in futs:
        try:
            f.result(timeout=60)
            outcomes["ok"] += 1
        except LoadShedError:
            outcomes["shed"] += 1
    assert outcomes["ok"] >= 1
    assert outcomes["shed"] >= 1, f"no deadline shed: {outcomes}"
    ingress.close()
    serve.delete("slowpoke")


def test_prefix_routing_policy_sticks_and_survives_scaling(cluster):
    @serve.deployment(name="kv", num_replicas=2,
                      ray_actor_options={"num_cpus": 0.25})
    class KV:
        def __call__(self, body):
            import os

            return os.getpid()

    handle = serve.run(KV.bind(), name="kv").options(
        routing_policy="prefix")
    prompts = [{"prompt": f"conversation-{i}: tell me more"}
               for i in range(6)]
    first = [ray_tpu.get(handle.remote(p), timeout=120) for p in prompts]
    for _ in range(3):  # repeats stay on their replica
        again = [ray_tpu.get(handle.remote(p), timeout=60)
                 for p in prompts]
        assert again == first
    assert len(set(first)) > 1  # keys actually spread across replicas
    # handles survive pickling with the policy intact
    import cloudpickle

    h2 = cloudpickle.loads(cloudpickle.dumps(handle))
    assert h2._routing_policy == "prefix"
    with pytest.raises(ValueError):
        handle.options(routing_policy="bogus")
    serve.delete("kv")


def test_serve_state_and_cli_surface(cluster):
    """The controller mirrors autoscale state into the serve KV namespace:
    util.state.serve_state() and `ray-tpu serve` read it back."""

    @serve.deployment(name="mirrored",
                      autoscaling_config={"min_replicas": 1,
                                          "max_replicas": 2,
                                          "window_s": 2.0},
                      ray_actor_options={"num_cpus": 0.25})
    class Mirrored:
        def __call__(self, body):
            return "ok"

    handle = serve.run(Mirrored.bind(), name="mirrored")
    assert ray_tpu.get(handle.remote({}), timeout=120) == "ok"
    from ray_tpu.util.state import serve_state

    deadline = time.monotonic() + 30.0
    entry = None
    while time.monotonic() < deadline:
        entry = serve_state().get("mirrored")
        if entry and entry.get("rollup", {}).get("samples", 0) > 1:
            break
        time.sleep(0.5)
    assert entry is not None, "serve KV mirror never published"
    assert entry["target"] >= 1
    assert "arrival_rate" in entry["rollup"]
    serve.delete("mirrored")
    # delete cleans the mirror up
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline:
        if "mirrored" not in serve_state():
            break
        time.sleep(0.5)
    assert "mirrored" not in serve_state()


def test_bench_serve_smoke(cluster):
    """tools/bench_serve --smoke end to end in a fresh interpreter: the
    SERVE_r01 acceptance shape (rate-based up AND down, zero drops across
    a rolling update) must reproduce."""
    import json
    import os
    import subprocess
    import sys

    out_path = "/tmp/ray_tpu_serve_smoke.json"
    try:
        os.unlink(out_path)
    except FileNotFoundError:
        pass
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.bench_serve", "--smoke",
         "--out", out_path],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, \
        f"bench_serve failed:\n{proc.stdout[-4000:]}\n{proc.stderr[-2000:]}"
    with open(out_path) as f:
        result = json.load(f)
    assert result["dropped_requests"] == 0
    assert result["requests_completed"] == result["requests_fired"]
    assert result["scaled_up"] and result["scaled_down"]
    assert result["ttft_p99_ms"] > 0
    assert result["rolling_update_weights_version"] == 1


# ---------------------------------------------------------------------------
# server-side TTFT: window differentiation, policy pressure, SLO finding
# ---------------------------------------------------------------------------


def test_window_ttft_p99_from_replica_samples():
    w = DeploymentMetricsWindow(window_s=10.0)
    st = _stat(arrived=20, completed=20, execute_sum=4.0, execute_count=20)
    st["ttft_samples"] = [0.05] * 18 + [0.9, 1.1]
    w.observe([_stat()], now=100.0)
    w.observe([st], now=102.0)
    # p99 sees the slow-first-byte tail, not the happy median
    assert w.ttft_p99_s(102.0) == pytest.approx(1.1)
    assert w.rollup(102.0)["ttft_p99_s"] == pytest.approx(1.1)
    # samples age out with the window
    assert w.ttft_p99_s(102.0 + 11.0) is None


def test_policy_ttft_slo_pressure():
    """TTFT p99 over the registered target reads as up-pressure even when
    the rate math says capacity is sufficient (streams slow to first
    byte are invisible to Little's law)."""
    auto = AutoscalingConfig(min_replicas=1, max_replicas=4,
                             target_ongoing_requests=2.0,
                             upscale_delay_s=0.0, scale_cooldown_s=0.0)
    w = DeploymentMetricsWindow(window_s=10.0)
    st = _stat(arrived=10, completed=10, execute_sum=0.5, execute_count=10)
    st["ttft_samples"] = [2.0] * 8
    w.observe([_stat()], now=10.0)
    w.observe([st], now=11.0)
    assert decide(w, current_target=1, config=auto, state=PolicyState(),
                  now=11.0).direction == "hold"  # demand alone is tiny
    d = decide(w, current_target=1, config=auto, state=PolicyState(),
               now=11.0, ttft_target_s=0.5)
    assert d.direction == "up"
    assert "ttft" in d.reason and "SLO" in d.reason
    assert d.metrics["ttft_p99_s"] == pytest.approx(2.0)


def test_ttft_slo_violation_finding_e2e(cluster):
    """Replica-stamped TTFT flows to the serve rollup, and a registered
    `ttft_target_s` the deployment can't meet becomes a
    `serve_slo_violation` finding on `ttft_p99_s` in the health scan."""
    from ray_tpu.util.state import cluster_health, serve_state

    @serve.deployment(name="slow_first_byte", max_ongoing_requests=4,
                      autoscaling_config={"min_replicas": 1,
                                          "max_replicas": 1,
                                          "window_s": 30.0},
                      ray_actor_options={"num_cpus": 0.25})
    class SlowFirstByte:
        async def __call__(self, body):
            import asyncio

            await asyncio.sleep(0.15)  # every first byte is late
            return "late"

    serve.run(SlowFirstByte.bind(), name="slow_first_byte")
    ingress = serve.build_ingress(
        "slow_first_byte", {"ttft_target_s": 0.01, "max_queue_depth": 64})
    futs = [ingress.submit({}) for _ in range(10)]
    assert all(f.result(timeout=120) == "late" for f in futs)

    # the controller tick drains replica ttft samples into the window and
    # mirrors rollup["ttft_p99_s"] into the serve KV namespace
    deadline = time.monotonic() + 45.0
    entry = None
    while time.monotonic() < deadline:
        entry = serve_state().get("slow_first_byte")
        if entry and entry.get("rollup", {}).get("ttft_p99_s"):
            break
        time.sleep(0.5)
    assert entry and entry["rollup"]["ttft_p99_s"] >= 0.1, entry
    assert entry.get("slo", {}).get("ttft_target_s") == 0.01

    findings = [f for f in cluster_health(scan=True)["findings"]
                if f["kind"] == "serve_slo_violation"
                and f.get("metric") == "ttft_p99_s"]
    assert findings and findings[0]["deployment"] == "slow_first_byte"
    assert findings[0]["value"] > findings[0]["target"]
    ingress.close()
    serve.delete("slow_first_byte")

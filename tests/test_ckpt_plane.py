"""Checkpoint-plane acceptance tests (ray_tpu/ckpt/).

Covers the four north-star properties:
(a) async save overlaps a running train loop — the step-side pause is
    bounded and far below the blocking save cost;
(b) content-addressed dedup — consecutive saves of a mostly-unchanged
    tree share chunks, asserted from manifest stats and the diff tool;
(c) crash-mid-save atomicity — a torn save never becomes ``latest``;
    restore falls back to the previous valid checkpoint;
(d) restore-time resharding — a 4-host sharded save restores byte-exact
    onto a 2-host mesh through the weight-plane planner, with plan-level
    ``no_gather()`` and per-host byte accounting,
plus the train/tune wiring (manager fallback, PBT manifest-ref swap) and
the GCS-registered store surface (``util.state.list_checkpoints``).
"""

import json
import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import ckpt
from ray_tpu.weights.spec import (
    MeshSpec,
    ShardedTreeSpec,
    box_slices,
    host_boxes,
)


def _tree(scale: float = 1.0, n: int = 1 << 16):
    return {
        "layers": {
            "w0": np.full((n,), scale, np.float32),
            "w1": np.arange(n, dtype=np.float32) * scale,
        },
        "opt": {"step": int(scale), "lr": 0.1},
    }


# ---------------------------------------------------------------------------
# (b) dedup + diff
# ---------------------------------------------------------------------------


def test_incremental_save_dedup(tmp_path):
    store = ckpt.CheckpointStore(str(tmp_path), name="dedup")
    m1 = ckpt.save_checkpoint(store, _tree(1.0), step=1)
    assert m1.stats["bytes_reused"] == 0
    # second save: only w0 changes — w1 and the opt leaves dedup
    tree2 = _tree(1.0)
    tree2["layers"]["w0"][:] = 2.0
    m2 = ckpt.save_checkpoint(store, tree2, step=2)
    assert m2.parent == m1.ckpt_id
    assert m2.stats["chunks_written"] == 1  # just the new w0
    assert m2.stats["dedup_ratio"] > 0.45  # w1 is half the bytes
    diff = ckpt.diff_manifests(m1, m2)
    assert diff["changed_leaves"] == ["layers/w0"]
    assert diff["shared_bytes"] == m2.stats["bytes_reused"]
    # restore returns the new tree, exact (including non-array leaves)
    out = ckpt.restore_tree(store)
    np.testing.assert_array_equal(out["layers"]["w0"], tree2["layers"]["w0"])
    np.testing.assert_array_equal(out["layers"]["w1"], tree2["layers"]["w1"])
    assert out["opt"] == {"step": 1, "lr": 0.1}


def test_retention_keeps_pins_and_counts_drops(tmp_path):
    store = ckpt.CheckpointStore(str(tmp_path), name="ret")
    ids = [ckpt.save_checkpoint(store, _tree(float(i)), step=i).ckpt_id
           for i in range(5)]
    store.pin(ids[0])
    # grace_s=0: no save is in flight here, so GC may reap immediately
    # (the default grace window protects chunks of in-flight async saves)
    out = store.retention(keep_last=2, grace_s=0)
    assert out["dropped_manifests"] == 2  # ids[1], ids[2]
    assert out["dropped_chunks"] > 0
    left = store.list_ids()
    assert ids[0] in left and ids[3] in left and ids[4] in left
    assert ids[1] not in left and ids[2] not in left
    # pinned + survivors still restore after the chunk GC
    np.testing.assert_array_equal(
        ckpt.restore_tree(store, ids[0])["layers"]["w1"],
        _tree(0.0)["layers"]["w1"])
    assert store.stats()["drops"]["dropped_manifests"] == 2
    # a young orphan chunk (an in-flight save whose manifest has not
    # committed yet) survives a default-grace retention pass
    from ray_tpu.ckpt import manifest as mf

    h, created = mf.write_chunk(store.root, b"in-flight chunk bytes")
    assert created
    store.retention(keep_last=2)
    assert os.path.exists(mf.chunk_path(store.root, h))


# ---------------------------------------------------------------------------
# (c) crash mid-save: torn state never becomes latest
# ---------------------------------------------------------------------------


def test_crash_mid_save_latest_unmoved(tmp_path, monkeypatch):
    store = ckpt.CheckpointStore(str(tmp_path), name="torn")
    good = ckpt.save_checkpoint(store, _tree(1.0), step=1)
    assert store.latest_id() == good.ckpt_id

    # kill the saver between the chunk writes and the manifest commit
    import ray_tpu.ckpt.manifest as mf

    real_commit = mf.commit

    def _die(root, manifest):
        raise OSError("simulated crash before manifest rename")

    monkeypatch.setattr(mf, "commit", _die)
    saver = ckpt.CheckpointSaver(store)
    saver.save(_tree(2.0), step=2)
    with pytest.raises(RuntimeError, match="background checkpoint save"):
        saver.wait()
    monkeypatch.setattr(mf, "commit", real_commit)

    # the torn save is invisible: latest unchanged, restore = previous
    assert store.latest_id() == good.ckpt_id
    out = ckpt.restore_tree(store)
    np.testing.assert_array_equal(out["layers"]["w0"],
                                  _tree(1.0)["layers"]["w0"])

    # a literally torn manifest file (crashed mid-write without the atomic
    # helper) is skipped by listing AND by the LATEST pointer validation
    torn = os.path.join(store.root, "manifests", "stepzzz-torn.json")
    os.makedirs(os.path.dirname(torn), exist_ok=True)
    with open(torn, "w") as f:
        f.write('{"ckpt_id": "stepzzz-torn", "step":')  # truncated JSON
    mf.atomic_write(os.path.join(store.root, "LATEST"),
                    json.dumps({"ckpt_id": "stepzzz-torn"}).encode())
    assert store.latest_id() == good.ckpt_id  # pointer fell back
    assert "stepzzz-torn" not in store.list_ids()


# ---------------------------------------------------------------------------
# (a) async save overlaps the train loop
# ---------------------------------------------------------------------------


def test_async_save_overlaps_train_loop(tmp_path):
    n = 1 << 20  # 4 MiB per leaf: serialize+hash+write dwarfs the snapshot
    state = {"w": np.zeros(n, np.float32), "m": np.zeros(n, np.float32)}
    step_s = 0.12  # simulated step compute, the window writes overlap into

    def step(i):
        state["w"] += 1.0  # mutate in place: the snapshot must isolate
        state["m"] *= 0.9
        time.sleep(step_s)

    # blocking-save reference: step + full synchronous save per iteration
    # (state fully mutates between saves, so dedup cannot help either side)
    bstore = ckpt.CheckpointStore(str(tmp_path / "blocking"))
    saves = []
    t0 = time.perf_counter()
    for i in range(3):
        step(i)
        t1 = time.perf_counter()
        ckpt.save_checkpoint(bstore, state, step=i)
        saves.append(time.perf_counter() - t1)
    blocking_total = time.perf_counter() - t0
    blocking_save_s = sorted(saves)[1]  # median of 3

    state["w"][:] = 0.0  # fresh run for the async phase
    state["m"][:] = 0.0
    astore = ckpt.CheckpointStore(str(tmp_path / "async"))
    saver = ckpt.CheckpointSaver(astore)
    pauses = []
    overlapped = 0
    t0 = time.perf_counter()
    for i in range(3):
        step(i)
        t1 = time.perf_counter()
        saver.save(state, step=i)
        pauses.append(time.perf_counter() - t1)
        if saver.in_flight():
            overlapped += 1  # save() returned with the write still running
    manifest = saver.wait()
    async_total = time.perf_counter() - t0
    assert manifest is not None and astore.latest_id() == manifest.ckpt_id
    # the step-side pause is bounded: well under the blocking save cost
    assert sum(pauses) / len(pauses) < 0.6 * blocking_save_s, (
        pauses, blocking_save_s)
    assert overlapped >= 1
    # and the loop as a whole ran faster than with blocking saves: the
    # chunk writes overlapped the step compute instead of serializing
    assert async_total < blocking_total, (async_total, blocking_total)
    # in-place mutation after save() did not leak into the snapshot:
    # the final checkpoint is exactly the state at the last save point
    np.testing.assert_array_equal(
        ckpt.restore_tree(astore)["w"], np.full(n, 3.0, np.float32))
    assert manifest.stats["pause_s"] < manifest.stats["write_s"] + step_s


# ---------------------------------------------------------------------------
# (d) sharded save + restore onto a smaller mesh, no gather anywhere
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield ray_tpu
    ray_tpu.shutdown()


def _sharded_spec(num_hosts):
    mesh = MeshSpec((num_hosts,), ("data",),
                    tuple(f"rank{i}" for i in range(num_hosts)))
    return ShardedTreeSpec(
        mesh=mesh,
        parts={"opt/m": ("data", None), "opt/v": ("data", None)},
        meta={"opt/m": ((8, 4), "<f4"), "opt/v": ((8, 4), "<f4")})


def _global_tree():
    return {"opt/m": np.arange(32, dtype=np.float32).reshape(8, 4),
            "opt/v": np.arange(32, 64, dtype=np.float32).reshape(8, 4)}


@ray_tpu.remote(num_cpus=0.2)
class _SaveHost:
    """One host of the 4-mesh: holds ONLY its dim-0 shard."""

    def __init__(self, root, rank):
        self.store = ckpt.CheckpointStore(root, name="elastic")
        self.rank = rank
        self.spec = _sharded_spec(4)
        self.host = self.spec.mesh.hosts[rank]

    def save(self, cid):
        full = _global_tree()
        shards = {}
        for leaf in self.spec.meta:
            box = host_boxes(self.spec.mesh, self.spec.part_of(leaf),
                             self.spec.meta[leaf][0], self.host)[0]
            shards[leaf] = {box: full[leaf][box_slices(box)]}
        return ckpt.save_host_shards(self.store, cid, self.spec, self.host,
                                     shards, step=7)

    def commit(self, cid):
        man = ckpt.commit_host_parts(self.store, cid, self.spec, step=7)
        return man.ckpt_id


@ray_tpu.remote(num_cpus=0.2)
class _RestoreHost:
    def __init__(self, root, rank):
        self.store = ckpt.CheckpointStore(root, name="elastic")
        self.rank = rank
        self.spec = _sharded_spec(2)
        self.host = self.spec.mesh.hosts[rank]

    def restore(self, cid):
        shards, stats = ckpt.restore_shards(self.store, self.spec,
                                            self.host, cid)
        return ({leaf: {str(b): a for b, a in boxes.items()}
                 for leaf, boxes in shards.items()}, stats)


def test_elastic_4_to_2_restore_no_gather(cluster, tmp_path):
    root = str(tmp_path / "elastic")
    cid = ckpt.new_ckpt_id(7)
    savers = [_SaveHost.remote(root, i) for i in range(4)]
    ray_tpu.get([s.save.remote(cid) for s in savers], timeout=120)
    committed = ray_tpu.get(savers[0].commit.remote(cid), timeout=120)
    assert committed == cid

    store = ckpt.CheckpointStore(root)
    man = store.read(cid)
    # plan-level no-gather assertion BEFORE any byte moves
    plan = ckpt.restore_plan(man, _sharded_spec(2))
    assert plan.no_gather()
    full = _global_tree()
    assert plan.max_host_leaf_bytes("opt/m") < full["opt/m"].nbytes

    restorers = [_RestoreHost.remote(root, i) for i in range(2)]
    outs = ray_tpu.get([r.restore.remote(cid) for r in restorers],
                       timeout=120)
    for rank, (shards, stats) in enumerate(outs):
        assert stats["no_gather"]
        # each of the 2 hosts reads exactly its half of every leaf
        assert stats["bytes_read"] == sum(a.nbytes for a in full.values()) // 2
        for leaf, arr in full.items():
            box = f"(({rank * 4}, {rank * 4 + 4}), (0, 4))"
            np.testing.assert_array_equal(shards[leaf][box],
                                          arr[rank * 4:(rank + 1) * 4])
    for a in savers + restorers:
        ray_tpu.kill(a)


def test_commit_refuses_partial_sharded_save(tmp_path):
    store = ckpt.CheckpointStore(str(tmp_path), name="partial")
    spec = _sharded_spec(4)
    cid = ckpt.new_ckpt_id(1)
    full = _global_tree()
    # only 3 of 4 hosts land their shards
    for rank in range(3):
        host = spec.mesh.hosts[rank]
        shards = {}
        for leaf in spec.meta:
            box = host_boxes(spec.mesh, spec.part_of(leaf),
                             spec.meta[leaf][0], host)[0]
            shards[leaf] = {box: full[leaf][box_slices(box)]}
        ckpt.save_host_shards(store, cid, spec, host, shards)
    with pytest.raises(TimeoutError, match="refusing"):
        ckpt.commit_host_parts(store, cid, spec, timeout=0.3)
    assert store.latest_id() is None  # nothing became visible


# ---------------------------------------------------------------------------
# train wiring: manager over the plane, fallback past torn records
# ---------------------------------------------------------------------------


def test_train_manager_backed_by_plane_with_fallback(tmp_path):
    from ray_tpu.train.checkpoint import CheckpointManager

    run_dir = str(tmp_path / "run")
    mgr = CheckpointManager(run_dir, num_to_keep=2)
    for step in (1, 2):
        src = tmp_path / f"src{step}"
        src.mkdir()
        (src / "state.json").write_text(json.dumps({"step": step}))
        mgr.register(str(src), {"step": step})
    # storage is the plane: manifests + chunks, no copied staging dirs
    assert os.path.isdir(os.path.join(run_dir, "ckpts", "manifests"))
    latest = mgr.latest()
    with open(os.path.join(latest.as_directory(), "state.json")) as f:
        assert json.load(f)["step"] == 2
    # a record whose manifest never committed (saver died) falls back
    mgr.register_manifest("step0000000099-deadbeef", {"step": 99})
    t0 = time.perf_counter()
    latest = mgr.latest()
    assert latest is not None
    with open(os.path.join(latest.as_directory(), "state.json")) as f:
        assert json.load(f)["step"] == 2
    # the fallback is cheap the second time (materialized dir is cached)
    assert mgr.latest() is not None
    assert time.perf_counter() - t0 < 60


def test_train_manager_migrates_pre_plane_records(tmp_path):
    from ray_tpu.ckpt.manifest import atomic_write
    from ray_tpu.train.checkpoint import CheckpointManager

    run_dir = tmp_path / "legacy_run"
    ckpt_dir = run_dir / "checkpoint_000003"
    ckpt_dir.mkdir(parents=True)
    (ckpt_dir / "state.json").write_text(json.dumps({"step": 3}))
    atomic_write(str(run_dir / "checkpoint_manager.json"), json.dumps({
        "index": 3,
        "records": [{"path": str(ckpt_dir), "metrics": {"step": 3},
                     "time": 123.0}],  # pre-plane record shape
    }).encode())
    mgr = CheckpointManager(str(run_dir), num_to_keep=2)
    latest = mgr.latest()
    assert latest is not None
    with open(os.path.join(latest.as_directory(), "state.json")) as f:
        assert json.load(f)["step"] == 3
    # new registrations coexist with the migrated record
    src = tmp_path / "legacy_src"
    src.mkdir()
    (src / "state.json").write_text(json.dumps({"step": 4}))
    mgr.register(str(src), {"step": 4})
    with open(os.path.join(mgr.latest().as_directory(), "state.json")) as f:
        assert json.load(f)["step"] == 4


# ---------------------------------------------------------------------------
# tune wiring: PBT exploit swaps manifest refs, not pickled trees
# ---------------------------------------------------------------------------


def test_tune_checkpoint_ref_roundtrip(tmp_path):
    from ray_tpu.tune import tuner as tuner_mod

    tuner_mod._session.ckpt_root = str(tmp_path / "tune")
    try:
        ref = tuner_mod._save_trial_checkpoint({"progress": 0.25,
                                                "w": np.ones(4, np.float32)})
        assert set(ref) == {"__ckpt_ref__", "root"}  # tiny, no tree inside
        # saving the same state again dedups to the same chunks
        ref2 = tuner_mod._save_trial_checkpoint({"progress": 0.25,
                                                 "w": np.ones(4, np.float32)})
        store = ckpt.CheckpointStore(ref["root"])
        m2 = store.read(ref2["__ckpt_ref__"])
        assert m2.stats["chunks_written"] == 0  # 100% dedup
        cfg = tuner_mod._resolve_checkpoint_ref(
            {"lr": 0.1, "__checkpoint__": ref})
        assert cfg["__checkpoint__"]["progress"] == 0.25
        np.testing.assert_array_equal(cfg["__checkpoint__"]["w"],
                                      np.ones(4, np.float32))
        # a plain (non-ref) checkpoint value passes through untouched
        passthru = tuner_mod._resolve_checkpoint_ref(
            {"__checkpoint__": {"progress": 1.0}})
        assert passthru["__checkpoint__"] == {"progress": 1.0}
    finally:
        tuner_mod._session.ckpt_root = None


# ---------------------------------------------------------------------------
# GCS registration: state API surface
# ---------------------------------------------------------------------------


def test_list_checkpoints_state_api(cluster, tmp_path):
    from ray_tpu.util.state import list_checkpoints

    store = ckpt.CheckpointStore(str(tmp_path / "reg"), name="reg_test")
    ckpt.save_checkpoint(store, {"w": np.ones(8, np.float32)}, step=3)
    store.pin(store.latest_id())
    out = list_checkpoints()
    assert "reg_test" in out
    entry = out["reg_test"]
    assert entry["latest"] == store.latest_id()
    assert entry["pinned"] == [store.latest_id()]
    assert entry["num_checkpoints"] == 1
    assert entry["checkpoints"][0]["step"] == 3


# ---------------------------------------------------------------------------
# satellite: per-task arg/returned byte accounting on task events
# ---------------------------------------------------------------------------


def test_task_summary_object_bytes(cluster):
    from ray_tpu.util import state

    @ray_tpu.remote
    def _echo(blob):
        return blob + blob

    payload = b"x" * 4096
    out = ray_tpu.get(_echo.remote(payload), timeout=60)
    assert len(out) == 2 * len(payload)
    deadline = time.time() + 30
    sizes = {}
    while time.time() < deadline:
        summ = state.summarize_tasks()
        sizes = {fn: v for fn, v in summ.get(
            "per_function_bytes", {}).items() if "_echo" in fn}
        if sizes and next(iter(sizes.values()))["ret_bytes"]:
            break
        time.sleep(0.5)
    assert sizes, "echo task never surfaced in the summary"
    entry = next(iter(sizes.values()))
    assert entry["arg_bytes"] >= len(payload)
    assert entry["ret_bytes"] >= 2 * len(payload)

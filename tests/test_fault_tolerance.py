"""Fault tolerance: task retries, actor restarts, node failure.

Reference tier: python/ray/tests/test_failure*.py + chaos tests (SURVEY.md §4)
driven through the cluster_utils harness.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu.exceptions import TaskError


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


def test_task_retry_on_worker_death(cluster):
    @ray_tpu.remote(max_retries=3)
    def die_once(path):
        # first attempt kills its worker; the retry succeeds
        if not os.path.exists(path):
            open(path, "w").close()
            os._exit(1)
        return "survived"

    marker = f"/tmp/ray_tpu_die_once_{time.time()}"
    try:
        assert ray_tpu.get(die_once.remote(marker), timeout=120) == "survived"
    finally:
        if os.path.exists(marker):
            os.unlink(marker)


def test_task_no_retry_exhausted(cluster):
    @ray_tpu.remote(max_retries=1)
    def always_dies():
        os._exit(1)

    with pytest.raises(TaskError, match="worker died"):
        ray_tpu.get(always_dies.remote(), timeout=120)


def test_actor_restart(cluster):
    @ray_tpu.remote(max_restarts=2)
    class Flaky:
        def __init__(self):
            self.count = 0

        def crash(self):
            os._exit(1)

        def ping(self):
            self.count += 1
            return self.count

    a = Flaky.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == 1
    with pytest.raises(TaskError):
        ray_tpu.get(a.crash.remote(), timeout=60)  # kills the actor process
    # actor restarts (state resets) and serves again
    deadline = time.time() + 60
    value = None
    while time.time() < deadline:
        try:
            value = ray_tpu.get(a.ping.remote(), timeout=30)
            break
        except TaskError:
            time.sleep(0.5)
    assert value == 1  # fresh instance after restart


def test_actor_dead_after_max_restarts(cluster):
    @ray_tpu.remote(max_restarts=0)
    class Fragile:
        def go(self):
            os._exit(1)

    a = Fragile.remote()
    a.go.remote()
    time.sleep(1.0)
    with pytest.raises(TaskError, match="(?i)actor"):
        ray_tpu.get(a.go.remote(), timeout=60)


def test_kill_actor(cluster):
    @ray_tpu.remote
    class Victim:
        def ping(self):
            return "ok"

    a = Victim.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "ok"
    ray_tpu.kill(a)
    time.sleep(0.5)
    with pytest.raises(TaskError, match="(?i)actor"):
        ray_tpu.get(a.ping.remote(), timeout=60)

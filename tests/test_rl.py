"""RL smoke tests: PPO on CartPole improves (reference tier: rllib
tuned_examples run-to-reward, shrunk for CI)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import PPO, PPOConfig


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


def test_ppo_cartpole_improves(cluster):
    algo = PPOConfig(
        env="CartPole-v1",
        num_env_runners=2,
        num_envs_per_runner=4,
        rollout_length=128,
        epochs=8,
        seed=1,
    ).build()
    first = algo.train()
    assert first["num_env_steps_sampled"] == 2 * 4 * 128
    returns = []
    for _ in range(20):
        m = algo.train()
        returns.append(m["episode_return_mean"])
    algo.stop()
    # CartPole random play ~ 20; PPO must clearly improve within ~20k steps
    assert max(returns) > 60, returns

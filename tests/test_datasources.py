"""TFRecord / WebDataset / SQL / HuggingFace datasources (reference:
python/ray/data/datasource/{tfrecords,webdataset,sql}_datasource.py)."""

import sqlite3

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata
from ray_tpu.data.datasources import (decode_example, encode_example,
                                      write_tfrecords, write_webdataset)


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(resources={"CPU": 4.0})
    yield
    ray_tpu.shutdown()


def test_example_proto_roundtrip():
    row = {"label": 3, "weights": [0.5, 1.5], "name": b"cat",
           "ids": [1, 2, 300000]}
    out = decode_example(encode_example(row))
    assert out["label"] == 3
    assert out["ids"] == [1, 2, 300000]
    assert out["name"] == b"cat"
    assert out["weights"] == pytest.approx([0.5, 1.5])


def test_read_tfrecords(cluster, tmp_path):
    rows = [{"i": i, "x": float(i) / 2, "tag": f"r{i}".encode()}
            for i in range(20)]
    write_tfrecords(rows[:10], str(tmp_path / "a.tfrecords"))
    write_tfrecords(rows[10:], str(tmp_path / "b.tfrecords"))
    ds = rdata.read_tfrecords(str(tmp_path))
    out = sorted(ds.take_all(), key=lambda r: r["i"])
    assert len(out) == 20
    assert out[5]["tag"] == b"r5"
    assert out[7]["x"] == pytest.approx(3.5)


@pytest.mark.skipif(
    __import__("importlib").util.find_spec("tensorflow") is None,
    reason="tensorflow not in image")
def test_tfrecords_tensorflow_compat(tmp_path):
    import tensorflow as tf

    write_tfrecords([{"v": 7}], str(tmp_path / "c.tfrecords"))
    recs = list(tf.data.TFRecordDataset(str(tmp_path / "c.tfrecords")))
    ex = tf.train.Example.FromString(recs[0].numpy())
    assert ex.features.feature["v"].int64_list.value[0] == 7


def test_read_webdataset(cluster, tmp_path):
    rows = [{"__key__": f"s{i:03d}", "txt": f"caption {i}",
             "bin": bytes([i] * 4)} for i in range(6)]
    write_webdataset(rows[:3], str(tmp_path / "shard0.tar"))
    write_webdataset(rows[3:], str(tmp_path / "shard1.tar"))
    ds = rdata.read_webdataset(str(tmp_path))
    out = sorted(ds.take_all(), key=lambda r: r["__key__"])
    assert len(out) == 6
    assert out[2]["txt"] == "caption 2"
    assert out[4]["bin"] == bytes([4] * 4)


def test_read_sql(cluster, tmp_path):
    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE pets (name TEXT, age INT)")
    conn.executemany("INSERT INTO pets VALUES (?, ?)",
                     [("rex", 3), ("ada", 7), ("bo", 1)])
    conn.commit()
    conn.close()
    ds = rdata.read_sql("SELECT name, age FROM pets WHERE age > 2",
                        lambda: sqlite3.connect(db))
    out = sorted(ds.take_all(), key=lambda r: r["name"])
    assert out == [{"name": "ada", "age": 7}, {"name": "rex", "age": 3}]


def test_from_huggingface(cluster):
    datasets = pytest.importorskip("datasets")
    hf = datasets.Dataset.from_dict(
        {"text": [f"t{i}" for i in range(8)], "label": list(range(8))})
    ds = rdata.from_huggingface(hf)
    out = sorted(ds.take_all(), key=lambda r: r["label"])
    assert len(out) == 8 and out[3]["text"] == "t3"

"""Autoscaler v2 instance lifecycle (reference:
python/ray/autoscaler/v2/instance_manager/ + its unit tests)."""

import pytest

from ray_tpu.autoscaler.config import NodeTypeConfig
from ray_tpu.autoscaler.instance_manager import (ALLOCATED,
                                                 ALLOCATION_FAILED, QUEUED,
                                                 RAY_RUNNING, RAY_STOPPING,
                                                 REQUESTED, TERMINATED,
                                                 TERMINATING,
                                                 InstanceManager,
                                                 InvalidTransition)


class FakeProvider:
    """Synchronous fake with controllable failures."""

    def __init__(self, fail_launches: int = 0):
        self.nodes = {}
        self._n = 0
        self.fail_launches = fail_launches
        self.terminated = []

    def create_nodes(self, node_type, count):
        if self.fail_launches > 0:
            self.fail_launches -= 1
            raise RuntimeError("quota exceeded")
        out = []
        for _ in range(count):
            self._n += 1

            class N:
                pass

            n = N()
            n.node_id = f"prov-{self._n}"
            n.node_type = getattr(node_type, "name", "cpu")
            n.slice_name = ""
            self.nodes[n.node_id] = n
            out.append(n)
        return out

    def terminate_node(self, node):
        self.nodes.pop(node.node_id, None)
        self.terminated.append(node.node_id)

    def non_terminated_nodes(self):
        return list(self.nodes.values())


def _types():
    return {"cpu": NodeTypeConfig(name="cpu", resources={"CPU": 4.0},
                                  min_workers=0, max_workers=10)}


def _gcs_view(provider, alive=True):
    from ray_tpu.autoscaler.autoscaler import PROVIDER_ID_LABEL

    return [{"node_id": f"gcs-{pid}", "alive": alive,
             "labels": {PROVIDER_ID_LABEL: pid}}
            for pid in provider.nodes]


def test_full_lifecycle_to_running():
    im = InstanceManager()
    prov = FakeProvider()
    im.set_targets({"cpu": 2})
    assert len(im.by_state(QUEUED)) == 2
    im.step(prov, _types())  # launch -> ALLOCATED (sync provider)
    assert len(im.by_state(ALLOCATED)) == 2
    im.step(prov, _types(), gcs_nodes=_gcs_view(prov))
    assert len(im.by_state(RAY_RUNNING)) == 2
    assert all(i.raylet_node_id for i in im.by_state(RAY_RUNNING))


def test_allocation_failure_retries_with_backoff_then_gives_up():
    im = InstanceManager(max_allocation_retries=2, retry_backoff_s=0.0)
    prov = FakeProvider(fail_launches=99)
    im.set_targets({"cpu": 1})
    for _ in range(1 + 2 * 2 + 2):  # enough passes for 2 retries + give-up
        im.step(prov, _types())
    assert im.instances == {}  # gave up -> TERMINATED and forgotten
    assert prov.nodes == {}


def test_retry_succeeds_after_transient_failure():
    im = InstanceManager(max_allocation_retries=3, retry_backoff_s=0.0)
    prov = FakeProvider(fail_launches=1)
    im.set_targets({"cpu": 1})
    im.step(prov, _types())  # fails -> ALLOCATION_FAILED
    im.step(prov, _types())  # requeued
    im.step(prov, _types())  # relaunched ok
    assert len(im.by_state(ALLOCATED)) == 1
    inst = im.by_state(ALLOCATED)[0]
    assert inst.retries == 1


def test_stuck_allocated_instance_terminated():
    im = InstanceManager(ray_start_timeout_s=0.0)
    prov = FakeProvider()
    im.set_targets({"cpu": 1})
    im.step(prov, _types())
    assert len(im.by_state(ALLOCATED)) == 1
    # no gcs registration ever arrives; next pass times it out
    im.step(prov, _types(), gcs_nodes=[])
    im.step(prov, _types(), gcs_nodes=[])
    assert prov.terminated, "stuck instance should be terminated"
    assert im.instances == {}


def test_scale_down_drains_running_instances():
    im = InstanceManager()
    prov = FakeProvider()
    im.set_targets({"cpu": 2})
    im.step(prov, _types())
    im.step(prov, _types(), gcs_nodes=_gcs_view(prov))
    assert len(im.by_state(RAY_RUNNING)) == 2
    drained = []
    im.set_targets({"cpu": 1})
    assert len(im.by_state(RAY_STOPPING)) == 1
    im.step(prov, _types(), gcs_nodes=_gcs_view(prov),
            drain=lambda nid: drained.append(nid))
    im.step(prov, _types(), gcs_nodes=_gcs_view(prov))
    assert drained and len(prov.terminated) == 1
    assert im.active_count("cpu") == 1


def test_dead_node_detected_and_cleaned():
    im = InstanceManager()
    prov = FakeProvider()
    im.set_targets({"cpu": 1})
    im.step(prov, _types())
    im.step(prov, _types(), gcs_nodes=_gcs_view(prov))
    assert len(im.by_state(RAY_RUNNING)) == 1
    im.step(prov, _types(), gcs_nodes=_gcs_view(prov, alive=False))
    assert len(im.by_state(TERMINATING)) + len(prov.terminated) >= 1


def test_persistence_roundtrip():
    store = {}
    im = InstanceManager(store=store)
    prov = FakeProvider()
    im.set_targets({"cpu": 2})
    im.step(prov, _types())
    assert len(store) == 2
    # a restarted manager resumes the same instances (no double-launch)
    im2 = InstanceManager(store=store)
    assert im2.active_count("cpu") == 2
    assert len(im2.by_state(ALLOCATED)) == 2
    im2.step(prov, _types(), gcs_nodes=_gcs_view(prov))
    assert len(im2.by_state(RAY_RUNNING)) == 2
    assert len(prov.nodes) == 2  # never launched extras


def test_invalid_transition_rejected():
    im = InstanceManager()
    inst = im.add("cpu")
    with pytest.raises(InvalidTransition):
        im.transition(inst, RAY_RUNNING)  # QUEUED cannot jump to RUNNING


def test_scale_down_sheds_allocated_before_running():
    im = InstanceManager()
    prov = FakeProvider()
    im.set_targets({"cpu": 2})
    im.step(prov, _types())  # both ALLOCATED
    im.set_targets({"cpu": 1})
    assert len(im.by_state(TERMINATING)) == 1  # ALLOCATED shed immediately
    im.step(prov, _types())
    assert im.active_count("cpu") == 1 and len(prov.terminated) == 1


def test_async_provider_node_adopted_not_leaked():
    """A provider that provisions asynchronously (create_nodes returns [])
    must have its late node adopted by the REQUESTED instance instead of
    leaking it and double-launching."""
    im = InstanceManager(request_timeout_s=3600.0)
    prov = FakeProvider()

    real_create = prov.create_nodes

    def async_create(node_type, count):
        real_create(node_type, count)  # provisions, but reports nothing
        return []

    prov.create_nodes = async_create
    im.set_targets({"cpu": 1})
    im.step(prov, _types())  # REQUESTED, no provider_node_id yet
    assert len(im.by_state(REQUESTED)) == 1
    im.step(prov, _types())  # adopts the orphan from the provider view
    assert len(im.by_state(ALLOCATED)) == 1
    assert im.by_state(ALLOCATED)[0].provider_node_id
    assert len(prov.nodes) == 1  # no double-launch


def test_vanished_node_detected_after_grace():
    im = InstanceManager(request_timeout_s=0.0)
    prov = FakeProvider()
    im.set_targets({"cpu": 1})
    im.step(prov, _types())
    im.step(prov, _types(), gcs_nodes=_gcs_view(prov))
    assert len(im.by_state(RAY_RUNNING)) == 1
    # the node's GCS entry disappears entirely (evicted/tombstoned)
    import time as _t

    _t.sleep(0.01)
    im.step(prov, _types(), gcs_nodes=[])
    # detected, drained through TERMINATING, and (same pass) the provider
    # node was reclaimed
    assert prov.terminated, "vanished node should be reclaimed"
    assert not im.by_state(RAY_RUNNING)

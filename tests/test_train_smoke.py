"""Tier-1 smoke: one bucketed + sharded-update train step on the CPU mesh.

Runs ``tools.bench_train.bench_step_flavors`` (the same callable the
overlap microbench CLI uses) under ``JAX_PLATFORMS=cpu`` so the sharded
step, the split programs, and the traced bucketed pipeline cannot rot
between BENCH rounds — if any flavor stops compiling or diverges, this
fails in CI rather than in the next bench round on hardware.
"""

import numpy as np


def test_bench_train_step_flavors_smoke():
    from tools.bench_train import bench_step_flavors

    out = bench_step_flavors(bucket_bytes=64 << 10, steps=1, warmup=0)
    assert out["n_devices"] == 8  # conftest's forced CPU mesh
    for key in ("fused_step_us", "fused_sharded_step_us",
                "split_sharded_step_us", "traced_sharded_step_us"):
        assert key in out and np.isfinite(out[key]) and out[key] > 0
    assert out["opt_state_bytes_per_replica"] < out["opt_state_bytes_total"] / 4
    plan = out["bucket_plan"]
    assert plan["num_buckets"] >= 1
    assert plan["total_bytes"] > 0

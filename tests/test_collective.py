"""Collective tests: CPU store tier across actor processes + XLA tier on the
virtual 8-device mesh (reference: util/collective/tests/* CPU tiers,
SURVEY.md §4)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.collective.types import ReduceOp


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=6)
    yield ray_tpu
    ray_tpu.shutdown()


@ray_tpu.remote(num_cpus=1)
class Peer:
    def __init__(self, rank, world):
        self.rank = rank
        self.world = world

    def _init_collective(self, world_size, rank, backend, group_name):
        from ray_tpu import collective as col

        col.init_collective_group(world_size, rank, backend, group_name)
        return True

    def do_allreduce(self):
        from ray_tpu import collective as col

        out = col.allreduce(np.full((4,), float(self.rank + 1)))
        return out

    def do_broadcast(self):
        from ray_tpu import collective as col

        return col.broadcast(np.full((3,), float(self.rank)), src_rank=1)

    def do_allgather(self):
        from ray_tpu import collective as col

        return col.allgather(np.array([self.rank]))

    def do_reducescatter(self):
        from ray_tpu import collective as col

        return col.reducescatter(np.arange(4, dtype=np.float64))

    def do_sendrecv(self):
        from ray_tpu import collective as col

        if self.rank == 0:
            col.send(np.array([42.0]), dst_rank=1)
            return None
        return col.recv(src_rank=0)


def test_cpu_collective_ops(cluster):
    from ray_tpu import collective as col

    world = 2
    peers = [Peer.remote(r, world) for r in range(world)]
    col.create_collective_group(peers, world, list(range(world)), backend="cpu")

    out = ray_tpu.get([p.do_allreduce.remote() for p in peers], timeout=120)
    np.testing.assert_allclose(out[0], np.full((4,), 3.0))
    np.testing.assert_allclose(out[1], np.full((4,), 3.0))

    out = ray_tpu.get([p.do_broadcast.remote() for p in peers], timeout=120)
    np.testing.assert_allclose(out[0], np.full((3,), 1.0))

    out = ray_tpu.get([p.do_allgather.remote() for p in peers], timeout=120)
    assert [int(x[0]) for x in out[0]] == [0, 1]

    out = ray_tpu.get([p.do_reducescatter.remote() for p in peers], timeout=120)
    np.testing.assert_allclose(out[0], np.array([0.0, 2.0]))
    np.testing.assert_allclose(out[1], np.array([4.0, 6.0]))

    out = ray_tpu.get([p.do_sendrecv.remote() for p in peers], timeout=120)
    np.testing.assert_allclose(out[1], np.array([42.0]))

    for p in peers:
        ray_tpu.kill(p)


def test_xla_group_single_process():
    """XLA backend over the virtual 8-device CPU mesh: ops lower to XLA
    collectives exactly as they would over ICI."""
    from ray_tpu.collective.collective_group import XlaGroup

    import jax

    group = XlaGroup("g", world_size=8, rank=0)
    x = np.arange(8, dtype=np.float32)

    out = group.allreduce(x)
    np.testing.assert_allclose(np.asarray(out), np.full((8,), 28.0))

    out = group.allreduce(x, ReduceOp.MAX)
    np.testing.assert_allclose(np.asarray(out), np.full((8,), 7.0))

    # axis-0 chunks are per-member contributions (same convention as the
    # sibling ops): 8 members each contribute ones(8); member i receives
    # element i of the summed chunk
    rs = group.reducescatter(np.ones((64,), np.float32))
    np.testing.assert_allclose(np.asarray(rs), np.full((8,), 8.0))
    with np.testing.assert_raises(ValueError):
        group.reducescatter(np.ones((8,), np.float32))

    bc = group.broadcast(np.arange(8, dtype=np.float32), src_rank=3)
    np.testing.assert_allclose(np.asarray(bc), np.full((8,), 3.0))

    perm = [(i, (i + 1) % 8) for i in range(8)]
    pp = group.ppermute(np.arange(8, dtype=np.float32), perm)
    np.testing.assert_allclose(np.asarray(pp), np.roll(np.arange(8), 1))

    # global (64,): member d holds [8d, 8d+8); all-to-all transposes blocks
    a2a = np.asarray(group.alltoall(np.arange(64, dtype=np.float32)))
    expect = np.arange(64).reshape(8, 8).T.reshape(-1)
    np.testing.assert_allclose(a2a, expect)

    ag = np.asarray(group.allgather(np.arange(8, dtype=np.float32)))
    np.testing.assert_allclose(ag[:8], np.arange(8.0))
    assert ag.shape == (64,)


def test_xla_group_multi_worker_spmd():
    """Multi-controller simulation on the CPU tier: N worker actors each
    init an XLA-backend collective group over their own virtual 8-device
    mesh and run the SAME shard_map collective program — every controller
    must compute the identical result (the single-host analog of SPMD over
    ICI, where each host executes the same lowered program)."""
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    try:
        @ray_tpu.remote(num_cpus=0.5)
        class SpmdWorker:
            def __init__(self, rank, world):
                import ray_tpu.collective as col

                col.init_collective_group(world, rank, backend="xla",
                                          group_name="spmd")
                self.rank = rank

            def gradient_sync(self):
                """The dp gradient-sync pattern: allreduce(AVERAGE) of a
                sharded gradient, then reducescatter for the fsdp flavor."""
                import numpy as np
                import ray_tpu.collective as col

                grad = np.arange(8, dtype=np.float32)
                avg = np.asarray(col.allreduce(grad, op=ReduceOp.AVERAGE,
                                               group_name="spmd"))
                rs = np.asarray(col.reducescatter(
                    np.ones((64,), np.float32), group_name="spmd"))
                ag = np.asarray(col.allgather(
                    np.full((8,), float(3), np.float32), group_name="spmd"))
                return avg.tolist(), rs.tolist(), ag.shape

        world = 2
        workers = [SpmdWorker.remote(r, world) for r in range(world)]
        outs = ray_tpu.get([w.gradient_sync.remote() for w in workers],
                           timeout=300)
        # every controller computed the same collective results
        assert outs[0] == outs[1]
        avg, rs, ag_shape = outs[0]
        assert rs == [8.0] * 8  # psum_scatter of ones over 8 devices
        for w in workers:
            ray_tpu.kill(w)
    finally:
        ray_tpu.shutdown()

"""Typed wire schema + versioned framing (reference: src/ray/protobuf/*.proto).

The control plane must never unpickle network input: payloads are strict
msgpack over an explicit struct registry, and every frame carries the wire
protocol version.
"""

import asyncio
import os
import pickle

import pytest

from ray_tpu._private import wire
from ray_tpu._private.common import (Bundle, NodeInfo, PlacementGroupSpec,
                                     TaskOptions, TaskSpec)
from ray_tpu._private.ids import JobID, NodeID, ObjectID, PlacementGroupID, TaskID
from ray_tpu._private.rpc import RpcServer, RpcClient, RpcVersionError


def test_struct_roundtrip():
    jid = JobID.from_int(7)
    spec = TaskSpec(
        task_id=TaskID.of(jid), job_id=jid, function_key="fn",
        args_blob=b"\x00blob", num_returns=2,
        options=TaskOptions(num_cpus=2.0, resources={"TPU": 1.0},
                            label_selector={"k": "v"}))
    pg = PlacementGroupSpec(
        pg_id=PlacementGroupID.from_random(),
        bundles=[Bundle(resources={"CPU": 1.0})], strategy="SPREAD")
    node = NodeInfo(node_id=NodeID.from_random(), address="a:1",
                    object_store_address="b:2", total_resources={"CPU": 4.0})
    msg = {"spec": spec, "pg": pg, "node": node,
           "oids": [ObjectID.for_task_return(spec.task_id, 0)],
           "seen": {1, 2, 3}, "blob": b"raw", "n": None}
    out = wire.loads(wire.dumps(msg))
    assert out["spec"] == spec
    assert out["pg"] == pg
    assert out["node"] == node
    assert out["oids"][0] == ObjectID.for_task_return(spec.task_id, 0)
    assert out["seen"] == {1, 2, 3}
    assert out["blob"] == b"raw" and out["n"] is None


def test_numpy_roundtrip():
    import numpy as np

    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    out = wire.loads(wire.dumps({"a": a}))
    assert out["a"].dtype == np.float32 and (out["a"] == a).all()


def test_unregistered_type_rejected():
    class Private:
        pass

    with pytest.raises(wire.WireError):
        wire.dumps({"x": Private()})


def test_pickle_payload_never_executed(tmp_path):
    """A pickle blob fed to wire.loads must raise without running its reducer."""
    marker = tmp_path / "pwned"

    class Evil:
        def __reduce__(self):
            return (os.system, (f"touch {marker}",))

    blob = pickle.dumps(Evil())
    with pytest.raises(wire.WireError):
        wire.loads(blob)
    assert not marker.exists()


def test_forward_compat_unknown_field_dropped():
    # simulate a newer sender adding a field: decode drops it, keeps the rest
    import msgpack

    payload = msgpack.packb(
        ["Bundle", {"resources": {"CPU": 1.0}, "label_selector": {},
                    "field_from_the_future": 42}], use_bin_type=True)
    ext = msgpack.ExtType(1, payload)
    out = wire.loads(msgpack.packb(ext))
    assert isinstance(out, Bundle) and out.resources == {"CPU": 1.0}


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_unversioned_frame_rejected():
    """A legacy 4-element (no version) frame drops the connection; a versioned
    client on the same server still works."""

    async def main():
        import msgpack

        async def handler(method, payload, conn):
            return wire.dumps({"ok": True})

        server = RpcServer(handler)
        addr = await server.start()
        host, _, port = addr.rpartition(":")

        # raw legacy frame: [msg_id, kind, method, payload] without version
        reader, writer = await asyncio.open_connection(host, int(port))
        body = msgpack.packb([1, 0, "Ping", b""], use_bin_type=True)
        writer.write(len(body).to_bytes(4, "big") + body)
        await writer.drain()
        got = await reader.read(1)  # server must close, not answer
        assert got == b""
        writer.close()

        # wrong version number is rejected the same way
        reader, writer = await asyncio.open_connection(host, int(port))
        body = msgpack.packb([999, 1, 0, "Ping", b""], use_bin_type=True)
        writer.write(len(body).to_bytes(4, "big") + body)
        await writer.drain()
        assert await reader.read(1) == b""
        writer.close()

        # a real client still round-trips
        client = await RpcClient(addr).connect()
        reply = wire.loads(await client.call("Ping", wire.dumps({})))
        assert reply == {"ok": True}
        await client.close()
        await server.stop()

    _run(main())


def test_client_rejects_bad_server_version(monkeypatch):
    """Client-side: a reply frame with the wrong version fails pending calls
    with RpcVersionError (not a retryable connection error)."""

    async def main():
        import msgpack

        async def on_client(reader, writer):
            await reader.read(64)  # swallow the request
            body = msgpack.packb([999, 1, 1, "", b""], use_bin_type=True)
            writer.write(len(body).to_bytes(4, "big") + body)
            await writer.drain()

        server = await asyncio.start_server(on_client, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        client = await RpcClient(f"127.0.0.1:{port}").connect()
        with pytest.raises(RpcVersionError):
            await client.call("Ping", b"", timeout=5.0)
        await client.close()
        server.close()

    _run(main())

"""Core-throughput floors: catch order-of-magnitude regressions in the
submit/execute/object paths (reference: release/microbenchmark tracking of
ray_perf.py numbers). Floors sit far below measured best-of (see
MICROBENCH_r04.json) because CI hosts are noisy single-core VMs — this
guards against wedged batching/scheduling, not run-to-run variance.
"""

import pytest

import ray_tpu
from ray_tpu._private import microbenchmark


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield ray_tpu
    ray_tpu.shutdown()


# calibrated for the WORST case — mid-full-suite on a saturated 1-core CI
# host (measured ~4x below standalone best-of): these floors catch a
# wedged submit/execute path (the round-3 deadlock measured ~0), not noise
FLOORS = {
    "tasks_async_batch_per_s": 250.0,
    "tasks_pipeline1k_per_s": 400.0,
    "actor_calls_async_batch_per_s": 700.0,
    "put_small_per_s": 1200.0,
}


def test_core_throughput_floors(cluster):
    results = {r["name"]: r for r in microbenchmark.main(duration=1.5)}
    failures = []
    for name, floor in FLOORS.items():
        rate = results[name]["rate_per_s"]
        if rate < floor:
            failures.append(f"{name}: {rate:.0f}/s < floor {floor:.0f}/s")
    assert not failures, "; ".join(failures)
    # object plane bandwidth (10MB roundtrips)
    gbs = results["put_get_10MB_roundtrips_per_s"]["GB_per_s"]
    assert gbs >= 0.4, f"object plane bandwidth {gbs} GB/s below floor"

"""Seal-once / execution-epoch fencing of the object plane.

Reproduces the duplicate-execution race (a zombie task attempt whose reply
was lost keeps running and writes its result while the owner's retry writes
the same object id) and verifies the fix: attempt-fenced stores, a
max-attempt location directory, and self-healing deletion of displaced
copies. Reference semantics: plasma's seal-once object lifecycle
(src/ray/object_manager/plasma/obj_lifecycle_mgr.cc).
"""

import asyncio
import os
import pickle
import time

import numpy as np
import pytest

from ray_tpu._private import wire
import ray_tpu
from ray_tpu._private.object_store import ObjectStoreServer


# ---------------------------------------------------------------------------
# unit tier: store-level attempt fencing
# ---------------------------------------------------------------------------


@pytest.fixture
def store(tmp_path):
    s = ObjectStoreServer("deadbeef" * 4, capacity=1 << 20,
                          spill_dir=str(tmp_path))
    yield s
    s.shutdown()


def _write(store, oid, payload, attempt):
    reply = store.create(oid, len(payload), attempt)
    if reply["status"] != "ok":
        return reply
    if "shm_name" in reply:
        from ray_tpu._private.object_store import ShmSegment

        seg = ShmSegment(reply["shm_name"])
        try:
            seg.buf[: len(payload)] = payload
        finally:
            seg.close()
    else:
        from ray_tpu._private.object_store import ShmSegment

        seg = ShmSegment(reply["arena_name"])
        try:
            off = reply["offset"]
            seg.buf[off : off + len(payload)] = payload
        finally:
            seg.close()
    store.seal(oid, attempt)
    return reply


def _read(store, oid):
    from ray_tpu._private.object_store import ShmSegment

    acc = store.access(oid)
    if acc["status"] == "inline":
        return acc["blob"]
    if acc["status"] == "shm_arena":
        seg = ShmSegment(acc["arena_name"])
        try:
            return bytes(seg.buf[acc["offset"] : acc["offset"] + acc["size"]])
        finally:
            seg.close()
    seg = ShmSegment(acc["shm_name"])
    try:
        return bytes(seg.buf[: acc["size"]])
    finally:
        seg.close()


def test_newer_attempt_displaces_stale_copy(store):
    oid = os.urandom(16)
    _write(store, oid, b"A" * 256, attempt=0)
    _write(store, oid, b"B" * 300, attempt=1)
    assert store.object_attempt(oid) == 1
    assert _read(store, oid) == b"B" * 300


def test_stale_writer_is_fenced(store):
    oid = os.urandom(16)
    _write(store, oid, b"B" * 300, attempt=1)
    reply = store.create(oid, 256, 0)  # zombie arrives late
    assert reply["status"] == "stale_attempt"
    assert _read(store, oid) == b"B" * 300


def test_stale_seal_ignored(store):
    """A zombie that created before the retry displaced it must not be able
    to seal (and wake readers onto) the replacement entry."""
    oid = os.urandom(16)
    created = store.create(oid, 256, 0)
    assert created["status"] == "ok"  # zombie mid-write
    _write(store, oid, b"B" * 300, attempt=1)
    assert store.seal(oid, 0) is False  # zombie's seal: fenced
    assert store.object_attempt(oid) == 1
    assert _read(store, oid) == b"B" * 300


def test_same_attempt_create_is_idempotent(store):
    oid = os.urandom(16)
    _write(store, oid, b"A" * 256, attempt=2)
    reply = store.create(oid, 256, 2)
    assert reply["status"] == "exists"


def test_put_inline_attempt_rules(store):
    oid = os.urandom(16)
    store.put_inline(oid, b"old", attempt=0)
    store.put_inline(oid, b"new", attempt=1)
    assert store.access(oid)["blob"] == b"new"
    store.put_inline(oid, b"zombie", attempt=0)  # late zombie: ignored
    assert store.access(oid)["blob"] == b"new"


def test_stale_write_chunk_fenced(store):
    oid = os.urandom(16)
    store.create(oid, 64, 0)
    _write(store, oid, b"B" * 300, attempt=1)
    with pytest.raises(KeyError):
        store.write_chunk(oid, 0, b"Z" * 8, attempt=0)


# ---------------------------------------------------------------------------
# integration tier: zombie task execution (reply-dropped PushTask)
# ---------------------------------------------------------------------------


@pytest.fixture
def zombie_cluster():
    ray_tpu.shutdown()
    # every worker's FIRST PushTask executes fully but the reply connection
    # drops — the owner retries, producing a duplicate execution racing the
    # zombie's store writes
    os.environ["RAY_TPU_TESTING_RPC_REPLY_FAILURE"] = "PushTask=1:0"
    try:
        ray_tpu.init(num_cpus=2)
        yield ray_tpu
    finally:
        ray_tpu.shutdown()
        os.environ.pop("RAY_TPU_TESTING_RPC_REPLY_FAILURE", None)


def test_zombie_retry_consistency(zombie_cluster):
    """The detector scenario from data/dataset.py: a block's stored bytes
    must match the metadata from the accepted attempt, even when a zombie
    attempt wrote the same object id with different (nondeterministic)
    content."""

    @ray_tpu.remote(num_returns=2, max_retries=2)
    def produce():
        # nondeterministic sizes: each attempt produces a different row
        # count, so metadata/data divergence between attempts is detectable
        rows = 150_000 + int.from_bytes(os.urandom(2), "big")
        data = np.arange(rows, dtype=np.float64)  # > inline threshold
        return {"rows": rows}, data

    meta_ref, data_ref = produce.remote()
    meta = ray_tpu.get(meta_ref, timeout=120)
    data = ray_tpu.get(data_ref, timeout=120)
    assert meta["rows"] == len(data), (
        "object-plane consistency bug: accepted attempt's metadata does not "
        "match the stored block")


def test_zombie_retry_consistency_stress(zombie_cluster):
    """Many concurrent duplicate executions; every task's metadata must
    match its stored data."""

    @ray_tpu.remote(num_returns=2, max_retries=2)
    def produce(i):
        rows = 100_000 + int.from_bytes(os.urandom(2), "big")
        return {"rows": rows, "i": i}, np.full(rows, i, dtype=np.float64)

    pairs = [produce.remote(i) for i in range(8)]
    for i, (meta_ref, data_ref) in enumerate(pairs):
        meta = ray_tpu.get(meta_ref, timeout=180)
        data = ray_tpu.get(data_ref, timeout=180)
        assert meta["rows"] == len(data)
        assert meta["i"] == i
        assert data[0] == i


# ---------------------------------------------------------------------------
# multi-node tier: directory max-attempt rule + self-healing deletes
# ---------------------------------------------------------------------------


def _rpc(address, method, req, timeout=30.0):
    from ray_tpu._private.rpc import RetryingRpcClient

    async def go():
        client = RetryingRpcClient(address)
        try:
            return wire.loads(await client.call(
                method, wire.dumps(req), timeout=timeout))
        finally:
            await client.close()

    return asyncio.run(go())


def test_directory_prefers_newest_attempt_and_self_heals():
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    cluster = Cluster(head_node_args={"resources": {"CPU": 1.0}})
    cluster.add_node(resources={"CPU": 1.0})
    ray_tpu.init(address=cluster.address)
    try:
        cluster.wait_for_nodes(2)
        nodes = [n for n in ray_tpu.nodes() if n["alive"]]
        assert len(nodes) == 2
        addr_a, addr_b = nodes[0]["address"], nodes[1]["address"]
        oid = os.urandom(16)
        # zombie copy (attempt 0) on node A; committed copy (attempt 1) on B
        _rpc(addr_a, "StorePutInline", {"oid": oid, "blob": b"stale-A",
                                        "attempt": 0})
        _rpc(addr_b, "StorePutInline", {"oid": oid, "blob": b"fresh-B",
                                        "attempt": 1})
        # directory self-heal: node A's displaced copy gets deleted
        deadline = time.time() + 30
        while time.time() < deadline:
            if not _rpc(addr_a, "StoreContains", {"oid": oid})["contains"]:
                break
            time.sleep(0.2)
        assert not _rpc(addr_a, "StoreContains", {"oid": oid})["contains"], (
            "stale attempt-0 copy still present on node A")
        # a pull on node A must fetch the committed attempt-1 bytes
        got = _rpc(addr_a, "StoreGet", {"oid": oid, "timeout": 30.0,
                                        "pull": True}, timeout=45.0)
        if got["status"] == "inline":
            payload = got["blob"]
        elif got["status"] == "shm_arena":
            from ray_tpu._private.object_store import ShmSegment

            seg = ShmSegment(got["arena_name"])
            try:
                payload = bytes(
                    seg.buf[got["offset"] : got["offset"] + got["size"]])
            finally:
                seg.close()
        else:
            from ray_tpu._private.object_store import ShmSegment

            seg = ShmSegment(got["shm_name"])
            try:
                payload = bytes(seg.buf[: got["size"]])
            finally:
                seg.close()
        assert payload == b"fresh-B"
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()

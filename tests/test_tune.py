"""Tune tests (reference tier: python/ray/tune/tests basics + ASHA)."""

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune import ASHAScheduler, TuneConfig, Tuner


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


def test_grid_and_random_search(cluster):
    def objective(config):
        score = -(config["x"] - 3) ** 2 + config["bonus"]
        tune.report({"score": score})
        return {"score": score}

    tuner = Tuner(
        objective,
        param_space={
            "x": tune.grid_search([1, 2, 3, 4]),
            "bonus": tune.choice([0.0]),
        },
        tune_config=TuneConfig(metric="score", mode="max", num_samples=1,
                               max_concurrent_trials=3),
    )
    grid = tuner.fit()
    assert len(grid) == 4
    best = grid.get_best_result()
    assert best.config["x"] == 3
    assert best.metrics["score"] == 0.0


def test_trial_error_isolated(cluster):
    def objective(config):
        if config["x"] == 2:
            raise ValueError("bad trial")
        tune.report({"score": config["x"]})
        return {"score": config["x"]}

    grid = Tuner(
        objective,
        param_space={"x": tune.grid_search([1, 2, 3])},
        tune_config=TuneConfig(metric="score", mode="max"),
    ).fit()
    errors = [r for r in grid.results if r.error]
    assert len(errors) == 1
    assert grid.get_best_result().config["x"] == 3


def test_asha_stops_bad_trials(cluster):
    def objective(config):
        for step in range(1, 20):
            score = config["lr"] * step
            tune.report({"score": score, "training_iteration": step})
        return {"score": score}

    grid = Tuner(
        objective,
        param_space={"lr": tune.grid_search([0.01, 0.1, 1.0, 10.0])},
        tune_config=TuneConfig(
            metric="score", mode="max", max_concurrent_trials=2,
            scheduler=ASHAScheduler(metric="score", mode="max", max_t=19,
                                    grace_period=2, reduction_factor=2)),
    ).fit()
    best = grid.get_best_result()
    assert best.config["lr"] == 10.0
    stopped = [r for r in grid.results if r.stopped_early]
    assert stopped  # at least one loser stopped before max_t


def test_result_dataframe(cluster):
    def objective(config):
        tune.report({"score": config["x"]})
        return {"score": config["x"]}

    grid = Tuner(
        objective,
        param_space={"x": tune.grid_search([5, 7])},
        tune_config=TuneConfig(metric="score", mode="max"),
    ).fit()
    df = grid.get_dataframe()
    assert set(df["config/x"]) == {5, 7}

"""Object broadcast across nodes: completed pulls announce new locations,
so an N-node fan-out forms a tree off the origin (reference: the
1 GiB / 50-node broadcast envelope, release/benchmarks/README.md:19-20;
pull_manager.cc / push_manager.cc source selection).
"""

import pickle

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


def test_broadcast_object_to_all_nodes():
    ray_tpu.shutdown()
    cluster = Cluster(head_node_args={"resources": {"CPU": 2.0}})
    for i in range(3):
        cluster.add_node(resources={"CPU": 2.0, f"node{i}": 2.0})
    ray_tpu.init(address=cluster.address)
    cluster.wait_for_nodes(4)
    try:
        payload = np.arange(1_500_000, dtype=np.float64)  # ~12 MB, store path
        ref = ray_tpu.put(payload)

        def make_reader(i):
            @ray_tpu.remote(resources={f"node{i}": 1.0}, num_cpus=0.5)
            def read(arr):
                return float(arr.sum())
            return read

        expect = float(payload.sum())
        refs = [make_reader(i).remote(ref) for i in range(3)]
        out = ray_tpu.get(refs, timeout=300)
        assert out == [expect] * 3
        # every puller announced its copy: the directory must list multiple
        # holders (the broadcast tree's fan-out substrate)
        w = ray_tpu._private.worker.global_worker()
        locs = w._run(w._gcs_call("ObjectLocGet", {"oid": ref.id.binary()}))
        assert len(locs["locations"]) >= 2, locs
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()

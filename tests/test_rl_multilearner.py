"""Multi-learner gradient sync (reference tier: rllib multi-learner /
learner_group tests): N=2 learners syncing gradients over the collective
substrate must produce the same parameters as N=1 on the same batch
stream, and must still learn end-to-end."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import PPO, PPOConfig
from ray_tpu.rl.ppo import PPOLearner


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield ray_tpu
    ray_tpu.shutdown()


def _synthetic_batch(rng, n, obs_dim, n_actions):
    return {
        "obs": rng.standard_normal((n, obs_dim)).astype(np.float32),
        "actions": rng.integers(0, n_actions, n).astype(np.int32),
        "logp": (-np.log(n_actions)
                 + 0.1 * rng.standard_normal(n)).astype(np.float32),
        "advantages": rng.standard_normal(n).astype(np.float32),
        "returns": rng.standard_normal(n).astype(np.float32),
    }


def test_two_learners_match_single(cluster):
    """The north-star contract: sharded gradients allreduced across 2
    learners == the single-learner gradient, so params stay identical (to
    float tolerance) across a stream of updates."""
    from ray_tpu.rl.learner_group import LearnerGroup

    obs_dim, n_actions = 4, 2
    cfg = PPOConfig(env="CartPole-v1", epochs=2, num_minibatches=4, seed=3)
    single = PPOLearner(cfg, obs_dim, n_actions)

    def factory(rank, world_size, group_name):
        return PPOLearner(cfg, obs_dim, n_actions, world_size=world_size,
                          rank=rank, group_name=group_name)

    group = LearnerGroup(factory, num_learners=2)
    try:
        rng = np.random.default_rng(0)
        for step in range(3):
            batch = _synthetic_batch(rng, 256 + 32 * step, obs_dim, n_actions)
            m1 = single.update(dict(batch))
            m2 = group.update(batch)
            assert np.isfinite(m1["loss"]) and np.isfinite(m2["loss"])
        import jax

        p1 = jax.tree.map(np.asarray, single.get_params())
        p2 = group.get_params()
        flat1 = jax.tree_util.tree_leaves(p1)
        flat2 = jax.tree_util.tree_leaves(p2)
        assert len(flat1) == len(flat2)
        for a, b in zip(flat1, flat2):
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)
        # and the two group ranks agree bitwise-identically with each other
        params_all = group.foreach_learner("get_params")
        for a, b in zip(jax.tree_util.tree_leaves(params_all[0]),
                        jax.tree_util.tree_leaves(params_all[1])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    finally:
        group.shutdown()


def test_ppo_two_learners_improves(cluster):
    algo = PPOConfig(
        env="CartPole-v1",
        num_env_runners=2,
        num_envs_per_runner=4,
        rollout_length=128,
        epochs=8,
        num_learners=2,
        seed=1,
    ).build()
    returns = []
    for _ in range(20):
        m = algo.train()
        returns.append(m["episode_return_mean"])
    algo.stop()
    assert max(returns) > 60, returns


def test_impala_two_learners_improves(cluster):
    from ray_tpu.rl import IMPALAConfig

    # same data scale as test_impala_cartpole_improves, split over 2 learners
    algo = IMPALAConfig(
        env="CartPole-v1",
        num_env_runners=2,
        num_envs_per_runner=4,
        rollout_length=64,
        num_rollouts_per_update=2,
        num_learners=2,
        seed=1,
    ).build()
    returns = []
    for _ in range(90):
        m = algo.train()
        returns.append(m["episode_return_mean"])
    algo.stop()
    assert max(returns) > 60, returns


def test_appo_two_learners_smoke(cluster):
    from ray_tpu.rl import APPOConfig

    algo = APPOConfig(
        env="CartPole-v1",
        num_env_runners=2,
        num_envs_per_runner=2,
        rollout_length=32,
        num_learners=2,
        target_update_freq=2,
        seed=2,
    ).build()
    for _ in range(4):
        m = algo.train()
        assert np.isfinite(m["loss"])
    state = algo.get_state()
    assert "target_params" in state
    # ranks stayed in lockstep: both report the same update counter
    counts = algo.learner_group.foreach_learner("get_state")
    assert counts[0]["updates_done"] == counts[1]["updates_done"] == 4
    algo.stop()


def test_ppo_multilearner_checkpoint_roundtrip(cluster, tmp_path):
    algo = PPOConfig(env="CartPole-v1", num_env_runners=1,
                     num_envs_per_runner=2, rollout_length=32, epochs=1,
                     num_learners=2, seed=5).build()
    algo.train()
    path = algo.save_checkpoint(str(tmp_path))
    state = algo.get_state()
    algo2 = PPOConfig(env="CartPole-v1", num_env_runners=1,
                      num_envs_per_runner=2, rollout_length=32, epochs=1,
                      num_learners=2, seed=5).build()
    algo2.restore_from_checkpoint(path)
    import jax

    s2 = algo2.get_state()
    for a, b in zip(jax.tree_util.tree_leaves(state["params"]),
                    jax.tree_util.tree_leaves(s2["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    algo.stop()
    algo2.stop()

"""Pipeline-plane acceptance tests (ray_tpu/train/pipeline/): MPMD
pipeline-parallel training over stage actor gangs on the CPU tier.

Covers the tentpole flows:
(a) 1F1B schedule golden (exact per-stage send/recv/compute sequence per
    microbatch) + the analytic bubble bound,
(b) 2-stage end-to-end loss/param parity vs the single-mesh fused
    TrainStepBundle step (same init, same data, same optimizer semantics),
    with the timeline golden asserted off the same run (pipe.send /
    pipe.recv spans form matched cross-process flow pairs per microbatch
    in the chrome trace),
(c) stage-actor kill -> gang re-form -> restore from per-stage ckpt
    manifests -> mid-run resume with deterministic replay,
plus the bench smoke (tier-1) for tools/bench_pipeline.py.
"""

import os

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.train.pipeline import (
    PipelineConfig,
    PipelineTrainer,
    bubble_upper_bound,
    build_interleaved_schedule,
    build_schedule,
    make_microbatches,
    max_inflight_activations,
    partition_layers,
    simulate,
    stage_param_keys,
)


def _cfg(**kw):
    import jax.numpy as jnp

    from ray_tpu.models.transformer import TransformerConfig

    base = dict(vocab_size=64, d_model=32, n_layers=2, n_heads=4,
                n_kv_heads=4, d_ff=64, max_seq_len=32, remat=False,
                dtype=jnp.float32, attention_impl="xla")
    base.update(kw)
    return TransformerConfig(**base)


@pytest.fixture(scope="module")
def cluster():
    from ray_tpu.util import tracing

    prev = os.environ.get("RAY_TPU_ENABLE_TRACING")
    os.environ["RAY_TPU_ENABLE_TRACING"] = "1"
    tracing.enable()
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield ray_tpu
    ray_tpu.shutdown()
    # fully restore tracing state: _enabled is a process-level cache, and
    # leaving it on would silently put every later test module in this
    # pytest process on the traced (span-recording, phase-split) paths
    if prev is None:
        os.environ.pop("RAY_TPU_ENABLE_TRACING", None)
    else:
        os.environ["RAY_TPU_ENABLE_TRACING"] = prev
    tracing._enabled = None


# ---------------------------------------------------------------------------
# schedule geometry (pure, no cluster)
# ---------------------------------------------------------------------------


def test_1f1b_schedule_golden_2x4():
    sched = build_schedule(2, 4)
    assert [tuple(op) for op in sched[0]] == [
        ("fwd", 0, 0), ("send_f", 0, 0),
        ("fwd", 1, 0), ("send_f", 1, 0), ("recv_b", 0, 0), ("bwd", 0, 0),
        ("fwd", 2, 0), ("send_f", 2, 0), ("recv_b", 1, 0), ("bwd", 1, 0),
        ("fwd", 3, 0), ("send_f", 3, 0), ("recv_b", 2, 0), ("bwd", 2, 0),
        ("recv_b", 3, 0), ("bwd", 3, 0),
    ]
    assert [tuple(op) for op in sched[1]] == [
        ("recv_f", 0, 0), ("fwd", 0, 0), ("bwd", 0, 0), ("send_b", 0, 0),
        ("recv_f", 1, 0), ("fwd", 1, 0), ("bwd", 1, 0), ("send_b", 1, 0),
        ("recv_f", 2, 0), ("fwd", 2, 0), ("bwd", 2, 0), ("send_b", 2, 0),
        ("recv_f", 3, 0), ("fwd", 3, 0), ("bwd", 3, 0), ("send_b", 3, 0),
    ]


def test_1f1b_schedule_properties_4x8():
    S, M = 4, 8
    sched = build_schedule(S, M)
    for s, ops in enumerate(sched):
        kinds = [op.kind for op in ops]
        # every microbatch runs exactly one fwd and one bwd per stage
        assert kinds.count("fwd") == M and kinds.count("bwd") == M
        # warmup depth: S-1-s warmup forwards + the first steady-state
        # forward run before the first backward
        first_bwd = kinds.index("bwd")
        assert kinds[:first_bwd].count("fwd") == min(S - s, M)
        # in-flight stash never exceeds the 1F1B bound
        inflight = peak = 0
        for k, *_ in ops:
            if k == "fwd":
                inflight += 1
                peak = max(peak, inflight)
            elif k == "bwd":
                inflight -= 1
        assert peak <= max_inflight_activations(s, S)
        # interior stages send/recv every microbatch both ways
        if 0 < s < S - 1:
            assert kinds.count("send_f") == kinds.count("send_b") == M
            assert kinds.count("recv_f") == kinds.count("recv_b") == M


def test_1f1b_bubble_matches_analytic_bound():
    for S, M in [(2, 4), (2, 8), (4, 8), (4, 16), (8, 32)]:
        sim = simulate(S, M, t_fwd=1.0, t_bwd=2.0)
        bound = bubble_upper_bound(S, M)
        assert sim["bubble_fraction"] <= bound + 1e-9, (S, M)
        # with equal per-mb costs 1F1B achieves the bound exactly
        assert abs(sim["bubble_fraction"] - bound) < 1e-9, (S, M)
    # communication costs only ever add bubble
    assert simulate(4, 8, t_comm=0.5)["bubble_fraction"] >= \
        bubble_upper_bound(4, 8)


def test_interleaved_schedule_golden_2x4_v2():
    """Exact per-rank op streams for S=2, V=2, M=4 (virtual stages
    q = chunk*2 + rank; warmup = 2*(S-1-rank) + (V-1)*S)."""
    sched = build_interleaved_schedule(2, 4, 2)
    assert [tuple(op) for op in sched[0]] == [
        ("fwd", 0, 0), ("send_f", 0, 0),
        ("fwd", 1, 0), ("send_f", 1, 0),
        ("recv_f", 0, 1), ("fwd", 0, 1), ("send_f", 0, 1),
        ("recv_f", 1, 1), ("fwd", 1, 1), ("send_f", 1, 1),
        ("fwd", 2, 0), ("send_f", 2, 0),
        ("recv_b", 0, 1), ("bwd", 0, 1), ("send_b", 0, 1),
        ("fwd", 3, 0), ("send_f", 3, 0),
        ("recv_b", 1, 1), ("bwd", 1, 1), ("send_b", 1, 1),
        ("recv_f", 2, 1), ("fwd", 2, 1), ("send_f", 2, 1),
        ("recv_b", 0, 0), ("bwd", 0, 0),
        ("recv_f", 3, 1), ("fwd", 3, 1), ("send_f", 3, 1),
        ("recv_b", 1, 0), ("bwd", 1, 0),
        ("recv_b", 2, 1), ("bwd", 2, 1), ("send_b", 2, 1),
        ("recv_b", 3, 1), ("bwd", 3, 1), ("send_b", 3, 1),
        ("recv_b", 2, 0), ("bwd", 2, 0),
        ("recv_b", 3, 0), ("bwd", 3, 0),
    ]
    assert [tuple(op) for op in sched[1]] == [
        ("recv_f", 0, 0), ("fwd", 0, 0), ("send_f", 0, 0),
        ("recv_f", 1, 0), ("fwd", 1, 0), ("send_f", 1, 0),
        ("recv_f", 0, 1), ("fwd", 0, 1), ("bwd", 0, 1), ("send_b", 0, 1),
        ("recv_f", 1, 1), ("fwd", 1, 1), ("bwd", 1, 1), ("send_b", 1, 1),
        ("recv_f", 2, 0), ("fwd", 2, 0), ("send_f", 2, 0),
        ("recv_b", 0, 0), ("bwd", 0, 0), ("send_b", 0, 0),
        ("recv_f", 3, 0), ("fwd", 3, 0), ("send_f", 3, 0),
        ("recv_b", 1, 0), ("bwd", 1, 0), ("send_b", 1, 0),
        ("recv_f", 2, 1), ("fwd", 2, 1), ("bwd", 2, 1), ("send_b", 2, 1),
        ("recv_f", 3, 1), ("fwd", 3, 1), ("bwd", 3, 1), ("send_b", 3, 1),
        ("recv_b", 2, 0), ("bwd", 2, 0), ("send_b", 2, 0),
        ("recv_b", 3, 0), ("bwd", 3, 0), ("send_b", 3, 0),
    ]


def test_interleaved_schedule_properties_and_validation():
    # every (chunk, mb) runs exactly one fwd + one bwd on its rank
    for S, M, V in [(2, 4, 2), (4, 8, 2), (2, 4, 4), (3, 6, 2)]:
        sched = build_interleaved_schedule(S, M, V)
        for r, ops in enumerate(sched):
            fwds = [(op.chunk, op.mb) for op in ops if op.kind == "fwd"]
            bwds = [(op.chunk, op.mb) for op in ops if op.kind == "bwd"]
            want = {(c, m) for c in range(V) for m in range(M)}
            assert set(fwds) == want and len(fwds) == M * V, (S, M, V, r)
            assert set(bwds) == want and len(bwds) == M * V, (S, M, V, r)
            # in-flight stash bounded by the interleaved warmup depth
            inflight = peak = 0
            for k, *_ in ops:
                if k == "fwd":
                    inflight += 1
                    peak = max(peak, inflight)
                elif k == "bwd":
                    inflight -= 1
            assert peak <= max_inflight_activations(r, S, V), (S, M, V, r)
    # V=1 degenerates to the plain schedule, exactly
    assert build_interleaved_schedule(2, 4, 1) == build_schedule(2, 4)
    # the chunk rotation only closes on whole groups of S
    with pytest.raises(ValueError, match="divisible"):
        build_interleaved_schedule(2, 3, 2)
    with pytest.raises(ValueError, match="chunk"):
        build_interleaved_schedule(2, 4, 0)


def test_interleaved_bubble_matches_analytic_bound():
    """The simulator (real channel semantics: FIFO edges + finite ring
    depth) hits (S-1)/(S-1+V*M) exactly at equal per-chunk costs — and
    never deadlocks or desyncs, which the simulator raises on."""
    shapes = [(2, 4, 2), (2, 8, 2), (4, 8, 2), (2, 4, 4), (3, 6, 2),
              (4, 4, 2), (2, 8, 1), (4, 8, 1)]
    for S, M, V in shapes:
        for depth in (0, 2):
            sim = simulate(S, M, t_fwd=1.0, t_bwd=2.0, num_chunks=V,
                           channel_depth=depth)
            bound = bubble_upper_bound(S, M, V)
            assert abs(sim["bubble_fraction"] - bound) < 1e-9, \
                (S, M, V, depth)
    # interleaving strictly shrinks the bubble at fixed S, M
    assert bubble_upper_bound(4, 8, 2) < bubble_upper_bound(4, 8, 1)


def test_partition_keys_cover_model_disjointly():
    cfg = _cfg(n_layers=5)
    for S in (1, 2, 3, 5):
        bounds = partition_layers(cfg.n_layers, S)
        assert bounds[0][0] == 0 and bounds[-1][1] == cfg.n_layers
        seen = []
        for s in range(S):
            seen += stage_param_keys(cfg, s, S)
        expected = {"embed", "final_norm", "lm_head"} | {
            f"layer_{i}" for i in range(cfg.n_layers)}
        assert set(seen) == expected and len(seen) == len(set(seen))


def test_tied_embeddings_single_stage_and_rejection():
    import jax
    import optax

    from ray_tpu.train.pipeline import StagePrograms

    cfg = _cfg(tie_embeddings=True)
    # S > 1 cannot host a tied head (the table would live on two stages)
    with pytest.raises(ValueError, match="tie_embeddings"):
        StagePrograms(cfg, 0, 2, optax.sgd(0.1))
    # S == 1 ties logits to the embed table — no phantom lm_head param
    progs = StagePrograms(cfg, 0, 1, optax.sgd(0.1))
    params = progs.init(jax.random.PRNGKey(0))
    assert "lm_head" not in params and "embed" in params
    mbs = make_microbatches(cfg, PipelineConfig(
        num_stages=1, num_microbatches=1, microbatch_size=1, seq_len=8),
        0, 0)
    loss, _ = progs.fwd_loss(params, mbs[0]["tokens"], mbs[0]["targets"],
                             mbs[0]["mask"])
    assert np.isfinite(float(loss))


# ---------------------------------------------------------------------------
# end-to-end acceptance flow, one gang + one single-mesh reference:
# (1) 2-stage loss/param parity vs the fused TrainStepBundle step,
# (2) timeline golden off the same run (cross-process flow pairs per mb),
# (3) stage kill -> per-stage manifest restore -> deterministic resume
#     that KEEPS matching the single-mesh run (ckpt round-trip fidelity)
# ---------------------------------------------------------------------------


def test_two_stage_parity_timeline_kill_restore(cluster, tmp_path):
    import jax

    from ray_tpu.parallel.mesh import create_mesh, default_mesh_axes
    from ray_tpu.parallel.train import TrainStepBundle, make_optimizer
    from ray_tpu.util import tracing

    cfg = _cfg()
    M = 4
    pipe = PipelineConfig(num_stages=2, num_microbatches=M,
                          microbatch_size=2, seq_len=16,
                          clip_global_norm=1.0, ckpt_every=2,
                          step_timeout_s=60.0)
    steps = 3
    tracing.clear()
    trainer = PipelineTrainer(cfg, pipe, seed=5, run_name="parity",
                              ckpt_root=str(tmp_path))
    try:
        stats = trainer.train(steps)  # saves per-stage manifests at step 2
        pipe_losses = [s["loss"] for s in stats]

        # -- (1) parity: same init params, same data, the fused step with
        # optax.chain(clip_by_global_norm(1.0), adamw(schedule)) --
        mesh = create_mesh(default_mesh_axes(8))
        bundle = TrainStepBundle(cfg, mesh, optimizer=make_optimizer(),
                                 donate=False)
        params = trainer.init_params
        opt_state = bundle.optimizer.init(params)

        def ref_step(step):
            nonlocal params, opt_state
            mbs = make_microbatches(cfg, pipe, 5, step)
            batch = {k: np.concatenate([m[k] for m in mbs])
                     for k in mbs[0]}
            params, opt_state, loss = bundle._fused_step(
                params, opt_state, batch)
            return float(loss)

        ref_losses = [ref_step(s) for s in range(steps)]
        np.testing.assert_allclose(pipe_losses, ref_losses, rtol=0,
                                   atol=1e-5)

        def assert_param_parity():
            merged = trainer.merged_params()
            ref = jax.tree.leaves({k: params[k] for k in sorted(params)})
            got = jax.tree.leaves({k: merged[k] for k in sorted(merged)})
            for a, b in zip(ref, got):
                np.testing.assert_allclose(
                    np.asarray(a, np.float64), np.asarray(b, np.float64),
                    rtol=0, atol=1e-5)

        assert_param_parity()
        # activations actually crossed the channel plane
        assert stats[0]["activation_bytes_per_mb"] > 0

        # -- (2) timeline golden off the same run: pipe.send/pipe.recv
        # spans pair up across the two stage processes per microbatch,
        # and the chrome trace renders them as matched ph:"s"/"f" flow
        # arrows (the /api/timeline contract) --
        def _spans():
            spans = tracing.get_spans()
            sends = [s for s in spans if s["name"] == "pipe.send"]
            recvs = [s for s in spans if s["name"] == "pipe.recv"]
            # per step: M activation sends + M grad sends, mirrored recvs
            want = 2 * M * steps
            return (sends, recvs) if len(sends) >= want \
                and len(recvs) >= want else None

        deadline = time.time() + 30
        got = _spans()
        while got is None and time.time() < deadline:
            time.sleep(0.5)
            got = _spans()
        assert got is not None, "pipe.send/recv spans never surfaced"
        sends, recvs = got
        by_id = {s["span_id"]: s for s in sends}
        paired = 0
        for r in recvs:
            parent = by_id.get(r.get("parent_id"))
            if parent is None:
                continue
            paired += 1
            assert parent["mb"] == r["mb"]
            assert parent["pid"] != r["pid"], \
                "send/recv must sit on different stage processes"
        assert paired >= 2 * M * steps
        events = tracing.spans_to_chrome_events(sends + recvs)
        flow_s = {e["id"] for e in events if e.get("ph") == "s"}
        flow_f = {e["id"] for e in events if e.get("ph") == "f"}
        assert flow_s and flow_s == flow_f
        assert len(flow_s) >= 2 * M * steps
        # fwd/bwd compute spans carry the per-microbatch tags the
        # timeline groups by (the bubble is visible per microbatch)
        all_spans = tracing.get_spans()
        fwd = [s for s in all_spans if s["name"] == "pipe.fwd"]
        assert {(s["stage"], s["mb"]) for s in fwd} >= {
            (st, mb) for st in (0, 1) for mb in range(M)}

        # -- (3) failure: kill stage 1 and train on. The dead actor (or
        # its wedged neighbor) surfaces on the controller's wait-any; the
        # gang re-forms at a fresh channel generation and restores every
        # stage from its step-2 manifest --
        assert trainer.last_saved_step == 2
        for s in range(2):
            assert os.path.isdir(str(tmp_path / f"stage{s}")), \
                "per-stage ckpt store missing"
        ray_tpu.kill(trainer.actors[1])
        more = trainer.train(5)

        assert trainer.recoveries == 1
        assert trainer.restored_steps == [2], \
            "gang must resume from the step-2 per-stage manifests"
        assert trainer.step == 5
        # deterministic replay: the re-run of step 2 (restored state +
        # regenerated microbatches) reproduces the original loss exactly
        rerun_step2 = next(s for s in more if s["step"] == 2)
        np.testing.assert_allclose(rerun_step2["loss"], stats[2]["loss"],
                                   rtol=0, atol=1e-6)
        # restore fidelity: the post-recovery steps 3 and 4 STILL match
        # the single-mesh run — the per-stage manifests round-tripped
        # params AND optimizer state byte-faithfully
        ref_more = [ref_step(3), ref_step(4)]
        np.testing.assert_allclose(
            [s["loss"] for s in more if s["step"] in (3, 4)], ref_more,
            rtol=0, atol=1e-5)
        assert_param_parity()
    finally:
        trainer.shutdown()
        tracing.clear()


def test_interleaved_two_stage_parity_v2(cluster, tmp_path):
    """S=2, V=2 (4 virtual stages on 2 ranks, non-contiguous chunks):
    fp32 loss AND param parity vs the fused single-mesh step, plus a
    ckpt save/restore round trip through the chunked manifest layout."""
    import jax

    from ray_tpu.parallel.mesh import create_mesh, default_mesh_axes
    from ray_tpu.parallel.train import TrainStepBundle, make_optimizer

    cfg = _cfg(n_layers=4)
    M = 4
    pipe = PipelineConfig(num_stages=2, num_microbatches=M,
                          microbatch_size=2, seq_len=16,
                          clip_global_norm=1.0, virtual_stages=2,
                          ckpt_every=2, step_timeout_s=60.0)
    steps = 3
    trainer = PipelineTrainer(cfg, pipe, seed=9, run_name="ilv_parity",
                              ckpt_root=str(tmp_path))
    try:
        stats = trainer.train(steps)
        pipe_losses = [s["loss"] for s in stats]

        mesh = create_mesh(default_mesh_axes(8))
        bundle = TrainStepBundle(cfg, mesh, optimizer=make_optimizer(),
                                 donate=False)
        params = trainer.init_params
        opt_state = bundle.optimizer.init(params)

        def ref_step(step):
            nonlocal params, opt_state
            mbs = make_microbatches(cfg, pipe, 9, step)
            batch = {k: np.concatenate([m[k] for m in mbs])
                     for k in mbs[0]}
            params, opt_state, loss = bundle._fused_step(
                params, opt_state, batch)
            return float(loss)

        ref_losses = [ref_step(s) for s in range(steps)]
        np.testing.assert_allclose(pipe_losses, ref_losses, rtol=0,
                                   atol=1e-5)
        merged = trainer.merged_params()
        assert set(merged) == set(params)
        for k in sorted(params):
            for a, b in zip(jax.tree.leaves(params[k]),
                            jax.tree.leaves(merged[k])):
                np.testing.assert_allclose(
                    np.asarray(a, np.float64), np.asarray(b, np.float64),
                    rtol=0, atol=1e-5)

        # chunked-manifest layout: the ckpt_every=2 save committed per-rank
        # manifests nesting per virtual stage under ``chunks``, and the
        # chunk param keys across ranks re-merge to the full model's key
        # set (the V=1 kill/restore e2e covers gang recovery; re-forming a
        # second gang here would double this test's wall on the 1-core
        # tier — restore_ckpt's chunk-mismatch guard is unit-exercised by
        # reading the trees back directly)
        assert trainer.last_saved_step == 2
        from ray_tpu import ckpt as ckpt_plane

        seen_keys = set()
        for s in range(pipe.num_stages):
            store = ckpt_plane.CheckpointStore(
                str(tmp_path / f"stage{s}"), name=f"ilv_parity-s{s}")
            man = store.latest()
            assert man is not None and man.step == 2
            tree = ckpt_plane.restore_tree(store, man.ckpt_id)
            assert set(tree["chunks"]) == {str(v * 2 + s) for v in range(2)}
            for sub in tree["chunks"].values():
                seen_keys |= set(sub["params"])
        assert seen_keys == set(params)
    finally:
        trainer.shutdown()


# ---------------------------------------------------------------------------
# bench smoke (tier-1): the PIPE_r* harness runs end to end
# ---------------------------------------------------------------------------


def test_bench_pipeline_smoke(cluster, tmp_path):
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from ray_tpu.util import tracing
    from tools.bench_pipeline import main as bench_main

    out = str(tmp_path / "PIPE_smoke.json")
    # bench the untraced paths (the real PIPE_r* condition): the module
    # fixture's tracing would otherwise switch bundle.step to the
    # phase-split programs and double the smoke's compile bill
    tracing._enabled = False
    try:
        rows = bench_main(stages=(2,), microbatches=2, microbatch_size=1,
                          seq_len=16, steps=1, n_layers=2, out=out)
    finally:
        tracing._enabled = True
    names = {r["name"]: r["value"] for r in rows}
    assert names["single_mesh_tokens_per_s"] > 0
    assert names["pipeline_s2_tokens_per_s"] > 0
    assert names["pipeline_s2_activation_bytes_per_microbatch"] > 0
    # the reported bubble obeys the 1F1B bound
    assert names["pipeline_s2_bubble_fraction"] <= \
        names["pipeline_s2_bubble_bound"] + 1e-9
    assert os.path.exists(out)


def test_bucketed_stage_apply_matches_whole_tree(cluster, tmp_path):
    """PR 12: `bucket_bytes` routes a stage through the bucketed optimizer
    apply (per-bucket opt state, `pipe.bucket_apply` spans;
    `PipelineConfig.bucket_bytes` passes it to every stage). Adam-family
    transforms are per-leaf, so the bucketed apply must reproduce the
    whole-tree apply bit-for-bit — asserted on two single-stage actors fed
    IDENTICAL microbatches, one per mode."""
    import cloudpickle
    import flax.linen as nn
    import jax

    from ray_tpu.models.transformer import Transformer
    from ray_tpu.train.pipeline import schedule as sched
    from ray_tpu.train.pipeline.stage import PipelineStage
    from ray_tpu.weights import WeightStore

    cfg = _cfg()
    cfg_blob = cloudpickle.dumps(cfg)
    M = 2
    params = nn.unbox(Transformer(cfg).init(
        jax.random.PRNGKey(7), np.zeros((1, 16), np.int32))["params"])
    store = WeightStore("bk_seed")
    store.publish({"params": params}, durable=True)
    stages = {
        label: PipelineStage.options(num_cpus=1).remote(
            0, 1, cfg_blob, None, f"bk_{label}", 0,
            bucket_bytes=bucket_bytes)
        for label, bucket_bytes in (("whole", None), ("bucketed", 4 << 10))
    }
    try:
        ray_tpu.get([a.init_weights.remote("bk_seed")
                     for a in stages.values()], timeout=120)
        ops = [list(op) for op in sched.build_schedule(1, M)[0]]
        mbs = make_microbatches(cfg, PipelineConfig(
            num_stages=1, num_microbatches=M, microbatch_size=2,
            seq_len=16), seed=11, step=0)
        results = ray_tpu.get(
            [a.run_schedule.remote(0, ops, mbs) for a in stages.values()],
            timeout=120)
        assert results[0]["losses"] == results[1]["losses"]
        ray_tpu.get([a.apply_grads.remote(1.0 / M)
                     for a in stages.values()], timeout=60)
        trees = ray_tpu.get([a.pull_params.remote()
                             for a in stages.values()], timeout=60)
        wl = jax.tree_util.tree_leaves(trees[0])
        bl = jax.tree_util.tree_leaves(trees[1])
        assert len(wl) == len(bl) and len(wl) > 4
        for a, b in zip(wl, bl):
            assert np.array_equal(np.asarray(a), np.asarray(b))
    finally:
        for a in stages.values():
            try:
                ray_tpu.get(a.shutdown.remote(), timeout=10)
            except Exception:
                pass
            ray_tpu.kill(a)
        store.shutdown()


def test_stage_dp_group_bucketed_allreduce(cluster, tmp_path):
    """Two data-parallel replicas of a single-stage pipeline, fed
    DIFFERENT microbatches: run_schedule launches every grad bucket's
    allreduce asynchronously (overlapping the controller round-trip), and
    after apply_grads both replicas hold the IDENTICAL params — proof the
    cross-replica sum reached both sides."""
    import cloudpickle

    from ray_tpu.train.pipeline.stage import PipelineStage
    from ray_tpu.train.pipeline import schedule as sched
    from ray_tpu.weights import WeightStore

    cfg = _cfg()
    cfg_blob = cloudpickle.dumps(cfg)
    M = 2
    # seed one param tree both replicas pull (same init)
    import flax.linen as nn
    import jax

    from ray_tpu.models.transformer import Transformer

    params = nn.unbox(Transformer(cfg).init(
        jax.random.PRNGKey(3), np.zeros((1, 16), np.int32))["params"])
    store = WeightStore("dp_bucket_seed")
    store.publish({"params": params}, durable=True)
    replicas = [
        PipelineStage.options(num_cpus=1).remote(
            0, 1, cfg_blob, None, f"dpb_r{r}", 0,
            bucket_bytes=4 << 10,
            dp_group={"name": "dpb", "world_size": 2, "rank": r,
                      "backend": "cpu"})
        for r in range(2)
    ]
    try:
        ray_tpu.get([a.ready.remote() for a in replicas], timeout=60)
        ray_tpu.get([a.init_weights.remote("dp_bucket_seed")
                     for a in replicas], timeout=120)
        ops = [list(op) for op in sched.build_schedule(1, M)[0]]
        refs = []
        for r, a in enumerate(replicas):
            mbs = make_microbatches(cfg, PipelineConfig(
                num_stages=1, num_microbatches=M, microbatch_size=2,
                seq_len=16), seed=100 + r, step=0)  # different data!
            refs.append(a.run_schedule.remote(0, ops, mbs))
        results = ray_tpu.get(refs, timeout=120)
        assert all(res["reduce_launched"] for res in results)
        sq = ray_tpu.get([a.grad_sqnorm.remote() for a in replicas],
                         timeout=60)
        assert sq[0] == pytest.approx(sq[1])  # both see the summed grads
        ray_tpu.get([a.apply_grads.remote(1.0 / (2 * M))
                     for a in replicas], timeout=60)
        trees = ray_tpu.get([a.pull_params.remote() for a in replicas],
                            timeout=60)
        la = jax.tree_util.tree_leaves(trees[0])
        lb = jax.tree_util.tree_leaves(trees[1])
        for x, y in zip(la, lb):
            assert np.array_equal(np.asarray(x), np.asarray(y))
    finally:
        for a in replicas:
            try:
                ray_tpu.get(a.shutdown.remote(), timeout=10)
            except Exception:
                pass
            ray_tpu.kill(a)
        store.shutdown()

"""Core API tests in local mode (reference: python/ray/tests/test_basic.py tier)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import TaskError


def test_put_get(ray_local):
    ref = ray_tpu.put({"a": 1, "b": np.arange(10)})
    out = ray_tpu.get(ref)
    assert out["a"] == 1
    np.testing.assert_array_equal(out["b"], np.arange(10))


def test_task_roundtrip(ray_local):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 2)) == 3
    # ObjectRef args resolve before execution
    assert ray_tpu.get(add.remote(add.remote(1, 1), 3)) == 5


def test_multiple_returns(ray_local):
    @ray_tpu.remote(num_returns=2)
    def two():
        return 1, 2

    a, b = two.remote()
    assert ray_tpu.get(a) == 1 and ray_tpu.get(b) == 2


def test_task_error_propagates(ray_local):
    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom")

    with pytest.raises(TaskError, match="kaboom"):
        ray_tpu.get(boom.remote())


def test_options_override(ray_local):
    @ray_tpu.remote
    def f():
        return 42

    assert ray_tpu.get(f.options(num_cpus=2, num_returns=1).remote()) == 42
    with pytest.raises(ValueError):
        f.options(bogus=1)


def test_actor_basics(ray_local):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def incr(self, by=1):
            self.n += by
            return self.n

    c = Counter.remote(10)
    assert ray_tpu.get(c.incr.remote()) == 11
    assert ray_tpu.get(c.incr.remote(5)) == 16
    with pytest.raises(AttributeError):
        c.nonexistent


def test_named_actor(ray_local):
    @ray_tpu.remote
    class A:
        def ping(self):
            return "pong"

    A.options(name="the_actor").remote()
    h = ray_tpu.get_actor("the_actor")
    assert ray_tpu.get(h.ping.remote()) == "pong"
    ray_tpu.kill(h)
    with pytest.raises(ValueError):
        ray_tpu.get_actor("the_actor")


def test_wait(ray_local):
    refs = [ray_tpu.put(i) for i in range(4)]
    ready, rest = ray_tpu.wait(refs, num_returns=2)
    assert len(ready) == 2 and len(rest) == 2


def test_runtime_context(ray_local):
    ctx = ray_tpu.get_runtime_context()
    assert len(ctx.get_job_id()) == 8


def test_cannot_call_remote_directly(ray_local):
    @ray_tpu.remote
    def f():
        return 1

    with pytest.raises(TypeError):
        f()


def test_serialization_oob_roundtrip():
    from ray_tpu._private.serialization import dumps_oob, loads_oob

    arr = np.random.rand(1000, 100)
    blob = dumps_oob({"x": arr, "y": [1, 2, 3]})
    out = loads_oob(blob)
    np.testing.assert_array_equal(out["x"], arr)
    assert out["y"] == [1, 2, 3]


def test_ids_structure():
    from ray_tpu._private.ids import JobID, ObjectID, TaskID

    job = JobID.from_int(7)
    task = TaskID.of(job)
    assert task.job_id() == job
    obj = ObjectID.for_task_return(task, 3)
    assert obj.task_id() == task and obj.return_index() == 3

"""Weight-plane acceptance tests (ray_tpu/weights/): mesh-aware sharded
weight transfer and live resharding on the 8-device virtual CPU mesh.

Covers the four north-star flows:
(a) learner -> N env-runner broadcast via publish/pull with version
    monotonicity,
(b) train-mesh -> differently-sharded serve-replica publish with plan-level
    no-gather and byte-accounting assertions,
(c) elastic re-form: a killed group's durable-published state is pulled
    back resharded onto the shrunken mesh,
(d) rolling serve weight update with zero dropped requests,
plus planner geometry units and the same-mesh collective lowering.
"""

import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.weights import (
    MeshSpec,
    ShardedTreeSpec,
    WeightStore,
    collective_reshard,
    local_shards_of,
    plan_reshard,
    publish_host_shards,
)


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield ray_tpu
    ray_tpu.shutdown()


def _tree(scale: float = 1.0):
    return {
        "layer0": {"w": (np.arange(64, dtype=np.float32).reshape(8, 8)
                         * scale),
                   "b": np.arange(8, dtype=np.float32) * scale},
        "step": np.asarray([scale], np.float32),
    }


# ---------------------------------------------------------------------------
# planner geometry (pure, no cluster)
# ---------------------------------------------------------------------------


def _replicated_leaves(dst):
    return {leaf for leaf in dst.meta if all(a is None for a in
                                             dst.part_of(leaf))}


def _moved_sharded(plan, dst):
    """Bytes moved for leaves the destination actually shards (replicated
    leaves are broadcasts: each replica receives a copy by declaration)."""
    rep = _replicated_leaves(dst)
    return sum(e.nbytes for e in plan.edges
               if not e.local and e.leaf not in rep)


def _unique_sharded(src, dst):
    import numpy as np

    from ray_tpu.weights.spec import box_nbytes, unique_boxes

    rep = _replicated_leaves(dst)
    total = 0
    for leaf, (shape, dtype) in src.meta.items():
        if leaf in rep:
            continue
        item = np.dtype(dtype).itemsize
        for box in unique_boxes(src.mesh, src.part_of(leaf), shape):
            total += box_nbytes(box, item)
    return total


def test_plan_cross_mesh_bytes_and_no_gather():
    tree = _tree()
    src_mesh = MeshSpec((4,), ("data",), tuple(f"t{i}" for i in range(4)))
    dst_mesh = MeshSpec((2,), ("model",), ("s0", "s1"))
    src = ShardedTreeSpec.from_tree(tree, src_mesh,
                                    default_part=("data",))
    dst = ShardedTreeSpec.from_tree(
        tree, dst_mesh,
        parts={"layer0/w": (None, "model"), "layer0/b": ("model",),
               "step": ()})
    # 'step' (shape (1,)) cannot shard 4-ways; publish it replicated on src
    src.parts["step"] = ()
    plan = plan_reshard(src, dst)
    stats = plan.stats()
    # every dst byte arrives exactly once: for the sharded leaves, moved
    # bytes <= unique shard bytes (the tiny replicated 'step' leaf is a
    # declared broadcast — each replica legitimately receives its copy)
    assert _moved_sharded(plan, dst) <= _unique_sharded(src, dst)
    # published bytes never exceed unique shard bytes, broadcast included
    assert plan.unique_chunk_bytes() <= src.total_unique_bytes()
    # no single host ever holds a full gathered copy of a sharded leaf
    assert plan.no_gather()
    full_w = 64 * 4
    assert plan.max_host_leaf_bytes("layer0/w") < full_w
    assert stats["num_edges"] > 0 and stats["src_hosts"] == 4


def test_plan_broadcast_fanout_and_chunk_dedup():
    tree = _tree()
    src = ShardedTreeSpec.from_tree(tree, MeshSpec.host_mesh(["learner"]))
    dst = ShardedTreeSpec.replicated(tree, [f"r{i}" for i in range(8)])
    plan = plan_reshard(src, dst)
    # replicated destinations share ONE published chunk per leaf
    assert plan.fanout() == 8
    assert plan.unique_chunk_bytes() == src.total_unique_bytes()
    assert plan.bytes_moved() == 8 * src.total_unique_bytes()


def test_plan_rejects_mismatched_trees():
    a = ShardedTreeSpec.from_tree({"w": np.zeros(4)},
                                  MeshSpec.host_mesh(["a"]))
    b = ShardedTreeSpec.from_tree({"v": np.zeros(4)},
                                  MeshSpec.host_mesh(["a"]))
    with pytest.raises(ValueError, match="differ on leaves"):
        plan_reshard(a, b)


# ---------------------------------------------------------------------------
# (a) learner -> 8 env-runner broadcast, version monotonicity
# ---------------------------------------------------------------------------


class _ToyCore:
    def __init__(self, rank, world_size, group_name):
        self.params = {"w": np.zeros(4, np.float32)}

    def update(self, batch):
        self.params["w"] = self.params["w"] + 1.0
        return {"step": float(self.params["w"][0])}

    def get_params(self):
        return self.params

    def get_state(self):
        return self.params

    def set_state(self, state):
        self.params = state


def _toy_factory(rank, world_size, group_name):
    return _ToyCore(rank, world_size, group_name)


@ray_tpu.remote(num_cpus=0.2)
class _Runner:
    def __init__(self, store_name):
        from ray_tpu.rl.env_runner import WeightSync

        self.sync = WeightSync(store_name, start_after=-1)
        self.seen = []

    def poll(self, timeout=0.0):
        v = self.sync.poll(timeout=timeout)
        if v is not None:
            self.seen.append(v)
        return v

    def report(self):
        return {"versions": list(self.seen),
                "w0": float(self.sync.weights["w"][0])
                if self.sync.weights is not None else None}


def test_learner_broadcast_to_runners(cluster):
    from ray_tpu.rl.learner_group import LearnerGroup

    store_name = "bcast_test"
    runners = [_Runner.remote(store_name) for _ in range(8)]
    group = LearnerGroup(_toy_factory, num_learners=1,
                         num_cpus_per_learner=0.5)
    try:
        v1 = group.publish_weights(store_name)
        got = ray_tpu.get([r.poll.remote(timeout=30.0) for r in runners],
                          timeout=120)
        assert got == [v1] * 8
        # nothing new: poll returns None, version does not regress
        assert ray_tpu.get([r.poll.remote(0.0) for r in runners],
                           timeout=60) == [None] * 8
        group.update(np.zeros(1))
        v2 = group.publish_weights(store_name)
        assert v2 > v1
        got = ray_tpu.get([r.poll.remote(timeout=30.0) for r in runners],
                          timeout=120)
        assert got == [v2] * 8
        reports = ray_tpu.get([r.report.remote() for r in runners],
                              timeout=60)
        for rep in reports:
            assert rep["versions"] == sorted(rep["versions"]) == [v1, v2]
            assert rep["w0"] == 1.0  # post-update params reached every runner
        stats = WeightStore(store_name).stats()
        assert stats["latest"] == v2
    finally:
        group.shutdown()
        for r in runners:
            ray_tpu.kill(r)


# ---------------------------------------------------------------------------
# (b) train mesh -> differently-sharded serve replicas through the store
# ---------------------------------------------------------------------------


@ray_tpu.remote(num_cpus=0.2)
class _SrcHost:
    """One host of the train mesh: holds ONLY its shards (cut locally from
    the deterministic test tree — the full tree never crosses a boundary)."""

    def __init__(self, store_name, host, src_spec, dst_spec):
        self.store_name = store_name
        self.host = host
        self.src = src_spec
        self.dst = dst_spec

    def publish(self, version):
        shards = local_shards_of(_tree(), self.src, self.host)
        return publish_host_shards(
            WeightStore(self.store_name), version, self.src, self.host,
            shards, dst_spec=self.dst, durable=False)


@ray_tpu.remote(num_cpus=0.2)
class _DstHost:
    def __init__(self, store_name, host, dst_spec):
        self.store_name = store_name
        self.host = host
        self.dst = dst_spec

    def pull(self, version):
        shards = WeightStore(self.store_name).pull_shards(
            self.dst, self.host, version)
        return {leaf: {str(box): arr for box, arr in boxes.items()}
                for leaf, boxes in shards.items()}


def test_cross_mesh_publish_pull_no_gather(cluster):
    tree = _tree()
    store_name = "reshard_test"
    src_mesh = MeshSpec((4,), ("data",), tuple(f"t{i}" for i in range(4)))
    dst_mesh = MeshSpec((2,), ("model",), ("s0", "s1"))
    src = ShardedTreeSpec.from_tree(tree, src_mesh, default_part=("data",))
    src.parts["step"] = ()
    dst = ShardedTreeSpec.from_tree(
        tree, dst_mesh,
        parts={"layer0/w": (None, "model"), "layer0/b": ("model",),
               "step": ()})
    plan = plan_reshard(src, dst)
    assert plan.no_gather()
    assert _moved_sharded(plan, dst) <= _unique_sharded(src, dst)

    srcs = [_SrcHost.remote(store_name, h, src, dst)
            for h in src_mesh.hosts]
    version = 1
    ray_tpu.get([s.publish.remote(version) for s in srcs], timeout=120)

    dsts = [_DstHost.remote(store_name, h, dst) for h in dst_mesh.hosts]
    out = ray_tpu.get([d.pull.remote(version) for d in dsts], timeout=120)
    # s0 gets columns 0:4, s1 columns 4:8 of w; halves of b; all of step
    for i, host_out in enumerate(out):
        wbox = f"((0, 8), ({i * 4}, {i * 4 + 4}))"
        np.testing.assert_array_equal(
            host_out["layer0/w"][wbox], tree["layer0"]["w"][:, i*4:(i+1)*4])
        bbox = f"(({i * 4}, {i * 4 + 4}),)"
        np.testing.assert_array_equal(
            host_out["layer0/b"][bbox], tree["layer0"]["b"][i*4:(i+1)*4])
        np.testing.assert_array_equal(host_out["step"]["((0, 1),)"],
                                      tree["step"])

    stats = WeightStore(store_name).stats()["versions"][str(version)]
    # published exactly the planned unique chunks; every dst host pulled
    # only its own shard bytes
    assert stats["bytes_published"] == plan.unique_chunk_bytes()
    assert stats["bytes_pulled"] == plan.bytes_moved()
    for a in srcs + dsts:
        ray_tpu.kill(a)


# ---------------------------------------------------------------------------
# (c) elastic re-form: killed group's state reshards onto the smaller mesh
# ---------------------------------------------------------------------------


def test_elastic_reform_reshards_state(cluster):
    from ray_tpu.train.config import ScalingConfig
    from ray_tpu.train.scaling_policy import (ElasticScalingPolicy,
                                              mesh_spec_for)
    from ray_tpu.train.worker_group import TrainWorker

    store_name = "elastic_test"
    old_world = 4
    workers = [TrainWorker.options(num_cpus=0.2).remote(i, old_world)
               for i in range(old_world)]
    # every rank durably publishes ITS shard (dim 0) of the optimizer state
    version = 1
    ray_tpu.get([
        w.publish_weight_shards.remote(
            store_name, version,
            {"opt": {"m": np.full((2, 3), float(i), np.float32)}})
        for i, w in enumerate(workers)], timeout=120)
    # the whole incarnation dies (elastic failure)
    for w in workers:
        ray_tpu.kill(w)

    # scaling policy picks the next mesh-shaped size for what's left
    scaling = ScalingConfig(num_workers=old_world, elastic=True,
                            min_workers=1, elastic_granularity="pow2",
                            resources_per_worker={"CPU": 1.0})
    policy = ElasticScalingPolicy(scaling)
    new_world = policy.size_after_failure(old_world, {"CPU": 2.0})
    assert new_world == 2
    assert mesh_spec_for(new_world).hosts == ("rank0", "rank1")

    new_workers = [TrainWorker.options(num_cpus=0.2).remote(i, new_world)
                   for i in range(new_world)]
    out = ray_tpu.get([
        w.pull_weight_shards.remote(store_name) for w in new_workers],
        timeout=120)
    for rank, res in enumerate(out):
        assert res["version"] == version
        m = res["tree"]["opt"]["m"]
        assert m.shape == (4, 3)  # global dim0=8 resharded 4 -> 2
        expect = np.repeat(np.arange(rank * 2, rank * 2 + 2,
                                     dtype=np.float32), 2)[:, None]
        np.testing.assert_array_equal(m, np.broadcast_to(expect, (4, 3)))
    for w in new_workers:
        ray_tpu.kill(w)


# ---------------------------------------------------------------------------
# (d) rolling serve weight update: zero dropped requests
# ---------------------------------------------------------------------------


class _ServedModel:
    def __init__(self, store_name):
        self.store_name = store_name
        self.version = 0
        self.w = np.zeros(4, np.float32)

    def __call__(self, body):
        time.sleep(0.005)
        return {"version": self.version, "w0": float(self.w[0])}

    def update_weights(self, version=None):
        tree, ver = WeightStore(self.store_name).pull(
            version, return_version=True)
        # attribute swap is atomic under the GIL: in-flight requests keep
        # serving the old tree, the next request sees the new one
        self.w, self.version = tree["w"], ver
        return ver


def test_rolling_serve_weight_update_zero_drops(cluster):
    from ray_tpu.serve import api as serve

    store_name = "serve_weights_test"
    store = WeightStore(store_name)
    app = serve.deployment(
        _ServedModel, name="wmodel", num_replicas=3,
        ray_actor_options={"num_cpus": 0.3}).bind(store_name)
    handle = serve.run(app)
    try:
        failures = []
        responses = []
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                try:
                    responses.append(
                        ray_tpu.get(handle.remote({}), timeout=60))
                except Exception as e:  # any dropped request fails the test
                    failures.append(repr(e))

        threads = [threading.Thread(target=hammer, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.5)
        v1 = store.publish({"w": np.full(4, 7.0, np.float32)})
        acks = handle.broadcast("update_weights", timeout=120)
        assert acks == [v1] * 3  # every replica applied the update
        time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not failures, failures[:3]
        assert len(responses) > 20
        # traffic flowed before, during, and after the update; post-update
        # responses carry the new version/weights
        assert responses[0]["version"] == 0
        assert responses[-1]["version"] == v1 and responses[-1]["w0"] == 7.0
    finally:
        serve.delete("wmodel")


# ---------------------------------------------------------------------------
# same-mesh lowering: collective tier (no store involved)
# ---------------------------------------------------------------------------


@ray_tpu.remote(num_cpus=0.3)
class _MeshMember:
    def __init__(self, rank, world, src_spec, dst_spec):
        from ray_tpu import collective as col

        self.rank = rank
        self.src = src_spec
        self.dst = dst_spec
        self.group = col.init_collective_group(world, rank, backend="cpu",
                                               group_name="wp_reshard")

    def reshard(self):
        host = self.src.mesh.hosts[self.rank]
        shards = local_shards_of(_tree(), self.src, host)
        plan = plan_reshard(self.src, self.dst)
        out = collective_reshard(plan, self.group, host, shards)
        return {leaf: {str(b): a for b, a in boxes.items()}
                for leaf, boxes in out.items()}


def test_collective_reshard_same_mesh(cluster):
    tree = _tree()
    mesh = MeshSpec((2,), ("x",), ("m0", "m1"))
    src = ShardedTreeSpec.from_tree(
        tree, mesh, parts={"layer0/w": ("x",), "layer0/b": ("x",),
                           "step": ()})
    dst = ShardedTreeSpec.from_tree(
        tree, mesh, parts={"layer0/w": (None, "x"), "layer0/b": ("x",),
                           "step": ()})
    members = [_MeshMember.remote(i, 2, src, dst) for i in range(2)]
    out = ray_tpu.get([m.reshard.remote() for m in members], timeout=120)
    for i, res in enumerate(out):
        np.testing.assert_array_equal(
            res["layer0/w"][f"((0, 8), ({i * 4}, {i * 4 + 4}))"],
            tree["layer0"]["w"][:, i * 4:(i + 1) * 4])
        # b: same partition on both sides -> pure local edges
        np.testing.assert_array_equal(
            res["layer0/b"][f"(({i * 4}, {i * 4 + 4}),)"],
            tree["layer0"]["b"][i * 4:(i + 1) * 4])
    for m in members:
        ray_tpu.kill(m)


def test_jax_reshard_on_virtual_mesh(cluster):
    """XLA-tier lowering on the 8-device CPU mesh: one device_put per leaf
    re-lays the tree onto a new NamedSharding."""
    from ray_tpu.weights import jax_reshard
    from ray_tpu.utils import import_jax

    jax = import_jax()
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    tree = _tree()
    out = jax_reshard(tree, {"data": 4, "model": 2},
                      {"layer0/w": ("data", "model"),
                       "layer0/b": ("model",)})
    w = out["layer0"]["w"]
    assert len(w.sharding.device_set) == 8
    np.testing.assert_array_equal(np.asarray(w), tree["layer0"]["w"])
    np.testing.assert_array_equal(np.asarray(out["step"]), tree["step"])


# ---------------------------------------------------------------------------
# collective redistribution lowering (portable, no-gather by construction)
# ---------------------------------------------------------------------------


def test_lower_collective_rounds_and_cost_model():
    from ray_tpu.weights import DcnCostModel, lower_collective

    tree = {"w": np.arange(1024, dtype=np.float32).reshape(8, 128)}
    src = ShardedTreeSpec.from_tree(
        tree, MeshSpec((4,), ("data",), tuple(f"t{i}" for i in range(4))),
        default_part=("data",))
    dst = ShardedTreeSpec.from_tree(
        tree, MeshSpec((2,), ("model",), ("s0", "s1")),
        parts={"w": (None, "model")})
    plan = plan_reshard(src, dst)
    cm = DcnCostModel(node_of=lambda h: "A" if h in ("t0", "t1", "s0")
                      else "B")
    prog = lower_collective(plan, cm)
    st = prog.stats()
    # every non-local edge is scheduled exactly once
    assert st["num_edges"] == sum(1 for e in plan.edges if not e.local)
    assert sorted(i for rnd in prog.rounds for i in rnd) == \
        [i for i, e in enumerate(plan.edges) if not e.local]
    # the DCN/ICI split follows the node mapping and prices the estimate
    assert st["dcn_bytes"] + st["ici_bytes"] == plan.bytes_moved()
    assert st["dcn_bytes"] > 0 and st["est_seconds"] > 0
    # a tight in-flight budget forces more rounds, each within budget
    one_edge = max(e.nbytes for e in plan.edges if not e.local)
    tight = lower_collective(plan, cm, inflight_limit_bytes=one_edge)
    assert len(tight.rounds) > len(prog.rounds)
    assert tight.max_round_host_bytes() <= one_edge


def test_lower_collective_refuses_gather_and_logs_fallback():
    from ray_tpu.weights import (ReshardLoweringError, lower_collective,
                                 lowering_fallback_counts,
                                 maybe_lower_collective)

    tree = {"w": np.arange(64, dtype=np.float32).reshape(8, 8)}
    src = ShardedTreeSpec.from_tree(
        tree, MeshSpec((4,), ("data",), tuple(f"t{i}" for i in range(4))),
        default_part=("data",))
    # dst axis of size 1: one host would hold the full (non-replicated-
    # by-declaration) leaf — the gather the lowering must refuse
    dst = ShardedTreeSpec.from_tree(
        tree, MeshSpec((1,), ("x",), ("g0",)), parts={"w": ("x",)})
    plan = plan_reshard(src, dst)
    assert not plan.no_gather()
    with pytest.raises(ReshardLoweringError):
        lower_collective(plan)
    before = lowering_fallback_counts().get("plan_not_no_gather", 0)
    assert maybe_lower_collective(plan) is None  # logged, never silent
    assert lowering_fallback_counts()["plan_not_no_gather"] == before + 1


@ray_tpu.remote(num_cpus=0.3)
class _ProgramMember:
    """Collective-group member executing a pre-lowered redistribution
    program (the bounded-in-flight path under collective_reshard)."""

    def __init__(self, rank, world, src_spec, dst_spec, limit):
        from ray_tpu import collective as col
        from ray_tpu.weights import lower_collective

        self.rank = rank
        self.src = src_spec
        self.dst = dst_spec
        plan = plan_reshard(src_spec, dst_spec)
        assert plan.no_gather()
        self.program = lower_collective(plan, inflight_limit_bytes=limit)
        self.group = col.init_collective_group(world, rank, backend="cpu",
                                               group_name="wp_redist")

    def run(self):
        from ray_tpu.weights import redistribute

        host = self.src.mesh.hosts[self.rank]
        shards = local_shards_of(_tree(), self.src, host)
        out = redistribute(self.program, self.group, host, shards)
        return {leaf: {str(b): a for b, a in boxes.items()}
                for leaf, boxes in out.items()}


def test_redistribute_program_multi_round(cluster):
    """A byte-tight in-flight budget splits the exchange into many
    rounds; the round-sequenced execution still lands every byte."""
    tree = _tree()
    mesh = MeshSpec((2,), ("x",), ("m0", "m1"))
    src = ShardedTreeSpec.from_tree(
        tree, mesh, parts={"layer0/w": ("x",), "layer0/b": ("x",),
                           "step": ()})
    dst = ShardedTreeSpec.from_tree(
        tree, mesh, parts={"layer0/w": (None, "x"), "layer0/b": ("x",),
                           "step": ()})
    plan = plan_reshard(src, dst)
    biggest = max(e.nbytes for e in plan.edges if not e.local)
    members = [_ProgramMember.remote(i, 2, src, dst, biggest)
               for i in range(2)]
    out = ray_tpu.get([m.run.remote() for m in members], timeout=120)
    for i, res in enumerate(out):
        np.testing.assert_array_equal(
            res["layer0/w"][f"((0, 8), ({i * 4}, {i * 4 + 4}))"],
            tree["layer0"]["w"][:, i * 4:(i + 1) * 4])
    for m in members:
        ray_tpu.kill(m)


def test_jax_reshard_transition_no_rematerialization(cluster):
    """Regression for the MULTICHIP_r05 warning: a device-tier sharding
    TRANSITION (live jax.Array -> different layout) must take the
    explicit shard-assembly lowering — zero bare cross-sharding
    device_puts, zero XLA "involuntary full rematerialization" output."""
    import logging
    import warnings

    from ray_tpu.utils import import_jax
    from ray_tpu.weights import jax_reshard, reshard_lowering_stats
    from ray_tpu.weights.transport import reset_reshard_lowering_stats

    jax = import_jax()
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    tree = {"w": np.arange(4096, dtype=np.float32).reshape(64, 64),
            "b": np.arange(64, dtype=np.float32)}
    reset_reshard_lowering_stats()
    records = []
    handler = logging.Handler()
    handler.emit = lambda rec: records.append(rec.getMessage())
    root = logging.getLogger()
    root.addHandler(handler)
    try:
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            # host upload, then two live transitions across layouts
            t1 = jax_reshard(tree, {"data": 8}, {"w": ("data",),
                                                 "b": ("data",)})
            t2 = jax_reshard(t1, {"data": 4, "model": 2},
                             {"w": ("data", "model"), "b": ("data",)})
            t3 = jax_reshard(t2, {"data": 2, "model": 4},
                             {"w": ("model", "data"), "b": (None,)})
    finally:
        root.removeHandler(handler)
    np.testing.assert_array_equal(np.asarray(t3["w"]), tree["w"])
    np.testing.assert_array_equal(np.asarray(t3["b"]), tree["b"])
    stats = reshard_lowering_stats()
    assert stats["host_put"] == 2           # the initial upload
    assert stats["lowered"] >= 3            # every live transition
    assert stats["fallback"] == 0           # no bare cross-sharding put
    spill = [m for m in records if "rematerialization" in m.lower()]
    spill += [str(w.message) for w in caught
              if "rematerialization" in str(w.message).lower()]
    assert not spill, spill


# ---------------------------------------------------------------------------
# delta + quantized publishes (the compression tier of the weight plane)
# ---------------------------------------------------------------------------


def _delta_tree(rng, n_leaves=8, rows=128):
    return {f"l{i}": rng.normal(size=(rows, 64)).astype(np.float32)
            for i in range(n_leaves)}


def test_delta_publish_byte_exact_and_under_half_bytes(cluster):
    """A small-update delta publish ships only the changed chunks
    (< 50% of full-publish bytes) and pulls stay BYTE-exact against the
    logical tree — unchanged leaves alias the base version's chunks by
    content address."""
    rng = np.random.default_rng(0)
    tree = _delta_tree(rng)
    store = WeightStore("w_delta")
    v1 = store.publish(tree, durable=True)
    tree2 = dict(tree)
    tree2["l3"] = tree["l3"] + 1.0  # 1 of 8 leaves changed
    v2 = store.publish(tree2, durable=True, delta_from=v1)
    pulled = store.pull(v2)
    for k in tree2:
        np.testing.assert_array_equal(pulled[k], tree2[k])
    vs = store.stats()["versions"]
    full, delta = vs[str(v1)], vs[str(v2)]
    assert delta["bytes_published"] < 0.5 * full["bytes_published"], \
        (full, delta)
    assert delta["bytes_reused"] == 7 * tree["l0"].nbytes


def test_chained_deltas_survive_retention(cluster):
    """v3/v4 delta off their predecessors; retention (keep=2) retires the
    intermediate versions, but the aliased chunk entries keep the refs
    alive — the newest delta version still pulls byte-exact."""
    rng = np.random.default_rng(1)
    tree = _delta_tree(rng)
    store = WeightStore("w_chain")
    v = store.publish(tree, durable=True)
    for i in range(3):  # three chained deltas -> the base retires
        tree = dict(tree)
        tree[f"l{i}"] = tree[f"l{i}"] * 2.0 + i
        v = store.publish(tree, durable=True, delta_from=v)
    pulled = store.pull(v)
    for k in tree:
        np.testing.assert_array_equal(pulled[k], tree[k])
    # the earliest version really is retired (not silently kept)
    vs = sorted(int(x) for x in store.stats()["versions"])
    with pytest.raises(Exception):
        store.manifest(vs[0])


def test_delta_base_vanished_falls_back_to_full(cluster):
    rng = np.random.default_rng(2)
    tree = _delta_tree(rng, n_leaves=4)
    store = WeightStore("w_fall")
    for _ in range(4):  # roll versions so v1 retires
        store.publish(tree, durable=True)
    v = store.publish(tree, durable=True, delta_from=1)  # retired base
    vs = store.stats()["versions"][str(v)]
    assert vs["bytes_reused"] == 0  # full publish, no silent aliasing
    pulled = store.pull(v)
    for k in tree:
        np.testing.assert_array_equal(pulled[k], tree[k])


def test_quantized_publish_pull_and_compose_with_delta(cluster):
    """Quantized chunk encoding: int8 publish ships <30% of the raw
    bytes, pulls (full AND sharded) transparently dequantize, and an
    unchanged delta on top of a quantized base reuses every chunk (delta
    hashing keys on RAW bytes, so the tiers compose)."""
    rng = np.random.default_rng(3)
    tree = _delta_tree(rng)
    raw = sum(a.nbytes for a in tree.values())
    store = WeightStore("w_quant")
    v1 = store.publish(tree, durable=True, compression="int8")
    p1 = store.pull(v1)
    for k in tree:
        rel = np.abs(p1[k] - tree[k]).max() / np.abs(tree[k]).max()
        assert rel < 0.02, (k, rel)
    vs = store.stats()["versions"]
    assert vs[str(v1)]["bytes_published"] < 0.3 * raw
    # sharded pull decodes the same bytes
    dst_mesh = MeshSpec((2,), ("data",), ("h0", "h1"))
    dst = ShardedTreeSpec.from_tree(tree, dst_mesh, default_part=("data",))
    shards = store.pull_shards(dst, "h0", v1)
    box = next(iter(shards["l0"]))
    np.testing.assert_array_equal(shards["l0"][box], p1["l0"][:64])
    # delta on an unchanged tree: zero new bytes, pulls match the base
    v2 = store.publish(tree, durable=True, delta_from=v1,
                       compression="int8")
    assert store.stats()["versions"][str(v2)]["bytes_published"] == 0
    p2 = store.pull(v2)
    for k in tree:
        np.testing.assert_array_equal(p2[k], p1[k])


def test_plain_publish_unchanged_by_compression_tier(cluster):
    """Regression guard: the default publish writes NO encodings into the
    manifest and pulls are bitwise-identical — the compression tier is
    strictly opt-in."""
    rng = np.random.default_rng(4)
    tree = _delta_tree(rng, n_leaves=3)
    store = WeightStore("w_plain")
    v = store.publish(tree, durable=True)
    man = store.manifest(v)
    for c in man["chunks"].values():
        assert c["enc"] is None
        assert c["sha"]  # content address recorded for future deltas
    pulled = store.pull(v)
    for k in tree:
        np.testing.assert_array_equal(pulled[k], tree[k])


def test_learner_group_delta_quantized_publish(cluster):
    """The rl publish path: LearnerGroup.publish_weights(delta=True)
    publishes against the learner's previous version; with compression
    the env-runner-facing pull dequantizes transparently."""
    from ray_tpu.rl.learner_group import LearnerGroup

    group = LearnerGroup(_toy_factory, num_learners=2)
    try:
        store = WeightStore("w_lg")
        v1 = group.publish_weights("w_lg", durable=True, delta=True)
        v2 = group.publish_weights("w_lg", durable=True, delta=True)
        vs = store.stats()["versions"]
        # params unchanged between publishes -> the second is all-reuse
        assert vs[str(v2)]["bytes_published"] == 0
        assert vs[str(v2)]["bytes_reused"] > 0
        t1, t2 = store.pull(v1), store.pull(v2)
        for k in t1:
            np.testing.assert_array_equal(t1[k], t2[k])
        v3 = group.publish_weights("w_lg", durable=True, delta=True,
                                   compression="int8")
        assert store.latest() == v3
    finally:
        group.shutdown()

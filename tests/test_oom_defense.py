"""OOM defense: a worker that allocates unboundedly is killed by the node
memory monitor (group-by-owner, newest first) — the NODE survives, other
tasks keep running, and the task's owner sees OutOfMemoryError.

Reference: src/ray/common/memory_monitor.h:52,
src/ray/raylet/worker_killing_policy_group_by_owner.cc,
python/ray/tests/test_memory_pressure.py scenarios.

Uses the deterministic budget accounting mode
(RAY_TPU_MEMORY_MONITOR_CAPACITY_BYTES): usage = worker RSS / budget, so
the test is independent of the CI host's real memory pressure.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu.exceptions import OutOfMemoryError, TaskError


@pytest.fixture
def oom_cluster():
    ray_tpu.shutdown()
    # 500 MiB worker-RSS budget; the hog allocates well past it
    os.environ["RAY_TPU_MEMORY_MONITOR_CAPACITY_BYTES"] = str(500 * 1024 * 1024)
    os.environ["RAY_TPU_MEMORY_USAGE_THRESHOLD"] = "0.9"
    try:
        ray_tpu.init(num_cpus=4)
        yield ray_tpu
    finally:
        del os.environ["RAY_TPU_MEMORY_MONITOR_CAPACITY_BYTES"]
        del os.environ["RAY_TPU_MEMORY_USAGE_THRESHOLD"]
        ray_tpu.shutdown()


def test_memory_hog_killed_node_survives(oom_cluster):
    @ray_tpu.remote(max_retries=0, num_cpus=1.0)
    def hog():
        blocks = []
        while True:  # allocate ~50 MiB/step until the monitor intervenes
            blocks.append(bytearray(os.urandom(50 * 1024 * 1024)))
            time.sleep(0.1)

    @ray_tpu.remote(num_cpus=1.0)
    def fine(i):
        return i * 2

    ref = hog.remote()
    with pytest.raises(OutOfMemoryError):
        ray_tpu.get(ref, timeout=180)
    # the node survived: fresh tasks still schedule and run
    assert ray_tpu.get([fine.remote(i) for i in range(4)],
                       timeout=120) == [0, 2, 4, 6]


def test_victim_policy_group_by_owner_newest_first():
    from ray_tpu._private.memory_monitor import MemoryMonitor

    workers = [
        {"pid": 1, "job": "a", "started": 10.0},
        {"pid": 2, "job": "a", "started": 30.0},
        {"pid": 3, "job": "a", "started": 20.0},
        {"pid": 4, "job": "b", "started": 40.0},
    ]
    v = MemoryMonitor.pick_victim(workers)
    # job "a" is the largest group; its newest member (pid 2) dies first
    assert v["pid"] == 2

    assert MemoryMonitor.pick_victim([]) is None

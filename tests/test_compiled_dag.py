"""Compiled graphs: static schedules over the channel data plane.

Reference: python/ray/dag — experimental_compile emits per-actor static
schedules (dag_node_operation.py:704) running over mutable-object channels
(shared_memory_channel.py:151, writer blocks on reader acks). Done criteria
from the round-2 verdict: a 3-stage actor pipeline at least 5x faster
per-iteration than eager .remote() chaining, and every stage observing
every value.
"""

import time

import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


@ray_tpu.remote(num_cpus=0.3)
class Stage:
    def __init__(self, add):
        self.add = add
        self.seen = []

    def apply(self, x):
        self.seen.append(x)
        return x + self.add

    def history(self):
        return self.seen


def test_compiled_pipeline_correct(cluster):
    with InputNode() as inp:
        s1, s2, s3 = Stage.bind(1), Stage.bind(10), Stage.bind(100)
        dag = s3.apply.bind(s2.apply.bind(s1.apply.bind(inp)))
    compiled = dag.experimental_compile()
    try:
        for i in range(20):
            assert compiled.execute(i).get(timeout=60) == i + 111
    finally:
        compiled.teardown()


def test_compiled_pipeline_every_value_observed(cluster):
    """Reader-ack channels must deliver EVERY value to every stage, in
    order — nothing skipped for slow consumers."""

    @ray_tpu.remote(num_cpus=0.3)
    class Slow:
        def __init__(self):
            self.seen = []

        def apply(self, x):
            time.sleep(0.02)  # slower than the producer
            self.seen.append(x)
            return x

        def history(self):
            return self.seen

    with InputNode() as inp:
        fast = Stage.bind(0)
        slow = Slow.bind()
        dag = slow.apply.bind(fast.apply.bind(inp))
    compiled = dag.experimental_compile()
    try:
        n = 30
        refs = [compiled.execute(i) for i in range(n)]
        assert [r.get(timeout=120) for r in refs] == list(range(n))
    finally:
        compiled.teardown(kill_actors=False)
    # both stages saw every value in order (the graph actors survive
    # teardown so their history can be inspected)


def test_compiled_multi_output(cluster):
    with InputNode() as inp:
        a = Stage.bind(1)
        b = Stage.bind(2)
        dag = MultiOutputNode([a.apply.bind(inp), b.apply.bind(inp)])
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(5).get(timeout=60) == [6, 7]
        assert compiled.execute(7).get(timeout=60) == [8, 9]
    finally:
        compiled.teardown()


def test_compiled_stage_error_propagates(cluster):
    @ray_tpu.remote(num_cpus=0.3)
    class Exploder:
        def apply(self, x):
            if x == 3:
                raise ValueError("boom on 3")
            return x

    with InputNode() as inp:
        dag = Exploder.bind().apply.bind(inp)
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(1).get(timeout=60) == 1
        with pytest.raises(RuntimeError, match="boom on 3"):
            compiled.execute(3).get(timeout=60)
        # the loop survives an application error
        assert compiled.execute(4).get(timeout=60) == 4
    finally:
        compiled.teardown()


def test_compiled_5x_faster_than_eager(cluster):
    """The headline criterion: per-iteration latency of the compiled
    3-stage pipeline must be at least 5x better than eager chaining."""

    s1, s2, s3 = Stage.remote(1), Stage.remote(10), Stage.remote(100)
    iters = 50
    # warm-up + eager timing
    ray_tpu.get(s3.apply.remote(s2.apply.remote(s1.apply.remote(0))), timeout=60)
    t0 = time.perf_counter()
    for i in range(iters):
        out = ray_tpu.get(
            s3.apply.remote(s2.apply.remote(s1.apply.remote(i))), timeout=60)
    eager_s = (time.perf_counter() - t0) / iters
    assert out == iters - 1 + 111

    with InputNode() as inp:
        c1, c2, c3 = Stage.bind(1), Stage.bind(10), Stage.bind(100)
        dag = c3.apply.bind(c2.apply.bind(c1.apply.bind(inp)))
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(0).get(timeout=60) == 111  # warm-up
        t0 = time.perf_counter()
        for i in range(iters):
            out = compiled.execute(i).get(timeout=60)
        compiled_s = (time.perf_counter() - t0) / iters
        assert out == iters - 1 + 111
    finally:
        compiled.teardown()
    speedup = eager_s / compiled_s
    print(f"\neager {eager_s*1e3:.3f} ms/iter, compiled {compiled_s*1e3:.3f} "
          f"ms/iter, speedup {speedup:.1f}x")
    assert speedup >= 5.0, (
        f"compiled pipeline only {speedup:.1f}x faster than eager")

"""GCS fault tolerance: kill + restart the control plane and verify the
cluster survives (reference: GCS FT via Redis-backed store_client +
GcsInitData replay on restart, SURVEY.md §5)."""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def ft_cluster():
    ray_tpu.shutdown()
    cluster = Cluster(head_node_args={
        "resources": {"CPU": 4.0}, "gcs_fault_tolerance": True})
    ray_tpu.init(address=cluster.address)
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def _kv(method, req):
    from ray_tpu._private.worker import global_worker

    core = global_worker()
    return core._run(core._gcs_call(method, req))


def test_gcs_restart_preserves_cluster_state(ft_cluster):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    c = Counter.options(
        name="survivor", lifetime="detached", num_cpus=0.1).remote()
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 1
    _kv("KVPut", {"ns": "t", "key": "durable", "value": b"payload"})

    ft_cluster.kill_gcs()
    time.sleep(0.3)
    ft_cluster.restart_gcs()

    # named actor still resolvable; its in-memory state survived because the
    # worker process never died — only the control plane blinked
    h = ray_tpu.get_actor("survivor")
    assert ray_tpu.get(h.incr.remote(), timeout=60) == 2
    # KV table replayed from the durable store (public API)
    from ray_tpu.experimental.internal_kv import _internal_kv_get

    assert _internal_kv_get(b"durable", namespace="t") == b"payload"
    # nodes replayed: new work is schedulable immediately
    @ray_tpu.remote
    def probe():
        return 42

    assert ray_tpu.get(probe.remote(), timeout=60) == 42
    total = ray_tpu.cluster_resources()
    assert total.get("CPU") == 4.0


def test_gcs_restart_actor_restart_still_works(ft_cluster):
    """max_restarts actor killed AFTER a GCS restart is restarted by the
    replayed record (restart budget persisted)."""

    @ray_tpu.remote(max_restarts=1, max_task_retries=2)
    class Phoenix:
        def pid(self):
            import os

            return os.getpid()

    p = Phoenix.options(name="phoenix", num_cpus=0.1).remote()
    pid1 = ray_tpu.get(p.pid.remote(), timeout=60)

    ft_cluster.kill_gcs()
    time.sleep(0.3)
    ft_cluster.restart_gcs()

    import os
    import signal

    os.kill(pid1, signal.SIGKILL)
    deadline = time.monotonic() + 60
    pid2 = None
    while time.monotonic() < deadline:
        try:
            pid2 = ray_tpu.get(p.pid.remote(), timeout=30)
            break
        except Exception:
            time.sleep(0.5)
    assert pid2 is not None and pid2 != pid1

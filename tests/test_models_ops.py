"""Model + ops tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.models import CONFIGS, Transformer, lm_loss
from ray_tpu.ops.attention import flash_attention, reference_attention
from ray_tpu.ops.ring_attention import ring_attention, ulysses_attention
from ray_tpu.parallel import TrainStepBundle, create_mesh


def test_flash_matches_reference_interpret():
    rng = jax.random.PRNGKey(0)
    B, S, H, D = 2, 256, 4, 64
    q, k, v = (jax.random.normal(r, (B, S, H, D), jnp.float32)
               for r in jax.random.split(rng, 3))
    ref = reference_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, True, True)  # interpret mode
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-2)


def test_flash_grads_match():
    rng = jax.random.PRNGKey(1)
    B, S, H, D = 1, 128, 2, 64
    q, k, v = (jax.random.normal(r, (B, S, H, D), jnp.float32)
               for r in jax.random.split(rng, 3))

    def f_ref(q, k, v):
        return reference_attention(q, k, v, True).sum()

    def f_flash(q, k, v):
        return flash_attention(q, k, v, True, True).sum()

    g_ref = jax.grad(f_ref)(q, k, v)
    g_flash = jax.grad(f_flash)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_flash), np.asarray(g_ref),
                               atol=2e-3, rtol=2e-2)


def test_ring_attention_matches_reference():
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = create_mesh({"seq": 8})
    rng = jax.random.PRNGKey(2)
    B, S, H, D = 2, 64, 2, 16
    q, k, v = (jax.random.normal(r, (B, S, H, D), jnp.float32)
               for r in jax.random.split(rng, 3))

    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq", causal=True),
        mesh=mesh, in_specs=(P(None, "seq"),) * 3, out_specs=P(None, "seq"),
        check_rep=False)
    out = ring(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-2)


def test_ulysses_matches_reference():
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = create_mesh({"seq": 2}, devices=jax.devices()[:2])
    rng = jax.random.PRNGKey(3)
    B, S, H, D = 2, 32, 4, 16
    q, k, v = (jax.random.normal(r, (B, S, H, D), jnp.float32)
               for r in jax.random.split(rng, 3))
    uly = shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "seq", causal=True),
        mesh=mesh, in_specs=(P(None, "seq"),) * 3, out_specs=P(None, "seq"),
        check_rep=False)
    out = uly(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-2)


def test_tiny_model_forward_and_loss():
    cfg = CONFIGS["tiny"]
    model = Transformer(cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    logits = model.apply({"params": params}, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    loss = lm_loss(logits, tokens)
    assert np.isfinite(float(loss))


def test_train_step_dp_fsdp_tp():
    """Full train step jitted over a dp*fsdp*tp mesh: loss decreases."""
    cfg = CONFIGS["tiny"]
    mesh = create_mesh({"data": 2, "fsdp": 2, "seq": 1, "tensor": 2})
    bundle = TrainStepBundle(cfg, mesh)
    params, opt_state = bundle.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    batch = bundle.make_batch(rng, batch_size=4, seq_len=64)
    losses = []
    for _ in range(5):
        params, opt_state, loss = bundle.step(params, opt_state, batch)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]  # memorizing one batch


def test_param_shardings_cover_mesh():
    cfg = CONFIGS["tiny"]
    mesh = create_mesh({"data": 1, "fsdp": 4, "seq": 1, "tensor": 2})
    bundle = TrainStepBundle(cfg, mesh)
    specs = jax.tree.leaves(
        bundle.param_shardings, is_leaf=lambda x: hasattr(x, "spec"))
    assert any("tensor" in str(s.spec) for s in specs)
    assert any("fsdp" in str(s.spec) for s in specs)

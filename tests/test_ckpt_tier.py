"""Storage-tier plane tests (ray_tpu/ckpt/tier/).

Covers the tier's acceptance properties:
(a) backend contract — LocalFS / bucket (+ object-plane, under a
    cluster) behave identically behind ``ChunkBackend``, including
    multipart uploads whose aborted halves are never visible;
(b) parallel IO — bounded fetch with sha256 verification (corrupt remote
    bytes are *rejected*, with per-chunk fallback to the local tier),
    range coalescing, in-flight byte-cap progress;
(c) crash/fault lifecycle — a mirror pump killed mid-upload never
    reports residency ``remote``; re-mirroring is idempotent by content
    address and uploads only the remainder;
(d) retention sweeper — never reaps a chunk reachable from a pinned or
    in-flight (part-file) manifest, on either tier, regardless of age;
(e) elastic restore-through-the-tier — a 4-host sharded save mirrors,
    evicts locally, and restores byte-exact onto a 2-host mesh pulling
    ONLY the intersecting chunks from the remote tier (per-host byte
    and per-op accounting).
"""

import hashlib
import json
import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import ckpt
from ray_tpu.ckpt import manifest as mf
from ray_tpu.ckpt.tier.backend import (
    BackendUnavailable,
    backend_from_descriptor,
)
from ray_tpu.ckpt.tier.pario import (
    ChunkFetchError,
    ParallelIO,
    coalesce_ranges,
)
from ray_tpu.ckpt.tier.sweeper import SweepPolicy, sweep_store
from ray_tpu.weights.spec import (
    MeshSpec,
    ShardedTreeSpec,
    box_slices,
    host_boxes,
)


def _tree(scale: float = 1.0, leaves: int = 6, n: int = 256):
    # distinct content per leaf: content addressing must not collapse
    # the tree into one chunk
    return {f"layer{i}": np.arange(n, dtype=np.float32) * scale + i
            for i in range(leaves)}


def _bucket_store(tmp_path, name, **kw):
    shim = ckpt.FaultShim(ckpt.DirBucketClient(str(tmp_path / "bucket")))
    store = ckpt.TieredStore(str(tmp_path / name), name=name,
                             backend=ckpt.BucketBackend(shim), **kw)
    return store, shim


# ---------------------------------------------------------------------------
# (a) backend contract
# ---------------------------------------------------------------------------


def _backends(tmp_path):
    return [
        ckpt.LocalFSBackend(str(tmp_path / "localfs")),
        ckpt.BucketBackend(ckpt.DirBucketClient(str(tmp_path / "bucket")),
                           prefix="tierA"),
    ]


def test_backend_contract(tmp_path):
    data = b"tier chunk payload bytes"
    h = hashlib.sha256(data).hexdigest()
    for backend in _backends(tmp_path):
        assert backend.put(h, data) is True
        assert backend.put(h, data) is False  # content-addressed dedup
        assert backend.has(h)
        assert backend.get(h) == data
        assert backend.get(h, offset=5, length=7) == data[5:12]
        assert backend.list_chunks() == {h: len(data)}
        mt = backend.chunk_mtime(h)
        assert mt is not None and abs(mt - time.time()) < 60
        with pytest.raises(KeyError):
            backend.get("0" * 64)
        # manifests ride the same contract
        backend.put_manifest("step0000000001-aa", b'{"x": 1}')
        assert backend.get_manifest("step0000000001-aa") == b'{"x": 1}'
        assert backend.list_manifests() == ["step0000000001-aa"]
        with pytest.raises(KeyError):
            backend.get_manifest("step0000000009-zz")
        st = backend.stats()
        assert st["num_chunks"] == 1 and st["chunk_bytes"] == len(data)
        # descriptor round-trip: an equivalent backend in another process
        clone = backend_from_descriptor(backend.descriptor())
        assert clone.has(h) and clone.get(h) == data
        assert clone.list_manifests() == ["step0000000001-aa"]
        backend.delete(h)
        assert not backend.has(h)
        backend.delete(h)  # idempotent
        backend.delete_manifest("step0000000001-aa")
        assert backend.list_manifests() == []


def test_bucket_multipart_upload_and_aborted_invisible(tmp_path):
    shim = ckpt.FaultShim(ckpt.DirBucketClient(str(tmp_path / "b")))
    backend = ckpt.BucketBackend(shim, multipart_bytes=1024)
    data = bytes(range(256)) * 20  # 5120 B: 5 parts above the threshold
    h = hashlib.sha256(data).hexdigest()
    assert backend.put(h, data) is True
    assert shim.ops("create_multipart") == 1
    assert shim.ops("upload_part") == 5
    assert shim.ops("complete_multipart") == 1
    assert backend.get(h) == data
    # ranged read across a part boundary
    assert backend.get(h, offset=1000, length=100) == data[1000:1100]

    # a multipart that dies mid-part is aborted and never visible
    shim.fail_after = shim.ops("upload_part") + 2
    shim.fail_ops = ("upload_part",)
    data2 = bytes(reversed(data))
    h2 = hashlib.sha256(data2).hexdigest()
    with pytest.raises(BackendUnavailable):
        backend.put(h2, data2)
    assert not backend.has(h2)
    assert backend.list_chunks() == {h: len(data)}
    # no staging leftovers leak into the object listing
    assert all("multipart" not in k
               for k in shim.client.list_objects(""))


# ---------------------------------------------------------------------------
# (b) parallel IO: coalescing, verification, byte-cap progress
# ---------------------------------------------------------------------------


def test_coalesce_ranges():
    assert coalesce_ranges([], 64) == []
    # unsorted input, overlapping + gap-mergeable spans
    out = coalesce_ranges([(100, 10), (0, 10), (15, 5), (300, 8)], gap=8)
    assert out == [(0, 20), (100, 10), (300, 8)]
    # zero-length ranges drop; gap=0 merges only touching spans
    assert coalesce_ranges([(0, 4), (4, 4), (9, 4), (2, 0)], gap=0) == [
        (0, 8), (9, 4)]


def test_parallel_fetch_verifies_and_reports_per_chunk(tmp_path):
    root = str(tmp_path / "pool")
    backend = ckpt.LocalFSBackend(root)
    sizes = {}
    datas = {}
    for i in range(8):
        data = bytes([i]) * 100
        h, created = mf.write_chunk(root, data)
        assert created
        sizes[h] = len(data)
        datas[h] = data
    # cap far below the batch total: workers queue on the gate but every
    # chunk still lands (progress is guaranteed, an oversized chunk is
    # admitted alone)
    io = ParallelIO(backend, threads=4, inflight_bytes=150, coalesce_gap=16)
    out = io.fetch(dict(sizes))
    assert out == datas
    assert io.counters["fetch_chunks"] == 8
    # corrupt ONE chunk on disk: the fetch rejects it by sha256 and the
    # other seven arrive as the verified partial result
    bad = sorted(sizes)[0]
    with open(mf.chunk_path(root, bad), "wb") as f:
        f.write(b"\xff" + datas[bad][1:])
    with pytest.raises(ChunkFetchError) as ei:
        io.fetch(dict(sizes))
    assert set(ei.value.errors) == {bad}
    assert len(ei.value.partial) == 7
    assert ei.value.partial[sorted(sizes)[1]] == datas[sorted(sizes)[1]]
    assert io.counters["verify_failures"] == 1


def test_read_ranges_coalesces_round_trips(tmp_path):
    shim = ckpt.FaultShim(ckpt.DirBucketClient(str(tmp_path / "b")))
    backend = ckpt.BucketBackend(shim)
    data = bytes(i % 251 for i in range(4096))
    h = hashlib.sha256(data).hexdigest()
    backend.put(h, data)
    io = ParallelIO(backend, threads=2, coalesce_gap=64)
    before = shim.ops("get")
    ranges = [(0, 16), (40, 16), (2000, 32), (3000, 8)]
    out = io.read_ranges(h, ranges)
    assert out == [data[off:off + ln] for off, ln in ranges]
    # (0,16)+(40,16) coalesce (gap 24 <= 64); the far two stay separate
    assert shim.ops("get") - before == 3


# ---------------------------------------------------------------------------
# tiered lifecycle: commit -> mirror pump -> evict -> read-through restore
# ---------------------------------------------------------------------------


def test_tier_smoke_save_mirror_evict_restore(tmp_path):
    """Tier-1 smoke: async save -> pump mirrors -> evict local bytes ->
    restore pulls from the (fault-shimmed) remote tier, byte-exact."""
    store, shim = _bucket_store(tmp_path, "smoke", mirror=True)
    try:
        tree = _tree(1.5)
        man = ckpt.save_checkpoint(store, tree, step=1)
        entry = store.wait_mirrored(man.ckpt_id, timeout=30.0)
        assert entry["state"] == "remote"
        assert entry["upload_chunks"] == len(man.chunk_set())
        assert store.verify(man.ckpt_id)["ok"]
        out = store.evict_local(man.ckpt_id)
        assert out["evicted_chunks"] == len(man.chunk_set())
        for h in man.chunk_set():
            assert not os.path.exists(mf.chunk_path(store.root, h))
        assert store.residency()[man.ckpt_id]["evicted"]
        restored = ckpt.restore_tree(store)
        for k, arr in tree.items():
            np.testing.assert_array_equal(restored[k], arr)
        # read-through cached the chunks back into the local pool
        for h in man.chunk_set():
            assert os.path.exists(mf.chunk_path(store.root, h))
        # residency rides the store stats for the state API / dashboard
        rows = {r["ckpt_id"]: r for r in store.stats()["checkpoints"]}
        assert rows[man.ckpt_id]["residency"] == "evicted"
    finally:
        store.close()


def test_mirror_dedup_across_steps(tmp_path):
    store, shim = _bucket_store(tmp_path, "dedup", mirror=False)
    try:
        tree = _tree(2.0)
        m1 = ckpt.save_checkpoint(store, tree, step=1)
        c1 = store.mirror_now(m1.ckpt_id)
        assert c1["upload_chunks"] == len(m1.chunk_set())
        assert c1["dedup_chunks"] == 0
        tree["layer0"] = tree["layer0"] + 0.25  # 1-of-6 delta (no other
        # layer's content collides with a fractional shift)
        m2 = ckpt.save_checkpoint(store, tree, step=2)
        c2 = store.mirror_now(m2.ckpt_id)
        assert c2["upload_chunks"] == 1  # only the changed leaf moves
        assert c2["dedup_chunks"] == len(m2.chunk_set()) - 1
        # re-mirroring an already-remote checkpoint uploads nothing
        c3 = store.mirror_now(m2.ckpt_id)
        assert c3["upload_chunks"] == 0
        assert c3["dedup_chunks"] == len(m2.chunk_set())
    finally:
        store.close()


def test_pump_killed_mid_upload_never_remote_then_idempotent(tmp_path):
    """(c) the crash contract: a mirror that dies mid-upload leaves
    residency ``mirroring`` (never ``remote``), uploads no manifest, and
    an explicit re-mirror after the fault clears uploads only the
    chunks the first attempt did not land."""
    store, shim = _bucket_store(tmp_path, "crash", mirror=True,
                                io_threads=2)
    try:
        # let 2 chunk uploads through, then the backend "dies"
        shim.fail_after = 2
        shim.fail_ops = ("put",)
        man = ckpt.save_checkpoint(store, _tree(3.0), step=1)
        total = len(man.chunk_set())
        assert total == 6
        with pytest.raises(RuntimeError, match="mirror of"):
            store.wait_mirrored(man.ckpt_id, timeout=30.0)
        entry = store.residency()[man.ckpt_id]
        assert entry["state"] == "mirroring"  # never presented as durable
        assert "BackendUnavailable" in entry["error"]
        # the partially-uploaded checkpoint has NO remote manifest: a
        # remote reader can never see a checkpoint missing its chunks
        assert store.backend.list_manifests() == []
        landed = len(store.backend.list_chunks())
        assert 0 < landed < total

        # fault clears -> re-mirror is idempotent by content address
        shim.clear_fault()
        c = store.mirror_now(man.ckpt_id)
        assert c["upload_chunks"] == total - landed  # only the remainder
        assert c["dedup_chunks"] == landed
        assert store.residency()[man.ckpt_id]["state"] == "remote"
        assert store.verify(man.ckpt_id, deep=True)["ok"]
    finally:
        store.close()


def test_corrupt_remote_rejected_with_local_fallback(tmp_path):
    store, shim = _bucket_store(tmp_path, "corrupt", mirror=False)
    try:
        tree = _tree(4.0)
        man = ckpt.save_checkpoint(store, tree, step=1)
        store.mirror_now(man.ckpt_id)
        sizes = man.chunk_set()
        shim.corrupt_get = lambda key: "chunks/" in key
        # deep verify detects every corrupted chunk
        report = store.verify(man.ckpt_id, deep=True)
        assert not report["ok"]
        assert report["corrupt_chunks"] == len(sizes)
        # prefer="remote" (verification-style read) falls back per chunk
        # to the intact local copy instead of failing the batch
        out = store.fetch_chunks(dict(sizes), prefer="remote")
        for h in sizes:
            assert hashlib.sha256(out[h]).hexdigest() == h
        assert store.io.counters["verify_failures"] >= len(sizes)
        # with the local copy evicted too, corrupt bytes are an ERROR —
        # never a silently-wrong restore
        shim.corrupt_get = False
        store.evict_local(man.ckpt_id)
        shim.corrupt_get = lambda key: "chunks/" in key
        with pytest.raises(ChunkFetchError):
            ckpt.restore_tree(store, man.ckpt_id)
        shim.corrupt_get = False
        restored = ckpt.restore_tree(store, man.ckpt_id)
        for k, arr in tree.items():
            np.testing.assert_array_equal(restored[k], arr)
    finally:
        store.close()


def test_evict_refuses_unmirrored_and_lossy_remote(tmp_path):
    store, _shim = _bucket_store(tmp_path, "evict", mirror=False)
    try:
        m1 = ckpt.save_checkpoint(store, _tree(1.0), step=1)
        with pytest.raises(ValueError, match="refusing to evict"):
            store.evict_local(m1.ckpt_id)  # residency is local
        store.mirror_now(m1.ckpt_id)
        # the remote tier losing a chunk blocks eviction of the only copy
        lost = sorted(m1.chunk_set())[0]
        store.backend.delete(lost)
        with pytest.raises(RuntimeError, match="remote tier lost"):
            store.evict_local(m1.ckpt_id)
        store.io.put_many({lost: mf.read_chunk(store.root, lost)})

        # chunks shared with a local-resident checkpoint survive eviction
        tree2 = _tree(1.0)
        tree2["layer0"] = tree2["layer0"] + 0.25
        m2 = ckpt.save_checkpoint(store, tree2, step=2)
        store.mirror_now(m2.ckpt_id)
        store.evict_local(m2.ckpt_id)
        shared = set(m1.chunk_set()) & set(m2.chunk_set())
        assert shared
        for h in shared:  # m1 is still local-resident and needs them
            assert os.path.exists(mf.chunk_path(store.root, h))
        only_m2 = set(m2.chunk_set()) - set(m1.chunk_set())
        for h in only_m2:
            assert not os.path.exists(mf.chunk_path(store.root, h))
    finally:
        store.close()


def test_adopt_remote_on_fresh_host(tmp_path):
    store, _ = _bucket_store(tmp_path, "origin", mirror=False)
    tree = _tree(5.0)
    man = ckpt.save_checkpoint(store, tree, step=3)
    store.mirror_now(man.ckpt_id)
    store.close()
    # a replacement host attaches to the same bucket with an empty root
    fresh = ckpt.TieredStore(
        str(tmp_path / "fresh"), name="fresh", mirror=False,
        backend=ckpt.BucketBackend(
            ckpt.DirBucketClient(str(tmp_path / "bucket"))))
    try:
        adopted = fresh.adopt_remote()
        assert adopted == [man.ckpt_id]
        entry = fresh.residency()[man.ckpt_id]
        assert entry["state"] == "remote" and entry["evicted"]
        restored = ckpt.restore_tree(fresh, man.ckpt_id)
        for k, arr in tree.items():
            np.testing.assert_array_equal(restored[k], arr)
    finally:
        fresh.close()


# ---------------------------------------------------------------------------
# (d) retention sweeper: pinned / in-flight / grace invariants
# ---------------------------------------------------------------------------


def test_sweeper_keep_last_both_tiers_protects_pins_and_inflight(tmp_path):
    store, _ = _bucket_store(tmp_path, "sweep", mirror=False)
    ids = []
    for i in range(3):
        m = ckpt.save_checkpoint(store, _tree(float(i + 1)), step=i)
        store.mirror_now(m.ckpt_id)
        ids.append(m.ckpt_id)
    store.pin(ids[0])

    # a pinned auxiliary manifest outside the LATEST chain — the weight
    # plane's durable publish shape (write_manifest + pin, no commit)
    data = b"durable weights payload"
    wh, _ = mf.write_chunk(store.root, data)
    wman = mf.Manifest(
        ckpt_id="weights-pol-v0000000001", step=1, ts=time.time(),
        parent=None, skeleton={"__leaf__": "w"}, spec=None,
        leaves={"w": mf.LeafEntry(
            kind=mf.ND, shape=(len(data),), dtype="|u1",
            chunks={mf.encode_box(((0, len(data)),)): (wh, len(data))})},
        stats={"weights_store": "pol", "weights_version": 1})
    mf.write_manifest(store.root, wman)
    store.pin(wman.ckpt_id)
    store.mirror_now(wman.ckpt_id)
    assert store.latest_id() == ids[-1]  # durable publish moved no LATEST

    # an in-flight sharded save: a part-file referencing an orphan chunk
    # far older than any grace window
    orphan, _ = mf.write_chunk(store.root, b"slow peer host chunk")
    old = time.time() - 86400
    os.utime(mf.chunk_path(store.root, orphan), (old, old))
    part_dir = os.path.join(store.root, mf.PART_DIR, "step0000000099-beef")
    os.makedirs(part_dir)
    mf.atomic_write(
        os.path.join(part_dir, "step0000000099-beef.rank3.json"),
        json.dumps({"host": "rank3", "leaves": {
            "opt/m": {"((0, 4), (0, 4))": [orphan, 20]}}}).encode())
    # plus a plain orphan chunk, equally old, protected by NOTHING
    doomed, _ = mf.write_chunk(store.root, b"no manifest ever named me")
    os.utime(mf.chunk_path(store.root, doomed), (old, old))
    store.close()

    report = sweep_store(store.root, SweepPolicy(keep_last=1, grace_s=0))
    # keep-last=1 drops ids[1]; ids[0] is pinned, ids[2] is newest, and
    # the pinned weights manifest does NOT consume the keep-last slot
    assert report["local"]["dropped_manifests"] == 1
    assert report["remote"]["dropped_manifests"] == 1
    survivor = ckpt.TieredStore(store.root, mirror=False)
    try:
        left = survivor.list_ids()
        assert ids[0] in left and ids[2] in left and wman.ckpt_id in left
        assert ids[1] not in left
        assert ids[1] not in survivor.backend.list_manifests()
        # pinned checkpoints still restore from both tiers
        np.testing.assert_array_equal(
            ckpt.restore_tree(survivor, ids[0])["layer0"],
            _tree(1.0)["layer0"])
        assert survivor.backend.get(wh) == data
        # the in-flight chunk survived (part-file protection beats age);
        # the unprotected orphan was reaped
        assert os.path.exists(mf.chunk_path(store.root, orphan))
        assert not os.path.exists(mf.chunk_path(store.root, doomed))

        # the save commits (part-file gone) -> the orphan loses its
        # protection and the next zero-grace sweep reaps it
        import shutil

        shutil.rmtree(os.path.dirname(part_dir))
        sweep_store(store.root, SweepPolicy(keep_last=1, grace_s=0))
        assert not os.path.exists(mf.chunk_path(store.root, orphan))
    finally:
        survivor.close()


def test_sweeper_grace_window_spares_young_remote_orphans(tmp_path):
    store, _ = _bucket_store(tmp_path, "grace", mirror=False)
    m = ckpt.save_checkpoint(store, _tree(1.0), step=1)
    store.mirror_now(m.ckpt_id)
    # a just-uploaded remote orphan: an in-flight mirror of a checkpoint
    # whose remote manifest has not landed yet
    data = b"mid-mirror remote chunk"
    h = hashlib.sha256(data).hexdigest()
    store.backend.put(h, data)
    store.close()
    sweep_store(store.root, SweepPolicy(keep_last=None, grace_s=3600))
    backend = ckpt.BucketBackend(
        ckpt.DirBucketClient(str(tmp_path / "bucket")))
    assert backend.has(h)  # young: spared
    sweep_store(store.root, SweepPolicy(keep_last=None, grace_s=0))
    assert not backend.has(h)  # grace disabled, nothing references it
    # the mirrored checkpoint's chunks were live throughout
    for ch in m.chunk_set():
        assert backend.has(ch)


def test_retention_keep_last_ignores_pinned_aux_manifests(tmp_path):
    """Regression: a pinned ``weights-*`` manifest sorts after every
    ``step*`` id and must not consume the keep-last slot (which would
    evict the newest real checkpoint)."""
    store = ckpt.CheckpointStore(str(tmp_path), name="kl")
    ids = [ckpt.save_checkpoint(store, _tree(float(i)), step=i).ckpt_id
           for i in range(2)]
    aux = mf.Manifest(ckpt_id="weights-kl-v0000000007", step=7,
                      ts=time.time(), parent=None,
                      skeleton={"__leaf__": "w"}, spec=None, leaves={})
    mf.write_manifest(store.root, aux)
    store.pin(aux.ckpt_id)
    store.retention(keep_last=1, grace_s=0)
    left = store.list_ids()
    assert ids[1] in left  # the newest training ckpt survived
    assert aux.ckpt_id in left
    assert ids[0] not in left


# ---------------------------------------------------------------------------
# (e) elastic 4 -> 2 restore THROUGH the tier: per-host chunk accounting
# ---------------------------------------------------------------------------


def _sharded_spec(num_hosts):
    mesh = MeshSpec((num_hosts,), ("data",),
                    tuple(f"rank{i}" for i in range(num_hosts)))
    return ShardedTreeSpec(
        mesh=mesh,
        parts={"opt/m": ("data", None), "opt/v": ("data", None)},
        meta={"opt/m": ((8, 4), "<f4"), "opt/v": ((8, 4), "<f4")})


def _global_tree():
    return {"opt/m": np.arange(32, dtype=np.float32).reshape(8, 4),
            "opt/v": np.arange(32, 64, dtype=np.float32).reshape(8, 4)}


def test_sharded_save_mirror_evict_restore_2host_accounting(tmp_path):
    store, shim = _bucket_store(tmp_path, "elastic", mirror=False)
    try:
        spec4 = _sharded_spec(4)
        cid = ckpt.new_ckpt_id(7)
        full = _global_tree()
        for host in spec4.mesh.hosts:
            shards = {}
            for leaf in spec4.meta:
                box = host_boxes(spec4.mesh, spec4.part_of(leaf),
                                 spec4.meta[leaf][0], host)[0]
                shards[leaf] = {box: full[leaf][box_slices(box)]}
            ckpt.save_host_shards(store, cid, spec4, host, shards, step=7)
        man = ckpt.commit_host_parts(store, cid, spec4, step=7)
        assert man.ckpt_id == cid
        assert len(man.chunk_set()) == 8  # 4 boxes x 2 leaves
        store.mirror_now(cid)
        store.evict_local(cid)
        for h in man.chunk_set():
            assert not os.path.exists(mf.chunk_path(store.root, h))

        spec2 = _sharded_spec(2)
        total = sum(a.nbytes for a in full.values())
        for rank, host in enumerate(spec2.mesh.hosts):
            gets_before = shim.ops("get")
            shards, stats = ckpt.restore_shards(store, spec2, host, cid)
            assert stats["no_gather"]
            # each of the 2 hosts reads exactly its half of every leaf...
            assert stats["bytes_read"] == total // 2
            # ...as exactly the 4 intersecting remote chunks — the other
            # host's half is never fetched (ranks' source boxes are
            # disjoint, so the read-through cache cannot help either)
            assert stats["chunks_read"] == 4
            assert shim.ops("get") - gets_before == 4
            for leaf, arr in full.items():
                (box, shard), = shards[leaf].items()
                np.testing.assert_array_equal(
                    shard, arr[rank * 4:(rank + 1) * 4])
    finally:
        store.close()


# ---------------------------------------------------------------------------
# cluster surface: object-plane tier + GCS sweep RPC + state API
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield ray_tpu
    ray_tpu.shutdown()


def test_object_plane_backend_tier(cluster, tmp_path):
    backend = ckpt.ObjectPlaneBackend("tier_test")
    data = b"object plane chunk bytes"
    h = hashlib.sha256(data).hexdigest()
    assert backend.put(h, data) is True
    assert backend.put(h, data) is False
    assert backend.get(h) == data
    assert backend.get(h, offset=7, length=5) == data[7:12]
    assert backend.has(h)
    assert backend.list_chunks() == {h: len(data)}
    assert backend.chunk_mtime(h) is not None
    with pytest.raises(KeyError):
        backend.get("0" * 64)
    backend.delete(h)
    assert not backend.has(h)

    # a checkpoint mirrored into the cluster restores after local evict:
    # the vault actor owns the refs, not the saving worker
    store = ckpt.TieredStore(str(tmp_path / "op"), name="op-tier",
                             mirror=False, backend=backend)
    try:
        tree = _tree(6.0)
        man = ckpt.save_checkpoint(store, tree, step=1)
        store.mirror_now(man.ckpt_id)
        store.evict_local(man.ckpt_id)
        restored = ckpt.restore_tree(store, man.ckpt_id)
        for k, arr in tree.items():
            np.testing.assert_array_equal(restored[k], arr)
    finally:
        store.close()


def test_gcs_sweep_rpc_and_state_surface(cluster, tmp_path):
    shim = ckpt.FaultShim(ckpt.DirBucketClient(str(tmp_path / "swb")))
    store = ckpt.TieredStore(str(tmp_path / "swroot"), name="swept-store",
                             mirror=False, backend=ckpt.BucketBackend(shim),
                             sweep={"keep_last": 1, "grace_s": 0})
    ids = []
    for i in range(3):
        m = ckpt.save_checkpoint(store, _tree(float(i + 1)), step=i)
        store.mirror_now(m.ckpt_id)
        ids.append(m.ckpt_id)
    store.mirror()  # stats (incl. the sweep policy + residency) -> KV

    from ray_tpu.util import state

    listed = state.list_checkpoints()["swept-store"]
    assert listed["sweep"] == {"keep_last": 1, "grace_s": 0}
    assert listed["tier"]["residency_summary"] == {"remote": 3}

    core = state._core()
    out = core._run(core._gcs_call("CkptSweep", {}), 60.0)
    reports = [r for r in out["reports"] if r["name"] == "swept-store"]
    assert len(reports) == 1
    assert reports[0]["local"]["dropped_manifests"] == 2
    assert store.list_ids() == [ids[2]]
    # the report is queryable back out of the state API
    swept = state.ckpt_sweeps()["swept-store"]
    assert swept["dropped_manifests"] >= 2
    store.close()


# ---------------------------------------------------------------------------
# CLI: the status view's goodput column
# ---------------------------------------------------------------------------


def test_status_payload_goodput_column(monkeypatch):
    from ray_tpu.scripts import cli
    from ray_tpu.util import state

    monkeypatch.setattr(state, "summarize_cluster",
                        lambda: {"nodes": {"alive": 1}})
    monkeypatch.setattr(state, "goodput", lambda: {
        "trainA": {"goodput_fraction": 0.75321, "wall_s": 10.0},
        "tuneB": {"goodput_fraction": 0.5}})
    out = cli._status_payload()
    assert out["nodes"] == {"alive": 1}
    assert out["goodput"] == {"trainA": 0.7532, "tuneB": 0.5}

    def _boom():
        raise RuntimeError("pre-goodput GCS")

    monkeypatch.setattr(state, "goodput", _boom)
    assert cli._status_payload()["goodput"] == {}

"""MoE transformer (expert parallelism) + ViT model tests (CPU tier:
8-device virtual mesh per conftest)."""

import dataclasses

import numpy as np
import pytest

from ray_tpu.utils import import_jax

jax = import_jax()
import jax.numpy as jnp  # noqa: E402

from ray_tpu.models import (  # noqa: E402
    CONFIGS,
    Transformer,
    VIT_CONFIGS,
    VisionTransformer,
    ViTConfig,
    accuracy,
    classification_loss,
)
from ray_tpu.parallel import TrainStepBundle, create_mesh  # noqa: E402


def test_moe_forward_shape_and_aux():
    cfg = CONFIGS["moe-tiny"]
    model = Transformer(cfg)
    tokens = jnp.zeros((2, 32), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    logits, cols = model.apply({"params": params}, tokens, mutable=["losses"])
    assert logits.shape == (2, 32, cfg.vocab_size)
    aux = jax.tree.leaves(cols["losses"])
    assert len(aux) == cfg.n_layers  # every block is MoE at moe_every=1
    # balanced-router aux is ~1.0; catastrophically unbalanced >> 1
    assert all(0.5 < float(a) < 4.0 for a in aux)


def test_moe_has_expert_params():
    cfg = CONFIGS["moe-tiny"]
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32))
    import flax.linen as nn

    unboxed = nn.meta.unbox(params)
    layer0 = unboxed["params"]["layer_0"]
    assert "moe" in layer0
    assert layer0["moe"]["gate_proj"].shape == (
        cfg.n_experts, cfg.d_model, cfg.d_ff)


def test_moe_trains_on_expert_mesh():
    mesh = create_mesh(
        {"data": 1, "fsdp": 1, "seq": 2, "tensor": 2, "expert": 2},
        devices=jax.devices()[:8])
    bundle = TrainStepBundle(CONFIGS["moe-tiny"], mesh)
    params, opt = bundle.init(jax.random.PRNGKey(0))
    batch = bundle.make_batch(np.random.default_rng(0), 8, 64)
    losses = []
    for _ in range(10):
        params, opt, loss = bundle.step(params, opt, batch)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], f"MoE loss did not decrease: {losses}"


def test_moe_num_params_counts_experts():
    dense = dataclasses.replace(CONFIGS["moe-tiny"], n_experts=0)
    moe = CONFIGS["moe-tiny"]
    assert moe.num_params() > dense.num_params()


def test_vit_forward_and_train():
    cfg = VIT_CONFIGS["vit-tiny"]
    model = VisionTransformer(cfg)
    rng = np.random.default_rng(0)

    # synthetic separable task: class = brightest quadrant (4 classes)
    def make_batch(n):
        images = rng.normal(0, 0.3, (n, 32, 32, 3)).astype(np.float32)
        labels = rng.integers(0, 4, n)
        for i, lab in enumerate(labels):
            y0, x0 = (lab // 2) * 16, (lab % 2) * 16
            images[i, y0:y0 + 16, x0:x0 + 16] += 2.0
        return jnp.asarray(images), jnp.asarray(labels, jnp.int32)

    cfg = dataclasses.replace(cfg, num_classes=4, n_layers=2, d_model=64,
                              n_heads=4, d_ff=128)
    model = VisionTransformer(cfg)
    images, labels = make_batch(32)
    params = model.init(jax.random.PRNGKey(0), images)["params"]

    import optax

    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, images, labels):
        def loss_fn(p):
            logits = model.apply({"params": p}, images)
            return classification_loss(logits, labels), logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, accuracy(logits, labels)

    # overfit one fixed batch: deterministic learning check
    accs = []
    for i in range(60):
        params, opt_state, loss, acc = step(params, opt_state, images, labels)
        accs.append(float(acc))
    assert np.mean(accs[-5:]) > 0.9, f"ViT failed to learn: {accs[-5:]}"


def test_dryrun_covers_all_parallelism_axes():
    """The dry-run mesh plans must exercise every axis >1 across the set:
    dp + fsdp + tp on one mesh (the real-pod shape), sp + ep on another."""
    import __graft_entry__ as g

    plans = g._mesh_plans_for(8)
    assert len(plans) == 2
    covered = {k for p in plans for k, v in p.items() if v > 1}
    assert covered == {"data", "fsdp", "seq", "tensor", "expert"}
    dp_mesh = plans[0]
    assert dp_mesh["data"] == 2 and dp_mesh["fsdp"] == 2 and dp_mesh["tensor"] == 2

"""Fast control-plane smoke (tier-1, not slow): the provisioning plane's
bench tool runs end-to-end at a tiny scale and its envelope completes —
leases grant, actors create at warm-pool (not cold-spawn) rates, pool
stats surface. Throughput numbers come from the full
tools/bench_control_plane.py run (STRESS_r*.json)."""

import json
import os
import subprocess
import sys


def test_control_plane_bench_smoke(tmp_path):
    out = tmp_path / "cp.json"
    proc = subprocess.run(
        [sys.executable, os.path.join("tools", "bench_control_plane.py"),
         "--nodes", "2", "--actors", "10", "--tasks", "400",
         "--lease-samples", "6", "--out", str(out)],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=dict(os.environ), capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (
        f"bench failed\n--- stdout ---\n{proc.stdout[-4000:]}"
        f"\n--- stderr ---\n{proc.stderr[-3000:]}")
    result = json.loads(out.read_text())
    assert result["mode"] == "warm"
    assert result["actors"] == 10 and result["tasks"] == 400
    # conservative floors (the 1-CPU CI host is the budget): the cold-spawn
    # path measured 0.9 actor creates/s at STRESS_r05 — warm adoption must
    # clear it by a wide margin even at smoke scale
    assert result["actor_creates_per_s"] > 3.0, result
    assert result["tasks_per_s"] > 50, result
    assert result["lease_grant_p50_ms"] < 500, result
    # pool stats surfaced from every node, and the zygote actually served
    pools = result["worker_pools"]
    assert len(pools) == 2
    assert any(p.get("zygote_alive") for p in pools.values()), pools
    assert sum(p.get("hits", 0) + p.get("misses", 0)
               for p in pools.values()) > 0, pools

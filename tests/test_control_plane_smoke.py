"""Fast control-plane smoke (tier-1, not slow): the provisioning plane's
bench tool runs end-to-end at a tiny scale and its envelope completes —
leases grant, actors create at warm-pool (not cold-spawn) rates, the
multi-driver phase aggregates, pool stats surface. Throughput numbers come
from the full tools/bench_control_plane.py run (STRESS_r*.json).

Also the submit fast-path regression guards (ISSUE 13): a warm submit must
not re-frame the TaskSpec through wire.dumps, and a burst of `.remote()`
calls must wake the io loop at most once."""

import json
import os
import subprocess
import sys
import time


def test_control_plane_bench_smoke(tmp_path):
    out = tmp_path / "cp.json"
    proc = subprocess.run(
        [sys.executable, os.path.join("tools", "bench_control_plane.py"),
         "--nodes", "2", "--actors", "10", "--tasks", "400",
         "--lease-samples", "6", "--drivers", "2", "--out", str(out)],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=dict(os.environ), capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (
        f"bench failed\n--- stdout ---\n{proc.stdout[-4000:]}"
        f"\n--- stderr ---\n{proc.stderr[-3000:]}")
    result = json.loads(out.read_text())
    assert result["mode"] == "warm"
    assert result["actors"] == 10 and result["tasks"] == 400
    # conservative floors (the 1-CPU CI host is the budget): the cold-spawn
    # path measured 0.9 actor creates/s at STRESS_r05 — warm adoption must
    # clear it by a wide margin even at smoke scale
    assert result["actor_creates_per_s"] > 3.0, result
    assert result["tasks_per_s"] > 50, result
    assert result["lease_grant_p50_ms"] < 500, result
    # spawn-backed multi-grant top-up: a count=8 lease grants ~8 (forking
    # the remainder), not the 1-2 the warm pool happened to hold (the old
    # cap). >= 6 because top-up is best-effort by design — a refused fork
    # or one slow registration on a loaded host legally drops a grant
    assert result["lease_multigrant_count8"] >= 6, result
    # the submit fast path engaged and framed the spec exactly once.
    # The frac floor is loose on purpose: submits racing ahead of the
    # first task's template-caching drive (function push, renv prep on
    # the loop thread) legitimately take the slow path — a fixed ~40
    # warm-up submits, which is 10% of the 400-task smoke but 0.2% of a
    # full STRESS run. The strict per-submit guards live in
    # test_submit_fast_path_regression_guards.
    assert result["submit_spec_frames"] == 1, result
    assert result["submit_fast_path_frac"] > 0.5, result
    # multi-driver phase: 2 forked drivers, aggregate over the union window
    assert result["drivers"] == 2
    assert result["multidriver_tasks"] == 400, result
    assert len(result["per_driver_tasks_per_s"]) == 2
    assert result["aggregate_tasks_per_s"] > 50, result
    # pool stats surfaced from every node, and the zygote actually served
    pools = result["worker_pools"]
    assert len(pools) == 2
    assert any(p.get("zygote_alive") for p in pools.values()), pools
    assert sum(p.get("hits", 0) + p.get("misses", 0)
               for p in pools.values()) > 0, pools


def test_submit_fast_path_regression_guards():
    """Per-submit cost guards: (1) the TaskSpec template is wire-framed
    once per (function, options) — the second and later submits of the
    same function reuse the cached blob; (2) a burst of `.remote()` calls
    while the io loop is busy pays at most ONE call_soon_threadsafe."""
    import ray_tpu

    ray_tpu.init()
    try:
        from ray_tpu._private.worker import _global_worker as core

        @ray_tpu.remote(num_cpus=0.1)
        def f(i):
            return i + 1

        # first submit frames + caches the template (slow path)
        assert ray_tpu.get(f.remote(0), timeout=120) == 1
        frames0 = core._submit_stats["spec_frames"]

        # occupy the io loop so the burst below cannot be drained mid-way:
        # every submit lands while the loop is provably busy
        import asyncio

        async def _block():
            time.sleep(0.3)  # blocking ON the loop, intentionally

        blocker = asyncio.run_coroutine_threadsafe(_block(), core.loop)
        time.sleep(0.05)  # let the loop enter the blocker
        wake0 = core._submit_stats["kickoff_wakeups"]
        refs = [f.remote(i) for i in range(100)]
        wake1 = core._submit_stats["kickoff_wakeups"]
        blocker.result(timeout=10)
        assert wake1 - wake0 <= 1, (wake0, wake1)
        assert ray_tpu.get(refs, timeout=120) == list(range(1, 101))
        # no re-framing of the spec template on warm submits
        assert core._submit_stats["spec_frames"] == frames0, (
            frames0, core._submit_stats)
        assert core._submit_stats["fast_path"] >= 100
        # (3) the serialization scratch pool absorbs warm submits: after
        # the first submit sized the per-thread buffer, a same-shape burst
        # re-packs into it instead of allocating per call
        stats = core.submit_stats()
        assert stats["pack_pool_hits"] >= 95, stats
        assert stats["pack_pool_hits"] > 10 * stats["pack_pool_misses"]
        # semantics preserved through the fast path: dependency chains,
        # multiple returns, and errors still behave
        @ray_tpu.remote(num_cpus=0.1, num_returns=2)
        def two(x):
            return x, x * 10

        a, b = two.remote(3)
        chained = f.remote(b)
        assert ray_tpu.get([a, chained], timeout=120) == [3, 31]

        # (4) wait() partitions readiness via the per-poll set
        # intersection (not per-ref store probes) — the counter proves the
        # vectorized path actually engaged, and semantics hold
        polls0 = core._submit_stats["wait_vector_polls"]
        more = [f.remote(i) for i in range(20)]
        done, not_done = ray_tpu.wait(more, num_returns=20, timeout=120)
        assert len(done) == 20 and not not_done
        assert core._submit_stats["wait_vector_polls"] > polls0, (
            polls0, core._submit_stats)

        @ray_tpu.remote(num_cpus=0.1)
        def boom():
            raise ValueError("intentional")

        import pytest

        with pytest.raises(Exception, match="intentional"):
            ray_tpu.get(boom.remote(), timeout=120)
    finally:
        ray_tpu.shutdown()


def test_resource_view_delta_coalescing():
    """N availability updates inside one GCS tick -> ONE batched
    resource_view publish carrying only the latest view; values flapping
    back to the published view are suppressed entirely."""
    import asyncio

    from ray_tpu._private import wire
    from ray_tpu._private.common import NodeInfo
    from ray_tpu._private.gcs import GcsServer
    from ray_tpu._private.ids import NodeID

    async def _run():
        gcs = GcsServer()
        pushes = []

        class FakeConn:
            conn_id = 1

            async def push(self, channel, payload):
                pushes.append((channel, wire.loads(payload)))

        gcs.subs[1] = (FakeConn(), {"resource_view"})
        info = NodeInfo(node_id=NodeID.from_random(), address="host:1",
                        object_store_address="",
                        total_resources={"CPU": 8.0})
        await gcs._rpc_RegisterNode({"info": info}, None)
        await asyncio.sleep(0)  # let the registration publish land
        assert len(pushes) == 1, pushes
        assert pushes[0][1]["views"][0]["available"] == {"CPU": 8.0}

        # a burst of heartbeat availability changes within one tick...
        for i in range(10):
            await gcs._rpc_Heartbeat(
                {"node_id": info.node_id,
                 "available": {"CPU": float(i)}}, None)
        assert len(pushes) == 1  # nothing published before the tick
        gcs._flush_resource_views()
        await asyncio.sleep(0)
        # ...coalesces to ONE publish carrying the LATEST view
        assert len(pushes) == 2, pushes
        views = pushes[1][1]["views"]
        assert len(views) == 1
        assert views[0]["available"] == {"CPU": 9.0}

        # delta suppression: flapping back to the published value inside
        # the tick publishes nothing at all
        await gcs._rpc_Heartbeat(
            {"node_id": info.node_id, "available": {"CPU": 3.0}}, None)
        await gcs._rpc_Heartbeat(
            {"node_id": info.node_id, "available": {"CPU": 9.0}}, None)
        gcs._flush_resource_views()
        await asyncio.sleep(0)
        assert len(pushes) == 2, pushes

        # node death flushes immediately with alive=False
        await gcs._mark_node_dead(info.node_id, "test")
        await asyncio.sleep(0)
        dead = [m for _, m in pushes[2:]
                for v in m["views"] if not v["alive"]]
        assert dead, pushes
        gcs.store.close()

    asyncio.run(_run())


def test_renv_keyed_warm_pool_replenish():
    """A hot non-default runtime env gets warm workers too: after leases
    for an env_vars renv, the replenish loop keys on its hash and tops up
    warm workers of that exact shape (STRESS_r06's 113-miss pattern)."""
    import ray_tpu

    ray_tpu.init()
    try:
        from ray_tpu.util.state import get_node_stats, list_nodes

        @ray_tpu.remote(num_cpus=0.1, runtime_env={
            "env_vars": {"RTPU_HOT_RENV_TEST": "1"}})
        def hot():
            return os.environ.get("RTPU_HOT_RENV_TEST")

        assert ray_tpu.get(hot.remote(), timeout=180) == "1"
        deadline = time.time() + 60
        warm = {}
        while time.time() < deadline:
            node = [n for n in list_nodes() if n["alive"]][0]
            warm = get_node_stats(node["address"]).get("worker_pool", {})
            if warm.get("warm_hot_renv", 0) >= 1:
                break
            time.sleep(0.5)
        assert warm.get("hot_renv_hash"), warm
        assert warm.get("warm_hot_renv", 0) >= 1, warm
    finally:
        ray_tpu.shutdown()

"""Train layer e2e: JaxTrainer with checkpointing + failure recovery,
plus the overlapped/cross-replica-sharded train step (PR 12):

- the sharded single-program step is BIT-EXACT in fp32 against the fused
  step over multiple steps on the 8-device CPU mesh (params AND opt state
  after all-gather, global-norm clip engaged and included);
- optimizer-state memory per replica is ~1/N of the unsharded state;
- bucket-plan boundary cases (giant leaf, many tiny leaves);
- the traced sharded path emits `train.bucket_allreduce` spans nested
  under `train.fwd_bwd`, and NO XLA buffer-donation/alias warnings appear
  anywhere (donation restored on the split path);
- the bucketed collective tier (AsyncBucketReducer/ShardedBucketOptimizer)
  reduces correctly across ranks and keeps 1/N opt state;
- JaxTrainer wires grad sync into the train context.

Reference tier: python/ray/train/v2/tests (controller/worker-group/failure
policy units driven end-to-end here on CPU workers).
"""

import dataclasses
import os
import warnings

import numpy as np
import pytest

import ray_tpu
from ray_tpu.train import (
    Checkpoint,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=6)
    yield ray_tpu
    ray_tpu.shutdown()


def _sgd_loop(config):
    """A tiny numpy "training" loop with report + checkpoint."""
    import json

    import numpy as np

    from ray_tpu import train

    ctx = train.get_context()
    w = np.zeros(4)
    start = 0
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        with open(os.path.join(ckpt.as_directory(), "state.json")) as f:
            state = json.load(f)
        w = np.array(state["w"])
        start = state["step"]
    target = np.arange(4.0)
    for step in range(start, config["steps"]):
        w = w + 0.5 * (target - w)
        loss = float(((target - w) ** 2).mean())
        if (step + 1) % config["ckpt_every"] == 0 and ctx.get_world_rank() == 0:
            import tempfile

            with tempfile.TemporaryDirectory() as d:
                with open(os.path.join(d, "state.json"), "w") as f:
                    json.dump({"w": w.tolist(), "step": step + 1}, f)
                train.report({"loss": loss, "step": step + 1},
                             checkpoint=Checkpoint.from_directory(d))
        else:
            train.report({"loss": loss, "step": step + 1})
    return {"final_loss": loss, "rank": ctx.get_world_rank()}


def test_jax_trainer_e2e(cluster, tmp_path):
    trainer = JaxTrainer(
        _sgd_loop,
        train_loop_config={"steps": 6, "ckpt_every": 2},
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1.0}),
        run_config=RunConfig(storage_path=str(tmp_path), name="e2e"),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 6
    assert result.metrics["loss"] < 1e-2
    assert result.checkpoint is not None
    assert os.path.exists(os.path.join(result.checkpoint.path, "state.json"))


def _flaky_loop(config):
    import json

    from ray_tpu import train

    ctx = train.get_context()
    marker = config["marker"]
    start = 0
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        with open(os.path.join(ckpt.as_directory(), "state.json")) as f:
            start = json.load(f)["step"]
    for step in range(start, config["steps"]):
        if step == 3 and ctx.get_world_rank() == 0 and not os.path.exists(marker):
            open(marker, "w").close()
            os._exit(1)  # simulate worker death mid-run
        if ctx.get_world_rank() == 0:
            import tempfile

            with tempfile.TemporaryDirectory() as d:
                with open(os.path.join(d, "state.json"), "w") as f:
                    json.dump({"step": step + 1}, f)
                train.report({"step": step + 1},
                             checkpoint=Checkpoint.from_directory(d))
        else:
            train.report({"step": step + 1})
    return {"done": True, "resumed_from": start}


def test_failure_policy_restart(cluster, tmp_path):
    marker = str(tmp_path / "died_once")
    trainer = JaxTrainer(
        _flaky_loop,
        train_loop_config={"steps": 5, "marker": marker},
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1.0}),
        run_config=RunConfig(storage_path=str(tmp_path), name="flaky",
                             failure_config=FailureConfig(max_failures=2)),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 5
    assert os.path.exists(marker)  # the crash really happened


def test_training_failed_raises(cluster, tmp_path):
    def always_fails(config):
        raise RuntimeError("bad loop")

    from ray_tpu.train import TrainingFailedError

    trainer = JaxTrainer(
        always_fails,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1,
                                     resources_per_worker={"CPU": 1.0}),
        run_config=RunConfig(storage_path=str(tmp_path), name="failing"),
    )
    with pytest.raises(TrainingFailedError, match="bad loop"):
        trainer.fit()


# ---------------------------------------------------------------------------
# Overlapped bucketed allreduce + cross-replica sharded optimizer update
# ---------------------------------------------------------------------------


DP = 8  # conftest forces an 8-device CPU mesh


def _bitwise_equal_trees(a, b, repl):
    """Leaf-by-leaf bitwise comparison (gathering sharded leaves)."""
    import jax

    bad = []
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for i, (x, y) in enumerate(zip(la, lb)):
        x = np.asarray(jax.device_put(x, repl))
        y = np.asarray(jax.device_put(y, repl))
        if not np.array_equal(x, y):
            bad.append((i, float(np.abs(
                x.astype(np.float64) - y.astype(np.float64)).max())))
    return bad


@pytest.fixture(scope="module")
def sharded_bundle():
    """One tiny-config bundle on the 8-device mesh, clip LOW enough that
    the global-norm clip actually engages every step — plus the captured
    warnings from compiling/running every program flavor."""
    import jax
    from ray_tpu.models.transformer import CONFIGS
    from ray_tpu.parallel import TrainStepBundle, create_mesh, make_optimizer

    cfg = dataclasses.replace(CONFIGS["tiny"], max_seq_len=64)
    mesh = create_mesh({"data": DP, "fsdp": 1, "seq": 1, "tensor": 1,
                        "expert": 1})
    factory = lambda spec_fn: make_optimizer(  # noqa: E731
        learning_rate=1e-2, warmup_steps=2, total_steps=100, clip=0.05,
        clip_spec_fn=spec_fn)
    with warnings.catch_warnings(record=True) as wrec:
        warnings.simplefilter("always")
        bundle = TrainStepBundle(cfg, mesh, optimizer_factory=factory,
                                 shard_update=True, bucket_bytes=64 << 10)
        batch = bundle.make_batch(np.random.default_rng(0), 16, 64)
        runs = {}
        # fused (unsharded) reference, 3 steps
        pf, sf = bundle.init(jax.random.PRNGKey(0))
        for _ in range(3):
            pf, sf, lf = bundle._fused_step(pf, sf, batch)
        runs["fused"] = (pf, sf, float(lf))
        # sharded single-program step (the untraced perf path), 3 steps
        ps, ss = bundle.init_sharded(jax.random.PRNGKey(0))
        for _ in range(3):
            ps, ss, ls = bundle.step(ps, ss, batch)
        runs["sharded"] = (ps, ss, float(ls))
        # split paths (the traced-tier programs), 3 steps each
        pa, sa = bundle.init(jax.random.PRNGKey(0))
        for _ in range(3):
            la, ga = bundle._fwd_bwd(pa, batch)
            pa, sa = bundle._opt_apply(ga, sa, pa)
        runs["split"] = (pa, sa, float(la))
        pb, sb = bundle.init_sharded(jax.random.PRNGKey(0))
        for _ in range(3):
            lb, gb = bundle._fwd_bwd_rs(pb, batch)
            pb, sb = bundle._opt_apply_sharded(gb, sb, pb)
        runs["split_sharded"] = (pb, sb, float(lb))
    return {"bundle": bundle, "batch": batch, "runs": runs,
            "warnings": [str(w.message) for w in wrec]}


def test_sharded_update_bitexact_vs_fused(sharded_bundle):
    """The acceptance contract: the cross-replica sharded-update step
    reproduces the fused step bit-for-bit in fp32 over 3 steps — params
    AND optimizer state after all-gather, with the global-norm clip (low
    threshold, so it engages) computed from shard-local sqnorms."""
    import jax

    b = sharded_bundle["bundle"]
    pf, sf, lf = sharded_bundle["runs"]["fused"]
    ps, ss, ls = sharded_bundle["runs"]["sharded"]
    # clip engaged: the raw grad norm exceeds the 0.05 threshold
    _, grads = b._fwd_bwd(pf, sharded_bundle["batch"])
    gnorm = float(np.sqrt(sum(
        float(np.sum(np.square(np.asarray(g, dtype=np.float64))))
        for g in jax.tree_util.tree_leaves(grads))))
    assert gnorm > 0.05, "test misconfigured: clip never engages"
    assert _bitwise_equal_trees(pf, ps, b.repl) == []
    assert _bitwise_equal_trees(sf, b.unshard_opt_state(ss), b.repl) == []
    assert lf == ls


def test_split_sharded_matches_split_unsharded(sharded_bundle):
    """The phase-split programs agree with each other bitwise too (the
    traced tier keeps the same numerics whether the update is sharded)."""
    b = sharded_bundle["bundle"]
    pa, sa, _ = sharded_bundle["runs"]["split"]
    pb, sb, _ = sharded_bundle["runs"]["split_sharded"]
    assert _bitwise_equal_trees(pa, pb, b.repl) == []
    assert _bitwise_equal_trees(sa, b.unshard_opt_state(sb), b.repl) == []


def test_no_donation_alias_warnings(sharded_bundle):
    """Donation restored on the split path (grads donated in _opt_apply,
    params+opt in the sharded flavor): compiling and running every
    program flavor must produce zero XLA donation/alias warnings."""
    bad = [w for w in sharded_bundle["warnings"]
           if "donat" in w.lower() or "alias" in w.lower()]
    assert bad == [], f"XLA donation warnings: {bad[:2]}"


def test_sharded_opt_state_memory_is_1_over_n(sharded_bundle):
    """Optimizer-state bytes per replica ~ 1/N of the unsharded state
    (replicated scalars keep it from being exactly 1/N)."""
    b = sharded_bundle["bundle"]
    _, ss, _ = sharded_bundle["runs"]["sharded"]
    _, sf, _ = sharded_bundle["runs"]["fused"]
    per = b.opt_state_bytes_per_replica(ss)
    total = b.opt_state_bytes_per_replica(sf)
    assert per < total / (DP / 2), (per, total)  # well under half
    assert per == pytest.approx(total / DP, rel=0.05)


def test_bucket_plan_boundary_cases():
    from ray_tpu.collective.bucketed import plan_buckets

    KB = 1024
    f4 = np.dtype(np.float32)
    # one giant leaf larger than bucket_bytes -> its own bucket
    meta = {"tiny_a": ((8,), f4), "giant": ((1024, 1024), f4),
            "tiny_b": ((8,), f4)}
    plan = plan_buckets(meta, bucket_bytes=64 * KB, world_size=4)
    giant = [b for b in plan.buckets if "giant" in b.paths]
    assert len(giant) == 1 and giant[0].paths[-1] == "giant"
    assert giant[0].nbytes > 64 * KB  # not split, not dropped
    # many tiny leaves pack into ONE bucket
    meta = {f"leaf{i:03d}": ((4,), f4) for i in range(100)}
    plan = plan_buckets(meta, bucket_bytes=64 * KB, world_size=4)
    assert plan.num_buckets == 1
    assert plan.buckets[0].nbytes == 100 * 16
    # packing respects the bound and preserves layer order
    meta = {f"l{i:02d}": ((1024,), f4) for i in range(32)}  # 4KB each
    plan = plan_buckets(meta, bucket_bytes=8 * KB, world_size=4)
    assert all(b.nbytes <= 8 * KB for b in plan.buckets)
    order = [p for b in plan.buckets for p in b.paths]
    assert order == sorted(order)
    # owners balance bytes across ranks
    loads = plan.bytes_per_rank()
    assert max(loads) <= 2 * min(loads)
    with pytest.raises(ValueError):
        plan_buckets(meta, bucket_bytes=0)


def test_traced_sharded_step_spans(sharded_bundle):
    """Tracing ON routes the sharded step through the explicit bucketed
    pipeline: per-bucket reduce programs, each a `train.bucket_allreduce`
    span nested under `train.fwd_bwd` (what /api/timeline renders)."""
    import jax
    from ray_tpu.util import tracing

    b = sharded_bundle["bundle"]
    batch = sharded_bundle["batch"]
    ps, ss = b.init_sharded(jax.random.PRNGKey(0))
    tracing.enable()
    try:
        before = len(tracing._buffer)
        ps, ss, loss = b.step(ps, ss, batch)
        spans = list(tracing._buffer)[before:]
    finally:
        tracing._enabled = False
        os.environ.pop("RAY_TPU_ENABLE_TRACING", None)
    names = [s["name"] for s in spans]
    n_buckets = b.bucket_plan.num_buckets
    assert n_buckets > 1
    assert names.count("train.bucket_allreduce") == n_buckets
    assert names.count("train.fwd_bwd") == 1
    assert names.count("train.optimizer") == 1
    fwd_ids = {s["span_id"] for s in spans if s["name"] == "train.fwd_bwd"}
    assert all(s["parent_id"] in fwd_ids for s in spans
               if s["name"] == "train.bucket_allreduce")
    # and the same spans render through the PR 10 timeline path (what
    # GET /api/timeline serves): complete slices with bucket attrs
    from ray_tpu.util.tracing import spans_to_chrome_events

    events = spans_to_chrome_events(spans)
    slices = [e for e in events if e.get("ph") == "X"
              and e.get("name") == "train.bucket_allreduce"]
    assert len(slices) == n_buckets
    assert all("bucket" in (e.get("args") or {}) for e in slices)
    # the traced (explicit-bucket) step trains the same objective: its
    # loss matches the untraced sharded step's first-step loss closely
    # (per-replica local-batch kernels differ from the fused program at
    # ulp level, so this is allclose, not bitwise — OVERLAP.md)
    p0, s0 = b.init_sharded(jax.random.PRNGKey(0))
    _, _, l0 = b.step(p0, s0, batch)
    assert float(loss) == pytest.approx(float(l0), rel=1e-4)


def test_traced_sharded_step_uneven_masks(sharded_bundle):
    """The explicit bucketed path must weight replicas by their valid-
    token counts (the fused step's global normalization), not average
    per-replica means — regression for the mean-of-means bug: with wildly
    uneven masks across data shards, one traced step still reproduces the
    untraced sharded step's loss and params to fp32 tolerance."""
    import jax
    from ray_tpu.util import tracing

    b = sharded_bundle["bundle"]
    batch = dict(sharded_bundle["batch"])
    mask = np.zeros((16, 64), np.float32)
    mask[0, :4] = 1.0    # replica 0: 4 valid tokens
    for row in range(2, 16):
        mask[row] = 1.0  # replicas 1..7: 128 each
    batch["mask"] = jax.device_put(mask, b.batch_sharding)
    p0, s0 = b.init_sharded(jax.random.PRNGKey(0))
    p0, s0, l_ref = b.step(p0, s0, batch)  # untraced sharded (fused prog)
    tracing.enable()
    try:
        p1, s1 = b.init_sharded(jax.random.PRNGKey(0))
        p1, s1, l_tr = b.step(p1, s1, batch)
    finally:
        tracing._enabled = False
        os.environ.pop("RAY_TPU_ENABLE_TRACING", None)
    assert float(l_tr) == pytest.approx(float(l_ref), rel=1e-4)
    for x, y in zip(jax.tree_util.tree_leaves(p0),
                    jax.tree_util.tree_leaves(p1)):
        np.testing.assert_allclose(
            np.asarray(jax.device_put(x, b.repl)),
            np.asarray(jax.device_put(y, b.repl)), atol=5e-4, rtol=1e-3)


# ---------------------------------------------------------------------------
# Bucketed collective tier (multi-controller): AsyncBucketReducer +
# cross-replica ShardedBucketOptimizer
# ---------------------------------------------------------------------------


@ray_tpu.remote
class _GradRank:
    """One data-parallel rank for the collective-tier tests."""

    def __init__(self, rank: int, world: int, base: str):
        from ray_tpu.collective.bucketed import init_sharded_optimizer_groups

        self.rank, self.world, self.base = rank, world, base
        init_sharded_optimizer_groups(world, rank, backend="cpu",
                                      base_name=base)

    def reduce_tree(self, seed: int, bucket_bytes: int):
        import jax
        from ray_tpu.collective.bucketed import (
            AsyncBucketReducer, leaf_meta, plan_buckets)

        tree = _grad_tree(seed)
        plan = plan_buckets(leaf_meta(tree), bucket_bytes=bucket_bytes,
                            world_size=self.world)
        red = AsyncBucketReducer(self.base, plan)
        try:
            out = red.reduce_tree(tree)
        finally:
            red.shutdown()
        return jax.tree_util.tree_map(np.asarray, out)

    def sharded_steps(self, n_steps: int, bucket_bytes: int, clip: float):
        import optax
        from ray_tpu.collective.bucketed import (
            ShardedBucketOptimizer, leaf_meta, plan_buckets)

        params = _grad_tree(1000)  # same init on every rank
        plan = plan_buckets(leaf_meta(params), bucket_bytes=bucket_bytes,
                            world_size=self.world)
        opt = ShardedBucketOptimizer(
            self.base, plan, self.rank, optax.adam(1e-2), params,
            clip_global_norm=clip)
        stats = None
        try:
            for step in range(n_steps):
                grads = _grad_tree(step * self.world + self.rank)
                params, stats = opt.step(grads)
        finally:
            opt.shutdown()
        return {k: np.asarray(v) for k, v in params.items()}, stats


def _grad_tree(seed: int):
    rng = np.random.default_rng(seed)
    return {
        "wide": rng.normal(size=(64, 16)).astype(np.float32),
        "bias": rng.normal(size=(16,)).astype(np.float32),
        "deep": rng.normal(size=(32, 8)).astype(np.float32),
    }


def test_async_bucket_reducer_sums_across_ranks(cluster):
    world = 4
    base = "t_reducer"
    ranks = [_GradRank.options(num_cpus=0.5).remote(r, world, base)
             for r in range(world)]
    outs = ray_tpu.get([a.reduce_tree.remote(seed=r, bucket_bytes=1 << 10)
                        for r, a in enumerate(ranks)], timeout=120)
    # reference: np-stacked sum in rank order (the reducer's op)
    expect = {}
    for key in ("wide", "bias", "deep"):
        expect[key] = np.stack([_grad_tree(r)[key]
                                for r in range(world)]).sum(axis=0)
    for out in outs:  # every rank sees the identical reduced tree
        for key in expect:
            assert np.array_equal(out[key], expect[key])
    for a in ranks:
        ray_tpu.kill(a)


def test_sharded_bucket_optimizer_cross_replica(cluster):
    """Each rank keeps ~1/N of the optimizer state, applies only its
    buckets, and every rank converges to the IDENTICAL full param tree
    (bit-for-bit across ranks) matching a single-process reference that
    consumes the same summed grads."""
    import optax

    world, steps, clip = 4, 2, 0.5
    base = "t_shopt"
    ranks = [_GradRank.options(num_cpus=0.5).remote(r, world, base)
             for r in range(world)]
    outs = ray_tpu.get(
        [a.sharded_steps.remote(steps, 1 << 10, clip) for a in ranks],
        timeout=180)
    params0, stats0 = outs[0]
    # all ranks bitwise identical
    for params_r, stats_r in outs[1:]:
        for key in params0:
            assert np.array_equal(params0[key], params_r[key])
    # opt state is sharded: per-rank bytes well under the full state, and
    # the owned bucket sets partition the plan
    full_state_bytes = sum(a.nbytes * 2 for a in _grad_tree(0).values())
    owned = [set(s["owned_buckets"]) for _, s in outs]
    assert all(s["opt_state_bytes"] < full_state_bytes for _, s in outs)
    for i in range(world):
        for j in range(i + 1, world):
            assert not (owned[i] & owned[j])
    # reference: same summed grads through the same per-leaf math
    ref = _grad_tree(1000)
    opt = optax.adam(1e-2)
    state = opt.init(ref)
    for step in range(steps):
        summed = {}
        for key in ref:
            summed[key] = np.stack([
                _grad_tree(step * world + r)[key] for r in range(world)
            ]).sum(axis=0)
        # clip factor from per-leaf sqnorms folded in leaf order (the
        # optimizer's documented association)
        acc = np.float32(0.0)
        for key in ref:  # dict order == tree order
            acc = np.float32(acc + np.float32(
                np.sum(np.square(summed[key].astype(np.float32)))))
        gnorm = np.float32(np.sqrt(acc))
        factor = np.float32(clip / max(float(gnorm), clip))
        clipped = {k: (v * factor).astype(v.dtype) for k, v in summed.items()}
        upd, state = opt.update(clipped, state, ref)
        ref = optax.apply_updates(ref, upd)
    for key in ref:
        np.testing.assert_allclose(params0[key], np.asarray(ref[key]),
                                   rtol=2e-6, atol=2e-7)
    for a in ranks:
        ray_tpu.kill(a)


def _grad_sync_loop(config):
    """Train-loop side of the wiring test: allreduce a deterministic tree
    through the context's bucket reducer and report what came back."""
    import numpy as np

    from ray_tpu import train

    ctx = train.get_context()
    assert ctx.grad_sync is not None
    tree = {"w": np.full((8, 4), float(ctx.get_world_rank() + 1),
                         np.float32),
            "b": np.ones((4,), np.float32)}
    red = ctx.make_bucket_reducer(tree)
    try:
        out = red.reduce_tree(tree)
    finally:
        red.shutdown()
    train.report({"w_sum": float(out["w"][0, 0]),
                  "b_sum": float(out["b"][0]), "step": 1})
    return {"ok": True}


def test_trainer_grad_sync_e2e(cluster, tmp_path):
    trainer = JaxTrainer(
        _grad_sync_loop,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1.0},
                                     grad_sync_backend="cpu",
                                     grad_sync_bucket_bytes=1 << 10),
        run_config=RunConfig(storage_path=str(tmp_path), name="gsync"),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["w_sum"] == 3.0  # 1 + 2 across the two ranks
    assert result.metrics["b_sum"] == 2.0

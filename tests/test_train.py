"""Train layer e2e: JaxTrainer with checkpointing + failure recovery.

Reference tier: python/ray/train/v2/tests (controller/worker-group/failure
policy units driven end-to-end here on CPU workers).
"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu.train import (
    Checkpoint,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=6)
    yield ray_tpu
    ray_tpu.shutdown()


def _sgd_loop(config):
    """A tiny numpy "training" loop with report + checkpoint."""
    import json

    import numpy as np

    from ray_tpu import train

    ctx = train.get_context()
    w = np.zeros(4)
    start = 0
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        with open(os.path.join(ckpt.as_directory(), "state.json")) as f:
            state = json.load(f)
        w = np.array(state["w"])
        start = state["step"]
    target = np.arange(4.0)
    for step in range(start, config["steps"]):
        w = w + 0.5 * (target - w)
        loss = float(((target - w) ** 2).mean())
        if (step + 1) % config["ckpt_every"] == 0 and ctx.get_world_rank() == 0:
            import tempfile

            with tempfile.TemporaryDirectory() as d:
                with open(os.path.join(d, "state.json"), "w") as f:
                    json.dump({"w": w.tolist(), "step": step + 1}, f)
                train.report({"loss": loss, "step": step + 1},
                             checkpoint=Checkpoint.from_directory(d))
        else:
            train.report({"loss": loss, "step": step + 1})
    return {"final_loss": loss, "rank": ctx.get_world_rank()}


def test_jax_trainer_e2e(cluster, tmp_path):
    trainer = JaxTrainer(
        _sgd_loop,
        train_loop_config={"steps": 6, "ckpt_every": 2},
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1.0}),
        run_config=RunConfig(storage_path=str(tmp_path), name="e2e"),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 6
    assert result.metrics["loss"] < 1e-2
    assert result.checkpoint is not None
    assert os.path.exists(os.path.join(result.checkpoint.path, "state.json"))


def _flaky_loop(config):
    import json

    from ray_tpu import train

    ctx = train.get_context()
    marker = config["marker"]
    start = 0
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        with open(os.path.join(ckpt.as_directory(), "state.json")) as f:
            start = json.load(f)["step"]
    for step in range(start, config["steps"]):
        if step == 3 and ctx.get_world_rank() == 0 and not os.path.exists(marker):
            open(marker, "w").close()
            os._exit(1)  # simulate worker death mid-run
        if ctx.get_world_rank() == 0:
            import tempfile

            with tempfile.TemporaryDirectory() as d:
                with open(os.path.join(d, "state.json"), "w") as f:
                    json.dump({"step": step + 1}, f)
                train.report({"step": step + 1},
                             checkpoint=Checkpoint.from_directory(d))
        else:
            train.report({"step": step + 1})
    return {"done": True, "resumed_from": start}


def test_failure_policy_restart(cluster, tmp_path):
    marker = str(tmp_path / "died_once")
    trainer = JaxTrainer(
        _flaky_loop,
        train_loop_config={"steps": 5, "marker": marker},
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1.0}),
        run_config=RunConfig(storage_path=str(tmp_path), name="flaky",
                             failure_config=FailureConfig(max_failures=2)),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 5
    assert os.path.exists(marker)  # the crash really happened


def test_training_failed_raises(cluster, tmp_path):
    def always_fails(config):
        raise RuntimeError("bad loop")

    from ray_tpu.train import TrainingFailedError

    trainer = JaxTrainer(
        always_fails,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1,
                                     resources_per_worker={"CPU": 1.0}),
        run_config=RunConfig(storage_path=str(tmp_path), name="failing"),
    )
    with pytest.raises(TrainingFailedError, match="bad loop"):
        trainer.fit()

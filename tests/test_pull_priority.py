"""Prioritized pull admission (reference: object_manager/pull_manager.cc:
get > task-arg > background classes, priority upgrades, obsolete-pull
cancellation)."""

import asyncio

import pytest

from ray_tpu._private.pull_manager import (PRIO_ARG, PRIO_BACKGROUND,
                                           PRIO_GET, PullQueue)


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_priority_order_beats_fifo():
    """With one slot busy, a later-arriving GET pull is admitted before an
    earlier-queued BACKGROUND pull."""

    async def main():
        q = PullQueue(slots=1)
        order = []

        async def pull(oid, prio, hold=0.05):
            q.request(oid, prio)
            assert await q.admit(oid)
            order.append(oid)
            await asyncio.sleep(hold)
            q.release(oid)

        first = asyncio.ensure_future(pull(b"hold", PRIO_ARG))
        await asyncio.sleep(0.01)  # occupies the slot
        bg = asyncio.ensure_future(pull(b"bg", PRIO_BACKGROUND))
        await asyncio.sleep(0.01)  # bg queued first...
        hot = asyncio.ensure_future(pull(b"hot", PRIO_GET))
        await asyncio.gather(first, bg, hot)
        assert order == [b"hold", b"hot", b"bg"], order

    _run(main())


def test_fifo_within_class():
    async def main():
        q = PullQueue(slots=1)
        order = []

        async def pull(oid):
            q.request(oid, PRIO_ARG)
            assert await q.admit(oid)
            order.append(oid)
            await asyncio.sleep(0.02)
            q.release(oid)

        tasks = [asyncio.ensure_future(pull(f"o{i}".encode()))
                 for i in range(4)]
        await asyncio.gather(*tasks)
        assert order == [b"o0", b"o1", b"o2", b"o3"], order

    _run(main())


def test_priority_upgrade():
    """A queued background pull upgraded by a hot requester is admitted
    ahead of mid-priority arrivals."""

    async def main():
        q = PullQueue(slots=1)
        order = []

        async def pull(oid, prio):
            q.request(oid, prio)
            assert await q.admit(oid)
            order.append(oid)
            await asyncio.sleep(0.02)
            q.release(oid)

        hold = asyncio.ensure_future(pull(b"hold", PRIO_ARG))
        await asyncio.sleep(0.01)
        bg = asyncio.ensure_future(pull(b"bg", PRIO_BACKGROUND))
        mid = asyncio.ensure_future(pull(b"mid", PRIO_ARG))
        await asyncio.sleep(0.01)
        q.request(b"bg", PRIO_GET)  # upgrade: a get now needs it
        await asyncio.gather(hold, bg, mid)
        assert order == [b"hold", b"bg", b"mid"], order

    _run(main())


def test_stale_pull_cancelled_without_waiters():
    async def main():
        q = PullQueue(slots=1, stale_ttl_s=0.2)

        async def hold():
            q.request(b"hold", PRIO_ARG)
            assert await q.admit(b"hold")
            await asyncio.sleep(1.2)
            q.release(b"hold")

        async def stale():
            q.request(b"stale", PRIO_ARG)  # no waiter ever asserts interest
            return await q.admit(b"stale")

        h = asyncio.ensure_future(hold())
        await asyncio.sleep(0.01)
        admitted = await stale()
        assert admitted is False  # cancelled as obsolete, never transferred
        await h

    _run(main())


def test_waiter_keeps_pull_alive():
    async def main():
        q = PullQueue(slots=1, stale_ttl_s=0.2)

        async def hold():
            q.request(b"hold", PRIO_ARG)
            assert await q.admit(b"hold")
            await asyncio.sleep(0.9)
            q.release(b"hold")

        async def wanted():
            q.request(b"wanted", PRIO_ARG)
            q.add_waiter(b"wanted")  # a getter is actively blocked on it
            return await q.admit(b"wanted")

        h = asyncio.ensure_future(hold())
        await asyncio.sleep(0.01)
        assert await wanted() is True
        q.release(b"wanted")
        await h

    _run(main())

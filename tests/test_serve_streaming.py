"""Serve streaming responses + rolling updates (reference:
serve/_private/proxy.py streaming, serve/_private/deployment_state.py
versioned rollouts)."""

import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(resources={"CPU": 6.0})
    yield
    try:
        serve.shutdown()
    except Exception:
        pass
    ray_tpu.shutdown()


def test_handle_streaming_chunks_incremental(cluster):
    @serve.deployment
    class Tokens:
        async def __call__(self, body):
            import asyncio

            for i in range(4):
                await asyncio.sleep(0.4)
                yield {"token": i}

    handle = serve.run(Tokens.bind(), name="tok")
    t0 = time.monotonic()
    gen = handle.options(stream=True).remote({})
    first_ref = next(gen)
    first = ray_tpu.get(first_ref, timeout=60)
    first_latency = time.monotonic() - t0
    rest = [ray_tpu.get(r, timeout=60) for r in gen]
    assert first == {"token": 0}
    assert rest == [{"token": 1}, {"token": 2}, {"token": 3}]
    # chunk 0 arrived long before the full 1.6s of production
    assert first_latency < 1.5, f"stream not incremental: {first_latency:.1f}s"
    serve.delete("tok")


def test_sync_generator_target_streams(cluster):
    @serve.deployment
    def letters(body):
        for c in "abc":
            yield c

    handle = serve.run(letters.bind(), name="letters")
    out = [ray_tpu.get(r, timeout=60)
           for r in handle.options(stream=True).remote({})]
    assert out == ["a", "b", "c"]
    serve.delete("letters")


def test_http_proxy_streams_chunks(cluster):
    import json
    import urllib.request

    @serve.deployment
    class Stream:
        async def __call__(self, body):
            import asyncio

            for i in range(3):
                await asyncio.sleep(0.2)
                yield {"i": i}

    serve.run(Stream.bind(), name="stream")
    port = serve.start_http_proxy()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/stream?stream=1",
        data=b"{}", headers={"Content-Type": "application/json"})
    chunks = []
    with urllib.request.urlopen(req, timeout=60) as resp:
        for line in resp:
            line = line.strip()
            if line:
                chunks.append(json.loads(line))
    assert chunks == [{"i": 0}, {"i": 1}, {"i": 2}]
    serve.delete("stream")


def test_rolling_update_zero_dropped(cluster):
    """Redeploying must keep serving: requests issued continuously across
    the rollout all succeed, and the new version takes over."""
    import threading

    def make_app(version):
        @serve.deployment(num_replicas=2, ray_actor_options={"num_cpus": 0.2})
        class App:
            def __call__(self, body):
                time.sleep(0.05)
                return {"version": version}

        return App.bind()

    handle = serve.run(make_app(1), name="roll")
    results, errors = [], []
    stop = threading.Event()

    def hammer():
        h = serve.get_app_handle("roll")
        while not stop.is_set():
            try:
                results.append(ray_tpu.get(h.remote({}), timeout=60))
            except Exception as e:  # any dropped request fails the test
                errors.append(e)
            time.sleep(0.05)

    t = threading.Thread(target=hammer, daemon=True)
    t.start()
    time.sleep(1.0)
    serve.run(make_app(2), name="roll")  # rolling redeploy
    deadline = time.time() + 90
    while time.time() < deadline:
        tail = [r["version"] for r in results[-6:]]
        if len(tail) == 6 and all(v == 2 for v in tail):
            break
        time.sleep(0.5)
    stop.set()
    t.join(timeout=30)
    assert not errors, f"dropped requests during rollout: {errors[:3]}"
    versions = {r["version"] for r in results}
    assert versions == {1, 2}, versions
    tail = [r["version"] for r in results[-6:]]
    assert all(v == 2 for v in tail), f"rollout did not complete: {tail}"
    serve.delete("roll")
